"""Turn a telemetry run journal into a human-readable run summary.

The journal (schema v1, ``mxnet_tpu/telemetry.py``, written when
``MXNET_TELEMETRY`` names a directory) holds one JSONL record per
training step plus one per notable event. This tool reconstructs:

* step-time quantiles (p50/p95/p99, exact — computed over the raw
  per-step records, not histogram buckets) and the data-wait vs
  window-wait breakdown;
* the throughput curve (samples/sec over the run, bucketed);
* a fault/guardrail event table (retries, reconnects, dead workers,
  masked steps, rollbacks, preemption checkpoints, compiles);
* the final metrics-registry snapshot, when the journal was closed
  cleanly.

    python tools/telemetry_report.py runs/telemetry-1234.jsonl
    python tools/telemetry_report.py --json runs/telemetry-1234.jsonl
    python tools/telemetry_report.py --stats 127.0.0.1:9911
    python tools/telemetry_report.py --stats h1:9911 h2:9911 h3:9911
    python tools/telemetry_report.py --diff old.jsonl new.jsonl

``--diff OLD NEW`` compares two journals regression-first: step-time
quantile and throughput deltas, the wait-breakdown shift, per-counter
deltas, and event-vocabulary changes (events that appeared or
disappeared between the runs) — the human companion to the automated
``tools/perf_gate.py`` gate (docs/perf_gates.md).

The summary's ``samples_per_sec`` is sum(samples) / sum(wall_ms):
step walls are measured boundary-to-boundary in the fit loops, so the
figure reconstructs what a Speedometer callback reports (asserted
within 5% in tests/test_telemetry.py).

With ``MXNET_PEAK_FLOPS`` set (peak accelerator FLOP/s), the
steady-state section also prints achieved FLOP/s and MFU from the
``step.model_flops`` gauge the Executor records at each compile event
(docs/mfu_analysis.md methodology).

``--stats host:port`` instead queries a live ``ServeServer``'s
introspection frame (telemetry registry snapshot + engine queue/bucket
state) — same trusted-cluster pickle wire as the serving transport.
Several targets render as ONE fleet table (per-replica queue depth,
in-flight, active decode slots, warmed buckets, shed counts — the
operator's imbalance eyeball for a replicated serve fleet, a dead
replica shown as unreachable instead of sinking the table).
"""
import argparse
import json
import os

SCHEMA_VERSION = 1

_CURVE_BUCKETS = 20


def load_jsonl(path, schema=None, what="record"):
    """Torn-final-line-tolerant JSONL loader — THE one read side of
    the journal/spill write contract (one flushed line per record, so
    a crash tears at most the FINAL line; a parse failure there is
    tolerated, anywhere earlier is real corruption and raises). With
    ``schema`` set, every record's ``v`` must match or the file is
    refused. Shared by this tool, ``tools/trace_report.py`` and
    ``tools/perf_gate.py`` — evolve the contract here, once."""
    with open(path) as f:
        lines = [ln.strip() for ln in f]
    while lines and not lines[-1]:
        lines.pop()
    records = []
    for i, line in enumerate(lines):
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            if i == len(lines) - 1:
                break            # torn final line: the crash signature
            raise ValueError("%s:%d: corrupt %s" % (path, i + 1, what))
        if schema is not None and rec.get("v") != schema:
            raise ValueError(
                "%s:%d: %s schema v%r, this reader understands v%d"
                % (path, i + 1, what, rec.get("v"), schema))
        records.append(rec)
    return records


def load(path):
    """Parse a journal into a record list (schema-checked)."""
    return load_jsonl(path, schema=SCHEMA_VERSION,
                      what="journal record")


def _quantile(sorted_vals, q):
    """Exact quantile of an already-sorted list (nearest-rank with the
    numpy 'linear' convention's index rounding). Mirrors
    mxnet_tpu.telemetry.quantile — kept standalone so this tool (and
    xplane_summary, which imports it) never drags the framework/jax
    import."""
    if not sorted_vals:
        return None
    idx = int(round(q * (len(sorted_vals) - 1)))
    return sorted_vals[idx]


def _curve(steps):
    """samples/sec over the run, in up to _CURVE_BUCKETS equal step
    spans: [{"step": first step of span, "samples_per_sec": ...}]."""
    if not steps:
        return []
    span = max(1, (len(steps) + _CURVE_BUCKETS - 1) // _CURVE_BUCKETS)
    out = []
    for i in range(0, len(steps), span):
        chunk = steps[i:i + span]
        wall_s = sum(float(s.get("wall_ms", 0.0)) for s in chunk) / 1e3
        samples = sum(int(s.get("samples", 0)) for s in chunk)
        out.append({
            "step": i,
            "samples_per_sec": round(samples / wall_s, 2) if wall_s
            else None})
    return out


def summarize(records):
    """Aggregate a record list (from :func:`load`, optionally filtered
    by the caller — e.g. to one run's records) into the summary dict
    format_report renders."""
    steps = [r for r in records if r.get("kind") == "step"]
    events = [r for r in records if r.get("kind") == "event"]
    snap = next((r.get("metrics") for r in reversed(records)
                 if r.get("kind") == "snapshot"), None)

    out = {"schema": SCHEMA_VERSION, "steps": len(steps),
           "events": {}}
    for e in events:
        name = e.get("event", "?")
        out["events"][name] = out["events"].get(name, 0) + 1

    if steps:
        # steady-state view: steps flagged compile=True carried an XLA
        # compile (the journal marks them at write time) — one-off wall
        # that would otherwise poison every quantile and the
        # throughput figure. They are reported separately below.
        steady = [s for s in steps if not s.get("compile")] or steps
        compile_ms = sum(float(s.get("wall_ms", 0.0)) for s in steps
                         if s.get("compile"))
        walls = sorted(float(s.get("wall_ms", 0.0)) for s in steady)
        total_s = sum(walls) / 1e3
        samples = sum(int(s.get("samples", 0)) for s in steady)
        out["samples"] = samples
        out["wall_s"] = round(total_s, 3)
        out["compile_steps"] = sum(1 for s in steps
                                   if s.get("compile"))
        out["compile_ms"] = round(compile_ms, 3)
        out["samples_per_sec"] = round(samples / total_s, 3) \
            if total_s else None
        out["step_ms"] = {
            "mean": round(sum(walls) / len(walls), 3),
            "p50": round(_quantile(walls, 0.50), 3),
            "p95": round(_quantile(walls, 0.95), 3),
            "p99": round(_quantile(walls, 0.99), 3),
            "min": round(walls[0], 3),
            "max": round(walls[-1], 3)}
        for key in ("data_wait_ms", "window_wait_ms"):
            tot = sum(float(s.get(key, 0.0)) for s in steady)
            out[key + "_total"] = round(tot, 3)
            out[key + "_share"] = round(tot / (total_s * 1e3), 4) \
                if total_s else None
        out["throughput_curve"] = _curve(steady)

        # MFU (docs/mfu_analysis.md): achieved FLOP/s = the compiled
        # step's cost-analysis FLOPs (step.model_flops gauge) times
        # steady-state steps/sec; MFU against the MXNET_PEAK_FLOPS
        # hint (read here, at report time — the journal predates it)
        g = (snap or {}).get("step.model_flops", {})
        flops = g.get("value") if g.get("type") == "gauge" else None
        if flops and total_s:
            out["model_flops"] = flops
            out["flops_per_sec"] = flops * len(steady) / total_s
            try:
                peak = float(os.environ.get("MXNET_PEAK_FLOPS") or 0.0)
            except ValueError:
                peak = 0.0
            if peak > 0:
                out["peak_flops"] = peak
                out["mfu"] = round(out["flops_per_sec"] / peak, 4)

    serving = _serving_section(events, snap)
    if serving:
        out["serving"] = serving

    if snap is not None:
        out["counters"] = {k: v["value"] for k, v in sorted(snap.items())
                           if v.get("type") == "counter"}
        out["gauges"] = {k: v["value"] for k, v in sorted(snap.items())
                         if v.get("type") == "gauge"
                         and v.get("value") is not None}
    return out


def _serving_section(events, snap):
    """Aggregate the serving engine's journal events (serve.batch /
    serve.shed / serve.timeout / serve.decode.finish) and serve.*
    snapshot counters into the report's serving block. Empty dict =
    no serving activity in this journal."""
    out = {}
    batches = [e.get("fields", {}) for e in events
               if e.get("event") == "serve.batch"]
    if batches:
        fills = sorted(float(b.get("fill", 0)) for b in batches)
        waits = sorted(float(b.get("wait_ms", 0.0)) for b in batches)
        fwd = sorted(float(b.get("forward_ms", 0.0)) for b in batches)
        out["forwards"] = len(batches)
        out["rows"] = int(sum(fills))
        out["mean_fill"] = round(sum(fills) / len(fills), 3)
        out["batch_wait_ms"] = {
            "p50": round(_quantile(waits, 0.50), 3),
            "p95": round(_quantile(waits, 0.95), 3)}
        out["forward_ms"] = {
            "p50": round(_quantile(fwd, 0.50), 3),
            "p95": round(_quantile(fwd, 0.95), 3)}
    finishes = [e.get("fields", {}) for e in events
                if e.get("event") == "serve.decode.finish"]
    if finishes:
        toks = sorted(int(f.get("tokens", 0)) for f in finishes)
        ms = sorted(float(f.get("ms", 0.0)) for f in finishes)
        out["decode_sequences"] = len(finishes)
        out["decode_tokens"] = int(sum(toks))
        out["decode_ms"] = {"p50": round(_quantile(ms, 0.50), 3),
                            "p95": round(_quantile(ms, 0.95), 3)}
    if snap is not None:
        # streaming latency first-class: TTFT and inter-token gap
        # quantiles straight off the registry histograms (populated
        # by every decode emission, streamed or not)
        for name, key in (("serve.ttft_ms", "ttft_ms"),
                          ("serve.inter_token_ms",
                           "inter_token_ms")):
            h = snap.get(name) or {}
            if h.get("type") == "histogram" and h.get("count"):
                out[key] = {"count": h["count"], "p50": h.get("p50"),
                            "p95": h.get("p95"), "p99": h.get("p99")}
        # speculative decoding: per-round-row acceptance (the knob
        # that decides whether the draft is earning its keep —
        # docs/serving.md §speculative)
        h = snap.get("serve.spec.accept_rate") or {}
        if h.get("type") == "histogram" and h.get("count"):
            out["spec_accept_rate"] = {
                "count": h["count"],
                "mean": round(h["sum"] / h["count"], 4)
                if h.get("sum") is not None else None,
                "p50": h.get("p50"), "p95": h.get("p95"),
                "p99": h.get("p99")}
        counters = {k: v["value"] for k, v in snap.items()
                    if k.startswith("serve.")
                    and v.get("type") == "counter" and v.get("value")}
        if counters:
            out["counters"] = dict(sorted(counters.items()))
        # attach only alongside real serving activity: the gauge is
        # published by every Generator construction, and a bare
        # kv-bytes figure must not conjure a serving section into a
        # journal that never served
        kvb = snap.get("serve.decode.kv_bytes_per_slot",
                       {}).get("value")
        if kvb and out:
            out["kv_bytes_per_slot"] = int(kvb)
    for name in ("serve.shed", "serve.timeout", "serve.drain"):
        n = sum(1 for e in events if e.get("event") == name)
        if n:
            out[name.split(".", 1)[1] + "_events"] = n
    return out


def format_report(summary):
    """The summary dict as a human-readable text report."""
    lines = ["telemetry run summary (journal schema v%d)"
             % summary["schema"],
             "=" * 46, ""]
    if summary["steps"]:
        sm = summary["step_ms"]
        lines += [
            "steps: %d   samples: %d   wall: %.2fs   throughput: "
            "%.1f samples/sec (steady state)"
            % (summary["steps"], summary["samples"], summary["wall_s"],
               summary["samples_per_sec"] or 0.0)]
        if summary.get("compile_steps"):
            lines.append(
                "compile: %d step(s) carried an XLA compile "
                "(%.1f ms total) — excluded from the figures above"
                % (summary["compile_steps"], summary["compile_ms"]))
        lines += [
            "",
            "step time (ms):",
            "| mean | p50 | p95 | p99 | min | max |",
            "|---|---|---|---|---|---|",
            "| %.2f | %.2f | %.2f | %.2f | %.2f | %.2f |"
            % (sm["mean"], sm["p50"], sm["p95"], sm["p99"], sm["min"],
               sm["max"]),
            "",
            "wait breakdown: data %.1f%%, dispatch window %.1f%% of "
            "step wall"
            % (100.0 * (summary.get("data_wait_ms_share") or 0.0),
               100.0 * (summary.get("window_wait_ms_share") or 0.0)),
        ]
        if summary.get("flops_per_sec"):
            mfu_line = ("model FLOPs/step: %.4g — achieved %.4g "
                        "FLOP/s" % (summary["model_flops"],
                                    summary["flops_per_sec"]))
            if summary.get("mfu") is not None:
                mfu_line += ("   MFU: %.1f%% of %.4g peak "
                             "(MXNET_PEAK_FLOPS)"
                             % (100.0 * summary["mfu"],
                                summary["peak_flops"]))
            lines.append(mfu_line)
        curve = summary.get("throughput_curve") or []
        if len(curve) > 1:
            lines += ["", "throughput curve (samples/sec by step span):"]
            for pt in curve:
                lines.append("  step %5d+  %s" % (
                    pt["step"],
                    "%.1f" % pt["samples_per_sec"]
                    if pt["samples_per_sec"] is not None else "-"))
    else:
        lines.append("no step records (events-only journal)")

    serving = summary.get("serving")
    if serving:
        lines += ["", "serving:"]
        if "forwards" in serving:
            lines.append(
                "  %d engine forward(s) served %d row(s) — mean batch "
                "fill %.2f" % (serving["forwards"], serving["rows"],
                               serving["mean_fill"]))
            lines.append(
                "  batch wait p50/p95: %.2f/%.2f ms   forward p50/p95: "
                "%.2f/%.2f ms"
                % (serving["batch_wait_ms"]["p50"],
                   serving["batch_wait_ms"]["p95"],
                   serving["forward_ms"]["p50"],
                   serving["forward_ms"]["p95"]))
        if "decode_sequences" in serving:
            lines.append(
                "  continuous decode: %d sequence(s), %d token(s), "
                "request p50/p95: %.1f/%.1f ms"
                % (serving["decode_sequences"],
                   serving["decode_tokens"],
                   serving["decode_ms"]["p50"],
                   serving["decode_ms"]["p95"]))
        if serving.get("ttft_ms"):
            t = serving["ttft_ms"]
            lines.append(
                "  TTFT p50/p95/p99: %.1f/%.1f/%.1f ms over %d first "
                "token(s)" % (t["p50"], t["p95"], t["p99"],
                              t["count"]))
        if serving.get("inter_token_ms"):
            t = serving["inter_token_ms"]
            lines.append(
                "  inter-token p50/p95/p99: %.2f/%.2f/%.2f ms over "
                "%d gap(s)" % (t["p50"], t["p95"], t["p99"],
                               t["count"]))
        if serving.get("spec_accept_rate"):
            a = serving["spec_accept_rate"]
            lines.append(
                "  speculative accept rate: mean %.2f   p50/p95: "
                "%.2f/%.2f over %d round-row(s) (docs/serving.md "
                "§speculative — below ~0.4 the draft costs more "
                "than it saves)"
                % (a["mean"] or 0.0, a["p50"], a["p95"], a["count"]))
        if serving.get("kv_bytes_per_slot"):
            kvb = serving["kv_bytes_per_slot"]
            lines.append(
                "  decode state: %d bytes/slot (%.2f MiB — int8 "
                "quantize_kv halves KV rows; block_type='ssm' makes "
                "it O(1) in max_len; see docs/serving.md)"
                % (kvb, kvb / 2.0 ** 20))
        for key, label in (("shed_events", "shed"),
                           ("timeout_events", "timed out"),
                           ("drain_events", "drain(s)")):
            if serving.get(key):
                lines.append("  %d request(s) %s"
                             % (serving[key], label))
        if serving.get("counters"):
            for name, val in serving["counters"].items():
                lines.append("  %-36s %d" % (name, val))

    if summary["events"]:
        lines += ["", "events:",
                  "| event | count |", "|---|---|"]
        for name in sorted(summary["events"]):
            lines.append("| %s | %d |" % (name, summary["events"][name]))

    if summary.get("counters"):
        lines += ["", "final counters (registry snapshot):"]
        for name, val in summary["counters"].items():
            lines.append("  %-36s %d" % (name, val))
    if summary.get("gauges"):
        lines += ["", "gauges:"]
        for name, val in summary["gauges"].items():
            lines.append("  %-36s %g" % (name, val))
    return "\n".join(lines)


def _pct(old, new):
    """Signed percent change new vs old; None when undefined."""
    if old is None or new is None or not old:
        return None
    return round(100.0 * (float(new) - float(old)) / float(old), 1)


def diff_summaries(old, new):
    """Regression-oriented diff of two :func:`summarize` outputs.
    Positive step-time deltas and negative throughput deltas are the
    regression directions; ``suspects`` collects the headline fields
    that moved the wrong way by more than 10%."""
    out = {"steps": [old.get("steps"), new.get("steps")],
           "suspects": []}
    for key, worse_when in (("samples_per_sec", "down"),
                            ("wall_s", "up"),
                            ("compile_steps", "up"),
                            ("compile_ms", "up")):
        o, n = old.get(key), new.get(key)
        if o is None and n is None:
            continue
        pct = _pct(o, n)
        out[key] = {"old": o, "new": n, "pct": pct}
        if pct is not None and (pct < -10 if worse_when == "down"
                                else pct > 10):
            out["suspects"].append(key)
    sm_o, sm_n = old.get("step_ms") or {}, new.get("step_ms") or {}
    if sm_o or sm_n:
        out["step_ms"] = {}
        for q in ("mean", "p50", "p95", "p99", "min", "max"):
            pct = _pct(sm_o.get(q), sm_n.get(q))
            out["step_ms"][q] = {"old": sm_o.get(q), "new": sm_n.get(q),
                                 "pct": pct}
            if q in ("p50", "p95") and pct is not None and pct > 10:
                out["suspects"].append("step_ms." + q)
    for key in ("data_wait_ms_share", "window_wait_ms_share"):
        o, n = old.get(key), new.get(key)
        if o is not None or n is not None:
            out[key] = {"old": o, "new": n}
    # counter deltas over the union (a counter that disappears entirely
    # usually marks deleted instrumentation — a gate-worthy smell)
    co = old.get("counters") or {}
    cn = new.get("counters") or {}
    deltas = {}
    for k in sorted(set(co) | set(cn)):
        ov, nv = co.get(k), cn.get(k)
        if ov != nv:
            deltas[k] = {"old": ov, "new": nv}
    if deltas:
        out["counter_deltas"] = deltas
    ev_o = set(old.get("events") or {})
    ev_n = set(new.get("events") or {})
    out["events_added"] = sorted(ev_n - ev_o)
    out["events_removed"] = sorted(ev_o - ev_n)
    if out["events_removed"]:
        out["suspects"].append("events_removed")
    ev_counts = {}
    for k in sorted(ev_o & ev_n):
        ov = (old.get("events") or {}).get(k)
        nv = (new.get("events") or {}).get(k)
        if ov != nv:
            ev_counts[k] = {"old": ov, "new": nv}
    if ev_counts:
        out["event_count_changes"] = ev_counts
    return out


def format_diff(diff, old_path="OLD", new_path="NEW"):
    """The diff dict as a regression-oriented text table."""
    lines = ["telemetry journal diff", "=" * 46,
             "  old: %s" % old_path, "  new: %s" % new_path, ""]

    def row(label, o, n, pct=None):
        tail = "" if pct is None else "  (%+.1f%%)" % pct
        return "| %-18s | %10s | %10s |%s" % (label, o, n, tail)

    lines += ["| field              |        old |        new |",
              "|---|---|---|",
              row("steps", diff["steps"][0], diff["steps"][1])]
    for key in ("samples_per_sec", "wall_s", "compile_steps",
                "compile_ms"):
        if key in diff:
            d = diff[key]
            lines.append(row(key, d["old"], d["new"], d["pct"]))
    for q, d in (diff.get("step_ms") or {}).items():
        lines.append(row("step_ms." + q, d["old"], d["new"], d["pct"]))
    for key in ("data_wait_ms_share", "window_wait_ms_share"):
        if key in diff:
            d = diff[key]
            lines.append(row(key, d["old"], d["new"]))
    if diff.get("counter_deltas"):
        lines += ["", "counters that changed:",
                  "| counter | old | new |", "|---|---|---|"]
        for k, d in diff["counter_deltas"].items():
            lines.append("| %s | %s | %s |" % (k, d["old"], d["new"]))
    if diff.get("event_count_changes"):
        lines += ["", "event counts that changed:",
                  "| event | old | new |", "|---|---|---|"]
        for k, d in diff["event_count_changes"].items():
            lines.append("| %s | %s | %s |" % (k, d["old"], d["new"]))
    if diff.get("events_added"):
        lines += ["", "events only in new: "
                  + ", ".join(diff["events_added"])]
    if diff.get("events_removed"):
        lines += ["", "events only in old (deleted instrumentation?): "
                  + ", ".join(diff["events_removed"])]
    lines.append("")
    if diff.get("suspects"):
        lines.append("regression suspects (>10%% the wrong way): %s"
                     % ", ".join(diff["suspects"]))
    else:
        lines.append("no regression suspects (>10% thresholds)")
    return "\n".join(lines)


def fetch_stats(addr, timeout=10.0):
    """Query a live ServeServer's ``stats`` introspection frame.
    Speaks the serving wire directly (4-byte length prefix + pickle) so
    this tool still needs no framework import. Trusted cluster only —
    the reply unpickles, exactly like the serving transport itself."""
    import pickle
    import socket
    import struct

    host, _, port = str(addr).rpartition(":")
    if not host:
        raise ValueError("--stats wants HOST:PORT, got %r" % (addr,))
    with socket.create_connection((host, int(port)),
                                  timeout=timeout) as sock:
        payload = pickle.dumps(("stats", None), protocol=4)
        sock.sendall(struct.pack(">I", len(payload)) + payload)
        hdr = b""
        while len(hdr) < 4:
            chunk = sock.recv(4 - len(hdr))
            if not chunk:
                raise ConnectionError("server closed during stats reply")
            hdr += chunk
        (n,) = struct.unpack(">I", hdr)
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(min(1 << 20, n - len(buf)))
            if not chunk:
                raise ConnectionError("server closed mid stats reply")
            buf += chunk
    reply = pickle.loads(bytes(buf))
    if not reply or reply[0] != "ok":
        raise RuntimeError("stats query failed: %r" % (reply,))
    return reply[1]


def format_fleet(rows):
    """Multi-target stats replies as one fleet table — the operator's
    imbalance eyeball (per-replica queue depth, in-flight, active
    decode slots, warmed buckets, shed counts) without Perfetto.
    ``rows``: ``[(addr, stats-or-None)]`` — a None/failed fetch
    renders as unreachable rather than sinking the table."""
    def gauge(snap, name):
        v = (snap.get(name) or {}).get("value")
        return "-" if v is None else ("%g" % v)

    header = ("| replica | role | model | queue | in-flight | streams "
              "| admitted | shed | shed/s | req/s | timeouts | "
              "active slots | warmed |")
    lines = ["serve fleet stats (%d target(s))" % len(rows),
             "=" * 46, "", header,
             "|---|---|---|---|---|---|---|---|---|---|---|---|---|"]
    for addr, stats in rows:
        if not stats:
            lines.append("| %s | unreachable | - | - | - | - | - | - "
                         "| - | - | - | - | - |" % addr)
            continue
        eng = stats.get("engine") or {}
        snap = stats.get("telemetry") or {}
        warmed = eng.get("warmed")
        lines.append("| %s | %s | %s | %s | %s | %s | %s | %s | %s "
                     "| %s | %s | %s | %s |"
                     % (addr,
                        eng.get("role", "engine"),
                        eng.get("model_id") or "-",
                        eng.get("queue_depth", "-"),
                        eng.get("in_flight", "-"),
                        eng.get("streams_in_flight", "-"),
                        eng.get("admitted", eng.get("dispatched",
                                                    "-")),
                        eng.get("shed", "-"),
                        # windowed rates (per router poll window):
                        # router targets aggregate them fleet-wide,
                        # plain engine targets have no poller -> "-"
                        eng.get("shed_rate", "-"),
                        eng.get("req_rate", "-"),
                        eng.get("timeouts", "-"),
                        gauge(snap, "serve.decode.active_slots"),
                        ",".join(str(b) for b in warmed)
                        if warmed else "-"))
    reach = [(a, s) for a, s in rows if s]
    if reach:
        engines = [s.get("engine") or {} for _, s in reach]
        lines += ["", "fleet totals: queue=%s in-flight=%s "
                  "admitted=%s shed=%s over %d reachable replica(s)"
                  % (sum(int(e.get("queue_depth") or 0)
                         for e in engines),
                     sum(int(e.get("in_flight") or 0)
                         for e in engines),
                     # same admitted-or-dispatched fallback as the
                     # per-row column: a router target counts
                     # dispatched, an engine counts admitted
                     sum(int(e.get("admitted",
                                   e.get("dispatched")) or 0)
                         for e in engines),
                     sum(int(e.get("shed") or 0) for e in engines),
                     len(reach))]
    return "\n".join(lines)


def format_stats(stats):
    """A live-server stats reply as a text report."""
    lines = ["serve server stats", "=" * 46, "", "engine:"]
    for key, val in sorted((stats.get("engine") or {}).items()):
        lines.append("  %-24s %s" % (key, val))
    snap = stats.get("telemetry") or {}
    counters = {k: v["value"] for k, v in sorted(snap.items())
                if v.get("type") == "counter" and v.get("value")}
    if counters:
        lines += ["", "counters:"]
        for name, val in counters.items():
            lines.append("  %-36s %d" % (name, val))
    gauges = {k: v["value"] for k, v in sorted(snap.items())
              if v.get("type") == "gauge" and v.get("value") is not None}
    if gauges:
        lines += ["", "gauges:"]
        for name, val in gauges.items():
            lines.append("  %-36s %g" % (name, val))
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("journal", nargs="?",
                   help="path to a telemetry *.jsonl journal")
    p.add_argument("--json", action="store_true",
                   help="emit the summary dict as JSON instead of text")
    p.add_argument("--stats", metavar="HOST:PORT", nargs="+",
                   help="query live ServeServer stats frames instead "
                        "of reading a journal; several targets render "
                        "as one fleet table (per-replica queue depth, "
                        "in-flight, warmed buckets, shed counts)")
    p.add_argument("--diff", nargs=2, metavar=("OLD", "NEW"),
                   help="compare two journals (regression-oriented "
                        "table; the human companion to tools/"
                        "perf_gate.py)")
    args = p.parse_args(argv)
    try:
        if args.diff:
            old_p, new_p = args.diff
            diff = diff_summaries(summarize(load(old_p)),
                                  summarize(load(new_p)))
            print(json.dumps(diff, indent=2) if args.json
                  else format_diff(diff, old_p, new_p))
            return
        if args.stats:
            if len(args.stats) == 1:
                stats = fetch_stats(args.stats[0])
                print(json.dumps(stats, indent=2, default=str)
                      if args.json else format_stats(stats))
                return
            rows = []
            for addr in args.stats:
                try:
                    rows.append((addr, fetch_stats(addr)))
                except Exception:  # noqa: BLE001 — one dead replica
                    rows.append((addr, None))   # must not sink the
                    #                             fleet table
            print(json.dumps({a: s for a, s in rows}, indent=2,
                             default=str)
                  if args.json else format_fleet(rows))
            return
        if not args.journal:
            p.error("give a journal path (or --stats HOST:PORT, or "
                    "--diff OLD NEW)")
        summary = summarize(load(args.journal))
        if args.json:
            print(json.dumps(summary, indent=2))
        else:
            print(format_report(summary))
    except BrokenPipeError:        # `... | head` is a normal usage
        pass


if __name__ == "__main__":
    main()
