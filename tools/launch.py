#!/usr/bin/env python
"""Distributed job launcher (reference: tools/launch.py + dmlc_tracker).

The reference launched scheduler/server/worker processes over
ssh/mpi/sge/yarn. TPU-native clusters run ONE SPMD program per host, so
the launcher's job collapses to: pick a coordinator, assign process ids,
start the same command everywhere with the right env
(mxnet_tpu.parallel.dist.init() reads it — DMLC_* names kept for
reference-script compat).

  # N local processes on one host (the dmlc_tracker 'local' mode —
  # how the multi-process tests run without a cluster):
  python tools/launch.py -n 4 --launcher local python train.py

  # one process per host over ssh:
  python tools/launch.py -n 2 --launcher ssh -H hosts.txt python train.py
"""
from __future__ import annotations

import argparse
import os
import shlex
import socket
import subprocess
import sys


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _free_port_block(n):
    """Base port with ports base..base+n-1 all currently bindable —
    dist_async server i binds base+i (ps_async.server_endpoints), so
    checking only the base would let one occupied follow-on port kill
    the whole job at startup."""
    if n <= 1:
        return _free_port()
    for _ in range(100):
        base = _free_port()
        ok = True
        for i in range(1, n):
            with socket.socket() as s:
                try:
                    s.bind(("", base + i))
                except OSError:
                    ok = False
                    break
        if ok:
            return base
    raise RuntimeError("no block of %d consecutive free ports found" % n)


def _worker_env(rank, n, coord_uri, coord_port, extra=()):
    env = dict(os.environ)
    env.update({
        "DMLC_ROLE": "worker",
        "DMLC_PS_ROOT_URI": coord_uri,
        "DMLC_PS_ROOT_PORT": str(coord_port),
        "DMLC_NUM_WORKER": str(n),
        "DMLC_WORKER_ID": str(rank),
    })
    env.update(dict(extra))
    return env


def _server_env(sid, n_workers, n_servers, coord_uri, coord_port,
                extra=()):
    """Server-role env (dist_async parameter-server shard sid; servers
    bind coord_port+sid — parallel/ps_async.server_endpoints)."""
    env = dict(os.environ)
    env.update({
        "DMLC_ROLE": "server",
        "DMLC_PS_ROOT_URI": coord_uri,
        "DMLC_PS_ROOT_PORT": str(coord_port),
        "DMLC_NUM_WORKER": str(n_workers),
        "DMLC_NUM_SERVER": str(n_servers),
        "DMLC_SERVER_ID": str(sid),
    })
    env.update(dict(extra))
    return env


def launch_local(n, command, env_extra=(), num_servers=0):
    """Fork n local worker processes (dmlc_tracker 'local' launcher),
    plus num_servers parameter-server processes for dist_async (the
    reference tracker launched servers the same way: same command,
    DMLC_ROLE=server — the framework import enters the server loop).
    If any process dies, the survivors are killed — a partial cluster
    would block forever inside jax.distributed.initialize."""
    import time
    port = _free_port_block(max(1, num_servers))
    extra = list(env_extra)
    if num_servers:
        extra.append(("DMLC_NUM_SERVER", str(num_servers)))
    procs = [subprocess.Popen(
        command, env=_server_env(s, n, num_servers, "127.0.0.1", port,
                                 env_extra))
        for s in range(num_servers)]
    procs += [subprocess.Popen(
        command, env=_worker_env(r, n, "127.0.0.1", port, extra))
        for r in range(n)]
    rc = 0
    while True:
        codes = [p.poll() for p in procs]
        bad = [c for c in codes if c not in (None, 0)]
        if bad and any(c is None for c in codes):
            for p in procs:
                if p.poll() is None:
                    p.kill()
        if all(c is not None for c in codes):
            rc = next((c for c in codes if c), 0)
            break
        time.sleep(0.1)
    return rc


def launch_ssh(n, hosts, command, env_extra=()):
    """One worker per host over ssh; host 0 is the coordinator."""
    if len(hosts) < n:
        raise SystemExit("need %d hosts, got %d" % (n, len(hosts)))
    port = _free_port()
    cmd_str = " ".join(shlex.quote(c) for c in command)
    procs = []
    for r in range(n):
        env = _worker_env(r, n, hosts[0], port, env_extra)
        keys = ["DMLC_ROLE", "DMLC_PS_ROOT_URI", "DMLC_PS_ROOT_PORT",
                "DMLC_NUM_WORKER", "DMLC_WORKER_ID"] + \
            [k for k, _ in env_extra]
        exports = " ".join("%s=%s" % (k, shlex.quote(str(env[k])))
                           for k in keys)
        procs.append(subprocess.Popen(
            ["ssh", "-o", "StrictHostKeyChecking=no", hosts[r],
             "cd %s && env %s %s" % (shlex.quote(os.getcwd()), exports,
                                     cmd_str)]))
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="launch a distributed mxnet_tpu job")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="parameter-server process count (dist_async; "
                         "0 = collective-only job, no servers)")
    ap.add_argument("--launcher", choices=("local", "ssh"),
                    default="local")
    ap.add_argument("-H", "--hostfile",
                    help="one host per line (ssh launcher)")
    ap.add_argument("--env", action="append", default=[],
                    metavar="K=V", help="extra env for every worker")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    for kv in args.env:
        if "=" not in kv:
            ap.error("--env expects K=V, got %r" % kv)
    extra = [kv.split("=", 1) for kv in args.env]

    if args.launcher == "local":
        return launch_local(args.num_workers, args.command, extra,
                            num_servers=args.num_servers)
    if args.num_servers:
        ap.error("--num-servers is supported by the local launcher "
                 "only (ssh server placement needs explicit "
                 "MXNET_PS_SERVER_URIS)")
    with open(args.hostfile) as f:
        hosts = [ln.strip() for ln in f if ln.strip()]
    return launch_ssh(args.num_workers, hosts, args.command, extra)


if __name__ == "__main__":
    sys.exit(main())
