#!/bin/bash
# Whole-model A/B on the live TPU: isolate which default flipped since
# the round-3 capture (2387 img/s, 28.1% MFU) regressed ResNet-50.
# Two suspects, each a custom_vjp boundary XLA cannot fuse across:
#   - MXNET_POOL_DENSE_BWD: kh*kw dense max-pool bwd (r5 default,
#     since reverted by this A/B's own result)
#   - the r4 one-pass/closed-form BatchNorm (vs plain autodiff BN,
#     the default again for the same reason)
#
#   bash tools/tpu_ab_regression.sh [outfile]
#
# Appends one JSON line per config to <outfile> (default
# bench_out/ab_regression.jsonl), tagging each with its env config.
set -u -o pipefail
cd "$(dirname "$0")/.."
OUT="${1:-bench_out/ab_regression.jsonl}"
mkdir -p "$(dirname "$OUT")"

run() {  # run <tag> [ENV=V...] — pins ALL BN/pool knobs per config so
         # an exported var in the operator's shell cannot mislabel runs
  local tag="$1"; shift
  echo "== $tag ==" >&2
  local line
  line="$(env MXNET_BN_PALLAS=0 MXNET_BN_IMPL= MXNET_POOL_DENSE_BWD=0 \
          MXNET_BN_STATS= "$@" python bench.py)" \
      || { echo "FAILED $tag" >&2; return 0; }
  MXTPU_AB_LINE="$line" MXTPU_AB_TAG="$tag" python -c '
import json, os
rec = json.loads(os.environ["MXTPU_AB_LINE"])
rec["ab_config"] = os.environ["MXTPU_AB_TAG"]
print(json.dumps(rec))
' >> "$OUT" || echo "TAG-FAILED $tag" >&2
}

run dense_pool+onepass_bn   MXNET_POOL_DENSE_BWD=1 MXNET_BN_IMPL=onepass
run sas_pool+onepass_bn     MXNET_POOL_DENSE_BWD=0 MXNET_BN_IMPL=onepass
run dense_pool+autodiff_bn  MXNET_POOL_DENSE_BWD=1
run sas_pool+autodiff_bn    MXNET_POOL_DENSE_BWD=0
run sas_pool+pallas_bn      MXNET_POOL_DENSE_BWD=0 MXNET_BN_PALLAS=1
run bn_stats_auto           MXNET_BN_STATS=auto
run bn_stats_dot            MXNET_BN_STATS=dot
echo "== A/B done; results in $OUT =="
