"""Journal-backed perf-regression gate: CPU-deterministic scenarios
checked against committed baselines (docs/perf_gates.md, ROADMAP 5).

The live-TPU bench lost 4 of 5 rounds to the tunnel being down
(BENCH_r01-r05), so the measured wins of earlier PRs — PR 2's ≤1
blocking host sync per step, PR 11's one-executable donated-buffer
steps, PR 8/10's journal + trace vocabulary — were protected only by
scattered per-PR tests. This tool turns the telemetry journal and
trace spill those PRs built into ONE enforcement surface:

* each **scenario** (TrainStep fit, Module fit, GSPMD layout step,
  PS push/pull under fault injection, ServeEngine request path,
  ContinuousDecoder) runs a short deterministic workload in a fresh
  subprocess on the CPU backend with ``MXNET_TELEMETRY`` +
  ``MXNET_TRACE`` on;
* a **gate fingerprint** is extracted from the journal + spill:
  per-step blocking-host-sync counts, compile-event counts and which
  step carries them, the jit-cache size across donated steps, the
  trace-span vocabulary/nesting shape, the journal schema version,
  key counter values (ps.retries, guardrail.masked_steps, serve.shed)
  and noise-tolerant CPU step-time figures;
* the fingerprint is compared against the committed baseline in
  ``perf_baselines/<scenario>.json`` — EXACT match for every count and
  shape field, a ratio tolerance (default 3x, env
  ``MXNET_GATE_TIME_RATIO``) for wall-clock times;
* a failure prints which field diverged AND which PR-won property that
  field protects, so a gate failure reads as "you reintroduced a
  per-step host sync", not as a JSON diff.

    python tools/perf_gate.py                    # all scenarios
    python tools/perf_gate.py --scenario trainstep,gspmd
    python tools/perf_gate.py --bless            # regenerate baselines
    python tools/perf_gate.py --keep /tmp/gate   # keep run artifacts
    python tools/perf_gate.py --no-time          # skip the time bounds

``tools/perf_gate.sh`` runs this gate plus every smoke-lint and marker
test subset — the one builder entrypoint. Count/shape fields are
deterministic run-to-run (asserted in tests/test_perf_gate.py, marker
``gate``); after an INTENDED behavior change, re-bless and commit the
new baselines with the change that caused them.
"""
import argparse
import json
import os
import subprocess
import sys

_SELF = os.path.abspath(__file__)
_REPO = os.path.dirname(os.path.dirname(_SELF))
sys.path.insert(0, _REPO)

GATE_SCHEMA = 1
DEFAULT_TIME_RATIO = 3.0
BASELINE_DIR = os.path.join(_REPO, "perf_baselines")


# ---------------------------------------------------------------------------
# scenario workloads (run in a fresh child process; see _child_main)
# ---------------------------------------------------------------------------
# Every workload must be CPU-deterministic: fixed seeds, fixed fault
# specs, sequential request submission where concurrency would make
# event counts racy. Each emits a `gate.probe` journal event carrying
# the in-process measurements a journal record can't (host-sync deltas);
# everything else is read back from the journal + trace spill.

def _mlp(classes=2, hidden=32):
    import mxnet_tpu as mx
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=hidden)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=classes)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _toy(n=96, d=16, classes=2, seed=0):
    import numpy as np
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = rng.integers(0, classes, n).astype(np.float32)
    return X, y


def _sync_marks_probe(marks, steps_per_epoch, warm_epochs=1):
    """Per-step host-sync figures from cumulative counter marks taken
    at each batch end. Steady state = epochs after `warm_epochs`;
    deltas are only taken WITHIN an epoch (the epoch boundary pays the
    metric read + window drain by design)."""
    steady_deltas = []
    for e in range(warm_epochs, len(marks) // steps_per_epoch):
        base = e * steps_per_epoch
        for i in range(1, steps_per_epoch):
            steady_deltas.append(marks[base + i] - marks[base + i - 1])
    return {
        "max_step_syncs_steady": max(steady_deltas) if steady_deltas
        else None,
        "fit_total_syncs": marks[-1] - marks[0] if marks else None,
    }


def _scn_trainstep():
    """PR 2/3 surface: pipelined TrainStep.fit with the guardrail on
    and one deterministically injected NaN step (nan@6 of 12)."""
    from mxnet_tpu import io, profiler, telemetry
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.parallel import make_train_step
    from mxnet_tpu.parallel.resilience import (FaultInjector,
                                               install_fault_injector)
    X, y = _toy()
    step = make_train_step(_mlp(), optimizer="sgd",
                           optimizer_params={"rescale_grad": 1.0 / 24})
    train = io.NDArrayIter(X, y, batch_size=24)     # 4 steps/epoch
    marks = []
    install_fault_injector(FaultInjector("nan@6"))  # epoch 2, step 2
    try:
        step.fit(train, num_epoch=3, initializer=Xavier(), lr=0.1,
                 seed=0, batch_end_callback=lambda _p: marks.append(
                     profiler.host_sync_count()))
    finally:
        install_fault_injector(None)
    telemetry.journal_event("gate.probe",
                            **_sync_marks_probe(marks, 4))


def _scn_module():
    """The Module fit path (executor group + device metrics)."""
    import mxnet_tpu as mx
    from mxnet_tpu import io, profiler, telemetry
    X, y = _toy()
    train = io.NDArrayIter(X, y, batch_size=24)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    marks = []
    mod.fit(train, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1,
                              "rescale_grad": 1.0 / 24},
            batch_end_callback=lambda _p: marks.append(
                profiler.host_sync_count()))
    telemetry.journal_event("gate.probe",
                            **_sync_marks_probe(marks, 4))


def _scn_gspmd():
    """PR 11 surface: one-jit GSPMD fit over the forced-8-device
    data×fsdp mesh with zero1 optimizer sharding — the jit-cache gauge
    must stay at ONE executable across donated steps."""
    from mxnet_tpu import io, profiler, telemetry
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.parallel import SpecLayout, make_mesh, make_train_step
    X, y = _toy(classes=8)
    mesh = make_mesh({"data": 2, "fsdp": 4})
    layout = SpecLayout(mesh, min_shard_size=0)
    step = make_train_step(_mlp(classes=8), layout=layout,
                           optimizer="adam", optimizer_sharding="zero1",
                           optimizer_params={"rescale_grad": 1.0 / 24})
    train = io.NDArrayIter(X, y, batch_size=24)     # 24 % 8 == 0
    marks = []
    step.fit(train, num_epoch=3, initializer=Xavier(), lr=0.05,
             seed=0, batch_end_callback=lambda _p: marks.append(
                 profiler.host_sync_count()))
    telemetry.journal_event("gate.probe",
                            **_sync_marks_probe(marks, 4))


def _scn_ps_faults():
    """PR 1 surface: async PS push/pull under a deterministic
    mid-push disconnect + dropped pull reply — exactly-once replay
    means the retry counters are exact, not flaky."""
    import threading

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.parallel.ps_async import AsyncPSClient, AsyncPSServer
    from mxnet_tpu.parallel.resilience import (FaultInjector,
                                               install_fault_injector)
    t0 = telemetry.now_ms()
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=1)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    c = AsyncPSClient(host="127.0.0.1", port=srv.port)
    c.set_optimizer(mx.optimizer.SGD(learning_rate=0.1,
                                     rescale_grad=1.0))
    c.init("w", np.ones((4,), np.float32))
    inj = install_fault_injector(
        FaultInjector("send:disconnect@3;recv:drop@6"))
    try:
        for i in range(8):
            c.push("w", np.full((4,), float(i % 3), np.float32))
        c.pull("w")
    finally:
        install_fault_injector(None)
    c.close()
    srv.stop()
    assert inj.fired == [("send", 3, "disconnect"),
                         ("recv", 6, "drop")], inj.fired
    telemetry.journal_event("gate.probe",
                            ps_elapsed_ms=round(
                                telemetry.now_ms() - t0, 3))


def _serve_predictor(feat=8, classes=4):
    import mxnet_tpu as mx
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.predictor import Predictor
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=classes)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    arg_shapes, _, _ = net.infer_shape(data=(2, feat))
    mx.random.seed(7)
    init = Xavier()
    args = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        arr = mx.nd.zeros(shp)
        init(name, arr)
        args[name] = arr
    return Predictor(net, args, data_names=("data",))


def _scn_serve():
    """PR 9 surface: warmed buckets + sequential requests (each its
    own deterministic batch), then a zero-capacity engine so the shed
    count is exact."""
    import numpy as np

    from mxnet_tpu import telemetry
    from mxnet_tpu.serve import Overloaded, ServeEngine
    t0 = telemetry.now_ms()
    pred = _serve_predictor()
    x = np.zeros((1, 8), np.float32)
    with ServeEngine(pred, buckets=(1, 2, 4), max_wait_ms=0.0,
                     feature_shapes=[(8,)],
                     install_sigterm=False) as eng:
        eng.warmup()
        for _ in range(4):                  # sequential: fill=1 each
            eng.infer(x, timeout=60.0)
    with ServeEngine(pred, buckets=(1,), max_wait_ms=0.0, queue_cap=0,
                     feature_shapes=[(8,)],
                     install_sigterm=False) as eng:
        for _ in range(2):                  # cap 0: every submit sheds
            try:
                eng.submit(x)
            except Overloaded:
                pass
    telemetry.journal_event("gate.probe",
                            serve_elapsed_ms=round(
                                telemetry.now_ms() - t0, 3))


def _scn_router():
    """PR 14 surface: fleet router over two in-process replicas —
    replica 1 sheds every request (queue cap 0) so each of the 4
    sequential requests reroutes to replica 2 (exact reroute count),
    then replica 2 is recycled (drain -> in-process restart ->
    re-warm over the wire -> readmit) and serves one more. Counters,
    reroutes, recycle events and the router->replica span edges are
    all deterministic."""
    import numpy as np

    from mxnet_tpu import telemetry
    from mxnet_tpu.serve import ServeEngine, ServeRouter, ServeServer
    t0 = telemetry.now_ms()
    pred = _serve_predictor()
    x = np.zeros((1, 8), np.float32)

    def make_replica(cap):
        kw = {} if cap is None else {"queue_cap": cap}
        eng = ServeEngine(pred, buckets=(1, 2), max_wait_ms=0.0,
                          feature_shapes=[(8,)],
                          install_sigterm=False, **kw)
        return eng, ServeServer(eng)
    e1, s1 = make_replica(0)              # sheds everything
    e2, s2 = make_replica(None)
    live = {"e": e2, "s": s2}
    router = ServeRouter(poll_ms=0)       # no background poller: every
    #                                       stats RPC is scripted
    router.add_replica(s1.host, s1.port, name="r1")
    router.add_replica(s2.host, s2.port, name="r2")
    router.poll_now()
    for _ in range(4):                    # r1 sheds -> reroute to r2
        router.infer(x, timeout=60.0)

    def restart():
        live["s"].close()
        live["e"].close()
        live["e"], live["s"] = make_replica(None)
        return (live["s"].host, live["s"].port)
    router.recycle("r2", restart=restart)
    router.infer(x, timeout=60.0)         # the readmitted replica serves
    router.close()
    for closer in (s1, live["s"], e1, live["e"]):
        closer.close()
    telemetry.journal_event("gate.probe",
                            router_elapsed_ms=round(
                                telemetry.now_ms() - t0, 3))


def _decode_workload(quantize_kv, block_type="attention"):
    """Shared body of the decode scenarios: sequential ragged
    requests through a 3-slot pool so admissions/steps/finishes are
    exact and every admission is a slot turnover (the jit-cache gauge
    must stay at ONE compiled (B, 1) step across them)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.generation import Generator
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel import make_train_step
    t0 = telemetry.now_ms()
    V, L, H, DIM, T = 50, 2, 2, 32, 24
    sym = transformer.get_symbol(V, 12, num_layers=L, num_heads=H,
                                 dim=DIM, max_len=T,
                                 pos_encoding="learned",
                                 block_type=block_type)
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(0)
    state = step.init_state(Xavier(), {"data": (2, 12),
                                       "softmax_label": (2, 12)})
    gen = Generator(state[0], V, T, num_layers=L, num_heads=H,
                    dim=DIM, batch_size=3, quantize_kv=quantize_kv,
                    block_type=block_type)
    with gen.serving_decoder() as dec:
        for length, max_new in ((4, 5), (6, 3), (3, 4)):
            dec.submit(np.arange(length), max_new,
                       eos_id=None).result(300.0)
    telemetry.journal_event("gate.probe",
                            decode_elapsed_ms=round(
                                telemetry.now_ms() - t0, 3))


def _scn_disagg():
    """PR 15 surface: prefill/decode disaggregation — one prefill +
    one decode in-process replica behind the role-aware router,
    sequential ragged generates with ONE injected transport fault torn
    into the 2nd prefill frame. The pure prefill replays to the
    identical blob, every admission is a scatter-only import (zero
    decode-side prefill graph calls), and the decode (B, 1) step stays
    ONE compiled executable across imported-slot turnover."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.generation import Generator
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel import make_train_step
    from mxnet_tpu.parallel.resilience import (FaultInjector,
                                               install_fault_injector)
    from mxnet_tpu.serve import (ContinuousDecoder, PrefillEngine,
                                 ServeRouter, ServeServer)
    t0 = telemetry.now_ms()
    V, L, H, DIM, T = 50, 2, 2, 32, 24
    sym = transformer.get_symbol(V, 12, num_layers=L, num_heads=H,
                                 dim=DIM, max_len=T,
                                 pos_encoding="learned")
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(0)
    params = step.init_state(Xavier(), {"data": (2, 12),
                                        "softmax_label": (2, 12)})[0]

    def gen(bs):
        return Generator(params, V, T, num_layers=L, num_heads=H,
                         dim=DIM, batch_size=bs)
    pre = PrefillEngine(gen(1))
    dec = ContinuousDecoder(gen(3))
    s1, s2 = ServeServer(pre), ServeServer(dec)
    router = ServeRouter(poll_ms=0)       # scripted polling only
    router.add_replica(s1.host, s1.port, name="prefill0")
    router.add_replica(s2.host, s2.port, name="decode0")
    router.poll_now()
    inj = install_fault_injector(
        FaultInjector("prefill_send:disconnect@2"))
    try:
        for length, max_new in ((4, 5), (6, 3), (3, 4)):
            router.generate(np.arange(1, length + 1), max_new,
                            session="s")
    finally:
        install_fault_injector(None)
    assert inj.fired == [("prefill_send", 2, "disconnect")], inj.fired
    st = dec.stats()
    assert st["prefills"] == 0 and st["imported"] == 3, st
    router.close()
    for closer in (s1, s2, dec, pre):
        closer.close()
    telemetry.journal_event("gate.probe",
                            disagg_elapsed_ms=round(
                                telemetry.now_ms() - t0, 3))


def _scn_failover():
    """PR 16 surface: fleet survives replica death — two in-process
    decode replicas behind the router. One pinned replica "dies"
    (every data send AND the liveness probe dropped) mid-generate:
    the router fails the pin over and REPLAYS the request on the
    survivor token-for-token (same prompt + seed => byte-equal row).
    Then a recycle of the replica holding a live session migrates it
    mid-decode (evacuate -> resume on a survivor) instead of
    draining, and the migrated row is byte-equal to an undisturbed
    run. Failover/replay/migration/evacuation counters, the
    suspect->revive cycle and the decode resume/dedup counters are
    all deterministic; the (B, 1) decode step stays ONE compiled
    executable across the evacuated-slot turnover."""
    import threading
    import time

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.generation import Generator
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel import make_train_step
    from mxnet_tpu.parallel.resilience import (FaultInjector,
                                               install_fault_injector)
    from mxnet_tpu.serve import ContinuousDecoder, ServeRouter, ServeServer
    t0 = telemetry.now_ms()
    V, L, H, DIM, T = 50, 2, 2, 32, 24
    sym = transformer.get_symbol(V, 12, num_layers=L, num_heads=H,
                                 dim=DIM, max_len=T,
                                 pos_encoding="learned")
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(0)
    params = step.init_state(Xavier(), {"data": (2, 12),
                                        "softmax_label": (2, 12)})[0]

    def gen():
        return Generator(params, V, T, num_layers=L, num_heads=H,
                         dim=DIM, batch_size=3)

    def cval(name):
        rec = telemetry.snapshot().get(name) or {}
        return rec.get("value", 0)
    d0 = ContinuousDecoder(gen())
    d1 = ContinuousDecoder(gen())
    s0, s1 = ServeServer(d0), ServeServer(d1)
    router = ServeRouter(poll_ms=0)       # scripted polling only
    router.add_replica(s0.host, s0.port, name="d0")
    router.add_replica(s1.host, s1.port, name="d1")
    router.poll_now()
    p = np.arange(1, 5)
    kw = {"temperature": 0.8, "top_k": 8, "seed": 7}
    r1 = router.generate(p, 5, session="s", timeout=120.0, **kw)
    pin = router.sessions()["s"]
    idx = int(pin[-1])                    # add_replica order == family
    # the pinned replica "dies": every data send and the control-path
    # liveness probe fail from here on
    inj = install_fault_injector(FaultInjector(
        "router%d_send:drop@1x*;router%d_ctl_send:drop@1x*"
        % (idx, idx)))
    try:
        r2 = router.generate(p, 5, session="s", timeout=120.0, **kw)
    finally:
        install_fault_injector(None)
    assert inj.fired and {f[0] for f in inj.fired} <= {
        "router%d_send" % idx, "router%d_ctl_send" % idx}, inj.fired
    # token-exact replay: same prompt + seed on the survivor
    assert np.array_equal(r1, r2), (r1, r2)
    assert router.sessions()["s"] != pin
    router.poll_now()                     # fault gone -> revive
    # -- live migration: recycle the replica holding session "m" ----
    steps0 = cval("serve.decode.steps")
    box = {}

    def bg():
        box["row"] = router.generate(np.arange(1, 4), 18, session="m",
                                     timeout=120.0, temperature=0.8,
                                     top_k=8, seed=11)
    th = threading.Thread(target=bg)
    th.start()
    while cval("serve.decode.steps") < steps0 + 2:   # mid-decode
        time.sleep(0.005)
    router.recycle(router.sessions()["m"], timeout=60.0)
    th.join(120.0)
    assert not th.is_alive(), "migrated generate never completed"
    # byte-equal to an undisturbed run of the same request
    ver = router.generate(np.arange(1, 4), 18, session="v",
                          timeout=120.0, temperature=0.8, top_k=8,
                          seed=11)
    assert np.array_equal(box["row"], ver), (box["row"], ver)
    st0, st1 = d0.stats(), d1.stats()
    assert st0["evacuated"] + st1["evacuated"] == 1, (st0, st1)
    assert st0["resumed"] + st1["resumed"] == 1, (st0, st1)
    router.close()
    for closer in (s0, s1, d0, d1):
        closer.close()
    telemetry.journal_event("gate.probe",
                            failover_elapsed_ms=round(
                                telemetry.now_ms() - t0, 3))


def _scn_decode():
    """PR 9 surface: continuous-batching decode, sequential ragged
    requests so admissions/steps/finishes are exact."""
    _decode_workload(quantize_kv=False)


def _scn_decode_q8():
    """PR 13 surface: the SAME ragged workload with int8 KV caches —
    the per-row q8 op must keep jit cache size 1 across slot
    turnover and publish the (halved) kv_bytes_per_slot gauge."""
    _decode_workload(quantize_kv=True)


def _scn_decode_ssm():
    """ISSUE 19 surface: the SAME ragged workload on an O(1)-state
    SSM generator — slot turnover over constant (H, hd, hd) state
    blobs must keep jit cache size 1 (the recurrence needs no per-row
    twin at all) and publish a kv_bytes_per_slot gauge that never
    mentions max_len."""
    _decode_workload(quantize_kv=False, block_type="ssm")


def _scn_streaming():
    """PR 17 surface: streamed generate frames + chunked prefill.
    One decode replica behind the wire: a streamed generate's
    on_token tail byte-equals the one-shot row (greedy AND seeded —
    the terminal reply cross-checks every stream bitwise), a long
    prompt under MXNET_PREFILL_CHUNK admits in a deterministic chunk
    count with the same bits, and the (B, 1) decode step stays ONE
    compiled executable across streamed + chunked turnover. Stream/
    chunk counters are exact; frame counts are noisy (the handler
    coalesces emissions per wire frame, which is scheduling-
    dependent)."""
    import os as _os

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.generation import Generator
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel import make_train_step
    from mxnet_tpu.serve import ContinuousDecoder, ServeServer
    from mxnet_tpu.serve.net import ServeClient
    t0 = telemetry.now_ms()
    V, L, H, DIM, T = 50, 2, 2, 32, 24
    sym = transformer.get_symbol(V, 12, num_layers=L, num_heads=H,
                                 dim=DIM, max_len=T)
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(0)
    params = step.init_state(Xavier(), {"data": (2, 12),
                                        "softmax_label": (2, 12)})[0]

    def gen(bs):
        return Generator(params, V, T, num_layers=L, num_heads=H,
                         dim=DIM, batch_size=bs)
    p, long_p = np.arange(1, 5), np.arange(1, 11)
    kw = {"temperature": 0.8, "top_k": 8, "seed": 7}
    single = gen(1)
    want = single.generate(p[None], 8, eos_id=0)[0]
    want_s = single.generate(p[None], 8, eos_id=0, **kw)[0]
    want_l = single.generate(long_p[None], 6, eos_id=0)[0]
    dec = ContinuousDecoder(gen(2))
    srv = ServeServer(dec)
    with ServeClient(srv.host, srv.port) as cli:
        toks = []
        out = cli.generate(p, 8, eos_id=0, on_token=toks.append)
        assert np.array_equal(out, want), (out, want)
        assert np.array_equal(toks, want[p.size:]), (toks, want)
        toks = []
        out = cli.generate(p, 8, eos_id=0, on_token=toks.append,
                           **kw)
        assert np.array_equal(out, want_s), (out, want_s)
        assert np.array_equal(toks, want_s[p.size:]), (toks, want_s)
        # chunked prefill: 10-token prompt in 3-token slices -> 4
        # chunks, bit-identical row
        _os.environ["MXNET_PREFILL_CHUNK"] = "3"
        try:
            out = cli.generate(long_p, 6, eos_id=0)
        finally:
            _os.environ.pop("MXNET_PREFILL_CHUNK", None)
        assert np.array_equal(out, want_l), (out, want_l)

    def cval(name):
        rec = telemetry.snapshot().get(name) or {}
        return rec.get("value", 0)
    assert cval("serve.decode.streams") == 2
    assert cval("serve.decode.prefill_chunks") == 4
    srv.close()
    dec.close()
    telemetry.journal_event("gate.probe",
                            streaming_elapsed_ms=round(
                                telemetry.now_ms() - t0, 3))


def _scn_spec_decode():
    """PR 18 surface: speculative decoding in the serving fleet —
    one decode replica with a 1-layer truncated draft attached. A
    plain (non-speculative) request runs FIRST and alone, tracing
    the (B, 1) target step, then greedy + sampled speculative
    requests run draft/verify rounds: every row byte-equals the
    single-row generate (shared-noise verification — speculation is
    a schedule, not a sampler), the target owns exactly TWO compiled
    programs ((B, 1) step + (B, gamma+1) verify), the draft exactly
    ONE, and the round/draft-step/acceptance counters are exact for
    the deterministic workload."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.generation import Generator
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel import make_train_step
    from mxnet_tpu.serve import ContinuousDecoder, ServeServer
    from mxnet_tpu.serve.net import ServeClient
    t0 = telemetry.now_ms()
    V, L, H, DIM, T = 50, 2, 2, 32, 24
    sym = transformer.get_symbol(V, 12, num_layers=L, num_heads=H,
                                 dim=DIM, max_len=T)
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(0)
    params = step.init_state(Xavier(), {"data": (2, 12),
                                        "softmax_label": (2, 12)})[0]

    def gen(bs):
        return Generator(params, V, T, num_layers=L, num_heads=H,
                         dim=DIM, batch_size=bs)
    p = np.arange(1, 5)
    kw = {"temperature": 0.8, "top_k": 8, "seed": 7}
    single = gen(1)
    want = single.generate(p[None], 8, eos_id=0)[0]
    want_s = single.generate(p[None], 8, eos_id=0, **kw)[0]
    target = gen(2)
    dec = ContinuousDecoder(target,
                            draft=target.truncated_draft(num_layers=1),
                            lookahead=3)
    srv = ServeServer(dec)
    with ServeClient(srv.host, srv.port) as cli:
        # the plain request runs FIRST and alone so the (B, 1) step
        # traces before any verify — the jit gauge then pins the
        # full two-program target contract
        out = cli.generate(p, 8, eos_id=0)
        assert np.array_equal(out, want), (out, want)
        out = cli.generate(p, 8, eos_id=0, speculative=True)
        assert np.array_equal(out, want), (out, want)
        out = cli.generate(p, 8, eos_id=0, speculative=True, **kw)
        assert np.array_equal(out, want_s), (out, want_s)
    st = dec.stats()
    assert st["spec_rounds"] > 0 and st["draft_steps"] > 0, st
    assert st["spec_accepted"] <= st["spec_proposed"], st
    assert st["draft_prefills"] == 2, st   # one per speculative admit

    def gval(name):
        rec = telemetry.snapshot().get(name) or {}
        return rec.get("value", 0)
    assert gval("serve.decode.jit_cache_size") == 2
    assert gval("serve.spec.draft_jit_cache_size") == 1
    srv.close()
    dec.close()
    telemetry.journal_event("gate.probe",
                            spec_decode_elapsed_ms=round(
                                telemetry.now_ms() - t0, 3))


def _scn_controller():
    """PR 20 surface: the fleet controller over an in-process
    2-replica fleet — one forced scale-out on a scripted sustained
    queue-depth signal, one self-heal of a cold-killed replica, one
    scale-in back to the floor, and one rollout gated down by a
    deliberately broken canary artifact (rolled back, zero traffic
    ever routed to it). Decisions are explicit ``tick()`` calls
    against scripted stats frames, so every serve.ctrl counter is
    exact."""
    import numpy as np

    from mxnet_tpu import telemetry
    from mxnet_tpu.serve import (FleetController, ServeEngine,
                                 ServeRouter, ServeServer)
    t0 = telemetry.now_ms()
    pred = _serve_predictor()
    x = np.zeros((1, 8), np.float32)

    class Scripted(ServeEngine):
        fake_depth = 0

        def introspect(self):
            out = super().introspect()
            out["queue_depth"] += self.fake_depth
            return out

    class Broken:
        def forward(self, *arrays):
            raise RuntimeError("deliberately broken artifact")

    cells = {}                  # "host:port" -> (engine, server)

    def spawn(manifest=None):
        model = Broken() if manifest == "bad" else pred
        eng = Scripted(model, buckets=(1, 2), max_wait_ms=0.0,
                       feature_shapes=[(8,)], install_sigterm=False)
        srv = ServeServer(eng)
        cells["%s:%d" % (srv.host, srv.port)] = (eng, srv)
        return (srv.host, srv.port)

    def retire(name, addr):
        cell = cells.pop(addr, None)
        if cell is not None:
            cell[1].close()
            cell[0].close()

    def script_depth(depth):
        for eng, _ in cells.values():
            eng.fake_depth = depth

    router = ServeRouter(poll_ms=0)       # every stats RPC scripted
    for i in range(2):
        host, port = spawn(None)
        router.add_replica(host, port, name="r%d" % i)
    router.poll_now()
    ctrl = FleetController(router, spawn, retire=retire, poll_ms=0,
                           min_replicas=2, max_replicas=3,
                           sustain=1, cooldown=0, canary_inputs=[x])
    # 1. scale-out: a sustained (sustain=1) scripted depth signal
    script_depth(50)
    assert len(ctrl.tick()["scaled_out"]) == 1
    script_depth(2)                       # neutral band: no action
    router.infer(x, timeout=60.0)         # the grown fleet serves
    # 2. heal: kill r1 cold (no drain); the next tick suspects,
    # probe-confirms, and respawns it under the same name
    desc = router.replicas()["r1"]
    retire("r1", "%s:%d" % (desc["host"], desc["port"]))
    assert ctrl.tick()["healed"] == ["r1"]
    # 3. scale-in: an idle window drains the newest replica away
    script_depth(0)
    assert len(ctrl.tick()["scaled_in"]) == 1
    # 4. gated rollback: the broken artifact fails its canary on the
    # first replica and rolls back — the fleet stays on the prior
    res = ctrl.rollout("bad", model_id="vBad")
    assert res.rolled_back, res
    router.infer(x, timeout=60.0)         # still serving, uniform
    ctrl.close()
    router.close()
    for eng, srv in list(cells.values()):
        srv.close()
        eng.close()
    telemetry.journal_event("gate.probe",
                            controller_elapsed_ms=round(
                                telemetry.now_ms() - t0, 3))


# which PR-won property each gauge protects is resolved through
# _PROPERTY_NOTES below; `gauges` lists the gauge names a scenario
# REQUIRES in the final snapshot (absence is itself a gate failure),
# `noisy_counters`/`noisy_events` name snapshot fields excluded from
# the exact compare because their values are timing-dependent.
SCENARIOS = {
    "trainstep": {
        "fn": _scn_trainstep,
        "desc": "pipelined TrainStep.fit + guardrail NaN masking",
        "gauges": ("trainstep.jit_cache_size",),
        "noisy_counters": (), "noisy_events": (),
    },
    "module": {
        "fn": _scn_module,
        "desc": "Module.fit executor-group path",
        "gauges": ("step.model_flops",),
        "noisy_counters": (), "noisy_events": (),
    },
    "gspmd": {
        "fn": _scn_gspmd,
        "desc": "one-jit GSPMD fit (data×fsdp, zero1)",
        "gauges": ("trainstep.jit_cache_size", "gspmd.sharded_params"),
        "noisy_counters": (), "noisy_events": (),
    },
    "ps_faults": {
        "fn": _scn_ps_faults,
        "desc": "PS push/pull under injected disconnect+drop",
        "gauges": (),
        "noisy_counters": (), "noisy_events": (),
    },
    "serve": {
        "fn": _scn_serve,
        "desc": "ServeEngine request path + exact shed",
        "gauges": (),
        "noisy_counters": (), "noisy_events": (),
    },
    "router": {
        "fn": _scn_router,
        "desc": "fleet router: shed-and-retry + zero-drop recycle "
                "over two in-process replicas",
        "gauges": ("serve.router.replicas_live",
                   "serve.router.sessions"),
        "noisy_counters": (), "noisy_events": (),
    },
    "decode": {
        "fn": _scn_decode,
        "desc": "ContinuousDecoder sequential ragged requests",
        "gauges": ("serve.decode.jit_cache_size",
                   "serve.decode.kv_bytes_per_slot"),
        "noisy_counters": (), "noisy_events": (),
    },
    "decode_q8": {
        "fn": _scn_decode_q8,
        "desc": "ContinuousDecoder ragged requests, int8 KV caches "
                "(quantize_kv)",
        "gauges": ("serve.decode.jit_cache_size",
                   "serve.decode.kv_bytes_per_slot"),
        "noisy_counters": (), "noisy_events": (),
    },
    "decode_ssm": {
        "fn": _scn_decode_ssm,
        "desc": "ContinuousDecoder ragged requests, O(1) SSM state "
                "blobs (block_type='ssm')",
        "gauges": ("serve.decode.jit_cache_size",
                   "serve.decode.kv_bytes_per_slot"),
        "noisy_counters": (), "noisy_events": (),
    },
    "disagg": {
        "fn": _scn_disagg,
        "desc": "prefill/decode disaggregation: role-aware router, "
                "KV handoff with one injected mid-handoff fault",
        "gauges": ("serve.decode.jit_cache_size",
                   "serve.router.replicas_live"),
        "noisy_counters": (), "noisy_events": (),
    },
    "failover": {
        "fn": _scn_failover,
        "desc": "fleet replica death: token-exact generate failover "
                "+ one live mid-decode session migration",
        "gauges": ("serve.decode.jit_cache_size",
                   "serve.router.replicas_live"),
        "noisy_counters": (), "noisy_events": (),
    },
    "streaming": {
        "fn": _scn_streaming,
        "desc": "streamed generate frames (token-exact vs one-shot) "
                "+ chunked prefill, one decode replica on the wire",
        "gauges": ("serve.decode.jit_cache_size",
                   "serve.decode.kv_bytes_per_slot"),
        # emissions coalesce into wire frames per handler wakeup —
        # the frame count is scheduling-dependent, the token
        # sequence is not
        "noisy_counters": ("serve.net.stream_frames",),
        "noisy_events": (),
    },
    "spec_decode": {
        "fn": _scn_spec_decode,
        "desc": "speculative decoding: draft/verify rounds on one "
                "decode replica, token-exact vs plain decode",
        "gauges": ("serve.decode.jit_cache_size",
                   "serve.spec.draft_jit_cache_size",
                   "serve.decode.kv_bytes_per_slot"),
        "noisy_counters": (), "noisy_events": (),
    },
    "controller": {
        "fn": _scn_controller,
        "desc": "fleet controller: scripted scale-out, self-heal, "
                "scale-in, and one canary-gated rollback",
        "gauges": ("serve.router.replicas_live",
                   "serve.router.replicas"),
        "noisy_counters": (), "noisy_events": (),
    },
}

# field-path prefix -> the protected property a regression names.
# Ordered most-specific first; the first match wins.
_PROPERTY_NOTES = (
    ("counts.probe.max_step_syncs_steady",
     "PR 2 pipelined hot loop: at most ONE blocking host sync per "
     "steady-state step (a stray .asnumpy()/wait in the step loop "
     "re-serializes host and device)"),
    ("counts.probe.fit_total_syncs",
     "PR 2 pipelined hot loop: total blocking host syncs across the "
     "fit are budgeted (window drains + epoch metric reads only)"),
    ("counts.gauges.trainstep.jit_cache_size",
     "PR 11 donated-buffer sharding: ONE cached executable across "
     "donated steps (a growing jit cache is the step-2-recompile "
     "regression — outgoing state lost its pinned sharding)"),
    ("counts.gauges.gspmd.sharded_params",
     "PR 11 SpecLayout placement: the expected parameter count is "
     "sharded over the data×fsdp mesh"),
    ("counts.gauges.serve.decode.jit_cache_size",
     "PR 13 int8 continuous decode: ONE compiled (B, 1) step across "
     "slot turnover (a growing jit cache means admissions recompile "
     "— the per-admission-recompile regression continuous batching "
     "exists to avoid); with a speculative draft attached the target "
     "owns exactly TWO programs — the step plus the (B, gamma+1) "
     "verify (PR 18)"),
    ("counts.gauges.serve.spec.draft_jit_cache_size",
     "PR 18 speculative compile discipline: the draft owns exactly "
     "ONE compiled (B, 1) program across propose steps, catch-ups "
     "and slot turnover"),
    ("counts.counters.serve.spec.rounds",
     "PR 18 speculative serving: one verify forward per draft/"
     "verify round, exactly — a drifting round count means the "
     "acceptance walk or the round scheduler changed"),
    ("counts.counters.serve.spec.accepted",
     "PR 18 shared-noise verification: the accepted-token count is "
     "exact for a deterministic workload (a drift means draft "
     "proposal or target verification changed numerically — and "
     "token-exactness vs plain decode is probably gone with it)"),
    ("counts.counters.serve.spec.",
     "PR 18 speculative serving: draft-step/proposal/draft-prefill "
     "counters are exact for a deterministic request sequence"),
    ("counts.gauges.serve.decode.kv_bytes_per_slot",
     "PR 13/19 decode HBM diet: state bytes per slot follow from the "
     "cache pytree's shapes/dtypes alone — a drift means the int8 "
     "rows, per-token scale caches, or O(1) SSM state blobs changed "
     "layout"),
    ("counts.compile",
     "compile discipline: XLA compiles happen exactly where the "
     "baseline says (first step / per jit variant); extra compile "
     "events or a later compile-flagged step mean steady-state "
     "recompilation"),
    ("counts.counters.ps.retries",
     "PR 1 resilience: deterministic fault injection produces the "
     "exact retry count (exactly-once replay, no hidden extra "
     "round trips)"),
    ("counts.counters.ps.reconnects",
     "PR 1 resilience: reconnect-and-replay count under injected "
     "disconnects is exact"),
    ("counts.counters.guardrail.masked_steps",
     "PR 3 guardrails: the injected non-finite step is masked on "
     "device and counted exactly once"),
    ("counts.counters.serve.router.rerouted",
     "PR 14 shed-and-retry: a replica-local Overloaded retries on "
     "the next-least-loaded replica, counted exactly (a drifting "
     "reroute count means dispatch order or the on_fatal hook "
     "changed)"),
    ("counts.counters.serve.router.recycles",
     "PR 14 zero-drop rolling restarts: drain -> restart -> re-warm "
     "-> readmit ran to completion exactly as scripted"),
    ("counts.counters.serve.decode.streams",
     "PR 17 streaming: one stream per streamed generate, exactly — "
     "a drift means the frame subscription path double-registers or "
     "silently degrades to one-shot"),
    ("counts.counters.serve.decode.prefill_chunks",
     "PR 17 chunked prefill: ceil(prompt/MXNET_PREFILL_CHUNK) chunk "
     "forwards per long admission, exactly — a drift means the "
     "chunk loop re-runs slices or stopped interleaving"),
    ("counts.counters.serve.net.stream",
     "PR 17 streaming wire: streamed requests counted once at the "
     "server (frame counts are scheduling-dependent and excluded "
     "where streams run)"),
    ("counts.counters.serve.router.streams",
     "PR 17 streaming relay: the router relays frames without "
     "buffering, one stream per streamed generate"),
    ("counts.counters.serve.prefill.batched",
     "PR 17 batched prefill: coalesced prefill groups — nonzero "
     "only where concurrent prompts rode one padded forward"),
    ("counts.counters.serve.prefill.",
     "PR 15 disaggregation: prefill fan-out is exact — requests "
     "prefilled on prefill-role replicas and handoffs shipped, "
     "counted one per generate even across the injected mid-handoff "
     "replay (a drift means role-aware dispatch or the pure-replay "
     "path changed)"),
    ("counts.counters.serve.decode.imported",
     "PR 15 disaggregation: every admission of a remote-prefilled "
     "sequence is a scatter-only import — exactly one admit per "
     "request, zero prefill graph calls on the decode replica"),
    ("counts.counters.serve.router.generates",
     "PR 15 disaggregation: completed generate dispatches are exact "
     "for a deterministic request sequence"),
    ("counts.counters.serve.router.failovers",
     "PR 16 replica-death failover: a pinned replica whose probe "
     "fails is failed over exactly once per dead pin (a drift means "
     "the probe discriminator or pin handoff changed)"),
    ("counts.counters.serve.router.replays",
     "PR 16 token-exact replay: the recovery record replays a "
     "mid-flight generate exactly once — on the survivor after a "
     "dead pin, on the same replica after a transient fault"),
    ("counts.counters.serve.router.migrations",
     "PR 16 live session migration: each mid-decode session a "
     "recycle evacuates resumes on a survivor exactly once "
     "(bit-exact continuation, never a from-scratch replay)"),
    ("counts.counters.serve.router.evacuations",
     "PR 16 evacuating recycle: a decode-role recycle exports its "
     "active sessions instead of draining them — the evacuate count "
     "is exact for a scripted recycle"),
    ("counts.counters.serve.decode.resumed",
     "PR 16 migration landing: every evacuated session is admitted "
     "exactly once via the scatter-only resume path (no re-prefill, "
     "no divergence)"),
    ("counts.counters.serve.decode.evacuated",
     "PR 16 session export: the engine exports exactly the sessions "
     "the recycle evacuated mid-decode"),
    ("counts.counters.serve.decode.deduped",
     "PR 16 exactly-once admission: the decode dedup table swallows "
     "replayed admits — a drift means the admit-id lineage or the "
     "dedup window changed"),
    ("counts.counters.serve.router.",
     "PR 14 fleet router: dispatch/suspect/session counters are "
     "exact for a deterministic request sequence"),
    ("counts.gauges.serve.router.replicas_live",
     "PR 14 fleet health: every replica is live again after the "
     "recycle (a stuck draining/suspect replica shrinks the fleet)"),
    ("counts.counters.serve.ctrl.",
     "PR 20 fleet controller: scale-out/scale-in/heal/promote/"
     "rollback decisions are exact for a scripted signal sequence — "
     "a drift means hysteresis, cooldown, the liveness probe, or the "
     "rollout gate changed semantics"),
    ("counts.counters.serve.shed",
     "PR 9 backpressure: a full queue sheds with the typed "
     "Overloaded, counted exactly"),
    ("counts.counters.serve.",
     "PR 9 serving engine: admission/forward/decode counters are "
     "exact for a deterministic request sequence"),
    ("counts.counters.host_syncs",
     "PR 2 sync budget: the process-wide blocking-host-sync total "
     "for this deterministic workload is exact"),
    ("counts.journal_schema",
     "PR 8 journal schema version: readers refuse unknown schemas — "
     "bump SCHEMA_VERSION and re-bless deliberately, never drift"),
    ("counts.events",
     "PR 8/10 event vocabulary: every journal event the scenario "
     "used to emit must still be emitted, exactly as often"),
    ("counts.steps",
     "journal step records: the fit loops journal one record per "
     "step"),
    ("trace.",
     "PR 10 tracing: the span vocabulary / nesting shape of this "
     "path (a span that disappears or re-parents breaks trace "
     "consumers and usually marks deleted instrumentation)"),
    ("times.",
     "noise-tolerant CPU time bound (ratio tolerance, not exact — "
     "see --no-time / MXNET_GATE_TIME_RATIO)"),
)


def property_note(path):
    for prefix, note in _PROPERTY_NOTES:
        if path.startswith(prefix):
            return note
    return "gate fingerprint field (see docs/perf_gates.md)"


# ---------------------------------------------------------------------------
# fingerprint extraction
# ---------------------------------------------------------------------------

# the one torn-final-line-tolerant JSONL loader (the journal/spill
# write contract's read side) is shared across the tools — schema
# checked per file kind at the call sites in run_scenario
try:
    from tools.telemetry_report import load_jsonl
except ImportError:
    from telemetry_report import load_jsonl


def _intish(v):
    if isinstance(v, float) and v.is_integer():
        return int(v)
    return v


def extract_fingerprint(scenario, journal_records, trace_records):
    """The gate fingerprint: counts/shapes (exact-compared) + times
    (ratio-compared) from one scenario run's journal and trace spill."""
    from mxnet_tpu.trace import span_shape

    cfg = SCENARIOS[scenario]
    counts, times = {}, {}
    run_start = next((r for r in journal_records
                      if r.get("kind") == "run_start"), None)
    counts["journal_schema"] = (run_start or {}).get("schema")
    steps = [r for r in journal_records if r.get("kind") == "step"]
    counts["steps"] = len(steps)
    counts["compile_steps"] = sorted(
        int(s.get("step", -1)) for s in steps if s.get("compile"))

    events, probe = {}, {}
    for r in journal_records:
        if r.get("kind") != "event":
            continue
        ev = r.get("event", "?")
        events[ev] = events.get(ev, 0) + 1
        if ev == "gate.probe":
            for k, v in (r.get("fields") or {}).items():
                if k.endswith("_ms"):
                    times[k] = v
                else:
                    probe[k] = v
    counts["compile_events"] = events.get("compile", 0)
    counts["events"] = {k: v for k, v in sorted(events.items())
                        if k not in cfg["noisy_events"]}
    counts["probe"] = dict(sorted(probe.items()))

    snap = next((r.get("metrics") for r in reversed(journal_records)
                 if r.get("kind") == "snapshot"), None) or {}
    counts["counters"] = {
        k: _intish(v.get("value")) for k, v in sorted(snap.items())
        if v.get("type") == "counter" and k not in cfg["noisy_counters"]}
    counts["gauges"] = {}
    for g in cfg["gauges"]:
        val = snap.get(g, {}).get("value")
        # model_flops is workload-determined but large; presence +
        # exact value are both deterministic, so keep it exact
        counts["gauges"][g] = _intish(val) if val is not None else None

    steady = sorted(float(s.get("wall_ms", 0.0)) for s in steps
                    if not s.get("compile"))
    if steady:
        times["step_ms_p50"] = round(
            steady[int(round(0.5 * (len(steady) - 1)))], 3)

    return {"gate_schema": GATE_SCHEMA, "scenario": scenario,
            "counts": counts, "trace": span_shape(trace_records),
            "times": times}


# ---------------------------------------------------------------------------
# compare
# ---------------------------------------------------------------------------

class Failure:
    def __init__(self, path, baseline, live, why=None):
        self.path, self.baseline, self.live = path, baseline, live
        self.why = why

    def format(self):
        head = "%s: baseline %r -> live %r" % (
            self.path, self.baseline, self.live)
        if self.why:
            head += "  (%s)" % self.why
        return head + "\n      regressed property: %s" \
            % property_note(self.path)


def _cmp_tree(path, base, live, fails):
    if isinstance(base, dict) or isinstance(live, dict):
        bkeys = set(base or {}) if isinstance(base, dict) else set()
        lkeys = set(live or {}) if isinstance(live, dict) else set()
        for k in sorted(bkeys | lkeys):
            sub = "%s.%s" % (path, k)
            if k not in lkeys:
                fails.append(Failure(sub, (base or {}).get(k), None,
                                     "missing from live run"))
            elif k not in bkeys:
                fails.append(Failure(sub, None, (live or {}).get(k),
                                     "not in baseline — re-bless if "
                                     "intended"))
            else:
                _cmp_tree(sub, base[k], live[k], fails)
        return
    if base != live:
        fails.append(Failure(path, base, live))


def time_ratio_for(baseline, override=None):
    if override is not None:
        return float(override)
    env = os.environ.get("MXNET_GATE_TIME_RATIO")
    if env:
        return float(env)
    return float(baseline.get("time_ratio") or DEFAULT_TIME_RATIO)


def compare(baseline, live, time_ratio=None, check_times=True):
    """Baseline record (the perf_baselines/*.json dict) vs a live
    fingerprint -> list of Failure. Counts and trace shape are exact;
    times fail only beyond `time_ratio` x baseline."""
    fails = []
    bfp = baseline["fingerprint"]
    if bfp.get("gate_schema") != live.get("gate_schema"):
        fails.append(Failure("gate_schema", bfp.get("gate_schema"),
                             live.get("gate_schema")))
        return fails
    _cmp_tree("counts", bfp.get("counts"), live.get("counts"), fails)
    _cmp_tree("trace", bfp.get("trace"), live.get("trace"), fails)
    if check_times:
        ratio = time_ratio_for(baseline, time_ratio)
        for k, bv in sorted((bfp.get("times") or {}).items()):
            lv = (live.get("times") or {}).get(k)
            if lv is None:
                # a vanished time field means the probe/step records
                # that produced it stopped being emitted — deleted
                # instrumentation, not noise
                fails.append(Failure("times." + k, bv, None,
                                     "missing from live run"))
            elif bv and float(lv) > float(bv) * ratio:
                fails.append(Failure(
                    "times." + k, bv, lv,
                    "exceeds %.2gx ratio tolerance" % ratio))
    return fails


# ---------------------------------------------------------------------------
# the runner (parent side)
# ---------------------------------------------------------------------------

def scenario_env(out_dir):
    """The child's env: deterministic by construction. EVERY MXNET_*
    and BENCH_* knob from the operator's shell is dropped (a stray
    MXNET_DISPATCH_AHEAD=1 would shift the sync-count fingerprint and
    read as a false PR 2 regression) and XLA_FLAGS is pinned to
    exactly the forced-8-device mesh; then the six knobs the gate
    itself needs are set."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("MXNET_", "BENCH_"))}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["MXNET_TELEMETRY"] = os.path.join(out_dir, "journal.jsonl")
    env["MXNET_TRACE"] = os.path.join(out_dir, "trace.jsonl")
    env["PYTHONHASHSEED"] = "0"
    env["MXNET_PS_RETRY_BASE"] = "0.01"
    # no heartbeat may fire inside a scenario window (its ping count
    # would be timing-dependent)
    env["MXNET_PS_HEARTBEAT_INTERVAL"] = "600"
    return env


def run_scenario(name, out_dir, timeout=600):
    """Run one scenario subprocess; returns (fingerprint, None) or
    (None, failure_text). A scenario that dies before producing any
    journal is a GATE FAILURE with the child's stderr attached, never
    an unhandled traceback (the bench_common error-stub contract)."""
    os.makedirs(out_dir, exist_ok=True)
    env = scenario_env(out_dir)
    # journal + spill open in APPEND mode; a reused --keep dir must
    # not accumulate the previous run's records into this fingerprint
    for stale in (env["MXNET_TELEMETRY"], env["MXNET_TRACE"]):
        if os.path.exists(stale):
            os.unlink(stale)
    try:
        proc = subprocess.run(
            [sys.executable, _SELF, "--run-scenario", name],
            env=env, cwd=_REPO, capture_output=True, text=True,
            timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, "scenario %r timed out after %ds" % (name, timeout)
    tail = "\n".join(proc.stderr.strip().splitlines()[-8:])
    if proc.returncode != 0:
        return None, "scenario %r exited rc=%d before completing:\n%s" \
            % (name, proc.returncode, tail)
    jpath = env["MXNET_TELEMETRY"]
    tpath = env["MXNET_TRACE"]
    if not os.path.exists(jpath):
        return None, "scenario %r produced no journal at %s:\n%s" \
            % (name, jpath, tail)
    try:
        fp = extract_fingerprint(name, load_jsonl(jpath),
                                 load_jsonl(tpath)
                                 if os.path.exists(tpath) else [])
    except ValueError as e:
        return None, "scenario %r journal/trace unreadable: %s" \
            % (name, e)
    return fp, None


def baseline_path(name, baselines=None):
    return os.path.join(baselines or BASELINE_DIR, name + ".json")


def load_baseline(name, baselines=None):
    with open(baseline_path(name, baselines)) as f:
        return json.load(f)


def bless(name, fingerprint, baselines=None):
    path = baseline_path(name, baselines)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    rec = {"scenario": name,
           "description": SCENARIOS[name]["desc"],
           "time_ratio": DEFAULT_TIME_RATIO,
           "bless_cmd": "python tools/perf_gate.py --bless "
                        "--scenario " + name,
           "fingerprint": fingerprint}
    with open(path, "w") as f:
        json.dump(rec, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def _child_main(name):
    """Scenario body, run in the fresh subprocess the parent spawned
    (journal/trace destinations arrive via env). The name resolves
    BEFORE the journal opens, so a bad scenario dies with no journal —
    the exact before-any-journal failure the parent must report as a
    gate failure, not a traceback."""
    fn = SCENARIOS[name]["fn"]
    from mxnet_tpu import telemetry, trace
    t0 = telemetry.now_ms()
    telemetry.start_journal()
    trace.start_tracing()
    fn()
    telemetry.journal_event(
        "gate.probe", elapsed_ms=round(telemetry.now_ms() - t0, 3))
    trace.stop_tracing()
    telemetry.close_journal()


def main(argv=None):
    p = argparse.ArgumentParser(
        description="journal-backed perf-regression gate "
                    "(docs/perf_gates.md)")
    p.add_argument("--scenario", default=None,
                   help="comma-separated subset (default: all)")
    p.add_argument("--bless", action="store_true",
                   help="regenerate the baselines instead of comparing")
    p.add_argument("--baselines", default=None,
                   help="baseline dir (default perf_baselines/)")
    p.add_argument("--keep", default=None, metavar="DIR",
                   help="keep per-scenario journals/traces under DIR")
    p.add_argument("--no-time", action="store_true",
                   help="skip the wall-clock ratio checks")
    p.add_argument("--time-ratio", type=float, default=None,
                   help="override the time ratio tolerance")
    p.add_argument("--json", action="store_true",
                   help="emit a machine-readable result")
    p.add_argument("--run-scenario", default=None,
                   help=argparse.SUPPRESS)   # internal: child mode
    args = p.parse_args(argv)

    if args.run_scenario:
        _child_main(args.run_scenario)
        return 0

    names = list(SCENARIOS) if not args.scenario else [
        s.strip() for s in args.scenario.split(",") if s.strip()]
    for n in names:
        if n not in SCENARIOS:
            p.error("unknown scenario %r (have: %s)"
                    % (n, ", ".join(SCENARIOS)))

    import tempfile
    work = args.keep or tempfile.mkdtemp(prefix="perf_gate_")
    results = {}
    failed = False
    mode = "bless" if args.bless else "check"
    print("== perf gate (%s): %d scenario(s), baselines in %s =="
          % (mode, len(names), args.baselines or BASELINE_DIR))
    for name in names:
        fp, err = run_scenario(name, os.path.join(work, name))
        if err is not None:
            failed = True
            results[name] = {"status": "error", "error": err}
            print("  %-10s ERROR\n    %s" % (name,
                                             err.replace("\n", "\n    ")))
            continue
        if args.bless:
            path = bless(name, fp, args.baselines)
            results[name] = {"status": "blessed", "baseline": path}
            print("  %-10s blessed -> %s"
                  % (name, os.path.relpath(path, _REPO)))
            continue
        try:
            base = load_baseline(name, args.baselines)
        except (OSError, ValueError) as e:
            failed = True
            results[name] = {"status": "error",
                             "error": "no readable baseline: %s" % e}
            print("  %-10s ERROR no readable baseline (%s) — run "
                  "--bless and commit it" % (name, e))
            continue
        fails = compare(base, fp, time_ratio=args.time_ratio,
                        check_times=not args.no_time)
        if fails:
            failed = True
            results[name] = {"status": "fail",
                             "failures": [f.format() for f in fails]}
            print("  %-10s FAIL (%d divergence(s))" % (name, len(fails)))
            for f in fails:
                print("    - " + f.format())
        else:
            results[name] = {"status": "ok"}
            c = fp["counts"]
            print("  %-10s OK (steps=%d, %d compile event(s), %d "
                  "span name(s))"
                  % (name, c["steps"], c["compile_events"],
                     len(fp["trace"]["spans"])))
    if not args.keep and not failed:
        import shutil
        shutil.rmtree(work, ignore_errors=True)
    elif failed and not args.keep:
        print("artifacts kept for inspection under %s" % work)
    if args.json:
        print(json.dumps(results, indent=2))
    if failed:
        print("PERF GATE: FAIL — a committed-baseline property "
              "regressed (or changed intentionally: re-bless with "
              "tools/perf_gate.py --bless and commit the new "
              "baselines)")
        return 1
    print("PERF GATE: OK" if not args.bless else
          "PERF GATE: baselines regenerated — review + commit "
          "perf_baselines/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
