#!/usr/bin/env bash
# Thin wrapper (kept for muscle memory / existing docs): the perf +
# placement lints and the `gspmd`/hotloop/metric test subsets now live
# in tools/perf_gate.sh — the one superset entrypoint
# (docs/perf_gates.md).
#
#   tools/perf_smoke.sh
exec "$(dirname "$0")/perf_gate.sh" --only perf "$@"
