#!/usr/bin/env bash
# Hot-loop perf smoke: the pipelining + device-metric-parity test
# subset (tests/test_hotloop.py, CPU backend), the GSPMD one-jit
# subset (pytest marker `gspmd`), plus lints that keep the step loops
# and the placement layer honest. Run from anywhere.
#
#   tools/perf_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

# -- lint: no blocking host reads inside the step loops ------------------
# The pipelining claim (docs/performance.md "Pipelined training hot
# loop") dies one .asnumpy() at a time: a single D2H read per batch
# re-serializes host and device. The SPMD fit loop and the executor
# group's feed path must stay free of them (metric fallbacks and
# checkpoint/save paths live elsewhere).
lint_hits=$(grep -n "\.asnumpy()" \
    mxnet_tpu/parallel/trainer.py \
    mxnet_tpu/module/executor_group.py || true)
if [ -n "$lint_hits" ]; then
    echo "PERF LINT FAIL: blocking .asnumpy() in a step-loop file" >&2
    echo "$lint_hits" >&2
    echo "Feed device arrays (NDArray._data / place_batch) instead, or" >&2
    echo "move the read outside the per-step path." >&2
    exit 1
fi
echo "perf lint: OK (no .asnumpy() in trainer.py / executor_group.py)"

# -- lint: one placement layer ------------------------------------------
# All mesh placement routes through parallel/sharding.py
# (place/constrain + the layout objects). A raw jax.device_put or
# with_sharding_constraint in the module executors or the SPMD trainer
# bypasses the SpecLayout registry — exactly the drift the one-jit
# GSPMD path exists to prevent (docs/parallelism.md).
lint_hits=$(grep -rn "jax\.device_put\|with_sharding_constraint" \
    mxnet_tpu/module/*.py \
    mxnet_tpu/parallel/trainer.py || true)
if [ -n "$lint_hits" ]; then
    echo "PLACEMENT LINT FAIL: raw device_put/with_sharding_constraint" >&2
    echo "outside the placement layer (mxnet_tpu/parallel/sharding.py)" >&2
    echo "$lint_hits" >&2
    echo "Route it through sharding.place / sharding.constrain / the" >&2
    echo "bound layout instead." >&2
    exit 1
fi
echo "placement lint: OK (no raw device_put/with_sharding_constraint" \
     "in module/ or trainer.py)"

# -- the GSPMD one-jit subset (marker: gspmd) ----------------------------
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m pytest tests/ -q -m gspmd -p no:cacheprovider "$@"

# -- the pipelining + metric-parity subset -------------------------------
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m pytest tests/test_hotloop.py tests/test_metric.py -q \
    -p no:cacheprovider "$@"
