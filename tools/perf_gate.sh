#!/usr/bin/env bash
# THE one builder entrypoint (docs/perf_gates.md): every smoke lint,
# every marker test subset the four *_smoke.sh scripts used to own, the
# `gate` test subset, and the journal-backed perf-regression gate
# (tools/perf_gate.py vs the committed perf_baselines/). The four
# *_smoke.sh scripts are kept as thin delegating wrappers — a lint
# below rejects any new *_smoke.sh that does not route through here.
#
#   tools/perf_gate.sh                  # everything
#   tools/perf_gate.sh --only fault     # exactly what fault_smoke.sh ran
#   tools/perf_gate.sh --only perf|obs|serve|gate
#   tools/perf_gate.sh --skip-gate      # lints + test subsets only
#
# Extra args pass through to pytest. Slow tiers: FAULT_SMOKE_SLOW=1,
# OBS_SMOKE_SLOW=1, SERVE_SMOKE_SLOW=1 (unchanged from the wrappers).
set -euo pipefail
cd "$(dirname "$0")/.."

ONLY=all
SKIP_GATE=0
while [ $# -gt 0 ]; do
    case "$1" in
        --only) ONLY="$2"; shift 2 ;;
        --skip-gate) SKIP_GATE=1; shift ;;
        *) break ;;
    esac
done
PLATFORM="${JAX_PLATFORMS:-cpu}"

# ---------------------------------------------------------------------------
# lints
# ---------------------------------------------------------------------------

lint_fault() {
    # -- no silent exception swallowing in the parallel layer ------------
    # Bare `except Exception: pass` is how the pre-resilience hangs were
    # born: a swallowed transport error leaves a peer waiting forever.
    local hits
    hits=$(grep -rn -A1 "except Exception" mxnet_tpu/parallel/ \
        | grep -B1 "^[^:]*[-:][0-9]*[-:] *pass *$" || true)
    if [ -n "$hits" ]; then
        echo "FAULT LINT FAIL: bare 'except Exception: pass' in mxnet_tpu/parallel/" >&2
        echo "$hits" >&2
        echo "Classify the error (resilience.RetryPolicy.is_transient), re-raise, or log it." >&2
        exit 1
    fi
    echo "fault lint: OK (no silent exception swallowing in mxnet_tpu/parallel/)"

    # -- signal handlers must chain, not clobber -------------------------
    hits=$(grep -rn "signal\.signal(" mxnet_tpu/ \
        | grep -v "mxnet_tpu/guardrail\.py" \
        | grep -v "mxnet_tpu/kvstore_server\.py" || true)
    if [ -n "$hits" ]; then
        echo "SIGNAL LINT FAIL: raw signal.signal() outside guardrail.py/kvstore_server.py" >&2
        echo "$hits" >&2
        echo "Use guardrail.GracefulShutdown (chains the previous handler) instead of clobbering." >&2
        exit 1
    fi
    echo "signal lint: OK (no unguarded signal.signal registration)"
}

lint_perf() {
    # -- no blocking host reads inside the step loops --------------------
    # The pipelining claim (docs/performance.md) dies one .asnumpy() at
    # a time: a single D2H read per batch re-serializes host and device.
    local hits
    hits=$(grep -n "\.asnumpy()" \
        mxnet_tpu/parallel/trainer.py \
        mxnet_tpu/module/executor_group.py || true)
    if [ -n "$hits" ]; then
        echo "PERF LINT FAIL: blocking .asnumpy() in a step-loop file" >&2
        echo "$hits" >&2
        echo "Feed device arrays (NDArray._data / place_batch) instead, or" >&2
        echo "move the read outside the per-step path." >&2
        exit 1
    fi
    echo "perf lint: OK (no .asnumpy() in trainer.py / executor_group.py)"

    # -- one placement layer --------------------------------------------
    # All mesh placement routes through parallel/sharding.py; a raw
    # device_put/with_sharding_constraint elsewhere bypasses SpecLayout.
    hits=$(grep -rn "jax\.device_put\|with_sharding_constraint" \
        mxnet_tpu/module/*.py \
        mxnet_tpu/parallel/trainer.py || true)
    if [ -n "$hits" ]; then
        echo "PLACEMENT LINT FAIL: raw device_put/with_sharding_constraint" >&2
        echo "outside the placement layer (mxnet_tpu/parallel/sharding.py)" >&2
        echo "$hits" >&2
        echo "Route it through sharding.place / sharding.constrain / the" >&2
        echo "bound layout instead." >&2
        exit 1
    fi
    echo "placement lint: OK (no raw device_put/with_sharding_constraint" \
         "in module/ or trainer.py)"
}

lint_obs() {
    # -- ad-hoc timing must go through the telemetry registry ------------
    # A raw time.time()/time.perf_counter() call site in the hot layers
    # is a measurement nobody can see: it bypasses the registry, the
    # journal and the trace spill.
    local hits
    hits=$(grep -rn "time\.time()\|time\.perf_counter()" \
        mxnet_tpu/parallel/ mxnet_tpu/serve/ \
        | grep -v "/telemetry\.py:" | grep -v "/profiler\.py:" \
        | grep -v "/trace\.py:" || true)
    if [ -n "$hits" ]; then
        echo "OBS LINT FAIL: ad-hoc timing call site in the instrumented tree" >&2
        echo "$hits" >&2
        echo "Route the measurement through mxnet_tpu/telemetry.py" >&2
        echo "(telemetry.now_ms(), telemetry.histogram(...).timer())" >&2
        echo "or mxnet_tpu/trace.py spans." >&2
        exit 1
    fi
    echo "obs lint: OK (no ad-hoc timing in mxnet_tpu/parallel/ or mxnet_tpu/serve/)"

    # -- trace ids must be deterministic ---------------------------------
    hits=$(grep -nE "import uuid|uuid\.uuid|random\.random\(" \
        mxnet_tpu/trace.py || true)
    if [ -n "$hits" ]; then
        echo "OBS LINT FAIL: nondeterministic id source in mxnet_tpu/trace.py" >&2
        echo "$hits" >&2
        echo "Trace ids come from the seeded per-process counter (_next_id)." >&2
        exit 1
    fi
    echo "obs lint: OK (no uuid/random.random in mxnet_tpu/trace.py)"
}

lint_serve() {
    # -- raw sockets only in serve/net.py --------------------------------
    # Every byte on the serving wire goes through serve/net.py (ps_async
    # framing + FaultInjector hooks); a raw `socket.` call site — or a
    # bare `import socket` staging one — anywhere else (engine.py,
    # decode.py, the fleet router fanning out over ServeClient, the
    # disaggregation prefill engine shipping KV blobs) bypasses the
    # fault grammar and its tests: the prefill handoff leg is
    # killable ONLY because its bytes ride net.py's prefill_send/
    # prefill_recv points.
    local hits
    hits=$(grep -rnE "socket\.|^import socket|^from socket" \
        mxnet_tpu/serve/ \
        | grep -v "mxnet_tpu/serve/net\.py:" || true)
    if [ -n "$hits" ]; then
        echo "SERVE LINT FAIL: raw socket usage in mxnet_tpu/serve/ outside net.py" >&2
        echo "$hits" >&2
        echo "Route transport through mxnet_tpu/serve/net.py (ps_async framing" >&2
        echo "+ FaultInjector hooks) so MXNET_FAULT_SPEC keeps covering it —" >&2
        echo "router.py (per-replica families router<I>_*) and the disagg" >&2
        echo "handoff (prefill_send/prefill_recv) included." >&2
        exit 1
    fi
    echo "serve lint: OK (no raw socket usage in mxnet_tpu/serve/ outside net.py;" \
         "router.py + prefill.py included)"
}

lint_gate() {
    # -- every smoke script routes through this entrypoint ---------------
    # A new *_smoke.sh with its own lints/subsets re-fragments the build
    # checks this script exists to unify (ROADMAP item 5): add a section
    # here and make the new script a thin `exec perf_gate.sh --only X`
    # wrapper like the four existing ones.
    local f
    for f in tools/*_smoke.sh; do
        # require the actual delegation form, not a mere mention in a
        # comment: an exec line handing control to perf_gate.sh
        if ! grep -Eq '^[[:space:]]*exec .*perf_gate\.sh"? --only' "$f"; then
            echo "SMOKE LINT FAIL: $f does not route through tools/perf_gate.sh" >&2
            echo "Make it a thin wrapper (exec tools/perf_gate.sh --only <section>)" >&2
            echo "and put its lints/test subsets in a perf_gate.sh section." >&2
            exit 1
        fi
    done
    echo "smoke lint: OK (every tools/*_smoke.sh routes through perf_gate.sh)"
}

# ---------------------------------------------------------------------------
# test subsets (exactly what the four smoke scripts ran)
# ---------------------------------------------------------------------------

tests_fault() {
    local marker="faults and not slow" gmarker="guardrail and not slow"
    if [ "${FAULT_SMOKE_SLOW:-0}" = "1" ]; then
        marker="faults"; gmarker="guardrail"
    fi
    env JAX_PLATFORMS="$PLATFORM" \
        python -m pytest tests/test_dist_async.py -q -m "$marker" \
        -p no:cacheprovider "$@"
    env JAX_PLATFORMS="$PLATFORM" \
        python -m pytest tests/test_guardrail.py -q -m "$gmarker" \
        -p no:cacheprovider "$@"
}

tests_perf() {
    env JAX_PLATFORMS="$PLATFORM" \
        python -m pytest tests/ -q -m gspmd -p no:cacheprovider "$@"
    env JAX_PLATFORMS="$PLATFORM" \
        python -m pytest tests/test_hotloop.py tests/test_metric.py -q \
        -p no:cacheprovider "$@"
}

tests_obs() {
    local marker="(telemetry or trace) and not slow"
    if [ "${OBS_SMOKE_SLOW:-0}" = "1" ]; then
        marker="telemetry or trace"
    fi
    env JAX_PLATFORMS="$PLATFORM" \
        python -m pytest tests/test_telemetry.py tests/test_trace.py -q \
        -m "$marker" -p no:cacheprovider "$@"
}

tests_serve() {
    local marker="serve and not slow"
    if [ "${SERVE_SMOKE_SLOW:-0}" = "1" ]; then
        marker="serve"
    fi
    env JAX_PLATFORMS="$PLATFORM" \
        python -m pytest tests/test_serve.py tests/test_serve_decode.py \
        tests/test_serve_router.py tests/test_serve_disagg.py \
        tests/test_serve_failover.py tests/test_serve_streaming.py \
        tests/test_serve_ssm.py tests/test_serve_controller.py \
        -q -m "$marker" -p no:cacheprovider "$@"
    # deterministic chaos harness, smoke tier: 2-replica subprocess
    # fleet, one SIGKILL mid-run, every reply byte-equal to fault-free
    env JAX_PLATFORMS="$PLATFORM" python tools/chaos_fleet.py --smoke
    # controller tier: the FleetController (not the harness) must
    # respawn the SIGKILL'd replica — heals == kills, same contract
    env JAX_PLATFORMS="$PLATFORM" python tools/chaos_fleet.py \
        --controller --smoke
}

tests_gate() {
    env JAX_PLATFORMS="$PLATFORM" \
        python -m pytest tests/ -q -m "gate and not slow" \
        -p no:cacheprovider "$@"
}

run_gate() {
    # the journal-backed regression gate itself, against the COMMITTED
    # baselines (docs/perf_gates.md; --bless + commit after an intended
    # behavior change)
    env JAX_PLATFORMS="$PLATFORM" python tools/perf_gate.py
}

case "$ONLY" in
    fault)  lint_fault; tests_fault "$@" ;;
    perf)   lint_perf;  tests_perf "$@" ;;
    obs)    lint_obs;   tests_obs "$@" ;;
    serve)  lint_serve; tests_serve "$@" ;;
    gate)   lint_gate;  tests_gate "$@"; [ "$SKIP_GATE" = "1" ] || run_gate ;;
    all)
        lint_fault; lint_perf; lint_obs; lint_serve; lint_gate
        tests_fault "$@"; tests_perf "$@"; tests_obs "$@"
        tests_serve "$@"; tests_gate "$@"
        [ "$SKIP_GATE" = "1" ] || run_gate
        ;;
    *) echo "unknown --only section: $ONLY (fault|perf|obs|serve|gate)" >&2
       exit 2 ;;
esac
echo "== perf_gate.sh ($ONLY): all checks passed =="
