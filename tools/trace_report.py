"""Merge distributed-trace spill files into Chrome trace-event /
Perfetto JSON, plus a text critical-path summary per trace.

The spill files (schema v1, ``mxnet_tpu/trace.py``, written when
``MXNET_TRACE`` names a directory — one ``trace-<pid>.jsonl`` per
process) hold one JSON line per finished span or instant event. This
tool:

* merges any number of spill files (client + server processes of one
  job) into ONE Chrome trace-event JSON: a lane per (process, thread),
  complete ``X`` events for spans, ``i`` events for instants, and flow
  arrows (``s``/``f``) wherever a span's parent lives on a different
  thread or process — the wire/thread hops a single ``trace_id``
  causally stitches together;
* prints a critical-path summary per trace: from each root span, the
  longest-duration child chain, with durations, share of the root, and
  the process/thread transitions along the way.

    python tools/trace_report.py runs/trace-*.jsonl -o merged.json
    python tools/trace_report.py --text-only runs/trace-1234.jsonl

Open ``merged.json`` in https://ui.perfetto.dev or chrome://tracing.
Standalone on purpose: no framework import, so it runs anywhere the
spill files land. Torn-line tolerance matches the telemetry journal:
a crash tears at most a file's FINAL line and that is tolerated;
corruption anywhere earlier raises.
"""
import argparse
import json

# the one torn-line-tolerant loader lives in telemetry_report (both
# tools stay framework-import-free); sys.path[0] is tools/ when run as
# a program, the repo root when imported as a package module
try:
    from tools.telemetry_report import load_jsonl
except ImportError:
    from telemetry_report import load_jsonl

SCHEMA_VERSION = 1


def load(path):
    """Parse one spill file into a record list (torn final line
    tolerated, unknown schema refused — the shared
    telemetry_report.load_jsonl contract)."""
    return load_jsonl(path, schema=SCHEMA_VERSION, what="trace record")


def merge(paths):
    """All records of all spill files, in file order."""
    records = []
    for p in paths:
        records.extend(load(p))
    return records


def _span_index(spans):
    return {(r["trace"], r["span"]): r for r in spans}


def to_chrome(records):
    """The merged records as a Chrome trace-event JSON object
    (``{"traceEvents": [...], "displayTimeUnit": "ms"}``)."""
    spans = [r for r in records if r.get("kind") == "span"]
    instants = [r for r in records if r.get("kind") == "instant"]
    index = _span_index(spans)
    events = []

    # process/thread lane labels
    lanes = {}
    for r in spans + instants:
        lanes.setdefault((r["pid"], r["tid"]),
                         r.get("tname", "thread %d" % r["tid"]))
    for pid in sorted({pid for pid, _ in lanes}):
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": "pid %d" % pid}})
    for (pid, tid), tname in sorted(lanes.items()):
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": tname}})

    for r in spans:
        args = {"trace": r["trace"], "span": r["span"]}
        args.update(r.get("attrs") or {})
        events.append({"name": r["name"], "cat": "span", "ph": "X",
                       "ts": r["ts_us"], "dur": max(r.get("dur_us", 1), 1),
                       "pid": r["pid"], "tid": r["tid"], "args": args})
    for r in instants:
        args = dict(r.get("attrs") or {})
        if r.get("trace"):
            args["trace"] = r["trace"]
        events.append({"name": r["name"], "cat": "instant", "ph": "i",
                       "s": "t", "ts": r["ts_us"], "pid": r["pid"],
                       "tid": r["tid"], "args": args})

    # flow arrows: a span whose parent lives on another thread/process
    # is a causal hop (the PS/serve wire, or a cross-thread handoff in
    # the serve engine) — bind parent -> child with an s/f pair
    for r in spans:
        parent = index.get((r["trace"], r.get("parent")))
        if parent is None:
            continue
        if (parent["pid"], parent["tid"]) == (r["pid"], r["tid"]):
            continue
        fid = "%s:%s" % (r["trace"], r["span"])
        # the s event must sit inside the source slice and the f event
        # inside the destination slice for viewers to draw the arrow
        src_ts = min(max(parent["ts_us"], r["ts_us"]),
                     parent["ts_us"] + max(parent.get("dur_us", 1), 1))
        events.append({"name": "wire", "cat": "wire", "ph": "s",
                       "id": fid, "ts": src_ts, "pid": parent["pid"],
                       "tid": parent["tid"]})
        events.append({"name": "wire", "cat": "wire", "ph": "f",
                       "bp": "e", "id": fid, "ts": r["ts_us"],
                       "pid": r["pid"], "tid": r["tid"]})

    events.sort(key=lambda e: e.get("ts", 0))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def critical_path(records, max_traces=None):
    """Text critical-path summary: per trace, walk from the root span
    down the longest-duration child at every level."""
    spans = [r for r in records if r.get("kind") == "span"]
    index = _span_index(spans)
    by_trace = {}
    for r in spans:
        by_trace.setdefault(r["trace"], []).append(r)

    lines = ["critical path per trace (%d trace(s), %d span(s))"
             % (len(by_trace), len(spans)), "=" * 52]
    traces = sorted(by_trace.items(),
                    key=lambda kv: min(s["ts_us"] for s in kv[1]))
    if max_traces is not None and len(traces) > max_traces:
        lines.append("(showing the first %d of %d traces)"
                     % (max_traces, len(traces)))
        traces = traces[:max_traces]
    for trace_id, trace_spans in traces:
        children = {}
        for s in trace_spans:
            children.setdefault(s.get("parent"), []).append(s)
        roots = [s for s in trace_spans
                 if (trace_id, s.get("parent")) not in index]
        for root in sorted(roots, key=lambda s: s["ts_us"]):
            root_ms = root.get("dur_us", 1) / 1000.0
            lines.append("")
            lines.append("trace %s  root %s  %.3f ms"
                         % (trace_id, root["name"], root_ms))
            cur, depth = root, 0
            while True:
                kids = children.get(cur["span"])
                if not kids:
                    break
                nxt = max(kids, key=lambda s: s.get("dur_us", 0))
                depth += 1
                hop = ""
                if (nxt["pid"], nxt["tid"]) != (cur["pid"], cur["tid"]):
                    hop = "  [-> pid %d/%s]" % (
                        nxt["pid"], nxt.get("tname", nxt["tid"]))
                ms = nxt.get("dur_us", 1) / 1000.0
                share = 100.0 * ms / root_ms if root_ms else 0.0
                lines.append("  %s%s  %.3f ms  (%.1f%% of root)%s"
                             % ("  " * depth, nxt["name"], ms, share,
                                hop))
                cur = nxt
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("spills", nargs="+",
                   help="trace-*.jsonl spill file(s) to merge")
    p.add_argument("-o", "--out", default="trace.json",
                   help="merged Chrome trace-event JSON output "
                        "(default trace.json)")
    p.add_argument("--text-only", action="store_true",
                   help="print only the critical-path summary, write "
                        "no JSON")
    p.add_argument("--max-traces", type=int, default=50,
                   help="cap the summary's trace count (default 50)")
    args = p.parse_args(argv)
    records = merge(args.spills)
    try:
        if not args.text_only:
            payload = to_chrome(records)
            with open(args.out, "w") as f:
                json.dump(payload, f)
            print("wrote %s (%d events) — open in ui.perfetto.dev or "
                  "chrome://tracing" % (args.out,
                                        len(payload["traceEvents"])))
        print(critical_path(records, max_traces=args.max_traces))
    except BrokenPipeError:        # `... | head` is a normal usage
        pass


if __name__ == "__main__":
    main()
