#!/usr/bin/env bash
# Observability smoke: the telemetry + trace test subsets (pytest
# markers `telemetry` and `trace`, docs/observability.md) plus the
# lints that keep the timing/id discipline honest. Run from anywhere.
#
#   tools/obs_smoke.sh                 # fast tier
#   OBS_SMOKE_SLOW=1 tools/obs_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

# -- lint: ad-hoc timing must go through the telemetry registry ----------
# A raw time.time()/time.perf_counter() call site in the instrumented
# hot layers (mxnet_tpu/parallel/, mxnet_tpu/serve/) is a measurement
# nobody can see: it bypasses the registry (no histogram, no journal,
# no Prometheus export) and the trace spill. trace.py, telemetry.py and
# profiler.py are the sanctioned clock owners — instrumented code uses
# telemetry.now_ms() / Histogram.timer() / trace spans instead.
lint_hits=$(grep -rn "time\.time()\|time\.perf_counter()" \
    mxnet_tpu/parallel/ mxnet_tpu/serve/ \
    | grep -v "/telemetry\.py:" | grep -v "/profiler\.py:" \
    | grep -v "/trace\.py:" || true)
if [ -n "$lint_hits" ]; then
    echo "OBS LINT FAIL: ad-hoc timing call site in the instrumented tree" >&2
    echo "$lint_hits" >&2
    echo "Route the measurement through mxnet_tpu/telemetry.py" >&2
    echo "(telemetry.now_ms(), telemetry.histogram(...).timer())" >&2
    echo "or mxnet_tpu/trace.py spans." >&2
    exit 1
fi
echo "obs lint: OK (no ad-hoc timing in mxnet_tpu/parallel/ or mxnet_tpu/serve/)"

# -- lint: trace ids must be deterministic -------------------------------
# uuid / random.random in the trace layer would make span/trace ids
# irreproducible — a fault-injection test could no longer replay the
# identical trace structure, and two runs of one job would diverge.
id_hits=$(grep -nE "import uuid|uuid\.uuid|random\.random\(" \
    mxnet_tpu/trace.py || true)
if [ -n "$id_hits" ]; then
    echo "OBS LINT FAIL: nondeterministic id source in mxnet_tpu/trace.py" >&2
    echo "$id_hits" >&2
    echo "Trace ids come from the seeded per-process counter (_next_id)." >&2
    exit 1
fi
echo "obs lint: OK (no uuid/random.random in mxnet_tpu/trace.py)"

# -- the telemetry + trace test subsets ----------------------------------
marker="(telemetry or trace) and not slow"
if [ "${OBS_SMOKE_SLOW:-0}" = "1" ]; then
    marker="telemetry or trace"
fi
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m pytest tests/test_telemetry.py tests/test_trace.py -q \
    -m "$marker" -p no:cacheprovider "$@"
