#!/usr/bin/env bash
# Thin wrapper (kept for muscle memory / existing docs): the timing/id
# lints and the `telemetry`/`trace` test subsets now live in
# tools/perf_gate.sh — the one superset entrypoint (docs/perf_gates.md).
#
#   tools/obs_smoke.sh                 # fast tier
#   OBS_SMOKE_SLOW=1 tools/obs_smoke.sh
exec "$(dirname "$0")/perf_gate.sh" --only obs "$@"
