#!/usr/bin/env bash
# Observability smoke: the telemetry test subset (pytest marker
# `telemetry`, docs/observability.md) plus a lint that keeps the one
# timing source of truth honest. Run from anywhere.
#
#   tools/obs_smoke.sh                 # fast tier
#   OBS_SMOKE_SLOW=1 tools/obs_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

# -- lint: ad-hoc timing must go through the telemetry registry ----------
# A raw time.time()/time.perf_counter() call site in mxnet_tpu/parallel/
# is a measurement nobody can see: it bypasses the registry (no
# histogram, no journal, no Prometheus export). telemetry.py and
# profiler.py are the sanctioned clock owners — instrumented code uses
# telemetry.now_ms() / Histogram.timer() instead.
lint_hits=$(grep -rn "time\.time()\|time\.perf_counter()" mxnet_tpu/parallel/ \
    | grep -v "/telemetry\.py:" | grep -v "/profiler\.py:" || true)
if [ -n "$lint_hits" ]; then
    echo "OBS LINT FAIL: ad-hoc timing call site in mxnet_tpu/parallel/" >&2
    echo "$lint_hits" >&2
    echo "Route the measurement through mxnet_tpu/telemetry.py" >&2
    echo "(telemetry.now_ms(), telemetry.histogram(...).timer())." >&2
    exit 1
fi
echo "obs lint: OK (no ad-hoc time.time()/perf_counter() in mxnet_tpu/parallel/)"

# -- the telemetry test subset -------------------------------------------
marker="telemetry and not slow"
if [ "${OBS_SMOKE_SLOW:-0}" = "1" ]; then
    marker="telemetry"
fi
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m pytest tests/test_telemetry.py -q -m "$marker" \
    -p no:cacheprovider "$@"
