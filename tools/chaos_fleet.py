"""Deterministic chaos harness for the serve fleet
(docs/robustness.md §fleet failure semantics).

A subprocess replica fleet runs a closed-loop generate workload while
a declarative kill schedule SIGKILLs child replicas mid-run and
restarts them. The acceptance property is the fleet's whole
robustness contract in one sentence: EVERY request resolves to
exactly one successful response, token-for-token equal to the
fault-free run — greedy and seeded alike, and a STREAMED request's
concatenated frame tokens byte-equal the fault-free generated tail
even when the kill fires mid-stream (no duplicated, no missing
frames). Every second streamed request additionally decodes
SPECULATIVELY (a 1-layer truncated draft on every replica via
``MXNET_SPEC_DRAFT``, docs/serving.md §speculative) — the oracle
stays a plain generate, because speculation must never change a
byte, kills and draft-equipped failover replays included.

The schedule is the ``kill<I>`` member of the ``MXNET_FAULT_SPEC``
step-rule family (``parallel/resilience.py``): the call counted is
one COMPLETED fleet request, so the schedule is deterministic in
request-completion order, never wall time::

    python tools/chaos_fleet.py                     # 3 replicas,
                                                    # 6 clients x 25,
                                                    # kill1@40
    python tools/chaos_fleet.py --fault-spec kill0@20;kill2@80
    MXNET_FAULT_SPEC=kill2@60 python tools/chaos_fleet.py
    python tools/chaos_fleet.py --smoke             # perf-gate smoke
    python tools/chaos_fleet.py --controller        # controller tier
    python tools/chaos_fleet.py --controller --smoke

``--controller`` hands replica lifecycle to the ``FleetController``
(docs/serving.md §fleet controller): the harness only SIGKILLs —
the controller's own suspect -> probe -> heal path must respawn the
victim under the same name (the harness's restart thread is disabled,
so a controller that fails to heal FAILS the run: heals must equal
kills). The acceptance contract is unchanged on top: every request
exactly one response, byte-equal to the fault-free oracle.

``kill1@40`` SIGKILLs child replica index 1 when the 40th request
completes; the harness then restarts it (new subprocess, re-admitted
to the router under the same name) while the surviving replicas
absorb the load. Requests in flight on the victim fail over through
the router's recovery record (token-exact replay, dedup-guarded);
established decode sessions re-pin. The fault-free oracle is an
in-process ``Generator`` over the same deterministic seed-0 params
every replica builds, so byte-equality needs no second fleet run.

One JSON line out (``{"metric": "chaos_fleet", "ok": ...}``), exit
status 0 only when every request met the contract.
"""
import argparse
import json
import os
import re
import sys
import threading
import time

os.environ.setdefault("MXNET_MATMUL_PRECISION", "default")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

_KILL_RE = re.compile(r"(?:^|;)\s*kill(\d+)@")


def _lm_params(args):
    """Deterministic transformer-LM params every process shares (same
    seed everywhere — a migrated session's KV rows must be THIS
    model's rows on the survivor too)."""
    import mxnet_tpu as mx
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel import make_train_step

    sym = transformer.get_symbol(
        args.lm_vocab, 12, num_layers=args.lm_layers,
        num_heads=args.lm_heads, dim=args.lm_dim,
        max_len=args.lm_max_len)
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(0)
    return step.init_state(Xavier(), {"data": (2, 12),
                                      "softmax_label": (2, 12)})[0]


def _lm_generator(args, batch_size):
    from mxnet_tpu.generation import Generator
    return Generator(_lm_params(args), args.lm_vocab, args.lm_max_len,
                     num_layers=args.lm_layers,
                     num_heads=args.lm_heads, dim=args.lm_dim,
                     batch_size=batch_size)


def _replica_child(args):
    """``--replica`` subprocess body: one ContinuousDecoder +
    ServeServer, port announced as one JSON line on stdout, serving
    until stdin closes. ``install_sigterm=True``: a polite TERM
    evacuates active sessions back to the router instead of killing
    them — the harness's SIGKILL is the impolite case the failover
    path owns."""
    from mxnet_tpu.serve import ContinuousDecoder, ServeServer

    eng = ContinuousDecoder(_lm_generator(args, args.slots),
                            queue_cap=256, install_sigterm=True)
    srv = ServeServer(eng)
    print(json.dumps({"port": srv.port, "host": srv.host}), flush=True)
    try:
        while sys.stdin.readline():       # parent holds the pipe open
            pass
    finally:
        srv.close()
        eng.close(timeout=30.0)
    return 0


def _spawn_replica(args):
    """One replica subprocess; returns (proc, (host, port)). The
    child's env drops MXNET_FAULT_SPEC — kill rules schedule the
    PARENT's SIGKILLs; replicas themselves run fault-free."""
    import select
    import subprocess
    env = dict(os.environ)
    env.pop("MXNET_FAULT_SPEC", None)
    cmd = [sys.executable, os.path.abspath(__file__), "--replica",
           "--slots", str(args.slots),
           "--lm-vocab", str(args.lm_vocab),
           "--lm-dim", str(args.lm_dim),
           "--lm-layers", str(args.lm_layers),
           "--lm-heads", str(args.lm_heads),
           "--lm-max-len", str(args.lm_max_len)]
    proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                            stdout=subprocess.PIPE, text=True,
                            env=env)
    deadline = time.monotonic() + 300.0   # XLA import is the cost
    remain = deadline - time.monotonic()
    if remain <= 0 or not select.select([proc.stdout], [], [],
                                        remain)[0]:
        proc.kill()
        raise RuntimeError("replica startup timed out (rc=%s)"
                           % proc.poll())
    line = proc.stdout.readline()
    if not line:
        raise RuntimeError(
            "replica died before announcing its port (rc=%s)"
            % proc.poll())
    rec = json.loads(line)
    return proc, (rec["host"], rec["port"])


def _kill_fleet(procs):
    for p in procs:
        if p is None or p.poll() is not None:
            continue
        try:
            p.stdin.close()               # EOF = drain + exit
        except OSError:
            pass
    for p in procs:
        if p is None:
            continue
        try:
            p.wait(15.0)
        except Exception:  # noqa: BLE001 — escalate to kill
            p.kill()


def _request_plan(args):
    """The full request matrix, deterministic in (client, j): mixed
    greedy / seeded sampling, varied prompt lengths, eos enabled (a
    random tiny LM does emit eos early — the oracle matches
    bit-for-bit, so early stops are covered, not avoided). Every
    third request STREAMS (on_token frames): its collected tokens
    must concatenate byte-equal to the fault-free row's generated
    tail even when the kill schedule fires mid-stream — the
    delivered-prefix replay contract (docs/robustness.md §mid-stream
    failover)."""
    plan = {}
    for c in range(args.clients):
        for j in range(args.requests):
            rng = np.random.RandomState(7919 + 131 * c + j)
            prompt = rng.randint(1, args.lm_vocab,
                                 (3 + (c + j) % 4,)).astype(np.int64)
            seeded = (j % 2 == 1)
            plan[(c, j)] = {
                "prompt": prompt,
                "temperature": 0.8 if seeded else 0.0,
                "top_k": 8 if seeded else None,
                "seed": 1000 * c + j,
                "stream": (c + j) % 3 == 0,
                # every second STREAMED request runs speculatively
                # (docs/serving.md §speculative): the hint must
                # change nothing the oracle can see — same bytes
                # through draft/verify rounds, kills and replays on
                # draft-equipped survivors included
                "speculative": (c + j) % 6 == 0,
            }
    return plan


def _oracle_rows(args, plan):
    """The fault-free run: one in-process Generator emits every
    request's expected row up front (generate is deterministic, so
    this IS what an unfaulted fleet returns)."""
    gen = _lm_generator(args, 1)
    want = {}
    for key in sorted(plan):
        r = plan[key]
        want[key] = gen.generate(
            r["prompt"][None], args.max_new, eos_id=0,
            temperature=r["temperature"], top_k=r["top_k"],
            seed=r["seed"])[0]
    return want


def _run(args):
    from mxnet_tpu import telemetry
    from mxnet_tpu.parallel.resilience import FaultInjector
    from mxnet_tpu.serve import FleetController, ServeRouter

    spec = args.fault_spec or os.environ.get("MXNET_FAULT_SPEC") \
        or args.default_spec
    inj = FaultInjector(spec)             # validates the rule grammar
    kill_points = sorted({int(m) for m in _KILL_RE.findall(spec)})
    for i in kill_points:
        if i >= args.replicas:
            raise SystemExit(
                "kill%d@... targets a replica the fleet does not "
                "have (--replicas %d)" % (i, args.replicas))

    # every replica (restarts included — _spawn_replica copies this
    # env) builds a 1-layer truncated draft: speculative requests run
    # draft/verify rounds, and a kill mid-round fails over to a
    # survivor that decodes them speculatively too. The oracle stays
    # a PLAIN in-process generate — speculation is a performance
    # hint, so byte-equality against the unsped run IS the contract.
    os.environ.setdefault("MXNET_SPEC_DRAFT", "layers=1,gamma=4")

    plan = _request_plan(args)
    want = _oracle_rows(args, plan)

    procs, router, ctrl = [None] * args.replicas, None, None
    procs_by_addr = {}                    # "host:port" -> proc
    restarts, kills = [], []
    tick_lock = threading.Lock()
    completed = [0]
    results = {k: [] for k in plan}
    stream_toks = {k: [] for k in plan if plan[k]["stream"]}
    failures = []

    def ctrl_spawn(manifest=None):
        """Controller spawn hook (also boots the initial fleet):
        one subprocess replica, tracked by address so the retire
        hook can reap exactly the process behind a fleet slot."""
        proc, (host, port) = _spawn_replica(args)
        procs_by_addr["%s:%d" % (host, port)] = proc
        if router is not None:            # heal/rollout, not boot
            restarts.append({"at_request": completed[0]})
        return (host, port)

    def ctrl_retire(name, addr):
        proc = procs_by_addr.pop(addr, None)
        if proc is None:
            return
        if proc.poll() is None:
            try:
                proc.stdin.close()        # EOF = drain + exit
            except OSError:
                pass
        try:
            proc.wait(15.0)
        except Exception:  # noqa: BLE001 — escalate to kill
            proc.kill()

    def restart_replica(i, name):
        """Background: boot a fresh child, then swap it in under the
        victim's name (remove drops the dead entry's pins; in-flight
        requests to it fail over through the normal fault path)."""
        proc, (host, port) = _spawn_replica(args)
        procs[i] = proc
        try:
            router.remove_replica(name)
        except KeyError:
            pass
        router.add_replica(host, port, name=name)
        restarts.append({"replica": i, "at_request": completed[0]})

    def on_complete():
        with tick_lock:
            completed[0] += 1
            fired = [i for i in kill_points
                     if inj.on_chaos_tick("kill%d" % i)]
            for i in fired:
                name = "replica%d" % i
                if args.controller:
                    desc = router.replicas().get(name)
                    p = procs_by_addr.get(
                        "%s:%d" % (desc["host"], desc["port"])) \
                        if desc else None
                else:
                    p = procs[i]
                if p is not None and p.poll() is None:
                    p.kill()              # SIGKILL — no goodbye frame
                    p.wait()
                kills.append({"replica": i,
                              "at_request": completed[0]})
                if args.controller:
                    continue              # the CONTROLLER must heal it
                t = threading.Thread(
                    target=restart_replica,
                    args=(i, name), daemon=True)
                t.start()
                restart_threads.append(t)

    def client(c):
        for j in range(args.requests):
            r = plan[(c, j)]
            toks = [] if r["stream"] else None
            try:
                row = router.generate(
                    r["prompt"], args.max_new, eos_id=0,
                    temperature=r["temperature"], top_k=r["top_k"],
                    seed=r["seed"], session="c%d" % c,
                    timeout=args.deadline,
                    speculative=r["speculative"],
                    on_token=toks.append if r["stream"] else None)
            except Exception as exc:  # noqa: BLE001 — a failed
                # request IS the finding this harness exists to catch
                failures.append({"client": c, "j": j,
                                 "error": "%s: %s"
                                 % (type(exc).__name__, exc)})
                continue
            results[(c, j)].append(np.asarray(row))
            if r["stream"]:
                stream_toks[(c, j)].append(np.asarray(toks,
                                                      np.int64))
            on_complete()

    def heals():
        return int(telemetry.counter("serve.ctrl.heals").value)

    restart_threads = []
    t0 = time.monotonic()
    try:
        for i in range(args.replicas):
            if args.controller:
                addr = ctrl_spawn()
            else:
                procs[i], addr = _spawn_replica(args)
            if i == 0:
                addrs = []
            addrs.append(addr)
        router = ServeRouter(poll_ms=args.poll_ms,
                             conns_per_replica=args.clients + 2)
        for i, (host, port) in enumerate(addrs):
            router.add_replica(host, port, name="replica%d" % i)
        if args.controller:
            # supervision only — the huge sustain keeps autoscaling
            # out of the chaos contract, heal is streak-exempt
            ctrl = FleetController(router, ctrl_spawn,
                                   retire=ctrl_retire,
                                   min_replicas=1,
                                   max_replicas=args.replicas,
                                   sustain=10 ** 6,
                                   poll_ms=100.0)
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(args.clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for t in restart_threads:
            t.join(300.0)
        if ctrl is not None:
            # the controller owns respawn: hold the fleet open until
            # its heal count catches the kill schedule (bounded)
            deadline = time.monotonic() + 300.0
            while heals() < len(kills) and \
                    time.monotonic() < deadline:
                time.sleep(0.1)
        fleet = router.stats()
    finally:
        if ctrl is not None:
            ctrl.close()
        if router is not None:
            router.close()
        _kill_fleet(procs + list(procs_by_addr.values()))
    wall = time.monotonic() - t0

    mismatches = []
    for key in sorted(plan):
        got = results[key]
        if len(got) != 1:
            mismatches.append({"client": key[0], "j": key[1],
                               "responses": len(got)})
        elif not np.array_equal(got[0], want[key]):
            mismatches.append({"client": key[0], "j": key[1],
                               "got": got[0].tolist(),
                               "want": want[key].tolist()})
        elif key in stream_toks:
            # the streamed contract: concatenated frame tokens ==
            # the fault-free generated tail, exactly once, even when
            # a kill fired mid-stream
            tail = want[key][len(plan[key]["prompt"]):]
            if not np.array_equal(stream_toks[key][0], tail):
                mismatches.append(
                    {"client": key[0], "j": key[1], "kind": "stream",
                     "got": stream_toks[key][0].tolist(),
                     "want": tail.tolist()})

    def cval(name):
        e = telemetry.snapshot().get(name)
        return int(e["value"]) if e else 0

    ok = not failures and not mismatches and \
        len(kills) == len(kill_points) and \
        len(restarts) == len(kills) and \
        (not args.controller or cval("serve.ctrl.heals") == len(kills))
    print(json.dumps({
        "metric": "chaos_fleet",
        "ok": ok,
        "controller": bool(args.controller),
        "heals": cval("serve.ctrl.heals") if args.controller else None,
        "requests": args.clients * args.requests,
        "streamed": len(stream_toks),
        "speculative": sum(1 for r in plan.values()
                           if r["speculative"]),
        "clients": args.clients,
        "replicas": args.replicas,
        "fault_spec": spec,
        "kills": kills,
        "restarts": restarts,
        "failures": failures[:10],
        "mismatches": mismatches[:10],
        "failovers": cval("serve.router.failovers"),
        "replays": cval("serve.router.replays"),
        "migrations": cval("serve.router.migrations"),
        "rerouted": fleet.get("rerouted"),
        "wall_s": round(wall, 2)}))
    return 0 if ok else 1


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--clients", type=int, default=6)
    p.add_argument("--requests", type=int, default=25,
                   help="generates per client")
    p.add_argument("--max-new", type=int, default=8)
    p.add_argument("--deadline", type=float, default=240.0,
                   help="per-request end-to-end budget (seconds)")
    p.add_argument("--fault-spec", default=None,
                   help="kill schedule (MXNET_FAULT_SPEC kill<I>@nth "
                        "family; default env MXNET_FAULT_SPEC, then "
                        "the built-in schedule)")
    p.add_argument("--poll-ms", type=int, default=50)
    p.add_argument("--slots", type=int, default=4,
                   help="decode slots per replica")
    p.add_argument("--smoke", action="store_true",
                   help="perf-gate scale: 2 replicas, 2 clients x 3 "
                        "requests, kill1@2")
    p.add_argument("--controller", action="store_true",
                   help="controller tier: the FleetController owns "
                        "respawn (harness restart thread disabled); "
                        "heals must equal kills")
    p.add_argument("--lm-vocab", type=int, default=50)
    p.add_argument("--lm-dim", type=int, default=32)
    p.add_argument("--lm-layers", type=int, default=2)
    p.add_argument("--lm-heads", type=int, default=2)
    p.add_argument("--lm-max-len", type=int, default=24)
    p.add_argument("--replica", action="store_true",
                   help=argparse.SUPPRESS)   # internal: child mode
    args = p.parse_args(argv)
    if args.smoke:
        args.replicas, args.clients, args.requests = 2, 2, 3
        args.default_spec = "kill1@2"
    else:
        args.default_spec = "kill1@40"
    if args.replica:
        return _replica_child(args)
    return _run(args)


if __name__ == "__main__":
    sys.exit(main())
