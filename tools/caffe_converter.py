"""Convert Caffe models (.prototxt + .caffemodel) to this framework's
checkpoint format (symbol.json + .params).

Reference seam: tools/caffe_converter/ (convert_model.py /
caffe_parser.py / convert_symbol.py). The reference shells out to
caffe's generated protobuf bindings; here the .caffemodel is read with
a ~60-line protobuf WIRE-FORMAT walker (varint / length-delimited
field iteration against the well-known NetParameter field numbers), so
the converter needs neither caffe nor a compiled caffe.proto — it runs
in this repo's environment as-is.

Supported layer types (the classic-CNN vocabulary the reference's
converter handled): Convolution, InnerProduct, Pooling, ReLU, LRN,
Dropout, Softmax/SoftmaxWithLoss, BatchNorm (+ its paired Scale),
Eltwise (sum), Concat, Flatten, Input/Data. BatchNorm follows caffe's
split convention: the BatchNorm layer's blobs are (mean, var,
scale_factor) and the FOLLOWING Scale layer carries (gamma, beta);
they fuse into one framework BatchNorm node.

Usage:
    python tools/caffe_converter.py net.prototxt net.caffemodel out
    # writes out-symbol.json and out-0000.params; load with
    # mx.model.load_checkpoint("out", 0)
"""
from __future__ import annotations

import struct
import sys


# ---------------------------------------------------------------------------
# protobuf wire format (the subset caffemodel files use)
# ---------------------------------------------------------------------------

def _varint(buf, i):
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def _fields(buf):
    """Yield (field_no, wire_type, payload) over a message buffer.
    payload: int for varint/fixed, memoryview for length-delimited."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _varint(buf, i)
        field, wt = key >> 3, key & 7
        if wt == 0:                       # varint
            val, i = _varint(buf, i)
            yield field, wt, val
        elif wt == 1:                     # fixed64
            yield field, wt, struct.unpack_from("<q", buf, i)[0]
            i += 8
        elif wt == 2:                     # length-delimited
            ln, i = _varint(buf, i)
            yield field, wt, memoryview(buf)[i:i + ln]
            i += ln
        elif wt == 5:                     # fixed32
            yield field, wt, struct.unpack_from("<i", buf, i)[0]
            i += 4
        else:
            raise ValueError("unsupported wire type %d" % wt)


def _floats(payload, packed):
    import numpy as np
    if packed:
        return np.frombuffer(bytes(payload), "<f4")
    return np.array([struct.unpack("<f", struct.pack("<i", payload))[0]],
                    "<f4")


def _parse_blob(buf):
    """BlobProto: data=5 (packed float), shape=7 {dim=1}, legacy
    num/channels/height/width = 1/2/3/4."""
    import numpy as np
    data, shape, legacy = [], [], {}
    for f, wt, v in _fields(buf):
        if f == 5:
            data.append(_floats(v, wt == 2))
        elif f == 7 and wt == 2:
            shape = [val for ff, _, val in _fields(v) if ff == 1]
        elif f in (1, 2, 3, 4) and wt == 0:
            legacy[f] = v
    arr = np.concatenate(data) if data else np.zeros((0,), "<f4")
    if not shape and legacy:
        shape = [legacy.get(k, 1) for k in (1, 2, 3, 4)]
        while len(shape) > 1 and shape[0] == 1:
            shape = shape[1:]
    return arr.reshape(shape) if shape else arr


def _parse_layer(buf):
    """LayerParameter: name=1, type=2 (string; V1 uses enum), blobs=7."""
    out = {"name": None, "type": None, "blobs": []}
    for f, wt, v in _fields(buf):
        if f == 1 and wt == 2:
            out["name"] = bytes(v).decode()
        elif f == 2 and wt == 2:
            out["type"] = bytes(v).decode()
        elif f == 7 and wt == 2:
            out["blobs"].append(_parse_blob(v))
    return out


def parse_caffemodel(path):
    """-> list of {name, type, blobs} for layers that carry weights."""
    with open(path, "rb") as f:
        buf = f.read()
    layers = []
    for field, wt, v in _fields(buf):
        if field == 100 and wt == 2:          # layer (new format)
            layers.append(_parse_layer(v))
        elif field == 2 and wt == 2:          # layers (V1 format)
            lay = _parse_layer(v)
            if lay["name"] is not None:
                layers.append(lay)
    return [l for l in layers if l["blobs"]]


# ---------------------------------------------------------------------------
# prototxt (protobuf text format, the subset net definitions use)
# ---------------------------------------------------------------------------

def _tokenize(text):
    out, i, n = [], 0, len(text)
    while i < n:
        c = text[i]
        if c in " \t\r\n":
            i += 1
        elif c == "#":
            while i < n and text[i] != "\n":
                i += 1
        elif c in "{}:":
            out.append(c)
            i += 1
        elif c in "\"'":
            j = text.index(c, i + 1)
            out.append(("str", text[i + 1:j]))
            i = j + 1
        else:
            j = i
            while j < n and text[j] not in " \t\r\n{}:#\"'":
                j += 1
            out.append(("tok", text[i:j]))
            i = j
    return out


def _parse_block(toks, i=0):
    """-> (dict-of-lists, next_index); nested blocks recurse."""
    out = {}
    while i < len(toks):
        t = toks[i]
        if t == "}":
            return out, i + 1
        key = t[1]
        i += 1
        if toks[i] == ":":
            i += 1
            val = toks[i][1]
            i += 1
            if toks[i - 1][0] == "tok":
                low = val.lower()
                if low in ("true", "false"):
                    val = low == "true"
                else:
                    try:
                        val = int(val)
                    except ValueError:
                        try:
                            val = float(val)
                        except ValueError:
                            pass
        elif toks[i] == "{":
            val, i = _parse_block(toks, i + 1)
        else:
            raise ValueError("expected ':' or '{' after %r" % key)
        out.setdefault(key, []).append(val)
    return out, i


def parse_prototxt(path):
    with open(path) as f:
        net, _ = _parse_block(_tokenize(f.read()))
    return net


def _one(d, key, default=None):
    v = d.get(key)
    return v[0] if v else default


# V1 (upgrade-era) prototxts name layer types with enum tokens; map the
# ones this converter supports onto their modern string names so old
# nets get a real conversion instead of a KeyError.
_V1_LAYER_TYPES = {
    "ACCURACY": "Accuracy",
    "CONCAT": "Concat",
    "CONVOLUTION": "Convolution",
    "DATA": "Data",
    "DROPOUT": "Dropout",
    "ELTWISE": "Eltwise",
    "FLATTEN": "Flatten",
    "INNER_PRODUCT": "InnerProduct",
    "LRN": "LRN",
    "POOLING": "Pooling",
    "RELU": "ReLU",
    "SOFTMAX": "Softmax",
    "SOFTMAX_LOSS": "SoftmaxWithLoss",
}

# the rest of the V1 enum vocabulary (caffe.proto V1LayerParameter) —
# recognized so the error says "old prototxt" instead of a generic
# unknown-layer message
_V1_KNOWN_UNSUPPORTED = {
    "ARGMAX", "BNLL", "DUMMY_DATA", "EUCLIDEAN_LOSS", "EXP",
    "HDF5_DATA", "HDF5_OUTPUT", "HINGE_LOSS", "IM2COL", "IMAGE_DATA",
    "INFOGAIN_LOSS", "MEMORY_DATA", "MULTINOMIAL_LOGISTIC_LOSS", "MVN",
    "POWER", "SIGMOID", "SIGMOID_CROSS_ENTROPY_LOSS", "SILENCE",
    "SLICE", "SPLIT", "TANH", "THRESHOLD", "WINDOW_DATA",
}


def _canonical_type(ltype):
    """Modern string type for a layer's declared type, mapping V1 enum
    tokens; unsupported V1 enums get an actionable upgrade error."""
    if not isinstance(ltype, str):
        return ltype
    if ltype in _V1_LAYER_TYPES:
        return _V1_LAYER_TYPES[ltype]
    if ltype in _V1_KNOWN_UNSUPPORTED:
        raise NotImplementedError(
            "V1 enum layer type %r has no converter here — upgrade "
            "your prototxt (caffe's upgrade_net_proto_text) to the "
            "string-typed format, or port the layer" % ltype)
    return ltype


def _pair(param, key, default=0):
    """caffe kernel_size/pad/stride may repeat (h, w) or appear as
    *_h/*_w; normalize to a (h, w) tuple."""
    vals = param.get(key)
    if vals:
        return (vals[0], vals[-1]) if len(vals) > 1 \
            else (vals[0], vals[0])
    h = _one(param, key + "_h")
    w = _one(param, key + "_w")
    if h is not None or w is not None:
        return (h or default, w or default)
    return (default, default)


# ---------------------------------------------------------------------------
# symbol construction + weight mapping
# ---------------------------------------------------------------------------

def convert(prototxt, caffemodel=None):
    """-> (symbol, arg_params, aux_params) — framework-native objects.

    Layer name == our node name, so caffe blob k of layer L lands in
    the parameter the symbol names (L_weight, L_bias, L_gamma, ...).
    """
    import numpy as np

    import mxnet_tpu as mx

    net = parse_prototxt(prototxt)
    weights = {l["name"]: l for l in
               parse_caffemodel(caffemodel)} if caffemodel else {}

    tops = {}                       # caffe top name -> symbol
    arg_params, aux_params = {}, {}
    layers = net.get("layer") or net.get("layers") or []
    # caffe pairs BatchNorm with a following Scale layer; fuse them
    pending_bn = {}                 # top -> (name, mean, var, in, eps)
    n_softmax = sum(
        1 for l in layers
        if _V1_LAYER_TYPES.get(_one(l, "type"), _one(l, "type"))
        in ("Softmax", "SoftmaxWithLoss"))
    last_syms = []                  # output heads, in layer order

    def blob(lname, idx):
        lay = weights.get(lname)
        if lay is None or idx >= len(lay["blobs"]):
            return None
        return np.asarray(lay["blobs"][idx])

    # net-level inputs (input: "data" / input_shape or input_dim)
    for iname in net.get("input", []):
        tops[iname] = mx.sym.Variable(iname)

    for lay in layers:
        ltype = _canonical_type(_one(lay, "type"))
        name = _one(lay, "name")
        bottoms = [tops[b] for b in lay.get("bottom", [])]
        top = _one(lay, "top", name)

        if ltype in ("Input", "Data"):
            # train-prototxt Data layers declare BOTH tops
            # (top: "data" top: "label"); register every one
            for t in lay.get("top", [name]):
                tops[t] = mx.sym.Variable(t)
            continue
        if ltype == "Convolution":
            p = _one(lay, "convolution_param", {})
            kh, kw = _pair(p, "kernel_size")
            sh, sw = _pair(p, "stride", 1)
            ph, pw = _pair(p, "pad", 0)
            nf = _one(p, "num_output")
            nobias = not _one(p, "bias_term", True)
            group = _one(p, "group", 1)
            sym = mx.sym.Convolution(
                bottoms[0], num_filter=nf, kernel=(kh, kw),
                stride=(sh, sw), pad=(ph, pw), no_bias=nobias,
                num_group=group, name=name)
            w = blob(name, 0)
            if w is not None:
                arg_params["%s_weight" % name] = mx.nd.array(w)
            b = blob(name, 1)
            if b is not None and not nobias:
                arg_params["%s_bias" % name] = mx.nd.array(
                    b.reshape(-1))
        elif ltype == "InnerProduct":
            p = _one(lay, "inner_product_param", {})
            nh = _one(p, "num_output")
            nobias = not _one(p, "bias_term", True)
            sym = mx.sym.FullyConnected(
                mx.sym.Flatten(bottoms[0]), num_hidden=nh,
                no_bias=nobias, name=name)
            w = blob(name, 0)
            if w is not None:
                arg_params["%s_weight" % name] = mx.nd.array(
                    w.reshape(nh, -1))
            b = blob(name, 1)
            if b is not None and not nobias:
                arg_params["%s_bias" % name] = mx.nd.array(
                    b.reshape(-1))
        elif ltype == "Pooling":
            p = _one(lay, "pooling_param", {})
            global_pool = bool(_one(p, "global_pooling", False))
            kh, kw = _pair(p, "kernel_size")
            sh, sw = _pair(p, "stride", 1)
            ph, pw = _pair(p, "pad", 0)
            # caffe pool enum/string: 0/MAX, 1/AVE
            pt = _one(p, "pool", 0)
            pool_type = "avg" if pt in (1, "AVE") else "max"
            sym = mx.sym.Pooling(
                bottoms[0], kernel=(kh or 1, kw or 1),
                stride=(sh, sw), pad=(ph, pw), pool_type=pool_type,
                global_pool=global_pool,
                pooling_convention="full", name=name)
        elif ltype == "ReLU":
            p = _one(lay, "relu_param", {})
            slope = float(_one(p, "negative_slope", 0) or 0)
            if slope:
                # caffe's leaky ReLU lives on the ReLU layer as
                # negative_slope; dropping it silently rectified
                # every negative activation
                sym = mx.sym.LeakyReLU(bottoms[0], act_type="leaky",
                                       slope=slope, name=name)
            else:
                sym = mx.sym.Activation(bottoms[0], act_type="relu",
                                        name=name)
        elif ltype == "LRN":
            p = _one(lay, "lrn_param", {})
            sym = mx.sym.LRN(
                bottoms[0], nsize=_one(p, "local_size", 5),
                alpha=_one(p, "alpha", 1e-4),
                beta=_one(p, "beta", 0.75),
                knorm=_one(p, "k", 1.0), name=name)
        elif ltype == "Dropout":
            p = _one(lay, "dropout_param", {})
            sym = mx.sym.Dropout(
                bottoms[0], p=_one(p, "dropout_ratio", 0.5),
                name=name)
        elif ltype == "BatchNorm":
            bn_p = _one(lay, "batch_norm_param", {})
            bn_eps = _one(bn_p, "eps", 1e-5)
            mean, var = blob(name, 0), blob(name, 1)
            sf = blob(name, 2)
            if mean is not None and sf is not None and sf.size:
                # caffe stores UNSCALED accumulators
                scale = 1.0 / sf.reshape(-1)[0] if sf.reshape(-1)[0] \
                    else 0.0
                mean, var = mean * scale, var * scale
            pending_bn[top] = (name, mean, var, bottoms[0], bn_eps)
            tops[top] = bottoms[0]     # placeholder until Scale fuses
            continue
        elif ltype == "Scale":
            src = lay.get("bottom", [None])[0]
            if src in pending_bn:
                bn_name, mean, var, bn_in, bn_eps = \
                    pending_bn.pop(src)
                sym = mx.sym.BatchNorm(bn_in, eps=bn_eps,
                                       fix_gamma=False,
                                       use_global_stats=True,
                                       name=bn_name)
                if mean is not None:
                    aux_params["%s_moving_mean" % bn_name] = \
                        mx.nd.array(mean.reshape(-1))
                    aux_params["%s_moving_var" % bn_name] = \
                        mx.nd.array(var.reshape(-1))
                g, b = blob(name, 0), blob(name, 1)
                if g is not None:
                    arg_params["%s_gamma" % bn_name] = mx.nd.array(
                        g.reshape(-1))
                if b is not None:
                    arg_params["%s_beta" % bn_name] = mx.nd.array(
                        b.reshape(-1))
            else:
                raise NotImplementedError(
                    "standalone Scale layer %r (only the "
                    "BatchNorm+Scale pair is supported)" % name)
        elif ltype == "Eltwise":
            p = _one(lay, "eltwise_param", {})
            op = _one(p, "operation", 1)
            if op not in (1, "SUM"):
                raise NotImplementedError(
                    "Eltwise operation %r (only SUM)" % op)
            coeffs = [float(c) for c in p.get("coeff", [])]
            if coeffs and len(coeffs) != len(bottoms):
                raise ValueError(
                    "Eltwise layer %r: %d coeff values for %d bottoms"
                    % (name, len(coeffs), len(bottoms)))
            terms = bottoms if not coeffs else \
                [b if c == 1.0 else b * c
                 for b, c in zip(bottoms, coeffs)]
            sym = terms[0]
            for b in terms[1:]:
                sym = sym + b
        elif ltype == "Concat":
            p = _one(lay, "concat_param", {})
            sym = mx.sym.Concat(*bottoms,
                                dim=_one(p, "axis", 1), name=name)
        elif ltype == "Flatten":
            sym = mx.sym.Flatten(bottoms[0], name=name)
        elif ltype in ("Softmax", "SoftmaxWithLoss"):
            # single-head nets keep the conventional "softmax" name
            # (so softmax_label matches Module defaults); multi-loss
            # nets (GoogLeNet's three heads) keep their caffe names
            # to avoid node collisions
            sname = "softmax" if n_softmax == 1 else name
            if len(bottoms) > 1:       # explicit label bottom
                sym = mx.sym.SoftmaxOutput(bottoms[0], bottoms[1],
                                           name=sname)
            else:
                sym = mx.sym.SoftmaxOutput(bottoms[0], name=sname)
        elif ltype in ("Accuracy",):
            continue
        else:
            raise NotImplementedError(
                "caffe layer type %r (layer %r) has no converter"
                % (ltype, name))
        tops[top] = sym
        # the net's output = the last symbol actually PRODUCED (an
        # Accuracy/Data tail or a BN awaiting its Scale must not
        # dangle); multi-head nets group every loss head
        if ltype in ("Softmax", "SoftmaxWithLoss"):
            last_syms.append(sym)
        last_produced = sym

    if pending_bn:
        raise ValueError("BatchNorm layer(s) %r have no paired Scale"
                         % [v[0] for v in pending_bn.values()])
    if last_syms:
        out = last_syms[0] if len(last_syms) == 1 \
            else mx.sym.Group(last_syms)
    else:
        try:
            out = last_produced
        except UnboundLocalError:
            raise ValueError("prototxt produced no layers")
    return out, arg_params, aux_params


def main(argv):
    if len(argv) != 4:
        raise SystemExit("usage: caffe_converter.py net.prototxt "
                         "net.caffemodel out_prefix")
    import mxnet_tpu as mx
    sym, arg_params, aux_params = convert(argv[1], argv[2])
    mx.model.save_checkpoint(argv[3], 0, sym, arg_params, aux_params)
    print("wrote %s-symbol.json / %s-0000.params (%d args, %d aux)"
          % (argv[3], argv[3], len(arg_params), len(aux_params)))


if __name__ == "__main__":
    main(sys.argv)
