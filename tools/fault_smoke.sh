#!/usr/bin/env bash
# Fault-injection smoke: the failure-path test subset (pytest marker
# `faults`, docs/robustness.md) plus a lint that keeps the resilience
# layer honest. Run from anywhere; exercises only the fast in-thread
# tier unless FAULT_SMOKE_SLOW=1 adds the multi-process variants.
#
#   tools/fault_smoke.sh            # fast tier (deterministic, no kills)
#   FAULT_SMOKE_SLOW=1 tools/fault_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

# -- lint: no silent exception swallowing in the parallel layer ----------
# Bare `except Exception: pass` is how the pre-resilience hangs were
# born: a swallowed transport error leaves a peer waiting forever.
# Handle it, classify it, or at minimum log it.
lint_hits=$(grep -rn -A1 "except Exception" mxnet_tpu/parallel/ \
    | grep -B1 "^[^:]*[-:][0-9]*[-:] *pass *$" || true)
if [ -n "$lint_hits" ]; then
    echo "FAULT LINT FAIL: bare 'except Exception: pass' in mxnet_tpu/parallel/" >&2
    echo "$lint_hits" >&2
    echo "Classify the error (resilience.RetryPolicy.is_transient), re-raise, or log it." >&2
    exit 1
fi
echo "fault lint: OK (no silent exception swallowing in mxnet_tpu/parallel/)"

# -- lint: signal handlers must chain, not clobber -----------------------
# guardrail.GracefulShutdown chains the previous handler; a stray
# signal.signal() anywhere else clobbers it (and every other handler in
# the process). New registrations go through GracefulShutdown or get an
# explicit allowlist entry here.
sig_hits=$(grep -rn "signal\.signal(" mxnet_tpu/ \
    | grep -v "mxnet_tpu/guardrail\.py" \
    | grep -v "mxnet_tpu/kvstore_server\.py" || true)
if [ -n "$sig_hits" ]; then
    echo "SIGNAL LINT FAIL: raw signal.signal() outside guardrail.py/kvstore_server.py" >&2
    echo "$sig_hits" >&2
    echo "Use guardrail.GracefulShutdown (chains the previous handler) instead of clobbering." >&2
    exit 1
fi
echo "signal lint: OK (no unguarded signal.signal registration)"

# -- the fault-injection + guardrail test subsets ------------------------
marker="faults and not slow"
gmarker="guardrail and not slow"
if [ "${FAULT_SMOKE_SLOW:-0}" = "1" ]; then
    marker="faults"
    gmarker="guardrail"
fi
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m pytest tests/test_dist_async.py -q -m "$marker" \
    -p no:cacheprovider "$@"
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m pytest tests/test_guardrail.py -q -m "$gmarker" \
    -p no:cacheprovider "$@"
