#!/usr/bin/env bash
# Thin wrapper (kept for muscle memory / existing docs): the fault
# lints + `faults`/`guardrail` test subsets now live in
# tools/perf_gate.sh — the one superset entrypoint (docs/perf_gates.md).
#
#   tools/fault_smoke.sh            # fast tier (deterministic, no kills)
#   FAULT_SMOKE_SLOW=1 tools/fault_smoke.sh
exec "$(dirname "$0")/perf_gate.sh" --only fault "$@"
