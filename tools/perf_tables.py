"""Render markdown performance tables from bench_out/ artifacts.

Keeps docs/performance.md honest: every number in the docs should trace
to a committed capture, and regenerating the tables after a bench
session is one command:

    python tools/perf_tables.py            # prints markdown to stdout
    python tools/perf_tables.py --json     # machine-readable summary

Reads every *.json / *.jsonl under bench_out/ (one JSON object per
line), groups by metric, and prints the most recent record per
(metric, variant-ish key). Records with value=null are skipped.
"""
from __future__ import annotations

import argparse
import glob
import json
import os

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_records(out_dir):
    recs = []
    for path in sorted(glob.glob(os.path.join(out_dir, "*.json*"))):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line.startswith("{"):
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if rec.get("value") is None:
                        continue
                    rec["_file"] = os.path.basename(path)
                    recs.append(rec)
        except OSError:
            continue
    return recs


def _fmt(v):
    if isinstance(v, float):
        return "%.4g" % v
    return str(v)


def training_table(recs):
    rows = [r for r in recs
            if r.get("metric", "").endswith("_train_throughput")]
    if not rows:
        return ""
    out = ["## Training (one chip)", "",
           "| workload | value | unit | vs baseline | MFU | step ms |",
           "|---|---|---|---|---|---|"]
    seen = set()
    for r in rows:
        key = (r["metric"], r.get("seq_len"), r.get("window"),
               r.get("remat"))
        if key in seen:
            continue
        seen.add(key)
        name = r["metric"].replace("_train_throughput", "")
        if r.get("seq_len"):
            name += " T=%d" % r["seq_len"]
        if r.get("window"):
            name += " W=%d" % r["window"]
        if r.get("remat"):
            name += " (remat)"
        out.append("| %s | %s | %s | %s | %s | %s |" % (
            name, _fmt(r["value"]), r.get("unit", ""),
            _fmt(r.get("vs_baseline", "")),
            _fmt(r["mfu"]) if r.get("mfu") is not None else "",
            _fmt(r.get("step_time_ms", ""))))
    return "\n".join(out)


def decode_table(recs):
    rows = [r for r in recs if "decode_throughput" in
            r.get("metric", "")]
    if not rows:
        return ""
    out = ["## Decode / serving (one chip)", "",
           "| mode | tokens/s | ms/token | batch | quantize |",
           "|---|---|---|---|---|"]
    for r in rows:
        mode = "greedy"
        if r.get("beam"):
            mode = "beam-%d" % r["beam"]
        if r.get("quantize"):
            mode += " int8"
        out.append("| %s | %s | %s | %s | %s |" % (
            mode, _fmt(r["value"]), _fmt(r.get("ms_per_token", "")),
            r.get("batch", ""), r.get("quantize") or "-"))
    return "\n".join(out)


def bn_table(recs):
    rows = [r for r in recs
            if r.get("metric") == "batchnorm_train_fwd_bwd"]
    if not rows:
        return ""
    out = ["## BatchNorm one-pass vs two-pass (fwd+bwd)", "",
           "| shape | one-pass ms | two-pass ms | speedup |",
           "|---|---|---|---|"]
    for r in rows:
        out.append("| %s | %s | %s | %sx |" % (
            "x".join(str(d) for d in r["shape"]),
            _fmt(r["one_pass_ms"]), _fmt(r["two_pass_ms"]),
            _fmt(r["speedup"])))
    return "\n".join(out)


def pipeline_table(recs):
    rows = [r for r in recs if r.get("metric", "").startswith(
        "input_pipeline")]
    if not rows:
        return ""
    out = ["## Input pipeline", "",
           "| variant | img/s | threads | batch |",
           "|---|---|---|---|"]
    for r in rows:
        name = r.get("variant") or r["metric"].replace(
            "input_pipeline_", "")
        out.append("| %s | %s | %s | %s |" % (
            name, _fmt(r["value"]), r.get("threads", ""),
            r.get("batch", "")))
    return "\n".join(out)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out-dir", default=os.path.join(_REPO,
                                                     "bench_out"))
    p.add_argument("--json", action="store_true")
    args = p.parse_args()
    recs = load_records(args.out_dir)
    if args.json:
        print(json.dumps(recs, indent=1))
        return
    sections = [t for t in (training_table(recs), decode_table(recs),
                            bn_table(recs), pipeline_table(recs)) if t]
    if not sections:
        raise SystemExit("no records with values under %s"
                         % args.out_dir)
    print("\n\n".join(sections))


if __name__ == "__main__":
    main()
