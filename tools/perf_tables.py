"""Render markdown performance tables from bench_out/ artifacts.

Keeps docs/performance.md honest: every number in the docs should trace
to a committed capture, and regenerating the tables after a bench
session is one command:

    python tools/perf_tables.py            # prints markdown to stdout
    python tools/perf_tables.py --json     # machine-readable summary

Reads every *.json / *.jsonl under bench_out/ (one JSON object per
line), groups by metric, and prints the most recent record per
(metric, variant-ish key). Records with value=null are skipped, and so
are A/B experiment rows (`ab_config` tag from tpu_ab_regression.sh) —
they measure deliberately non-default configs and must never shadow
the numbers of record, in these tables or in bench.py's last_known
outage fallback (which shares is_experiment_row below).
"""
from __future__ import annotations

import argparse
import glob
import json
import os

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def is_experiment_row(rec):
    """True for A/B experiment records (tools/tpu_ab_regression.sh
    tags them ab_config) — deliberately non-default configurations
    that must never be selected as a number of record. Shared by the
    table renderer here and bench.py's last_known fallback."""
    return bool(rec.get("ab_config"))


def _mtime(path):
    try:
        return os.path.getmtime(path)
    except OSError:
        return 0.0


def load_records(out_dir):
    """Records in best-effort chronological order: files sorted by
    mtime (then name as the tiebreak — e.g. a fresh checkout where all
    mtimes match), lines within a file in append order. Downstream
    newest-wins dedup relies on this ordering."""
    recs = []
    paths = glob.glob(os.path.join(out_dir, "*.json*"))
    for path in sorted(paths, key=lambda p: (_mtime(p),
                                             os.path.basename(p))):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line.startswith("{"):
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if rec.get("value") is None:
                        continue
                    if is_experiment_row(rec):
                        continue
                    rec["_file"] = os.path.basename(path)
                    recs.append(rec)
        except OSError:
            continue
    return recs


def _fmt(v):
    if isinstance(v, float):
        return "%.4g" % v
    return str(v)


def _dedupe_newest(rows, keyfn):
    """Newest capture wins. load_records orders files by mtime and
    lines by append order, so the LAST record per key is the most
    recent — iterate reversed for the dedup, then restore encounter
    order for stable table layout (advisor r4: the old first-wins scan
    rendered the OLDEST record)."""
    newest = {}
    for r in reversed(rows):
        newest.setdefault(keyfn(r), r)
    out = []
    for r in rows:
        k = keyfn(r)
        if newest.get(k) is r:
            out.append(r)
    return out


def training_table(recs):
    rows = [r for r in recs
            if r.get("metric", "").endswith("_train_throughput")]
    if not rows:
        return ""
    out = ["## Training (one chip)", "",
           "| workload | value | unit | vs baseline | MFU | step ms |",
           "|---|---|---|---|---|---|"]
    for r in _dedupe_newest(rows, lambda r: (
            r["metric"], r.get("seq_len"), r.get("window"),
            r.get("remat"))):
        name = r["metric"].replace("_train_throughput", "")
        if r.get("seq_len"):
            name += " T=%d" % r["seq_len"]
        if r.get("window"):
            name += " W=%d" % r["window"]
        if r.get("remat"):
            name += " (remat)"
        out.append("| %s | %s | %s | %s | %s | %s |" % (
            name, _fmt(r["value"]), r.get("unit", ""),
            _fmt(r.get("vs_baseline", "")),
            _fmt(r["mfu"]) if r.get("mfu") is not None else "",
            _fmt(r.get("step_time_ms", ""))))
    return "\n".join(out)


def decode_table(recs):
    rows = [r for r in recs if "decode_throughput" in
            r.get("metric", "")]
    if not rows:
        return ""
    out = ["## Decode / serving (one chip)", "",
           "| mode | tokens/s | ms/token | batch | quantize | notes |",
           "|---|---|---|---|---|---|"]
    for r in _dedupe_newest(rows, lambda r: (
            r["metric"], r.get("quantize"), r.get("batch"),
            r.get("prompt_len"), r.get("new_tokens"))):
        mode = "greedy"
        if r.get("beam"):
            mode = "beam-%d" % r["beam"]
        if r.get("speculative_lookahead"):
            mode = "speculative-%d" % r["speculative_lookahead"]
        if r.get("kv_heads"):
            mode += " gqa-%d" % r["kv_heads"]
        if r.get("quantize"):
            mode += " " + str(r["quantize"])
        notes = ""
        if r.get("spec_accepted_per_round") is not None:
            notes = "%.2f accepted/round" % r["spec_accepted_per_round"]
        out.append("| %s | %s | %s | %s | %s | %s |" % (
            mode, _fmt(r["value"]), _fmt(r.get("ms_per_token", "")),
            r.get("batch", ""), r.get("quantize") or "-", notes))
    return "\n".join(out)


def bn_table(recs):
    rows = [r for r in recs
            if r.get("metric") == "batchnorm_train_fwd_bwd"]
    if not rows:
        return ""
    out = ["## BatchNorm one-pass vs two-pass (fwd+bwd)", "",
           "| shape | one-pass ms | two-pass ms | speedup |",
           "|---|---|---|---|"]
    for r in _dedupe_newest(rows, lambda r: tuple(r["shape"])):
        out.append("| %s | %s | %s | %sx |" % (
            "x".join(str(d) for d in r["shape"]),
            _fmt(r["one_pass_ms"]), _fmt(r["two_pass_ms"]),
            _fmt(r["speedup"])))
    return "\n".join(out)


def pipeline_table(recs):
    rows = [r for r in recs if r.get("metric", "").startswith(
        "input_pipeline")]
    if not rows:
        return ""
    out = ["## Input pipeline", "",
           "| variant | img/s | threads | batch |",
           "|---|---|---|---|"]
    for r in _dedupe_newest(rows, lambda r: (
            r["metric"], r.get("variant"), r.get("threads"))):
        name = r.get("variant") or r["metric"].replace(
            "input_pipeline_", "")
        out.append("| %s | %s | %s | %s |" % (
            name, _fmt(r["value"]), r.get("threads", ""),
            r.get("batch", "")))
    return "\n".join(out)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out-dir", default=os.path.join(_REPO,
                                                     "bench_out"))
    p.add_argument("--json", action="store_true")
    args = p.parse_args()
    recs = load_records(args.out_dir)
    if args.json:
        print(json.dumps(recs, indent=1))
        return
    sections = [t for t in (training_table(recs), decode_table(recs),
                            bn_table(recs), pipeline_table(recs)) if t]
    if not sections:
        raise SystemExit("no records with values under %s"
                         % args.out_dir)
    print("\n\n".join(sections))


if __name__ == "__main__":
    main()
