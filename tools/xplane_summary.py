"""Summarize a jax.profiler xplane trace: device time per HLO category.

The reproducible half of docs/mfu_analysis.md: turns a trace directory
into the BN-vs-matmul breakdown table.

    python - <<'PY'
    import jax
    jax.profiler.start_trace("/tmp/trace")
    ...  # run a few steps, sync with np.asarray(jax.device_get(x))
    jax.profiler.stop_trace()
    PY
    python tools/xplane_summary.py /tmp/trace

Parses the raw *.xplane.pb protos. On TPU the "/device:TPU:N" planes'
"XLA Ops" line holds the HLO-op events and the table is exact; on the
CPU backend the single "/host:CPU" plane also carries runtime/compile
events, so CPU output is indicative only. Two environment quirks this tool handles
(learned the hard way — see docs/mfu_analysis.md):
- must run under PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python (the
  tool re-execs itself to set this before importing the proto);
- uses tensorflow.tsl.profiler.protobuf.xplane_pb2 directly — the
  tensorboard_plugin_profile conversion API is broken against the
  installed TF 2.21.
"""
import collections
import glob
import os
import re
import sys

# the proto parse needs the pure-python protobuf backend; re-exec is
# only safe when WE are the program (an importer would restart itself)
if __name__ == "__main__" and \
        os.environ.get("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION") != \
        "python":
    os.environ["PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION"] = "python"
    os.execv(sys.executable, [sys.executable] + sys.argv)

# one quantile rule across the observability tools (run as a script,
# sys.path[0] is tools/; imported as a package module, it is the repo
# root — hence the two spellings)
try:
    from tools.telemetry_report import _quantile
except ImportError:
    from telemetry_report import _quantile


# op-name -> coarse category. Order matters: first match wins, so the
# specific multi-word keys (all-reduce, reduce-window) must precede the
# bare "reduce" of the bn-stats bucket.
_CATEGORIES = (
    ("collectives", ("all-reduce", "all-gather", "reduce-scatter",
                     "collective-permute", "all-to-all")),
    ("pooling", ("reduce-window", "select-and-scatter", "pool")),
    # reductions BEFORE the convert row: bf16->f32 statistics lower as
    # "%convert_reduce_fusion" — they are reduce work (the BN-stats
    # share this table exists to expose), not layout casts
    ("bn-stats / reductions", ("reduce", "variance", "norm")),
    # "convert" (dtype cast) before the "conv" substring would claim it
    ("copies / layout", ("convert",)),
    ("convolution", ("conv",)),
    ("matmul", ("dot", "einsum", "matmul")),
    ("copies / layout", ("copy", "transpose", "bitcast", "reshape",
                         "pad", "slice", "concatenate")),
    ("elementwise fusion", ("fusion", "add", "multiply", "subtract",
                            "divide", "tanh", "exp", "maximum")),
    ("custom / pallas", ("custom-call",)),
)


def _step_label(name, ev, stat_names):
    """Group key for one StepTraceAnnotation event: the annotation
    name plus its step_num/group_id stat when the plane carries one
    ('train_step#12'); TraceMe-encoded metadata ('name#k=v#') falls
    back to the raw name."""
    base = name.split("#", 1)[0]
    for st in ev.stats:
        if stat_names.get(st.metadata_id) in ("step_num", "group_id",
                                              "step_id"):
            v = st.int64_value or st.uint64_value
            return "%s#%d" % (base, v)
    return name


def _category(name):
    # events carry full HLO text ("%divide_subtract_fusion = (f32[...])
    # fusion(f32[...] %param), kind=kLoop ..."); match only the
    # instruction name plus the opcode token after "=", not operand text
    # (shape strings contain "slice"/"convert"-like substrings)
    low = name.lower()
    head = low.split(" = ", 1)
    if len(head) == 2:
        # opcode = the identifier right before the operand list, i.e.
        # after the result type (which itself contains parens/braces:
        # "(f32[8]{0:T(1024)}, ...) fusion(...)")
        m = re.search(r"[)}\]]\s+([a-z][a-z0-9._-]*)\(", head[1])
        low = head[0] + " " + (m.group(1) if m else "")
    for cat, keys in _CATEGORIES:
        if any(k in low for k in keys):
            return cat
    return "other"


def summarize(trace_dir):
    # check the backend protobuf ACTUALLY picked (the env var only
    # matters before the first protobuf import — a caller who imported
    # tensorflow first is already locked to the C++/upb backend)
    from google.protobuf.internal import api_implementation
    if api_implementation.Type() != "python":
        raise RuntimeError(
            "protobuf is running the %r backend, which mis-parses "
            "these planes; set PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION="
            "python before the FIRST protobuf/tensorflow import "
            "(running this file as a script does it automatically)"
            % api_implementation.Type())
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(
        trace_dir, "**", "*.xplane.pb"), recursive=True)
    if not paths:
        raise SystemExit("no *.xplane.pb under %s" % trace_dir)

    per_cat = collections.Counter()
    per_op = collections.Counter()
    step_ps = collections.Counter()     # StepTraceAnnotation groups
    total = 0
    async_ps = 0
    for path in paths:
        xspace = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xspace.ParseFromString(f.read())
        # when a real accelerator plane exists (TPU runs), host planes
        # must be ignored wholesale: their python-activity events (e.g.
        # "np.asarray(jax.Array)" blocking on a readback) span the whole
        # trace and would swamp the device table. /host:CPU is only the
        # compute plane on the CPU backend, where no device plane exists.
        has_device = any(p.name.startswith("/device:") and
                         any(ln.events for ln in p.lines)
                         for p in xspace.planes)
        for plane in xspace.planes:
            if has_device:
                if not plane.name.startswith("/device:"):
                    continue
            elif not (re.search(r"/device:|tpu|gpu", plane.name,
                                re.IGNORECASE)
                      or plane.name == "/host:CPU"):
                continue
            ev_names = {eid: em.name
                        for eid, em in plane.event_metadata.items()}
            stat_names = {sid: sm.name
                          for sid, sm in plane.stat_metadata.items()}
            # step groups (ISSUE 8 satellite): device planes carry a
            # "Steps" line with one event per StepTraceAnnotation (the
            # markers PR 2's profiler.step_scope emits) — aggregate
            # them into the per-step device-time table. These lines
            # overlap the per-op line, so they stay OUT of the
            # category/total tally below.
            for line in plane.lines:
                if line.name.lower() != "steps":
                    continue
                for ev in line.events:
                    name = ev_names.get(ev.metadata_id, "?")
                    step_ps[_step_label(name, ev, stat_names)] += \
                        ev.duration_ps
            # device planes carry overlapping lines: XLA Modules / Steps
            # span the same wall time as the per-op line, and "Async XLA
            # Ops" holds in-flight copy spans that overlap compute — keep
            # exactly the HLO-op line when one exists, else every line
            # (CPU backend)
            lines = [ln for ln in plane.lines
                     if ln.name.lower() == "xla ops"] or list(plane.lines)
            for line in lines:
                for ev in line.events:
                    name = ev_names.get(ev.metadata_id, "?")
                    # python host-activity frames leak into /host:CPU on
                    # the CPU backend AND into tunneled-TPU traces where
                    # no /device: plane exists (the round-3 capture's
                    # "np.asarray(jax.Array)" 73% artifact); keep
                    # HLO-op events only
                    if ".py:" in name or name.startswith("$") or \
                            name.startswith(("np.", "jax.",
                                             "PjitFunction",
                                             "PyArray", "Thread")):
                        continue
                    if name.split("#", 1)[0].split(" = ", 1)[0] == \
                            "train_step":
                        # a step marker leaking onto an op/host line
                        # (CPU backend has no Steps line): count it as
                        # a step group, never as device op work
                        step_ps[_step_label(name, ev, stat_names)] += \
                            ev.duration_ps
                        continue
                    dur = ev.duration_ps
                    # async copy/slice pairs (HBM<->VMEM prefetches from
                    # XLA's memory-space assignment, S(1) layouts) span
                    # wall time OVERLAPPED with compute — counting them
                    # as device work double-books the window (they
                    # dominated this table as "copies / layout" before
                    # this split). Track separately, out of the share
                    # denominator.
                    head = name.split(" = ", 1)[0]
                    if re.search(r"%(copy|slice|collective-permute|"
                                 r"all-reduce|all-gather|"
                                 r"reduce-scatter|all-to-all)"
                                 r"-(start|done)",
                                 head):
                        async_ps += dur
                        continue
                    per_cat[_category(name)] += dur
                    per_op[name] += dur
                    total += dur
    return per_cat, per_op, total, async_ps, dict(step_ps)


def _print_steps(step_ps):
    """Per-step device-time table from the StepTraceAnnotation groups
    (empty when the trace carries no step markers)."""
    if not step_ps:
        return
    print("\nstep groups (StepTraceAnnotation):")
    print("| step | device ms |")
    print("|---|---|")
    def _key(item):
        base, _, num = item[0].partition("#")
        return (base, int(num)) if num.isdigit() else (item[0], -1)

    shown = sorted(step_ps.items(), key=_key)
    for name, ps in shown[:30]:
        print("| %s | %.2f |" % (name, ps / 1e9))
    if len(shown) > 30:
        print("| ... %d more steps ... | |" % (len(shown) - 30))
    durs = sorted(ps / 1e9 for _, ps in shown)
    print("(%d steps; mean %.2f ms, p50 %.2f, p95 %.2f)"
          % (len(durs), sum(durs) / len(durs),
             _quantile(durs, 0.50), _quantile(durs, 0.95)))


def main():
    if len(sys.argv) != 2:
        raise SystemExit("usage: xplane_summary.py <trace_dir>")
    per_cat, per_op, total, async_ps, step_ps = summarize(sys.argv[1])
    if not total and not step_ps:
        raise SystemExit("no device events found (trace too short, or "
                         "only host planes present)")
    if total:
        print("device time by category:")
        print("| category | ms | share |")
        print("|---|---|---|")
        for cat, ps in per_cat.most_common():
            print("| %s | %.2f | %.1f%% |" % (cat, ps / 1e9,
                                              100.0 * ps / total))
        if async_ps:
            print("(async copy/collective start-done spans — HBM<->VMEM "
                  "prefetches and in-flight comm, overlapped with compute "
                  "— excluded above: %.2f ms)" % (async_ps / 1e9))
    _print_steps(step_ps)
    if total:
        print("\ntop 15 ops:")
        for name, ps in per_op.most_common(15):
            print("  %8.2f ms  %4.1f%%  %s" % (
                ps / 1e9, 100.0 * ps / total, name[:90]))


if __name__ == "__main__":
    main()
