#!/bin/bash
# One-shot TPU measurement session — run the moment the axon tunnel is
# up. Captures every number the docs/judge need, in priority order, so
# a flaky tunnel still yields the headline artifact first.
#
#   bash tools/tpu_bench_session.sh [outdir]
#
# Produces in <outdir> (default bench_out/):
#   resnet50.json            headline (the BENCH_rN.json payload)
#   transformer_lm.json      MFU workload
#   sweep.jsonl              catalog sweep (one line per network)
#   decode.json / decode_int8.json   KV-cache generation throughput
#   longcontext.jsonl        4k..32k single-chip context sweep
#   raw_jax_control.txt      framework-overhead control
#   trace/ + trace_summary.txt   xplane device-time breakdown
set -u -o pipefail
cd "$(dirname "$0")/.."
export OUT="${1:-bench_out}"
mkdir -p "$OUT"
FAILED=()
note() { [ "$1" -ne 0 ] && FAILED+=("$2 (rc=$1)"); true; }

echo "== 1. headline resnet-50 =="
python bench.py | tee "$OUT/resnet50.json"; note $? resnet50

echo "== 2. transformer LM (MFU workload) =="
python bench.py --network transformer_lm | tee "$OUT/transformer_lm.json"; note $? transformer_lm

echo "== 3. catalog sweep =="
: > "$OUT/sweep.jsonl"
for net in resnet-18 resnet-34 resnet-101 resnet-152 inception-bn \
           inception-v3 alexnet; do
  echo "-- $net"
  python bench.py --network "$net" | tee -a "$OUT/sweep.jsonl"; note $? "sweep:$net"
done

echo "== 3b. decode throughput (float + int8 + on-device beam) =="
python bench.py --network transformer_lm --decode | tee "$OUT/decode.json"; note $? decode
python bench.py --network transformer_lm --decode --quantize int8 \
    | tee "$OUT/decode_int8.json"; note $? decode_int8
python bench.py --network transformer_lm --decode --beam 4 \
    | tee "$OUT/decode_beam4.json"; note $? decode_beam4
BENCH_TLM_KV_HEADS=4 python bench.py --network transformer_lm --decode \
    | tee "$OUT/decode_gqa4.json"; note $? decode_gqa4

echo "== 3c. long-context sweep (batch 1) =="
: > "$OUT/longcontext.jsonl"
for T in 4096 8192 16384; do
  BENCH_ITERS=10 python bench.py --network transformer_lm --batch 1 \
      --seq-len "$T" | tee -a "$OUT/longcontext.jsonl"; note $? "lctx:$T"
done
BENCH_ITERS=5 python bench.py --network transformer_lm --batch 1 \
    --seq-len 32768 --remat | tee -a "$OUT/longcontext.jsonl"; note $? lctx:32768
# windowed attention: O(T*W) compute lets 32k train un-rematerialized
BENCH_ITERS=5 python bench.py --network transformer_lm --batch 1 \
    --seq-len 32768 --window 4096 \
    | tee -a "$OUT/longcontext.jsonl"; note $? lctx:32768w4096

echo "== 3d0. BatchNorm one-pass vs two-pass microbench =="
python benchmark/bench_bn.py | tee "$OUT/bn_micro.jsonl"; note $? bn_micro

echo "== 3d. input-pipeline train overlap (net img/s with real decode) =="
python benchmark/bench_input_pipeline.py --train-overlap \
    --n 512 --batch-size 128 --threads 8 \
    | tee "$OUT/pipeline_overlap.json"; note $? pipeline_overlap

echo "== 4. raw-JAX controls (resnet-50 + the sub-30%-MFU nets) =="
python benchmark/raw_jax_resnet.py | tee "$OUT/raw_jax_control.txt"; note $? raw_jax_control
python benchmark/raw_jax_controls.py --network alexnet \
    | tee -a "$OUT/raw_jax_control.txt"; note $? raw_jax_alexnet
python benchmark/raw_jax_controls.py --network inception-v3 \
    | tee -a "$OUT/raw_jax_control.txt"; note $? raw_jax_inception

echo "== 5. device trace + breakdown =="
python - <<'PY'
import os, sys
sys.path.insert(0, os.getcwd())
import numpy as np, jax
from mxnet_tpu.models import resnet
from mxnet_tpu.parallel import make_train_step
from mxnet_tpu.initializer import Xavier
sym = resnet.get_symbol(num_classes=1000, num_layers=50,
                        image_shape=(3, 224, 224))
step = make_train_step(sym, optimizer="sgd",
                       optimizer_params={"momentum": 0.9,
                                         "rescale_grad": 1.0 / 128},
                       compute_dtype="bfloat16")
state = step.init_state(Xavier(), {"data": (128, 3, 224, 224),
                                   "softmax_label": (128,)})
b = step.place_batch({
    "data": np.zeros((128, 3, 224, 224), np.float32),
    "softmax_label": np.zeros((128,), np.float32)})
rng = jax.random.PRNGKey(0)
state, outs = step(state, b, 0.1, rng)          # compile
np.asarray(jax.device_get(outs[0][0, 0]))
out = os.environ.get("OUT", "bench_out")
jax.profiler.start_trace(out + "/trace")
for _ in range(5):
    state, outs = step(state, b, 0.1, rng)
np.asarray(jax.device_get(outs[0][0, 0]))
jax.profiler.stop_trace()
print("trace done")
PY
python tools/xplane_summary.py "$OUT/trace" \
    | tee "$OUT/trace_summary.txt"; note $? trace_summary

if [ ${#FAILED[@]} -gt 0 ]; then
  echo "== session FINISHED WITH FAILURES: ${FAILED[*]}; artifacts in $OUT =="
  exit 1
fi
echo "== session complete; artifacts in $OUT =="
