#!/bin/bash
# One-shot TPU measurement session — run the moment the axon tunnel is
# up. Captures every number the docs/judge need, in priority order, so
# a flaky tunnel still yields the headline artifact first.
#
#   bash tools/tpu_bench_session.sh [outdir]
#
# Produces in <outdir> (default bench_out/):
#   resnet50.json            headline (the BENCH_rN.json payload)
#   transformer_lm.json      MFU workload
#   sweep.jsonl              catalog sweep (one line per network)
#   decode*.json             KV-cache generation (greedy/int8/beam/gqa/spec)
#   longcontext.jsonl        4k..32k single-chip context sweep
#   raw_jax_control.txt      framework-overhead control
#   trace/ + trace_summary.txt   xplane device-time breakdown
#
# Artifacts are written through a temp file and installed ONLY on
# stage success — a mid-session tunnel drop must never overwrite a
# previously-committed good capture with a value:null diagnostic
# (bench.py's last_known fallback reads these same files).
set -u -o pipefail
cd "$(dirname "$0")/.."
export OUT="${1:-bench_out}"
mkdir -p "$OUT"
FAILED=()
REFRESHED=()
note() { [ "$1" -ne 0 ] && FAILED+=("$2 (rc=$1)"); true; }

# Validate a would-be JSON capture BEFORE install: a diagnostic line
# (value null / live:false — the bench_common fail_payload contract,
# including the SIGTERM death stub) or torn/garbled output must never
# overwrite a previously-committed good capture that last_known cites.
# Non-JSON artifacts (trace_summary.txt etc.) skip the check.
ok_capture() {  # ok_capture <dest-name> <content-file>
  case "$1" in *.json|*.jsonl|*.jsonl.new) ;; *) return 0 ;; esac
  python - "$2" <<'PY'
import json, sys
ok = False
with open(sys.argv[1]) as f:
    for line in f:
        line = line.strip()
        if not line or not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            sys.exit(1)            # torn output: not installable
        # the fail_payload/death-stub diagnostic signature is
        # live:false (every whole-run failure path sets it). Anything
        # else that parses counts as a capture: micro benches carry
        # their own keys (one_pass_ms etc.), and a per-row error stub
        # WITHOUT live:false (the gspmd row) rides inside an otherwise
        # good sweep by design.
        if rec.get("live") is False:
            sys.exit(1)
        ok = True
sys.exit(0 if ok else 1)
PY
}

# stdout ONLY goes through tee into the artifact (stderr stays on the
# console/session log — backend warnings must never land inside a
# committed .json and break strict consumers)
cap() {   # cap <outfile> <label> <cmd...>: install output on success only
  local out="$1" label="$2"; shift 2
  local tmp; tmp="$(mktemp)"
  "$@" | tee "$tmp"
  local rc=${PIPESTATUS[0]}
  if [ "$rc" -eq 0 ] && [ -s "$tmp" ] && ok_capture "$out" "$tmp"; then
    mv "$tmp" "$out"; REFRESHED+=("$out")
  else rm -f "$tmp"; fi
  note "$rc" "$label"
}
capa() {  # capa <outfile> <label> <cmd...>: append on success only
  local out="$1" label="$2"; shift 2
  local tmp; tmp="$(mktemp)"
  "$@" | tee "$tmp"
  local rc=${PIPESTATUS[0]}
  if [ "$rc" -eq 0 ] && [ -s "$tmp" ] && ok_capture "$out" "$tmp"; then
    cat "$tmp" >> "$out"; REFRESHED+=("$out")
  fi
  rm -f "$tmp"
  note "$rc" "$label"
}

echo "== 1. headline resnet-50 =="
cap "$OUT/resnet50.json" resnet50 python bench.py

echo "== 2. transformer LM (MFU workload) =="
cap "$OUT/transformer_lm.json" transformer_lm \
    python bench.py --network transformer_lm

echo "== 3. catalog sweep =="
SWEEP="$OUT/sweep.jsonl.new"; : > "$SWEEP"
for net in resnet-18 resnet-34 resnet-101 resnet-152 inception-bn \
           inception-v3 alexnet; do
  echo "-- $net"
  capa "$SWEEP" "sweep:$net" python bench.py --network "$net"
done
[ -s "$SWEEP" ] && mv "$SWEEP" "$OUT/sweep.jsonl" || rm -f "$SWEEP"

echo "== 3b. decode throughput (float + int8 + beam + gqa + spec) =="
cap "$OUT/decode.json" decode \
    python bench.py --network transformer_lm --decode
cap "$OUT/decode_int8.json" decode_int8 \
    python bench.py --network transformer_lm --decode --quantize int8
cap "$OUT/decode_beam4.json" decode_beam4 \
    python bench.py --network transformer_lm --decode --beam 4
cap "$OUT/decode_gqa4.json" decode_gqa4 \
    env BENCH_TLM_KV_HEADS=4 python bench.py --network transformer_lm \
        --decode
cap "$OUT/decode_spec4.json" decode_spec4 \
    python bench.py --network transformer_lm --decode --speculative 4
# int8 KV caches matter most at long prompts (cache reads dominate)
cap "$OUT/decode_kv8.json" decode_kv8 \
    python bench.py --network transformer_lm --decode --quantize kv8 \
        --seq-len 1024
cap "$OUT/decode_int8kv8.json" decode_int8kv8 \
    python bench.py --network transformer_lm --decode \
        --quantize int8+kv8 --seq-len 1024
# serve-path A/B through the ContinuousDecoder slot pool: bf16 vs
# int8 cache bytes/slot + decode step ms + slots-per-HBM-budget
# (benchmark/bench_decode.py; the per-row q8 path, slot turnover on)
cap "$OUT/decode_kv_ab.json" decode_kv_ab \
    python benchmark/bench_decode.py
# O(1)-state decode A/B: f32 attention vs block_type="ssm" at long
# context — bytes/slot constant in max_len, slots-in-budget ratio,
# handoff bytes constant in prompt length (ISSUE 19)
cap "$OUT/decode_ssm_ab.json" decode_ssm_ab \
    env BENCH_DECODE_MODE=ssm python benchmark/bench_decode.py

echo "== 3c. long-context sweep (batch 1) =="
LCTX="$OUT/longcontext.jsonl.new"; : > "$LCTX"
for T in 4096 8192 16384; do
  capa "$LCTX" "lctx:$T" env BENCH_ITERS=10 python bench.py \
      --network transformer_lm --batch 1 --seq-len "$T"
done
capa "$LCTX" lctx:32768 env BENCH_ITERS=5 python bench.py \
    --network transformer_lm --batch 1 --seq-len 32768 --remat
# windowed attention cuts FLOPs, not activation residency: pair it
# with remat (un-rematerialized 32k OOMs — measured round 5)
capa "$LCTX" lctx:32768w4096 env BENCH_ITERS=5 python bench.py \
    --network transformer_lm --batch 1 --seq-len 32768 --window 4096 \
    --remat
# the chunked fused-CE head unlocks everything past 32k (the dense
# head's (B*T, vocab) logits are the OOM); 49152 = the longest
# single-chip config proven live round 5. 4-layer 65536 trips an
# axon remote-compile size cap — do not stage it.
capa "$LCTX" lctx:32768w4096chunk env BENCH_ITERS=5 \
    BENCH_TLM_LOSS_CHUNK=4096 python bench.py \
    --network transformer_lm --batch 1 --seq-len 32768 --window 4096 \
    --remat
capa "$LCTX" lctx:49152w4096chunk env BENCH_ITERS=3 \
    BENCH_TLM_LOSS_CHUNK=4096 python bench.py \
    --network transformer_lm --batch 1 --seq-len 49152 --window 4096 \
    --remat
[ -s "$LCTX" ] && mv "$LCTX" "$OUT/longcontext.jsonl" || rm -f "$LCTX"

echo "== 3d0. BatchNorm one-pass vs two-pass microbench =="
cap "$OUT/bn_micro.jsonl" bn_micro python benchmark/bench_bn.py

echo "== 3d1. max-pool dense backward vs SelectAndScatter =="
cap "$OUT/pool_micro.jsonl" pool_micro python benchmark/bench_pool.py

echo "== 3d2. embedding-grad formulation (scatter vs segsum vs matmul) =="
# BENCH_EMBGRAD_MODEL=1 adds the whole-model A/B (two bench.py runs):
# the round-5 lesson is that micro wins routinely lose at model level,
# so the staged capture must carry both or it cannot decide the knob
cap "$OUT/embgrad_micro.jsonl" embgrad_micro \
    env BENCH_EMBGRAD_MODEL=1 python benchmark/bench_embgrad.py

echo "== 3d. input-pipeline train overlap (net img/s with real decode) =="
cap "$OUT/pipeline_overlap.json" pipeline_overlap \
    python benchmark/bench_input_pipeline.py --train-overlap \
        --n 512 --batch-size 128 --threads 8

echo "== 4. raw-JAX controls (resnet-50 + the sub-30%-MFU nets) =="
CTRL="$OUT/raw_jax_control.txt.new"; : > "$CTRL"
capa "$CTRL" raw_jax_control python benchmark/raw_jax_resnet.py
capa "$CTRL" raw_jax_alexnet \
    python benchmark/raw_jax_controls.py --network alexnet
capa "$CTRL" raw_jax_inception \
    python benchmark/raw_jax_controls.py --network inception-v3
[ -s "$CTRL" ] && mv "$CTRL" "$OUT/raw_jax_control.txt" || rm -f "$CTRL"

echo "== 4b. serve engine offered-load sweep =="
# full-suite auto-capture (ROADMAP item 5): bench_serve/bench_scaling
# now carry the same last_known fallback as bench.py, and every tunnel
# window refreshes their committed captures here
cap "$OUT/serve.json" serve python bench_serve.py

echo "== 4b2. serve fleet sweep (router + subprocess replicas) =="
# the ROADMAP-2 scaling anchor: req/s should scale near-linearly in
# replicas at bounded p99 (docs/serving.md §fleet; CPU acceptance is
# >=2x at --replicas 3 vs 1 — on a TPU slice set --work-ms 0 so the
# real per-forward device time is the service time)
cap "$OUT/serve_fleet.json" serve_fleet \
    python bench_serve.py --replicas "${BENCH_FLEET_REPLICAS:-3}"

echo "== 4b3. prefill/decode disaggregation A/B =="
# disaggregated (P prefill + D decode replicas) vs colocated at equal
# chip count: decode inter-token p99 under concurrent long-prompt
# load (acceptance <= 0.7x), handoff cost vs one prefill (<= 0.15),
# int8-vs-bf16 blob bytes (<= 0.55) — docs/serving.md §disaggregated
cap "$OUT/serve_disagg.json" serve_disagg \
    python bench_serve.py --disagg "${BENCH_DISAGG_SPLIT:-1:1}"

echo "== 4b4. streaming + chunked-prefill A/B =="
# streamed frames vs one-shot (TTFT p50 <= 0.25x one-shot total at
# max_new >= 32) and chunked vs monolithic prefill under long-prompt
# load (inter-token p99 <= 0.5x) — docs/serving.md §streaming
cap "$OUT/serve_streaming.json" serve_streaming \
    python bench_serve.py --streaming

echo "== 4b5. speculative decoding A/B =="
# plain vs draft/verify continuous batching on one doctored target
# (effective inter-token p99 ratio < 1.0 and tokens per target
# forward > 1.5 at gamma=4, byte-identical output asserted) —
# docs/serving.md §speculative
cap "$OUT/serve_spec.json" serve_spec \
    python bench_serve.py --speculative

echo "== 4b6. fleet-controller load-doubling autoscale =="
# baseline load, then doubled clients (the FleetController must scale
# out mid-window on the sustained depth signal), then the doubled
# load against the grown fleet — acceptance >= 1 scale-out, zero
# errors, recovered p99 < pressure p99 (docs/serving.md §fleet
# controller)
cap "$OUT/serve_controller.json" serve_controller \
    python bench_serve.py --controller

echo "== 4c. scaling sweep + GSPMD one-jit row =="
# single chip unless the slice offers more (BENCH_SCALING_DEVICES=1,4,8
# on a multi-chip window); the gspmd row is the 28.8%->45% MFU
# trajectory anchor (docs/parallelism.md "One-jit GSPMD path")
# bench_scaling defaults its platform to cpu (dead-tunnel hang guard):
# hand it the session's real backend explicitly
cap "$OUT/scaling.json" scaling \
    env BENCH_PLATFORM="${BENCH_PLATFORM:-${JAX_PLATFORMS:-tpu}}" \
    python bench_scaling.py --devices "${BENCH_SCALING_DEVICES:-1}"

echo "== 5. device trace + breakdown =="
python - <<'PY'
import os, sys
sys.path.insert(0, os.getcwd())
import numpy as np, jax
from mxnet_tpu.models import resnet
from mxnet_tpu.parallel import make_train_step
from mxnet_tpu.initializer import Xavier
sym = resnet.get_symbol(num_classes=1000, num_layers=50,
                        image_shape=(3, 224, 224))
step = make_train_step(sym, optimizer="sgd",
                       optimizer_params={"momentum": 0.9,
                                         "rescale_grad": 1.0 / 128},
                       compute_dtype="bfloat16")
state = step.init_state(Xavier(), {"data": (128, 3, 224, 224),
                                   "softmax_label": (128,)})
b = step.place_batch({
    "data": np.zeros((128, 3, 224, 224), np.float32),
    "softmax_label": np.zeros((128,), np.float32)})
rng = jax.random.PRNGKey(0)
state, outs = step(state, b, 0.1, rng)          # compile
np.asarray(jax.device_get(outs[0][0, 0]))
out = os.environ.get("OUT", "bench_out")
jax.profiler.start_trace(out + "/trace")
for _ in range(5):
    state, outs = step(state, b, 0.1, rng)
np.asarray(jax.device_get(outs[0][0, 0]))
jax.profiler.stop_trace()
print("trace done")
PY
cap "$OUT/trace_summary.txt" trace_summary \
    python tools/xplane_summary.py "$OUT/trace"

# -- refresh summary (ROADMAP item 5): the full-suite auto-capture -----
# Every tunnel window that got this far refreshed its captures above;
# COMMITTING them is what makes bench_common.last_known able to cite
# this window after the tunnel dies again — only git-tracked captures
# count. Deduplicate (capa appends touch the same file repeatedly).
if [ ${#REFRESHED[@]} -gt 0 ]; then
  UNIQ=$(printf '%s\n' "${REFRESHED[@]}" | sort -u)
  echo "== refreshed captures this window =="
  printf '  %s\n' $UNIQ
  echo "commit them so the last_known fallback can cite this window:"
  echo "  git add $(echo $UNIQ | tr '\n' ' ')"
else
  echo "== no captures refreshed (nothing installable this window) =="
fi

if [ ${#FAILED[@]} -gt 0 ]; then
  echo "== session FINISHED WITH FAILURES: ${FAILED[*]}; artifacts in $OUT =="
  exit 1
fi
echo "== session complete; artifacts in $OUT =="
