#!/usr/bin/env python
"""im2rec — pack an image folder / .lst file into RecordIO shards
(reference: tools/im2rec.py + tools/im2rec.cc, multi-threaded OpenCV
there; thread-pool PIL here).

Usage (same CLI surface as the reference):
  python tools/im2rec.py prefix image_root --list    # make .lst
  python tools/im2rec.py prefix image_root           # pack .rec from .lst
"""
from __future__ import annotations

import argparse
import concurrent.futures
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from mxnet_tpu import recordio  # noqa: E402


_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def list_image(root, recursive=True):
    """Yield (index, relpath, label) walking root (reference
    im2rec.py:list_image)."""
    i = 0
    cat = {}
    for path, dirs, files in sorted(os.walk(root, followlinks=True)):
        dirs.sort()
        files.sort()
        for fname in files:
            fpath = os.path.join(path, fname)
            suffix = os.path.splitext(fname)[1].lower()
            if os.path.isfile(fpath) and suffix in _EXTS:
                if path not in cat:
                    cat[path] = len(cat)
                yield (i, os.path.relpath(fpath, root), cat[path])
                i += 1
        if not recursive:
            break


def write_list(path_out, image_list):
    with open(path_out, "w") as fout:
        for i, item in enumerate(image_list):
            line = "%d\t" % item[0]
            for j in item[2:]:
                line += "%f\t" % j
            line += "%s\n" % item[1]
            fout.write(line)


def read_list(path_in):
    with open(path_in) as fin:
        for line in fin:
            line = [i.strip() for i in line.strip().split("\t")]
            line_len = len(line)
            if line_len < 3:
                continue
            item = [int(line[0])] + [line[-1]] + \
                [float(i) for i in line[1:-1]]
            yield item


def _encode_image(args, item, root):
    from PIL import Image
    import io as _io
    import numpy as np
    fullpath = os.path.join(root, item[1])
    try:
        img = Image.open(fullpath).convert("RGB")
    except Exception as e:  # unreadable image -> skip
        print("imread error, skipping %s: %s" % (fullpath, e))
        return None
    if args.center_crop:
        w, h = img.size
        s = min(w, h)
        img = img.crop(((w - s) // 2, (h - s) // 2,
                        (w + s) // 2, (h + s) // 2))
    if args.resize:
        w, h = img.size
        if min(w, h) != args.resize:
            if w < h:
                nw, nh = args.resize, int(h * args.resize / w)
            else:
                nw, nh = int(w * args.resize / h), args.resize
            img = img.resize((nw, nh), Image.BILINEAR)
    buf = _io.BytesIO()
    img.save(buf, format="JPEG", quality=args.quality)
    header = recordio.IRHeader(0, item[2] if len(item) == 3
                               else item[2:], item[0], 0)
    return recordio.pack(header, buf.getvalue())


def make_rec(args, image_list, root, prefix):
    rec_path = prefix + ".rec"
    idx_path = prefix + ".idx"
    record = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    with concurrent.futures.ThreadPoolExecutor(args.num_thread) as pool:
        futures = [(item[0], pool.submit(_encode_image, args, item, root))
                   for item in image_list]
        count = 0
        for idx, fut in futures:
            packed = fut.result()
            if packed is None:
                continue
            record.write_idx(idx, packed)
            count += 1
            if count % 1000 == 0:
                print("processed %d images" % count)
    record.close()
    print("wrote %d records to %s" % (count, rec_path))


def main():
    parser = argparse.ArgumentParser(
        description="Create an image list / RecordIO file")
    parser.add_argument("prefix", help="prefix of .lst/.rec files")
    parser.add_argument("root", help="image root folder")
    parser.add_argument("--list", action="store_true",
                        help="create image list instead of record")
    parser.add_argument("--recursive", action="store_true", default=True)
    parser.add_argument("--shuffle", type=bool, default=True)
    parser.add_argument("--train-ratio", type=float, default=1.0)
    parser.add_argument("--test-ratio", type=float, default=0)
    parser.add_argument("--resize", type=int, default=0)
    parser.add_argument("--center-crop", action="store_true")
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--num-thread", type=int, default=4)
    args = parser.parse_args()

    if args.list:
        image_list = list(list_image(args.root, args.recursive))
        if args.shuffle:
            random.seed(100)
            random.shuffle(image_list)
        N = len(image_list)
        n_train = int(N * args.train_ratio)
        n_test = int(N * args.test_ratio)
        if args.train_ratio < 1.0:
            write_list(args.prefix + "_train.lst", image_list[:n_train])
            if n_test:
                write_list(args.prefix + "_test.lst",
                           image_list[n_train:n_train + n_test])
            write_list(args.prefix + "_val.lst",
                       image_list[n_train + n_test:])
        else:
            write_list(args.prefix + ".lst", image_list)
    else:
        lst = args.prefix + ".lst"
        assert os.path.isfile(lst), \
            "%s not found; run with --list first" % lst
        image_list = list(read_list(lst))
        make_rec(args, image_list, args.root, args.prefix)


if __name__ == "__main__":
    main()
