#!/bin/bash
# Auto-capture watcher for the flaky axon TPU tunnel.
#
# The tunnel has been down for entire driver rounds (BENCH_r02..r04 all
# recorded outages), so waiting for a human to notice an uptime window
# loses it. This watcher probes the backend cheaply every ~8 min; the
# moment a probe succeeds it runs the full staged measurement session
# (tools/tpu_bench_session.sh) ONCE, commits the bench_out/ artifacts,
# and exits — a transient window is never wasted.
#
#   nohup bash tools/tunnel_watch.sh >/tmp/tunnel_watch.log 2>&1 &
#
# State files (host-local, not committed):
#   /tmp/tunnel_status   one line per probe (UP/DOWN + timestamp)
#   /tmp/tpu_session.log session output on recovery
#
# Env knobs:
#   TUNNEL_PROBE_INTERVAL  seconds between probes (default 480)
#   TUNNEL_PROBE_TIMEOUT   per-probe hang cutoff (default 120)
#   TUNNEL_SESSION_BUDGET  max session seconds (default 5400)
#   TUNNEL_WATCH_LOOP=1    keep watching after a capture instead of
#                          exiting (for very long unattended runs)
set -u
cd "$(dirname "$0")/.."
INTERVAL="${TUNNEL_PROBE_INTERVAL:-480}"
PROBE_T="${TUNNEL_PROBE_TIMEOUT:-120}"
BUDGET="${TUNNEL_SESSION_BUDGET:-5400}"
while true; do
  # A dead tunnel HANGS inside backend init (never raises), so the
  # probe must live in a subprocess under a hard timeout.
  if timeout "$PROBE_T" python -c \
      "import jax; print(jax.devices()[0].device_kind)" \
      >/tmp/tunnel_probe.out 2>&1; then
    echo "UP $(date -u +%FT%TZ) $(cat /tmp/tunnel_probe.out)" \
        >> /tmp/tunnel_status
    echo "capturing..." >> /tmp/tunnel_status
    timeout "$BUDGET" bash tools/tpu_bench_session.sh bench_out \
        > /tmp/tpu_session.log 2>&1
    rc=$?
    echo "session rc=$rc $(date -u +%FT%TZ)" >> /tmp/tunnel_status
    # pathspec'd commit: never sweep unrelated staged work into the
    # auto-capture commit, and only bench_out/ moves
    git add bench_out/ 2>/dev/null
    git commit -q -m "TPU capture: bench session artifacts (auto-captured on tunnel recovery)

Full staged session: headline resnet-50, transformer LM, catalog
sweep, decode (float/int8/beam4/gqa4/speculative), long-context, BN
microbench, pipeline overlap, raw-JAX controls, device trace.
Session rc=$rc." -- bench_out/ 2>/dev/null
    echo "committed $(date -u +%FT%TZ)" >> /tmp/tunnel_status
    [ "${TUNNEL_WATCH_LOOP:-0}" = "1" ] || exit 0
  else
    echo "DOWN $(date -u +%FT%TZ)" >> /tmp/tunnel_status
  fi
  sleep "$INTERVAL"
done
