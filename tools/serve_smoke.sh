#!/usr/bin/env bash
# Thin wrapper (kept for muscle memory / existing docs): the transport
# lint and the `serve` test subset now live in tools/perf_gate.sh —
# the one superset entrypoint (docs/perf_gates.md).
#
#   tools/serve_smoke.sh                 # fast tier
#   SERVE_SMOKE_SLOW=1 tools/serve_smoke.sh
exec "$(dirname "$0")/perf_gate.sh" --only serve "$@"
