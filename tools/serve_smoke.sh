#!/usr/bin/env bash
# Serving smoke: the serving-engine test subset (pytest marker
# `serve`, docs/serving.md) plus a lint that keeps the transport
# boundary honest. Run from anywhere.
#
#   tools/serve_smoke.sh                 # fast tier
#   SERVE_SMOKE_SLOW=1 tools/serve_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

# -- lint: raw sockets only in serve/net.py ------------------------------
# The serving engine and the continuous decoder are transport-free by
# design: every byte on the wire goes through serve/net.py, which
# reuses the ps_async framing + FaultInjector hooks — a raw `socket.`
# call site anywhere else bypasses the fault grammar (and its tests).
lint_hits=$(grep -rn "socket\." mxnet_tpu/serve/ \
    | grep -v "mxnet_tpu/serve/net\.py:" || true)
if [ -n "$lint_hits" ]; then
    echo "SERVE LINT FAIL: raw socket. usage in mxnet_tpu/serve/ outside net.py" >&2
    echo "$lint_hits" >&2
    echo "Route transport through mxnet_tpu/serve/net.py (ps_async framing" >&2
    echo "+ FaultInjector hooks) so MXNET_FAULT_SPEC keeps covering it." >&2
    exit 1
fi
echo "serve lint: OK (no raw socket. usage in mxnet_tpu/serve/ outside net.py)"

# -- the serving test subset ---------------------------------------------
marker="serve and not slow"
if [ "${SERVE_SMOKE_SLOW:-0}" = "1" ]; then
    marker="serve"
fi
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m pytest tests/test_serve.py tests/test_serve_decode.py \
    -q -m "$marker" -p no:cacheprovider "$@"
