"""Weak-scaling harness for the SPMD train step — the analogue of the
reference's multi-GPU/multi-node scaling tables
(example/image-classification/README.md:302-319, AlexNet/Inception-v3/
ResNet-152 on 1..256 K80s at ~90% efficiency).

Runs the same per-device batch on growing device counts and reports
step time, weak-scaling efficiency, and the collective traffic XLA
inserted (parsed from the optimized HLO: all-reduce / all-gather /
reduce-scatter / collective-permute / all-to-all output bytes).

Without real multi-chip hardware it runs on a virtual CPU mesh
(xla_force_host_platform_device_count) — collective BYTES are exact
(they're a property of the partitioning, not the fabric), times are
correctness-grade only. On a real slice run it unchanged:

    python bench_scaling.py                      # 1,2,4,8 devices, resnet-8
    python bench_scaling.py --devices 1,4,8 --network transformer_lm
    python bench_scaling.py --zero1              # + sharded optimizer

Prints one JSON line per device count, a GSPMD one-jit row (the
`data × fsdp` SpecLayout + ZeRO-sharded optimizer path of
docs/parallelism.md "One-jit GSPMD path"; --skip-gspmd drops it), a
summary line {"metric": "scaling_sweep", ...} the driver can archive,
then a markdown table. On backend failure the summary line carries the
newest COMMITTED bench_out/ capture as a `last_known` sub-object
(bench.py's tunnel-outage pattern via bench_common.py) instead of a
stack trace.
"""
import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--devices", default="1,2,4,8",
                   help="comma-separated device counts")
    p.add_argument("--network", default="resnet",
                   choices=["resnet", "transformer_lm"])
    p.add_argument("--seq-parallel", action="store_true",
                   help="transformer_lm over an 'sp' mesh (ring "
                        "attention) instead of a data mesh")
    p.add_argument("--window", type=int, default=0,
                   help="banded (windowed) attention for "
                        "transformer_lm, all rows incl. the GSPMD one; "
                        "with --seq-parallel the ring communication "
                        "scales with the window")
    p.add_argument("--expert-parallel", action="store_true",
                   help="transformer_lm MoE over an 'expert' mesh "
                        "(all_to_all token exchange); experts = 2x "
                        "devices")
    p.add_argument("--per-device-batch", type=int, default=8)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--zero1", action="store_true",
                   help="shard optimizer state (ZeRO-1)")
    p.add_argument("--skip-gspmd", action="store_true",
                   help="drop the one-jit GSPMD (data x fsdp "
                        "SpecLayout + sharded-optimizer) row")
    p.add_argument("--fsdp", type=int, default=0,
                   help="fsdp axis size for the GSPMD row (0 = auto: "
                        "largest of 4/2/1 dividing the device count)")
    p.add_argument("--full-size", action="store_true",
                   help="the REAL bench.py configs (resnet-50 224px "
                        "batch 128/dev; transformer dim 2048): exact "
                        "collective bytes for the roofline in "
                        "docs/scaling.md. Pair with --compile-only on "
                        "a CPU host")
    p.add_argument("--compile-only", action="store_true",
                   help="lower+compile and report collective bytes "
                        "without running the step")
    return p.parse_args()


_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")
_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4,
                "u32": 4, "s64": 8, "u64": 8, "pred": 1, "s8": 1,
                "u8": 1}
# every `dtype[dims]` group in an instruction's output shape (tuple
# outputs like `(f32[8], /*index=1*/f32[8]) all-reduce(...)` list many,
# with index comments interleaved)
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
# the executing op: whitespace-preceded collective name followed by its
# operand list paren. Operand REFERENCES (`get-tuple-element(%all-reduce
# .82)`) don't match: there the name is followed by `)` or `,`, not `(`.
_OP_RE = re.compile(
    r"\s((?:%s)[\w.-]*)\(" % "|".join(_COLLECTIVES))


def collective_bytes(hlo_text):
    """Sum output bytes of collective ops in optimized HLO, per op kind.

    Caveat: a collective INSIDE a while/fori loop appears once in the
    text but executes once per trip — e.g. the plain ring's ppermute
    (n-1 trips) vs the windowed ring's unrolled ceil((W-1)/Tb) hops
    count the same here despite very different wire traffic. Loop-free
    programs (dp/zero1/MoE) are exact; ring comparisons need the trip
    count applied by the reader (or real-fabric timing).

    Reads lines like
      %all-reduce = f32[64,128]{1,0} all-reduce(%dot), replica_groups=...
    incl. variadic tuple outputs. Bytes are per-device (each device
    materializes its own output buffer); multiply by the group size for
    fabric-level traffic. Async `-done` halves of start/done pairs are
    skipped so traffic isn't counted twice."""
    out = {}
    for line in hlo_text.splitlines():
        if " = " not in line:
            continue
        shapes_part = line.split(" = ", 1)[1]
        m = _OP_RE.search(shapes_part)
        if not m or m.group(1).endswith("-done"):
            continue
        kind = next(c for c in _COLLECTIVES
                    if m.group(1).startswith(c))
        total = 0
        for dt, dims in _SHAPE_RE.findall(shapes_part[:m.start()]):
            if dt not in _DTYPE_BYTES:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            total += n * _DTYPE_BYTES[dt]
        out[kind] = out.get(kind, 0) + total
    return out


def build_step(network, mesh, global_batch, zero1, seq_parallel=False,
               seq_len=64, num_experts=0, full_size=False, window=0,
               layout=None):
    from mxnet_tpu import models
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.parallel import make_train_step

    kw = dict(optimizer="sgd", optimizer_params={"momentum": 0.9},
              mesh=mesh)
    if layout is not None:
        # the GSPMD one-jit row: SpecLayout placement + the optimizer
        # state folded across the data x fsdp replicas
        kw = dict(optimizer="adam", optimizer_params={},
                  layout=layout, optimizer_sharding="zero1")
    elif zero1:
        kw.update(optimizer="adam", optimizer_params={},
                  optimizer_sharding="zero1")
    if full_size:
        kw["compute_dtype"] = "bfloat16"   # match bench.py exactly
    if network == "resnet":
        if full_size:
            sym = models.get_symbol(network="resnet", num_classes=1000,
                                    num_layers=50,
                                    image_shape=(3, 224, 224))
            shapes = {"data": (global_batch, 3, 224, 224),
                      "softmax_label": (global_batch,)}
        else:
            sym = models.get_symbol(network="resnet", num_classes=10,
                                    num_layers=8, image_shape=(3, 8, 8))
            shapes = {"data": (global_batch, 3, 8, 8),
                      "softmax_label": (global_batch,)}
    else:
        if full_size:
            sym = models.get_symbol(
                network="transformer", vocab_size=32768,
                seq_len=seq_len, num_layers=4, num_heads=16, dim=2048,
                seq_axis="sp" if seq_parallel else None,
                num_experts=num_experts,
                expert_axis="expert" if num_experts else None,
                attention_window=window)
        else:
            sym = models.get_symbol(
                network="transformer", vocab_size=256, seq_len=seq_len,
                num_layers=2, num_heads=4, dim=64,
                seq_axis="sp" if seq_parallel else None,
                num_experts=num_experts,
                expert_axis="expert" if num_experts else None,
                attention_window=window)
        shapes = {"data": (global_batch, seq_len),
                  "softmax_label": (global_batch, seq_len)}
    step = make_train_step(sym, **kw)
    state = step.init_state(Xavier(), shapes)
    return step, state, shapes


def _telemetry_row(step, state, bd, rng, iters, gb, n):
    """Per-step telemetry journal for one device count (ISSUE 8
    satellite): a short extra pass where each step blocks on a scalar
    readback, so the recorded walls are true per-step times. Returns
    (summary dict for the JSON row, live state). Never fails the
    bench."""
    import jax
    import numpy as np
    from mxnet_tpu import telemetry
    try:
        import tempfile
        jr = telemetry.journal()
        if jr is None:
            jr = telemetry.start_journal(
                tempfile.mkdtemp(prefix="bench-scaling-telemetry-"),
                run="bench_scaling")
        walls = []
        # prime: the scalar-readback program compiles here, not inside
        # the first recorded step
        state, outs = step(state, bd, 0.1, rng)
        np.asarray(jax.device_get(outs[0].ravel()[0]))
        # short pass — each step pays a blocking readback, so don't
        # repeat the whole headline iteration count (same cap bench.py
        # uses)
        for i in range(max(3, min(int(iters), 10))):
            t0 = telemetry.now_ms()
            state, outs = step(state, bd, 0.1, rng)
            np.asarray(jax.device_get(outs[0].ravel()[0]))
            walls.append(telemetry.now_ms() - t0)
            telemetry.journal_step(loop="bench_scaling", devices=n,
                                   step=i, wall_ms=round(walls[-1], 3),
                                   samples=gb)
        walls.sort()
        return {"journal": jr.path,
                "step_ms_p50": round(telemetry.quantile(walls, 0.5), 3),
                "step_ms_p95": round(telemetry.quantile(walls, 0.95), 3),
                "samples_per_sec": round(
                    gb * len(walls) / (sum(walls) / 1e3), 1)}, state
    except Exception as e:  # noqa: BLE001 — telemetry never fails a bench
        return {"error": str(e)[:200]}, state


def _make_batch(network, shapes, gb):
    import numpy as np
    rng_np = np.random.RandomState(0)
    if network == "resnet":
        return {"data": rng_np.standard_normal(
            shapes["data"]).astype(np.float32),
            "softmax_label": rng_np.randint(
                0, 10, gb).astype(np.float32)}
    toks = rng_np.randint(0, 256, shapes["data"]).astype(np.float32)
    return {"data": toks, "softmax_label": np.roll(toks, -1, axis=1)}


def _measure(step, state, bd, rng, iters):
    """Warmup + the headline timed loop (readback barrier, not
    block_until_ready: through the axon tunnel the latter does not
    guarantee device completion). Returns (sec/step, live state)."""
    import jax
    import numpy as np
    state, outs = step(state, bd, 0.1, rng)   # warmup (cached)
    np.asarray(jax.device_get(outs[0]))
    t0 = time.time()
    for _ in range(iters):
        state, outs = step(state, bd, 0.1, rng)
    np.asarray(jax.device_get(outs[0]))
    return (time.time() - t0) / iters, state


def _gspmd_row(args, devices, n):
    """The one-jit GSPMD row (docs/parallelism.md "One-jit GSPMD
    path"): data x fsdp mesh, SpecLayout auto rules, optimizer state
    folded across ALL replicas — the trajectory row for the
    28.8% -> 45% MFU target next tunnel window."""
    import jax
    import numpy as np
    from mxnet_tpu import telemetry
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.sharding import SpecLayout

    # explicit --fsdp divisibility was validated in _run, pre-sweep;
    # the auto pick divides by construction
    f = args.fsdp or max(d for d in (4, 2, 1) if n % d == 0)
    mesh = make_mesh({"data": n // f, "fsdp": f},
                     devices=devices[:n])
    # min_shard_size=0: the smoke-size nets are tiny — on a real run
    # the MXNET_FSDP_MIN_SIZE default keeps tiny tensors replicated
    layout = SpecLayout(mesh, min_shard_size=0 if not args.full_size
                        else None)
    gb = args.per_device_batch * n
    seq_len = 2048 if (args.full_size
                       and args.network == "transformer_lm") else 64
    step, state, shapes = build_step(
        args.network, None, gb, False, seq_len=seq_len,
        full_size=args.full_size, window=args.window, layout=layout)
    opt_bytes = int(telemetry.gauge(
        "gspmd.opt_state_bytes_per_dev").value or 0)
    bd = step.place_batch(_make_batch(args.network, shapes, gb))
    rng = jax.random.PRNGKey(0)

    lowered = step.lower(state, bd, 0.1, rng)
    compiled = lowered.compile()
    coll = collective_bytes(compiled.as_text())
    row = {"devices": n, "mode": "gspmd",
           "mesh": {"data": n // f, "fsdp": f},
           "global_batch": gb, "zero1": True,
           "opt_state_bytes_per_dev": opt_bytes,
           "collective_bytes_per_dev": coll,
           "full_size": bool(args.full_size)}
    if args.compile_only:
        row["step_ms"] = None
        return row
    dt, state = _measure(step, state, bd, rng, args.iters)
    telemetry_row, state = _telemetry_row(step, state, bd, rng,
                                          args.iters, gb, n)
    row.update(step_ms=round(dt * 1e3, 2),
               samples_s=round(gb / dt, 1), telemetry=telemetry_row)
    if args.network == "transformer_lm":
        row["seq_len"] = seq_len
        row["tokens_s"] = round(gb * seq_len / dt, 1)
    return row


def _fail_summary(err):
    """Diagnostic summary line instead of a stack trace, with the
    newest committed capture attached (the bench.py last_known
    pattern, ROADMAP item 5) — a dead tunnel still yields a
    contentful, parseable artifact."""
    try:
        from bench_common import fail_payload
        payload = fail_payload("scaling_sweep", "samples/s", err)
    except ImportError:
        payload = {"metric": "scaling_sweep", "value": None,
                   "unit": "samples/s", "vs_baseline": None,
                   "live": False, "error": "%s: %s"
                   % (type(err).__name__, err)}
    print(json.dumps(payload))
    raise SystemExit(1)


def main():
    args = _parse_args()
    counts = sorted({int(c) for c in args.devices.split(",")})
    try:   # killed mid-run -> still exactly one parseable JSON line
        from bench_common import install_death_stub
        install_death_stub("scaling_sweep", "samples/s")
    except ImportError:
        pass

    # force the host platform BEFORE backend init (a dead TPU tunnel
    # hangs; and the virtual mesh needs the flag locked in first)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=%d"
            % max(counts + [8])).strip()
    # the image presets JAX_PLATFORMS=axon; override unless the caller
    # explicitly picked a platform (BENCH_PLATFORM=tpu on a real slice)
    platform = os.environ.get("BENCH_PLATFORM", "cpu")
    os.environ["JAX_PLATFORMS"] = platform
    try:
        rows, gspmd_row = _run(args, counts, platform)
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001 — tunnel/backend outage path
        _fail_summary(e)

    best = max((r for r in rows + ([gspmd_row] if gspmd_row else [])
                if r.get("samples_s")),
               key=lambda r: r["samples_s"], default=None)
    rate = "tokens_s" if rows and "tokens_s" in rows[0] else "samples_s"
    try:
        import jax
        kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001
        kind = "unknown"
    print(json.dumps({
        "metric": "scaling_sweep",
        "value": best["samples_s"] if best else None,
        "unit": "samples/s", "vs_baseline": None, "live": True,
        "device_kind": kind, "network": args.network,
        "rows": rows, "gspmd": gspmd_row}))

    if args.compile_only or not rows:
        return
    base = rows[0]["step_ms"]
    print("\n| devices | global batch | step ms | %s | "
          "weak-scaling eff | collective bytes/dev |"
          % rate.replace("_s", "/s"))
    print("|---|---|---|---|---|---|")
    for r in rows + ([gspmd_row] if gspmd_row else []):
        if r.get("step_ms") is None:
            continue
        if r.get("mode") == "gspmd" and not args.zero1:
            # the GSPMD row always runs adam+zero1; without --zero1
            # the baseline row ran sgd+momentum, and a step-time ratio
            # would charge the optimizer difference to scaling loss
            eff_cell = "n/a (adam vs sgd base)"
        else:
            eff_cell = "%.0f%%" % (base / r["step_ms"] * 100)
        tot = sum(r["collective_bytes_per_dev"].values())
        print("| %s | %d | %.2f | %.1f | %s | %s |" % (
            "%d (gspmd)" % r["devices"] if r.get("mode") == "gspmd"
            else "%d" % r["devices"],
            r["global_batch"], r["step_ms"],
            r[rate if rate in r else "samples_s"], eff_cell,
            "{:,}".format(tot)))


def _run(args, counts, platform):
    import jax
    jax.config.update("jax_platforms", platform)
    import numpy as np  # noqa: F401 (helpers import their own)
    from mxnet_tpu.parallel import make_mesh

    devices = jax.devices()
    if len(devices) < max(counts):
        raise SystemExit("only %d devices visible, need %d"
                         % (len(devices), max(counts)))

    if (args.seq_parallel or args.expert_parallel) and \
            args.network != "transformer_lm":
        raise SystemExit("--seq-parallel/--expert-parallel need "
                         "--network transformer_lm")
    if args.seq_parallel and args.expert_parallel:
        raise SystemExit("pick one of --seq-parallel/--expert-parallel "
                         "(composition lives in the test suite)")
    # pure arg math — fail BEFORE the sweep burns a tunnel window,
    # not in _gspmd_row after every count has been measured
    if args.fsdp and not args.skip_gspmd and not args.seq_parallel \
            and not args.expert_parallel \
            and max(counts) % args.fsdp != 0:
        raise SystemExit("--fsdp %d does not divide %d devices (the "
                         "GSPMD row runs at the largest sweep count)"
                         % (args.fsdp, max(counts)))

    rows = []
    for n in counts:
        num_experts = 0
        if args.seq_parallel:
            # weak scaling in SEQUENCE length: 64 tokens per device on
            # an sp mesh, batch fixed — the long-context axis
            mesh = make_mesh({"sp": n}, devices=devices[:n])
            gb, seq_len = args.per_device_batch, 64 * n
        elif args.expert_parallel:
            # weak scaling in EXPERTS: 2 experts per device, tokens
            # fixed per device — the MoE capacity axis
            mesh = make_mesh({"expert": n}, devices=devices[:n])
            gb, seq_len = args.per_device_batch * n, 64
            num_experts = 2 * n
        else:
            mesh = make_mesh({"data": n}, devices=devices[:n])
            gb, seq_len = args.per_device_batch * n, 64
        if args.full_size:
            seq_len = 2048 if args.network == "transformer_lm" \
                else seq_len
        step, state, shapes = build_step(args.network, mesh, gb,
                                         args.zero1, args.seq_parallel,
                                         seq_len, num_experts,
                                         args.full_size, args.window)
        bd = step.place_batch(_make_batch(args.network, shapes, gb))
        rng = jax.random.PRNGKey(0)

        lowered = step.lower(state, bd, 0.1, rng)
        compiled = lowered.compile()
        coll = collective_bytes(compiled.as_text())

        if args.compile_only:
            rows.append({"devices": n, "global_batch": gb,
                         "step_ms": None,
                         "collective_bytes_per_dev": coll,
                         "zero1": bool(args.zero1),
                         "full_size": bool(args.full_size)})
            print(json.dumps(rows[-1]))
            continue

        dt, state = _measure(step, state, bd, rng, args.iters)
        telemetry_row, state = _telemetry_row(step, state, bd, rng,
                                              args.iters, gb, n)

        row = {"devices": n, "global_batch": gb,
               "step_ms": round(dt * 1e3, 2),
               "samples_s": round(gb / dt, 1),
               "collective_bytes_per_dev": coll,
               "zero1": bool(args.zero1),
               "telemetry": telemetry_row}
        if args.network == "transformer_lm":
            # under --seq-parallel the per-sample token count grows
            # with n, so tokens/s is the honest weak-scaling metric
            row["seq_len"] = seq_len
            row["tokens_s"] = round(gb * seq_len / dt, 1)
        rows.append(row)
        print(json.dumps(rows[-1]))

    gspmd_row = None
    if not args.skip_gspmd and not args.seq_parallel and \
            not args.expert_parallel:
        try:
            gspmd_row = _gspmd_row(args, devices, max(counts))
        except SystemExit:
            raise
        except Exception as e:  # noqa: BLE001 — a GSPMD-row failure
            # must not discard the sweep already measured above (the
            # rows are the scarce tunnel-window artifact)
            gspmd_row = {"devices": max(counts), "mode": "gspmd",
                         "step_ms": None,
                         "error": "%s: %s" % (type(e).__name__,
                                              str(e)[:300])}
        print(json.dumps(gspmd_row))
    return rows, gspmd_row


if __name__ == "__main__":
    main()
