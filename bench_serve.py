"""Serving benchmark: closed-loop load generation against the
ServeEngine (docs/serving.md), structured like bench.py — ONE JSON
line {"metric", "value", "unit", "vs_baseline", ...}.

Offered-load sweep: for each concurrency level C, C closed-loop
clients each run `requests` submit→wait round trips against a fresh
engine; the sweep rows report throughput, request-latency
p50/p95/p99, and the mean batch fill the batcher achieved (the
whole point of the engine — fill should rise with C while per-request
latency stays bounded by the coalesce window + one forward).

    python bench_serve.py                       # default sweep 1,2,4,8,16
    python bench_serve.py --concurrency 1,8,32 --requests 200
    python bench_serve.py --buckets 1,4,16 --wait-ms 2

The headline `value` is the best throughput across the sweep (req/s);
`vs_baseline` is the batching gain — best throughput over the C=1
(unbatched closed-loop) throughput — when the sweep includes C=1.
"""
import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("MXNET_MATMUL_PRECISION", "default")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402


def _build_predictor(feat, hidden, classes, seed=7):
    import mxnet_tpu as mx
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.predictor import Predictor

    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=hidden)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=classes)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    arg_shapes, _, _ = net.infer_shape(data=(1, feat))
    init = Xavier()
    args = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        arr = mx.nd.zeros(shp)
        init(name, arr)
        args[name] = arr
    return Predictor(net, args)


def _run_level(pred, feat, buckets, wait_ms, conc, requests):
    """One closed-loop level: conc clients x requests round trips
    against a FRESH engine (clean per-level stats). Returns the sweep
    row."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.serve import ServeEngine

    eng = ServeEngine(pred, buckets=buckets, max_wait_ms=wait_ms,
                      feature_shapes=[(feat,)],
                      install_sigterm=False)
    eng.warmup()
    lat = [[] for _ in range(conc)]
    errs = [0] * conc
    x = np.random.RandomState(0).standard_normal(
        (1, feat)).astype(np.float32)

    def client(ci):
        for _ in range(requests):
            t0 = telemetry.now_ms()
            try:
                eng.infer(x, timeout=60.0)
            except Exception:  # noqa: BLE001 — shed/timeout counts,
                errs[ci] += 1  # the row reports them
                continue
            lat[ci].append(telemetry.now_ms() - t0)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(conc)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    eng.close()
    st = eng.stats()
    flat = sorted(v for row in lat for v in row)
    done = len(flat)
    return {
        "concurrency": conc,
        "requests": done,
        "errors": sum(errs),
        "throughput_rps": round(done / wall, 2) if wall else None,
        "latency_ms": {
            "p50": round(telemetry.quantile(flat, 0.50), 3),
            "p95": round(telemetry.quantile(flat, 0.95), 3),
            "p99": round(telemetry.quantile(flat, 0.99), 3),
            "mean": round(sum(flat) / done, 3),
        } if done else None,
        "forwards": st["forwards"],
        "mean_batch_fill": round(st["mean_fill"], 3)
        if st["mean_fill"] else None,
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--concurrency", default="1,2,4,8,16",
                   help="comma-separated closed-loop client counts")
    p.add_argument("--requests", type=int,
                   default=int(os.environ.get("BENCH_SERVE_REQUESTS",
                                              "100")),
                   help="round trips per client per level")
    p.add_argument("--buckets", default=None,
                   help="engine buckets (default MXNET_SERVE_BUCKETS)")
    p.add_argument("--wait-ms", type=float, default=None,
                   help="coalesce window (default "
                        "MXNET_SERVE_MAX_WAIT_MS)")
    p.add_argument("--features", type=int, default=64)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--classes", type=int, default=16)
    args = p.parse_args(argv)

    try:   # killed mid-run -> still exactly one parseable JSON line
        from bench_common import install_death_stub
        install_death_stub("serve_throughput", "req/s")
    except ImportError:
        pass
    if os.environ.get("BENCH_PLATFORM"):
        os.environ["JAX_PLATFORMS"] = os.environ["BENCH_PLATFORM"]
    levels = sorted({int(c) for c in
                     args.concurrency.replace(",", " ").split()})
    buckets = tuple(int(b) for b in
                    args.buckets.replace(",", " ").split()) \
        if args.buckets else None

    try:
        pred = _build_predictor(args.features, args.hidden,
                                args.classes)
        sweep = [_run_level(pred, args.features, buckets, args.wait_ms,
                            c, args.requests) for c in levels]
    except Exception as e:  # noqa: BLE001 — diagnostic line, like
        # bench.py: the driver gets a parseable failure, not a trace,
        # with the newest committed capture attached (bench_common —
        # the bench.py last_known pattern, ROADMAP item 5) so a tunnel
        # outage still yields a contentful artifact
        try:
            from bench_common import fail_payload
            payload = fail_payload("serve_throughput", "req/s", e)
        except ImportError:
            payload = {"metric": "serve_throughput", "value": None,
                       "unit": "req/s", "vs_baseline": None,
                       "live": False, "error": "%s: %s"
                       % (type(e).__name__, e)}
        print(json.dumps(payload))
        sys.exit(1)

    best = max(sweep, key=lambda r: r["throughput_rps"] or 0.0)
    base = next((r for r in sweep if r["concurrency"] == 1), None)
    gain = (round(best["throughput_rps"] / base["throughput_rps"], 3)
            if base and base["throughput_rps"] else None)
    print(json.dumps({
        "metric": "serve_throughput",
        "value": best["throughput_rps"],
        "unit": "req/s",
        "vs_baseline": gain,          # batching gain over C=1
        "best_concurrency": best["concurrency"],
        "best_latency_ms": best["latency_ms"],
        "best_mean_batch_fill": best["mean_batch_fill"],
        "sweep": sweep}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
