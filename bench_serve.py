"""Serving benchmark: closed-loop load generation against the
ServeEngine (docs/serving.md), structured like bench.py — ONE JSON
line {"metric", "value", "unit", "vs_baseline", ...}.

Offered-load sweep: for each concurrency level C, C closed-loop
clients each run `requests` submit→wait round trips against a fresh
engine; the sweep rows report throughput, request-latency
p50/p95/p99, and the mean batch fill the batcher achieved (the
whole point of the engine — fill should rise with C while per-request
latency stays bounded by the coalesce window + one forward).

    python bench_serve.py                       # default sweep 1,2,4,8,16
    python bench_serve.py --concurrency 1,8,32 --requests 200
    python bench_serve.py --buckets 1,4,16 --wait-ms 2

The headline `value` is the best throughput across the sweep (req/s);
`vs_baseline` is the batching gain — best throughput over the C=1
(unbatched closed-loop) throughput — when the sweep includes C=1.

FLEET MODE (``--replicas N``, docs/serving.md §fleet): the same
offered-load sweep against a ``ServeRouter`` over N subprocess
replicas — each replica its own process (its own GIL, its own XLA
client) behind real TCP, exactly the production topology scaled down.
Rows add per-replica dispatch fill so imbalance is visible; the
acceptance shape is req/s scaling near-linearly in replicas at
bounded p99 (ROADMAP item 2):

    python bench_serve.py --replicas 3          # fleet sweep
    python bench_serve.py --replicas 1          # same topology, N=1
                                                #   (the scaling base)

``--work-ms`` (fleet default 5.0) adds a fixed per-forward service
time in each replica, modeling the device step a CPU-only CI host
doesn't have — set 0 to measure raw XLA-CPU forwards instead. The
emitted metric is ``serve_fleet_throughput`` (same shape, plus
``replicas`` and ``per_replica_fill``).

DISAGG MODE (``--disagg P:D``, docs/serving.md §disaggregated
prefill): prefill/decode disaggregation A/B at equal chip count. Two
fleets of transformer-Generator replicas run the SAME workload —
short-prompt decode sessions measured for inter-token latency while
long-prompt generate load runs concurrently:

* disaggregated — P prefill-role + D decode-role replicas: long
  prefills run on the prefill chips, the decode replicas only scatter
  imported KV rows (zero prefill graph calls, asserted);
* colocated — P+D decode-role replicas: every long prefill stalls the
  admitting replica's (B, 1) step loop for every active slot on it.

The headline ``value`` is the disaggregated decode inter-token p99
(wall/new-token of a short session under load); ``vs_baseline`` is
its ratio to the colocated p99 — the acceptance shape is <= 0.7 at
equal replica count. The payload also carries the handoff cost micro
(export + pickle + import vs one prefill at the flagship hd=128
shape; acceptance <= 0.15) and the int8-vs-bf16 blob bytes ratio
(acceptance <= 0.55). Emitted metric: ``serve_disagg_p99``.

    python bench_serve.py --disagg 1:1      # 2 chips vs 2 chips

STREAMING MODE (``--streaming``, docs/serving.md §streaming): the
PR-17 A/B pair on one in-process decode replica — streamed frames vs
one-shot (acceptance: streamed TTFT p50 <= 0.25x one-shot total at
max_new >= 32) and chunked vs monolithic prefill under long-prompt
load (acceptance: chunked inter-token p99 <= 0.5x unchunked at equal
replica count). Every sweep row in every mode also now reports
``ttft_ms``/``inter_token_ms`` quantiles: streaming callables feed
real per-emission marks, one-shot callables degenerate to TTFT ==
request latency with null inter-token. Emitted metric:
``serve_streaming_ttft``.

    python bench_serve.py --streaming

SPECULATIVE MODE (``--speculative``, docs/serving.md §speculative):
plain vs draft/verify continuous batching on ONE doctored target
(post-layer0 residual branches downscaled so the 1-layer truncated
draft tracks it — the high-acceptance regime). The headline ``value``
is the speculative per-session effective inter-token latency p99
((wall first->last token) / (tokens-1), p99 across sessions);
``vs_baseline`` is its ratio to the plain-decode p99 — acceptance is
< 1.0 AND ``tokens_per_target_forward`` > 1.5 at gamma=4. Output is
asserted byte-identical between the phases (exactness is the
contract, not an aspiration). Emitted metric: ``serve_spec_decode``.

    python bench_serve.py --speculative
"""
import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("MXNET_MATMUL_PRECISION", "default")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402


def _build_predictor(feat, hidden, classes, seed=7):
    import mxnet_tpu as mx
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.predictor import Predictor

    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=hidden)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=classes)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    arg_shapes, _, _ = net.infer_shape(data=(1, feat))
    init = Xavier()
    args = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        arr = mx.nd.zeros(shp)
        init(name, arr)
        args[name] = arr
    return Predictor(net, args)


class _TimedModel:
    """Forward wrapper adding a fixed service time per forward —
    the stand-in for device step latency on a CPU-only host (the
    sleep releases the GIL exactly like a device dispatch would)."""

    def __init__(self, pred, work_ms):
        self._pred = pred
        self._work_s = float(work_ms) / 1000.0

    def forward(self, *arrays):
        outs = self._pred.forward(*arrays)
        if self._work_s > 0:
            time.sleep(self._work_s)
        return outs


def _replica_child(args):
    """``--serve-replica`` subprocess body: one engine + ServeServer,
    port announced as one JSON line on stdout, serving until stdin
    closes (the parent's exit — however it exits — is the shutdown
    signal; no orphaned replicas)."""
    from mxnet_tpu.serve import ServeEngine, ServeServer

    pred = _build_predictor(args.features, args.hidden, args.classes)
    model = _TimedModel(pred, args.work_ms) if args.work_ms else pred
    buckets = tuple(int(b) for b in
                    args.buckets.replace(",", " ").split()) \
        if args.buckets else (1, 2, 4)
    eng = ServeEngine(model, buckets=buckets,
                      max_wait_ms=(0.5 if args.wait_ms is None
                                   else args.wait_ms),
                      queue_cap=512, feature_shapes=[(args.features,)],
                      install_sigterm=True)
    srv = ServeServer(eng)
    print(json.dumps({"port": srv.port, "host": srv.host}), flush=True)
    try:
        while sys.stdin.readline():       # parent holds the pipe open
            pass
    finally:
        srv.close()
        eng.close()
    return 0


def _spawn_fleet(args, n):
    """N replica subprocesses; returns (procs, [(host, port)])."""
    import subprocess
    cmd = [sys.executable, os.path.abspath(__file__),
           "--serve-replica",
           "--features", str(args.features),
           "--hidden", str(args.hidden),
           "--classes", str(args.classes),
           "--work-ms", str(args.work_ms)]
    if args.buckets:
        cmd += ["--buckets", args.buckets]
    if args.wait_ms is not None:
        cmd += ["--wait-ms", str(args.wait_ms)]
    procs, addrs = [], []
    for _ in range(n):
        procs.append(subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True))
    import select
    deadline = time.monotonic() + 180.0   # XLA import is the cost
    for p in procs:
        # bounded read: a child hung in startup must fail the bench
        # (fail_payload path), not wedge it on a blocking readline
        remain = deadline - time.monotonic()
        if remain <= 0 or not select.select([p.stdout], [], [],
                                            remain)[0]:
            raise RuntimeError(
                "replica fleet startup timed out (child rc=%s)"
                % p.poll())
        line = p.stdout.readline()
        if not line:
            raise RuntimeError(
                "replica subprocess died before announcing its port "
                "(rc=%s)" % p.poll())
        rec = json.loads(line)
        addrs.append((rec["host"], rec["port"]))
    return procs, addrs


def _kill_fleet(procs):
    for p in procs:
        try:
            p.stdin.close()               # EOF = drain + exit
        except OSError:
            pass
    for p in procs:
        try:
            p.wait(10.0)
        except Exception:  # noqa: BLE001 — escalate to kill
            p.kill()



def _lm_params(args):
    """Deterministic transformer-LM params every generator replica
    shares (same seed in every process — the prefill replica's
    exported rows must be THIS model's rows on the decode replica
    too, or the handoff would decode garbage)."""
    import mxnet_tpu as mx
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel import make_train_step

    sym = transformer.get_symbol(
        args.lm_vocab, 12, num_layers=args.lm_layers,
        num_heads=args.lm_heads, dim=args.lm_dim,
        max_len=args.lm_max_len)
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(0)
    state = step.init_state(Xavier(), {"data": (2, 12),
                                       "softmax_label": (2, 12)})
    return state[0]


def _lm_generator(args, batch_size):
    from mxnet_tpu.generation import Generator
    return Generator(_lm_params(args), args.lm_vocab, args.lm_max_len,
                     num_layers=args.lm_layers,
                     num_heads=args.lm_heads, dim=args.lm_dim,
                     batch_size=batch_size)


def _gen_replica_child(args):
    """``--serve-replica --role prefill|decode`` subprocess body: one
    Generator-backed engine + ServeServer (same announce/stdin-EOF
    lifecycle as the predictor replicas)."""
    from mxnet_tpu.serve import (ContinuousDecoder, PrefillEngine,
                                 ServeServer)

    if args.role == "prefill":
        eng = PrefillEngine(_lm_generator(args, 1))
    else:
        eng = ContinuousDecoder(_lm_generator(args, args.slots),
                                queue_cap=512)
    srv = ServeServer(eng)
    print(json.dumps({"port": srv.port, "host": srv.host}), flush=True)
    try:
        while sys.stdin.readline():
            pass
    finally:
        srv.close()
        eng.close(timeout=30.0)
    return 0


def _spawn_gen_fleet(args, roles):
    """One generator replica subprocess per role; returns
    (procs, [(host, port)])."""
    import select
    import subprocess
    procs = []
    for role in roles:
        cmd = [sys.executable, os.path.abspath(__file__),
               "--serve-replica", "--role", role,
               "--slots", str(args.slots),
               "--lm-vocab", str(args.lm_vocab),
               "--lm-dim", str(args.lm_dim),
               "--lm-layers", str(args.lm_layers),
               "--lm-heads", str(args.lm_heads),
               "--lm-max-len", str(args.lm_max_len)]
        procs.append(subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True))
    addrs = []
    deadline = time.monotonic() + 300.0   # XLA import is the cost
    for p in procs:
        remain = deadline - time.monotonic()
        if remain <= 0 or not select.select([p.stdout], [], [],
                                            remain)[0]:
            raise RuntimeError(
                "generator fleet startup timed out (child rc=%s)"
                % p.poll())
        line = p.stdout.readline()
        if not line:
            raise RuntimeError(
                "generator replica died before announcing its port "
                "(rc=%s)" % p.poll())
        rec = json.loads(line)
        addrs.append((rec["host"], rec["port"]))
    return procs, addrs


def _replica_engine_stats(addrs):
    """Raw per-replica engine stats straight off the wire (the
    router's cached extract drops the decode-specific fields the
    disagg assertions need: prefills, imported)."""
    from mxnet_tpu.serve import ServeClient
    out = []
    for host, port in addrs:
        with ServeClient(host, port) as c:
            out.append(c.stats().get("engine") or {})
    return out


def _run_disagg_config(args, roles, label):
    """One side of the A/B: spawn the fleet, run short-prompt decode
    sessions (measured) under concurrent long-prompt generate load,
    return the row."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.serve import ServeRouter

    rng = np.random.RandomState(0)
    short = rng.randint(1, args.lm_vocab, (args.short_prompt,))
    long_p = rng.randint(1, args.lm_vocab, (args.long_prompt,))
    procs, addrs = _spawn_gen_fleet(args, roles)
    router = None
    try:
        router = ServeRouter(
            replicas=addrs,
            conns_per_replica=args.sessions + args.load_clients + 2)
        # warm both graph shapes on EVERY replica before measuring
        # (cold XLA compiles are a one-time cost, not the steady
        # state this A/B is about) — per-replica direct clients, not
        # the router, whose placement would collapse sequential warm
        # sessions onto the first replica and leave the rest cold
        from mxnet_tpu.serve import ServeClient
        handoffs = []
        for (host, port), role in zip(addrs, roles):
            if role != "prefill":
                continue
            with ServeClient(host, port) as c:
                handoffs = [c.prefill(long_p), c.prefill(short)]
        for (host, port), role in zip(addrs, roles):
            if role == "prefill":
                continue
            with ServeClient(host, port) as c:
                if handoffs:              # disagg: warm the import
                    # scatter shapes, not the local prefill graphs
                    c.generate(long_p, 2, handoff=handoffs[0])
                    c.generate(short, args.max_new,
                               handoff=handoffs[1])
                else:                     # colocated: local prefills
                    c.generate(long_p, 2)
                    c.generate(short, args.max_new)
        stop = threading.Event()
        load_done = [0] * args.load_clients

        def load_client(ci):
            while not stop.is_set():
                try:
                    router.generate(long_p, 2,
                                    session="load%d" % ci)
                    load_done[ci] += 1
                except Exception:  # noqa: BLE001 — shed under burst
                    time.sleep(0.005)

        lat = [[] for _ in range(args.sessions)]
        dec_errs = [0] * args.sessions

        def decode_client(ci):
            for _ in range(args.requests):
                t0 = telemetry.now_ms()
                try:
                    router.generate(short, args.max_new,
                                    session="sess%d" % ci)
                except Exception:  # noqa: BLE001 — shed/timeout
                    dec_errs[ci] += 1  # counts; the row reports them
                    continue
                lat[ci].append(
                    (telemetry.now_ms() - t0) / args.max_new)

        loaders = [threading.Thread(target=load_client, args=(i,))
                   for i in range(args.load_clients)]
        clients = [threading.Thread(target=decode_client, args=(i,))
                   for i in range(args.sessions)]
        for t in loaders:
            t.start()
        time.sleep(0.2)                   # load reaches steady state
        t0 = time.perf_counter()
        for t in clients:
            t.start()
        for t in clients:
            t.join()
        wall = time.perf_counter() - t0
        stop.set()
        for t in loaders:
            t.join()
        flat = sorted(v for row in lat for v in row)
        eng_stats = _replica_engine_stats(addrs)
    finally:
        if router is not None:
            router.close()
        _kill_fleet(procs)
    if not flat:
        # every measured request failed: that is a BENCH failure (the
        # fail_payload diagnostic path), never a success-shaped
        # payload with a null p99
        raise RuntimeError(
            "disagg %s config: all %d decode requests errored "
            "(per-session errors %r)"
            % (label, args.sessions * args.requests, dec_errs))
    decode_stats = [s for s in eng_stats if "imported" in s]
    return {
        "config": label,
        "replicas": len(roles),
        "roles": list(roles),
        "decode_requests": len(flat),
        "decode_errors": sum(dec_errs),
        "long_generates": sum(load_done),
        "wall_s": round(wall, 3),
        "inter_token_ms": {
            "p50": round(telemetry.quantile(flat, 0.50), 3),
            "p95": round(telemetry.quantile(flat, 0.95), 3),
            "p99": round(telemetry.quantile(flat, 0.99), 3),
            "mean": round(sum(flat) / len(flat), 3),
        } if flat else None,
        # the disagg invariant, read off the live replicas: imported
        # admissions ran zero prefill graph calls decode-side
        "decode_replica_prefills": sum(
            s.get("prefills") or 0 for s in decode_stats),
        "decode_replica_imports": sum(
            s.get("imported") or 0 for s in decode_stats),
    }


def _handoff_micro(args):
    """Flagship-shape (hd=128) in-process handoff cost: export +
    pickle round trip + import scatter vs one prefill forward, plus
    the int8-vs-bf16 blob bytes ratio. No wire — the wire's cost is
    the pickle bytes, which the A/B fleet pays for real."""
    import pickle

    import jax

    import mxnet_tpu as mx
    from mxnet_tpu.generation import Generator, kv_blob_nbytes
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel import make_train_step

    V, L_, heads, dim = 64, 2, 2, 256            # head_dim 128
    P = int(os.environ.get("BENCH_DISAGG_FLAGSHIP_PROMPT", "384"))
    T_ = P + 128
    sym = transformer.get_symbol(V, 12, num_layers=L_,
                                 num_heads=heads, dim=dim,
                                 max_len=T_)
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(0)
    params = step.init_state(Xavier(), {"data": (2, 12),
                                        "softmax_label": (2, 12)})[0]

    def mk(**kw):
        return Generator(params, V, T_, num_layers=L_,
                         num_heads=heads, dim=dim, batch_size=1, **kw)

    gen = mk()
    prompt = np.arange(1, P + 1).reshape(1, -1).astype(np.float32)

    def prefill_once():
        logits, aux = gen._forward(gen._fresh_aux(), prompt, 0)
        np.asarray(logits[:, -1])         # host sync, like serving
        return aux

    def med(fn, reps):
        """Median single-iteration wall — GC/scheduler spikes must
        not decide a ratio criterion."""
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            times.append((time.perf_counter() - t0) * 1000.0)
        return sorted(times)[len(times) // 2]

    aux = prefill_once()                  # compile
    prefill_ms = med(prefill_once, 9)

    dec = mk().serving_decoder()
    wire = [None]
    try:
        blob = gen.export_kv_rows(aux, 0, P)
        dec.import_kv_rows(0, pickle.loads(pickle.dumps(blob)))
        jax.block_until_ready(list(dec._aux.values()))   # compile

        def handoff_once():
            blob = gen.export_kv_rows(aux, 0, P)
            wire[0] = pickle.dumps(blob, protocol=4)
            dec.import_kv_rows(0, pickle.loads(wire[0]))
            jax.block_until_ready(list(dec._aux.values()))
        handoff_ms = med(handoff_once, 21)
    finally:
        dec.close(timeout=10.0)

    # bytes ratio at the same shape/position: int8 rows + f32
    # per-token scales vs bf16 rows (shape math through the real
    # export path — a fresh aux has the real dtypes/shapes)
    g16, gq8 = mk(dtype="bfloat16"), mk(quantize_kv=True)
    bytes_bf16 = kv_blob_nbytes(
        g16.export_kv_rows(g16._fresh_aux(), 0, P))
    bytes_int8 = kv_blob_nbytes(
        gq8.export_kv_rows(gq8._fresh_aux(), 0, P))
    return {
        "shape": {"head_dim": dim // heads, "layers": L_,
                  "prompt": P},
        "prefill_ms": round(prefill_ms, 3),
        "handoff_ms": round(handoff_ms, 3),
        "handoff_frac": round(handoff_ms / prefill_ms, 4)
        if prefill_ms else None,
        "blob_bytes_bf16": bytes_bf16,
        "blob_bytes_int8": bytes_int8,
        "bytes_ratio_int8_vs_bf16": round(bytes_int8 / bytes_bf16, 4),
        "wire_bytes_f32": len(wire[0]),
    }


def _run_disagg(args):
    """The --disagg P:D A/B: disaggregated fleet vs colocated fleet
    at equal replica count, plus the flagship-shape handoff micro."""
    try:
        n_pre, n_dec = (int(x) for x in args.disagg.split(":"))
    except ValueError:
        raise SystemExit("--disagg wants P:D (e.g. 1:1), got %r"
                         % args.disagg)
    if n_pre < 1 or n_dec < 1:
        raise SystemExit("--disagg wants at least one prefill and one "
                         "decode replica, got %r" % args.disagg)
    disagg = _run_disagg_config(
        args, ["prefill"] * n_pre + ["decode"] * n_dec, "disagg")
    coloc = _run_disagg_config(
        args, ["decode"] * (n_pre + n_dec), "colocated")
    return disagg, coloc, _handoff_micro(args)


def _closed_loop(one_round_trip, conc, requests):
    """THE closed-loop measurement harness both sweep modes share:
    conc client threads x requests round trips of ``one_round_trip()``,
    returning the common row fields (throughput, latency quantiles,
    error count). Callers fold in their mode-specific extras.

    TTFT and inter-token quantiles ride every row: a round trip that
    returns a list of per-emission ``now_ms()`` marks (the streaming
    callables do) yields true time-to-first-token and gap quantiles;
    any other return (infer replies, one-shot rows) is a single-shot
    round trip whose first byte IS the whole reply — TTFT equals the
    request latency and inter-token is null."""
    from mxnet_tpu import telemetry

    lat = [[] for _ in range(conc)]
    ttft = [[] for _ in range(conc)]
    gaps = [[] for _ in range(conc)]
    errs = [0] * conc

    def client(ci):
        for _ in range(requests):
            t0 = telemetry.now_ms()
            try:
                marks = one_round_trip()
            except Exception:  # noqa: BLE001 — shed/timeout counts,
                errs[ci] += 1  # the row reports them
                continue
            t1 = telemetry.now_ms()
            lat[ci].append(t1 - t0)
            if isinstance(marks, list) and marks and \
                    all(type(m) is float for m in marks):
                ttft[ci].append(marks[0] - t0)
                gaps[ci].extend(b - a for a, b in
                                zip(marks, marks[1:]))
            else:
                # infer replies are LISTS of output arrays — only a
                # list of now_ms() floats is an emission-mark trail
                ttft[ci].append(t1 - t0)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(conc)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    flat = sorted(v for row in lat for v in row)
    tflat = sorted(v for row in ttft for v in row)
    gflat = sorted(v for row in gaps for v in row)
    done = len(flat)

    def _q(vals):
        return {"p50": round(telemetry.quantile(vals, 0.50), 3),
                "p99": round(telemetry.quantile(vals, 0.99), 3)}

    return {
        "concurrency": conc,
        "requests": done,
        "errors": sum(errs),
        "throughput_rps": round(done / wall, 2) if wall else None,
        "latency_ms": {
            "p50": round(telemetry.quantile(flat, 0.50), 3),
            "p95": round(telemetry.quantile(flat, 0.95), 3),
            "p99": round(telemetry.quantile(flat, 0.99), 3),
            "mean": round(sum(flat) / done, 3),
        } if done else None,
        "ttft_ms": _q(tflat) if tflat else None,
        "inter_token_ms": _q(gflat) if gflat else None,
    }


def _run_fleet_level(router, names, feat, conc, requests):
    """One closed-loop level against the (persistent) fleet: conc
    clients x requests round trips through the router. Per-replica
    fill comes from dispatch-count deltas."""
    before = {n: r["dispatched"]
              for n, r in router.replicas().items()}
    x = np.random.RandomState(0).standard_normal(
        (1, feat)).astype(np.float32)
    row = _closed_loop(lambda: router.request([x]), conc, requests)
    after = router.replicas()
    row["per_replica_fill"] = {
        n: after[n]["dispatched"] - before.get(n, 0) for n in names}
    return row


def _run_fleet(args, levels):
    """The --replicas N sweep: router + N subprocess replicas, one
    JSON line out (metric serve_fleet_throughput)."""
    from mxnet_tpu.serve import ServeRouter

    procs, addrs = _spawn_fleet(args, args.replicas)
    router = None
    try:
        # pool enough connections for the deepest sweep level — a
        # closed-loop client holds one for its whole round trip, and
        # re-dialing per request would measure TCP setup, not serving
        conns = max(int(c) for c in
                    args.concurrency.replace(",", " ").split())
        router = ServeRouter(replicas=addrs, conns_per_replica=conns)
        names = list(router.replicas())
        router.warmup()                   # no cold compiles in level 1
        sweep = [_run_fleet_level(router, names, args.features, c,
                                  args.requests) for c in levels]
        fleet_stats = router.stats()
    finally:
        if router is not None:
            router.close()
        _kill_fleet(procs)
    return sweep, fleet_stats


def _run_controller(args, conc):
    """The --controller load-doubling autoscale bench
    (docs/serving.md §fleet controller): a 2-replica subprocess fleet
    under a baseline closed-loop load, then DOUBLED clients — the
    FleetController's background ticks must scale out mid-window on
    the sustained queue-depth signal — then the same doubled load
    against the grown fleet. Acceptance: at least one scale-out, zero
    request errors in every window (nothing dropped while capacity
    changed under load), and the tail recovered — window-3 p99 below
    the pressure window's."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.serve import FleetController, ServeRouter

    procs, addrs = _spawn_fleet(args, 2)
    by_addr = {"%s:%d" % a: p for p, a in zip(procs, addrs)}
    router, ctrl = None, None

    def spawn(manifest=None):
        new_procs, new_addrs = _spawn_fleet(args, 1)
        procs.extend(new_procs)
        by_addr["%s:%d" % new_addrs[0]] = new_procs[0]
        return new_addrs[0]

    def retire(name, addr):
        proc = by_addr.pop(addr, None)
        if proc is not None:
            try:
                proc.stdin.close()        # EOF = drain + exit
            except OSError:
                pass

    x = np.random.RandomState(0).standard_normal(
        (1, args.features)).astype(np.float32)
    try:
        router = ServeRouter(replicas=addrs,
                             conns_per_replica=2 * conc + 2)
        router.warmup()                   # no cold compiles in window 1
        # sustain 5 ticks @100ms: the doubled load must hold the
        # depth signal for half a second before capacity moves — the
        # inter-window idle gap is far shorter, so the controller
        # never flaps between measurement windows. The depth band
        # (in 1.0 / out 5.0) sits between the baseline's steady
        # per-replica queue (~conc/replicas - 1 in service) and the
        # doubled load's, so only window 2 crosses it.
        ctrl = FleetController(router, spawn, retire=retire,
                               min_replicas=2, max_replicas=4,
                               scale_out_depth=5.0,
                               scale_in_depth=1.0,
                               sustain=5, poll_ms=100.0)

        def rt():
            return router.request([x])
        baseline = _closed_loop(rt, conc, args.requests)
        replicas_base = len(router.replicas())
        pressure = _closed_loop(rt, 2 * conc, args.requests)
        replicas_pressure = len(router.replicas())
        recovered = _closed_loop(rt, 2 * conc, args.requests)
        scale_outs = int(telemetry.counter(
            "serve.ctrl.scale_outs").value)
        fleet = router.stats()
    finally:
        if ctrl is not None:
            ctrl.close()
        if router is not None:
            router.close()
        _kill_fleet(procs)
    errors = (baseline["errors"] + pressure["errors"]
              + recovered["errors"])
    p99_p = (pressure["latency_ms"] or {}).get("p99")
    p99_r = (recovered["latency_ms"] or {}).get("p99")
    return {
        "baseline": baseline,
        "pressure": pressure,
        "recovered": recovered,
        "replicas_baseline": replicas_base,
        "replicas_pressure": replicas_pressure,
        "replicas_final": fleet.get("replicas"),
        "scale_outs": scale_outs,
        "errors": errors,
        "p99_recovery_ratio": round(p99_r / p99_p, 4)
        if p99_r and p99_p else None,
        "ok": bool(scale_outs >= 1 and errors == 0
                   and p99_r is not None and p99_p is not None
                   and p99_r < p99_p),
    }


def _run_level(pred, feat, buckets, wait_ms, conc, requests):
    """One closed-loop level: conc clients x requests round trips
    against a FRESH engine (clean per-level stats). Returns the sweep
    row."""
    from mxnet_tpu.serve import ServeEngine

    eng = ServeEngine(pred, buckets=buckets, max_wait_ms=wait_ms,
                      feature_shapes=[(feat,)],
                      install_sigterm=False)
    eng.warmup()
    x = np.random.RandomState(0).standard_normal(
        (1, feat)).astype(np.float32)
    row = _closed_loop(lambda: eng.infer(x, timeout=60.0), conc,
                       requests)
    eng.close()
    st = eng.stats()
    row["forwards"] = st["forwards"]
    row["mean_batch_fill"] = round(st["mean_fill"], 3) \
        if st["mean_fill"] else None
    return row


def _run_streaming(args):
    """The --streaming A/B pair (docs/serving.md §streaming), one
    in-process transformer decode replica behind real TCP each side:

    * streamed vs one-shot — the SAME short-prompt generate with and
      without frames; the acceptance shape is streamed TTFT p50 <=
      0.25x the one-shot total latency p50 at max_new >= 32 (the
      whole point of frames: the first token stops waiting for the
      last);
    * chunked vs monolithic prefill — short streamed sessions
      measured for inter-token gaps while a loader injects
      long-prompt generates; the acceptance shape is chunked
      inter-token p99 <= 0.5x unchunked at equal replica count (a
      monolithic long prefill stalls every active session for its
      whole forward, a chunk stalls them for one slice).

    Every graph width is warmed before measuring in each config —
    cold XLA compiles are a one-time cost, not the steady state."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.serve import ContinuousDecoder, ServeClient, \
        ServeServer

    rng = np.random.RandomState(0)
    short = rng.randint(1, args.lm_vocab, (args.short_prompt,))
    long_p = rng.randint(1, args.lm_vocab, (args.long_prompt,))
    max_new = max(int(args.max_new), 32)
    reps = max(8, min(args.requests, 40))

    def _q(vals):
        vals = sorted(vals)
        return {"p50": round(telemetry.quantile(vals, 0.50), 3),
                "p99": round(telemetry.quantile(vals, 0.99), 3)}

    # -- A/B 1: streamed TTFT vs one-shot total latency ------------
    dec = ContinuousDecoder(_lm_generator(args, args.slots),
                            queue_cap=512)
    srv = ServeServer(dec)
    try:
        with ServeClient(srv.host, srv.port) as cli:
            cli.generate(short, max_new)                      # warm
            cli.generate(short, max_new, on_token=lambda t: None)
            oneshot, ttfts, sgaps = [], [], []
            for _ in range(reps):
                t0 = telemetry.now_ms()
                cli.generate(short, max_new)
                oneshot.append(telemetry.now_ms() - t0)
            for _ in range(reps):
                marks = []
                t0 = telemetry.now_ms()
                cli.generate(short, max_new, on_token=lambda t:
                             marks.append(telemetry.now_ms()))
                ttfts.append(marks[0] - t0)
                sgaps.extend(b - a for a, b in
                             zip(marks, marks[1:]))
    finally:
        srv.close()
        dec.close()

    # -- A/B 2: chunked vs monolithic prefill under long load ------
    def config(chunk):
        os.environ["MXNET_PREFILL_CHUNK"] = str(chunk)
        d = ContinuousDecoder(_lm_generator(args, args.slots),
                              queue_cap=512)
        s = ServeServer(d)
        gaps = []
        try:
            with ServeClient(s.host, s.port) as cli, \
                    ServeClient(s.host, s.port) as loader:
                cli.generate(short, max_new)              # warm the
                loader.generate(long_p, 2)    # short, long (chunked
                stop = threading.Event()      # or monolithic) + step

                def load():
                    while not stop.is_set():
                        try:
                            loader.generate(long_p, 2)
                        except Exception:  # noqa: BLE001 — shed
                            time.sleep(0.005)

                lt = threading.Thread(target=load)
                lt.start()
                time.sleep(0.1)           # load reaches steady state
                try:
                    for _ in range(reps):
                        marks = []
                        cli.generate(short, max_new, on_token=lambda
                                     t: marks.append(
                                         telemetry.now_ms()))
                        gaps.extend(b - a for a, b in
                                    zip(marks, marks[1:]))
                finally:
                    stop.set()
                    lt.join()
        finally:
            s.close()
            d.close()
            os.environ.pop("MXNET_PREFILL_CHUNK", None)
        return gaps

    chunked = sorted(config(args.prefill_chunk))
    mono = sorted(config(0))
    oneshot, ttfts = sorted(oneshot), sorted(ttfts)
    return {
        "max_new": max_new,
        "requests": reps,
        "oneshot_total_ms": _q(oneshot),
        "streamed_ttft_ms": _q(ttfts),
        "streamed_inter_token_ms": _q(sgaps),
        # acceptance: <= 0.25 at max_new >= 32
        "ttft_vs_oneshot": round(
            telemetry.quantile(ttfts, 0.5)
            / telemetry.quantile(oneshot, 0.5), 4),
        "chunk": args.prefill_chunk,
        "long_prompt": int(args.long_prompt),
        "chunked_inter_token_ms": _q(chunked),
        "unchunked_inter_token_ms": _q(mono),
        # acceptance: <= 0.5 at equal replica count
        "chunked_p99_ratio": round(
            telemetry.quantile(chunked, 0.99)
            / telemetry.quantile(mono, 0.99), 4),
    }


def _doctored_lm_params(args, scale=1e-2):
    """Target params whose post-layer0 residual branches are
    downscaled so a 1-layer truncated draft tracks the full target
    closely — the high-acceptance regime speculative decoding is
    built for, made reproducible on random weights (with every
    ``layer<k>_`` tensor for k >= 1 scaled to ~0 the pre-norm
    residual blocks contribute ~nothing, so the deep target computes
    ~its own first layer). The SAME doctored target runs on BOTH
    sides of the A/B — the comparison is spec-vs-plain decoding of
    one model, not shallow-vs-deep models."""
    params = dict(_lm_params(args))
    deep = tuple("layer%d_" % k for k in range(1, args.lm_layers))
    for name in list(params):
        if name.startswith(deep):
            params[name] = params[name] * scale
    return params


def _run_speculative(args):
    """The --speculative A/B (docs/serving.md §speculative): plain
    vs draft/verify continuous batching on one in-process decode
    replica behind real TCP, same doctored target both phases.

    Measured shape: `reps` sequential streamed short-prompt sessions
    per phase; the per-session effective inter-token latency is
    (wall first token -> last token) / (tokens - 1) — the fair
    metric, because a spec round emits its accepted tokens in a
    burst (per-gap quantiles reward the in-burst ~0ms gaps and
    punish the round boundary; the session mean is what a caller
    experiences). Acceptance: spec p99 / plain p99 < 1.0 AND
    tokens-per-target-forward > 1.5 at gamma=4 — both only hold
    when acceptance is high, which the doctored tail provides."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.generation import Generator
    from mxnet_tpu.serve import ContinuousDecoder, ServeClient, \
        ServeServer

    rng = np.random.RandomState(0)
    short = rng.randint(1, args.lm_vocab, (args.short_prompt,))
    max_new = max(int(args.max_new), 32)
    reps = max(8, min(args.requests, 40))
    params = _doctored_lm_params(args)

    def _q(vals):
        vals = sorted(vals)
        return {"p50": round(telemetry.quantile(vals, 0.50), 3),
                "p99": round(telemetry.quantile(vals, 0.99), 3)}

    def phase(speculative):
        gen = Generator(params, args.lm_vocab, args.lm_max_len,
                        num_layers=args.lm_layers,
                        num_heads=args.lm_heads, dim=args.lm_dim,
                        batch_size=args.slots)
        draft = gen.truncated_draft(num_layers=args.draft_layers) \
            if speculative else None
        dec = ContinuousDecoder(gen, queue_cap=512, draft=draft,
                                lookahead=args.gamma)
        srv = ServeServer(dec)
        eff, gaps, toks = [], [], None
        try:
            with ServeClient(srv.host, srv.port) as cli:
                # warm BOTH target shapes before measuring: the
                # (B, 1) step and, in the spec phase, the
                # (B, gamma+1) verify + the draft pair
                cli.generate(short, max_new)
                if speculative:
                    cli.generate(short, max_new, speculative=True)
                s0 = dec.stats()
                for _ in range(reps):
                    marks = []
                    out = cli.generate(
                        short, max_new, speculative=speculative,
                        on_token=lambda t:
                        marks.append(telemetry.now_ms()))
                    if toks is None:
                        toks = [int(t) for t in out]
                    if len(marks) >= 2:
                        eff.append((marks[-1] - marks[0])
                                   / (len(marks) - 1))
                        gaps.extend(b - a for a, b in
                                    zip(marks, marks[1:]))
                s1 = dec.stats()
        finally:
            srv.close()
            dec.close()
        delta = {k: s1[k] - s0[k] for k in s1
                 if isinstance(s1[k], (int, float))
                 and isinstance(s0.get(k), (int, float))}
        return eff, gaps, delta, toks

    plain_eff, plain_gaps, plain_delta, plain_toks = phase(False)
    spec_eff, spec_gaps, spec_delta, spec_toks = phase(True)
    if spec_toks != plain_toks:
        # speculative decoding is exact BY CONSTRUCTION (shared-noise
        # verification, docs/serving.md §speculative) — a mismatch
        # here is a correctness bug, not a benchmark artifact
        raise RuntimeError(
            "speculative output diverged from plain decode: %r vs %r"
            % (spec_toks, plain_toks))
    plain_p99 = telemetry.quantile(sorted(plain_eff), 0.99)
    spec_p99 = telemetry.quantile(sorted(spec_eff), 0.99)
    # during the measured spec window every forward is a verify (the
    # sole client sends only speculative requests), so the target-
    # forward count is the steps delta
    tpf = round((reps * max_new) / spec_delta["steps"], 3) \
        if spec_delta.get("steps") else None
    acc = round(spec_delta["spec_accepted"]
                / spec_delta["spec_proposed"], 4) \
        if spec_delta.get("spec_proposed") else None
    return {
        "gamma": int(args.gamma),
        "draft_layers": int(args.draft_layers),
        "target_layers": int(args.lm_layers),
        "max_new": max_new,
        "requests": reps,
        "plain_inter_token_eff_ms": _q(plain_eff),
        "spec_inter_token_eff_ms": _q(spec_eff),
        # acceptance: < 1.0 (per-session effective latency, p99
        # across sessions)
        "inter_token_eff_p99_ratio": round(spec_p99 / plain_p99, 4),
        # acceptance: > 1.5 at gamma=4
        "tokens_per_target_forward": tpf,
        "accept_rate_mean": acc,
        "plain_inter_token_gap_ms": _q(plain_gaps),
        "spec_inter_token_gap_ms": _q(spec_gaps),
        "plain_stats": plain_delta,
        "spec_stats": spec_delta,
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--concurrency", default=None,
                   help="comma-separated closed-loop client counts "
                        "(default 1,2,4,8,16; fleet mode 4,8,16,32 — "
                        "past-saturation levels where replica count, "
                        "not the coalesce window, is the capacity "
                        "knob)")
    p.add_argument("--requests", type=int,
                   default=int(os.environ.get("BENCH_SERVE_REQUESTS",
                                              "100")),
                   help="round trips per client per level")
    p.add_argument("--buckets", default=None,
                   help="engine buckets (default MXNET_SERVE_BUCKETS)")
    p.add_argument("--wait-ms", type=float, default=None,
                   help="coalesce window (default "
                        "MXNET_SERVE_MAX_WAIT_MS)")
    p.add_argument("--features", type=int, default=64)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--classes", type=int, default=16)
    p.add_argument("--replicas", type=int, default=0,
                   help="fleet mode: router + this many subprocess "
                        "replicas (0 = classic in-process engine "
                        "sweep)")
    p.add_argument("--work-ms", type=float, default=None,
                   help="fixed per-forward service time in each "
                        "replica (fleet default 5.0; 0 = raw XLA-CPU "
                        "forwards)")
    p.add_argument("--disagg", default=None, metavar="P:D",
                   help="prefill/decode disaggregation A/B: P "
                        "prefill + D decode generator replicas vs "
                        "P+D colocated ones at equal chip count "
                        "(docs/serving.md §disaggregated prefill)")
    p.add_argument("--sessions", type=int,
                   default=int(os.environ.get("BENCH_DISAGG_SESSIONS",
                                              "4")),
                   help="disagg mode: measured short-prompt decode "
                        "session threads")
    p.add_argument("--load-clients", type=int,
                   default=int(os.environ.get("BENCH_DISAGG_LOAD",
                                              "2")),
                   help="disagg mode: concurrent long-prompt "
                        "generate load threads")
    p.add_argument("--controller", action="store_true",
                   help="load-doubling autoscale bench: 2 subprocess "
                        "replicas under a FleetController, baseline "
                        "load then doubled clients (the controller "
                        "must scale out mid-window) then the doubled "
                        "load against the grown fleet (docs/"
                        "serving.md §fleet controller); acceptance "
                        "is >= 1 scale-out, zero errors, recovered "
                        "p99 < pressure p99")
    p.add_argument("--streaming", action="store_true",
                   help="streaming A/B pair: streamed-vs-one-shot "
                        "TTFT and chunked-vs-monolithic prefill "
                        "inter-token p99 (docs/serving.md "
                        "§streaming)")
    p.add_argument("--speculative", action="store_true",
                   help="speculative-decoding A/B: plain vs "
                        "draft/verify continuous batching on the "
                        "same doctored target (docs/serving.md "
                        "§speculative); acceptance is effective "
                        "inter-token p99 ratio < 1.0 and tokens per "
                        "target forward > 1.5 at gamma=4")
    p.add_argument("--gamma", type=int, default=4,
                   help="speculative mode: draft lookahead per round")
    p.add_argument("--draft-layers", type=int, default=1,
                   help="speculative mode: truncated-draft depth")
    p.add_argument("--prefill-chunk", type=int, default=16,
                   help="streaming mode: MXNET_PREFILL_CHUNK for the "
                        "chunked side of the prefill A/B")
    p.add_argument("--short-prompt", type=int, default=4)
    p.add_argument("--long-prompt", type=int, default=None,
                   help="loader prompt tokens (default 96; streaming "
                        "mode 512 — the chunked-prefill A/B needs a "
                        "prefill wall that dwarfs one-core scheduling "
                        "noise)")
    p.add_argument("--max-new", type=int, default=16,
                   help="disagg mode: tokens per measured decode "
                        "request (inter-token = wall / this)")
    p.add_argument("--slots", type=int, default=4,
                   help="decode replica slot-pool width")
    p.add_argument("--lm-vocab", type=int, default=64)
    p.add_argument("--lm-dim", type=int, default=None,
                   help="decode replica width (default 64; "
                        "speculative mode 256 — below that, per-"
                        "forward dispatch overhead hides the "
                        "draft/target compute gap on CPU)")
    p.add_argument("--lm-layers", type=int, default=None,
                   help="decode replica depth (default 2; "
                        "speculative mode 4 — the draft/target depth "
                        "gap is where the speedup lives)")
    p.add_argument("--lm-heads", type=int, default=2)
    p.add_argument("--lm-max-len", type=int, default=None,
                   help="decode cache length (default 160; streaming "
                        "mode 544 to hold the long-prompt A/B)")
    p.add_argument("--role", default=None,
                   help=argparse.SUPPRESS)   # internal: child role
    p.add_argument("--serve-replica", action="store_true",
                   help=argparse.SUPPRESS)   # internal: child mode
    args = p.parse_args(argv)
    if args.lm_layers is None:
        args.lm_layers = 4 if args.speculative else 2
    if args.lm_dim is None:
        args.lm_dim = 256 if args.speculative else 64
    if args.speculative:
        if args.draft_layers >= args.lm_layers:
            p.error("--draft-layers must be < --lm-layers (the draft "
                    "must be cheaper than the target)")
        if args.short_prompt + max(args.max_new, 32) \
                > (args.lm_max_len or 160) - args.gamma:
            p.error("--short-prompt + max_new exceeds the speculative "
                    "headroom (--lm-max-len - gamma)")
    if args.long_prompt is None:
        args.long_prompt = 512 if args.streaming else 96
    if args.lm_max_len is None:
        args.lm_max_len = 544 if args.streaming else 160
    if args.streaming and \
            args.long_prompt + max(args.max_new, 32) > args.lm_max_len:
        p.error("--long-prompt + max_new exceeds --lm-max-len")
    if args.controller and args.buckets is None:
        # the autoscale signal is QUEUE DEPTH: unit buckets keep the
        # replicas from absorbing the doubled load by coalescing
        # (which would flatten the depth signal the bench exists to
        # drive over the policy threshold)
        args.buckets = "1"
    if args.work_ms is None:
        if args.controller:
            args.work_ms = 20.0
        else:
            args.work_ms = 5.0 if (args.replicas or args.serve_replica) \
                else 0.0

    if args.disagg:
        metric, unit = "serve_disagg_p99", "ms/token"
    elif args.speculative:
        metric, unit = "serve_spec_decode", "ms/token"
    elif args.streaming:
        metric, unit = "serve_streaming_ttft", "ms"
    elif args.controller:
        metric, unit = "serve_controller_scale", "ms"
    elif args.replicas:
        metric, unit = "serve_fleet_throughput", "req/s"
    else:
        metric, unit = "serve_throughput", "req/s"
    if not args.serve_replica:
        try:  # killed mid-run -> still exactly one parseable JSON line
            from bench_common import install_death_stub
            install_death_stub(metric, unit)
        except ImportError:
            pass
    if os.environ.get("BENCH_PLATFORM"):
        os.environ["JAX_PLATFORMS"] = os.environ["BENCH_PLATFORM"]
    if args.serve_replica:
        if args.role in ("prefill", "decode"):
            return _gen_replica_child(args)
        return _replica_child(args)
    if args.controller:
        conc = int(args.concurrency.replace(",", " ").split()[0]) \
            if args.concurrency else 8
        try:
            row = _run_controller(args, conc)
        except Exception as e:  # noqa: BLE001 — diagnostic line (the
            # bench_common fail_payload contract, like the sweeps)
            try:
                from bench_common import fail_payload
                payload = fail_payload(metric, unit, e)
            except ImportError:
                payload = {"metric": metric, "value": None,
                           "unit": unit, "vs_baseline": None,
                           "live": False, "error": "%s: %s"
                           % (type(e).__name__, e)}
            print(json.dumps(payload))
            sys.exit(1)
        print(json.dumps({
            "metric": metric,
            "value": (row["recovered"]["latency_ms"] or {}).get("p99"),
            "unit": unit,
            # acceptance shape: recovered p99 < pressure p99 at the
            # same doubled load (lower is better), zero errors, and
            # at least one controller scale-out mid-run
            "vs_baseline": row["p99_recovery_ratio"],
            **row}))
        return 0
    if args.speculative:
        try:
            row = _run_speculative(args)
        except Exception as e:  # noqa: BLE001 — diagnostic line (the
            # bench_common fail_payload contract, like the sweeps)
            try:
                from bench_common import fail_payload
                payload = fail_payload(metric, unit, e)
            except ImportError:
                payload = {"metric": metric, "value": None,
                           "unit": unit, "vs_baseline": None,
                           "live": False, "error": "%s: %s"
                           % (type(e).__name__, e)}
            print(json.dumps(payload))
            sys.exit(1)
        print(json.dumps({
            "metric": metric,
            "value": row["spec_inter_token_eff_ms"]["p99"],
            "unit": unit,
            # acceptance shape: spec effective inter-token p99 <
            # 1.0x plain on the same target (lower is better), with
            # tokens_per_target_forward > 1.5 at gamma=4
            "vs_baseline": row["inter_token_eff_p99_ratio"],
            **row}))
        return 0
    if args.streaming:
        try:
            row = _run_streaming(args)
        except Exception as e:  # noqa: BLE001 — diagnostic line (the
            # bench_common fail_payload contract, like the sweeps)
            try:
                from bench_common import fail_payload
                payload = fail_payload(metric, unit, e)
            except ImportError:
                payload = {"metric": metric, "value": None,
                           "unit": unit, "vs_baseline": None,
                           "live": False, "error": "%s: %s"
                           % (type(e).__name__, e)}
            print(json.dumps(payload))
            sys.exit(1)
        print(json.dumps({
            "metric": metric,
            "value": row["streamed_ttft_ms"]["p50"],
            "unit": unit,
            # acceptance shape: streamed TTFT p50 <= 0.25x the
            # one-shot total at max_new >= 32 (lower is better)
            "vs_baseline": row["ttft_vs_oneshot"],
            **row}))
        return 0
    if args.disagg:
        try:
            disagg, coloc, micro = _run_disagg(args)
        except Exception as e:  # noqa: BLE001 — diagnostic line (the
            # bench_common fail_payload contract, like the sweeps)
            try:
                from bench_common import fail_payload
                payload = fail_payload(metric, unit, e)
            except ImportError:
                payload = {"metric": metric, "value": None,
                           "unit": unit, "vs_baseline": None,
                           "live": False, "error": "%s: %s"
                           % (type(e).__name__, e)}
            print(json.dumps(payload))
            sys.exit(1)
        d_p99 = (disagg["inter_token_ms"] or {}).get("p99")
        c_p99 = (coloc["inter_token_ms"] or {}).get("p99")
        print(json.dumps({
            "metric": metric,
            "value": d_p99,
            "unit": unit,
            # acceptance shape: disagg p99 <= 0.7x colocated at equal
            # replica count (lower is better)
            "vs_baseline": round(d_p99 / c_p99, 4)
            if d_p99 and c_p99 else None,
            "disagg": disagg,
            "colocated": coloc,
            "handoff": micro}))
        return 0
    if args.concurrency is None:
        args.concurrency = "4,8,16,32" if args.replicas \
            else "1,2,4,8,16"
    levels = sorted({int(c) for c in
                     args.concurrency.replace(",", " ").split()})
    buckets = tuple(int(b) for b in
                    args.buckets.replace(",", " ").split()) \
        if args.buckets else None

    fleet_stats = None
    try:
        if args.replicas:
            sweep, fleet_stats = _run_fleet(args, levels)
        else:
            pred = _build_predictor(args.features, args.hidden,
                                    args.classes)
            sweep = [_run_level(pred, args.features, buckets,
                                args.wait_ms, c, args.requests)
                     for c in levels]
    except Exception as e:  # noqa: BLE001 — diagnostic line, like
        # bench.py: the driver gets a parseable failure, not a trace,
        # with the newest committed capture attached (bench_common —
        # the bench.py last_known pattern, ROADMAP item 5) so a tunnel
        # outage still yields a contentful artifact
        try:
            from bench_common import fail_payload
            payload = fail_payload(metric, "req/s", e)
        except ImportError:
            payload = {"metric": metric, "value": None,
                       "unit": "req/s", "vs_baseline": None,
                       "live": False, "error": "%s: %s"
                       % (type(e).__name__, e)}
        print(json.dumps(payload))
        sys.exit(1)

    best = max(sweep, key=lambda r: r["throughput_rps"] or 0.0)
    base = next((r for r in sweep if r["concurrency"] == levels[0]),
                None) if args.replicas else \
        next((r for r in sweep if r["concurrency"] == 1), None)
    gain = (round(best["throughput_rps"] / base["throughput_rps"], 3)
            if base and base["throughput_rps"] else None)
    payload = {
        "metric": metric,
        "value": best["throughput_rps"],
        "unit": "req/s",
        "vs_baseline": gain,          # gain over the sweep's base level
        "best_concurrency": best["concurrency"],
        "best_latency_ms": best["latency_ms"],
        "sweep": sweep}
    if args.replicas:
        payload["replicas"] = args.replicas
        payload["work_ms"] = args.work_ms
        payload["per_replica_fill"] = best["per_replica_fill"]
        payload["rerouted"] = (fleet_stats or {}).get("rerouted")
    else:
        payload["best_mean_batch_fill"] = best["mean_batch_fill"]
    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main())
