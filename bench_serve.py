"""Serving benchmark: closed-loop load generation against the
ServeEngine (docs/serving.md), structured like bench.py — ONE JSON
line {"metric", "value", "unit", "vs_baseline", ...}.

Offered-load sweep: for each concurrency level C, C closed-loop
clients each run `requests` submit→wait round trips against a fresh
engine; the sweep rows report throughput, request-latency
p50/p95/p99, and the mean batch fill the batcher achieved (the
whole point of the engine — fill should rise with C while per-request
latency stays bounded by the coalesce window + one forward).

    python bench_serve.py                       # default sweep 1,2,4,8,16
    python bench_serve.py --concurrency 1,8,32 --requests 200
    python bench_serve.py --buckets 1,4,16 --wait-ms 2

The headline `value` is the best throughput across the sweep (req/s);
`vs_baseline` is the batching gain — best throughput over the C=1
(unbatched closed-loop) throughput — when the sweep includes C=1.

FLEET MODE (``--replicas N``, docs/serving.md §fleet): the same
offered-load sweep against a ``ServeRouter`` over N subprocess
replicas — each replica its own process (its own GIL, its own XLA
client) behind real TCP, exactly the production topology scaled down.
Rows add per-replica dispatch fill so imbalance is visible; the
acceptance shape is req/s scaling near-linearly in replicas at
bounded p99 (ROADMAP item 2):

    python bench_serve.py --replicas 3          # fleet sweep
    python bench_serve.py --replicas 1          # same topology, N=1
                                                #   (the scaling base)

``--work-ms`` (fleet default 5.0) adds a fixed per-forward service
time in each replica, modeling the device step a CPU-only CI host
doesn't have — set 0 to measure raw XLA-CPU forwards instead. The
emitted metric is ``serve_fleet_throughput`` (same shape, plus
``replicas`` and ``per_replica_fill``).
"""
import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("MXNET_MATMUL_PRECISION", "default")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402


def _build_predictor(feat, hidden, classes, seed=7):
    import mxnet_tpu as mx
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.predictor import Predictor

    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=hidden)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=classes)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    arg_shapes, _, _ = net.infer_shape(data=(1, feat))
    init = Xavier()
    args = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        arr = mx.nd.zeros(shp)
        init(name, arr)
        args[name] = arr
    return Predictor(net, args)


class _TimedModel:
    """Forward wrapper adding a fixed service time per forward —
    the stand-in for device step latency on a CPU-only host (the
    sleep releases the GIL exactly like a device dispatch would)."""

    def __init__(self, pred, work_ms):
        self._pred = pred
        self._work_s = float(work_ms) / 1000.0

    def forward(self, *arrays):
        outs = self._pred.forward(*arrays)
        if self._work_s > 0:
            time.sleep(self._work_s)
        return outs


def _replica_child(args):
    """``--serve-replica`` subprocess body: one engine + ServeServer,
    port announced as one JSON line on stdout, serving until stdin
    closes (the parent's exit — however it exits — is the shutdown
    signal; no orphaned replicas)."""
    from mxnet_tpu.serve import ServeEngine, ServeServer

    pred = _build_predictor(args.features, args.hidden, args.classes)
    model = _TimedModel(pred, args.work_ms) if args.work_ms else pred
    buckets = tuple(int(b) for b in
                    args.buckets.replace(",", " ").split()) \
        if args.buckets else (1, 2, 4)
    eng = ServeEngine(model, buckets=buckets,
                      max_wait_ms=(0.5 if args.wait_ms is None
                                   else args.wait_ms),
                      queue_cap=512, feature_shapes=[(args.features,)],
                      install_sigterm=True)
    srv = ServeServer(eng)
    print(json.dumps({"port": srv.port, "host": srv.host}), flush=True)
    try:
        while sys.stdin.readline():       # parent holds the pipe open
            pass
    finally:
        srv.close()
        eng.close()
    return 0


def _spawn_fleet(args, n):
    """N replica subprocesses; returns (procs, [(host, port)])."""
    import subprocess
    cmd = [sys.executable, os.path.abspath(__file__),
           "--serve-replica",
           "--features", str(args.features),
           "--hidden", str(args.hidden),
           "--classes", str(args.classes),
           "--work-ms", str(args.work_ms)]
    if args.buckets:
        cmd += ["--buckets", args.buckets]
    if args.wait_ms is not None:
        cmd += ["--wait-ms", str(args.wait_ms)]
    procs, addrs = [], []
    for _ in range(n):
        procs.append(subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True))
    import select
    deadline = time.monotonic() + 180.0   # XLA import is the cost
    for p in procs:
        # bounded read: a child hung in startup must fail the bench
        # (fail_payload path), not wedge it on a blocking readline
        remain = deadline - time.monotonic()
        if remain <= 0 or not select.select([p.stdout], [], [],
                                            remain)[0]:
            raise RuntimeError(
                "replica fleet startup timed out (child rc=%s)"
                % p.poll())
        line = p.stdout.readline()
        if not line:
            raise RuntimeError(
                "replica subprocess died before announcing its port "
                "(rc=%s)" % p.poll())
        rec = json.loads(line)
        addrs.append((rec["host"], rec["port"]))
    return procs, addrs


def _kill_fleet(procs):
    for p in procs:
        try:
            p.stdin.close()               # EOF = drain + exit
        except OSError:
            pass
    for p in procs:
        try:
            p.wait(10.0)
        except Exception:  # noqa: BLE001 — escalate to kill
            p.kill()



def _closed_loop(one_round_trip, conc, requests):
    """THE closed-loop measurement harness both sweep modes share:
    conc client threads x requests round trips of ``one_round_trip()``,
    returning the common row fields (throughput, latency quantiles,
    error count). Callers fold in their mode-specific extras."""
    from mxnet_tpu import telemetry

    lat = [[] for _ in range(conc)]
    errs = [0] * conc

    def client(ci):
        for _ in range(requests):
            t0 = telemetry.now_ms()
            try:
                one_round_trip()
            except Exception:  # noqa: BLE001 — shed/timeout counts,
                errs[ci] += 1  # the row reports them
                continue
            lat[ci].append(telemetry.now_ms() - t0)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(conc)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    flat = sorted(v for row in lat for v in row)
    done = len(flat)
    return {
        "concurrency": conc,
        "requests": done,
        "errors": sum(errs),
        "throughput_rps": round(done / wall, 2) if wall else None,
        "latency_ms": {
            "p50": round(telemetry.quantile(flat, 0.50), 3),
            "p95": round(telemetry.quantile(flat, 0.95), 3),
            "p99": round(telemetry.quantile(flat, 0.99), 3),
            "mean": round(sum(flat) / done, 3),
        } if done else None,
    }


def _run_fleet_level(router, names, feat, conc, requests):
    """One closed-loop level against the (persistent) fleet: conc
    clients x requests round trips through the router. Per-replica
    fill comes from dispatch-count deltas."""
    before = {n: r["dispatched"]
              for n, r in router.replicas().items()}
    x = np.random.RandomState(0).standard_normal(
        (1, feat)).astype(np.float32)
    row = _closed_loop(lambda: router.request([x]), conc, requests)
    after = router.replicas()
    row["per_replica_fill"] = {
        n: after[n]["dispatched"] - before.get(n, 0) for n in names}
    return row


def _run_fleet(args, levels):
    """The --replicas N sweep: router + N subprocess replicas, one
    JSON line out (metric serve_fleet_throughput)."""
    from mxnet_tpu.serve import ServeRouter

    procs, addrs = _spawn_fleet(args, args.replicas)
    router = None
    try:
        # pool enough connections for the deepest sweep level — a
        # closed-loop client holds one for its whole round trip, and
        # re-dialing per request would measure TCP setup, not serving
        conns = max(int(c) for c in
                    args.concurrency.replace(",", " ").split())
        router = ServeRouter(replicas=addrs, conns_per_replica=conns)
        names = list(router.replicas())
        router.warmup()                   # no cold compiles in level 1
        sweep = [_run_fleet_level(router, names, args.features, c,
                                  args.requests) for c in levels]
        fleet_stats = router.stats()
    finally:
        if router is not None:
            router.close()
        _kill_fleet(procs)
    return sweep, fleet_stats


def _run_level(pred, feat, buckets, wait_ms, conc, requests):
    """One closed-loop level: conc clients x requests round trips
    against a FRESH engine (clean per-level stats). Returns the sweep
    row."""
    from mxnet_tpu.serve import ServeEngine

    eng = ServeEngine(pred, buckets=buckets, max_wait_ms=wait_ms,
                      feature_shapes=[(feat,)],
                      install_sigterm=False)
    eng.warmup()
    x = np.random.RandomState(0).standard_normal(
        (1, feat)).astype(np.float32)
    row = _closed_loop(lambda: eng.infer(x, timeout=60.0), conc,
                       requests)
    eng.close()
    st = eng.stats()
    row["forwards"] = st["forwards"]
    row["mean_batch_fill"] = round(st["mean_fill"], 3) \
        if st["mean_fill"] else None
    return row


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--concurrency", default=None,
                   help="comma-separated closed-loop client counts "
                        "(default 1,2,4,8,16; fleet mode 4,8,16,32 — "
                        "past-saturation levels where replica count, "
                        "not the coalesce window, is the capacity "
                        "knob)")
    p.add_argument("--requests", type=int,
                   default=int(os.environ.get("BENCH_SERVE_REQUESTS",
                                              "100")),
                   help="round trips per client per level")
    p.add_argument("--buckets", default=None,
                   help="engine buckets (default MXNET_SERVE_BUCKETS)")
    p.add_argument("--wait-ms", type=float, default=None,
                   help="coalesce window (default "
                        "MXNET_SERVE_MAX_WAIT_MS)")
    p.add_argument("--features", type=int, default=64)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--classes", type=int, default=16)
    p.add_argument("--replicas", type=int, default=0,
                   help="fleet mode: router + this many subprocess "
                        "replicas (0 = classic in-process engine "
                        "sweep)")
    p.add_argument("--work-ms", type=float, default=None,
                   help="fixed per-forward service time in each "
                        "replica (fleet default 5.0; 0 = raw XLA-CPU "
                        "forwards)")
    p.add_argument("--serve-replica", action="store_true",
                   help=argparse.SUPPRESS)   # internal: child mode
    args = p.parse_args(argv)
    if args.work_ms is None:
        args.work_ms = 5.0 if (args.replicas or args.serve_replica) \
            else 0.0

    metric = "serve_fleet_throughput" if args.replicas \
        else "serve_throughput"
    if not args.serve_replica:
        try:  # killed mid-run -> still exactly one parseable JSON line
            from bench_common import install_death_stub
            install_death_stub(metric, "req/s")
        except ImportError:
            pass
    if os.environ.get("BENCH_PLATFORM"):
        os.environ["JAX_PLATFORMS"] = os.environ["BENCH_PLATFORM"]
    if args.serve_replica:
        return _replica_child(args)
    if args.concurrency is None:
        args.concurrency = "4,8,16,32" if args.replicas \
            else "1,2,4,8,16"
    levels = sorted({int(c) for c in
                     args.concurrency.replace(",", " ").split()})
    buckets = tuple(int(b) for b in
                    args.buckets.replace(",", " ").split()) \
        if args.buckets else None

    fleet_stats = None
    try:
        if args.replicas:
            sweep, fleet_stats = _run_fleet(args, levels)
        else:
            pred = _build_predictor(args.features, args.hidden,
                                    args.classes)
            sweep = [_run_level(pred, args.features, buckets,
                                args.wait_ms, c, args.requests)
                     for c in levels]
    except Exception as e:  # noqa: BLE001 — diagnostic line, like
        # bench.py: the driver gets a parseable failure, not a trace,
        # with the newest committed capture attached (bench_common —
        # the bench.py last_known pattern, ROADMAP item 5) so a tunnel
        # outage still yields a contentful artifact
        try:
            from bench_common import fail_payload
            payload = fail_payload(metric, "req/s", e)
        except ImportError:
            payload = {"metric": metric, "value": None,
                       "unit": "req/s", "vs_baseline": None,
                       "live": False, "error": "%s: %s"
                       % (type(e).__name__, e)}
        print(json.dumps(payload))
        sys.exit(1)

    best = max(sweep, key=lambda r: r["throughput_rps"] or 0.0)
    base = next((r for r in sweep if r["concurrency"] == levels[0]),
                None) if args.replicas else \
        next((r for r in sweep if r["concurrency"] == 1), None)
    gain = (round(best["throughput_rps"] / base["throughput_rps"], 3)
            if base and base["throughput_rps"] else None)
    payload = {
        "metric": metric,
        "value": best["throughput_rps"],
        "unit": "req/s",
        "vs_baseline": gain,          # gain over the sweep's base level
        "best_concurrency": best["concurrency"],
        "best_latency_ms": best["latency_ms"],
        "sweep": sweep}
    if args.replicas:
        payload["replicas"] = args.replicas
        payload["work_ms"] = args.work_ms
        payload["per_replica_fill"] = best["per_replica_fill"]
        payload["rerouted"] = (fleet_stats or {}).get("rerouted")
    else:
        payload["best_mean_batch_fill"] = best["mean_batch_fill"]
    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main())
