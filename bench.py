"""Headline benchmark: ResNet-50 training throughput (img/s) on one chip.

Baseline: reference MXNet trains ResNet-50 at 109 img/s (batch 32) on one
K80 (BASELINE.md; example/image-classification/README.md:147-155). Same
workload here: full fwd+bwd+SGD-momentum update, synthetic ImageNet batch
(the reference's own benchmark mode, train_imagenet.py --benchmark 1).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} (plus
step-time / MFU diagnostics). On backend failure prints a diagnostic JSON
line instead of a stack trace, still rc!=0 so the driver records the error.
"""
import json
import os
import sys
import time
import traceback

# MXU-friendly matmul precision for the perf path (see mxnet_tpu/__init__)
os.environ.setdefault("MXNET_MATMUL_PRECISION", "default")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

BASELINE_IMG_S = 109.0  # reference ResNet-50, 1x K80, batch 32

# bf16/fp32 peak FLOP/s per chip by device kind, for the MFU estimate.
# (TPU v4/v5e/v5p/v6e public numbers; fp32 host fallback is a nominal 1e12.)
_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def _fail(stage, err):
    print(json.dumps({
        "metric": "resnet50_train_throughput", "value": None, "unit": "img/s",
        "vs_baseline": None, "error_stage": stage,
        "error": "".join(traceback.format_exception_only(type(err), err))
                 .strip()[:500]}))
    sys.exit(1)


def main():
    # --- stage 1: backend probe, before building anything -----------------
    # A dead TPU tunnel HANGS inside (GIL-holding) backend init rather
    # than raising — a signal-based watchdog cannot interrupt it. Probe in
    # a SUBPROCESS with a hard timeout so a hang becomes a diagnostic JSON
    # (not rc=124 with no output) before this process touches the backend.
    import subprocess

    timeout_s = int(os.environ.get("BENCH_BACKEND_TIMEOUT", "180"))
    probe_src = (
        "import jax, os\n"
        "p = os.environ.get('BENCH_PLATFORM')\n"
        "if p: jax.config.update('jax_platforms', p)\n"
        "jax.block_until_ready(jax.numpy.zeros((8, 8)) + 1.0)\n"
        "print('kind:', jax.devices()[0].device_kind)\n")
    try:
        r = subprocess.run([sys.executable, "-c", probe_src],
                           timeout=timeout_s, capture_output=True,
                           text=True)
        if r.returncode != 0:
            raise RuntimeError("backend probe failed: %s"
                               % r.stderr.strip()[-400:])
    except subprocess.TimeoutExpired:
        _fail("backend_init", TimeoutError(
            "backend init hung for %ds (TPU tunnel down or unresponsive)"
            % timeout_s))
    except Exception as e:  # noqa: BLE001
        _fail("backend_init", e)

    try:
        import jax
        if os.environ.get("BENCH_PLATFORM"):
            jax.config.update("jax_platforms",
                              os.environ["BENCH_PLATFORM"])
        devices = jax.devices()
        dev = devices[0]
        jax.block_until_ready(jax.numpy.zeros((8, 8)) + 1.0)
    except Exception as e:  # noqa: BLE001
        _fail("backend_init", e)

    # --- stage 2: build model + step fn on host (no device work) ----------
    try:
        from mxnet_tpu.models import resnet
        from mxnet_tpu.parallel import make_train_step
        from mxnet_tpu.initializer import Xavier

        batch = int(os.environ.get("BENCH_BATCH", "128"))
        # bf16 compute with f32 master weights (mp_sgd semantics) is the
        # TPU perf path; BENCH_DTYPE=float32 measures full precision
        dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
        image = 224
        sym = resnet.get_symbol(num_classes=1000, num_layers=50,
                                image_shape=(3, image, image))
        step = make_train_step(
            sym, optimizer="sgd",
            optimizer_params={"momentum": 0.9, "wd": 1e-4,
                              "rescale_grad": 1.0 / batch},
            compute_dtype=None if dtype == "float32" else dtype)
        x = np.random.RandomState(0).standard_normal(
            (batch, 3, image, image)).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 1000, (batch,)).astype(
            np.float32)
        batch_vals = {"data": x, "softmax_label": y}
    except Exception as e:  # noqa: BLE001
        _fail("graph_build", e)

    # --- stage 3: init params on device ------------------------------------
    try:
        state = step.init_state(Xavier(factor_type="in", magnitude=2.0),
                                {"data": (batch, 3, image, image),
                                 "softmax_label": (batch,)})
        rng = jax.random.PRNGKey(0)
    except Exception as e:  # noqa: BLE001
        _fail("param_init", e)

    # --- stage 4: compile + warmup -----------------------------------------
    # The batch lives on device for the whole loop (one H2D total): the
    # training loop overlaps host input with device compute via
    # PrefetchingIter; paying a fresh 38MB transfer per timed step would
    # measure the tunnel, not the chip. Sync via host readback of a
    # scalar — through the axon tunnel, block_until_ready alone does not
    # guarantee device completion.
    try:
        batch_dev = step.place_batch(batch_vals)
        for _ in range(2):
            state, outs = step(state, batch_dev, 0.1, rng)
        np.asarray(jax.device_get(outs[0]))
    except Exception as e:  # noqa: BLE001
        _fail("compile_warmup", e)

    # --- stage 5: timed loop ------------------------------------------------
    iters = int(os.environ.get("BENCH_ITERS", "20"))
    t0 = time.time()
    for _ in range(iters):
        state, outs = step(state, batch_dev, 0.1, rng)
    np.asarray(jax.device_get(outs[0]))   # true completion barrier
    dt = time.time() - t0

    img_s = batch * iters / dt
    step_ms = dt / iters * 1e3

    # MFU: actual FLOPs of the compiled step (XLA cost analysis) over the
    # chip's peak. Falls back to a 3x-forward analytic estimate.
    step_flops = None
    try:
        cost = step.cost_analysis(state, batch_vals, 0.1, rng)
        if cost and cost.get("flops"):
            step_flops = float(cost["flops"])
    except Exception:  # noqa: BLE001
        pass
    if not step_flops:
        step_flops = 3 * 2 * 3.86e9 * batch  # 3.86 GMACs fwd / 224px image
    peak = _PEAK_FLOPS.get(getattr(dev, "device_kind", ""), None)
    mfu = (step_flops / (dt / iters)) / peak if peak else None

    print(json.dumps({
        "metric": "resnet50_train_throughput",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "step_time_ms": round(step_ms, 2),
        "batch": batch,
        "compute_dtype": dtype,
        "device_kind": getattr(dev, "device_kind", "unknown"),
        "mfu": round(mfu, 4) if mfu is not None else None}))


if __name__ == "__main__":
    main()
