"""Headline benchmark. Default: ResNet-50 training throughput (img/s) on
one chip — same contract as always, ONE JSON line
{"metric", "value", "unit", "vs_baseline"}.

--network selects any catalog workload, mirroring the reference's
baseline table (example/image-classification/README.md:147-156) plus the
compute-dense transformer LM:

    python bench.py                          # resnet-50 (driver default)
    python bench.py --network resnet-18      # other depths: 34/101/152
    python bench.py --network inception-v3   # also inception-bn, alexnet
    python bench.py --network transformer_lm # MFU workload (tokens/s)

Baselines are the reference's published 1x K80 img/s numbers (BASELINE.md).
The transformer has no reference baseline (the reference predates it);
vs_baseline reports MFU against the 0.45 north-star instead.

On backend failure prints a diagnostic JSON line instead of a stack
trace, with the last committed bench_out/ capture attached as a
`last_known` SUB-OBJECT only (top-level value stays null). Exit codes
disambiguate for the driver:
  rc=1  real failure (bad install, graph build error, fast probe error)
  rc=3  tunnel HANG under the driver-default config with a last_known
        capture available — infra outage, not a regression
A driver that wants the old promote-stale-into-value behavior must
explicitly opt in with BENCH_ALLOW_LAST_KNOWN=1 (then rc=0 with
"source": "last_known", "live": false). Nothing is promoted silently.
"""
import argparse
import json
import os
import sys
import time
import traceback

# MXU-friendly matmul precision for the perf path (see mxnet_tpu/__init__)
os.environ.setdefault("MXNET_MATMUL_PRECISION", "default")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

# bf16/fp32 peak FLOP/s per chip by device kind, for the MFU estimate.
# (TPU v4/v5e/v5p/v6e public numbers; fp32 host fallback is a nominal 1e12.)
_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# image workloads: name -> (models.get_symbol kwargs, default batch,
# reference 1xK80 img/s baseline from BASELINE.md, fwd GMACs/image for
# the flops fallback, input size). inception-v3's baseline and GMACs are
# 299px figures — benching it at 224 would overstate vs_baseline ~1.8x.
_IMAGE_NETS = {
    "resnet-18": (dict(network="resnet", num_layers=18), 128, 185.0,
                  1.8, 224),
    "resnet-34": (dict(network="resnet", num_layers=34), 128, 172.0,
                  3.6, 224),
    "resnet-50": (dict(network="resnet", num_layers=50), 128, 109.0,
                  3.86, 224),
    "resnet-101": (dict(network="resnet", num_layers=101), 96, 78.0,
                   7.6, 224),
    "resnet-152": (dict(network="resnet", num_layers=152), 64, 57.0,
                   11.3, 224),
    "inception-bn": (dict(network="inception-bn"), 128, 152.0, 1.6, 224),
    "inception-v3": (dict(network="inception-v3"), 64, 30.4, 5.7, 299),
    "alexnet": (dict(network="alexnet"), 512, 457.0, 0.7, 224),
}

# transformer LM defaults: compute-dense enough that one v5e chip can
# reach the >=0.45 MFU north star (big matmuls, flash attention)
_TLM = dict(vocab=32768, seq_len=2048, layers=4, heads=16, dim=2048,
            batch=8)


# set by main(): last-known promotion only applies when the invocation
# is the driver-default config (no CLI/env overrides), so a stale
# capture can never stand in for a DIFFERENTLY-CONFIGURED run
_DEFAULT_CONFIG = False


def _last_known(metric):
    """Most recent COMMITTED bench_out/ capture for this metric, so a
    tunnel outage at driver-run time never produces a contentless
    artifact (implementation shared with bench_serve.py /
    bench_scaling.py via bench_common.py — only git-tracked files
    count, ordered by commit date). Returns (record, provenance) or
    (None, None)."""
    try:
        from bench_common import last_known
    except ImportError:      # moved/renamed sibling: degrade, don't die
        return None, None
    return last_known(metric)


def _fail(metric, stage, err):
    """Diagnostic JSON on failure; top-level value stays null and
    last_known is attached as a SUB-OBJECT only, never silently
    promoted (advisor r4: a driver recording value/rc without checking
    'live' must not log stale hardware numbers as a fresh run).

    Exit codes: rc=3 when the failure is a tunnel HANG (TimeoutError in
    backend_init — the flaky-infra signature) under the driver-default
    config with a last_known capture attached; rc=1 for everything
    else. BENCH_ALLOW_LAST_KNOWN=1 is the explicit driver opt-in that
    restores the old promotion (value from last_known, rc=0, labeled
    "source": "last_known", "live": false)."""
    unit = "tokens/s" if metric.startswith("transformer") else "img/s"
    err_s = "".join(traceback.format_exception_only(type(err), err)) \
            .strip()[:500]
    payload = {"metric": metric, "value": None, "unit": unit,
               "vs_baseline": None, "error_stage": stage, "error": err_s,
               "live": False}
    rc = 1
    rec, prov = _last_known(metric)
    if rec is not None:
        # _last_known returning a record proves bench_common imported
        from bench_common import carry_fields
        payload["last_known"] = carry_fields(rec, prov)
        if stage == "backend_init" and isinstance(err, TimeoutError) \
                and _DEFAULT_CONFIG:
            if os.environ.get("BENCH_ALLOW_LAST_KNOWN") == "1":
                payload.update(value=rec.get("value"),
                               vs_baseline=rec.get("vs_baseline"),
                               source="last_known", live=False)
                print(json.dumps(payload))
                sys.exit(0)
            rc = 3   # infra outage (stale data available), not a bug
    print(json.dumps(payload))
    sys.exit(rc)


def _probe_backend(metric):
    """A dead TPU tunnel HANGS inside (GIL-holding) backend init rather
    than raising — a signal-based watchdog cannot interrupt it. Probe in
    a SUBPROCESS with a hard timeout so a hang becomes a diagnostic JSON
    (not rc=124 with no output) before this process touches the backend.

    The tunnel flaps (three rounds of driver benches hit it down), so a
    single probe is not enough: retry every ~60 s within a
    BENCH_TUNNEL_WAIT budget (default 20 min), and only then fall back
    to the last committed capture via _fail."""
    import subprocess

    timeout_s = int(os.environ.get("BENCH_BACKEND_TIMEOUT", "180"))
    budget_s = float(os.environ.get("BENCH_TUNNEL_WAIT", "1200"))
    probe_src = (
        "import jax, os\n"
        "p = os.environ.get('BENCH_PLATFORM')\n"
        "if p: jax.config.update('jax_platforms', p)\n"
        "jax.block_until_ready(jax.numpy.zeros((8, 8)) + 1.0)\n"
        "print('kind:', jax.devices()[0].device_kind)\n")
    t0 = time.time()
    attempt = 0
    last_err = None
    saw_hang = False
    while True:
        attempt += 1
        remaining = budget_s - (time.time() - t0)
        try:
            r = subprocess.run([sys.executable, "-c", probe_src],
                               timeout=min(timeout_s, max(remaining, 30)),
                               capture_output=True, text=True)
            if r.returncode == 0:
                break
            last_err = RuntimeError("backend probe failed: %s"
                                    % r.stderr.strip()[-400:])
        except subprocess.TimeoutExpired:
            saw_hang = True
            last_err = TimeoutError(
                "backend init hung (TPU tunnel down or unresponsive); "
                "%d probes over %.0fs" % (attempt, time.time() - t0))
        except Exception as e:  # noqa: BLE001
            last_err = e
        remaining = budget_s - (time.time() - t0)
        if remaining <= 0:
            if saw_hang and not isinstance(last_err, TimeoutError):
                last_err = TimeoutError(
                    "backend init hung on earlier probes; final probe: "
                    "%s" % last_err)
            _fail(metric, "backend_init", last_err)
        print("bench: backend probe %d failed (%s); retrying, %.0fs of "
              "budget left" % (attempt, last_err, remaining),
              file=sys.stderr)
        time.sleep(min(60, max(remaining, 1)))

    try:
        import jax
        if os.environ.get("BENCH_PLATFORM"):
            jax.config.update("jax_platforms",
                              os.environ["BENCH_PLATFORM"])
        dev = jax.devices()[0]
        jax.block_until_ready(jax.numpy.zeros((8, 8)) + 1.0)
        return jax, dev
    except Exception as e:  # noqa: BLE001
        _fail(metric, "backend_init", e)


def _timed_loop(jax, step, state, batch_dev, iters, metric, lr=0.1):
    """Warmup (2 steps + hard sync) then the timed loop. Sync via host
    readback of a SCALAR derived from the last step's output — through
    the axon tunnel, block_until_ready alone does not guarantee device
    completion, and reading the full output tensor would measure tunnel
    transfer bandwidth, not the step (the transformer head's softmax
    output is ~2 GB; pulling it once cost more than 30 training steps).

    Returns (elapsed seconds, live state) — the state handed in is
    donated by the step, so the caller must carry the returned one."""
    rng = jax.random.PRNGKey(0)
    scalar = jax.jit(lambda x: x.ravel()[0])
    try:
        for _ in range(2):
            state, outs = step(state, batch_dev, lr, rng)
        np.asarray(jax.device_get(scalar(outs[0])))
    except Exception as e:  # noqa: BLE001
        _fail(metric, "compile_warmup", e)

    t0 = time.time()
    for _ in range(iters):
        state, outs = step(state, batch_dev, lr, rng)
    np.asarray(jax.device_get(scalar(outs[0])))  # completion barrier
    return time.time() - t0, state


def _telemetry_pass(jax, step, state, batch_dev, lr, iters, samples,
                    metric):
    """Per-step telemetry journal for the run (ISSUE 8 satellite):
    a short extra pass where each step blocks on a scalar readback, so
    the recorded wall times are true per-step times (the headline
    timed loop stays sync-free and is untouched). Writes the journal
    (MXNET_TELEMETRY, else a temp dir) and returns the summary dict
    folded into the BENCH json. Never fails the bench."""
    try:
        import tempfile

        from mxnet_tpu import telemetry
        from tools.telemetry_report import load, summarize

        jr = telemetry.journal()
        if jr is None:
            jr = telemetry.start_journal(
                tempfile.mkdtemp(prefix="bench-telemetry-"), run=metric)
        rng = jax.random.PRNGKey(0)
        scalar = jax.jit(lambda x: x.ravel()[0])
        n = max(3, min(int(iters), 10))
        # prime: the fresh scalar-readback jit compiles here, not
        # inside the first recorded step
        state, outs = step(state, batch_dev, lr, rng)
        np.asarray(jax.device_get(scalar(outs[0])))
        last = telemetry.now_ms()
        for i in range(n):
            state, outs = step(state, batch_dev, lr, rng)
            np.asarray(jax.device_get(scalar(outs[0])))
            now = telemetry.now_ms()
            telemetry.journal_step(loop="bench", run=metric, step=i,
                                   wall_ms=round(now - last, 3),
                                   samples=samples)
            last = now
        recs = [r for r in load(jr.path)
                if r.get("kind") == "step" and r.get("run") == metric]
        s = summarize(recs)
        return {"journal": jr.path, "synced_steps": n,
                "step_ms_p50": s["step_ms"]["p50"],
                "step_ms_p95": s["step_ms"]["p95"],
                "samples_per_sec": s["samples_per_sec"]}
    except Exception as e:  # noqa: BLE001 — telemetry never fails a bench
        return {"error": str(e)[:200]}


def _mfu(step, state, batch_vals, dev, sec_per_step, fallback_flops,
         jax, model_flops_only=False):
    """Actual FLOPs of the compiled step (XLA cost analysis; the analytic
    fallback covers kernels the analysis can't see) over the chip peak.

    model_flops_only (remat runs): cost analysis would count the
    recomputed forward too — that's HFU, not MFU — so use the analytic
    MODEL flops alone and a slower remat run can never report a higher
    MFU."""
    step_flops = None
    if not model_flops_only:
        try:
            cost = step.cost_analysis(state, batch_vals, 0.1,
                                      jax.random.PRNGKey(0))
            if cost and cost.get("flops"):
                step_flops = float(cost["flops"])
        except Exception:  # noqa: BLE001
            pass
    step_flops = max(step_flops or 0.0, fallback_flops)
    peak = _PEAK_FLOPS.get(getattr(dev, "device_kind", ""), None)
    mfu = (step_flops / sec_per_step) / peak if peak else None
    return mfu, step_flops


def bench_image(name, args):
    metric = _metric_for(name)
    net_kwargs, def_batch, baseline, gmacs, image = _IMAGE_NETS[name]
    jax, dev = _probe_backend(metric)

    batch = args.batch or int(os.environ.get("BENCH_BATCH", def_batch))
    dtype = args.dtype or os.environ.get("BENCH_DTYPE", "bfloat16")
    try:
        from mxnet_tpu import models
        from mxnet_tpu.parallel import make_train_step
        from mxnet_tpu.initializer import Xavier

        kwargs = dict(net_kwargs)
        kwargs.setdefault("num_classes", 1000)
        if kwargs["network"] == "resnet":
            kwargs["image_shape"] = (3, image, image)
        sym = models.get_symbol(**kwargs)
        step = make_train_step(
            sym, optimizer="sgd",
            optimizer_params={"momentum": 0.9, "wd": 1e-4,
                              "rescale_grad": 1.0 / batch},
            compute_dtype=None if dtype == "float32" else dtype,
            remat=args.remat or None)
        x = np.random.RandomState(0).standard_normal(
            (batch, 3, image, image)).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 1000, (batch,)).astype(
            np.float32)
        batch_vals = {"data": x, "softmax_label": y}
    except Exception as e:  # noqa: BLE001
        _fail(metric, "graph_build", e)

    try:
        state = step.init_state(Xavier(factor_type="in", magnitude=2.0),
                                {"data": (batch, 3, image, image),
                                 "softmax_label": (batch,)})
        batch_dev = step.place_batch(batch_vals)
    except Exception as e:  # noqa: BLE001
        _fail(metric, "param_init", e)

    iters = args.iters or int(os.environ.get("BENCH_ITERS", "20"))
    dt, state = _timed_loop(jax, step, state, batch_dev, iters, metric)

    img_s = batch * iters / dt
    # fwd GMACs x2 flops/MAC x3 (fwd + ~2x bwd)
    fallback = 3 * 2 * gmacs * 1e9 * batch
    mfu, _flops = _mfu(step, state, batch_vals, dev, dt / iters,
                       fallback, jax, model_flops_only=args.remat)
    # after _mfu: the telemetry pass keeps stepping (donating) the state
    telemetry = _telemetry_pass(jax, step, state, batch_dev, 0.1,
                                iters, batch, metric)
    print(json.dumps({
        "metric": metric,
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / baseline, 3),
        "step_time_ms": round(dt / iters * 1e3, 2),
        "batch": batch,
        "compute_dtype": dtype,
        "window": args.window,
        "remat": bool(args.remat),
        "device_kind": getattr(dev, "device_kind", "unknown"),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "telemetry": telemetry}))


def _metric_for(network, decode=False, beam=0, spec=0):
    """The payload metric name for a bench configuration — ONE place,
    shared by the branch benches and the death stub (a drifted copy
    files a killed run's diagnostic under the wrong metric). The
    ``_gqa%d`` suffix follows BENCH_TLM_KV_HEADS like the live
    branches always did."""
    if network != "transformer_lm":
        return "%s_train_throughput" % network.replace("-", "")
    if not decode:
        metric = "transformer_lm_train_throughput"
    elif beam:
        metric = "transformer_lm_beam%d_decode_throughput" % beam
    elif spec:
        metric = "transformer_lm_spec%d_decode_throughput" % spec
    else:
        metric = "transformer_lm_decode_throughput"
    kv_heads = int(os.environ.get("BENCH_TLM_KV_HEADS", "0")) or None
    if kv_heads:
        metric += "_gqa%d" % kv_heads
    return metric


def bench_transformer(args):
    """Compute-dense LM workload: tokens/s + MFU. vs_baseline = measured
    MFU / 0.45 north star (BASELINE.md; the reference has no transformer)."""
    metric = _metric_for("transformer_lm")
    kv_heads = int(os.environ.get("BENCH_TLM_KV_HEADS", "0")) or None
    jax, dev = _probe_backend(metric)

    c = dict(_TLM)
    for k in c:   # BENCH_TLM_DIM=256 etc. (smoke tests on CPU)
        c[k] = int(os.environ.get("BENCH_TLM_%s" % k.upper(), c[k]))
    if args.batch:
        c["batch"] = args.batch
    if args.seq_len:
        c["seq_len"] = args.seq_len
    B, T, D, L = c["batch"], c["seq_len"], c["dim"], c["layers"]
    V, F = c["vocab"], 4 * c["dim"]
    dtype = args.dtype or os.environ.get("BENCH_DTYPE", "bfloat16")
    try:
        from mxnet_tpu.models import transformer
        from mxnet_tpu.parallel import make_train_step
        from mxnet_tpu.initializer import Xavier

        # BENCH_TLM_LOSS_CHUNK=N: chunked fused CE head — bounds the
        # head's live memory at (N, vocab) instead of (B*T, vocab),
        # the enabler for 64k-token training on one chip
        loss_chunk = int(os.environ.get("BENCH_TLM_LOSS_CHUNK", "0"))
        sym = transformer.get_symbol(V, T, num_layers=L,
                                     num_heads=c["heads"], dim=D,
                                     ffn_hidden=F,
                                     num_kv_heads=kv_heads,
                                     attention_window=args.window or 0,
                                     loss_chunk=loss_chunk)
        step = make_train_step(
            sym, optimizer="adam",
            optimizer_params={"rescale_grad": 1.0 / B},
            compute_dtype=None if dtype == "float32" else dtype,
            remat=args.remat or None)
        rng_np = np.random.RandomState(0)
        toks = rng_np.randint(0, V, (B, T)).astype(np.float32)
        labels = np.roll(toks, -1, axis=1)
        labels[:, -1] = -1
        batch_vals = {"data": toks, "softmax_label": labels}
    except Exception as e:  # noqa: BLE001
        _fail(metric, "graph_build", e)

    try:
        state = step.init_state(Xavier(), {"data": (B, T),
                                           "softmax_label": (B, T)})
        batch_dev = step.place_batch(batch_vals)
    except Exception as e:  # noqa: BLE001
        _fail(metric, "param_init", e)

    iters = args.iters or int(os.environ.get("BENCH_ITERS", "20"))
    dt, state = _timed_loop(jax, step, state, batch_dev, iters, metric,
                            lr=1e-4)

    tok_s = B * T * iters / dt
    # analytic train flops (fwd x3): dense projections 8D^2+4DF per
    # token per layer (with GQA the k/v projections shrink to
    # Hkv*hd columns: 4D^2 + 4*D*kvdim), attention 4*Teff*D per token
    # per layer (QK^T + PV; Teff = min(T, window) under sliding-window
    # attention), vocab head 2DV per token. Matches the scaling-book
    # accounting; used as the floor under cost_analysis (the Pallas
    # flash kernel's internal flops are invisible to XLA's analysis).
    t_eff = min(T, args.window) if args.window else T
    kvdim = (D // c["heads"]) * kv_heads if kv_heads else D
    fwd = B * T * (L * (4 * D * D + 4 * D * kvdim + 4 * D * F
                        + 4 * t_eff * D)
                   + 2 * D * V)
    mfu, flops = _mfu(step, state, batch_vals, dev, dt / iters, 3 * fwd,
                      jax, model_flops_only=args.remat)
    # samples = tokens for the LM metric (tokens/s is the unit)
    telemetry = _telemetry_pass(jax, step, state, batch_dev, 1e-4,
                                iters, B * T, metric)
    print(json.dumps({
        "metric": metric,
        "value": round(tok_s, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 3) if mfu is not None else None,
        "step_time_ms": round(dt / iters * 1e3, 2),
        "batch": B, "seq_len": T, "dim": D, "layers": L,
        "compute_dtype": dtype,
        "window": args.window,
        "remat": bool(args.remat),
        "loss_chunk": loss_chunk or None,
        "device_kind": getattr(dev, "device_kind", "unknown"),
        "step_tflops": round(flops / 1e12, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "telemetry": telemetry}))


def bench_decode(args):
    """KV-cache decode throughput: the whole prefill+scan generation
    runs as ONE device program (Generator.generate_on_device), so the
    measurement is chip decode speed, not dispatch round-trips.
    Decode is memory-bandwidth-bound (every step streams the full
    parameter set + caches), so tokens/s is the metric; no baseline
    (the reference predates transformer serving)."""
    beam = int(args.beam or 0)
    spec = int(args.speculative or 0)
    # BENCH_TLM_KV_HEADS: grouped-query decode (cache holds Hkv heads
    # instead of H — the decode path is cache-bandwidth-bound, so this
    # measures the GQA win directly). Named before the probe so early
    # failures report under the right metric.
    metric = _metric_for("transformer_lm", decode=True, beam=beam,
                         spec=spec)
    kv_heads = int(os.environ.get("BENCH_TLM_KV_HEADS", "0")) or None
    jax, dev = _probe_backend(metric)

    c = dict(_TLM)
    for k in c:
        c[k] = int(os.environ.get("BENCH_TLM_%s" % k.upper(), c[k]))
    if args.batch:
        c["batch"] = args.batch
    B, D, L, V = c["batch"], c["dim"], c["layers"], c["vocab"]
    # --seq-len sets the prompt length for decode
    P = args.seq_len or int(os.environ.get("BENCH_DECODE_PROMPT",
                                           "128"))
    N = int(os.environ.get("BENCH_DECODE_TOKENS", "256"))
    # on-device speculative needs P + N + lookahead cache headroom on
    # both models (fixed-shape rounds may overrun by up to lookahead)
    max_len = P + N + (spec if spec else 0)
    dtype = args.dtype or os.environ.get("BENCH_DTYPE", "bfloat16")
    try:
        from mxnet_tpu.generation import Generator
        from mxnet_tpu.models import transformer
        from mxnet_tpu.parallel import make_train_step
        from mxnet_tpu.initializer import Xavier

        sym = transformer.get_symbol(V, max_len, num_layers=L,
                                     num_heads=c["heads"], dim=D,
                                     ffn_hidden=4 * D,
                                     num_kv_heads=kv_heads)
        step = make_train_step(sym, optimizer="sgd")
        state = step.init_state(Xavier(), {
            "data": (B, max_len), "softmax_label": (B, max_len)})
        qz = args.quantize or ""
        gen = Generator(state[0], V, max_len=max_len, num_layers=L,
                        num_heads=c["heads"], dim=D,
                        batch_size=B, num_kv_heads=kv_heads,
                        dtype=None if dtype == "float32" else dtype,
                        quantize="int8" if "int8" in qz else None,
                        quantize_kv="kv8" in qz)
        draft = None
        if spec:
            # draft = same vocab/batch, quarter the layers and half the
            # width (the classic small-proposer setup); its own random
            # init is fine — the bench measures the mechanism's cost,
            # and a random draft gives the WORST-case acceptance, so
            # the reported tokens/s is a floor
            dL = max(1, L // 4)
            dD, dH = D // 2, max(1, c["heads"] // 2)
            dsym = transformer.get_symbol(V, max_len, num_layers=dL,
                                          num_heads=dH, dim=dD,
                                          ffn_hidden=4 * dD)
            dstep = make_train_step(dsym, optimizer="sgd")
            dstate = dstep.init_state(Xavier(), {
                "data": (B, max_len), "softmax_label": (B, max_len)})
            draft = Generator(dstate[0], V, max_len=max_len,
                              num_layers=dL, num_heads=dH, dim=dD,
                              batch_size=B,
                              dtype=None if dtype == "float32"
                              else dtype)
        prompt = np.random.RandomState(0).randint(0, V, (B, P))
    except Exception as e:  # noqa: BLE001
        _fail(metric, "graph_build", e)

    # marginal-rate measurement: time the program at two generation
    # lengths and difference them, so the (identical) prefill cost
    # cancels and the metric is PURE decode tokens/s
    N_SHORT = max(1, N // 8)
    if beam:
        run = lambda n, i: gen.beam_search_on_device(prompt, n,
                                                     beam_size=beam)
    elif spec:
        run = lambda n, i: gen.generate_speculative_on_device(
            draft, prompt, n, lookahead=spec)
    else:
        run = lambda n, i: gen.generate_on_device(prompt, n, seed=i)
    rounds = None
    try:
        if spec:   # warmup doubles as the acceptance telemetry read
            out, rounds = gen.generate_speculative_on_device(
                draft, prompt, N, lookahead=spec, return_rounds=True)
        else:
            out = run(N, 0)                       # compile + warmup
        assert out.shape == (B, P + N)
        run(N_SHORT, 0)                           # compile short
    except Exception as e:  # noqa: BLE001
        _fail(metric, "compile_warmup", e)

    iters = args.iters or int(os.environ.get("BENCH_ITERS", "3"))

    def timed(n_tok):
        t0 = time.time()
        for i in range(iters):
            run(n_tok, i)
        return (time.time() - t0) / iters         # output is host numpy

    dt_long = timed(N)
    dt_short = timed(N_SHORT)
    dt_decode = max(dt_long - dt_short, 1e-9)
    tok_s = B * (N - N_SHORT) / dt_decode
    print(json.dumps({
        "metric": metric,
        "value": round(tok_s, 2),
        "unit": "tokens/s",
        "vs_baseline": None,
        "ms_per_token": round(dt_decode / (N - N_SHORT) * 1e3, 3),
        "end_to_end_tokens_s": round(B * N / dt_long, 2),
        "batch": B, "prompt_len": P, "new_tokens": N,
        "beam": beam or None,
        "speculative_lookahead": spec or None,
        "spec_rounds": rounds,
        "spec_accepted_per_round":
            round(N / rounds - 1, 3) if rounds else None,
        "kv_heads": kv_heads,
        "dim": D, "layers": L, "compute_dtype": dtype,
        "quantize": args.quantize,
        "device_kind": getattr(dev, "device_kind", "unknown")}))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--network", default="resnet-50",
                   choices=sorted(_IMAGE_NETS) + ["transformer_lm"])
    p.add_argument("--batch", type=int, default=None)
    p.add_argument("--seq-len", type=int, default=None,
                   help="transformer_lm only")
    p.add_argument("--iters", type=int, default=None)
    p.add_argument("--dtype", default=None,
                   choices=["float32", "bfloat16"])
    p.add_argument("--remat", action="store_true",
                   help="rematerialize the forward (activation memory "
                        "/ recompute trade — for configs that don't "
                        "fit HBM otherwise)")
    p.add_argument("--decode", action="store_true",
                   help="transformer_lm only: KV-cache generation "
                        "throughput instead of training")
    p.add_argument("--window", type=int, default=None,
                   help="transformer_lm only: sliding-window attention "
                        "width (training bench)")
    p.add_argument("--quantize", default=None,
                   choices=["int8", "kv8", "int8+kv8"],
                   help="with --decode: int8 = weight-only int8 "
                        "(halved weight HBM traffic), kv8 = int8 KV "
                        "caches with per-token scales (halved cache "
                        "traffic — the dominant stream at long "
                        "prompts), int8+kv8 = both")
    p.add_argument("--beam", type=int, default=None,
                   help="with --decode: on-device beam search width "
                        "(beams fold into the batch; tokens/s counts "
                        "emitted sequences, not beams)")
    p.add_argument("--speculative", type=int, default=None,
                   metavar="LOOKAHEAD",
                   help="with --decode: on-device speculative decoding "
                        "with a 1/4-depth half-width random-init draft "
                        "(worst-case acceptance floor); reports "
                        "acceptance telemetry")
    args = p.parse_args()
    if args.quantize and not args.decode:
        p.error("--quantize applies to --decode only")
    if args.beam and not args.decode:
        p.error("--beam applies to --decode only")
    if args.speculative and not args.decode:
        p.error("--speculative applies to --decode only")
    if args.speculative and args.beam:
        p.error("--speculative and --beam are mutually exclusive")
    global _DEFAULT_CONFIG
    _DEFAULT_CONFIG = (
        args.batch is None and args.seq_len is None
        and args.iters is None and args.dtype is None
        and not args.remat and not args.window and not args.quantize
        and not args.beam and not args.speculative
        and not any(k.startswith(("BENCH_BATCH", "BENCH_DTYPE",
                                  "BENCH_TLM_", "BENCH_DECODE_",
                                  "BENCH_ITERS"))
                    for k in os.environ))
    # killed mid-run -> still exactly one parseable JSON line with the
    # branch's real metric name (bench_common.install_death_stub;
    # _metric_for is the same naming the branch benches use)
    stub_metric = _metric_for(
        args.network, decode=bool(args.decode),
        beam=int(args.beam or 0), spec=int(args.speculative or 0))
    try:
        from bench_common import install_death_stub
        install_death_stub(stub_metric,
                           "tokens/s" if args.network ==
                           "transformer_lm" else "img/s")
    except ImportError:
        pass
    if args.network == "transformer_lm":
        if args.decode:
            if args.remat:
                p.error("--remat is a training knob; not valid with "
                        "--decode")
            bench_decode(args)
        else:
            bench_transformer(args)
    else:
        bench_image(args.network, args)


if __name__ == "__main__":
    main()
