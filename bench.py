"""Headline benchmark: ResNet-50 training throughput (img/s) on one chip.

Baseline: reference MXNet trains ResNet-50 at 109 img/s (batch 32) on one
K80 (BASELINE.md; example/image-classification/README.md:147-155). Same
workload here: full fwd+bwd+SGD-momentum update, synthetic ImageNet batch
(the reference's own benchmark mode, train_imagenet.py --benchmark 1).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import sys
import time

# MXU-friendly matmul precision for the perf path (see mxnet_tpu/__init__)
os.environ.setdefault("MXNET_MATMUL_PRECISION", "default")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

BASELINE_IMG_S = 109.0  # reference ResNet-50, 1x K80, batch 32


def main():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.models import resnet
    from mxnet_tpu.parallel import make_train_step
    from mxnet_tpu.initializer import Xavier

    batch = int(os.environ.get("BENCH_BATCH", "64"))
    image = 224
    sym = resnet.get_symbol(num_classes=1000, num_layers=50,
                            image_shape=(3, image, image))

    step = make_train_step(sym, optimizer="sgd",
                           optimizer_params={"momentum": 0.9, "wd": 1e-4,
                                             "rescale_grad": 1.0 / batch})
    state = step.init_state(Xavier(factor_type="in", magnitude=2.0),
                            {"data": (batch, 3, image, image),
                             "softmax_label": (batch,)})

    rng = jax.random.PRNGKey(0)
    x = np.random.RandomState(0).standard_normal(
        (batch, 3, image, image)).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 1000, (batch,)).astype(
        np.float32)
    batch_vals = {"data": x, "softmax_label": y}

    # warmup/compile
    for _ in range(2):
        state, outs = step(state, batch_vals, 0.1, rng)
    jax.block_until_ready(outs)

    iters = int(os.environ.get("BENCH_ITERS", "20"))
    t0 = time.time()
    for _ in range(iters):
        state, outs = step(state, batch_vals, 0.1, rng)
    jax.block_until_ready(outs)
    dt = time.time() - t0

    img_s = batch * iters / dt
    print(json.dumps({
        "metric": "resnet50_train_throughput",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3)}))


if __name__ == "__main__":
    main()
