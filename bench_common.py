"""Shared last-known-capture fallback for the bench family (bench.py,
bench_serve.py, bench_scaling.py — ROADMAP item 5).

BENCH_r01–r05 lost 4 of 5 rounds to the TPU tunnel being down; the
fallback pattern (bench.py pioneered it) makes a tunnel outage produce
a diagnostic JSON line with the most recent COMMITTED ``bench_out/``
capture attached as a ``last_known`` SUB-OBJECT — never silently
promoted into the top-level ``value`` (the driver opts in with
BENCH_ALLOW_LAST_KNOWN=1 where that behavior exists). Only git-tracked
captures count, ordered by commit date, so an uncommitted scratch run
can never stand in for a published number.
"""
import glob
import json
import os
import subprocess

_HERE = os.path.dirname(os.path.abspath(__file__))


def is_experiment_row(rec):
    """tools/perf_tables.is_experiment_row when importable (one
    predicate for every consumer of bench_out records), else the same
    rule inline (the benches must stay standalone-runnable)."""
    try:
        from tools.perf_tables import is_experiment_row as _impl
        return _impl(rec)
    except ImportError:
        return bool(rec.get("ab_config"))


def last_known(metric, here=_HERE):
    """Most recent COMMITTED bench_out/ capture for this metric.
    Returns (record, provenance) or (None, None)."""
    out_dir = os.path.join(here, "bench_out")
    best = None           # (commit_epoch, record, provenance)
    for path in sorted(glob.glob(os.path.join(out_dir, "*.json*"))):
        rel = os.path.relpath(path, here)
        try:
            r = subprocess.run(
                ["git", "log", "-1", "--format=%h %ct %cI", "--", rel],
                cwd=here, capture_output=True, text=True, timeout=10)
            if r.returncode != 0 or not r.stdout.strip():
                continue   # untracked: not a committed capture
            commit, epoch, date = r.stdout.strip().split(None, 2)
            # order by the EPOCH (%ct): ISO strings with mixed
            # committer timezones don't sort chronologically
            epoch = int(epoch)
        except Exception:  # noqa: BLE001
            continue
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line or not line.startswith("{"):
                        continue
                    rec = json.loads(line)
                    if is_experiment_row(rec):
                        continue
                    if rec.get("metric") == metric and \
                            rec.get("value") is not None and \
                            (best is None or epoch >= best[0]):
                        best = (epoch, rec,
                                {"file": rel, "commit": commit,
                                 "captured": date})
        except Exception:  # noqa: BLE001
            continue
    if best is None:
        return None, None
    return best[1], best[2]


# fields worth carrying from a stale capture into the diagnostic line
_CARRY = ("value", "unit", "vs_baseline", "mfu", "step_time_ms",
          "device_kind", "best_concurrency", "devices", "samples_s")


def carry_fields(rec, prov):
    """The ``last_known`` sub-object for an already-fetched capture —
    THE single definition of which fields a stale capture carries into
    a diagnostic line (bench.py needs the raw record too for its rc=3
    promotion logic, so it calls this rather than attach_last_known)."""
    out = {k: rec.get(k) for k in _CARRY if rec.get(k) is not None}
    out.update(prov or {})
    return out


def attach_last_known(payload, metric, here=_HERE):
    """Fold the newest committed capture for ``metric`` into
    ``payload["last_known"]`` (sub-object only; the top-level value is
    untouched). Returns True when a capture was found."""
    rec, prov = last_known(metric, here=here)
    if rec is None:
        return False
    payload["last_known"] = carry_fields(rec, prov)
    return True


def install_death_stub(metric, unit, **extra):
    """SIGTERM/SIGINT -> one parseable diagnostic JSON line, then
    exit 1. A bench killed mid-run (tunnel watchdog, CI timeout,
    tools/tpu_bench_session.sh moving on) previously died with a bare
    KeyboardInterrupt / nothing on stdout — no journal, no capture,
    nothing the driver could parse. With the stub installed the dying
    bench still emits the same ``fail_payload`` contract as any other
    failure path (value null, live:false, newest committed capture
    attached), so every exit of a bench process yields exactly one
    JSON line. Install it in main() BEFORE the heavy imports/workload:
    the whole point is covering the window where nothing else can.

    SIGKILL cannot be caught — that contract stops at the shell
    (tpu_bench_session.sh installs captures only on rc=0).

    Test hook: ``BENCH_TEST_HANG_AFTER_ARM=<seconds>`` prints
    ``BENCH_DEATH_STUB_ARMED`` to stderr and sleeps, so the
    kill-mid-run test (tests/test_bench_tools.py) has a deterministic
    window to deliver the signal in."""
    import signal
    import sys
    import time

    def _die(signum, _frame):
        err = RuntimeError(
            "killed by signal %d mid-run (no capture produced)"
            % signum)
        payload = fail_payload(metric, unit, err, signal=signum,
                               **extra)
        try:
            sys.stdout.write(json.dumps(payload) + "\n")
            sys.stdout.flush()
        finally:
            os._exit(1)

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _die)
    hang = float(os.environ.get("BENCH_TEST_HANG_AFTER_ARM", 0) or 0)
    if hang:
        sys.stderr.write("BENCH_DEATH_STUB_ARMED\n")
        sys.stderr.flush()
        time.sleep(hang)


def fail_payload(metric, unit, err, **extra):
    """The shared diagnostic-line shape for a failed bench run:
    null value, the error, live:false, and the newest committed
    capture attached (never promoted). One place to evolve the
    contract the driver parses."""
    import traceback
    payload = {"metric": metric, "value": None, "unit": unit,
               "vs_baseline": None, "live": False,
               "error": "".join(traceback.format_exception_only(
                   type(err), err)).strip()[:500]}
    payload.update(extra)
    try:
        attach_last_known(payload, metric)
    except Exception:  # noqa: BLE001 — fallback never masks the error
        pass
    return payload
