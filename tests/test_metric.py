"""Metric tests — reference: tests/python/unittest/test_metric.py."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import metric


def test_accuracy():
    m = metric.create("acc")
    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]])
    label = mx.nd.array([1, 0, 0])
    m.update([label], [pred])
    name, value = m.get()
    assert name == "accuracy"
    assert abs(value - 2.0 / 3) < 1e-6


def test_top_k():
    m = metric.create("top_k_accuracy", top_k=2)
    pred = mx.nd.array([[0.1, 0.5, 0.4], [0.6, 0.3, 0.1]])
    label = mx.nd.array([2, 1])
    m.update([label], [pred])
    _, value = m.get()
    assert abs(value - 1.0) < 1e-6  # both labels in top-2


def test_mse_mae_rmse():
    label = mx.nd.array([1.0, 2.0])
    pred = mx.nd.array([1.5, 1.0])
    for name, expect in [("mse", (0.25 + 1.0) / 2),
                         ("mae", (0.5 + 1.0) / 2),
                         ("rmse", np.sqrt((0.25 + 1.0) / 2))]:
        m = metric.create(name)
        m.update([label], [pred])
        assert abs(m.get()[1] - expect) < 1e-6, name


def test_perplexity():
    m = metric.create("perplexity", ignore_label=None)
    pred = mx.nd.array([[0.5, 0.5], [0.9, 0.1]])
    label = mx.nd.array([0, 0])
    m.update([label], [pred])
    expected = np.exp(-(np.log(0.5) + np.log(0.9)) / 2)
    assert abs(m.get()[1] - expected) < 1e-5


def test_composite_and_custom():
    m = metric.create(["acc", "ce"])
    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1]])
    label = mx.nd.array([1, 0])
    m.update([label], [pred])
    names, values = m.get()
    assert names == ["accuracy", "cross-entropy"]

    def feval(label, pred):
        return float(np.abs(label - pred.argmax(axis=1)).sum())
    cm = metric.np(feval, name="absdiff")
    cm.update([label], [pred])
    assert cm.get()[1] == 0.0


def test_f1():
    m = metric.create("f1")
    pred = mx.nd.array([[0.2, 0.8], [0.8, 0.2], [0.3, 0.7]])
    label = mx.nd.array([1, 0, 0])
    m.update([label], [pred])
    # tp=1 fp=1 fn=0 -> p=0.5 r=1 -> f1=2/3
    assert abs(m.get()[1] - 2.0 / 3) < 1e-6
