"""Pretrained-checkpoint inference against committed golden logits —
the reference's pretrained-zoo forward test
(tests/python/gpu/test_forward.py) made hermetic: a tiny seeded
ResNet-8 checkpoint lives in tests/fixtures/ (see make_zoo_fixture.py
to regenerate), and BOTH deployment paths must reproduce the recorded
logits:

  1. load_checkpoint -> Predictor       (the MXPredCreate path)
  2. Predictor.export -> CompiledPredictor.load  (AOT StableHLO reload)
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx

HERE = os.path.dirname(os.path.abspath(__file__))
PREFIX = os.path.join(HERE, "fixtures", "zoo_resnet8")


@pytest.fixture(scope="module")
def golden():
    blob = np.load(PREFIX + "_golden.npz")
    return blob["probe"], blob["logits"]


def test_checkpoint_predictor_reproduces_golden(golden):
    probe, want = golden
    pred = mx.predictor.load_checkpoint_predictor(PREFIX, 0)
    got = pred.forward(probe)[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    # softmax output: rows are distributions
    np.testing.assert_allclose(got.sum(axis=1), 1.0, rtol=1e-5)


def test_compiled_predictor_reproduces_golden(golden, tmp_path):
    probe, want = golden
    pred = mx.predictor.load_checkpoint_predictor(PREFIX, 0)
    prefix = str(tmp_path / "zoo_resnet8_aot")
    pred.export(prefix, {"data": probe.shape})

    reloaded = mx.predictor.CompiledPredictor.load(prefix)
    got = reloaded.forward(probe)[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    assert reloaded.output_names == pred.output_names


def test_module_load_checkpoint_reproduces_golden(golden):
    """Module.load path (the fit-resume surface) gives the same
    numbers as the predictor path."""
    probe, want = golden
    sym, arg_params, aux_params = mx.model.load_checkpoint(PREFIX, 0)
    mod = mx.mod.Module(sym, context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", probe.shape)],
             label_shapes=[("softmax_label", (probe.shape[0],))],
             for_training=False)
    mod.set_params(arg_params, aux_params)
    from mxnet_tpu import io, nd
    batch = io.DataBatch([nd.array(probe)],
                         [nd.zeros((probe.shape[0],))])
    mod.forward(batch, is_train=False)
    got = mod.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
