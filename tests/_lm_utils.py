"""Shared LM-test helpers: the arithmetic-stride toy corpus and the
NLL readout used by the transformer convergence gates. One copy, so
the loss/ignore-label conventions can't drift between gates."""
import numpy as np


def arith_corpus(B, T, vocab, seed=5):
    """(tokens, labels): each row counts by a random stride mod vocab —
    fully predictable from context, so tiny LMs drive NLL toward 0.
    labels are next-token with -1 (ignore) at the last position."""
    rng = np.random.RandomState(seed)
    starts = rng.randint(0, vocab, B)
    strides = rng.randint(1, 4, B)
    toks = ((starts[:, None] + strides[:, None] * np.arange(T)[None, :])
            % vocab).astype(np.float32)
    labels = np.roll(toks, -1, axis=1).astype(np.float32)
    labels[:, -1] = -1
    return toks, labels


def lm_nll(outs, labels, vocab):
    """Mean next-token NLL from the softmax output (B*T, V), ignoring
    -1-labelled positions."""
    B, T = labels.shape
    pr = np.asarray(outs[0]).astype(np.float32).reshape(B, T, vocab)
    tgt = labels.astype(int)
    bi, ti = np.nonzero(tgt >= 0)
    return float(-np.log(np.maximum(pr[bi, ti, tgt[bi, ti]],
                                    1e-9)).mean())
