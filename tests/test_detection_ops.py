"""SSD op stack vs numpy oracles (reference
src/operator/contrib/multibox_*.cc, src/operator/roi_pooling.cc)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


# -- numpy oracles (independent re-implementations of the reference
#    loops) -------------------------------------------------------------


def np_prior(h, w, sizes, ratios, clip=False, steps=(-1, -1),
             offsets=(0.5, 0.5)):
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    out = []
    for r in range(h):
        cy = (r + offsets[0]) * step_y
        for c in range(w):
            cx = (c + offsets[1]) * step_x
            for s in sizes:
                bw = s * h / w / 2
                bh = s / 2
                out.append([cx - bw, cy - bh, cx + bw, cy + bh])
            for ratio in ratios[1:]:
                sr = np.sqrt(ratio)
                bw = sizes[0] * h / w * sr / 2
                bh = sizes[0] / sr / 2
                out.append([cx - bw, cy - bh, cx + bw, cy + bh])
    out = np.array(out, np.float32)
    if clip:
        out = np.clip(out, 0, 1)
    return out[None]


def np_iou(a, b):
    iw = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    ih = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    i = iw * ih
    u = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - i
    return 0.0 if u <= 0 else i / u


def np_nms(rows, nms_threshold, force_suppress):
    rows = rows.copy()
    n = len(rows)
    for i in range(n):
        if rows[i, 0] < 0:
            continue
        for j in range(i + 1, n):
            if rows[j, 0] < 0:
                continue
            if force_suppress or rows[i, 0] == rows[j, 0]:
                if np_iou(rows[i, 2:6], rows[j, 2:6]) >= nms_threshold:
                    rows[j] = -1
    return rows


def test_multibox_prior_matches_reference_loop():
    x = nd.zeros((1, 3, 4, 6))
    out = nd._contrib_MultiBoxPrior(
        x, sizes=(0.5, 0.3), ratios=(1.0, 2.0, 0.5), clip=True).asnumpy()
    want = np_prior(4, 6, [0.5, 0.3], [1.0, 2.0, 0.5], clip=True)
    assert out.shape == (1, 4 * 6 * 4, 4)
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)


def test_multibox_prior_steps_offsets():
    x = nd.zeros((1, 1, 2, 2))
    out = nd.MultiBoxPrior(x, sizes=(0.4,), ratios=(1.0,),
                           steps=(0.6, 0.4), offsets=(0.3, 0.7)).asnumpy()
    want = np_prior(2, 2, [0.4], [1.0], steps=(0.6, 0.4),
                    offsets=(0.3, 0.7))
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)


def _simple_target_setup():
    # 4 anchors, 2 gt boxes, 3 classes (bg + 2)
    anchors = np.array([[0.0, 0.0, 0.5, 0.5],
                        [0.5, 0.5, 1.0, 1.0],
                        [0.0, 0.5, 0.5, 1.0],
                        [0.2, 0.2, 0.4, 0.4]], np.float32)[None]
    label = np.array([[[0, 0.05, 0.05, 0.45, 0.45],
                       [1, 0.55, 0.55, 0.95, 0.95],
                       [-1, -1, -1, -1, -1]]], np.float32)
    cls_pred = np.zeros((1, 3, 4), np.float32)
    return anchors, label, cls_pred


def test_multibox_target_matching_and_encoding():
    anchors, label, cls_pred = _simple_target_setup()
    loc_t, loc_m, cls_t = nd._contrib_MultiBoxTarget(
        nd.array(anchors), nd.array(label), nd.array(cls_pred))
    cls_t = cls_t.asnumpy()[0]
    loc_m = loc_m.asnumpy()[0].reshape(4, 4)
    loc_t = loc_t.asnumpy()[0].reshape(4, 4)

    # anchor0 matches gt0 (class 0 -> target 1), anchor1 gt1 (-> 2)
    assert cls_t[0] == 1.0 and cls_t[1] == 2.0
    # others below overlap threshold: negatives (background 0), since
    # negative_mining_ratio defaults to -1 (use all negatives)
    assert cls_t[2] == 0.0 and cls_t[3] == 0.0
    assert loc_m[0].all() and loc_m[1].all()
    assert not loc_m[2].any() and not loc_m[3].any()

    # loc encoding vs hand formula for anchor0/gt0
    a = anchors[0, 0]
    g = label[0, 0, 1:5]
    aw, ah = a[2] - a[0], a[3] - a[1]
    ax, ay = (a[0] + a[2]) / 2, (a[1] + a[3]) / 2
    gw, gh = g[2] - g[0], g[3] - g[1]
    gx, gy = (g[0] + g[2]) / 2, (g[1] + g[3]) / 2
    want = [(gx - ax) / aw / 0.1, (gy - ay) / ah / 0.1,
            np.log(gw / aw) / 0.2, np.log(gh / ah) / 0.2]
    np.testing.assert_allclose(loc_t[0], want, rtol=1e-4, atol=1e-5)


def test_multibox_target_negative_mining():
    anchors, label, cls_pred = _simple_target_setup()
    # anchor 3 is confidently background, anchor 2 is not: hard-negative
    # mining keeps the HARDEST negative (lowest bg prob) — reference
    # multibox_target.cc:229 sorts by -softmax_bg ascending-in-prob
    cls_pred[0, 0, :] = [0.1, 0.1, 0.1, 5.0]
    loc_t, loc_m, cls_t = nd._contrib_MultiBoxTarget(
        nd.array(anchors), nd.array(label), nd.array(cls_pred),
        negative_mining_ratio=0.5, negative_mining_thresh=0.5)
    cls_t = cls_t.asnumpy()[0]
    # 2 positives * 0.5 = 1 negative: anchor 2 (hard); anchor 3 ignored
    assert cls_t[0] == 1.0 and cls_t[1] == 2.0
    assert cls_t[2] == 0.0
    assert cls_t[3] == -1.0


def test_multibox_target_no_gt():
    anchors = np.array([[[0, 0, 0.5, 0.5]]], np.float32)
    label = -np.ones((1, 2, 5), np.float32)
    cls_pred = np.zeros((1, 2, 1), np.float32)
    loc_t, loc_m, cls_t = nd.MultiBoxTarget(
        nd.array(anchors), nd.array(label), nd.array(cls_pred))
    assert (cls_t.asnumpy() == -1).all()
    assert (loc_m.asnumpy() == 0).all()
    assert (loc_t.asnumpy() == 0).all()


def test_multibox_detection_nms_vs_numpy():
    rng = np.random.RandomState(0)
    A, C = 8, 3
    anchors = np.zeros((A, 4), np.float32)
    centers = rng.uniform(0.2, 0.8, (A, 2))
    anchors[:, 0:2] = centers - 0.1
    anchors[:, 2:4] = centers + 0.1
    # two clusters of overlapping anchors
    anchors[1] = anchors[0] + 0.01
    anchors[3] = anchors[2] + 0.01
    cls_prob = rng.uniform(0, 1, (1, C, A)).astype(np.float32)
    cls_prob /= cls_prob.sum(1, keepdims=True)
    loc_pred = (rng.uniform(-0.2, 0.2, (1, A * 4))).astype(np.float32)

    out = nd._contrib_MultiBoxDetection(
        nd.array(cls_prob), nd.array(loc_pred), nd.array(anchors[None]),
        nms_threshold=0.45, threshold=0.1).asnumpy()[0]

    # numpy oracle: decode + sort + nms
    scores = cls_prob[0, 1:].max(0)
    ids = cls_prob[0, 1:].argmax(0) + 1
    valid = scores >= 0.1
    boxes = np.zeros((A, 4), np.float32)
    for i in range(A):
        a = anchors[i]
        p = loc_pred[0, i * 4:i * 4 + 4]
        aw, ah = a[2] - a[0], a[3] - a[1]
        ax, ay = (a[0] + a[2]) / 2, (a[1] + a[3]) / 2
        ox = p[0] * 0.1 * aw + ax
        oy = p[1] * 0.1 * ah + ay
        ow = np.exp(p[2] * 0.2) * aw / 2
        oh = np.exp(p[3] * 0.2) * ah / 2
        boxes[i] = np.clip([ox - ow, oy - oh, ox + ow, oy + oh], 0, 1)
    order = np.argsort(-np.where(valid, scores, -1), kind="stable")
    rows = np.full((A, 6), -1, np.float32)
    for r, i in enumerate(order):
        if valid[i]:
            rows[r] = [ids[i] - 1, scores[i], *boxes[i]]
    want = np_nms(rows, 0.45, False)

    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_multibox_detection_force_suppress_and_topk():
    cls_prob = np.array([[[0.1, 0.2, 0.1],
                          [0.8, 0.1, 0.8],
                          [0.1, 0.7, 0.1]]], np.float32)  # (1,3,3)
    anchors = np.array([[[0.1, 0.1, 0.4, 0.4],
                         [0.12, 0.12, 0.42, 0.42],
                         [0.6, 0.6, 0.9, 0.9]]], np.float32)
    loc_pred = np.zeros((1, 12), np.float32)
    out = nd.MultiBoxDetection(
        nd.array(cls_prob), nd.array(loc_pred), nd.array(anchors),
        force_suppress=True, nms_threshold=0.5).asnumpy()[0]
    kept = out[out[:, 0] >= 0]
    # anchors 0/1 overlap heavily; different classes, but force_suppress
    # kills the lower-scoring one
    assert len(kept) == 2
    out2 = nd.MultiBoxDetection(
        nd.array(cls_prob), nd.array(loc_pred), nd.array(anchors),
        force_suppress=True, nms_threshold=0.5, nms_topk=1).asnumpy()[0]
    assert (out2[:, 0] >= 0).sum() == 1


def test_multibox_detection_background_id():
    # class 2 is background: anchor 0's best foreground is class 0,
    # anchor 1's is class 1 (renumbered to 1 — below background, so kept)
    cls_prob = np.array([[[0.9, 0.1],
                          [0.05, 0.6],
                          [0.05, 0.3]]], np.float32)  # (1,3,2)
    anchors = np.array([[[0.1, 0.1, 0.4, 0.4],
                         [0.6, 0.6, 0.9, 0.9]]], np.float32)
    loc_pred = np.zeros((1, 8), np.float32)
    out = nd.MultiBoxDetection(
        nd.array(cls_prob), nd.array(loc_pred), nd.array(anchors),
        background_id=2, threshold=0.01,
        nms_threshold=0.0).asnumpy()[0]
    kept = out[out[:, 0] >= 0]
    assert set(kept[:, 0].astype(int)) == {0, 1}
    np.testing.assert_allclose(sorted(kept[:, 1]), [0.6, 0.9], rtol=1e-5)


def test_roi_pooling_vs_numpy():
    rng = np.random.RandomState(1)
    data = rng.randn(2, 3, 8, 8).astype(np.float32)
    rois = np.array([[0, 0, 0, 7, 7],
                     [1, 2, 2, 6, 6],
                     [0, 4, 4, 7, 5]], np.float32)
    out = nd.ROIPooling(nd.array(data), nd.array(rois),
                        pooled_size=(2, 2), spatial_scale=1.0).asnumpy()

    def np_roi(img, x1, y1, x2, y2, ph, pw):
        rw = max(x2 - x1 + 1, 1)
        rh = max(y2 - y1 + 1, 1)
        out = np.zeros((img.shape[0], ph, pw), np.float32)
        for i in range(ph):
            for j in range(pw):
                hs = int(np.floor(i * rh / ph)) + y1
                he = int(np.ceil((i + 1) * rh / ph)) + y1
                ws = int(np.floor(j * rw / pw)) + x1
                we = int(np.ceil((j + 1) * rw / pw)) + x1
                hs, he = max(hs, 0), min(he, img.shape[1])
                ws, we = max(ws, 0), min(we, img.shape[2])
                if he > hs and we > ws:
                    out[:, i, j] = img[:, hs:he, ws:we].max((1, 2))
        return out

    for r, roi in enumerate(rois):
        want = np_roi(data[int(roi[0])], int(roi[1]), int(roi[2]),
                      int(roi[3]), int(roi[4]), 2, 2)
        np.testing.assert_allclose(out[r], want, rtol=1e-5, atol=1e-6,
                                   err_msg="roi %d" % r)


def test_roi_pooling_spatial_scale():
    data = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 15, 15]], np.float32)
    out = nd.ROIPooling(nd.array(data), nd.array(rois),
                        pooled_size=(1, 1), spatial_scale=0.25).asnumpy()
    assert out.reshape(()) == 15.0


def test_detection_ops_jittable():
    """The whole target+detection path must trace under jit (static
    shapes, no host sync) — that's the TPU-native requirement."""
    import jax

    anchors, label, cls_pred = _simple_target_setup()

    from mxnet_tpu.ops.registry import get_op

    tgt = get_op("_contrib_MultiBoxTarget")
    f = jax.jit(lambda a, l, c: tgt.fn(a, l, c,
                                       negative_mining_ratio=2.0))
    outs = f(anchors, label, cls_pred)
    assert outs[2].shape == (1, 4)

    det = get_op("_contrib_MultiBoxDetection")
    g = jax.jit(lambda c, l, a: det.fn(c, l, a))
    res = g(np.zeros((1, 3, 4), np.float32),
            np.zeros((1, 16), np.float32), anchors)
    assert res.shape == (1, 4, 6)


def test_nms_pallas_matches_xla_path():
    """The blocked Pallas NMS must agree with the dense-matrix XLA path
    on full MultiBoxDetection outputs (including vmap over the batch)."""
    import os
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    rng = np.random.RandomState(7)
    B, C, A = 2, 4, 300
    cls_prob = rng.rand(B, C, A).astype(np.float32)
    cls_prob /= cls_prob.sum(1, keepdims=True)
    loc_pred = (rng.rand(B, A * 4).astype(np.float32) - 0.5) * 0.4
    xy = rng.rand(1, A, 2).astype(np.float32)
    wh = rng.rand(1, A, 2).astype(np.float32) * 0.3
    anchor = np.concatenate([xy, xy + wh], axis=2)

    def run(impl, force):
        return nd._contrib_MultiBoxDetection(
            nd.array(cls_prob), nd.array(loc_pred), nd.array(anchor),
            nms_threshold=0.45, threshold=0.05, nms_topk=200,
            force_suppress=force, impl=impl).asnumpy()

    # impl is an op attr (part of the jit cache key), so the two runs
    # really trace + execute different NMS implementations; both the
    # class-aware and force_suppress branches are compared
    for force in (False, True):
        out_pallas = run("pallas", force)
        out_xla = run("xla", force)
        np.testing.assert_allclose(out_pallas, out_xla,
                                   rtol=1e-6, atol=1e-6)
        assert (out_pallas[:, :, 0] >= 0).sum() > 0  # something survived


def test_nms_pallas_iou_matches_shared_helper():
    """_iou_tile restates _box_iou_corner (Mosaic can't reuse it); pin
    the two implementations to identical numerics."""
    from mxnet_tpu.ops.nms_pallas import _iou_tile
    from mxnet_tpu.ops.detection_ops import _box_iou_corner
    rng = np.random.RandomState(3)
    xy = rng.rand(60, 2).astype(np.float32)
    a = np.concatenate([xy, xy + rng.rand(60, 2).astype(np.float32)], 1)
    b = a[rng.permutation(60)[:40]]
    np.testing.assert_array_equal(np.asarray(_iou_tile(a, b)),
                                  np.asarray(_box_iou_corner(a, b)))
