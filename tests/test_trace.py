"""Distributed tracing (ISSUE 10): span layer, wire propagation,
instrumented loops, and the Perfetto export.

The load-bearing assertions (acceptance):
- a PS client op and the server-side handler it caused share ONE
  trace_id with correct parent/child nesting, across threads
  (in-process) and across PROCESSES (subprocess variant), and the
  merged Chrome JSON contains the flow arrows;
- a concurrent serve request's client span, server handler span and
  the batcher's queue/pad/forward/respond lifecycle all share one
  trace_id;
- tracing enabled adds ZERO blocking host syncs vs disabled
  (profiler.host_sync_count identical);
- disabled mode is a bounded no-op (no spill file, cheap span calls);
- a torn final spill line is tolerated, earlier corruption is not;
- trace_report produces the golden Chrome-JSON shape.
"""
import json
import logging
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import config, io, profiler, telemetry, trace
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.parallel import make_train_step
from mxnet_tpu.parallel.ps_async import AsyncPSClient, AsyncPSServer
from mxnet_tpu.parallel.resilience import (FaultInjector,
                                           install_fault_injector)
from mxnet_tpu.serve import ServeClient, ServeEngine, ServeServer

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from tools import trace_report  # noqa: E402

pytestmark = pytest.mark.trace


@pytest.fixture
def trace_dir(tmp_path):
    """Tracing scoped to this test: fresh spill dir via override,
    tracing stopped + override cleared on exit."""
    trace.stop_tracing()
    d = str(tmp_path / "tr")
    config.set_override("MXNET_TRACE", d)
    yield d
    trace.stop_tracing()
    config.clear_override("MXNET_TRACE")


@pytest.fixture
def no_injector():
    yield
    install_fault_injector(None)


def _spans(path, name=None):
    recs = trace_report.load(path)
    spans = [r for r in recs if r.get("kind") == "span"]
    if name is None:
        return spans
    return [s for s in spans if s["name"] == name]


def _mlp():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=16)
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=2)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _toy(n=96, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d) > 0).astype(np.float32)
    return X, y


class _Echo:
    """Trivial forward-capable serve model (no compile, no jax)."""

    def forward(self, *arrays):
        return [np.asarray(arrays[0]) * 2.0]


# ---------------------------------------------------------------------------
# span layer
# ---------------------------------------------------------------------------

def test_disabled_mode_is_a_bounded_noop(tmp_path):
    """MXNET_TRACE unset: no tracer, no file, no context — and 100k
    span enters/exits stay cheap enough for hot-path call sites."""
    if os.environ.get("MXNET_TRACE"):
        pytest.skip("MXNET_TRACE set in the environment")
    trace.stop_tracing()
    config.clear_override("MXNET_TRACE")
    assert trace.tracer() is None
    assert not trace.enabled()
    assert trace.current_context() is None
    assert trace.wire_context() is None
    assert trace.start_span("x") is None
    trace.end_span(None)                       # tolerated
    trace.instant("x")
    assert trace.add_span("x", 0.0, 1.0) is None
    t0 = time.perf_counter()
    for _ in range(100_000):
        with trace.span("hot"):
            pass
    assert time.perf_counter() - t0 < 2.0      # ~µs/call, huge slack
    assert trace.stop_tracing() is None


def test_span_nesting_ids_and_attrs(trace_dir):
    with trace.span("root", a=1) as root:
        assert trace.current_context().span_id == root.span_id
        with trace.span("child") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
        trace.instant("mark", k=2)
        grand = trace.add_span("retro", telemetry.now_ms() - 5.0,
                               telemetry.now_ms(), parent=root, r=3)
        assert grand.trace_id == root.trace_id
    path = trace.stop_tracing()
    recs = trace_report.load(path)
    assert recs[0]["kind"] == "trace_start"
    assert recs[0]["schema"] == trace.TRACE_SCHEMA_VERSION
    by_name = {r["name"]: r for r in recs[1:]}
    assert by_name["root"]["parent"] is None
    assert by_name["root"]["attrs"] == {"a": 1}
    assert by_name["child"]["parent"] == by_name["root"]["span"]
    assert by_name["retro"]["parent"] == by_name["root"]["span"]
    assert by_name["retro"]["dur_us"] >= 4000
    assert by_name["mark"]["kind"] == "instant"
    # deterministic ids: pid-prefixed counter, no uuid/random
    pid = os.getpid()
    for r in recs[1:]:
        assert r["trace"].startswith("%d." % pid)


def test_thread_isolation(trace_dir):
    """Concurrent root spans on different threads land in DIFFERENT
    traces; nesting never crosses threads."""
    ready = threading.Barrier(2)
    results = {}

    def work(tag):
        with trace.span("root-" + tag) as root:
            ready.wait(5)
            with trace.span("child-" + tag) as child:
                results[tag] = (root.trace_id, child.trace_id,
                                child.parent_id, root.span_id)

    threads = [threading.Thread(target=work, args=(t,))
               for t in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    (ta, ca, pa, ra), (tb, cb, pb, rb) = results["a"], results["b"]
    assert ta == ca and pa == ra
    assert tb == cb and pb == rb
    assert ta != tb


def test_unwind_drops_open_spans(trace_dir):
    sp = trace.start_span("abandoned")
    assert trace.current_context() is not None
    trace.unwind()
    assert trace.current_context() is None
    with trace.span("after"):
        pass
    path = trace.stop_tracing()
    spans = _spans(path)
    assert [s["name"] for s in spans] == ["after"]
    assert spans[0]["parent"] is None
    trace.end_span(sp)                         # tolerated post-unwind


def test_spill_write_failure_disables_with_one_warning(trace_dir,
                                                       caplog):
    class Boom:
        def write(self, *_a):
            raise OSError(28, "No space left on device")

        def flush(self):
            pass

        def close(self):
            pass

    with trace.span("before"):
        pass
    sp = trace.tracer()
    sp._f = Boom()
    with caplog.at_level(logging.WARNING):
        for _ in range(5):
            with trace.span("lost"):
                pass
    warned = [r for r in caplog.records
              if "tracing output disabled" in r.message]
    assert len(warned) == 1
    assert sp._broken


def test_unwritable_destination_disables_with_one_warning(tmp_path,
                                                          caplog):
    """A destination unwritable at STARTUP (lazy auto-start) latches
    tracing off with one warning — never an OSError into the traced
    hot path. An explicit start_tracing() still raises."""
    trace.stop_tracing()
    blocker = tmp_path / "blocked"
    blocker.write_text("a file, not a dir")
    dest = str(blocker / "sub")
    config.set_override("MXNET_TRACE", dest)
    try:
        with caplog.at_level(logging.WARNING):
            for _ in range(3):
                with trace.span("x"):
                    pass
        assert not trace.enabled()
        assert trace.tracer() is None
        warned = [r for r in caplog.records
                  if "tracing disabled" in r.message]
        assert len(warned) == 1
        with pytest.raises(OSError):
            trace.start_tracing(dest)
    finally:
        trace.stop_tracing()
        config.clear_override("MXNET_TRACE")


# ---------------------------------------------------------------------------
# wire propagation: PS (acceptance)
# ---------------------------------------------------------------------------

def test_ps_trace_join_with_retry(trace_dir, no_injector):
    """The fault-injected PS acceptance path, in-process: a dropped
    push replays under retry, and client op span, retry instant,
    backoff span and server handler span all share one trace_id with
    correct parent/child nesting; the export carries flow arrows."""
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=1)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    client = AsyncPSClient("127.0.0.1", srv.port)
    try:
        client.init("w", np.ones(4, np.float32))
        install_fault_injector(FaultInjector("send:drop@1"))
        client.push("w", np.ones(4, np.float32))
        install_fault_injector(None)
        assert np.allclose(client.pull("w"), 1.0)
    finally:
        client.close()
        srv.stop()
    path = trace.stop_tracing()
    recs = trace_report.load(path)
    spans = [r for r in recs if r.get("kind") == "span"]
    push = next(s for s in spans if s["name"] == "ps.op.push")
    handle = next(s for s in spans if s["name"] == "ps.handle.push")
    # one trace across both ends, handler nested under the client op
    assert handle["trace"] == push["trace"]
    assert handle["parent"] == push["span"]
    assert handle["tid"] != push["tid"]
    # the retry is visible in the same trace: instant + backoff span
    retry = next(r for r in recs if r.get("kind") == "instant"
                 and r["name"] == "ps.retry")
    assert retry["trace"] == push["trace"]
    backoff = next(s for s in spans if s["name"] == "retry.backoff")
    assert backoff["trace"] == push["trace"]
    # flow arrows across the thread hop in the merged export
    chrome = trace_report.to_chrome(recs)
    flows = [e for e in chrome["traceEvents"]
             if e.get("ph") in ("s", "f")]
    assert any(e["ph"] == "s" for e in flows)
    assert any(e["ph"] == "f" for e in flows)


@pytest.mark.slow
def test_ps_trace_join_across_processes(tmp_path, trace_dir):
    """Acceptance: a real two-process run — the server writes its own
    spill file, and after merging, ONE trace_id spans both pids with
    the handler span parented under the client op span."""
    srv_dir = str(tmp_path / "srv_trace")
    port_file = str(tmp_path / "port")
    script = (
        "import os\n"
        "os.environ['MXNET_TRACE'] = %r\n"
        "os.environ['MXNET_PS_LINGER'] = '0.1'\n"
        "from mxnet_tpu.parallel.ps_async import AsyncPSServer\n"
        "srv = AsyncPSServer(host='127.0.0.1', port=0, num_workers=1)\n"
        "open(%r, 'w').write(str(srv.port))\n"
        "srv.serve_forever()\n" % (srv_dir, port_file))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-c", script], env=env)
    try:
        deadline = time.time() + 60
        while not os.path.exists(port_file):
            assert proc.poll() is None, "server process died"
            assert time.time() < deadline, "server never bound"
            time.sleep(0.05)
        time.sleep(0.1)
        port = int(open(port_file).read())
        client = AsyncPSClient("127.0.0.1", port)
        client.init("w", np.ones(4, np.float32))
        client.push("w", np.ones(4, np.float32))
        client.close()
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    client_spill = trace.stop_tracing()
    srv_spills = [os.path.join(srv_dir, f) for f in os.listdir(srv_dir)]
    assert len(srv_spills) == 1
    merged = trace_report.merge([client_spill] + srv_spills)
    spans = [r for r in merged if r.get("kind") == "span"]
    push = next(s for s in spans if s["name"] == "ps.op.push")
    handle = next(s for s in spans if s["name"] == "ps.handle.push")
    assert handle["trace"] == push["trace"]
    assert handle["parent"] == push["span"]
    assert handle["pid"] != push["pid"]        # two real processes
    chrome = trace_report.to_chrome(merged)
    pids = {e["pid"] for e in chrome["traceEvents"] if "pid" in e}
    assert len(pids) >= 2
    assert any(e.get("ph") == "f" for e in chrome["traceEvents"])


# ---------------------------------------------------------------------------
# wire propagation: serve (acceptance)
# ---------------------------------------------------------------------------

def test_serve_trace_join_and_lifecycle(trace_dir):
    """A concurrent serve run: client request span, server handler
    span and the batcher's queue -> pad -> forward -> respond
    lifecycle all share one trace_id (the batcher emits across a
    thread hop — flow arrows in the export)."""
    eng = ServeEngine(_Echo(), buckets=(1, 2, 4), max_wait_ms=2.0,
                      feature_shapes=[(4,)], install_sigterm=False)
    srv = ServeServer(eng)
    clients = [ServeClient(srv.host, srv.port) for _ in range(3)]
    try:
        outs = []
        threads = [threading.Thread(
            target=lambda c=c, i=i: outs.append(
                c.request([np.full((1, 4), i, np.float32)])))
            for i, c in enumerate(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(outs) == 3
    finally:
        for c in clients:
            c.close()
        srv.close()
        eng.close()
    path = trace.stop_tracing()
    recs = trace_report.load(path)
    spans = [r for r in recs if r.get("kind") == "span"]
    reqs = [s for s in spans if s["name"] == "serve.request"]
    assert len(reqs) == 3
    for req in reqs:
        mine = [s for s in spans if s["trace"] == req["trace"]]
        names = {s["name"] for s in mine}
        assert {"serve.request", "serve.handle", "serve.queue",
                "serve.pad", "serve.forward",
                "serve.respond"} <= names
        handle = next(s for s in mine if s["name"] == "serve.handle")
        assert handle["parent"] == req["span"]
    chrome = trace_report.to_chrome(recs)
    assert any(e.get("ph") == "f" for e in chrome["traceEvents"])


# ---------------------------------------------------------------------------
# instrumented fit loops (acceptance)
# ---------------------------------------------------------------------------

def test_trainstep_fit_spans_cross_reference_journal(trace_dir,
                                                     tmp_path):
    """train.step spans carry the journal's step seq, so a trace and a
    telemetry report of the same run cross-reference; wait children
    reconstruct the step's data/window breakdown."""
    telemetry.close_journal()
    config.set_override("MXNET_TELEMETRY", str(tmp_path / "tele"))
    try:
        X, y = _toy()
        step = make_train_step(_mlp())
        train = io.NDArrayIter(X, y, batch_size=32)
        step.fit(train, num_epoch=1, initializer=Xavier(), lr=0.1)
        jpath = telemetry.close_journal()
    finally:
        config.clear_override("MXNET_TELEMETRY")
    path = trace.stop_tracing()
    steps = _spans(path, "train.step")
    assert len(steps) == 3
    journal_steps = {r["step"] for r in
                     (json.loads(ln) for ln in open(jpath))
                     if r.get("kind") == "step"}
    for s in steps:
        assert s["attrs"]["loop"] == "trainstep"
        assert s["attrs"]["step"] in journal_steps
        kids = [k for k in _spans(path)
                if k.get("parent") == s["span"]]
        assert {"step.data_wait", "step.window_wait"} <= \
            {k["name"] for k in kids}


def test_module_fit_spans(trace_dir):
    X, y = _toy()
    train = io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    path = trace.stop_tracing()
    steps = _spans(path, "train.step")
    assert len(steps) == 3
    assert all(s["attrs"]["loop"] == "module" for s in steps)
    # prepare()'s staging rides the step span too
    stages = _spans(path, "module.stage")
    assert stages
    step_ids = {s["span"] for s in steps}
    assert any(s["parent"] in step_ids for s in stages)


def test_trace_adds_zero_host_syncs(trace_dir):
    """Acceptance: tracing on vs off — the instrumented epoch performs
    the IDENTICAL number of blocking host syncs (tracing is host wall
    clock + file appends only)."""
    X, y = _toy()
    step = make_train_step(_mlp())
    train = io.NDArrayIter(X, y, batch_size=32)
    # warm while tracing is ON (fixture): compiles included
    state, _ = step.fit(train, num_epoch=1, initializer=Xavier(),
                        lr=0.1)
    base = profiler.host_sync_count()
    state, _ = step.fit(train, num_epoch=1, state=state, lr=0.1)
    syncs_on = profiler.host_sync_count() - base

    trace.stop_tracing()
    config.clear_override("MXNET_TRACE")
    base = profiler.host_sync_count()
    state, _ = step.fit(train, num_epoch=1, state=state, lr=0.1)
    syncs_off = profiler.host_sync_count() - base
    assert syncs_on == syncs_off, (syncs_on, syncs_off)


def test_guardrail_masked_step_instant(trace_dir, no_injector):
    """A nan@N-injected masked step annotates the trace with an
    instant event inside the run's spans."""
    X, y = _toy()
    install_fault_injector(FaultInjector("nan@2"))
    step = make_train_step(_mlp())
    train = io.NDArrayIter(X, y, batch_size=32)
    step.fit(train, num_epoch=1, initializer=Xavier(), lr=0.5)
    install_fault_injector(None)
    path = trace.stop_tracing()
    recs = trace_report.load(path)
    marks = [r for r in recs if r.get("kind") == "instant"
             and r["name"] == "guardrail.masked_step"]
    assert marks
    assert marks[0]["attrs"]["total"] >= 1
    # a mark whose flag drained inside a step's window wait parents to
    # that step's trace; one drained at the epoch-end flush is a root
    # annotation (trace None) — both are valid placements
    step_traces = {s["trace"] for s in recs
                   if s.get("kind") == "span"
                   and s["name"] == "train.step"}
    for m in marks:
        assert m["trace"] is None or m["trace"] in step_traces


# ---------------------------------------------------------------------------
# spill format + report (golden shape)
# ---------------------------------------------------------------------------

def test_torn_spill_line_tolerated(trace_dir):
    with trace.span("a"):
        pass
    path = trace.stop_tracing()
    n = len(trace_report.load(path))
    with open(path, "a") as f:
        f.write('{"v": 1, "kind": "sp')       # crash signature
    assert len(trace_report.load(path)) == n
    # corruption anywhere earlier is NOT tolerated
    bad = path + ".bad"
    lines = open(path).read().splitlines()
    lines[0] = "not json"
    with open(bad, "w") as f:
        f.write("\n".join(lines))
    with pytest.raises(ValueError, match="corrupt"):
        trace_report.load(bad)
    # unknown schema refused
    v2 = path + ".v2"
    with open(v2, "w") as f:
        f.write('{"v": 99, "kind": "trace_start"}\n')
    with pytest.raises(ValueError, match="schema"):
        trace_report.load(v2)


def test_trace_report_golden_shape(trace_dir):
    with trace.span("root", a=1):
        with trace.span("inner"):
            pass
        trace.instant("blip")
    path = trace.stop_tracing()
    recs = trace_report.load(path)
    chrome = trace_report.to_chrome(recs)
    assert set(chrome) == {"traceEvents", "displayTimeUnit"}
    evs = chrome["traceEvents"]
    phs = {e["ph"] for e in evs}
    assert {"M", "X", "i"} <= phs
    xs = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(xs) == {"root", "inner"}
    for e in xs.values():
        assert {"ts", "dur", "pid", "tid", "args"} <= set(e)
        assert "trace" in e["args"] and "span" in e["args"]
    assert xs["root"]["args"]["a"] == 1
    # same-thread nesting draws NO flow arrow
    assert not [e for e in evs if e["ph"] in ("s", "f")]
    names = [e for e in evs if e["ph"] == "M"]
    assert {"process_name", "thread_name"} <= {e["name"] for e in names}
    summary = trace_report.critical_path(recs)
    assert "root" in summary and "inner" in summary
    assert "% of root" in summary
