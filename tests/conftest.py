"""Test env: run everything on a virtual 8-device CPU mesh so sharding
semantics (kvstore/parallel tests) are exercised without TPU hardware
(SURVEY.md §4: multi-process-on-one-host is the reference's distributed-test
pattern; virtual devices are the JAX analogue).

Set MXNET_TEST_ON_TPU=1 to run the suite against the real chip instead.

Gotcha this file works around: the image presets JAX_PLATFORMS=axon and a
pytest-registered plugin may import jax BEFORE this conftest, locking the
env value in — so setting os.environ here is NOT enough. jax.config.update
works post-import (as long as no backend has been initialized yet, which
is true until the first test runs). Without this, "CPU" tests silently run
over the axon TPU tunnel and hang for ~25 min when the tunnel is down.
"""
import os

if not os.environ.get("MXNET_TEST_ON_TPU"):
    # for child processes / late importers
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    # authoritative override even if jax was already imported
    import jax
    jax.config.update("jax_platforms", "cpu")
