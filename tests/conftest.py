"""Test env: run everything on a virtual 8-device CPU mesh so sharding
semantics (kvstore/parallel tests) are exercised without TPU hardware
(SURVEY.md §4: multi-process-on-one-host is the reference's distributed-test
pattern; virtual devices are the JAX analogue)."""
import os

# Hard override: the image presets JAX_PLATFORMS=axon (the one real TPU
# chip); tests must run on the virtual CPU mesh for determinism + sharding.
# Set MXNET_TEST_ON_TPU=1 to run the suite against the real chip instead.
if not os.environ.get("MXNET_TEST_ON_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
