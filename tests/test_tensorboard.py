"""TensorBoard bridge tests: the hand-rolled event-file writer must
produce files that TENSORBOARD'S OWN reader parses back exactly
(tags, steps, values), and the callback must plug into Module.fit.
Reference: python/mxnet/contrib/tensorboard.py.
"""
import glob
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib.tensorboard import (LogMetricsCallback,
                                           SummaryWriter)


def _load_events(logdir):
    loader_mod = pytest.importorskip(
        "tensorboard.backend.event_processing.event_file_loader")
    files = sorted(glob.glob(os.path.join(logdir, "events.out.*")))
    assert files, "no event files written"
    events = []
    for f in files:
        events.extend(loader_mod.EventFileLoader(f).Load())
    return events


def _value(v):
    """tensorboard's loader migrates simple_value into a rank-0 tensor
    proto (data_compat); accept either representation."""
    if v.HasField("tensor"):
        return v.tensor.float_val[0]
    return v.simple_value


def test_scalar_roundtrip_through_tensorboard_reader(tmp_path):
    logdir = str(tmp_path / "logs")
    with SummaryWriter(logdir) as w:
        w.add_scalar("loss", 1.5, global_step=1)
        w.add_scalar("loss", 0.75, global_step=2)
        w.add_scalar("acc/top1", 0.5, global_step=2)

    events = _load_events(logdir)
    assert events[0].file_version == "brain.Event:2"
    scalars = [(v.tag, e.step, _value(v))
               for e in events for v in e.summary.value]
    assert scalars == [("loss", 1, 1.5), ("loss", 2, 0.75),
                       ("acc/top1", 2, 0.5)]
    for e in events:
        assert e.wall_time > 1e9      # real timestamps


def test_log_metrics_callback(tmp_path):
    logdir = str(tmp_path / "logs")
    cb = LogMetricsCallback(logdir, prefix="train")
    metric = mx.metric.create("acc")
    metric.update([mx.nd.array([0, 1])],
                  [mx.nd.array([[0.9, 0.1], [0.2, 0.8]])])
    param = mx.model.BatchEndParam(epoch=0, nbatch=1,
                                   eval_metric=metric, locals=None)
    cb(param)
    cb(param)
    cb.close()

    scalars = [(v.tag, e.step, _value(v))
               for e in _load_events(logdir) for v in e.summary.value]
    assert [s[0] for s in scalars] == ["train/accuracy"] * 2
    assert [s[1] for s in scalars] == [1, 2]
    np.testing.assert_allclose([s[2] for s in scalars], [1.0, 1.0])


def test_callback_in_module_fit(tmp_path):
    """The bridge rides Module.fit's batch_end_callback seam unchanged
    (reference usage pattern)."""
    logdir = str(tmp_path / "fit_logs")
    X = np.random.RandomState(0).randn(64, 8).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), num_hidden=2, name="fc"),
        name="softmax")
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(net, context=mx.cpu())
    cb = LogMetricsCallback(logdir)
    mod.fit(it, num_epoch=2, optimizer="sgd",
            batch_end_callback=cb)
    cb.close()
    scalars = [(v.tag, e.step) for e in _load_events(logdir)
               for v in e.summary.value]
    assert len(scalars) == 8          # 4 batches x 2 epochs
    assert all(tag == "accuracy" for tag, _ in scalars)
