"""Parallel/SPMD tests — the TPU analogue of the reference's
tests/nightly/dist_sync_kvstore.py + multi_lenet.py (multi-process on one
host → virtual 8-device CPU mesh here)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.parallel import (make_mesh, data_parallel_mesh,
                                make_train_step)


def _mlp():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=32)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=2)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _toy(n=64, d=16, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    w = rng.standard_normal(d)
    y = (X @ w > 0).astype(np.float32)
    return X, y


def _train(step, state, X, y, lr=0.5, epochs=30):
    rng = jax.random.PRNGKey(0)
    batch = step.place_batch({"data": X, "softmax_label": y})
    for _ in range(epochs):
        state, outs = step(state, batch, lr, rng)
    return state, outs


def _acc(outs, y):
    pred = np.asarray(outs[0]).argmax(axis=1)
    return (pred == y).mean()


def test_train_step_single_device_converges():
    X, y = _toy()
    step = make_train_step(_mlp(), optimizer="sgd",
                           optimizer_params={"momentum": 0.9,
                                             "rescale_grad": 1.0 / 64})
    state = step.init_state(Xavier(), {"data": X.shape,
                                       "softmax_label": y.shape})
    state, outs = _train(step, state, X, y)
    assert _acc(outs, y) > 0.95


def test_train_step_dp_mesh_matches_single():
    """Data-parallel mesh step computes the same updates as single-device
    (grad all-reduce inserted by GSPMD must be exact)."""
    X, y = _toy()

    def run(mesh):
        step = make_train_step(_mlp(), optimizer="sgd",
                               optimizer_params={"rescale_grad": 1.0 / 64},
                               mesh=mesh)
        mx.random.seed(7)
        np.random.seed(7)
        state = step.init_state(Xavier(), {"data": X.shape,
                                           "softmax_label": y.shape})
        state, _ = _train(step, state, X, y, epochs=5)
        return {k: np.asarray(v) for k, v in state[0].items()}

    p_single = run(None)
    p_mesh = run(data_parallel_mesh())
    for k in p_single:
        np.testing.assert_allclose(p_single[k], p_mesh[k], rtol=2e-4,
                                   atol=1e-5, err_msg=k)


def test_train_step_dp_tp_mesh():
    """2-D (data × model) mesh: tensor-parallel shardings compile and
    converge (free capability vs the reference, SURVEY.md §2.3 TP row)."""
    X, y = _toy()
    mesh = make_mesh({"data": 4, "model": 2})
    step = make_train_step(_mlp(), optimizer="sgd",
                           optimizer_params={"momentum": 0.9,
                                             "rescale_grad": 1.0 / 64},
                           mesh=mesh)
    state = step.init_state(Xavier(), {"data": X.shape,
                                       "softmax_label": y.shape})
    # fc1 weight (32,16) must actually be sharded over 'model'
    shard = state[0]["fc1_weight"].sharding
    assert "model" in str(shard.spec), shard
    state, outs = _train(step, state, X, y)
    assert _acc(outs, y) > 0.95


def test_aux_state_threading_on_mesh():
    """BatchNorm moving stats update inside the sharded step."""
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=8)
    net = mx.sym.BatchNorm(net, name="bn", fix_gamma=False)
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=2)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    X, y = _toy(n=32, d=8)
    step = make_train_step(net, mesh=data_parallel_mesh())
    state = step.init_state(Xavier(), {"data": X.shape,
                                       "softmax_label": y.shape})
    before = np.asarray(state[2]["bn_moving_mean"]).copy()
    state, _ = _train(step, state, X, y, epochs=3)
    after = np.asarray(state[2]["bn_moving_mean"])
    assert not np.allclose(before, after)


def test_dist_rank_size_single_process():
    from mxnet_tpu.parallel import dist
    assert dist.rank() == 0
    assert dist.size() == 1


def test_train_step_bf16_compute_dtype():
    """Mixed precision: bf16 fwd/bwd, f32 master weights + BN stats —
    still converges on the toy problem (the mp_sgd semantics)."""
    X, y = _toy()
    step = make_train_step(_mlp(), optimizer="sgd",
                           optimizer_params={"momentum": 0.9,
                                             "rescale_grad": 1.0 / 64},
                           compute_dtype="bfloat16")
    state = step.init_state(Xavier(), {"data": X.shape,
                                       "softmax_label": y.shape})
    state, outs = _train(step, state, X, y)
    params = state[0]
    assert all(v.dtype == np.float32 for v in params.values())
    assert np.asarray(outs[0]).dtype == jnp.bfloat16
    assert _acc(outs, y) > 0.9


def test_train_step_remat_matches_plain():
    """Gradient mirroring (MXNET_BACKWARD_DO_MIRROR parity): remat'd
    backward computes identical gradients/updates."""
    X, y = _toy()
    kwargs = dict(optimizer="sgd",
                  optimizer_params={"rescale_grad": 1.0 / 64})
    plain = make_train_step(_mlp(), **kwargs)
    remat = make_train_step(_mlp(), remat=True, **kwargs)
    state_p = plain.init_state(Xavier(), {"data": X.shape,
                                          "softmax_label": y.shape})
    # identical initial params; real copies (the step donates buffers)
    state_r = jax.tree.map(jnp.copy, state_p)
    rng = jax.random.PRNGKey(0)
    bp = plain.place_batch({"data": X, "softmax_label": y})
    state_p, outs_p = plain(state_p, bp, 0.1, rng)
    state_r, outs_r = remat(state_r, bp, 0.1, rng)
    np.testing.assert_allclose(np.asarray(outs_p[0]),
                               np.asarray(outs_r[0]), rtol=1e-6)
    for k in state_p[0]:
        np.testing.assert_allclose(np.asarray(state_p[0][k]),
                                   np.asarray(state_r[0][k]),
                                   rtol=1e-5, atol=1e-6)


def test_train_step_zero1_matches_replicated():
    """ZeRO-1 (optimizer state sharded 1/N over 'data', reduce-scatter →
    sharded update → all-gather) computes the same trajectory as the
    replicated update — the server-side-optimizer capability of the
    reference's update_on_kvstore path (kvstore_dist_server.h:109-433)."""
    X, y = _toy()
    mesh = data_parallel_mesh()
    ndev = mesh.shape["data"]
    kwargs = dict(optimizer="adam",
                  optimizer_params={"rescale_grad": 1.0 / 64}, mesh=mesh)
    rep = make_train_step(_mlp(), **kwargs)
    z1 = make_train_step(_mlp(), optimizer_sharding="zero1", **kwargs)
    state_r = rep.init_state(Xavier(), {"data": X.shape,
                                        "softmax_label": y.shape})
    state_z = jax.tree.map(jnp.copy, state_r)
    # re-place the copied opt state with the zero1 shardings
    state_z = (state_z[0],
               {k: tuple(z1._place_opt(k, s) for s in v)
                for k, v in state_z[1].items()}, state_z[2])

    # optimizer state memory really is 1/N per device for shardable params
    m_shard = state_z[1]["fc1_weight"][0].sharding
    local = m_shard.shard_shape(state_z[1]["fc1_weight"][0].shape)
    assert np.prod(local) * ndev == np.prod(
        state_z[1]["fc1_weight"][0].shape), (local, ndev)

    rng = jax.random.PRNGKey(0)
    br = rep.place_batch({"data": X, "softmax_label": y})
    bz = z1.place_batch({"data": X, "softmax_label": y})
    for _ in range(5):
        state_r, outs_r = rep(state_r, br, 0.05, rng)
        state_z, outs_z = z1(state_z, bz, 0.05, rng)
    for k in state_r[0]:
        np.testing.assert_allclose(np.asarray(state_r[0][k]),
                                   np.asarray(state_z[0][k]),
                                   rtol=2e-5, atol=1e-6, err_msg=k)
    # updated params come back fully addressable (all-gathered layout)
    for k, v in state_z[0].items():
        assert "data" not in str(v.sharding.spec), (k, v.sharding)
    # persistent opt state stays in the 1/N layout across steps
    m_after = state_z[1]["fc1_weight"][0]
    assert "data" in str(m_after.sharding.spec), m_after.sharding


def test_train_step_clip_norm():
    """clip_norm bounds the effective (rescaled) global gradient norm:
    one clipped SGD step equals the manual scale-then-update oracle,
    and a huge threshold is a no-op."""
    X, y = _toy()
    B = X.shape[0]
    clip = 0.05   # small enough to certainly engage on step 1
    base = dict(optimizer="sgd",
                optimizer_params={"rescale_grad": 1.0 / B})
    plain = make_train_step(_mlp(), **base)
    clipped = make_train_step(_mlp(), clip_norm=clip, **base)
    loose = make_train_step(_mlp(), clip_norm=1e9, **base)

    state0 = plain.init_state(Xavier(), {"data": X.shape,
                                         "softmax_label": y.shape})
    rng = jax.random.PRNGKey(0)
    batch = plain.place_batch({"data": X, "softmax_label": y})
    lr = 0.5

    s_plain, _ = plain(jax.tree.map(jnp.copy, state0), batch, lr, rng)
    s_clip, _ = clipped(jax.tree.map(jnp.copy, state0), batch, lr, rng)
    s_loose, _ = loose(jax.tree.map(jnp.copy, state0), batch, lr, rng)

    # raw per-param updates recover the rescaled grads; compute the
    # oracle clip factor from them
    g = {k: (np.asarray(state0[0][k]) - np.asarray(s_plain[0][k])) / lr
         for k in state0[0]}
    gnorm = np.sqrt(sum((v.astype(np.float64) ** 2).sum()
                        for v in g.values()))
    assert gnorm > clip   # the test must actually engage the clip
    factor = clip / gnorm
    for k in state0[0]:
        want = np.asarray(state0[0][k]) - lr * factor * g[k]
        np.testing.assert_allclose(np.asarray(s_clip[0][k]), want,
                                   rtol=2e-5, atol=1e-6, err_msg=k)
        np.testing.assert_allclose(np.asarray(s_loose[0][k]),
                                   np.asarray(s_plain[0][k]),
                                   rtol=1e-6, atol=1e-7, err_msg=k)

    with pytest.raises(ValueError, match="clip_norm"):
        make_train_step(_mlp(), clip_norm=0.0, **base)


def test_zero1_requires_data_axis():
    with pytest.raises(ValueError):
        make_train_step(_mlp(), optimizer_sharding="zero1")
    with pytest.raises(ValueError):
        make_train_step(_mlp(), optimizer_sharding="bogus")


def test_bf16_compute_keeps_embedding_ids_exact():
    """compute_dtype must not cast Embedding-fed token ids: bf16 aliases
    ids >= 256, which would silently corrupt every LM batch."""
    vocab, T, B = 1000, 4, 4
    data = mx.sym.Variable("data")
    emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=8,
                           name="embed")
    pred = mx.sym.FullyConnected(mx.sym.Flatten(emb), num_hidden=vocab,
                                 name="out")
    net = mx.sym.SoftmaxOutput(pred, name="softmax")
    step = make_train_step(net, compute_dtype="bfloat16")
    assert step._id_inputs == {"data"}
    state = step.init_state(Xavier(), {"data": (B, T),
                                       "softmax_label": (B,)})
    # distinct high ids that all collapse to 896/1024-ish under bf16
    toks = np.array([[899, 901, 903, 905]] * B, np.float32)
    labels = np.zeros((B,), np.float32)
    # snapshot before the step: the jitted step donates param buffers
    snap = {k: np.asarray(v).astype(np.float32)
            for k, v in state[0].items()}
    batch = step.place_batch({"data": toks, "softmax_label": labels})
    state, outs = step(state, batch, 0.0, jax.random.PRNGKey(0))
    # lr=0: recompute the expected forward from the UNTOUCHED ids and
    # exact f32 embedding rows; if ids had been cast to bf16 the rows
    # for 899/901/903/905 would all be the row of 896
    rows = snap["embed_weight"][toks.astype(int)]
    assert not np.allclose(rows[0, 0], rows[0, 1]), "test ids degenerate"
    got = np.asarray(outs[0]).astype(np.float32)
    w = snap["out_weight"]
    b = snap["out_bias"]
    logits = rows.reshape(B, -1).astype(np.float32) @ w.T + b
    want = np.exp(logits - logits.max(1, keepdims=True))
    want /= want.sum(1, keepdims=True)
    # bf16 compute in the matmul: loose tolerance, but id aliasing would
    # produce a completely different distribution (wrong rows)
    np.testing.assert_allclose(got, want, rtol=0.1, atol=0.02)


def test_train_step_save_load_state_roundtrip():
    """SPMD checkpoint/resume: save under zero1 on a mesh, restore into
    (a) the same setup and (b) a mesh-less replicated step — training
    trajectories must continue identically."""
    import os
    import tempfile

    X, y = _toy()
    mesh = data_parallel_mesh()
    kwargs = dict(optimizer="adam",
                  optimizer_params={"rescale_grad": 1.0 / 64})
    z1 = make_train_step(_mlp(), mesh=mesh, optimizer_sharding="zero1",
                         **kwargs)
    state = z1.init_state(Xavier(), {"data": X.shape,
                                     "softmax_label": y.shape})
    rng = jax.random.PRNGKey(0)
    b = z1.place_batch({"data": X, "softmax_label": y})
    for _ in range(3):
        state, _ = z1(state, b, 0.05, rng)

    prefix = os.path.join(tempfile.mkdtemp(), "ckpt")
    # snapshot the post-save trajectory before donation eats the state
    path = z1.save_state(prefix, state)
    ref_state = z1.load_state(prefix)
    ref_state, ref_outs = z1(ref_state, b, 0.05, rng)

    # (a) same mesh/sharding resume
    re_state = z1.load_state(prefix)
    m = re_state[1]["fc1_weight"][0]
    assert "data" in str(m.sharding.spec), m.sharding   # zero1 restored
    re_state, re_outs = z1(re_state, b, 0.05, rng)
    np.testing.assert_allclose(np.asarray(re_outs[0]),
                               np.asarray(ref_outs[0]), rtol=1e-6)

    # (b) restore onto NO mesh (single chip) — same numbers
    single = make_train_step(_mlp(), **kwargs)
    s_state = single.load_state(prefix)
    bs = single.place_batch({"data": X, "softmax_label": y})
    s_state, s_outs = single(s_state, bs, 0.05, rng)
    np.testing.assert_allclose(np.asarray(s_outs[0]),
                               np.asarray(ref_outs[0]), rtol=2e-5,
                               atol=1e-6)

    # incompatible checkpoints fail loudly — BOTH directions: fewer
    # saved slots than needed (sgd ckpt -> adam) and more (adam ckpt ->
    # sgd, which would silently install adam's m as sgd momentum)
    sgd = make_train_step(_mlp(), optimizer="sgd")
    sgd_state = sgd.init_state(Xavier(), {"data": X.shape,
                                          "softmax_label": y.shape})
    sgd_prefix = prefix + "_sgd"
    sgd.save_state(sgd_prefix, sgd_state)
    adam = make_train_step(_mlp(), **kwargs)
    with pytest.raises(ValueError, match="optimizer slots"):
        adam.load_state(sgd_prefix)
    with pytest.raises(ValueError, match="optimizer slots"):
        sgd.load_state(prefix)
    # ...and a different model's checkpoint is rejected at load time
    other = make_train_step(mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), name="zzz", num_hidden=2),
        name="softmax"), **kwargs)
    with pytest.raises(ValueError, match="params"):
        other.load_state(prefix)


def test_train_step_fit_loop_and_resume(tmp_path):
    """TrainStep.fit: Module.fit UX on the SPMD path — trains to the
    accuracy gate, checkpoints per epoch, and a 'crashed' rerun resumes
    from the latest checkpoint instead of restarting."""
    from mxnet_tpu import io

    X, y = _toy(n=96)
    prefix = str(tmp_path / "ck")

    def make():
        train = io.NDArrayIter(X, y, batch_size=32, shuffle=True)
        step = make_train_step(_mlp(), optimizer="sgd",
                               optimizer_params={"momentum": 0.9,
                                                 "rescale_grad": 1.0 / 32},
                               mesh=data_parallel_mesh())
        return step, train

    seen = []
    step, train = make()
    state, acc = step.fit(
        train, num_epoch=12, initializer=Xavier(), lr=0.5,
        checkpoint_prefix=prefix,
        epoch_end_callback=lambda e, s: seen.append(e))
    assert acc > 0.95, acc
    assert seen == list(range(12))
    import glob
    assert len(glob.glob(prefix + "_*.npz")) == 12

    # rerun the same command: must resume AFTER epoch 11, not retrain —
    # and the update counter continues (scheduler/rng don't replay)
    lrs_seen = []
    step2, train2 = make()
    resumed = []
    state2, acc2 = step2.fit(
        train2, num_epoch=14, initializer=Xavier(), lr=0.5,
        lr_scheduler=lambda n: lrs_seen.append(n) or 0.5,
        checkpoint_prefix=prefix,
        epoch_end_callback=lambda e, s: resumed.append(e))
    assert resumed == [12, 13], resumed
    assert acc2 > 0.95
    assert lrs_seen[0] == 12 * 3, lrs_seen[:3]   # 3 batches/epoch

    # a third run with nothing left is a no-op, not a NaN metric
    step3, train3 = make()
    state3, acc3 = step3.fit(train3, num_epoch=14,
                             initializer=Xavier(),
                             checkpoint_prefix=prefix)
    assert acc3 is None and state3 is not None

    # stray non-epoch files next to the checkpoints don't break resume
    open(prefix + "_final.npz", "wb").close()
    step4, train4 = make()
    state4, _ = step4.fit(train4, num_epoch=15, initializer=Xavier(),
                          lr=0.5, checkpoint_prefix=prefix)
    assert state4 is not None


def test_train_step_export_compiled_roundtrip(tmp_path):
    """TrainStep.export -> CompiledTrainStep (round 5, the AOT
    training boundary behind the MXTpuTrain* C ABI): the exported
    program must (a) train — loss drops over compiled steps with no
    framework graph code involved, (b) expose trained params by name,
    (c) round-trip its state through save_state, and (d) track the
    in-process TrainStep trajectory exactly given the same seeds."""
    import numpy as np

    from mxnet_tpu.parallel.trainer import CompiledTrainStep

    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(mx.sym.Activation(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                              name="fc1"), act_type="relu"),
        num_hidden=2, name="fc2"), name="softmax")
    step = make_train_step(net, optimizer="adam",
                           optimizer_params={"rescale_grad": 1.0 / 32})
    state = step.init_state(Xavier(), {"data": (32, 8),
                                       "softmax_label": (32,)})
    rng = np.random.RandomState(0)
    X = rng.standard_normal((32, 8)).astype(np.float32)
    y = (X @ rng.standard_normal(8) > 0).astype(np.float32)
    batch = step.place_batch({"data": X, "softmax_label": y})
    prefix = str(tmp_path / "m")
    step.export(prefix, state, batch)

    def xent(outs):
        p = np.asarray(outs[0])
        return -np.log(p[np.arange(32), y.astype(int)] + 1e-9).mean()

    ct = CompiledTrainStep.load(prefix)
    assert ct.batch_names == ["data", "softmax_label"]
    first = last = None
    for i in range(40):
        outs = ct.step({"data": X, "softmax_label": y}, lr=1e-2)
        if i == 0:
            first = xent(outs)
    last = xent(outs)
    assert last < first * 0.5, (first, last)

    # (d) exact trajectory match vs the in-process step, same seeds
    import jax
    st = state
    for i in range(40):
        st, _ = step(st, batch, 1e-2, jax.random.PRNGKey(i))
    want = np.asarray(jax.device_get(st[0]["fc1_weight"]))
    got = ct.get_params()["fc1_weight"]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    # (c) state round-trip: reload continues from the trained state
    ct.save_state(str(tmp_path / "m"))     # overwrites m.state.npz
    ct2 = CompiledTrainStep.load(prefix)
    np.testing.assert_allclose(ct2.get_params()["fc1_weight"], got)

    # shape validation is loud
    try:
        ct.step({"data": X[:8], "softmax_label": y[:8]}, lr=1e-2)
        raise AssertionError("expected shape error")
    except ValueError as e:
        assert "shape" in str(e)


def test_train_step_resume_skips_torn_checkpoint(tmp_path):
    """Crash-resume robustness (docs/robustness.md): save_state
    publishes atomically (write-aside + rename), and fit's resume scan
    falls back past a torn newest checkpoint instead of crashing the
    restarted worker. Model-mismatch errors still fail loudly."""
    from mxnet_tpu import io

    X, y = _toy(n=96)
    prefix = str(tmp_path / "ck")

    def make():
        train = io.NDArrayIter(X, y, batch_size=32, shuffle=True)
        step = make_train_step(_mlp(), optimizer="sgd")
        return step, train

    step, train = make()
    step.fit(train, num_epoch=2, initializer=Xavier(), lr=0.5,
             checkpoint_prefix=prefix)
    # simulate a crash mid-save predating the atomic rename: a torn
    # .npz as the NEWEST checkpoint
    with open(prefix + "_0002.npz", "wb") as f:
        f.write(b"PK\x03\x04torn")
    resumed = []
    step2, train2 = make()
    step2.fit(train2, num_epoch=4, initializer=Xavier(), lr=0.5,
              checkpoint_prefix=prefix,
              epoch_end_callback=lambda e, s: resumed.append(e))
    # fell back to ck_0001 (epoch 1 done) -> trained epochs 2 and 3;
    # the torn 0002 was overwritten by a good one along the way
    assert resumed == [2, 3], resumed
    step3, _ = make()
    state3 = step3.load_state(prefix + "_0002")
    assert state3 is not None
