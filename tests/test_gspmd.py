"""One-jit GSPMD path (ISSUE 11): SpecLayout partition-spec registry
over a forced 8-device ``data × fsdp`` CPU mesh (conftest.py's
xla_force_host_platform_device_count).

The load-bearing acceptance assertions:
- a one-jit GSPMD ``TrainStep.fit`` epoch matches the single-device
  baseline numerically (rtol 2e-4 / atol 1e-5 — the same float
  reduction-order tolerance the plain DP-mesh parity test uses: the
  math is identical, the summation orders are not);
- each device holds a 1/N shard of the optimizer state
  (N = data × fsdp = 8);
- the blocking-host-sync counter stays ≤ 1 per step under GSPMD
  (the test_hotloop.py budget, unchanged by sharding);
- rule precedence / auto rule / describe(), and every layout
  validation failure is a raised ValueError, never an assert.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import io, profiler
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.parallel import make_mesh, make_train_step, SpecLayout
from mxnet_tpu.parallel.sharding import parse_spec

pytestmark = pytest.mark.gspmd


def _mlp(classes=8):
    """All param shapes divisible by 8 so every optimizer-state tensor
    can hold the full 1/N fold (fc1: (32,16)+(32,), fc2: (8,32)+(8,))."""
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=32)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=classes)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _toy(n=64, d=16, classes=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = rng.integers(0, classes, n).astype(np.float32)
    return X, y


def _dxf_mesh():
    return make_mesh({"data": 2, "fsdp": 4})


def _layout(mesh=None, **kw):
    kw.setdefault("min_shard_size", 0)   # toy tensors are tiny
    return SpecLayout(mesh or _dxf_mesh(), **kw)


# ---------------------------------------------------------------------------
# make_mesh / layout validation: ValueError, never assert
# ---------------------------------------------------------------------------

def test_make_mesh_mismatch_raises_valueerror():
    with pytest.raises(ValueError) as e:
        make_mesh({"data": 3, "fsdp": 4})
    msg = str(e.value)
    assert "3" in msg and "4" in msg and "8" in msg  # sizes AND count


def test_make_mesh_infers_one_axis_and_validates_inference():
    mesh = make_mesh({"data": 2, "fsdp": -1})
    assert dict(mesh.shape) == {"data": 2, "fsdp": 4}
    with pytest.raises(ValueError, match="does not divide"):
        make_mesh({"data": 3, "fsdp": -1})
    with pytest.raises(ValueError, match="at most one"):
        make_mesh({"data": -1, "fsdp": -1})
    with pytest.raises(ValueError, match="positive"):
        make_mesh({"data": 0, "fsdp": 8})


def test_speclayout_rejects_unknown_axis_and_bad_rules():
    mesh = _dxf_mesh()
    with pytest.raises(ValueError, match="not a mesh axis"):
        SpecLayout(mesh, rules=[("*", P("tp"))])
    with pytest.raises(ValueError, match="more than one dim"):
        SpecLayout(mesh, rules=[("*", P("fsdp", "fsdp"))])
    # an explicit rule that cannot apply fails LOUDLY at placement
    lay = SpecLayout(mesh, rules=[("w", P("fsdp"))], min_shard_size=0)
    with pytest.raises(ValueError, match="not divisible"):
        lay.param_nsharding("w", (6,))
    lay2 = SpecLayout(mesh, rules=[("b", P("fsdp", None))],
                      min_shard_size=0)
    with pytest.raises(ValueError, match="more dims"):
        lay2.param_nsharding("b", (32,))


# ---------------------------------------------------------------------------
# rule precedence / auto rule / describe
# ---------------------------------------------------------------------------

def test_rule_precedence_first_match_wins_and_auto_fallback():
    mesh = _dxf_mesh()
    lay = SpecLayout(mesh, rules=[
        ("fc1_weight", P(None, "fsdp")),      # exact, first
        ("fc1_*", P("fsdp", None)),           # glob, shadowed for fc1_weight
    ], min_shard_size=0)
    parts, label = lay.spec_for("fc1_weight", (32, 16))
    assert parts == (None, "fsdp") and "rule[0]" in label
    parts, label = lay.spec_for("fc1_gamma", (32, 16))
    assert parts == ("fsdp", None) and "rule[1]" in label
    # auto: LARGEST divisible dim over fsdp
    parts, label = lay.spec_for("other_weight", (8, 32))
    assert parts == (None, "fsdp") and label.startswith("auto")
    # nothing divisible -> replicated
    parts, label = lay.spec_for("odd", (6, 3))
    assert parts == (None, None) and "replicated" in label


def test_auto_rule_min_size_replicates_tiny_tensors():
    lay = SpecLayout(_dxf_mesh(), min_shard_size=1024)
    parts, label = lay.spec_for("small_bias", (32,))     # 32 < 1024
    assert parts == (None,) and "replicated" in label
    parts, _ = lay.spec_for("big_weight", (64, 64))      # 4096 >= 1024
    assert parts == ("fsdp", None) or parts == (None, "fsdp")


def test_describe_reports_claims_and_unused_rules():
    lay = SpecLayout(_dxf_mesh(), rules=[
        ("fc1_weight", P("fsdp", None)),
        ("never_matches_*", P("fsdp")),
    ], min_shard_size=0)
    lay.param_nsharding("fc1_weight", (32, 16))
    lay.param_nsharding("fc2_bias", (8,))
    rep = lay.describe()
    assert "fc1_weight" in rep and "rule[0]" in rep
    assert "8x16" in rep                   # per-device shard of (32,16)
    assert "fc2_bias" in rep and "auto" in rep
    assert "rule[1]" in rep and "matched no parameter" in rep


def test_parse_spec_grammar():
    assert parse_spec("fsdp,None") == ("fsdp", None)
    assert parse_spec("data+fsdp,None") == (("data", "fsdp"), None)
    assert parse_spec(P("fsdp", None)) == ("fsdp", None)
    assert parse_spec([("data", "fsdp"), None]) == (("data", "fsdp"),
                                                    None)
    assert parse_spec("None") == (None,)


# ---------------------------------------------------------------------------
# the one-jit step: parity, opt-state shards, sync budget
# ---------------------------------------------------------------------------

def _make_step(layout=None, **kw):
    kw.setdefault("optimizer", "adam")
    kw.setdefault("optimizer_params", {"rescale_grad": 1.0 / 32})
    return make_train_step(_mlp(), layout=layout, **kw)


def test_gspmd_fit_epoch_matches_single_device():
    """Acceptance: a full TrainStep.fit epoch on the data×fsdp layout
    (sharded params, folded optimizer state, activation constraints)
    lands on the same weights as the single-device fit. Tolerance
    rtol=2e-4/atol=1e-5: identical math, different float reduction
    order across the 8 shards."""
    X, y = _toy()

    def run(layout, sharding):
        mx.random.seed(11)
        np.random.seed(11)
        step = _make_step(layout=layout, optimizer_sharding=sharding)
        train = io.NDArrayIter(X, y, batch_size=32)
        state, acc = step.fit(train, num_epoch=3, initializer=Xavier(),
                              lr=0.05, seed=3)
        return {k: np.asarray(v) for k, v in state[0].items()}, acc

    p_single, _ = run(None, None)
    p_gspmd, _ = run(_layout(), "zero1")
    for k in p_single:
        np.testing.assert_allclose(p_gspmd[k], p_single[k], rtol=2e-4,
                                   atol=1e-5, err_msg=k)


def test_gspmd_opt_state_is_one_nth_per_device():
    """Acceptance: every optimizer-state tensor lives 1/N per device
    (N = data × fsdp = 8), and STAYS in that layout across donated
    steps (no GSPMD output-propagation drift, no step-2 recompile)."""
    mesh = _dxf_mesh()
    ndev = mesh.size
    step = _make_step(layout=_layout(mesh), optimizer_sharding="zero1")
    X, y = _toy()
    state = step.init_state(Xavier(), {"data": X.shape,
                                       "softmax_label": y.shape})

    def check(state):
        for name, states in state[1].items():
            for s in states:
                local = s.sharding.shard_shape(s.shape)
                assert np.prod(local) * ndev == np.prod(s.shape), \
                    (name, s.shape, local)

    check(state)
    b = step.place_batch({"data": X, "softmax_label": y})
    rng = jax.random.PRNGKey(0)
    for _ in range(3):
        state, outs = step(state, b, 0.05, rng)
    check(state)   # donated buffers kept their shardings
    # fresh params come back in the PARAM layout (all-gathered off the
    # zero fold), not stuck in the 1/N optimizer slice
    for k, v in state[0].items():
        parts, _ = step._layout.spec_for(k, v.shape)
        got = tuple(v.sharding.spec)
        got += (None,) * (v.ndim - len(got))   # P() drops trailing Nones
        assert got == tuple(parts), (k, v.sharding)


def test_gspmd_batch_and_activations_ride_the_data_axes():
    """The batch shards over data×fsdp (all 8 devices see distinct
    rows — fsdp is data parallelism, not replication) and the step's
    outputs stay batch-sharded (the module-boundary constraints keep
    GSPMD propagation on the data axes)."""
    step = _make_step(layout=_layout(), optimizer_sharding="zero1")
    X, y = _toy()
    b = step.place_batch({"data": X, "softmax_label": y})
    spec = b["data"].sharding.spec
    assert tuple(spec)[0] == ("data", "fsdp"), spec
    state = step.init_state(Xavier(), {"data": X.shape,
                                       "softmax_label": y.shape})
    state, outs = step(state, b, 0.05, jax.random.PRNGKey(0))
    out_spec = tuple(outs[0].sharding.spec)
    assert out_spec and out_spec[0] == ("data", "fsdp"), out_spec


def test_gspmd_fit_sync_budget_per_step():
    """Acceptance: ≤1 blocking host sync per step preserved under
    GSPMD — sharding must not reintroduce per-step device→host reads
    (same budget as test_hotloop.py: the window wait, +1 epoch-end
    metric read)."""
    X, y = _toy()
    step = _make_step(layout=_layout(), optimizer_sharding="zero1")
    train = io.NDArrayIter(X, y, batch_size=32)   # 2 steps/epoch
    # warm epoch: compiles + init (not the measured regime)
    state, _ = step.fit(train, num_epoch=1, initializer=Xavier(),
                        lr=0.05)
    n_steps = 2
    base = profiler.host_sync_count()
    state, _ = step.fit(train, num_epoch=1, state=state, lr=0.05)
    syncs = profiler.host_sync_count() - base
    assert syncs <= n_steps + 1, \
        "GSPMD epoch did %d blocking syncs for %d steps" \
        % (syncs, n_steps)


def test_zero1_requires_replica_axis_on_tp_only_layout():
    mesh = make_mesh({"tp": 8})
    lay = SpecLayout(mesh)
    with pytest.raises(ValueError, match="replica axis"):
        _make_step(layout=lay, optimizer_sharding="zero1")


def test_layout_and_mesh_are_mutually_exclusive():
    with pytest.raises(ValueError, match="not both"):
        make_train_step(_mlp(), mesh=make_mesh({"data": 8}),
                        layout=_layout())


def test_gspmd_checkpoint_roundtrip_across_layouts(tmp_path):
    """A checkpoint written under the data×fsdp zero1 layout restores
    onto a single-device step (and back) and continues the identical
    trajectory — save gathers, load re-places per the loading step's
    own layout."""
    X, y = _toy()
    g = _make_step(layout=_layout(), optimizer_sharding="zero1")
    state = g.init_state(Xavier(), {"data": X.shape,
                                    "softmax_label": y.shape})
    b = g.place_batch({"data": X, "softmax_label": y})
    rng = jax.random.PRNGKey(0)
    for _ in range(2):
        state, _ = g(state, b, 0.05, rng)
    prefix = str(tmp_path / "ck")
    g.save_state(prefix, state)

    ref = g.load_state(prefix)
    ref, ref_outs = g(ref, b, 0.05, rng)

    single = _make_step()
    s_state = single.load_state(prefix)
    bs = single.place_batch({"data": X, "softmax_label": y})
    s_state, s_outs = single(s_state, bs, 0.05, rng)
    np.testing.assert_allclose(np.asarray(s_outs[0]),
                               np.asarray(ref_outs[0]), rtol=2e-5,
                               atol=1e-6)


def test_gspmd_aux_stays_replicated_no_step2_recompile():
    """BN moving stats were placed replicated by init_state but came
    back sharded over fsdp via GSPMD propagation — the drifted layout
    missed the jit cache and every SpecLayout run paid a full step-2
    recompile (caught by review on the bench_scaling GSPMD row: 1590 ms
    headline vs 100 ms telemetry p50). The step must pin aux back to
    the replicated layout, and the executable must be compiled ONCE."""
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=32)
    net = mx.sym.BatchNorm(net, name="bn", fix_gamma=False)
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=8)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    step = make_train_step(net, optimizer="adam",
                           optimizer_params={"rescale_grad": 1.0 / 64},
                           layout=_layout(), optimizer_sharding="zero1")
    X, y = _toy()
    state = step.init_state(Xavier(), {"data": X.shape,
                                       "softmax_label": y.shape})
    b = step.place_batch({"data": X, "softmax_label": y})
    rng = jax.random.PRNGKey(0)
    for _ in range(3):
        state, _ = step(state, b, 0.05, rng)
        for k, v in state[2].items():
            assert tuple(v.sharding.spec) == (), (k, v.sharding)
    if hasattr(step._jit_step, "_cache_size"):
        assert step._jit_step._cache_size() == 1   # one executable


# ---------------------------------------------------------------------------
# the Module path binds the same layout
# ---------------------------------------------------------------------------

def test_module_accepts_layout_and_shards_params():
    """Module/executor_group bind through the same placement layer:
    params live per the layout's rules, batches shard over data×fsdp,
    and training still converges on the toy problem."""
    rng = np.random.default_rng(0)
    X = rng.standard_normal((96, 16)).astype(np.float32)
    y = (X @ rng.standard_normal(16) > 0).astype(np.float32)  # separable
    lay = _layout()
    mod = mx.mod.Module(_mlp(classes=2), context=mx.cpu(), layout=lay)
    train = io.NDArrayIter(X, y, batch_size=32)
    mod.fit(train, num_epoch=8, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9})
    w = mod._exec_group.execs[0].arg_dict["fc1_weight"]._data
    local = w.sharding.shard_shape(w.shape)
    assert np.prod(local) < np.prod(w.shape), w.sharding  # really sharded
    assert dict(mod.score(train, "acc"))["accuracy"] > 0.9


def test_module_layout_batch_must_divide_shards():
    from mxnet_tpu.base import MXNetError
    X, y = _toy(n=30, classes=2)
    mod = mx.mod.Module(_mlp(classes=2), context=mx.cpu(),
                        layout=_layout())
    with pytest.raises(MXNetError, match="divisible"):
        mod.bind([("data", (30, 16))], [("softmax_label", (30,))])


# ---------------------------------------------------------------------------
# telemetry + constraint knob
# ---------------------------------------------------------------------------

def test_layout_bind_telemetry_gauges():
    from mxnet_tpu import telemetry
    step = _make_step(layout=_layout(), optimizer_sharding="zero1")
    X, y = _toy()
    state = step.init_state(Xavier(), {"data": X.shape,
                                       "softmax_label": y.shape})
    assert telemetry.gauge("gspmd.sharded_params").value >= 1
    opt_bytes = telemetry.gauge("gspmd.opt_state_bytes_per_dev").value
    want = sum(int(np.prod(s.sharding.shard_shape(s.shape)))
               * s.dtype.itemsize
               for states in state[1].values() for s in states)
    assert opt_bytes == want


def test_constrain_acts_knob_off_still_trains():
    from mxnet_tpu import config as cfg
    assert cfg.get("MXNET_GSPMD_CONSTRAIN_ACTS") is True
    lay = SpecLayout(_dxf_mesh(), min_shard_size=0,
                     constrain_activations=False)
    assert lay.act_parts(2) is None
    step = _make_step(layout=lay, optimizer_sharding="zero1")
    X, y = _toy()
    state = step.init_state(Xavier(), {"data": X.shape,
                                       "softmax_label": y.shape})
    b = step.place_batch({"data": X, "softmax_label": y})
    state, outs = step(state, b, 0.05, jax.random.PRNGKey(0))
    assert np.isfinite(np.asarray(outs[0])).all()
