"""Real-data convergence gate — closes the "trains on seeded clusters"
vs "trains on data" gap (VERDICT r3 weakness #6). The reference anchors
this with MNIST in tests/python/train/test_mlp.py (Module.fit to >0.96
val accuracy); MNIST bytes are unreachable in this zero-egress image,
so the fixture is the real scanned handwritten-digit set that ships
inside scikit-learn (UCI optdigits: 1797 8x8 images, 10 classes),
committed as tests/fixtures/digits_8x8.npz so the test itself needs
only numpy. Same shape of claim: a genuine image-classification
dataset, a Module.fit training loop, an accuracy threshold.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io
from mxnet_tpu.initializer import Xavier

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "digits_8x8.npz")


def _load_split():
    with np.load(FIXTURE) as z:
        X = z["images"].astype(np.float32) / 16.0   # (1797, 8, 8)
        y = z["labels"].astype(np.float32)
    # deterministic interleaved split: 4/5 train, 1/5 held out
    idx = np.arange(len(y))
    test = idx % 5 == 0
    return (X[~test][:, None], y[~test]), (X[test][:, None], y[test])


def _lenet_sym():
    """Conv net sized for 8x8 inputs — the reference's LeNet gate
    shrunk to the fixture (example/image-classification/symbols)."""
    net = mx.sym.Variable("data")
    net = mx.sym.Convolution(net, name="conv1", kernel=(3, 3),
                             num_filter=16, pad=(1, 1))
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2),
                         stride=(2, 2))
    net = mx.sym.Convolution(net, name="conv2", kernel=(3, 3),
                             num_filter=32, pad=(1, 1))
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2),
                         stride=(2, 2))
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=64)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=10)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_module_fit_real_digits():
    """Module.fit on real images must reach >0.98 train accuracy and
    >0.95 held-out accuracy — the reference's test_mlp.py gate shape
    (it asserts MNIST val accuracy from a fit() run)."""
    (Xtr, ytr), (Xte, yte) = _load_split()
    mx.random.seed(0)
    np.random.seed(0)
    train = io.NDArrayIter(Xtr, ytr, batch_size=64, shuffle=True)
    val = io.NDArrayIter(Xte, yte, batch_size=64)
    mod = mx.mod.Module(_lenet_sym(), context=mx.cpu())
    # conv nets need fan-in-scaled init (the reference's conv examples
    # all pass Xavier/MSRA for the same reason); the fit() default
    # Uniform(0.01) keeps this net at chance for many epochs
    mod.fit(train, num_epoch=12, optimizer="sgd",
            initializer=Xavier(),
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1.0 / 64})
    train_acc = mod.score(train, "acc")
    val_acc = mod.score(val, "acc")
    acc_of = lambda s: s[0][1] if isinstance(s, list) else float(s)
    tr, va = acc_of(train_acc), acc_of(val_acc)
    assert tr > 0.98, "train accuracy gate failed: %.4f" % tr
    assert va > 0.95, "held-out accuracy gate failed: %.4f" % va


def test_real_digits_fixture_integrity():
    """The fixture stays what it claims to be: 1797 real 8x8 images,
    10 roughly-balanced classes, intensity range 0..16."""
    with np.load(FIXTURE) as z:
        X, y = z["images"], z["labels"]
    assert X.shape == (1797, 8, 8) and y.shape == (1797,)
    assert X.dtype == np.uint8 and X.max() == 16
    counts = np.bincount(y, minlength=10)
    assert counts.min() > 150 and counts.max() < 200
