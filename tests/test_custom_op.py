"""Custom-op infrastructure tests (reference:
tests/python/unittest/test_operator.py test_custom_op and the
example/numpy-ops softmax CustomOp).

Note: runs on the CPU backend — the dev-environment axon TPU plugin does
not implement host callbacks (real TPU PJRT does).
"""
import numpy as np
import pytest

import mxnet_tpu as mx


class _Softmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = np.exp(x - x.max(axis=1).reshape((x.shape[0], 1)))
        y /= y.sum(axis=1).reshape((x.shape[0], 1))
        self.assign(out_data[0], req[0], mx.nd.array(y))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        lbl = in_data[1].asnumpy().ravel().astype(np.int64)
        y = out_data[0].asnumpy()
        y[np.arange(lbl.shape[0]), lbl] -= 1.0
        self.assign(in_grad[0], req[0], mx.nd.array(y / y.shape[0]))


@mx.operator.register("test_softmax")
class _SoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def infer_shape(self, in_shape):
        return ([in_shape[0], (in_shape[0][0],)], [in_shape[0]], [])

    def create_operator(self, ctx, shapes, dtypes):
        return _Softmax()


class _Scale(mx.operator.CustomOp):
    def __init__(self, factor):
        self.factor = factor

    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], in_data[0] * self.factor)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0], out_grad[0] * self.factor)


@mx.operator.register("test_scale")
class _ScaleProp(mx.operator.CustomOpProp):
    def __init__(self, factor="2.0"):
        super().__init__(need_top_grad=True)
        self.factor = float(factor)

    def create_operator(self, ctx, shapes, dtypes):
        return _Scale(self.factor)


class TestEager:
    def test_forward(self):
        x = mx.nd.array(np.random.randn(4, 3).astype("float32"))
        lbl = mx.nd.array(np.array([0, 1, 2, 0], "float32"))
        y = mx.nd.Custom(x, lbl, op_type="test_softmax")
        np.testing.assert_allclose(y.asnumpy().sum(1), np.ones(4),
                                   rtol=1e-5)

    def test_backward(self):
        x = mx.nd.array(np.random.randn(4, 3).astype("float32"))
        lbl = mx.nd.array(np.array([0, 1, 2, 0], "float32"))
        x.attach_grad()
        with mx.autograd.record():
            y = mx.nd.Custom(x, lbl, op_type="test_softmax")
        y.backward()
        g = x.grad.asnumpy()
        np.testing.assert_allclose(g.sum(1), np.zeros(4), atol=1e-6)

    def test_top_grad_chain(self):
        """need_top_grad=True op composes with downstream jax-native ops."""
        x = mx.nd.array(np.random.randn(5).astype("float32"))
        x.attach_grad()
        with mx.autograd.record():
            y = mx.nd.Custom(x, op_type="test_scale", factor="3.0")
            z = (y * y).sum()
        z.backward()
        np.testing.assert_allclose(x.grad.asnumpy(),
                                   2 * 9 * x.asnumpy(), rtol=1e-5)

    def test_kwargs_reordering(self):
        x = mx.nd.array(np.random.randn(4, 3).astype("float32"))
        lbl = mx.nd.array(np.array([0, 1, 2, 0], "float32"))
        a = mx.nd.Custom(label=lbl, data=x, op_type="test_softmax")
        b = mx.nd.Custom(x, lbl, op_type="test_softmax")
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy())


class TestSymbolic:
    def test_infer_shape_fills_label(self):
        data = mx.sym.Variable("data")
        net = mx.sym.Custom(data=data, name="sm", op_type="test_softmax")
        assert net.list_arguments() == ["data", "sm_label"]
        args, outs, _ = net.infer_shape(data=(4, 3))
        assert args == [(4, 3), (4,)]
        assert outs == [(4, 3)]

    def test_positional_compose_auto_creates_label(self):
        net = mx.sym.Custom(mx.sym.Variable("data"), name="sm",
                            op_type="test_softmax")
        assert net.list_arguments() == ["data", "sm_label"]

    def test_executor_forward(self):
        data = mx.sym.Variable("data")
        net = mx.sym.Custom(data=data, name="sm", op_type="test_softmax")
        ex = net.simple_bind(data=(4, 3))
        x = np.random.randn(4, 3).astype("float32")
        out = ex.forward(data=x, sm_label=np.zeros(4, "float32"))
        np.testing.assert_allclose(out[0].asnumpy().sum(1), np.ones(4),
                                   rtol=1e-5)

    def test_module_training(self):
        """Custom softmax as the head of a Module-trained MLP: loss-driven
        accuracy must beat chance (VERDICT #8 done criterion)."""
        np.random.seed(0)
        mx.random.seed(0)
        N = 128
        X = np.random.randn(N, 8).astype("float32")
        w = np.random.randn(8)
        ylab = (X @ w > 0).astype("float32")

        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
        net = mx.sym.Custom(data=fc, name="softmax",
                            op_type="test_softmax")
        train = mx.io.NDArrayIter(X, ylab, batch_size=32, shuffle=True,
                                  label_name="softmax_label")
        mod = mx.mod.Module(net, ("data",), ("softmax_label",))
        mod.fit(train, num_epoch=6, optimizer="sgd",
                optimizer_params={"learning_rate": 0.5})
        score = mod.score(train, "acc")[0][1]
        assert score > 0.9, score


class TestRegistry:
    def test_unknown_type_raises(self):
        with pytest.raises(KeyError):
            mx.nd.Custom(mx.nd.zeros((2,)), op_type="no_such_op")

    def test_listing(self):
        assert "test_softmax" in mx.operator.get_all_registered()

    def test_aux_states_rejected(self):
        @mx.operator.register("test_auxful")
        class _AuxProp(mx.operator.CustomOpProp):
            def list_auxiliary_states(self):
                return ["counter"]

            def infer_shape(self, in_shape):
                return [in_shape[0]], [in_shape[0]], [(1,)]

        with pytest.raises(NotImplementedError):
            mx.nd.Custom(mx.nd.zeros((2,)), op_type="test_auxful")


def test_host_callback_failure_is_actionable(monkeypatch):
    """Remote/tunneled backends (axon) cannot run pure_callback; the
    executor must rewrite the runtime's bare UNIMPLEMENTED into an
    error naming the cause and the fix. Guarded structurally
    (graph-contains-Custom + UNIMPLEMENTED) so a backend rewording
    the message does not silently lose the rewrite — simulated here
    by making the jitted call raise the reworded form."""
    data = mx.sym.Variable("data")
    net = mx.sym.Custom(data, op_type="test_scale",
                        factor="2.0", name="sc")
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 3),
                          grad_req="null")

    def boom(*a, **k):
        raise RuntimeError(
            "UNIMPLEMENTED: Send/recv callbacks not supported")

    monkeypatch.setattr(exe, "_jit_fwd", boom)
    with pytest.raises(RuntimeError, match="host-attached backend"):
        exe.forward(is_train=False,
                    data=mx.nd.zeros((2, 3)))
