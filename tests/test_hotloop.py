"""Pipelined training hot loop: device-resident metrics, prefetch
placement, bounded async dispatch, and the blocking-host-sync budget.

The load-bearing assertions (ISSUE 4 acceptance):
- device metric accumulation equals the host metric within 1e-5;
- an instrumented fit epoch performs at most ONE blocking host sync
  per step (asserted on the CPU backend via the profiler's
  always-on counter);
- metrics without a device impl fall back to the host path unchanged.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io, metric, profiler
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.parallel import make_train_step


def _mlp():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=32)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=2)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _toy(n=96, d=16, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d) > 0).astype(np.float32)
    return X, y


# ---------------------------------------------------------------------------
# device-metric parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,kwargs", [
    ("acc", {}),
    ("ce", {}),
    ("mse", {}),
    ("mae", {}),
    ("rmse", {}),
    ("nll_loss", {}),
    ("top_k_accuracy", {"top_k": 3}),
    ("perplexity", {"ignore_label": 1}),
])
def test_device_metric_parity(name, kwargs):
    """Device accumulation equals the host metric within 1e-5 over
    several batches (acceptance gate names acc/ce/mse; the rest ride
    the same contract)."""
    rng = np.random.RandomState(7)
    host = metric.create(name, **kwargs)
    dev = metric.create(name, **kwargs)
    assert dev.supports_device_update
    for _ in range(5):
        if name in ("mse", "mae", "rmse"):
            label = rng.randn(16).astype(np.float32)
            pred = rng.randn(16).astype(np.float32)
        else:
            pred = rng.rand(16, 10).astype(np.float32) + 1e-3
            pred /= pred.sum(1, keepdims=True)
            label = rng.randint(0, 10, 16).astype(np.float32)
        host.update([mx.nd.array(label)], [mx.nd.array(pred)])
        dev.update_device([mx.nd.array(label)], [mx.nd.array(pred)])
    hv, dv = host.get()[1], dev.get()[1]
    assert abs(hv - dv) <= 1e-5 * max(1.0, abs(hv)), (name, hv, dv)


def test_device_metric_composite_and_fallback():
    """Composite fans out per child; a metric without a device impl
    (F1) transparently falls back to the host path — update_device is
    always safe to call."""
    pred = mx.nd.array([[0.2, 0.8], [0.8, 0.2], [0.3, 0.7]])
    label = mx.nd.array([1, 0, 0])

    f1h, f1d = metric.create("f1"), metric.create("f1")
    assert not f1d.supports_device_update
    f1h.update([label], [pred])
    f1d.update_device([label], [pred])        # falls back, same value
    assert f1h.get()[1] == f1d.get()[1]

    comp = metric.create(["acc", "ce"])
    assert comp.supports_device_update
    comp.update_device([label], [pred])
    names, values = comp.get()
    assert names == ["accuracy", "cross-entropy"]
    assert abs(values[0] - 2.0 / 3) < 1e-6

    mixed = metric.create(["acc", "f1"])      # one child host-only
    assert not mixed.supports_device_update
    mixed.update_device([label], [pred])      # still accumulates both
    assert abs(mixed.get()[1][0] - 2.0 / 3) < 1e-6


def test_device_metric_single_host_read():
    """update_device never blocks on the host; get() is the single
    blocking read (profiler's always-on sync counter)."""
    m = metric.create("acc")
    pred = mx.nd.array(np.random.RandomState(0).rand(8, 4))
    label = mx.nd.array(np.zeros(8))
    base = profiler.host_sync_count()
    for _ in range(10):
        m.update_device([label], [pred])
    assert profiler.host_sync_count() == base   # no per-update sync
    m.get()
    assert profiler.host_sync_count() == base + 1


# ---------------------------------------------------------------------------
# pipelined TrainStep.fit
# ---------------------------------------------------------------------------

def test_trainstep_fit_sync_budget_per_step():
    """One instrumented epoch of TrainStep.fit performs at most one
    blocking host sync per step: the bounded-dispatch-window wait.
    (+1 for the epoch-end metric read.)"""
    X, y = _toy()
    step = make_train_step(_mlp(), optimizer="sgd",
                           optimizer_params={"rescale_grad": 1.0 / 32})
    train = io.NDArrayIter(X, y, batch_size=32)   # 3 steps/epoch
    # warm epoch: compiles + init (not the measured regime)
    state, _ = step.fit(train, num_epoch=1, initializer=Xavier(), lr=0.1)
    n_steps = 3
    base = profiler.host_sync_count()
    state, acc = step.fit(train, num_epoch=1, initializer=Xavier(),
                          lr=0.1, state=state)
    syncs = profiler.host_sync_count() - base
    assert syncs <= n_steps + 1, \
        "pipelined epoch did %d blocking syncs for %d steps" \
        % (syncs, n_steps)


def test_trainstep_fit_fused_metric_matches_host_path():
    """Same data, same seeds: the fused on-device metric reports the
    same value as the host metric path within 1e-5."""
    X, y = _toy()

    def run(fuse):
        mx.random.seed(11)
        np.random.seed(11)
        step = make_train_step(
            _mlp(), optimizer="sgd",
            optimizer_params={"momentum": 0.9, "rescale_grad": 1.0 / 32})
        train = io.NDArrayIter(X, y, batch_size=32)
        _, acc = step.fit(train, num_epoch=4, initializer=Xavier(),
                          lr=0.5, seed=3, fuse_metric=fuse)
        return acc

    fused, host = run(True), run(False)
    assert abs(fused - host) <= 1e-5, (fused, host)
    assert fused > 0.9


def test_trainstep_fit_composite_fused_and_callbacks():
    """Composite metrics fuse too, and mid-epoch get() (Speedometer
    pattern) sees live values."""
    X, y = _toy()
    step = make_train_step(_mlp(), optimizer="sgd",
                           optimizer_params={"rescale_grad": 1.0 / 32})
    train = io.NDArrayIter(X, y, batch_size=32)
    seen = []

    def cb(param):
        names, values = param.eval_metric.get()
        seen.append((param.nbatch, names, values))

    step.fit(train, num_epoch=2, initializer=Xavier(), lr=0.5,
             eval_metric=["acc", "ce"], batch_end_callback=cb)
    assert len(seen) == 6
    assert seen[-1][1] == ["accuracy", "cross-entropy"]
    assert all(np.isfinite(v) for v in seen[-1][2])


def test_prefetching_iter_place_fn_stage():
    """PrefetchingIter's device-prefetch stage: batches arrive with
    .placed feeds (assembled off the hot loop) and fit consumes them."""
    X, y = _toy()
    step = make_train_step(_mlp(), optimizer="sgd",
                           optimizer_params={"rescale_grad": 1.0 / 32})
    pf = io.PrefetchingIter(io.NDArrayIter(X, y, batch_size=32),
                            place_fn=step.make_placer())
    batch = next(pf)
    assert set(batch.placed) == {"data", "softmax_label"}
    np.testing.assert_allclose(np.asarray(batch.placed["data"]),
                               batch.data[0].asnumpy())
    pf.reset()
    _, acc = step.fit(pf, num_epoch=6, initializer=Xavier(), lr=0.5)
    assert acc > 0.9


def test_prefetching_iter_worker_error_surfaces():
    """A place_fn failure propagates to the consumer instead of
    starving the queue — including a leaked StopIteration, which must
    NOT be misread as epoch end (silent early truncation)."""
    def boom(_batch):
        raise RuntimeError("placement exploded")

    X, y = _toy(n=32)
    pf = io.PrefetchingIter(io.NDArrayIter(X, y, batch_size=32),
                            place_fn=boom)
    with pytest.raises(RuntimeError, match="placement exploded"):
        next(pf)

    def leaky(_batch):
        raise StopIteration("bug in placement")

    pf2 = io.PrefetchingIter(io.NDArrayIter(X, y, batch_size=32),
                             place_fn=leaky)
    with pytest.raises(StopIteration, match="bug in placement"):
        pf2.iter_next()


def test_trainstep_fit_donate_false_keeps_caller_state():
    """TrainStep(donate=False) must hold for the fused metric step too:
    the state the caller passed in stays readable after fit."""
    X, y = _toy()
    step = make_train_step(_mlp(), optimizer="sgd", donate=False,
                           optimizer_params={"rescale_grad": 1.0 / 32})
    state0 = step.init_state(Xavier(), {"data": X.shape,
                                        "softmax_label": y.shape})
    before = np.asarray(state0[0]["fc1_weight"]).copy()
    train = io.NDArrayIter(X, y, batch_size=32)
    state1, _ = step.fit(train, num_epoch=1, state=state0, lr=0.5)
    # donate=False: the original buffers are intact, not deleted
    np.testing.assert_allclose(np.asarray(state0[0]["fc1_weight"]),
                               before)
    assert not np.allclose(np.asarray(state1[0]["fc1_weight"]), before)


def test_dispatch_ahead_window_is_bounded():
    """dispatch_ahead=1 degenerates to synchronous stepping (one wait
    per step) and still trains; the knob also reads the env default."""
    from mxnet_tpu import config as cfg
    assert cfg.get("MXNET_DISPATCH_AHEAD") == 2
    X, y = _toy()
    step = make_train_step(_mlp(), optimizer="sgd",
                           optimizer_params={"momentum": 0.9,
                                             "rescale_grad": 1.0 / 32})
    train = io.NDArrayIter(X, y, batch_size=32)
    _, acc = step.fit(train, num_epoch=10, initializer=Xavier(), lr=0.5,
                      dispatch_ahead=1)
    assert acc > 0.9


# ---------------------------------------------------------------------------
# pipelined Module.fit
# ---------------------------------------------------------------------------

def test_module_fit_sync_budget_and_staging():
    """Module.fit's hot loop: batch t+1 staged while step t runs, the
    device metric path removes per-batch metric reads — at most one
    blocking sync per step (the window wait), plus the epoch-end
    reads."""
    X, y = _toy()
    train = io.NDArrayIter(X, y, batch_size=32)   # 3 steps/epoch
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    # warm epoch (bind/init/compile)
    mod.fit(train, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9})
    n_steps, budget = 3, 3 + 2    # 1/step window + epoch-end metric/param
    base = profiler.host_sync_count()
    mod._fit_epoch(train, 1, metric.create("acc"), None, None)
    syncs = profiler.host_sync_count() - base
    assert syncs <= budget, \
        "module epoch did %d blocking syncs for %d steps" \
        % (syncs, n_steps)
    # and the full fit (incl. staging via prepare) still converges
    mod.fit(train, num_epoch=8, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            force_init=True, force_rebind=True)
    assert dict(mod.score(train, "acc"))["accuracy"] > 0.9


def test_module_score_device_metric_matches_host():
    """score() routes metrics through the device accumulator; a
    host-only CustomMetric on the same outputs agrees within 1e-5."""
    X, y = _toy()
    train = io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})

    def np_acc(label, pred):
        return float((pred.argmax(1) == label.astype(int)).sum()), \
            label.size

    host = dict(mod.score(train, metric.np(np_acc, name="host_acc")))
    dev = dict(mod.score(train, "acc"))
    assert abs(host["host_acc"] - dev["accuracy"]) <= 1e-5


# ---------------------------------------------------------------------------
# profiler plumbing
# ---------------------------------------------------------------------------

def test_profiler_step_markers_and_sync_events(tmp_path):
    """step_scope emits host timeline events (and StepTraceAnnotation
    on device traces); counted syncs appear as events while running."""
    import json
    out = str(tmp_path / "steps.json")
    profiler.profiler_set_config(mode="all", filename=out)
    profiler.profiler_set_state("run")
    try:
        with profiler.step_scope(7):
            mx.nd.ones((4,)).asnumpy()     # a counted blocking read
    finally:
        profiler.profiler_set_state("stop")
    trace = json.load(open(profiler.dump_profile()))
    names = [e["name"] for e in trace["traceEvents"]]
    assert "train_step#7" in names
    assert any(n.startswith("host_sync:") for n in names)
    cats = {e["cat"] for e in trace["traceEvents"]}
    assert "step" in cats and "sync" in cats


def test_compile_cache_knob_wires_jax_config(tmp_path):
    """MXNET_COMPILE_CACHE points JAX's persistent compilation cache at
    the given directory (warm restarts skip recompiles). Checked in a
    subprocess so the import-time wiring actually runs."""
    cache = str(tmp_path / "xla_cache")
    env = dict(os.environ, MXNET_COMPILE_CACHE=cache,
               JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    code = ("import jax, mxnet_tpu; "
            "assert jax.config.jax_compilation_cache_dir == %r, "
            "jax.config.jax_compilation_cache_dir; "
            "print('wired')" % cache)
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "wired" in out.stdout


# ---------------------------------------------------------------------------
# bench_bn env hygiene (satellite)
# ---------------------------------------------------------------------------

def test_bench_bn_does_not_leak_bn_impl_env():
    import jax.numpy as jnp
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmark"))
    import bench_bn
    prev = os.environ.pop("MXNET_BN_IMPL", None)
    try:
        x = jnp.ones((2, 3, 4, 4), jnp.float32)
        bench_bn.framework_bn(x, jnp.ones(3), jnp.zeros(3))
        assert "MXNET_BN_IMPL" not in os.environ
        os.environ["MXNET_BN_IMPL"] = "sentinel"
        bench_bn.framework_bn(x, jnp.ones(3), jnp.zeros(3))
        assert os.environ["MXNET_BN_IMPL"] == "sentinel"
    finally:
        os.environ.pop("MXNET_BN_IMPL", None)
        if prev is not None:
            os.environ["MXNET_BN_IMPL"] = prev
