"""SSM / gated linear-attention duality (mxnet_tpu/ops/ssm.py,
models/transformer.py block_type="ssm", ISSUE 19 tentpole).

Load-bearing acceptance gate: the CPU-deterministic parity grid —
the chunked-scan training/prefill form and the fused recurrent decode
form are the SAME recurrence, so a width-1 chunk is BITWISE the jitted
recurrent step (output and exit state) and every other chunk width
agrees to 1e-5. That bit-identical-state rule is what lets serving
hand a state blob from prefill to decode (and between replicas) with
no drift; tests/test_serve_ssm.py pins the serving half.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.generation import Generator, kv_blob_nbytes
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.models import transformer
from mxnet_tpu.ops.ssm import ssm_chunk_scan, ssm_recurrent_step
from mxnet_tpu.parallel import make_train_step

B_, H_, T_, D_ = 2, 3, 13, 8


def _inputs(seed=0, T=T_):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B_, H_, T, D_), jnp.float32)
    k = jnp.asarray(rng.randn(B_, H_, T, D_), jnp.float32)
    v = jnp.asarray(rng.randn(B_, H_, T, D_), jnp.float32)
    g = jnp.asarray(rng.randn(B_, H_, T), jnp.float32)
    return q, k, v, g


def _recurrent_chain(q, k, v, g, state=None):
    """Token-by-token fused decode over a T-long sequence, each step
    through jax.jit — the exact condition serving runs the step under
    (the bit-identical guarantee is stated under jit: eager dispatch
    skips XLA's fused multiply-adds and can differ in the last ulp)."""
    T = q.shape[2]
    if state is None:
        state = jnp.zeros((q.shape[0], q.shape[1], q.shape[3],
                           q.shape[3]), jnp.float32)
    step = jax.jit(ssm_recurrent_step)
    outs = []
    for t in range(T):
        o, state = step(q[:, :, t:t + 1], k[:, :, t:t + 1],
                        v[:, :, t:t + 1], g[:, :, t:t + 1], state)
        outs.append(o)
    return jnp.concatenate(outs, axis=2), state


class TestParityGrid:
    def test_width1_chunk_is_bitwise_the_recurrent_step(self):
        """ACCEPTANCE: chunk=1 scan == jitted fused step chain, bit
        for bit, in both the outputs and the exit state — the handoff
        contract itself."""
        q, k, v, g = _inputs()
        out_s, st_s = jax.jit(
            lambda *a: ssm_chunk_scan(*a, chunk=1))(q, k, v, g)
        out_r, st_r = _recurrent_chain(q, k, v, g)
        np.testing.assert_array_equal(np.asarray(out_s),
                                      np.asarray(out_r))
        np.testing.assert_array_equal(np.asarray(st_s),
                                      np.asarray(st_r))

    @pytest.mark.parametrize("W", [2, 3, 4, 8, 13, 64])
    def test_chunk_width_grid_vs_recurrent(self, W):
        """Every chunk width (dividing, non-dividing, padded past T)
        computes the same math as the fused recurrent form to 1e-5 —
        width changes the MXU/scan split, never the result."""
        q, k, v, g = _inputs(seed=W)
        out_c, st_c = ssm_chunk_scan(q, k, v, g, chunk=W)
        out_r, st_r = _recurrent_chain(q, k, v, g)
        np.testing.assert_allclose(np.asarray(out_c),
                                   np.asarray(out_r),
                                   rtol=0, atol=1e-5)
        np.testing.assert_allclose(np.asarray(st_c), np.asarray(st_r),
                                   rtol=0, atol=1e-5)

    def test_carried_state_continuation(self):
        """Scanning [0, T) in one call == scanning [0, 7) then [7, T)
        with the carried state — the chunked-prefill / decode
        transition in miniature."""
        q, k, v, g = _inputs(seed=3)
        out_full, st_full = ssm_chunk_scan(q, k, v, g, chunk=4)
        o1, s1 = ssm_chunk_scan(q[:, :, :7], k[:, :, :7], v[:, :, :7],
                                g[:, :, :7], chunk=4)
        o2, s2 = ssm_chunk_scan(q[:, :, 7:], k[:, :, 7:], v[:, :, 7:],
                                g[:, :, 7:], state=s1, chunk=4)
        np.testing.assert_allclose(
            np.asarray(jnp.concatenate([o1, o2], axis=2)),
            np.asarray(out_full), rtol=0, atol=1e-5)
        np.testing.assert_allclose(np.asarray(s2), np.asarray(st_full),
                                   rtol=0, atol=1e-5)

    def test_recurrent_step_continues_chunked_prefill(self):
        """The real serving sequence: chunked prefill over the prompt,
        then jitted fused steps — matches the all-chunked run 1e-5."""
        q, k, v, g = _inputs(seed=5)
        out_full, st_full = ssm_chunk_scan(q, k, v, g, chunk=64)
        P = 9
        _, s_pre = ssm_chunk_scan(q[:, :, :P], k[:, :, :P],
                                  v[:, :, :P], g[:, :, :P], chunk=64)
        out_dec, st_dec = _recurrent_chain(
            q[:, :, P:], k[:, :, P:], v[:, :, P:], g[:, :, P:],
            state=s_pre)
        np.testing.assert_allclose(np.asarray(out_dec),
                                   np.asarray(out_full[:, :, P:]),
                                   rtol=0, atol=1e-5)
        np.testing.assert_allclose(np.asarray(st_dec),
                                   np.asarray(st_full),
                                   rtol=0, atol=1e-5)

    def test_gradients_flow_and_are_finite(self):
        q, k, v, g = _inputs(seed=7)

        def loss(q_, k_, v_, g_):
            out, _ = ssm_chunk_scan(q_, k_, v_, g_, chunk=4)
            return jnp.sum(out ** 2)

        grads = jax.grad(loss, argnums=(0, 1, 2, 3))(q, k, v, g)
        for gr in grads:
            assert bool(jnp.all(jnp.isfinite(gr)))
            assert float(jnp.max(jnp.abs(gr))) > 0.0

    def test_recurrent_step_rejects_multi_token(self):
        q, k, v, g = _inputs()
        st = jnp.zeros((B_, H_, D_, D_), jnp.float32)
        with pytest.raises(ValueError, match="single-token"):
            ssm_recurrent_step(q, k, v, g, st)

    def test_shape_validation(self):
        q, k, v, g = _inputs()
        with pytest.raises(ValueError, match="share one"):
            ssm_chunk_scan(q, k[:, :, :5], v, g)
        with pytest.raises(ValueError, match="gate"):
            ssm_chunk_scan(q, k, v, g[:, :1])
        bad = jnp.zeros((B_, H_, D_, D_ + 1), jnp.float32)
        with pytest.raises(ValueError, match="state"):
            ssm_chunk_scan(q, k, v, g, state=bad)


V, L, H, DIM, ML = 31, 2, 2, 32, 20


def _params(block_type="ssm", seed=0):
    sym = transformer.get_symbol(V, 12, num_layers=L, num_heads=H,
                                 dim=DIM, max_len=ML,
                                 block_type=block_type)
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(seed)
    state = step.init_state(Xavier(), {"data": (2, 12),
                                       "softmax_label": (2, 12)})
    return state[0]


@pytest.fixture(scope="module")
def ssm_params():
    return _params()


@pytest.fixture(scope="module")
def mixed_params():
    return _params(block_type=("attention", "ssm"), seed=1)


def _gen(params, batch_size, block_type="ssm", **kw):
    return Generator(params, V, ML, num_layers=L, num_heads=H,
                     dim=DIM, batch_size=batch_size,
                     block_type=block_type, **kw)


class TestSymbols:
    def test_decode_symbol_aux_names(self):
        sym = transformer.get_decode_symbol(
            V, num_layers=2, num_heads=H, dim=DIM, max_len=ML,
            block_type="ssm")
        assert sym.list_auxiliary_states() == [
            "layer0_ssm_state", "layer1_ssm_state"]

    def test_mixed_stack_aux_names(self):
        sym = transformer.get_decode_symbol(
            V, num_layers=2, num_heads=H, dim=DIM, max_len=ML,
            block_type=("attention", "ssm"))
        aux = sym.list_auxiliary_states()
        assert "layer0_attn_k_cache" in aux
        assert "layer1_ssm_state" in aux

    def test_per_row_twin_binds_same_params(self):
        """The ContinuousDecoder contract: the per-row-position twin
        (for SSM, the op itself — the recurrence carries position)
        lists exactly the shared-position symbol's arguments."""
        common = dict(num_layers=L, num_heads=H, dim=DIM, max_len=ML,
                      block_type="ssm")
        a = transformer.get_decode_symbol(V, **common)
        b = transformer.get_decode_symbol(V, per_row_pos=True,
                                          **common)
        assert a.list_arguments() == b.list_arguments()

    def test_block_type_validation(self):
        with pytest.raises(ValueError, match="block_type"):
            transformer.get_symbol(V, 12, num_layers=2, num_heads=H,
                                   dim=DIM, block_type="mamba")
        with pytest.raises(ValueError, match="names each layer"):
            transformer.get_symbol(V, 12, num_layers=3, num_heads=H,
                                   dim=DIM,
                                   block_type=("ssm", "attention"))


class TestKnobRefusals:
    """PR 13's refusal-message precedent: every SSM-incompatible knob
    refuses loudly at construction, naming what IS supported."""

    def test_rolling_cache_refused(self):
        with pytest.raises(ValueError, match="rolling_cache"):
            transformer.get_decode_symbol(
                V, num_layers=L, num_heads=H, dim=DIM, max_len=ML,
                block_type="ssm", rolling_cache=True)

    def test_quantize_kv_pure_ssm_refused(self):
        with pytest.raises(ValueError, match="no KV cache"):
            transformer.get_decode_symbol(
                V, num_layers=L, num_heads=H, dim=DIM, max_len=ML,
                block_type="ssm", kv_quantize=True)

    def test_quantize_kv_mixed_composes(self, mixed_params):
        """int8 KV on the attention layers + f32 state blob on the
        SSM layer, side by side in one generator."""
        gen = _gen(mixed_params, 2,
                   block_type=("attention", "ssm"), quantize_kv=True)
        aux = gen._fresh_aux()
        assert aux["layer0_attn_k_cache"].dtype == jnp.int8
        assert aux["layer1_ssm_state"].dtype == jnp.float32

    def test_attention_window_pure_ssm_refused(self):
        with pytest.raises(ValueError, match="attention_window"):
            transformer.get_decode_symbol(
                V, num_layers=L, num_heads=H, dim=DIM, max_len=ML,
                block_type="ssm", attention_window=8)

    def test_seq_axis_refused_in_training_symbol(self):
        with pytest.raises(ValueError, match="seq_axis"):
            transformer.get_symbol(V, 12, num_layers=L, num_heads=H,
                                   dim=DIM, block_type="ssm",
                                   seq_axis="seq")

    def test_speculative_refused(self, ssm_params):
        gen = _gen(ssm_params, 2)
        with pytest.raises(ValueError, match="speculative"):
            gen.truncated_draft(num_layers=1)
        with pytest.raises(ValueError,
                           match="speculative decoding is not"):
            gen.generate_speculative(gen, np.arange(1, 4)[None], 3)


class TestGeneratorSSM:
    def test_greedy_host_vs_device(self, ssm_params):
        gen = _gen(ssm_params, 2)
        prompts = np.asarray([[3, 1, 4, 1], [5, 9, 2, 6]])
        host = gen.generate(prompts, 6)
        dev = gen.generate_on_device(prompts, 6)
        np.testing.assert_array_equal(host, np.asarray(dev))

    def test_mixed_greedy_host_vs_device(self, mixed_params):
        gen = _gen(mixed_params, 2, block_type=("attention", "ssm"))
        prompts = np.asarray([[3, 1, 4, 1], [5, 9, 2, 6]])
        np.testing.assert_array_equal(
            gen.generate(prompts, 6),
            np.asarray(gen.generate_on_device(prompts, 6)))

    def test_state_bytes_independent_of_max_len(self, ssm_params):
        """THE perf property: an SSM slot's bytes never mention
        max_len (vs attention's linear growth)."""
        hd = DIM // H
        want = L * H * hd * hd * 4            # f32 blob per layer
        g_small = Generator(ssm_params, V, 12, num_layers=L,
                            num_heads=H, dim=DIM, batch_size=2,
                            block_type="ssm")
        g_large = Generator(ssm_params, V, ML, num_layers=L,
                            num_heads=H, dim=DIM, batch_size=2,
                            block_type="ssm")
        assert g_small.state_bytes_per_slot() == want
        assert g_large.state_bytes_per_slot() == want
        assert g_large.kv_cache_bytes() == want * 2

    def test_export_blob_bytes_constant_in_pos(self, ssm_params):
        """The O(1) handoff: export_kv_rows ships the same bytes at
        any cached depth (attention blobs grow with pos)."""
        gen = _gen(ssm_params, 2)
        prompts = np.asarray([[3, 1, 4, 1, 5, 9, 2, 6]] * 2)
        _, aux = gen._forward(gen._fresh_aux(),
                              prompts.astype(np.float32), 0)
        b3 = gen.export_kv_rows(aux, 0, 3)
        b8 = gen.export_kv_rows(aux, 0, 8)
        assert kv_blob_nbytes(b3) == kv_blob_nbytes(b8)
        for blob in (b3, b8):
            st = blob["rows"]["layer0_ssm_state"]
            assert st.shape == (H, DIM // H, DIM // H)
            assert st.dtype == np.float32


@pytest.mark.slow
def test_ssm_stack_learns_the_arithmetic_corpus():
    """Convergence gate (the transformer gates' corpus): a pure-SSM
    stack drives next-token NLL toward zero — the chunked scan is a
    trainable block, not just a parity artifact."""
    from tests._lm_utils import arith_corpus, lm_nll
    Tn = 12
    toks, labels = arith_corpus(8, Tn, V)
    sym = transformer.get_symbol(V, Tn, num_layers=2, num_heads=H,
                                 dim=DIM, max_len=ML,
                                 block_type="ssm")
    step = make_train_step(sym, optimizer="adam",
                           optimizer_params={"learning_rate": 3e-3})
    mx.random.seed(0)
    state = step.init_state(Xavier(), {"data": (8, Tn),
                                       "softmax_label": (8, Tn)})
    bv = step.place_batch({"data": toks, "softmax_label": labels})
    rng = jax.random.PRNGKey(0)
    nll0 = None
    for i in range(60):
        state, outs = step(state, bv, 3e-3, rng)
        if nll0 is None:
            nll0 = lm_nll(outs, labels, V)
    nll = lm_nll(outs, labels, V)
    assert nll < 0.2 < nll0
