"""Continuous-batching decode (mxnet_tpu/serve/decode.py): the fixed
slot pool over the on-device KV cache.

Load-bearing acceptance gate: continuous-batching decode matches the
static ``Generator.generate`` token-for-token per sequence — greedy
exactly, sampled against a batch_size=1 generate with the same seed
(each request carries its own PRNG stream). Plus the throughput
property the subsystem exists for: ragged workloads finish in fewer
decode steps than static batching's worst sequence dictates.
"""
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.generation import Generator
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.models import transformer
from mxnet_tpu.parallel import make_train_step
from mxnet_tpu.serve import EngineClosed, Overloaded, SessionEvacuated

pytestmark = pytest.mark.serve

V, L, H, DIM, T, B = 50, 2, 2, 32, 24, 3


def _params(pos_encoding="learned", seed=0, num_kv_heads=None):
    sym = transformer.get_symbol(V, 12, num_layers=L, num_heads=H,
                                 dim=DIM, max_len=T,
                                 pos_encoding=pos_encoding,
                                 num_kv_heads=num_kv_heads)
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(seed)
    state = step.init_state(Xavier(), {"data": (2, 12),
                                       "softmax_label": (2, 12)})
    return state[0]


@pytest.fixture(scope="module")
def params():
    return _params()


def _gen(params, batch_size, **kw):
    return Generator(params, V, T, num_layers=L, num_heads=H, dim=DIM,
                     batch_size=batch_size, **kw)


class TestParity:
    def test_greedy_matches_static_generate_ragged(self, params):
        """ACCEPTANCE: 7 ragged requests through a 3-slot pool ==
        static per-sequence generate, token for token (eos and budget
        endings both exercised)."""
        pool = _gen(params, B)
        single = _gen(params, 1)
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, V, (p,)) for p in
                   (4, 6, 4, 5, 4, 6, 7)]
        maxnew = [8, 3, 12, 5, 2, 9, 4]
        with pool.serving_decoder() as dec:
            futs = [dec.submit(p, n, eos_id=0)
                    for p, n in zip(prompts, maxnew)]
            got = [f.result(120.0) for f in futs]
            st = dec.stats()
        for i, (p, n) in enumerate(zip(prompts, maxnew)):
            want = single.generate(p[None], n, eos_id=0)[0]
            np.testing.assert_array_equal(got[i], want)
        # slot reuse happened: more sequences than slots were admitted
        assert st["finished"] == len(prompts) > B
        # the throughput property: static batching pays
        # ceil(N/B) * max(maxnew) decode steps; continuous must beat it
        static_steps = -(-len(prompts) // B) * max(maxnew)
        assert st["steps"] < static_steps

    def test_sampled_matches_batch1_generate(self, params):
        """A sampled request reproduces a batch_size=1 generate with
        the same seed — its PRNG stream is per-request, independent of
        pool composition."""
        pool = _gen(params, B)
        single = _gen(params, 1)
        rng = np.random.RandomState(9)
        prompt = rng.randint(0, V, (5,))
        with pool.serving_decoder() as dec:
            # crowd the pool so the sampled row shares steps with
            # other active slots
            other = [dec.submit(rng.randint(0, V, (4,)), 10)
                     for _ in range(2)]
            f = dec.submit(prompt, 6, temperature=0.8, top_k=5,
                           seed=42)
            got = f.result(120.0)
            for o in other:
                o.result(120.0)
        want = single.generate(prompt[None], 6, temperature=0.8,
                               top_k=5, seed=42)[0]
        np.testing.assert_array_equal(got, want)

    def test_rope_per_row_positions(self):
        """RoPE path: per-row (B, T) position ids rotate each slot at
        its own depth — greedy parity against static generate."""
        params = _params(pos_encoding="rope", seed=4)
        pool = Generator(params, V, T, num_layers=L, num_heads=H,
                         dim=DIM, batch_size=2, pos_encoding="rope")
        single = Generator(params, V, T, num_layers=L, num_heads=H,
                           dim=DIM, batch_size=1, pos_encoding="rope")
        rng = np.random.RandomState(11)
        prompts = [rng.randint(0, V, (p,)) for p in (3, 6, 4)]
        maxnew = [9, 4, 6]
        with pool.serving_decoder() as dec:
            got = [dec.submit(p, n).result(120.0)
                   for p, n in zip(prompts, maxnew)]
        for p, n, g in zip(prompts, maxnew, got):
            np.testing.assert_array_equal(
                g, single.generate(p[None], n)[0])

    def test_generate_many_convenience(self, params):
        pool = _gen(params, B)
        single = _gen(params, 1)
        rng = np.random.RandomState(13)
        prompts = [rng.randint(0, V, (4,)) for _ in range(4)]
        with pool.serving_decoder() as dec:
            got = dec.generate_many(prompts, 5, eos_id=0,
                                    timeout=120.0)
        for p, g in zip(prompts, got):
            np.testing.assert_array_equal(
                g, single.generate(p[None], 5, eos_id=0)[0])


class TestContract:
    def test_capacity_and_table_validation(self, params):
        pool = _gen(params, B)
        with pool.serving_decoder() as dec:
            with pytest.raises(ValueError, match="max_len"):
                dec.submit(np.zeros(20, np.int64), 10)
            with pytest.raises(ValueError, match="empty"):
                dec.submit(np.zeros(0, np.int64), 2)

    def test_zero_new_tokens_is_the_prompt(self, params):
        pool = _gen(params, B)
        with pool.serving_decoder() as dec:
            prompt = np.arange(5)
            np.testing.assert_array_equal(
                dec.submit(prompt, 0).result(10.0), prompt)

    def test_queue_cap_sheds_typed(self, params):
        pool = _gen(params, B)
        dec = pool.serving_decoder(queue_cap=0)
        try:
            with pytest.raises(Overloaded):
                dec.submit(np.arange(4), 2)
        finally:
            dec.close()

    def test_close_drains_then_rejects(self, params):
        pool = _gen(params, B)
        single = _gen(params, 1)
        rng = np.random.RandomState(17)
        prompts = [rng.randint(0, V, (4,)) for _ in range(5)]
        dec = pool.serving_decoder()
        futs = [dec.submit(p, 6) for p in prompts]
        dec.close()
        for p, f in zip(prompts, futs):
            np.testing.assert_array_equal(
                f.result(1.0), single.generate(p[None], 6)[0])
        with pytest.raises(EngineClosed):
            dec.submit(np.arange(4), 2)

    def test_rolling_cache_still_refused(self, params):
        """Rolling caches remain shared-position only — and the
        refusal must now name quantize_kv as supported (the contract
        text changed when the int8 per-row op landed)."""
        rolling = Generator(params, V, T, num_layers=L, num_heads=H,
                            dim=DIM, batch_size=B, rolling_cache=True,
                            attention_window=8)
        with pytest.raises(ValueError, match="rolling") as e:
            rolling.serving_decoder()
        assert "quantize_kv" in str(e.value)

    def test_sampling_contract_checked_at_submit(self, params):
        pool = _gen(params, B)
        with pool.serving_decoder() as dec:
            with pytest.raises(ValueError, match="temperature"):
                dec.submit(np.arange(4), 2, top_k=3)


def _q8_shared_reference(q, k, v, kc, vc, ks, vs, p0, scale=None,
                         window=0):
    """Pinned copy of the pre-per-row shared-position
    cached_attention_q8 math. The (1,)-pos path of the live op must
    stay BITWISE equal to this forever — the per-row dispatch may
    never reroute or perturb the shared fast path."""
    import jax
    import jax.numpy as jnp
    B_, H_, Tn, D_ = q.shape
    Hkv = kc.shape[1]
    G = H_ // Hkv
    if scale is None:
        scale = D_ ** -0.5
    p0 = jnp.reshape(jnp.asarray(p0), ()).astype(jnp.int32)

    def quantize(x):
        xf = x.astype(jnp.float32)
        s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 127.0
        return jnp.round(xf / s[..., None]).astype(jnp.int8), s

    kq, kss = quantize(k)
    vq, vss = quantize(v)
    kc = jax.lax.dynamic_update_slice(kc, kq, (0, 0, p0, 0))
    vc = jax.lax.dynamic_update_slice(vc, vq, (0, 0, p0, 0))
    ks = jax.lax.dynamic_update_slice(ks, kss, (0, 0, p0))
    vs = jax.lax.dynamic_update_slice(vs, vss, (0, 0, p0))
    kf = kc.astype(jnp.float32) * ks[..., None]
    vf = vc.astype(jnp.float32) * vs[..., None]
    qg = q.reshape(B_, Hkv, G, Tn, D_)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg.astype(jnp.float32), kf,
                   precision=jax.lax.Precision.DEFAULT,
                   preferred_element_type=jnp.float32) * scale
    cols = jnp.arange(kc.shape[2])[None, :]
    rows = jnp.arange(Tn)[:, None]
    valid = cols <= p0 + rows
    if window:
        valid = valid & (p0 + rows - cols < window)
    s = jnp.where(valid, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgqk,bhkd->bhgqd", p, vf,
                     precision=jax.lax.Precision.DEFAULT)
    return (out.reshape(B_, H_, Tn, D_).astype(q.dtype),
            kc, vc, ks, vs)


class TestQuantizedKV:
    """Int8 KV caches through the per-row continuous-batching path
    (PR 13 tentpole): ragged pool decode == batch_size=1 quantized
    generate, scale caches ride the prefill merge, GQA grouping
    holds, and the shared-position op is bitwise untouched."""

    def test_greedy_q8_matches_batch1_quantized_ragged(self, params):
        """ACCEPTANCE: ragged greedy decode under quantize_kv=True
        matches batch_size=1 quantized Generator.generate
        token-for-token, with slot turnover exercised."""
        pool = _gen(params, B, quantize_kv=True)
        single = _gen(params, 1, quantize_kv=True)
        rng = np.random.RandomState(23)
        prompts = [rng.randint(0, V, (p,)) for p in
                   (4, 6, 4, 5, 4, 7)]
        maxnew = [8, 3, 12, 5, 2, 6]
        with pool.serving_decoder() as dec:
            futs = [dec.submit(p, n, eos_id=0)
                    for p, n in zip(prompts, maxnew)]
            got = [f.result(120.0) for f in futs]
            st = dec.stats()
        for i, (p, n) in enumerate(zip(prompts, maxnew)):
            want = single.generate(p[None], n, eos_id=0)[0]
            np.testing.assert_array_equal(got[i], want)
        assert st["finished"] == len(prompts) > B   # slot turnover

    @pytest.mark.slow
    def test_q8_gqa_head_grouping(self):
        """GQA + int8: the per-row q8 op groups q heads over the
        (fewer) cached kv heads exactly like the shared path. Slow
        tier (~9 s on the 1-core tier-1 host); GQA+int8 keeps a fast
        exemplar in test_serve_disagg.py's int8+GQA handoff parity and
        the non-GQA q8 pool parity stays fast above."""
        params = _params(seed=6, num_kv_heads=1)
        pool = Generator(params, V, T, num_layers=L, num_heads=H,
                         dim=DIM, batch_size=2, num_kv_heads=1,
                         quantize_kv=True)
        single = Generator(params, V, T, num_layers=L, num_heads=H,
                           dim=DIM, batch_size=1, num_kv_heads=1,
                           quantize_kv=True)
        rng = np.random.RandomState(29)
        prompts = [rng.randint(0, V, (p,)) for p in (3, 5, 4)]
        maxnew = [7, 4, 6]
        with pool.serving_decoder() as dec:
            got = [dec.submit(p, n).result(120.0)
                   for p, n in zip(prompts, maxnew)]
        for p, n, g in zip(prompts, maxnew, got):
            np.testing.assert_array_equal(
                g, single.generate(p[None], n)[0])

    def test_prefill_merge_scatters_scale_rows(self, params):
        """The batch-axis cache-row merge carries the per-token f32
        scale caches to the RIGHT slots (a merged int8 row without
        its scales would dequantize to garbage)."""
        pool = _gen(params, B, quantize_kv=True)
        rng = np.random.RandomState(31)
        pa, pb = rng.randint(0, V, (4,)), rng.randint(0, V, (6,))
        with pool.serving_decoder() as dec:
            fa = dec.submit(pa, 3)
            fb = dec.submit(pb, 3)
            fa.result(120.0)
            fb.result(120.0)
            aux = {k: np.asarray(v) for k, v in dec._aux.items()}
        for slot, prompt in ((0, pa), (1, pb)):
            rows = np.stack([prompt] * B).astype(np.float32)
            _, ref = pool._forward(pool._fresh_aux(), rows, 0)
            P = len(prompt)
            for name in aux:
                want = np.asarray(ref[name])[0]
                if name.endswith(("_k_scale", "_v_scale")):
                    np.testing.assert_array_equal(
                        aux[name][slot, :, :P], want[:, :P])
                    assert (aux[name][slot, :, :P] > 0).all()
                else:
                    np.testing.assert_array_equal(
                        aux[name][slot, :, :P], want[:, :P])

    def test_q8_shared_pos_bitwise_vs_pinned_reference(self):
        """(1,)-pos cached_attention_q8 is bitwise the pre-per-row
        implementation; a (B,) pos with equal entries agrees with it
        up to einsum association order."""
        import jax.numpy as jnp
        from mxnet_tpu.ops.attention import cached_attention_q8
        rng = np.random.RandomState(37)
        B_, H_, Hkv, Tn, D_, C = 2, 4, 2, 3, 8, 16
        q = jnp.asarray(rng.randn(B_, H_, Tn, D_), jnp.float32)
        k = jnp.asarray(rng.randn(B_, Hkv, Tn, D_), jnp.float32)
        v = jnp.asarray(rng.randn(B_, Hkv, Tn, D_), jnp.float32)
        kc = jnp.asarray(rng.randint(-127, 128, (B_, Hkv, C, D_)),
                         jnp.int8)
        vc = jnp.asarray(rng.randint(-127, 128, (B_, Hkv, C, D_)),
                         jnp.int8)
        ks = jnp.asarray(rng.rand(B_, Hkv, C) + 0.01, jnp.float32)
        vs = jnp.asarray(rng.rand(B_, Hkv, C) + 0.01, jnp.float32)
        p0 = 5
        got = cached_attention_q8(
            q, k, v, kc, vc, ks, vs, jnp.full((1,), p0, jnp.float32))
        ref = _q8_shared_reference(q, k, v, kc, vc, ks, vs, p0)
        for g, r in zip(got, ref):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(r))
        per_row = cached_attention_q8(
            q, k, v, kc, vc, ks, vs,
            jnp.full((B_,), p0, jnp.float32))
        for g, r in zip(per_row, ref):
            np.testing.assert_allclose(
                np.asarray(g, np.float32), np.asarray(r, np.float32),
                rtol=1e-6, atol=1e-6)

    def test_per_row_q8_capacity_check(self):
        import jax.numpy as jnp
        from mxnet_tpu.ops.attention import cached_attention_q8
        rng = np.random.RandomState(41)
        B_, Hkv, Tn, D_, C = 2, 2, 2, 8, 8
        q = jnp.asarray(rng.randn(B_, Hkv, Tn, D_), jnp.float32)
        k = v = q
        kc = vc = jnp.zeros((B_, Hkv, C, D_), jnp.int8)
        ks = vs = jnp.zeros((B_, Hkv, C), jnp.float32)
        with pytest.raises(ValueError, match="overrun"):
            cached_attention_q8(q, k, v, kc, vc, ks, vs,
                                jnp.asarray([0.0, 7.0]))

    def test_kv_bytes_gauge_and_slot_sizing(self, params):
        """serve.decode.kv_bytes_per_slot is published by Generator
        (static) and ContinuousDecoder (live pool, same number), int8
        caches genuinely shrink it, and describe() turns an HBM
        budget into a slot count."""
        from mxnet_tpu import telemetry
        g = telemetry.gauge("serve.decode.kv_bytes_per_slot")
        fp32 = _gen(params, B)
        fp32_bps = fp32.kv_cache_bytes() // B
        assert g.value == fp32_bps
        q8 = _gen(params, B, quantize_kv=True)
        q8_bps = q8.kv_cache_bytes() // B
        assert g.value == q8_bps
        assert q8_bps < 0.55 * fp32_bps
        with q8.serving_decoder() as dec:
            # the live pool republishes the same figure, measured from
            # the actual device arrays
            assert dec._kv_bytes_per_slot == q8_bps
            assert g.value == q8_bps
            report = dec.describe(hbm_budget=q8_bps * 10 + 1)
            assert "kv_bytes_per_slot: %d" % q8_bps in report
            assert "10 slot(s) fit" in report
            # introspect(): the stats-frame shape a decode replica
            # publishes — decode_free_slots is what the fleet
            # router's session placement consumes (serve/router.py)
            intro = dec.introspect()
            assert intro["decode_free_slots"] == B
            assert intro["slots"] == B
            assert intro["queue_depth"] == 0
            assert intro["in_flight"] == 0
            assert intro["draining"] is False


def _spec_dec(pool, lookahead=3, draft_layers=1, **kw):
    return pool.serving_decoder(
        draft=pool.truncated_draft(num_layers=draft_layers),
        lookahead=lookahead, **kw)


class TestSpeculative:
    """Per-slot draft/verify continuous batching (PR 18 tentpole):
    rounds of gamma compiled (B, 1) draft steps plus ONE (B, gamma+1)
    target verify forward, with common-random-numbers acceptance —
    so every output stays byte-identical to plain ``generate`` and
    ``speculative`` is a pure performance hint."""

    def test_spec_mixed_pool_matches_generate_ragged(self, params):
        """ACCEPTANCE: speculative and plain requests share the slot
        pool mid-flight; every sequence == static generate token for
        token, with eos and budget endings and slot turnover."""
        pool = _gen(params, B)
        single = _gen(params, 1)
        rng = np.random.RandomState(43)
        prompts = [rng.randint(0, V, (p,)) for p in
                   (4, 6, 4, 5, 4, 6)]
        maxnew = [8, 3, 12, 5, 4, 9]
        spec = [True, False, True, False, True, True]
        with _spec_dec(pool) as dec:
            futs = [dec.submit(p, n, eos_id=0, speculative=s)
                    for p, n, s in zip(prompts, maxnew, spec)]
            got = [f.result(120.0) for f in futs]
            st = dec.stats()
        for p, n, g in zip(prompts, maxnew, got):
            np.testing.assert_array_equal(
                g, single.generate(p[None], n, eos_id=0)[0])
        assert st["finished"] == len(prompts) > B    # slot turnover
        # the draft genuinely ran: rounds happened, proposals were
        # verified, and speculative admissions paid draft prefills
        # (batched admissions may share one, so <= the request count)
        assert st["spec_rounds"] > 0
        assert st["draft_steps"] >= st["spec_rounds"]
        assert 0 < st["spec_accepted"] <= st["spec_proposed"]
        assert 0 < st["draft_prefills"] <= sum(spec)

    def test_spec_sampled_matches_batch1_generate(self, params):
        """Sampled speculative request reproduces a batch_size=1
        generate with the same seed — acceptance reuses the EXACT
        per-token noise the verify pick consumes (common random
        numbers), so the distribution is not just equal, the draws
        are."""
        pool = _gen(params, B)
        single = _gen(params, 1)
        rng = np.random.RandomState(47)
        prompt = rng.randint(0, V, (5,))
        with _spec_dec(pool) as dec:
            # crowd the pool: a plain greedy row rides every verify
            # forward as a passenger
            other = dec.submit(rng.randint(0, V, (4,)), 10)
            f = dec.submit(prompt, 6, temperature=0.8, top_k=5,
                           seed=42, speculative=True)
            got = f.result(120.0)
            other.result(120.0)
        want = single.generate(prompt[None], 6, temperature=0.8,
                               top_k=5, seed=42)[0]
        np.testing.assert_array_equal(got, want)

    def test_spec_streaming_one_token_at_a_time(self, params):
        """A round commits up to gamma+1 tokens at once, but sinks
        still see them ONE at a time, in order, then the None
        terminator — the streaming contract is spec-oblivious."""
        pool = _gen(params, B)
        rng = np.random.RandomState(53)
        prompt = rng.randint(0, V, (4,))
        seen = []
        with _spec_dec(pool) as dec:
            fut = dec.submit(prompt, 8, speculative=True)
            fut.subscribe(seen.append)
            got = fut.result(120.0)
            deadline = time.monotonic() + 10.0
            while (not seen or seen[-1] is not None) and \
                    time.monotonic() < deadline:
                time.sleep(0.005)
        assert seen[-1] is None
        np.testing.assert_array_equal(np.asarray(seen[:-1]),
                                      got[len(prompt):])

    def test_spec_headroom_checked_at_submit(self, params):
        """Verify rounds write up to gamma speculative cache entries
        past EVERY live row's depth, so with a draft attached each
        admission needs P + n <= min(max_lens) - gamma — plain
        requests included, checked loudly at submit."""
        pool = _gen(params, B)
        with _spec_dec(pool, lookahead=4) as dec:   # cap = 24 - 4
            with pytest.raises(ValueError, match="headroom"):
                dec.submit(np.arange(1, 16), 8, speculative=True)
            with pytest.raises(ValueError, match="headroom"):
                dec.submit(np.arange(1, 16), 8)     # plain rows too
            # at the cap is fine
            dec.submit(np.arange(1, 13), 8,
                       speculative=True).result(120.0)
        # a lookahead that leaves no usable headroom at all is a
        # construction-time error, not a submit-time surprise
        with pytest.raises(ValueError, match="headroom"):
            _spec_dec(pool, lookahead=T)

    def test_spec_jit_cache_discipline(self, params):
        """The throughput contract: the target owns exactly TWO
        compiled programs — the (B, 1) step and the (B, gamma+1)
        verify — and the draft exactly ONE, however ragged the
        workload."""
        pool = _gen(params, B)
        rng = np.random.RandomState(59)
        with _spec_dec(pool) as dec:
            assert dec.introspect()["speculative"] is True
            # plain request first, alone: pins the (B, 1) step trace
            dec.submit(rng.randint(0, V, (4,)), 6).result(120.0)
            for p, n in ((3, 8), (6, 4), (5, 11)):
                dec.submit(rng.randint(0, V, (p,)), n,
                           speculative=True).result(120.0)
            assert telemetry.gauge(
                "serve.decode.jit_cache_size").value == 2
            assert telemetry.gauge(
                "serve.spec.draft_jit_cache_size").value == 1

    def test_spec_evacuate_resume_carries_hint(self, params):
        """Mid-decode migration of a speculative session: the export
        state records the hint, and the resumed stream on a second
        draft-attached pool emits the remaining tokens
        bit-identically."""
        single = _gen(params, 1)
        p = np.arange(1, 6)
        want = single.generate(p[None], 8, temperature=0.8, top_k=8,
                               seed=7)[0]
        d1 = _spec_dec(_gen(params, 2))
        d2 = _spec_dec(_gen(params, 2))
        try:
            fut = d1.submit(p, 8, temperature=0.8, top_k=8, seed=7,
                            speculative=True)
            deadline = time.monotonic() + 10.0
            while len(fut.emitted) < 3 and \
                    time.monotonic() < deadline:
                time.sleep(0.005)
            assert len(fut.emitted) >= 3
            assert d1.evacuate() == 1
            with pytest.raises(SessionEvacuated) as ei:
                fut.result(10.0)
            state = ei.value.state
            assert state["speculative"] is True
            got = d2.submit(p, 8, temperature=0.8, top_k=8, seed=7,
                            resume=state,
                            speculative=True).result(120.0)
            np.testing.assert_array_equal(got, want)
            assert d2.stats()["resumed"] == 1
        finally:
            d1.close()
            d2.close()

    def test_spec_env_draft_config(self, params, monkeypatch):
        """MXNET_SPEC_DRAFT attaches a truncated draft to every
        decoder built without an explicit ``draft=`` — the
        zero-code-change opt-in subprocess replicas use — and gamma
        is honored."""
        monkeypatch.setenv("MXNET_SPEC_DRAFT", "layers=1,gamma=2")
        pool = _gen(params, B)
        single = _gen(params, 1)
        rng = np.random.RandomState(61)
        prompt = rng.randint(0, V, (5,))
        with pool.serving_decoder() as dec:
            assert dec._draft is not None
            assert dec._draft.num_layers == 1
            assert dec._gamma == 2
            got = dec.submit(prompt, 7,
                             speculative=True).result(120.0)
            st = dec.stats()
        np.testing.assert_array_equal(
            got, single.generate(prompt[None], 7)[0])
        assert st["spec_rounds"] > 0

    def test_spec_env_parse_errors(self, monkeypatch):
        """spec_draft() validates loudly — a typo'd fleet env var must
        fail fast, not silently decode draft-less."""
        from mxnet_tpu.serve.decode import spec_draft
        for raw, msg in [("1,gamma=2", "fieldless"),
                         ("layers=one", "integer"),
                         ("layers=1,speed=9", "unknown field"),
                         ("gamma=2", "layers >= 1"),
                         ("layers=0", "layers >= 1"),
                         ("layers=1,gamma=0", "gamma >= 1")]:
            monkeypatch.setenv("MXNET_SPEC_DRAFT", raw)
            with pytest.raises(ValueError, match=msg):
                spec_draft()
        monkeypatch.setenv("MXNET_SPEC_DRAFT", "  ")
        assert spec_draft() is None
        monkeypatch.setenv("MXNET_SPEC_DRAFT", "layers=2,gamma=5")
        assert spec_draft() == (2, 5)
