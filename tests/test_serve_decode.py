"""Continuous-batching decode (mxnet_tpu/serve/decode.py): the fixed
slot pool over the on-device KV cache.

Load-bearing acceptance gate: continuous-batching decode matches the
static ``Generator.generate`` token-for-token per sequence — greedy
exactly, sampled against a batch_size=1 generate with the same seed
(each request carries its own PRNG stream). Plus the throughput
property the subsystem exists for: ragged workloads finish in fewer
decode steps than static batching's worst sequence dictates.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.generation import Generator
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.models import transformer
from mxnet_tpu.parallel import make_train_step
from mxnet_tpu.serve import EngineClosed, Overloaded

pytestmark = pytest.mark.serve

V, L, H, DIM, T, B = 50, 2, 2, 32, 24, 3


def _params(pos_encoding="learned", seed=0):
    sym = transformer.get_symbol(V, 12, num_layers=L, num_heads=H,
                                 dim=DIM, max_len=T,
                                 pos_encoding=pos_encoding)
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(seed)
    state = step.init_state(Xavier(), {"data": (2, 12),
                                       "softmax_label": (2, 12)})
    return state[0]


@pytest.fixture(scope="module")
def params():
    return _params()


def _gen(params, batch_size, **kw):
    return Generator(params, V, T, num_layers=L, num_heads=H, dim=DIM,
                     batch_size=batch_size, **kw)


class TestParity:
    def test_greedy_matches_static_generate_ragged(self, params):
        """ACCEPTANCE: 7 ragged requests through a 3-slot pool ==
        static per-sequence generate, token for token (eos and budget
        endings both exercised)."""
        pool = _gen(params, B)
        single = _gen(params, 1)
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, V, (p,)) for p in
                   (4, 6, 4, 5, 4, 6, 7)]
        maxnew = [8, 3, 12, 5, 2, 9, 4]
        with pool.serving_decoder() as dec:
            futs = [dec.submit(p, n, eos_id=0)
                    for p, n in zip(prompts, maxnew)]
            got = [f.result(120.0) for f in futs]
            st = dec.stats()
        for i, (p, n) in enumerate(zip(prompts, maxnew)):
            want = single.generate(p[None], n, eos_id=0)[0]
            np.testing.assert_array_equal(got[i], want)
        # slot reuse happened: more sequences than slots were admitted
        assert st["finished"] == len(prompts) > B
        # the throughput property: static batching pays
        # ceil(N/B) * max(maxnew) decode steps; continuous must beat it
        static_steps = -(-len(prompts) // B) * max(maxnew)
        assert st["steps"] < static_steps

    def test_sampled_matches_batch1_generate(self, params):
        """A sampled request reproduces a batch_size=1 generate with
        the same seed — its PRNG stream is per-request, independent of
        pool composition."""
        pool = _gen(params, B)
        single = _gen(params, 1)
        rng = np.random.RandomState(9)
        prompt = rng.randint(0, V, (5,))
        with pool.serving_decoder() as dec:
            # crowd the pool so the sampled row shares steps with
            # other active slots
            other = [dec.submit(rng.randint(0, V, (4,)), 10)
                     for _ in range(2)]
            f = dec.submit(prompt, 6, temperature=0.8, top_k=5,
                           seed=42)
            got = f.result(120.0)
            for o in other:
                o.result(120.0)
        want = single.generate(prompt[None], 6, temperature=0.8,
                               top_k=5, seed=42)[0]
        np.testing.assert_array_equal(got, want)

    def test_rope_per_row_positions(self):
        """RoPE path: per-row (B, T) position ids rotate each slot at
        its own depth — greedy parity against static generate."""
        params = _params(pos_encoding="rope", seed=4)
        pool = Generator(params, V, T, num_layers=L, num_heads=H,
                         dim=DIM, batch_size=2, pos_encoding="rope")
        single = Generator(params, V, T, num_layers=L, num_heads=H,
                           dim=DIM, batch_size=1, pos_encoding="rope")
        rng = np.random.RandomState(11)
        prompts = [rng.randint(0, V, (p,)) for p in (3, 6, 4)]
        maxnew = [9, 4, 6]
        with pool.serving_decoder() as dec:
            got = [dec.submit(p, n).result(120.0)
                   for p, n in zip(prompts, maxnew)]
        for p, n, g in zip(prompts, maxnew, got):
            np.testing.assert_array_equal(
                g, single.generate(p[None], n)[0])

    def test_generate_many_convenience(self, params):
        pool = _gen(params, B)
        single = _gen(params, 1)
        rng = np.random.RandomState(13)
        prompts = [rng.randint(0, V, (4,)) for _ in range(4)]
        with pool.serving_decoder() as dec:
            got = dec.generate_many(prompts, 5, eos_id=0,
                                    timeout=120.0)
        for p, g in zip(prompts, got):
            np.testing.assert_array_equal(
                g, single.generate(p[None], 5, eos_id=0)[0])


class TestContract:
    def test_capacity_and_table_validation(self, params):
        pool = _gen(params, B)
        with pool.serving_decoder() as dec:
            with pytest.raises(ValueError, match="max_len"):
                dec.submit(np.zeros(20, np.int64), 10)
            with pytest.raises(ValueError, match="empty"):
                dec.submit(np.zeros(0, np.int64), 2)

    def test_zero_new_tokens_is_the_prompt(self, params):
        pool = _gen(params, B)
        with pool.serving_decoder() as dec:
            prompt = np.arange(5)
            np.testing.assert_array_equal(
                dec.submit(prompt, 0).result(10.0), prompt)

    def test_queue_cap_sheds_typed(self, params):
        pool = _gen(params, B)
        dec = pool.serving_decoder(queue_cap=0)
        try:
            with pytest.raises(Overloaded):
                dec.submit(np.arange(4), 2)
        finally:
            dec.close()

    def test_close_drains_then_rejects(self, params):
        pool = _gen(params, B)
        single = _gen(params, 1)
        rng = np.random.RandomState(17)
        prompts = [rng.randint(0, V, (4,)) for _ in range(5)]
        dec = pool.serving_decoder()
        futs = [dec.submit(p, 6) for p in prompts]
        dec.close()
        for p, f in zip(prompts, futs):
            np.testing.assert_array_equal(
                f.result(1.0), single.generate(p[None], 6)[0])
        with pytest.raises(EngineClosed):
            dec.submit(np.arange(4), 2)

    def test_unsupported_cache_variants_raise(self, params):
        quant = Generator(params, V, T, num_layers=L, num_heads=H,
                          dim=DIM, batch_size=B, quantize_kv=True)
        with pytest.raises(ValueError, match="int8 KV"):
            quant.serving_decoder()

    def test_sampling_contract_checked_at_submit(self, params):
        pool = _gen(params, B)
        with pool.serving_decoder() as dec:
            with pytest.raises(ValueError, match="temperature"):
                dec.submit(np.arange(4), 2, top_k=3)
