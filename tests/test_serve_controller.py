"""The fleet controller (mxnet_tpu/serve/controller.py): health-gated
autoscaling, self-healing, rolling rollout with automatic rollback,
and crash-safe journaled state.

Everything here is deterministic: the controller is built with
``poll_ms=0`` (no background loop), the router with ``poll_ms=0`` (no
background poller), and every decision is driven by explicit
``tick()`` calls — hysteresis and cooldown count TICKS, so there are
no wall-clock sleeps in this fast tier. Load signals come from
scripted engine introspection (the same stats frame a real engine
answers), so a "sustained queue depth" is three scripted polls, not
three seconds of real queueing.
"""
import json
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.serve import (FleetController, ReplicaState,
                             ServeEngine, ServeRouter, ServeServer)

pytestmark = pytest.mark.serve

FEAT, CLASSES = 8, 4


def _predictor(seed=7):
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=CLASSES)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    arg_shapes, _, _ = net.infer_shape(data=(2, FEAT))
    mx.random.seed(seed)
    init = Xavier()
    args = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        arr = mx.nd.zeros(shp)
        init(name, arr)
        args[name] = arr
    return Predictor(net, args, data_names=("data",))


@pytest.fixture(scope="module")
def pred():
    return _predictor()


class _Broken:
    """A model whose forward always fails — the canary-failing
    artifact a rollout gate must refuse."""

    def forward(self, *arrays):
        raise RuntimeError("deliberately broken artifact")


class _Scripted(ServeEngine):
    """An engine whose stats frame reports SCRIPTED load signals on
    top of its real state — sustained queue depth and shedding become
    deterministic poll responses instead of real queues under real
    sleeps."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.fake_depth = 0
        self.fake_shed = None          # scripted cumulative counter
        self.fake_admitted = None

    def introspect(self):
        out = super().introspect()
        out["queue_depth"] += self.fake_depth
        if self.fake_shed is not None:
            out["shed"] = self.fake_shed
        if self.fake_admitted is not None:
            out["admitted"] = self.fake_admitted
        return out


class _CtrlFleet:
    """N in-process replicas behind a router, plus the spawn/retire
    hooks a controller drives — the whole supervised fleet in one
    process, every wire real."""

    def __init__(self, pred, n, engine_cls=_Scripted, model_id=None,
                 router_kw=None, **ctrl_kw):
        self.pred = pred
        self.engine_cls = engine_cls
        self.model_id = model_id
        self.cells = {}               # "host:port" -> (engine, server)
        self.retired = []             # (name, addr) retire-hook calls
        self.spawn_log = []           # manifests the spawn hook saw
        # manifest -> (model factory, stamp); None covers the default
        self.artifacts = {None: (lambda: self.pred, model_id)}
        self.router = ServeRouter(poll_ms=0, **(router_kw or {}))
        names = []
        for i in range(n):
            host, port = self._spawn(None)
            names.append(self.router.add_replica(host, port,
                                                 name="r%d" % i))
        self.names = names
        self.router.poll_now()
        ctrl_kw.setdefault("poll_ms", 0)
        self.ctrl = FleetController(self.router, self.spawn,
                                    retire=self.retire, **ctrl_kw)

    def _spawn(self, manifest):
        factory, stamp = self.artifacts[manifest]
        eng = self.engine_cls(factory(), buckets=(1, 2, 4),
                              max_wait_ms=0.0,
                              feature_shapes=[(FEAT,)],
                              install_sigterm=False)
        if stamp is not None:
            eng.model_id = stamp
        srv = ServeServer(eng)
        addr = (srv.host, srv.port)
        self.cells["%s:%d" % addr] = (eng, srv)
        return addr

    def spawn(self, manifest=None):
        self.spawn_log.append(manifest)
        return self._spawn(manifest)

    def retire(self, name, addr):
        self.retired.append((name, addr))
        cell = self.cells.pop(addr, None)
        if cell is not None:
            eng, srv = cell
            srv.close()
            eng.close()

    def kill(self, name):
        """SIGKILL analogue: the replica's server and engine vanish
        without draining (the router discovers it via transport
        faults / failed polls)."""
        desc = self.router.replicas()[name]
        addr = "%s:%d" % (desc["host"], desc["port"])
        eng, srv = self.cells.pop(addr)
        srv.close()
        eng.close()

    def engines(self):
        """name -> live engine, via the router's address records."""
        out = {}
        for name, desc in self.router.replicas().items():
            cell = self.cells.get("%s:%d" % (desc["host"],
                                             desc["port"]))
            if cell is not None:
                out[name] = cell[0]
        return out

    def close(self):
        self.ctrl.close()
        self.router.close()
        for eng, srv in self.cells.values():
            srv.close()
            eng.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _cval(name):
    return telemetry.counter(name).value


class TestKnobValidation:
    """The config-validated pattern: every bad policy dies loudly at
    construction, never as a silent misbehavior mid-supervision."""

    def _ctor(self, **kw):
        router = ServeRouter(poll_ms=0)
        try:
            kw.setdefault("poll_ms", 0)
            FleetController(router, lambda m=None: ("h", 1), **kw)
        finally:
            router.close()

    @pytest.mark.parametrize("kw,needle", [
        (dict(min_replicas=0), "MIN_REPLICAS"),
        (dict(min_replicas=3, max_replicas=2), "MAX_REPLICAS"),
        (dict(sustain=0), "SUSTAIN"),
        (dict(cooldown=-1), "COOLDOWN"),
        (dict(canary_timeout=0.0), "CANARY_TIMEOUT"),
        (dict(canary_timeout=float("inf")), "CANARY_TIMEOUT"),
        (dict(scale_out_shed=0.0), "SCALE_OUT_SHED"),
        (dict(scale_in_depth=5.0, scale_out_depth=4.0),
         "SCALE_IN_DEPTH"),
        (dict(poll_ms=-1.0), "POLL_MS"),
    ])
    def test_bad_knobs_raise(self, kw, needle):
        with pytest.raises(ValueError, match=needle):
            self._ctor(**kw)

    def test_env_knob_path(self, monkeypatch):
        from mxnet_tpu import config
        config.set_override("MXNET_CTRL_SUSTAIN", 0)
        try:
            with pytest.raises(ValueError, match="SUSTAIN"):
                self._ctor()
        finally:
            config.clear_override("MXNET_CTRL_SUSTAIN")

    def test_hooks_must_be_callable(self):
        router = ServeRouter(poll_ms=0)
        try:
            with pytest.raises(ValueError, match="spawn"):
                FleetController(router, "not-a-hook", poll_ms=0)
            with pytest.raises(ValueError, match="retire"):
                FleetController(router, lambda m=None: ("h", 1),
                                retire="nope", poll_ms=0)
        finally:
            router.close()


class TestAutoscale:
    def test_scale_out_on_sustained_depth(self, pred):
        """Depth over threshold for SUSTAIN consecutive ticks spawns
        exactly one warmed replica; the streak resets after."""
        with _CtrlFleet(pred, 1, sustain=2, cooldown=0,
                        scale_out_depth=4.0, max_replicas=3) as f:
            c0 = _cval("serve.ctrl.scale_outs")
            f.engines()["r0"].fake_depth = 8
            assert f.ctrl.tick()["scaled_out"] == []   # streak 1 of 2
            out = f.ctrl.tick()                        # sustained
            assert len(out["scaled_out"]) == 1
            assert _cval("serve.ctrl.scale_outs") == c0 + 1
            assert len(f.spawn_log) == 1
            reps = f.router.replicas()
            assert len(reps) == 2
            new = out["scaled_out"][0]
            # warm-before-admit: the spawned replica came in live AND
            # already compiled its declared buckets
            assert reps[new]["state"] == ReplicaState.LIVE
            assert reps[new]["stats"]["warmed"] == [1, 2, 4]
            # one infer proves the scaled-out replica actually serves
            f.router.infer(np.zeros((1, FEAT), np.float32))

    def test_scale_out_on_shed_window(self, pred):
        """A shedding window scales out even while queues look
        shallow — sheds mean admission is already failing."""
        with _CtrlFleet(pred, 1, sustain=1, cooldown=0,
                        scale_out_shed=2.0, max_replicas=2) as f:
            eng = f.engines()["r0"]
            eng.fake_shed = 10
            f.router.poll_now()            # window baseline
            assert f.ctrl.tick()["scaled_out"] == []   # delta 0
            eng.fake_shed = 15             # 5 sheds this window
            out = f.ctrl.tick()
            assert len(out["scaled_out"]) == 1

    def test_max_replicas_caps_scale_out(self, pred):
        with _CtrlFleet(pred, 2, sustain=1, cooldown=0,
                        max_replicas=2) as f:
            for eng in f.engines().values():
                eng.fake_depth = 50
            for _ in range(3):
                assert f.ctrl.tick()["scaled_out"] == []
            assert len(f.router.replicas()) == 2

    def test_scale_in_drains_to_floor(self, pred):
        """A sustained idle window retires the newest replica through
        the zero-drop drain, never below MIN_REPLICAS."""
        with _CtrlFleet(pred, 3, sustain=2, cooldown=0,
                        min_replicas=2) as f:
            c0 = _cval("serve.ctrl.scale_ins")
            f.ctrl.tick()                  # idle streak 1
            out = f.ctrl.tick()            # sustained -> retire one
            assert out["scaled_in"] == ["r2"]
            assert f.retired and f.retired[-1][0] == "r2"
            assert _cval("serve.ctrl.scale_ins") == c0 + 1
            # floor: two more sustained-idle ticks must NOT go below 2
            for _ in range(4):
                assert f.ctrl.tick()["scaled_in"] == []
            assert len(f.router.replicas()) == 2
            f.router.infer(np.zeros((1, FEAT), np.float32))

    def test_flap_suppression(self, pred):
        """An oscillating signal keeps resetting the streak: no
        action, ever — and after a real scale-out, cooldown holds
        further scaling until it expires."""
        with _CtrlFleet(pred, 1, sustain=2, cooldown=3,
                        max_replicas=4) as f:
            eng = f.engines()["r0"]
            for i in range(8):             # hot, cold, hot, cold ...
                eng.fake_depth = 8 if i % 2 == 0 else 0
                out = f.ctrl.tick()
                assert out["scaled_out"] == []
                assert out["scaled_in"] == []
            assert len(f.router.replicas()) == 1
            # now a SUSTAINED signal: scales once, then cooldown
            # suppresses the (still hot) signal for 3 ticks
            eng.fake_depth = 8
            f.ctrl.tick()                          # streak 1
            assert len(f.ctrl.tick()["scaled_out"]) == 1   # acts
            holds = [f.ctrl.tick()["scaled_out"] for _ in range(2)]
            assert holds == [[], []]               # cooling down
            # cooldown expired + streak sustained throughout: acts
            assert len(f.ctrl.tick()["scaled_out"]) == 1
            assert len(f.router.replicas()) == 3


class TestSelfHealing:
    def test_dead_replica_respawned_same_name(self, pred):
        """Suspect + probe-confirmed dead -> retired and respawned
        under the same name; the healed replica serves."""
        with _CtrlFleet(pred, 2, sustain=99) as f:
            c0 = _cval("serve.ctrl.heals")
            f.kill("r1")
            out = f.ctrl.tick()            # poll marks suspect, probe
            #                                confirms, heal respawns
            assert out["healed"] == ["r1"]
            assert _cval("serve.ctrl.heals") == c0 + 1
            reps = f.router.replicas()
            assert reps["r1"]["state"] == ReplicaState.LIVE
            assert reps["r1"]["stats"]["warmed"] == [1, 2, 4]
            for _ in range(4):
                f.router.infer(np.zeros((1, FEAT), np.float32))
            # a live replica is never healed
            assert f.ctrl.tick()["healed"] == []

    def test_in_flight_requests_survive_the_death(self, pred):
        """Requests in flight while a replica dies ride the router's
        failover/reroute path: a concurrent sweep sees exactly one
        response per request and zero errors, then the controller
        heals the corpse."""
        with _CtrlFleet(pred, 2, sustain=99) as f:
            x = np.zeros((1, FEAT), np.float32)
            errors, done = [], []

            def client():
                for _ in range(10):
                    try:
                        f.router.infer(x)
                        done.append(1)
                    except Exception as exc:   # noqa: BLE001 — count
                        errors.append(exc)

            ts = [threading.Thread(target=client) for _ in range(3)]
            for t in ts:
                t.start()
            f.kill("r0")
            for t in ts:
                t.join()
            assert not errors, errors
            assert len(done) == 30
            assert f.ctrl.tick()["healed"] == ["r0"]
            assert f.router.replicas()["r0"]["state"] == \
                ReplicaState.LIVE


class TestRollout:
    def test_promote_both_replicas(self, pred):
        """The happy path: every replica recycles onto the new
        artifact, every gate passes, the fleet ends uniform on the
        new stamp."""
        with _CtrlFleet(pred, 2, model_id="v1", sustain=99,
                        canary_inputs=[np.zeros((1, FEAT),
                                                np.float32)]) as f:
            f.artifacts["m2"] = (lambda: f.pred, "v2")
            c0 = _cval("serve.ctrl.promotes")
            res = f.ctrl.rollout("m2", model_id="v2")
            assert not res.rolled_back
            assert res.promoted == ["r0", "r1"]
            assert res.manifest == "m2"
            assert f.ctrl.manifest == "m2"
            assert _cval("serve.ctrl.promotes") == c0 + 2
            reps = f.router.replicas()
            assert {d["model_id"] for d in reps.values()} == {"v2"}
            # the old processes were retired, the new ones serve
            f.router.infer(np.zeros((1, FEAT), np.float32))

    def test_canary_failure_rolls_back(self, pred):
        """A deliberately broken artifact fails the canary on the
        FIRST replica: it rolls back to the prior manifest, the fleet
        is uniform on the old stamp, and a concurrent request sweep
        sees zero errors."""
        with _CtrlFleet(pred, 2, model_id="v1", sustain=99,
                        canary_inputs=[np.zeros((1, FEAT),
                                                np.float32)]) as f:
            f.artifacts["bad"] = (_Broken, "v2")
            c0 = _cval("serve.ctrl.rollbacks")
            x = np.zeros((1, FEAT), np.float32)
            stop, errors, done = threading.Event(), [], []

            def sweep():
                while not stop.is_set():
                    try:
                        f.router.infer(x)
                        done.append(1)
                    except Exception as exc:   # noqa: BLE001 — count
                        errors.append(exc)

            t = threading.Thread(target=sweep)
            t.start()
            try:
                res = f.ctrl.rollout("bad", model_id="v2")
            finally:
                stop.set()
                t.join()
            assert res.rolled_back
            assert "canary failed" in res.reason
            assert res.manifest is None          # the prior (default)
            assert f.ctrl.manifest is None
            assert _cval("serve.ctrl.rollbacks") == c0 + 1
            reps = f.router.replicas()
            assert {d["model_id"] for d in reps.values()} == {"v1"}
            assert not errors, errors
            assert done                          # the sweep ran
            f.router.infer(x)

    def test_stamp_mismatch_rolls_back(self, pred):
        """A spawn hook handing back the WRONG artifact (hello stamp
        disagrees with the manifest) fails the gate before any canary
        — exactly the half-promoted state model_id exists to catch."""
        with _CtrlFleet(pred, 2, model_id="v1", sustain=99) as f:
            f.artifacts["m2"] = (lambda: f.pred, "v1")   # stale build
            res = f.ctrl.rollout("m2", model_id="v2")
            assert res.rolled_back
            assert "stamp mismatch" in res.reason
            assert {d["model_id"]
                    for d in f.router.replicas().values()} == {"v1"}

    def test_gate_failure_on_second_replica_rolls_back_first(
            self, pred):
        """A gate that fails mid-fleet rolls back the already-promoted
        replicas too — never a mixed-version fleet after return."""
        with _CtrlFleet(pred, 2, model_id="v1", sustain=99,
                        canary_inputs=[np.zeros((1, FEAT),
                                                np.float32)]) as f:
            flaky = iter([lambda: f.pred, _Broken])

            def factory():
                return next(flaky)()
            f.artifacts["m2"] = (factory, "v2")
            c0 = _cval("serve.ctrl.rollbacks")
            res = f.ctrl.rollout("m2", model_id="v2")
            assert res.rolled_back
            # both touched replicas rolled back (r1 failed, r0 was
            # already promoted)
            assert _cval("serve.ctrl.rollbacks") == c0 + 2
            assert {d["model_id"]
                    for d in f.router.replicas().values()} == {"v1"}


class TestJournal:
    def test_actions_journal_atomically(self, pred, tmp_path):
        path = str(tmp_path / "ctrl.json")
        with _CtrlFleet(pred, 1, sustain=1, cooldown=0,
                        max_replicas=2, journal=path) as f:
            f.engines()["r0"].fake_depth = 50
            f.ctrl.tick()
            with open(path) as fh:
                doc = json.load(fh)
            assert doc["version"] == 1
            assert doc["pending_rollout"] is None
            assert [a["action"] for a in doc["actions"]] == \
                ["scale_out"]

    def test_restart_resumes_interrupted_rollout(self, pred,
                                                 tmp_path):
        """A controller that dies mid-rollout (spawn hook starts
        failing hard after the first promote) leaves the pending
        record in its journal; a NEW controller on the same journal
        rolls the fleet back to the prior manifest on its first
        tick instead of re-deciding from scratch."""
        path = str(tmp_path / "ctrl.json")
        with _CtrlFleet(pred, 2, model_id="v1", sustain=99,
                        journal=path) as f:
            calls = []

            def dying_factory():
                calls.append(1)
                if len(calls) > 1:
                    raise RuntimeError("spawn infrastructure down")
                return f.pred

            def dead_prior():
                raise RuntimeError("spawn infrastructure down")

            # promote r0 works, promote r1 dies — and the spawn
            # infrastructure stays down for the PRIOR artifact too,
            # so the in-process rollback also fails: exactly the
            # state a controller crash mid-rollout leaves behind
            f.artifacts["m2"] = (dying_factory, "v2")
            good_prior = f.artifacts[None]
            f.artifacts[None] = (dead_prior, "v1")
            with pytest.raises(RuntimeError,
                               match="infrastructure down"):
                f.ctrl.rollout("m2", model_id="v2")
            with open(path) as fh:
                pend = json.load(fh)["pending_rollout"]
            assert pend is not None
            assert pend["promoted"] == ["r0"]
            assert pend["promoting"] == "r1"

            # "restart": a fresh controller over the same journal and
            # a healed spawn path
            f.artifacts[None] = good_prior
            f.artifacts["m2"] = (lambda: f.pred, "v2")
            f.ctrl.close()
            c0 = _cval("serve.ctrl.rollbacks")
            f.ctrl = FleetController(f.router, f.spawn,
                                     retire=f.retire, journal=path,
                                     poll_ms=0, sustain=99)
            out = f.ctrl.tick()
            assert out["recovered"]
            assert _cval("serve.ctrl.rollbacks") >= c0 + 1
            assert f.ctrl.manifest is None       # back on the prior
            assert {d["model_id"]
                    for d in f.router.replicas().values()} == {"v1"}
            with open(path) as fh:
                assert json.load(fh)["pending_rollout"] is None
            f.router.infer(np.zeros((1, FEAT), np.float32))

    def test_journal_version_guard(self, pred, tmp_path):
        path = str(tmp_path / "ctrl.json")
        with open(path, "w") as fh:
            json.dump({"version": 999}, fh)
        router = ServeRouter(poll_ms=0)
        try:
            with pytest.raises(ValueError, match="version"):
                FleetController(router, lambda m=None: ("h", 1),
                                journal=path, poll_ms=0)
        finally:
            router.close()


class TestWindowedRates:
    def test_rates_are_per_window_deltas(self, pred):
        """shed_rate / req_rate are deltas of the cumulative counters
        between consecutive polls — and a counter that went BACKWARDS
        (replica restart) restarts the window instead of reporting a
        negative rate."""
        with _CtrlFleet(pred, 1, sustain=99) as f:
            eng = f.engines()["r0"]
            eng.fake_shed, eng.fake_admitted = 4, 10
            f.router.poll_now()
            st = f.router.replicas()["r0"]["stats"]
            # the very first poll of this fleet already ran in the
            # constructor (window exists): this poll sees the full
            # scripted jump
            assert st["shed_rate"] == 4
            f.router.poll_now()                  # no movement
            st = f.router.replicas()["r0"]["stats"]
            assert st["shed_rate"] == 0 and st["req_rate"] == 0
            eng.fake_shed, eng.fake_admitted = 7, 16
            f.router.poll_now()
            st = f.router.replicas()["r0"]["stats"]
            assert st["shed_rate"] == 3 and st["req_rate"] == 6
            # counter reset: rate = counts since the restart
            eng.fake_shed, eng.fake_admitted = 1, 2
            f.router.poll_now()
            st = f.router.replicas()["r0"]["stats"]
            assert st["shed_rate"] == 1 and st["req_rate"] == 2
            # the fleet aggregate carries the summed windowed rates
            agg = f.router.stats()
            assert "shed_rate" in agg and "req_rate" in agg


class TestModelIdPlumb:
    def test_export_manifest_carries_stamp(self, pred, tmp_path):
        prefix = str(tmp_path / "m")
        manifest = pred.export_buckets(prefix, [(FEAT,)],
                                       buckets=(1, 2))
        with open(manifest) as fh:
            doc = json.load(fh)
        assert doc["model_id"].startswith("gen-")
        # content-derived: a re-export of identical weights stamps
        # identically
        manifest2 = pred.export_buckets(str(tmp_path / "m2"),
                                        [(FEAT,)], buckets=(1, 2))
        with open(manifest2) as fh:
            assert json.load(fh)["model_id"] == doc["model_id"]
        # explicit stamp wins
        pred.export_buckets(str(tmp_path / "m3"), [(FEAT,)],
                            buckets=(1,), model_id="release-7")
        with open(str(tmp_path / "m3") + ".serve.json") as fh:
            assert json.load(fh)["model_id"] == "release-7"

    def test_hello_ships_stamp_and_router_records_it(self, pred,
                                                     tmp_path):
        prefix = str(tmp_path / "m")
        pred.export_buckets(prefix, [(FEAT,)], buckets=(1, 2))
        eng = ServeEngine.from_export(prefix, max_wait_ms=0.0,
                                      install_sigterm=False)
        assert eng.model_id and eng.model_id.startswith("gen-")
        srv = ServeServer(eng)
        router = ServeRouter(poll_ms=0)
        try:
            router.add_replica(srv.host, srv.port, name="r0")
            desc = router.replicas()["r0"]
            assert desc["model_id"] == eng.model_id
        finally:
            router.close()
            srv.close()
            eng.close()

    def test_in_process_models_report_none(self, pred):
        """The bugfix's compat half: engines without an export
        manifest hello model_id None and everything keeps working
        (duck-typed wire)."""
        eng = ServeEngine(pred, buckets=(1, 2), max_wait_ms=0.0,
                          feature_shapes=[(FEAT,)],
                          install_sigterm=False)
        srv = ServeServer(eng)
        router = ServeRouter(poll_ms=0)
        try:
            router.add_replica(srv.host, srv.port, name="r0")
            assert router.replicas()["r0"]["model_id"] is None
            router.infer(np.zeros((1, FEAT), np.float32))
        finally:
            router.close()
            srv.close()
            eng.close()


class TestRetireReplica:
    def test_zero_drop_retire_under_load(self, pred):
        """retire_replica drains like recycle then removes: a sweep
        running throughout sees one response per request."""
        with _CtrlFleet(pred, 2, sustain=99) as f:
            x = np.zeros((1, FEAT), np.float32)
            errors, done = [], []

            def client():
                for _ in range(8):
                    try:
                        f.router.infer(x)
                        done.append(1)
                    except Exception as exc:   # noqa: BLE001 — count
                        errors.append(exc)

            ts = [threading.Thread(target=client) for _ in range(3)]
            for t in ts:
                t.start()
            f.router.retire_replica("r1")
            for t in ts:
                t.join()
            assert not errors, errors
            assert len(done) == 24
            assert list(f.router.replicas()) == ["r0"]

    def test_refuses_last_live_replica(self, pred):
        with _CtrlFleet(pred, 1, sustain=99) as f:
            with pytest.raises(ValueError, match="no live replica"):
                f.router.retire_replica("r0")
