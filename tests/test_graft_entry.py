"""Guard the driver entry points: dryrun_multichip must keep compiling
and executing the full SPMD story (dp+tp+sp+pp+ep) on virtual devices —
this is the artifact the round driver records (MULTICHIP_rNN.json)."""
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_subprocess():
    env = dict(os.environ, PYTHONPATH=_REPO)
    # the entry forces the CPU platform itself (the round-1 failure was
    # exactly this going unset); no JAX_PLATFORMS needed here
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(4); "
         "print('GRAFT-DRYRUN-OK')"],
        capture_output=True, text=True, timeout=480, env=env, cwd=_REPO)
    assert out.returncode == 0, out.stderr[-1000:]
    assert "GRAFT-DRYRUN-OK" in out.stdout
