"""Optimizer tests — reference: tests/python/unittest/test_optimizer.py
(numpy-oracle update checks) + the Test mock-optimizer update path."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt


def _sgd_numpy(w, g, mom, lr, wd, momentum, rescale):
    g = g * rescale + wd * w
    if momentum == 0:
        return w - lr * g, mom
    mom = momentum * mom - lr * g
    return w + mom, mom


@pytest.mark.parametrize("momentum", [0.0, 0.9])
def test_sgd_matches_numpy(momentum):
    np.random.seed(0)
    w_np = np.random.randn(10, 4).astype(np.float32)
    sgd = opt.SGD(learning_rate=0.1, momentum=momentum, wd=0.01,
                  rescale_grad=0.5)
    w = mx.nd.array(w_np)
    state = sgd.create_state(0, w)
    mom_np = np.zeros_like(w_np)
    for _ in range(3):
        g_np = np.random.randn(10, 4).astype(np.float32)
        sgd.update(0, w, mx.nd.array(g_np), state)
        w_np, mom_np = _sgd_numpy(w_np, g_np, mom_np, 0.1, 0.01, momentum,
                                  0.5)
    np.testing.assert_allclose(w.asnumpy(), w_np, rtol=1e-5, atol=1e-6)


def test_adam_decreases_loss():
    np.random.seed(0)
    target = np.random.randn(20).astype(np.float32)
    w = mx.nd.zeros((20,))
    adam = opt.Adam(learning_rate=0.1)
    state = adam.create_state(0, w)
    first = float(((w.asnumpy() - target) ** 2).sum())
    for _ in range(50):
        grad = mx.nd.array(2 * (w.asnumpy() - target))
        adam.update(0, w, grad, state)
    last = float(((w.asnumpy() - target) ** 2).sum())
    assert last < first * 0.01


@pytest.mark.parametrize("name", ["sgd", "adam", "rmsprop", "adagrad",
                                  "adadelta", "ftrl", "adamax", "nadam",
                                  "nag", "signum", "test"])
def test_all_optimizers_update(name):
    np.random.seed(0)
    o = opt.create(name)
    w = mx.nd.array(np.random.randn(6, 3).astype(np.float32))
    g = mx.nd.array(np.random.randn(6, 3).astype(np.float32))
    before = w.asnumpy().copy()
    state = o.create_state(0, w)
    o.update(0, w, g, state)
    assert not np.allclose(before, w.asnumpy())


def test_lr_wd_mult():
    # reference test_optimizer: lr_mult/wd_mult routing by idx2name
    o = opt.SGD(learning_rate=1.0, param_idx2name={0: "a_weight",
                                                   1: "b_bias"})
    o.set_lr_mult({"a_weight": 0.0})
    w = mx.nd.ones((2, 2))
    g = mx.nd.ones((2, 2))
    o.update(0, w, g, o.create_state(0, w))
    np.testing.assert_allclose(w.asnumpy(), np.ones((2, 2)))  # lr_mult=0
    # bias gets wd_mult 0 automatically but lr 1.0
    w2 = mx.nd.ones((2,))
    o.update(1, w2, mx.nd.ones((2,)), o.create_state(1, w2))
    np.testing.assert_allclose(w2.asnumpy(), np.zeros((2,)), atol=1e-6)


def test_updater_states_roundtrip():
    u = opt.get_updater(opt.SGD(momentum=0.9, learning_rate=0.1))
    w = mx.nd.ones((3,))
    u(0, mx.nd.ones((3,)), w)
    blob = u.get_states()
    u2 = opt.get_updater(opt.SGD(momentum=0.9, learning_rate=0.1))
    u2.set_states(blob)
    w2 = w.copy()
    u(0, mx.nd.ones((3,)), w)
    u2(0, mx.nd.ones((3,)), w2)
    np.testing.assert_allclose(w.asnumpy(), w2.asnumpy(), rtol=1e-6)


def test_lr_scheduler_factor():
    from mxnet_tpu.lr_scheduler import FactorScheduler, MultiFactorScheduler
    s = FactorScheduler(step=10, factor=0.5)
    s.base_lr = 1.0
    assert s(1) == 1.0
    assert s(11) == 0.5
    assert s(21) == 0.25
    m = MultiFactorScheduler(step=[5, 15], factor=0.1)
    m.base_lr = 1.0
    assert m(1) == 1.0
    assert abs(m(6) - 0.1) < 1e-9
    assert abs(m(16) - 0.01) < 1e-9


def test_dcasgd_momentum():
    """Regression: DCASGD with momentum on multi-element weights."""
    o = opt.create("dcasgd", momentum=0.9, learning_rate=0.1)
    w = mx.nd.ones((4,))
    st = o.create_state(0, w)
    o.update(0, w, mx.nd.ones((4,)), st)
    assert not np.allclose(w.asnumpy(), np.ones(4))


def test_lamb_updates_on_device():
    o = opt.create("lamb", learning_rate=0.1)
    w = mx.nd.ones((4, 4))
    st = o.create_state(0, w)
    o.update(0, w, mx.nd.ones((4, 4)), st)
    assert np.isfinite(w.asnumpy()).all()
    assert not np.allclose(w.asnumpy(), np.ones((4, 4)))
