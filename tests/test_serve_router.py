"""The fleet router (mxnet_tpu/serve/router.py): least-loaded
dispatch, decode session affinity, shed-and-retry, suspect/reroute,
and zero-drop rolling restarts.

Load-bearing acceptance gates:
- Shed-and-retry: an Overloaded from one replica lands the request on
  the next replica, with ONE trace_id spanning router AND both
  replicas; Overloaded reaches the caller only when every live
  replica shed.
- Dead-replica reroute: an injected always-drop transport to one
  replica marks it suspect and reroutes — every request still
  succeeds, and a healthy poll revives the replica.
- Rolling-restart zero-drop: a closed-loop client sweep running while
  EVERY replica is recycled once (drain -> restart -> re-warm ->
  readmit) observes exactly one successful response per request — no
  drops, no client-visible errors, no sleeps-as-sync (the drain waits
  on the router's in-flight condition + the stats frame).
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import config, telemetry, trace
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.parallel.resilience import (FaultInjector, RetryPolicy,
                                           install_fault_injector)
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.serve import (EngineClosed, Overloaded, ReplicaState,
                             ServeClient, ServeEngine, ServeRouter,
                             ServeServer)

pytestmark = pytest.mark.serve

FEAT, CLASSES = 8, 4


def _predictor(seed=7):
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=CLASSES)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    arg_shapes, _, _ = net.infer_shape(data=(2, FEAT))
    mx.random.seed(seed)
    init = Xavier()
    args = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        arr = mx.nd.zeros(shp)
        init(name, arr)
        args[name] = arr
    return Predictor(net, args, data_names=("data",))


@pytest.fixture(scope="module")
def pred():
    return _predictor()


@pytest.fixture
def no_injector():
    yield
    install_fault_injector(None)


class _Slow:
    """Forward wrapper with a fixed per-forward delay — makes load
    observable without depending on model speed."""

    def __init__(self, pred, delay):
        self._pred = pred
        self.delay = delay

    def forward(self, *arrays):
        if self.delay:
            time.sleep(self.delay)
        return self._pred.forward(*arrays)


class _DecodeCapable(ServeEngine):
    """An engine whose introspection reports decode slot headroom —
    the signal a decode-capable replica publishes and the router's
    session placement consumes."""

    def __init__(self, *args, free_slots=0, **kwargs):
        super().__init__(*args, **kwargs)
        self.free_slots = free_slots

    def introspect(self):
        out = super().introspect()
        out["decode_free_slots"] = self.free_slots
        return out


class _Fleet:
    """N in-process replicas (engine + ServeServer) behind one router
    — the whole fleet in one test process, every wire real."""

    def __init__(self, pred, n, engine_cls=ServeEngine, delays=None,
                 caps=None, buckets=(1, 2, 4), router_kw=None,
                 engine_kw=None):
        self.pred = pred
        self.buckets = buckets
        self.engine_cls = engine_cls
        self.engine_kw = engine_kw or {}
        self.engines, self.servers = [], []
        for i in range(n):
            self._build(i, (delays or {}).get(i, 0.0),
                        (caps or {}).get(i))
        self.router = ServeRouter(poll_ms=0, **(router_kw or {}))
        self.names = [
            self.router.add_replica(s.host, s.port, name="r%d" % i)
            for i, s in enumerate(self.servers)]
        self.router.poll_now()

    def _build(self, i, delay, cap):
        kw = dict(self.engine_kw)
        if cap is not None:
            kw["queue_cap"] = cap
        model = _Slow(self.pred, delay) if delay else self.pred
        eng = self.engine_cls(model, buckets=self.buckets,
                              max_wait_ms=0.0,
                              feature_shapes=[(FEAT,)],
                              install_sigterm=False, **kw)
        srv = ServeServer(eng)
        if i < len(self.engines):
            self.engines[i], self.servers[i] = eng, srv
        else:
            self.engines.append(eng)
            self.servers.append(srv)
        return srv

    def restarter(self, i, delay=0.0, cap=None):
        """An in-process restart hook: drain+close the old replica,
        build a fresh one, hand its address back to the router."""
        def restart():
            self.servers[i].close()
            self.engines[i].close()
            srv = self._build(i, delay, cap)
            return (srv.host, srv.port)
        return restart

    def close(self):
        self.router.close()
        for s in self.servers:
            s.close()
        for e in self.engines:
            e.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class TestRetryPolicyHook:
    def test_on_fatal_reroutes_without_weakening_fast_fail(self):
        """Satellite: RetryPolicy.run(on_fatal=) — a fatal error
        retries only when the hook approves; without the hook the
        fast-fail contract is byte-identical."""
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise Overloaded("shed")
            return "ok"

        pol = RetryPolicy(max_retries=5, base_delay=0.001)
        # no hook: fatal raises on the FIRST call (fast fail)
        with pytest.raises(Overloaded):
            pol.run(flaky)
        assert len(calls) == 1
        # hook approves: retried until success, same budget
        calls.clear()
        assert pol.run(flaky, on_fatal=lambda e: True) == "ok"
        assert len(calls) == 3
        # hook declines: fast fail preserved
        calls.clear()
        with pytest.raises(Overloaded):
            pol.run(flaky, on_fatal=lambda e: False)
        assert len(calls) == 1
        # the hook is never consulted for TRANSIENT errors
        seen = []

        def transient_once():
            seen.append(1)
            if len(seen) < 2:
                raise ConnectionError("blip")
            return "ok"

        assert pol.run(transient_once,
                       on_fatal=lambda e: pytest.fail(
                           "on_fatal consulted for a transient "
                           "error")) == "ok"


class TestLeastLoaded:
    def test_skew_away_from_slow_replica(self, pred):
        """A slowed replica accumulates in-flight and the router
        routes around it: the fast replica serves the bulk."""
        with _Fleet(pred, 2, delays={0: 0.05}) as f:
            x = np.zeros((1, FEAT), np.float32)
            f.router.infer(x)            # both candidates warm paths

            def client():
                for _ in range(5):
                    f.router.infer(x)

            ts = [threading.Thread(target=client) for _ in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            reps = f.router.replicas()
            slow = reps["r0"]["dispatched"]
            fast = reps["r1"]["dispatched"]
        assert fast > slow, (slow, fast)
        assert slow >= 1                 # the slow one still serves

    def test_warm_bucket_preference(self, pred):
        """With equal load, a request prefers the replica whose
        bucket for its size is WARMED — a cold replica never costs a
        live request an XLA compile while a warm one is free."""
        with _Fleet(pred, 2) as f:
            # warm only replica 1 (index order would otherwise send
            # the request to r0)
            f.engines[1].warmup()
            f.router.poll_now()
            x = np.zeros((1, FEAT), np.float32)
            f.router.infer(x)
            reps = f.router.replicas()
            assert reps["r1"]["dispatched"] == 1
            assert reps["r0"]["dispatched"] == 0

    def test_stats_aggregation(self, pred):
        """router.stats() sums the fleet; introspect() adds the
        per-replica detail the stats frame ships."""
        with _Fleet(pred, 3) as f:
            x = np.zeros((1, FEAT), np.float32)
            for _ in range(6):
                f.router.infer(x)
            st = f.router.stats()
            assert st["replicas"] == 3 and st["live"] == 3
            assert st["dispatched"] == 6 and st["in_flight"] == 0
            intro = f.router.introspect()
            assert intro["role"] == "router"
            assert set(intro["per_replica"]) == {"r0", "r1", "r2"}
            assert sum(r["dispatched"]
                       for r in intro["per_replica"].values()) == 6
            # the fleet front end answers the same stats frame any
            # replica does — clients cannot tell a router apart
            with ServeServer(f.router) as front:
                c = ServeClient(front.host, front.port,
                                retry=RetryPolicy(base_delay=0.01))
                got = c.stats()
                c.close()
            assert got["engine"]["role"] == "router"
            assert set(got["engine"]["per_replica"]) == \
                {"r0", "r1", "r2"}


class TestSessionAffinity:
    def test_pin_and_turnover(self, pred):
        """New sessions land on the replica with the most free decode
        slots; every subsequent request of the session sticks to the
        pin; releasing the session (slot freed) lets it re-place on
        the new most-free replica."""
        with _Fleet(pred, 2, engine_cls=_DecodeCapable) as f:
            f.engines[0].free_slots = 1
            f.engines[1].free_slots = 4
            f.router.poll_now()
            x = np.zeros((1, FEAT), np.float32)
            f.router.infer(x, session="a")
            assert f.router.sessions()["a"] == "r1"
            # load the pin's replica: the session STAYS (affinity
            # beats least-loaded)
            for _ in range(4):
                f.router.infer(x, session="a")
            assert f.router.sessions()["a"] == "r1"
            assert f.router.replicas()["r1"]["dispatched"] == 5
            # slot turnover: r1 fills up, r0 frees — a NEW session
            # goes to r0
            f.engines[0].free_slots = 4
            f.engines[1].free_slots = 0
            f.router.poll_now()
            f.router.infer(x, session="b")
            assert f.router.sessions()["b"] == "r0"
            # release -> the id re-places like a new session
            assert f.router.release_session("a")
            f.router.infer(x, session="a")
            assert f.router.sessions()["a"] == "r0"

    def test_session_rides_the_wire(self, pred):
        """The session id crosses the front-end wire (an extra payload
        key old servers ignore) and drives the router's pin — remote
        clients get affinity without a new protocol."""
        with _Fleet(pred, 2, engine_cls=_DecodeCapable) as f:
            f.engines[1].free_slots = 4
            f.router.poll_now()
            x = np.zeros((1, FEAT), np.float32)
            with ServeServer(f.router) as front:
                c = ServeClient(front.host, front.port,
                                retry=RetryPolicy(base_delay=0.01))
                c.request([x], session="w")
                c.request([x], session="w")
                c.close()
            assert f.router.sessions()["w"] == "r1"
            assert f.router.replicas()["r1"]["dispatched"] == 2
            # and a session id against a BARE replica is harmlessly
            # ignored (single engine: nothing to route)
            c2 = ServeClient(f.servers[0].host, f.servers[0].port,
                             retry=RetryPolicy(base_delay=0.01))
            assert c2.request([x], session="w")[0].shape == \
                (1, CLASSES)
            c2.close()

    @pytest.mark.faults
    def test_fresh_pin_reroutes_on_transport_fault(self, pred,
                                                   no_injector):
        """A SPECULATIVE pin (placed by the failing dispatch itself)
        must not chain retries back to the dead replica through the
        pinned-branch fast path — the pin drops and the session
        re-places on a live replica."""
        with _Fleet(pred, 2, engine_cls=_DecodeCapable) as f:
            f.engines[0].free_slots = 4   # placement favors r0
            f.router.poll_now()
            install_fault_injector(FaultInjector(
                "router0_send:drop@1x*"))
            x = np.zeros((1, FEAT), np.float32)
            out = f.router.infer(x, session="s")   # r0 dead -> r1
            assert out[0].shape == (1, CLASSES)
            assert f.router.sessions()["s"] == "r1"
            assert f.router.replicas()["r0"]["state"] == \
                ReplicaState.SUSPECT
            # and while r0 is suspect, its (stale, attractive) slot
            # stats must not win NEW sessions either
            f.router.infer(x, session="s2")
            assert f.router.sessions()["s2"] == "r1"

    def test_session_cap_evicts_lru(self, pred):
        with _Fleet(pred, 2, router_kw={"session_cap": 2}) as f:
            x = np.zeros((1, FEAT), np.float32)
            for sid in ("a", "b", "c"):
                f.router.infer(x, session=sid)
            assert set(f.router.sessions()) == {"b", "c"}

    def test_established_pin_does_not_reroute_on_shed(self, pred):
        """An ESTABLISHED session sheds to the caller rather than
        silently abandoning its KV slot; a sessionless request (and a
        FRESH speculative pin) in the same state reroutes fine."""
        with _Fleet(pred, 2, engine_cls=_DecodeCapable) as f:
            f.engines[0].free_slots = 4   # sessions place on r0
            f.router.poll_now()
            x = np.zeros((1, FEAT), np.float32)
            f.router.infer(x, session="s")
            assert f.router.sessions()["s"] == "r0"
            f.engines[0]._cap = 0         # r0 now sheds everything
            # established pin: the shed is the caller's backpressure
            # signal, never a silent KV-state abandonment
            with pytest.raises(Overloaded):
                f.router.infer(x, session="s")
            assert f.router.sessions()["s"] == "r0"   # pin intact
            # sessionless traffic reroutes around the full replica
            assert f.router.infer(x)[0].shape == (1, CLASSES)
            # a FRESH pin is speculative (no KV state yet): it may
            # move — the new session lands on r1 despite r0's slots
            f.router.infer(x, session="fresh")
            assert f.router.sessions()["fresh"] == "r1"


class TestShedAndRetry:
    def test_reroute_lands_on_next_replica(self, pred):
        """ACCEPTANCE (shed-and-retry): replica 1 sheds (cap 0),
        the request lands on replica 2; Overloaded reaches the caller
        only when EVERY live replica shed."""
        with _Fleet(pred, 2, caps={0: 0}) as f:
            x = np.zeros((1, FEAT), np.float32)
            out = f.router.infer(x)
            assert out[0].shape == (1, CLASSES)
            reps = f.router.replicas()
            assert reps["r0"]["rerouted_from"] == 1
            assert reps["r1"]["dispatched"] == 1
            assert f.router.stats()["rerouted"] == 1
            # both shed -> typed Overloaded to the caller
            f.engines[1]._cap = 0
            with pytest.raises(Overloaded, match="every live replica"):
                f.router.infer(x)

    def test_one_trace_spans_router_and_both_replicas(self, pred,
                                                      tmp_path):
        """ACCEPTANCE: the shed-and-retry request produces ONE
        trace_id covering the client request, the router dispatch
        (with its reroute instant), and BOTH replicas' handlers."""
        trace.stop_tracing()
        dest = str(tmp_path / "spill.jsonl")
        trace.start_tracing(dest)
        try:
            with _Fleet(pred, 2, caps={0: 0}) as f, \
                    ServeServer(f.router) as front:
                c = ServeClient(front.host, front.port,
                                retry=RetryPolicy(base_delay=0.01))
                c.request([np.zeros((1, FEAT), np.float32)])
                c.close()
        finally:
            path = trace.stop_tracing()
        import json
        records = [json.loads(ln) for ln in open(path)
                   if ln.strip()]
        spans = [r for r in records if r.get("kind") == "span"]
        by_name = {}
        for r in spans:
            by_name.setdefault(r["name"], []).append(r)
        # the remote client's request span roots the trace
        tid = by_name["serve.request"][0]["trace"]
        # router front handler + two replica handlers, same trace
        handles = by_name["serve.handle"]
        assert len(handles) == 3
        assert all(h["trace"] == tid for h in handles)
        dispatch = by_name["serve.router.dispatch"]
        assert len(dispatch) == 1 and dispatch[0]["trace"] == tid
        assert dispatch[0]["attrs"]["reroutes"] == 1
        assert dispatch[0]["attrs"]["replica"] == "r1"
        # three serve.request spans: client->router, router->r0,
        # router->r1 — one trace end to end
        assert len(by_name["serve.request"]) == 3
        assert all(s["trace"] == tid
                   for s in by_name["serve.request"])
        reroutes = [r for r in records
                    if r.get("kind") == "instant"
                    and r["name"] == "serve.router.reroute"]
        assert len(reroutes) == 1 and reroutes[0]["trace"] == tid

    @pytest.mark.faults
    def test_dead_replica_reroute_and_revive(self, pred, no_injector):
        """ACCEPTANCE: an always-drop transport to replica 0 (its
        own injection point family — router0_send) marks it suspect
        and reroutes every request to replica 1; clearing the fault
        and polling revives it."""
        with _Fleet(pred, 2) as f:
            install_fault_injector(FaultInjector(
                "router0_send:drop@1x*"))
            x = np.zeros((1, FEAT), np.float32)
            for _ in range(3):
                assert f.router.infer(x)[0].shape == (1, CLASSES)
            reps = f.router.replicas()
            assert reps["r0"]["state"] == ReplicaState.SUSPECT
            assert reps["r1"]["dispatched"] == 3
            assert telemetry.counter(
                "serve.router.suspected").value >= 1
            # heal the wire: the next poll revives the replica (its
            # control points are a separate family — polls never died)
            install_fault_injector(None)
            f.router.poll_now()
            assert f.router.replicas()["r0"]["state"] == \
                ReplicaState.LIVE


class TestRollingRestart:
    def test_zero_drop_recycle_under_load(self, pred):
        """ACCEPTANCE: a closed-loop sweep runs while EVERY replica
        is recycled once; each request gets exactly one successful
        response — zero drops, zero client-visible errors. No
        sleeps-as-sync: recycle() blocks on the router's in-flight
        condition + the stats frame, the sweep is a fixed request
        count."""
        N_CLIENTS, N_REQ = 6, 18
        with _Fleet(pred, 3, delays={0: 0.002, 1: 0.002, 2: 0.002},
                    engine_kw={"queue_cap": 512}) as f:
            x = np.zeros((1, FEAT), np.float32)
            ok = [0] * N_CLIENTS
            errs = []
            started = threading.Barrier(N_CLIENTS + 1)

            def client(ci):
                started.wait()
                for _ in range(N_REQ):
                    try:
                        out = f.router.infer(x)
                        assert out[0].shape == (1, CLASSES)
                        ok[ci] += 1
                    except Exception as exc:  # noqa: BLE001 — the
                        errs.append(exc)      # test asserts none
                        return

            ts = [threading.Thread(target=client, args=(i,))
                  for i in range(N_CLIENTS)]
            for t in ts:
                t.start()
            started.wait()               # sweep provably in flight
            for i, name in enumerate(f.names):
                f.router.recycle(name, restart=f.restarter(i, 0.002))
            for t in ts:
                t.join()
            assert not errs, errs[:3]
            assert sum(ok) == N_CLIENTS * N_REQ
            st = f.router.stats()
            assert st["recycles"] == 3
            reps = f.router.replicas()
            assert all(r["state"] == ReplicaState.LIVE
                       for r in reps.values())
            # re-warm happened: every replica's buckets are warm again
            assert all(sorted(r["stats"]["warmed"]) == [1, 2, 4]
                       for r in reps.values())
            # the sweep's volume all arrived somewhere
            assert sum(r["dispatched"] for r in reps.values()) >= \
                N_CLIENTS * N_REQ

    def test_recycle_refuses_last_live_replica(self, pred):
        with _Fleet(pred, 1) as f:
            with pytest.raises(ValueError, match="no live replica"):
                f.router.recycle("r0")

    def test_recycle_without_restart_rewarns_and_readmits(self, pred):
        """restart=None: drain + re-warm + readmit (config-reload
        shape) — and dispatch EXCLUDES the replica while draining."""
        with _Fleet(pred, 2) as f:
            x = np.zeros((1, FEAT), np.float32)
            f.router.recycle("r0")
            reps = f.router.replicas()
            assert reps["r0"]["state"] == ReplicaState.LIVE
            assert sorted(reps["r0"]["stats"]["warmed"]) == [1, 2, 4]
            assert f.router.stats()["recycles"] == 1
            f.router.infer(x)

    def test_draining_replica_rejects_via_router(self, pred):
        """A replica draining OUTSIDE the router's control (its own
        SIGTERM/close) is observed at dispatch (EngineClosed answer)
        and routed around — via the self-healing polled-stats channel,
        NOT a sticky state flip (a restarted replica readmits on the
        next poll, no recycle() needed)."""
        with _Fleet(pred, 2) as f:
            x = np.zeros((1, FEAT), np.float32)
            f.engines[0].close()          # drains: submits now reject
            out = f.router.infer(x)       # observed + rerouted
            assert out[0].shape == (1, CLASSES)
            reps = f.router.replicas()
            assert reps["r0"]["stats"]["draining"]
            assert reps["r0"]["state"] == ReplicaState.LIVE
            # further requests skip r0 WITHOUT paying a round trip
            f.router.infer(x)
            assert f.router.replicas()["r1"]["dispatched"] == 2
            # the replica restarts itself on the SAME address (its
            # supervisor's job): the next poll readmits it — no
            # operator action, no recycle()
            host, port = f.servers[0].host, f.servers[0].port
            f.servers[0].close()
            f.engines[0] = ServeEngine(
                pred, buckets=f.buckets, max_wait_ms=0.0,
                feature_shapes=[(FEAT,)], install_sigterm=False)
            f.servers[0] = ServeServer(f.engines[0], host=host,
                                       port=port)
            f.router.poll_now()
            assert not f.router.replicas()["r0"]["stats"]["draining"]
            f.engines[1].close()          # r1 drains; r0 must serve
            assert f.router.infer(x)[0].shape == (1, CLASSES)


class TestBenchFleet:
    @pytest.mark.slow
    def test_bench_serve_fleet_emits_json(self, capsys):
        """--replicas N: router + subprocess replicas emit the
        serve_fleet_throughput line with per-replica fill."""
        import json

        import bench_serve
        assert bench_serve.main(["--replicas", "2",
                                 "--concurrency", "2,4",
                                 "--requests", "5",
                                 "--work-ms", "1",
                                 "--features", str(FEAT),
                                 "--hidden", "16",
                                 "--classes", str(CLASSES)]) == 0
        rec = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert rec["metric"] == "serve_fleet_throughput"
        assert rec["replicas"] == 2
        assert rec["value"] > 0
        assert len(rec["per_replica_fill"]) == 2
        assert sum(rec["per_replica_fill"].values()) > 0
        assert len(rec["sweep"]) == 2
        assert {"p50", "p95", "p99"} <= \
            set(rec["sweep"][0]["latency_ms"])
        assert sum(r["errors"] for r in rec["sweep"]) == 0


class TestRouterTelemetry:
    def test_gauges_and_fleet_report(self, pred):
        """The serve.router.* gauges track the fleet, and the
        multi-target --stats fleet table renders one row per
        replica."""
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        "..", "tools"))
        try:
            from telemetry_report import fetch_stats, format_fleet
        finally:
            sys.path.pop(0)
        with _Fleet(pred, 2) as f:
            x = np.zeros((1, FEAT), np.float32)
            for _ in range(4):
                f.router.infer(x)
            assert telemetry.gauge(
                "serve.router.replicas").value == 2
            assert telemetry.gauge(
                "serve.router.replicas_live").value == 2
            rows = [("%s:%d" % (s.host, s.port),
                     fetch_stats("%s:%d" % (s.host, s.port)))
                    for s in f.servers]
            text = format_fleet(rows)
        for s in f.servers:
            assert "%s:%d" % (s.host, s.port) in text
        assert "queue" in text and "warmed" in text
        # a dead target renders as unreachable, not a crash
        text2 = format_fleet(rows + [("127.0.0.1:1", None)])
        assert "unreachable" in text2
