"""Seeded convergence gates — the analogue of the reference's
tests/python/train/ suite (test_mlp.py accuracy thresholds,
test_dtype.py fp16 cifar): small models must actually train, across
dtypes, every CI run. Synthetic seeded datasets keep it hermetic
(no downloads); thresholds have slack over observed values so the
gates catch regressions, not noise."""
import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import io
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.parallel import make_train_step
from mxnet_tpu.test_utils import check_consistency


def _digits(n=512, seed=3):
    """MNIST-shaped stand-in: 10 classes, 784 features, linearly
    separable-ish clusters + noise."""
    rng = np.random.RandomState(seed)
    protos = rng.randn(10, 784).astype(np.float32)
    y = rng.randint(0, 10, n)
    X = protos[y] + 0.5 * rng.randn(n, 784).astype(np.float32)
    return X.astype(np.float32), y.astype(np.float32)


def _mlp_sym():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=64)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=10)
    return mx.sym.SoftmaxOutput(net, name="softmax")


@pytest.mark.parametrize("compute_dtype", [None, "bfloat16"])
def test_mlp_accuracy_gate(compute_dtype):
    """MLP on the digits stand-in must clear 95% train accuracy — in
    f32 AND with bf16 compute (f32 master weights), the mp_sgd path
    (reference tests/python/train/test_mlp.py + test_dtype.py)."""
    X, y = _digits()
    step = make_train_step(_mlp_sym(), optimizer="sgd",
                           optimizer_params={"momentum": 0.9,
                                             "rescale_grad": 1.0 / 512},
                           compute_dtype=compute_dtype)
    mx.random.seed(0)
    np.random.seed(0)
    state = step.init_state(Xavier(), {"data": X.shape,
                                       "softmax_label": y.shape})
    rng = jax.random.PRNGKey(0)
    batch = step.place_batch({"data": X, "softmax_label": y})
    for _ in range(40):
        state, outs = step(state, batch, 0.1, rng)
    acc = (np.asarray(outs[0]).astype(np.float32).argmax(1) == y).mean()
    assert acc > 0.95, "accuracy gate failed (%s): %.3f" % (
        compute_dtype, acc)


def test_lstm_lm_perplexity_gate():
    """Tiny LSTM LM (BucketingModule, the PTB workload shape): training
    perplexity must drop by 2x and end under 8 on the structured
    synthetic corpus (reference example/rnn/lstm_bucketing.py +
    tests/python/train convergence pattern)."""
    rng = np.random.RandomState(1)
    vocab = 32
    sents = []
    for _ in range(200):
        start, stride = rng.randint(0, vocab), rng.randint(1, 4)
        ln = int(rng.choice([8, 12]))
        sents.append([(start + i * stride) % vocab for i in range(ln)])
    train = mx.rnn.BucketSentenceIter(sents, 16, buckets=[8, 12],
                                      invalid_label=-1)

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=16,
                                 name="embed")
        cell = mx.rnn.LSTMCell(48, prefix="lstm_")
        outputs, _ = cell.unroll(seq_len, embed, layout="NTC",
                                 merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, 48))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        return (mx.sym.SoftmaxOutput(pred, label, name="softmax"),
                ("data",), ("softmax_label",))

    mod = mx.mod.BucketingModule(
        sym_gen, default_bucket_key=train.default_bucket_key)
    metric = mx.metric.Perplexity(-1)
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.02})

    def epoch_ppl():
        train.reset()
        metric.reset()
        for batch in train:
            mod.forward(batch, is_train=True)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
        return metric.get()[1]

    first = epoch_ppl()
    last = None
    for _ in range(6):
        last = epoch_ppl()
    # observed trajectory: 30.7 -> 3.97 by epoch 7 (lr 0.02); the gate
    # leaves ~2x slack so it trips on regressions, not rng noise
    assert last < first / 3, (first, last)
    assert last < 8.0, last


@pytest.mark.parametrize("pos_encoding", ["learned", "rope"])
def test_transformer_lm_loss_gate(pos_encoding):
    """Seeded transformer LM: NLL must drop below half its initial
    value within 30 steps (flagship long-context family; reference
    pattern tests/python/train gates). Both position encodings gate."""
    from mxnet_tpu.models import transformer

    from tests._lm_utils import arith_corpus, lm_nll

    vocab, T, B = 32, 16, 16
    toks, labels = arith_corpus(B, T, vocab)

    sym = transformer.get_symbol(vocab, T, num_layers=1, num_heads=2,
                                 dim=32, pos_encoding=pos_encoding)
    step = make_train_step(sym, optimizer="adam")
    mx.random.seed(11)
    np.random.seed(11)
    state = step.init_state(Xavier(), {"data": (B, T),
                                       "softmax_label": (B, T)})
    rng = jax.random.PRNGKey(0)
    batch = step.place_batch({"data": toks, "softmax_label": labels})

    state, outs = step(state, batch, 3e-3, rng)
    first = lm_nll(outs, labels, vocab)
    for _ in range(30):
        state, outs = step(state, batch, 3e-3, rng)
    final = lm_nll(outs, labels, vocab)
    assert final < first / 2, (first, final)


def test_check_consistency_dtype_grid():
    """bf16-vs-f32 consistency matrix on a conv+matmul block — the
    dtype axis of the reference's check_consistency ctx_list."""
    import jax.numpy as jnp

    w = np.random.RandomState(7).randn(32, 64).astype(np.float32) * 0.1
    x = np.random.RandomState(8).randn(8, 32).astype(np.float32)

    def f(x, w):
        return jnp.tanh(x @ w).sum(axis=1)

    check_consistency(f, [x, w], dtypes=["bfloat16", "float16"])


def test_check_consistency_dtype_grid_catches_divergence():
    """The grid must FAIL when a function's bf16 path diverges beyond
    tolerance (guard against a vacuous gate)."""
    import jax.numpy as jnp

    def unstable(x):
        # catastrophic cancellation amplified: bf16 loses it entirely
        return (x + 1e4) - 1e4

    x = np.full((4,), 0.37, np.float32)
    with pytest.raises(AssertionError):
        check_consistency(unstable, [x], dtypes=["bfloat16"])


@pytest.mark.parametrize("name,fn,args", [
    ("softmax", lambda x: jax.nn.softmax(x, axis=-1),
     [np.linspace(-8, 8, 64, dtype=np.float32).reshape(8, 8)]),
    ("logsumexp",
     lambda x: jax.scipy.special.logsumexp(x, axis=-1),
     [np.linspace(-6, 6, 64, dtype=np.float32).reshape(8, 8)]),
    ("layernorm",
     lambda x: (x - x.mean(-1, keepdims=True))
     / ((x.var(-1, keepdims=True) + 1e-5) ** 0.5),
     [np.random.RandomState(0).randn(8, 32).astype(np.float32)]),
    ("gelu", lambda x: jax.nn.gelu(x),
     [np.linspace(-4, 4, 64, dtype=np.float32)]),
    ("attention-scores",
     lambda q, k: jax.nn.softmax(
         (q @ k.T) / np.sqrt(16), axis=-1),
     [np.random.RandomState(1).randn(8, 16).astype(np.float32) * 0.5,
      np.random.RandomState(2).randn(8, 16).astype(np.float32) * 0.5]),
])
def test_bf16_grid_risky_ops(name, fn, args):
    """The numerically risky kernels (softmax family, normalization,
    smooth activations) must stay within bf16 tolerance of their f32
    baselines — the dtype axis of the reference's check_consistency
    matrix applied where it matters most on TPU."""
    check_consistency(fn, args, dtypes=["bfloat16"])
