"""Caffe converter: prototxt + caffemodel -> symbol + params.

The .caffemodel in these tests is ENCODED BY HAND with a ~30-line
protobuf wire-format writer, so the test needs neither caffe nor
compiled bindings — it exercises the converter's real binary path
(varint fields, packed float blobs, BlobShape and legacy NCHW dims,
BatchNorm scale_factor semantics, the BatchNorm+Scale fusion) against
a numpy forward reference.
"""
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

sys.path.insert(0, __file__.rsplit("/", 2)[0] + "/tools")
import caffe_converter  # noqa: E402


# ---- minimal protobuf wire writer -----------------------------------------

def _v(n):
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        out += bytes([b | (0x80 if n else 0)])
        if not n:
            return out


def _ld(field, payload):
    return _v((field << 3) | 2) + _v(len(payload)) + payload


def _varint_field(field, val):
    return _v(field << 3) + _v(val)


def _blob(arr, legacy=False):
    arr = np.asarray(arr, "<f4")
    data = _ld(5, arr.tobytes())           # packed floats
    if legacy:
        dims = list(arr.shape)
        dims = [1] * (4 - len(dims)) + dims
        shape = b"".join(_varint_field(f, d)
                         for f, d in zip((1, 2, 3, 4), dims))
        return shape + data
    shape = _ld(7, b"".join(_varint_field(1, d) for d in arr.shape))
    return shape + data


def _layer(name, ltype, blobs=(), legacy_blob=False):
    msg = _ld(1, name.encode()) + _ld(2, ltype.encode())
    for b in blobs:
        msg += _ld(7, _blob(b, legacy=legacy_blob))
    return msg


def _net(layers):
    return b"".join(_ld(100, l) for l in layers)


# ---- the network under test -----------------------------------------------

PROTOTXT = """
name: "tiny"  # comment survives tokenizer
input: "data"
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 pad: 1 stride: 1 } }
layer { name: "bn1" type: "BatchNorm" bottom: "conv1" top: "bn1" }
layer { name: "scale1" type: "Scale" bottom: "bn1" top: "bn1"
  scale_param { bias_term: true } }
layer { name: "relu1" type: "ReLU" bottom: "bn1" top: "bn1" }
layer { name: "pool1" type: "Pooling" bottom: "bn1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layer { name: "fc" type: "InnerProduct" bottom: "pool1" top: "fc"
  inner_product_param { num_output: 5 } }
layer { name: "prob" type: "Softmax" bottom: "fc" top: "prob" }
"""


def _weights(rng):
    w = {
        "conv1_w": rng.randn(4, 3, 3, 3).astype(np.float32) * 0.3,
        "conv1_b": rng.randn(4).astype(np.float32) * 0.1,
        "bn_mean": rng.randn(4).astype(np.float32) * 0.2,
        "bn_var": rng.rand(4).astype(np.float32) + 0.5,
        "gamma": rng.rand(4).astype(np.float32) + 0.5,
        "beta": rng.randn(4).astype(np.float32) * 0.1,
    }
    w["fc_w"] = rng.randn(5, 4 * 4 * 4).astype(np.float32) * 0.2
    w["fc_b"] = rng.randn(5).astype(np.float32) * 0.1
    return w


def _caffemodel(w, scale_factor=2.0, legacy_blob=False):
    # caffe stores UNSCALED accumulators: blob/scale_factor = stats
    return _net([
        _layer("conv1", "Convolution",
               [w["conv1_w"], w["conv1_b"]], legacy_blob),
        _layer("bn1", "BatchNorm",
               [w["bn_mean"] * scale_factor, w["bn_var"] * scale_factor,
                np.array([scale_factor], np.float32)]),
        _layer("scale1", "Scale", [w["gamma"], w["beta"]]),
        _layer("fc", "InnerProduct", [w["fc_w"], w["fc_b"]]),
    ])


def _ref_conv3x3(x, kw, kb):
    """Naive 3x3/pad-1/stride-1 conv, the shared numpy reference."""
    C_out = kw.shape[0]
    N, _, H, W = x.shape
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    out = np.zeros((N, C_out, H, W), np.float32)
    for i in range(H):
        for j in range(W):
            patch = xp[:, :, i:i + 3, j:j + 3].reshape(N, -1)
            out[:, :, i, j] = patch @ kw.reshape(C_out, -1).T
    return out + kb[None, :, None, None]


def _numpy_forward(w, x):
    N, _, H, W = x.shape
    conv = _ref_conv3x3(x, w["conv1_w"], w["conv1_b"])
    bn = (conv - w["bn_mean"][None, :, None, None]) / np.sqrt(
        w["bn_var"][None, :, None, None] + 1e-5)
    bn = bn * w["gamma"][None, :, None, None] \
        + w["beta"][None, :, None, None]
    relu = np.maximum(bn, 0)
    pooled = relu.reshape(N, 4, H // 2, 2, W // 2, 2).max((3, 5))
    logits = pooled.reshape(N, -1) @ w["fc_w"].T + w["fc_b"]
    e = np.exp(logits - logits.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


@pytest.mark.parametrize("legacy_blob", [False, True])
def test_convert_matches_numpy(tmp_path, legacy_blob):
    rng = np.random.RandomState(0)
    w = _weights(rng)
    proto = tmp_path / "net.prototxt"
    proto.write_text(PROTOTXT)
    model = tmp_path / "net.caffemodel"
    model.write_bytes(_caffemodel(w, legacy_blob=legacy_blob))

    sym, arg_params, aux_params = caffe_converter.convert(
        str(proto), str(model))
    assert set(arg_params) == {"conv1_weight", "conv1_bias",
                               "bn1_gamma", "bn1_beta",
                               "fc_weight", "fc_bias"}
    assert set(aux_params) == {"bn1_moving_mean", "bn1_moving_var"}

    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",),
                        context=mx.cpu())
    mod.bind(data_shapes=[("data", x.shape)], label_shapes=None,
             for_training=False)
    mod.set_params(arg_params, aux_params)
    from mxnet_tpu import io
    mod.forward(io.DataBatch(data=[mx.nd.array(x)]), is_train=False)
    got = mod.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(got, _numpy_forward(w, x),
                               rtol=2e-4, atol=2e-4)


def test_checkpoint_roundtrip(tmp_path):
    """CLI path: converted checkpoint loads via load_checkpoint."""
    rng = np.random.RandomState(1)
    w = _weights(rng)
    proto = tmp_path / "net.prototxt"
    proto.write_text(PROTOTXT)
    model = tmp_path / "net.caffemodel"
    model.write_bytes(_caffemodel(w))
    prefix = str(tmp_path / "converted")
    caffe_converter.main(["caffe_converter.py", str(proto),
                          str(model), prefix])
    sym, arg_params, aux_params = mx.model.load_checkpoint(prefix, 0)
    assert "conv1_weight" in arg_params
    assert "bn1_moving_mean" in aux_params
    assert "softmax_output" in sym.list_outputs()


def test_unsupported_layer_is_loud(tmp_path):
    proto = tmp_path / "net.prototxt"
    proto.write_text('input: "data"\n'
                     'layer { name: "x" type: "Crazy" '
                     'bottom: "data" top: "x" }\n')
    with pytest.raises(NotImplementedError, match="Crazy"):
        caffe_converter.convert(str(proto), None)


def test_train_prototxt_with_label_top_and_lrn(tmp_path):
    """The TRAIN prototxt shape: a multi-top Data layer
    (top: "data" top: "label"), SoftmaxWithLoss consuming the label
    bottom, an Accuracy tail that must not dangle, and an LRN layer
    whose k parameter must reach the op (caffe k=1 vs the framework
    default knorm=2 — silently wrong activations if dropped)."""
    proto = tmp_path / "train.prototxt"
    proto.write_text("""
layer { name: "input" type: "Data" top: "data" top: "label" }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 pad: 1 } }
layer { name: "lrn1" type: "LRN" bottom: "conv1" top: "lrn1"
  lrn_param { local_size: 3 alpha: 0.1 beta: 0.75 k: 1.0 } }
layer { name: "fc" type: "InnerProduct" bottom: "lrn1" top: "fc"
  inner_product_param { num_output: 5 } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "fc"
  bottom: "label" top: "loss" }
layer { name: "acc" type: "Accuracy" bottom: "fc" bottom: "label"
  top: "acc" }
""")
    rng = np.random.RandomState(2)
    w = {"conv1_w": rng.randn(4, 3, 3, 3).astype(np.float32) * 0.3,
         "conv1_b": rng.randn(4).astype(np.float32) * 0.1,
         "fc_w": rng.randn(5, 4 * 6 * 6).astype(np.float32) * 0.2,
         "fc_b": rng.randn(5).astype(np.float32) * 0.1}
    model = tmp_path / "train.caffemodel"
    model.write_bytes(_net([
        _layer("conv1", "Convolution", [w["conv1_w"], w["conv1_b"]]),
        _layer("fc", "InnerProduct", [w["fc_w"], w["fc_b"]]),
    ]))
    sym, arg_params, aux_params = caffe_converter.convert(
        str(proto), str(model))
    # the label bottom becomes the loss's label input, not a param
    assert "label" in sym.list_arguments()
    assert "label" not in arg_params

    x = rng.randn(2, 3, 6, 6).astype(np.float32)
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("label",), context=mx.cpu())
    mod.bind(data_shapes=[("data", x.shape)], label_shapes=None,
             for_training=False)
    mod.set_params(arg_params, aux_params)
    from mxnet_tpu import io
    mod.forward(io.DataBatch(data=[mx.nd.array(x)]), is_train=False)
    got = mod.get_outputs()[0].asnumpy()

    # numpy reference incl. caffe LRN (k=1, across channels)
    conv = _ref_conv3x3(x, w["conv1_w"], w["conv1_b"])
    sq = conv ** 2
    n = 3
    den = np.zeros_like(conv)
    for c in range(4):
        lo, hi = max(0, c - n // 2), min(4, c + n // 2 + 1)
        den[:, c] = sq[:, lo:hi].sum(1)
    lrn = conv / (1.0 + (0.1 / n) * den) ** 0.75
    logits = lrn.reshape(2, -1) @ w["fc_w"].T + w["fc_b"]
    e = np.exp(logits - logits.max(-1, keepdims=True))
    want = e / e.sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_resnet_style_eltwise_and_global_pool(tmp_path):
    """Residual nets: Eltwise SUM joins two branches, global average
    pooling feeds the classifier — the converter must wire both (and
    a branch that reuses a bottom twice must not double-register)."""
    proto = tmp_path / "res.prototxt"
    proto.write_text("""
input: "data"
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 3 kernel_size: 3 pad: 1 } }
layer { name: "conv2" type: "Convolution" bottom: "conv1" top: "conv2"
  convolution_param { num_output: 3 kernel_size: 3 pad: 1 } }
layer { name: "sum" type: "Eltwise" bottom: "conv1" bottom: "conv2"
  top: "sum" eltwise_param { operation: SUM } }
layer { name: "gap" type: "Pooling" bottom: "sum" top: "gap"
  pooling_param { pool: AVE global_pooling: true } }
layer { name: "fc" type: "InnerProduct" bottom: "gap" top: "fc"
  inner_product_param { num_output: 4 } }
layer { name: "prob" type: "Softmax" bottom: "fc" top: "prob" }
""")
    rng = np.random.RandomState(3)
    w = {"c1": rng.randn(3, 3, 3, 3).astype(np.float32) * 0.3,
         "b1": rng.randn(3).astype(np.float32) * 0.1,
         "c2": rng.randn(3, 3, 3, 3).astype(np.float32) * 0.3,
         "b2": rng.randn(3).astype(np.float32) * 0.1,
         "fw": rng.randn(4, 3).astype(np.float32),
         "fb": rng.randn(4).astype(np.float32)}
    model = tmp_path / "res.caffemodel"
    model.write_bytes(_net([
        _layer("conv1", "Convolution", [w["c1"], w["b1"]]),
        _layer("conv2", "Convolution", [w["c2"], w["b2"]]),
        _layer("fc", "InnerProduct", [w["fw"], w["fb"]]),
    ]))
    sym, arg_params, aux_params = caffe_converter.convert(
        str(proto), str(model))

    x = rng.randn(2, 3, 5, 5).astype(np.float32)
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",),
                        context=mx.cpu())
    mod.bind(data_shapes=[("data", x.shape)], label_shapes=None,
             for_training=False)
    mod.set_params(arg_params, aux_params)
    from mxnet_tpu import io
    mod.forward(io.DataBatch(data=[mx.nd.array(x)]), is_train=False)
    got = mod.get_outputs()[0].asnumpy()

    c1 = _ref_conv3x3(x, w["c1"], w["b1"])
    s = c1 + _ref_conv3x3(c1, w["c2"], w["b2"])
    gap = s.mean((2, 3))
    logits = gap @ w["fw"].T + w["fb"]
    e = np.exp(logits - logits.max(-1, keepdims=True))
    np.testing.assert_allclose(got, e / e.sum(-1, keepdims=True),
                               rtol=2e-4, atol=2e-4)


def test_relu_negative_slope_becomes_leaky(tmp_path):
    """relu_param.negative_slope must survive conversion as a
    LeakyReLU — plain ReLU silently zeroes every negative activation."""
    proto = tmp_path / "leaky.prototxt"
    proto.write_text("""
input: "data"
layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
  inner_product_param { num_output: 4 } }
layer { name: "relu1" type: "ReLU" bottom: "fc" top: "fc"
  relu_param { negative_slope: 0.1 } }
layer { name: "prob" type: "Softmax" bottom: "fc" top: "prob" }
""")
    rng = np.random.RandomState(5)
    w = {"fc_w": rng.randn(4, 6).astype(np.float32),
         "fc_b": rng.randn(4).astype(np.float32)}
    model = tmp_path / "leaky.caffemodel"
    model.write_bytes(_net([
        _layer("fc", "InnerProduct", [w["fc_w"], w["fc_b"]])]))
    sym, arg_params, aux_params = caffe_converter.convert(
        str(proto), str(model))

    x = rng.randn(3, 6).astype(np.float32)
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",), context=mx.cpu())
    mod.bind(data_shapes=[("data", x.shape)], label_shapes=None,
             for_training=False)
    mod.set_params(arg_params, aux_params)
    from mxnet_tpu import io
    mod.forward(io.DataBatch(data=[mx.nd.array(x)]), is_train=False)
    got = mod.get_outputs()[0].asnumpy()

    z = x @ w["fc_w"].T + w["fc_b"]
    act = np.where(z >= 0, z, 0.1 * z)        # leaky, NOT rectified
    e = np.exp(act - act.max(-1, keepdims=True))
    np.testing.assert_allclose(got, e / e.sum(-1, keepdims=True),
                               rtol=2e-4, atol=2e-4)


def test_eltwise_coeff_applied(tmp_path):
    """Eltwise SUM coeff multipliers must be applied (coeff: 1, -1 is
    caffe's subtraction idiom); mismatched arity is loud."""
    proto = tmp_path / "coef.prototxt"
    proto.write_text("""
input: "data"
layer { name: "f1" type: "InnerProduct" bottom: "data" top: "f1"
  inner_product_param { num_output: 4 } }
layer { name: "f2" type: "InnerProduct" bottom: "data" top: "f2"
  inner_product_param { num_output: 4 } }
layer { name: "diff" type: "Eltwise" bottom: "f1" bottom: "f2"
  top: "diff" eltwise_param { operation: SUM coeff: 1.0 coeff: -1.0 } }
layer { name: "prob" type: "Softmax" bottom: "diff" top: "prob" }
""")
    rng = np.random.RandomState(6)
    w = {"w1": rng.randn(4, 6).astype(np.float32),
         "b1": rng.randn(4).astype(np.float32),
         "w2": rng.randn(4, 6).astype(np.float32),
         "b2": rng.randn(4).astype(np.float32)}
    model = tmp_path / "coef.caffemodel"
    model.write_bytes(_net([
        _layer("f1", "InnerProduct", [w["w1"], w["b1"]]),
        _layer("f2", "InnerProduct", [w["w2"], w["b2"]])]))
    sym, arg_params, aux_params = caffe_converter.convert(
        str(proto), str(model))

    x = rng.randn(3, 6).astype(np.float32)
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",), context=mx.cpu())
    mod.bind(data_shapes=[("data", x.shape)], label_shapes=None,
             for_training=False)
    mod.set_params(arg_params, aux_params)
    from mxnet_tpu import io
    mod.forward(io.DataBatch(data=[mx.nd.array(x)]), is_train=False)
    got = mod.get_outputs()[0].asnumpy()

    d = (x @ w["w1"].T + w["b1"]) - (x @ w["w2"].T + w["b2"])
    e = np.exp(d - d.max(-1, keepdims=True))
    np.testing.assert_allclose(got, e / e.sum(-1, keepdims=True),
                               rtol=2e-4, atol=2e-4)

    bad = tmp_path / "bad.prototxt"
    bad.write_text("""
input: "data"
layer { name: "c1" type: "Convolution" bottom: "data" top: "c1"
  convolution_param { num_output: 2 kernel_size: 1 } }
layer { name: "s" type: "Eltwise" bottom: "c1" bottom: "data"
  top: "s" eltwise_param { operation: SUM coeff: 0.5 } }
""")
    with pytest.raises(ValueError, match="coeff"):
        caffe_converter.convert(str(bad), None)


def test_v1_enum_layer_types_convert(tmp_path):
    """V1 prototxts (enum layer types, `layers { ... }`) get a real
    conversion; unsupported V1 enums get the upgrade-your-prototxt
    error instead of a generic unknown-layer message."""
    proto = tmp_path / "v1.prototxt"
    proto.write_text("""
input: "data"
layers { name: "conv1" type: CONVOLUTION bottom: "data" top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 pad: 1 } }
layers { name: "relu1" type: RELU bottom: "conv1" top: "conv1" }
layers { name: "fc" type: INNER_PRODUCT bottom: "conv1" top: "fc"
  inner_product_param { num_output: 5 } }
layers { name: "prob" type: SOFTMAX bottom: "fc" top: "prob" }
""")
    rng = np.random.RandomState(8)
    w = {"conv1_w": rng.randn(4, 3, 3, 3).astype(np.float32) * 0.3,
         "conv1_b": rng.randn(4).astype(np.float32) * 0.1,
         "fc_w": rng.randn(5, 4 * 6 * 6).astype(np.float32) * 0.2,
         "fc_b": rng.randn(5).astype(np.float32) * 0.1}
    model = tmp_path / "v1.caffemodel"
    model.write_bytes(_net([
        _layer("conv1", "Convolution", [w["conv1_w"], w["conv1_b"]]),
        _layer("fc", "InnerProduct", [w["fc_w"], w["fc_b"]])]))
    sym, arg_params, aux_params = caffe_converter.convert(
        str(proto), str(model))

    x = rng.randn(2, 3, 6, 6).astype(np.float32)
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",), context=mx.cpu())
    mod.bind(data_shapes=[("data", x.shape)], label_shapes=None,
             for_training=False)
    mod.set_params(arg_params, aux_params)
    from mxnet_tpu import io
    mod.forward(io.DataBatch(data=[mx.nd.array(x)]), is_train=False)
    got = mod.get_outputs()[0].asnumpy()

    conv = np.maximum(_ref_conv3x3(x, w["conv1_w"], w["conv1_b"]), 0)
    logits = conv.reshape(2, -1) @ w["fc_w"].T + w["fc_b"]
    e = np.exp(logits - logits.max(-1, keepdims=True))
    np.testing.assert_allclose(got, e / e.sum(-1, keepdims=True),
                               rtol=2e-4, atol=2e-4)

    bad = tmp_path / "v1bad.prototxt"
    bad.write_text('input: "data"\n'
                   'layers { name: "p" type: POWER bottom: "data" '
                   'top: "p" }\n')
    with pytest.raises(NotImplementedError, match="upgrade"):
        caffe_converter.convert(str(bad), None)
