"""Fleet survives replica death (docs/robustness.md §fleet failure
semantics).

Load-bearing acceptance gates:

* a pinned replica dying mid-generate (transport fault + failed
  probe) replays the request on a survivor token-for-token — greedy
  and seeded alike — and the admit-id dedup table makes a replay onto
  a replica that already admitted it exactly-once;
* a migrating ``recycle()`` / SIGTERM evacuation exports every active
  decode session (KV rows + emitted tokens + PRNG progress) and the
  resumed stream emits the remaining tokens bit-identically — f32,
  int8 (quantize_kv) and GQA caches included;
* the router never wedges on its own plumbing: the poller survives a
  ``poll_now`` exception, and a decode-role drain timeout fails OPEN
  to SUSPECT (revived by the next successful poll), never stranding
  the replica DRAINING.
"""
import signal
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.generation import Generator, replay_key
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.models import transformer
from mxnet_tpu.parallel import make_train_step
from mxnet_tpu.parallel.resilience import (FaultInjector,
                                           install_fault_injector)
from mxnet_tpu.serve import (ContinuousDecoder, ServeRouter,
                             ServeServer, SessionEvacuated)

pytestmark = pytest.mark.serve

V, L, H, DIM, T = 50, 2, 2, 32, 24


def _params(seed=0, num_kv_heads=None):
    sym = transformer.get_symbol(V, 12, num_layers=L, num_heads=H,
                                 dim=DIM, max_len=T,
                                 num_kv_heads=num_kv_heads)
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(seed)
    return step.init_state(Xavier(), {"data": (2, 12),
                                      "softmax_label": (2, 12)})[0]


@pytest.fixture(scope="module")
def params():
    return _params()


def _gen(params, batch_size, **kw):
    return Generator(params, V, T, num_layers=L, num_heads=H, dim=DIM,
                     batch_size=batch_size, **kw)


def _cval(name):
    e = telemetry.snapshot().get(name)
    return int(e["value"]) if e else 0


def _wait(pred, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError("timed out waiting for %s" % what)


class _Fleet:
    """Two real decode replicas behind a poll-less router —
    deterministic: tests drive poll_now() themselves."""

    def __init__(self, params, **genkw):
        self.decoders = [ContinuousDecoder(_gen(params, 2, **genkw))
                         for _ in range(2)]
        self.servers = [ServeServer(d) for d in self.decoders]
        self.router = ServeRouter(poll_ms=0)
        for i, s in enumerate(self.servers):
            self.router.add_replica(s.host, s.port,
                                    name="replica%d" % i)
        self.router.poll_now()

    def decoder_of(self, name):
        return self.decoders[int(name[-1])]

    def close(self):
        self.router.close()
        for s in self.servers:
            s.close()
        for d in self.decoders:
            d.close()


# -- (a) token-exact generate failover -----------------------------------
class TestFailover:
    @pytest.mark.parametrize("sampling", [
        {"temperature": 0.0},
        pytest.param({"temperature": 0.8, "top_k": 8, "seed": 3},
                     marks=pytest.mark.slow)], ids=["greedy",
                                                    "seeded"])
    def test_dead_pin_replays_on_survivor_token_exact(self, params,
                                                      sampling):
        """Transport fault + failed probe on the pinned replica =
        dead: the retained recovery record replays on the survivor,
        byte-equal to the unfaulted run; the pin moves."""
        p = np.arange(1, 5)
        want = _gen(params, 1).generate(p[None], 6, eos_id=0,
                                        **sampling)[0]
        f = _Fleet(params)
        f0, r0 = (_cval("serve.router.failovers"),
                  _cval("serve.router.replays"))
        try:
            out = f.router.generate(p, 6, eos_id=0, session="s",
                                    **sampling)
            np.testing.assert_array_equal(out, want)
            pin = f.router.sessions()["s"]
            idx = int(pin[-1])
            # data AND control transport dead = the process is gone
            install_fault_injector(FaultInjector(
                "router%d_send:drop@1x*;router%d_ctl_send:drop@1x*"
                % (idx, idx)))
            try:
                out2 = f.router.generate(p, 6, eos_id=0, session="s",
                                         **sampling)
            finally:
                install_fault_injector(None)
            np.testing.assert_array_equal(out2, want)
            assert f.router.sessions()["s"] != pin
            assert _cval("serve.router.failovers") == f0 + 1
            assert _cval("serve.router.replays") == r0 + 1
        finally:
            f.close()

    def test_transient_fault_replays_exactly_once(self, params):
        """A reply lost AFTER the replica admitted (recv drop, probe
        fine): the replay carries the same admit id, the dedup table
        rides the original admission — admitted moves by ONE."""
        p = np.arange(2, 7)
        want = _gen(params, 1).generate(p[None], 5, eos_id=0,
                                        temperature=0.8, top_k=8,
                                        seed=11)[0]
        f = _Fleet(params)
        r0 = _cval("serve.router.replays")
        try:
            out = f.router.generate(p, 5, eos_id=0, temperature=0.8,
                                    top_k=8, seed=11, session="s")
            np.testing.assert_array_equal(out, want)
            pin = f.router.sessions()["s"]
            dec = f.decoder_of(pin)
            before = dec.stats()
            install_fault_injector(FaultInjector(
                "router%d_recv:drop@1" % int(pin[-1])))
            try:
                out2 = f.router.generate(p, 5, eos_id=0,
                                         temperature=0.8, top_k=8,
                                         seed=11, session="s")
            finally:
                install_fault_injector(None)
            np.testing.assert_array_equal(out2, want)
            after = dec.stats()
            assert after["admitted"] - before["admitted"] == 1
            assert after["deduped"] - before["deduped"] == 1
            assert f.router.sessions()["s"] == pin   # same replica
            assert _cval("serve.router.replays") == r0 + 1
        finally:
            f.close()

    def test_dedup_returns_same_future(self, params):
        """Decoder-level exactly-once contract: the same admit id
        resubmitted returns the ORIGINAL future, no second slot."""
        with _gen(params, 2).serving_decoder() as dec:
            f1 = dec.submit(np.arange(1, 5), 4, eos_id=0,
                            admit_id="cid:1")
            f2 = dec.submit(np.arange(1, 5), 4, eos_id=0,
                            admit_id="cid:1")
            assert f1 is f2
            f1.result(120.0)
            st = dec.stats()
            assert st["deduped"] == 1
            assert st["admitted"] == 1


# -- (b) live session migration ------------------------------------------
class TestMigration:
    def _evacuate_resume_parity(self, params, **genkw):
        """Core migration invariant, no router: evacuate mid-decode,
        resume the exported state on a SECOND pool, remaining tokens
        bit-identical; the PRNG re-derives by advancing the same
        splits."""
        single = _gen(params, 1, **genkw)
        p = np.arange(1, 6)
        want = single.generate(p[None], 8, temperature=0.8, top_k=8,
                               seed=7)[0]
        d1 = _gen(params, 2, **genkw).serving_decoder()
        d2 = _gen(params, 2, **genkw).serving_decoder()
        try:
            fut = d1.submit(p, 8, temperature=0.8, top_k=8, seed=7)
            _wait(lambda: len(fut.emitted) >= 3, what="3 emitted")
            assert d1.evacuate() == 1
            with pytest.raises(SessionEvacuated) as ei:
                fut.result(10.0)
            state = ei.value.state
            k = len(state["emitted"])
            assert k >= 3
            # export position = prompt + emitted - 1 (the last emitted
            # token is still pending, not yet fed)
            assert state["kv_blob"]["pos"] == len(p) + k - 1
            got = d2.submit(p, 8, temperature=0.8, top_k=8, seed=7,
                            resume=state).result(120.0)
            np.testing.assert_array_equal(got, want)
            st = d2.stats()
            assert st["resumed"] == 1
            assert st["prefills"] == 0    # scatter-only admission
            assert d1.stats()["evacuated"] == 1
            assert d1.stats()["finished"] == 0
        finally:
            d1.close()
            d2.close()

    def test_evacuate_resume_parity_f32(self, params):
        self._evacuate_resume_parity(params)

    @pytest.mark.slow
    def test_evacuate_resume_parity_bf16(self, params):
        self._evacuate_resume_parity(params, dtype="bfloat16")

    @pytest.mark.slow
    def test_evacuate_resume_parity_int8_kv_gqa(self):
        params = _params(seed=5, num_kv_heads=1)
        self._evacuate_resume_parity(params, quantize_kv=True,
                                     num_kv_heads=1)

    def test_replay_key_advances_splits(self):
        """replay_key(seed, k) == the key generate() holds after k
        picks — the invariant the resume path rests on."""
        import jax
        key = jax.random.PRNGKey(7)
        for k in range(4):
            np.testing.assert_array_equal(
                np.asarray(replay_key(7, k)), np.asarray(key))
            key, _ = jax.random.split(key)

    def test_migrating_recycle_completes_without_drain(self, params):
        """recycle() of a decode replica with an active session
        migrates it to the survivor mid-sequence (bounded by
        export+import, not by the sequence finishing) and the
        completed row is bit-identical."""
        p = np.arange(1, 4)
        want = _gen(params, 1).generate(p[None], 12, temperature=0.8,
                                        top_k=8, seed=9)[0]
        f = _Fleet(params)
        m0, e0 = (_cval("serve.router.migrations"),
                  _cval("serve.router.evacuations"))
        out = {}
        try:
            t = threading.Thread(target=lambda: out.update(
                row=f.router.generate(p, 12, temperature=0.8,
                                      top_k=8, seed=9, session="m")))
            t.start()
            _wait(lambda: "m" in f.router.sessions()
                  and any(d.stats()["active"]
                          for d in f.decoders), what="admission")
            victim = f.router.sessions()["m"]
            f.router.recycle(victim)
            t.join(60.0)
            assert not t.is_alive()
            np.testing.assert_array_equal(out["row"], want)
            # the victim exported the session MID-FLIGHT (only active
            # sessions export — recycle did not wait for the sequence
            # to finish), and exactly one resume completed it. Where
            # the resume lands is a race the contract doesn't pin:
            # usually the survivor, but a fast readmission makes the
            # recycled victim itself a legal target.
            assert f.decoder_of(victim).stats()["evacuated"] == 1
            assert sum(d.stats()["resumed"] for d in f.decoders) == 1
            assert sum(d.stats()["finished"] for d in f.decoders) == 1
            assert _cval("serve.router.migrations") == m0 + 1
            assert _cval("serve.router.evacuations") == e0 + 1
        finally:
            f.close()

    @pytest.mark.slow
    def test_sigterm_evacuates_instead_of_killing(self, params):
        """A polite SIGTERM on a decode replica exports its active
        sessions (the caller gets SessionEvacuated, resumable
        elsewhere) instead of killing them, and drains the pool."""
        # pin a benign base handler first: GracefulShutdown CHAINS
        # whatever is installed, and earlier tests in a full-suite run
        # leave process-exiting handlers behind (bench_serve's death
        # stub) that a real SIGTERM would otherwise reach
        prev = signal.signal(signal.SIGTERM, lambda *_a: None)
        d1 = ContinuousDecoder(_gen(params, 2), install_sigterm=True)
        d2 = _gen(params, 2).serving_decoder()
        try:
            p = np.arange(1, 6)
            want = _gen(params, 1).generate(p[None], 8,
                                            temperature=0.8, top_k=8,
                                            seed=4)[0]
            fut = d1.submit(p, 8, temperature=0.8, top_k=8, seed=4)
            _wait(lambda: len(fut.emitted) >= 2, what="2 emitted")
            import os
            os.kill(os.getpid(), signal.SIGTERM)
            with pytest.raises(SessionEvacuated) as ei:
                fut.result(10.0)
            got = d2.submit(p, 8, temperature=0.8, top_k=8, seed=4,
                            resume=ei.value.state).result(120.0)
            np.testing.assert_array_equal(got, want)
            # SIGTERM = the process is going away: pool drains
            _wait(lambda: d1.stats()["evacuated"] == 1,
                  what="evacuation stat")
            from mxnet_tpu.serve.engine import EngineClosed
            with pytest.raises(EngineClosed):
                d1.submit(p, 4)
        finally:
            d1.close()
            d2.close()
            signal.signal(signal.SIGTERM, prev)

    def test_resume_rejects_wrong_prompt_and_handoff_mix(self, params):
        with _gen(params, 2).serving_decoder() as d1, \
                _gen(params, 2).serving_decoder() as d2:
            fut = d1.submit(np.arange(1, 6), 8, temperature=0.8,
                            top_k=8, seed=7)
            _wait(lambda: len(fut.emitted) >= 2, what="2 emitted")
            d1.evacuate()
            with pytest.raises(SessionEvacuated) as ei:
                fut.result(10.0)
            state = ei.value.state
            with pytest.raises(ValueError, match="prompt"):
                d2.submit(np.arange(2, 7), 8, resume=state)
            # args must RESTATE the migrated request — a silently
            # diverging resume is a loud error instead
            with pytest.raises(ValueError, match="restate"):
                d2.submit(np.arange(1, 6), 8, temperature=0.8,
                          top_k=4, seed=7, resume=state)
            with pytest.raises(ValueError, match="restate"):
                d2.submit(np.arange(1, 6), 8, resume=state)
            with pytest.raises(ValueError, match="mutually"):
                d2.submit(np.arange(1, 6), 8, resume=state,
                          handoff={"first_token": 1, "kv_blob": None,
                                   "pos": 5})


# -- router plumbing robustness (satellites) -----------------------------
class _StuckEngine:
    """Engine-shaped stub: a decode-role replica whose engine forever
    reports one in-flight sequence (a wedged drain, distilled)."""

    role = "decode"

    def __init__(self, in_flight=1):
        self.in_flight = in_flight

    def introspect(self):
        return {"in_flight": self.in_flight, "queue_depth": 0,
                "draining": False, "warmed": [], "buckets": []}

    def evacuate(self):
        return 0                          # nothing active to export


class TestRouterPlumbing:
    def test_poller_survives_poll_now_exception(self):
        router = ServeRouter(poll_ms=5)
        try:
            calls = {"n": 0, "after_failure": 0}
            orig = router.poll_now

            def flaky():
                calls["n"] += 1
                if calls["n"] <= 3:
                    raise RuntimeError("injected poll failure")
                calls["after_failure"] += 1
                return orig()

            router.poll_now = flaky
            _wait(lambda: calls["after_failure"] >= 2,
                  what="poller recovery")
            assert router._poll_thread.is_alive()
        finally:
            router.close()

    def test_decode_drain_timeout_fails_open_to_suspect(self):
        """A decode-role replica that cannot drain parks SUSPECT
        (never stranded DRAINING), replicas_live drops, and the next
        successful poll revives it."""
        stuck, idle = _StuckEngine(1), _StuckEngine(0)
        s1, s2 = ServeServer(stuck), ServeServer(idle)
        router = ServeRouter(poll_ms=0)
        try:
            router.add_replica(s1.host, s1.port, name="stuck")
            router.add_replica(s2.host, s2.port, name="idle")
            router.poll_now()
            assert _cval("serve.router.replicas_live") == 2
            with pytest.raises(TimeoutError, match="drain budget"):
                router.recycle("stuck", timeout=0.3, warm=False)
            reps = router.replicas()
            assert reps["stuck"]["state"] == "suspect"
            assert _cval("serve.router.replicas_live") == 1
            stuck.in_flight = 0           # the wedge clears
            router.poll_now()             # ...and the poll revives it
            assert router.replicas()["stuck"]["state"] == "live"
            assert _cval("serve.router.replicas_live") == 2
        finally:
            router.close()
            s1.close()
            s2.close()


# -- MXNET_FAULT_SPEC validation + the kill family (satellites) ----------
class TestFaultSpecValidation:
    def test_unknown_wire_point_raises(self):
        with pytest.raises(ValueError, match="unknown injection point"):
            FaultInjector("serve_snd:drop@1")

    def test_router_family_points_accepted(self):
        FaultInjector("router3_ctl_recv:drop@1;router0_send:delay@2:0.1")

    def test_kill_as_wire_point_rejected(self):
        # `kill1:drop@2` parses as a WIRE rule naming point "kill1" —
        # the validation catches it (the kill family is step-indexed)
        with pytest.raises(ValueError, match="unknown injection point"):
            FaultInjector("kill1:drop@2")

    def test_bad_rule_still_actionable(self):
        with pytest.raises(ValueError,
                           match="bad MXNET_FAULT_SPEC rule"):
            FaultInjector("kill@")

    def test_kill_family_parses_and_ticks(self):
        inj = FaultInjector("kill1@3")
        assert [inj.on_chaos_tick("kill1") for _ in range(4)] == \
            [False, False, True, False]
        # distinct points count independently
        inj = FaultInjector("kill0@1;kill2@2x2")
        assert inj.on_chaos_tick("kill0") is True
        assert [inj.on_chaos_tick("kill2") for _ in range(4)] == \
            [False, True, True, False]
