"""Image pipeline tests — reference: tests/python/unittest/test_image.py
+ test_io.py ImageRecordIter coverage."""
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as img_mod
from mxnet_tpu import recordio


def _make_img(h=40, w=48, seed=0):
    rng = np.random.RandomState(seed)
    # smooth gradient + noise so jpeg survives roughly
    yy, xx = np.mgrid[0:h, 0:w]
    base = np.stack([yy * 255 // h, xx * 255 // w,
                     (yy + xx) * 255 // (h + w)], axis=2)
    return np.clip(base + rng.randint(0, 20, (h, w, 3)), 0,
                   255).astype(np.uint8)


def _encode(arr):
    from io import BytesIO
    from PIL import Image
    buf = BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return buf.getvalue()


def test_imdecode_imresize():
    arr = _make_img()
    decoded = img_mod.imdecode(_encode(arr))
    np.testing.assert_array_equal(decoded.asnumpy(), arr)
    small = img_mod.imresize(decoded, 16, 12)
    assert small.shape == (12, 16, 3)


def test_crops():
    arr = mx.nd.array(_make_img(), dtype=np.uint8)
    out, rect = img_mod.center_crop(arr, (24, 24))
    assert out.shape == (24, 24, 3)
    out, rect = img_mod.random_crop(arr, (24, 24))
    assert out.shape == (24, 24, 3)
    out = img_mod.resize_short(arr, 20)
    assert min(out.shape[:2]) == 20


def test_augmenter_list():
    augs = img_mod.CreateAugmenter((3, 24, 24), rand_crop=True,
                                   rand_mirror=True, mean=True, std=True,
                                   brightness=0.1)
    arr = mx.nd.array(_make_img(), dtype=np.uint8)
    data = arr
    for aug in augs:
        data = aug(data)[0]
    assert data.shape == (24, 24, 3)
    assert data.dtype == np.float32


def _write_rec(tmp, n=12):
    rec = os.path.join(tmp, "data.rec")
    idx = os.path.join(tmp, "data.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        packed = recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0),
            _make_img(seed=i), img_fmt=".png")
        w.write_idx(i, packed)
    w.close()
    return rec


def test_image_iter_rec():
    with tempfile.TemporaryDirectory() as tmp:
        rec = _write_rec(tmp)
        it = img_mod.ImageIter(batch_size=4, data_shape=(3, 24, 24),
                               path_imgrec=rec, shuffle=True,
                               rand_crop=True, rand_mirror=True)
        batch = next(it)
        assert batch.data[0].shape == (4, 3, 24, 24)
        assert batch.label[0].shape == (4,)
        n = 1 + sum(1 for _ in it)
        assert n == 3
        it.reset()
        assert sum(1 for _ in it) == 3


def test_image_record_iter_factory():
    with tempfile.TemporaryDirectory() as tmp:
        rec = _write_rec(tmp)
        it = mx.io.ImageRecordIter(
            path_imgrec=rec, data_shape=(3, 24, 24), batch_size=4,
            shuffle=False, rand_mirror=True, mean_r=123, mean_g=117,
            mean_b=104, preprocess_threads=2)
        batch = next(it)
        assert batch.data[0].shape == (4, 3, 24, 24)


def test_det_iter():
    with tempfile.TemporaryDirectory() as tmp:
        rec = os.path.join(tmp, "det.rec")
        idx = os.path.join(tmp, "det.idx")
        w = recordio.MXIndexedRecordIO(idx, rec, "w")
        for i in range(8):
            # label: header_width=2, obj_width=5, one object
            label = np.array([2, 5, i % 3, 0.1, 0.2, 0.8, 0.9],
                             np.float32)
            packed = recordio.pack_img(
                recordio.IRHeader(0, label, i, 0), _make_img(seed=i),
                img_fmt=".png")
            w.write_idx(i, packed)
        w.close()
        it = img_mod.ImageDetIter(batch_size=4, data_shape=(3, 24, 24),
                                  path_imgrec=rec, rand_mirror=True)
        batch = next(it)
        assert batch.data[0].shape == (4, 3, 24, 24)
        assert batch.label[0].shape == (4, 16, 5)
        lbl = batch.label[0].asnumpy()
        valid = lbl[lbl[:, :, 0] >= 0]
        assert valid.shape[0] >= 4  # one object per image survived


def test_im2rec_roundtrip():
    from PIL import Image
    with tempfile.TemporaryDirectory() as tmp:
        root = os.path.join(tmp, "imgs")
        for cls in ["a", "b"]:
            os.makedirs(os.path.join(root, cls))
            for i in range(3):
                Image.fromarray(_make_img(seed=i)).save(
                    os.path.join(root, cls, "%d.jpg" % i))
        prefix = os.path.join(tmp, "pack")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=repo)
        subprocess.run([sys.executable,
                        os.path.join(repo, "tools", "im2rec.py"),
                        prefix, root, "--list"], check=True, env=env)
        subprocess.run([sys.executable,
                        os.path.join(repo, "tools", "im2rec.py"),
                        prefix, root], check=True, env=env)
        assert os.path.exists(prefix + ".rec")
        it = img_mod.ImageIter(batch_size=2, data_shape=(3, 24, 24),
                               path_imgrec=prefix + ".rec")
        batch = next(it)
        assert batch.data[0].shape == (2, 3, 24, 24)


def test_mnist_iter_synthetic():
    """MNISTIter reads idx-ubyte files (write synthetic ones)."""
    import gzip
    import struct
    with tempfile.TemporaryDirectory() as tmp:
        img_p = os.path.join(tmp, "train-images-idx3-ubyte")
        lbl_p = os.path.join(tmp, "train-labels-idx1-ubyte")
        n = 20
        imgs = (np.random.RandomState(0).rand(n, 28, 28) * 255).astype(
            np.uint8)
        lbls = np.arange(n, dtype=np.uint8) % 10
        with open(img_p, "wb") as f:
            f.write(struct.pack(">IIII", 2051, n, 28, 28))
            f.write(imgs.tobytes())
        with open(lbl_p, "wb") as f:
            f.write(struct.pack(">II", 2049, n))
            f.write(lbls.tobytes())
        it = mx.io.MNISTIter(image=img_p, label=lbl_p, batch_size=5,
                             shuffle=False)
        batch = next(it)
        assert batch.data[0].shape == (5, 1, 28, 28)
        assert float(batch.data[0].asnumpy().max()) <= 1.0


def test_csv_iter():
    with tempfile.TemporaryDirectory() as tmp:
        data_csv = os.path.join(tmp, "d.csv")
        label_csv = os.path.join(tmp, "l.csv")
        np.savetxt(data_csv, np.arange(24).reshape(8, 3), delimiter=",")
        np.savetxt(label_csv, np.arange(8), delimiter=",")
        it = mx.io.CSVIter(data_csv=data_csv, data_shape=(3,),
                           label_csv=label_csv, batch_size=4)
        batch = next(it)
        assert batch.data[0].shape == (4, 3)


def test_native_image_pipeline_matches_python():
    """The native C++ decode+crop+resize path must agree with the PIL
    pipeline on deterministic (center-crop, no-mirror) settings; random
    settings must produce valid batches of the right shape/stats."""
    from mxnet_tpu import config
    from mxnet_tpu.image import native_decode
    if not native_decode.available():
        pytest.skip("native image decoder unavailable")
    import tempfile

    from PIL import Image as PILImage

    rng = np.random.RandomState(0)
    tmp = tempfile.mkdtemp()
    rec_path = os.path.join(tmp, "imgs.rec")
    idx_path = os.path.join(tmp, "imgs.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(8):
        arr = (rng.rand(40 + i, 50 + i, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), arr,
            img_fmt=".png"))
    w.close()

    def batch(native):
        config.set_override("MXNET_NATIVE_IMAGE", "1" if native else "0")
        try:
            it = img_mod.ImageIter(batch_size=8, data_shape=(3, 24, 24),
                                 path_imgrec=rec_path, shuffle=False,
                                 inter_method=1)
            assert bool(it._native) == native
            return it.next()
        finally:
            config.clear_override("MXNET_NATIVE_IMAGE")

    b_native = batch(True)
    b_python = batch(False)
    np.testing.assert_array_equal(b_native.label[0].asnumpy(),
                                  b_python.label[0].asnumpy())
    a = b_native.data[0].asnumpy()
    b = b_python.data[0].asnumpy()
    assert a.shape == b.shape == (8, 3, 24, 24)
    # one-pass bilinear vs PIL bilinear: close but not bit-equal
    assert np.abs(a - b).mean() < 8.0
    assert np.corrcoef(a.ravel(), b.ravel())[0, 1] > 0.97

    # randomized settings still produce the declared shape
    config.set_override("MXNET_NATIVE_IMAGE", "1")
    try:
        it = img_mod.ImageIter(batch_size=8, data_shape=(3, 24, 24),
                             path_imgrec=rec_path, rand_crop=True,
                             rand_mirror=True, mean=True, std=True)
        assert it._native
        out = it.next().data[0].asnumpy()
    finally:
        config.clear_override("MXNET_NATIVE_IMAGE")
    assert out.shape == (8, 3, 24, 24)
    assert abs(out.mean()) < 3.0      # normalized scale
