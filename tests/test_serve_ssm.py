"""SSM blocks through the serving stack (ISSUE 19: O(1)-cache
decode): the ContinuousDecoder slot pool holding constant-size
recurrent state blobs instead of (max_len, ...) KV rows.

Load-bearing acceptance gates: ragged pool decode == batch-1 generate
token-for-token across slot turnover (greedy AND seeded sampling),
ONE compiled (B, 1) program across that turnover
(serve.decode.jit_cache_size stays 1 — SSM needs no per-row twin at
all), export/import round-trip exactness including mid-decode
migration, and the O(1) wire property: handoff blob bytes constant in
prompt length.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.generation import Generator, kv_blob_nbytes
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.models import transformer
from mxnet_tpu.parallel import make_train_step
from mxnet_tpu.serve import PrefillEngine, SessionEvacuated

pytestmark = pytest.mark.serve

V, L, H, DIM, T, B = 50, 2, 2, 32, 24, 3


def _params(block_type="ssm", seed=0):
    sym = transformer.get_symbol(V, 12, num_layers=L, num_heads=H,
                                 dim=DIM, max_len=T,
                                 block_type=block_type)
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(seed)
    state = step.init_state(Xavier(), {"data": (2, 12),
                                       "softmax_label": (2, 12)})
    return state[0]


@pytest.fixture(scope="module")
def params():
    return _params()


@pytest.fixture(scope="module")
def mixed_params():
    return _params(block_type=("attention", "ssm"), seed=1)


def _gen(params, batch_size, block_type="ssm", **kw):
    return Generator(params, V, T, num_layers=L, num_heads=H, dim=DIM,
                     batch_size=batch_size, block_type=block_type,
                     **kw)


class TestParity:
    def test_greedy_matches_static_generate_ragged(self, params):
        """ACCEPTANCE: 7 ragged requests through a 3-slot SSM pool ==
        static per-sequence generate, token for token, with slot
        turnover — and the whole workload compiles ONE (B, 1) step."""
        pool = _gen(params, B)
        single = _gen(params, 1)
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, V, (p,)) for p in
                   (4, 6, 4, 5, 4, 6, 7)]
        maxnew = [8, 3, 12, 5, 2, 9, 4]
        with pool.serving_decoder() as dec:
            futs = [dec.submit(p, n, eos_id=0)
                    for p, n in zip(prompts, maxnew)]
            got = [f.result(120.0) for f in futs]
            st = dec.stats()
        for i, (p, n) in enumerate(zip(prompts, maxnew)):
            np.testing.assert_array_equal(
                got[i], single.generate(p[None], n, eos_id=0)[0])
        assert st["finished"] == len(prompts) > B   # turnover happened
        # the tentpole's serving invariant: slot membership changed
        # many times and the decode step never recompiled
        assert telemetry.gauge(
            "serve.decode.jit_cache_size").value == 1

    def test_sampled_matches_batch1_generate(self, params):
        pool = _gen(params, B)
        single = _gen(params, 1)
        rng = np.random.RandomState(9)
        prompt = rng.randint(0, V, (5,))
        with pool.serving_decoder() as dec:
            other = [dec.submit(rng.randint(0, V, (4,)), 10)
                     for _ in range(2)]
            got = dec.submit(prompt, 6, temperature=0.8, top_k=5,
                             seed=42).result(120.0)
            for o in other:
                o.result(120.0)
        want = single.generate(prompt[None], 6, temperature=0.8,
                               top_k=5, seed=42)[0]
        np.testing.assert_array_equal(got, want)

    def test_mixed_stack_greedy_parity(self, mixed_params):
        """Attention + SSM layers in one stack: KV rows and state
        blobs live side by side in the same slot pool."""
        bt = ("attention", "ssm")
        pool = _gen(mixed_params, 2, block_type=bt)
        single = _gen(mixed_params, 1, block_type=bt)
        rng = np.random.RandomState(5)
        prompts = [rng.randint(0, V, (p,)) for p in (3, 6, 4)]
        with pool.serving_decoder() as dec:
            got = [dec.submit(p, n).result(120.0)
                   for p, n in zip(prompts, (9, 4, 6))]
        for p, n, g in zip(prompts, (9, 4, 6), got):
            np.testing.assert_array_equal(
                g, single.generate(p[None], n)[0])


class TestSlotAccounting:
    def test_bytes_per_slot_state_agnostic(self, params):
        """Generator.state_bytes_per_slot() == the live pool's
        measured figure == the kv_bytes_per_slot gauge — one number
        for sizing whether the state is KV rows or an SSM blob, and
        for SSM it never mentions max_len."""
        gen = _gen(params, B)
        hd = DIM // H
        want = L * H * hd * hd * 4
        assert gen.state_bytes_per_slot() == want
        g = telemetry.gauge("serve.decode.kv_bytes_per_slot")
        with gen.serving_decoder() as dec:
            assert dec._kv_bytes_per_slot == want
            assert g.value == want
            report = dec.describe(hbm_budget=want * 10 + 1)
            assert "ssm state" in report
            assert "kv_bytes_per_slot: %d" % want in report
            assert "10 slot(s) fit" in report

    def test_ssm_slot_beats_attention_slot(self, params):
        """The capacity prize in miniature: even at this toy max_len
        the SSM slot is smaller; the ratio grows linearly with
        max_len (benchmark/bench_decode.py measures the flagship)."""
        attn = Generator(_params(block_type="attention", seed=2),
                         V, T, num_layers=L, num_heads=H, dim=DIM,
                         batch_size=2)
        ssm = _gen(params, 2)
        assert ssm.state_bytes_per_slot() < \
            attn.state_bytes_per_slot()


class TestHandoff:
    def test_disagg_handoff_parity_and_o1_bytes(self, params):
        """Prefill-replica handoff into an SSM decode pool: replies
        match the colocated path, and the blob on the wire is the
        SAME bytes for a 4-token and a 12-token prompt — the O(1)
        handoff the attention path can't have."""
        single = _gen(params, 1)
        pre = PrefillEngine(_gen(params, 2))
        rng = np.random.RandomState(7)
        p_short = rng.randint(0, V, (4,))
        p_long = rng.randint(0, V, (12,))
        h_short = pre.prefill(p_short)
        h_long = pre.prefill(p_long)
        assert kv_blob_nbytes(h_short["kv_blob"]) == \
            kv_blob_nbytes(h_long["kv_blob"])
        with _gen(params, B).serving_decoder() as dec:
            for p, h, n in ((p_short, h_short, 6), (p_long, h_long, 4)):
                got = dec.submit(p, n, handoff=h).result(120.0)
                np.testing.assert_array_equal(
                    got, single.generate(p[None], n)[0])
            assert dec.stats()["prefills"] == 0

    def test_coalesced_prefill_splits_mixed_lengths(self, params):
        """A mixed-length coalesced group must NOT right-pad under
        SSM (padding would be absorbed into the recurrent state):
        _run_group splits it into per-length subgroups whose replies
        are exactly the solo replies."""
        from mxnet_tpu.serve.prefill import _PendingPrefill
        eng = PrefillEngine(_gen(params, 2))
        rng = np.random.RandomState(11)
        p4 = rng.randint(0, V, (4,))
        p6 = rng.randint(0, V, (6,))
        group = [_PendingPrefill(np.asarray(p, np.int64), 0.0, None,
                                 None, 0) for p in (p4, p6)]
        eng._run_group(group)
        for g, p in zip(group, (p4, p6)):
            assert g.exc is None
            solo = eng.prefill(p)
            tok, blob, _ = g.out
            assert tok == solo["first_token"]
            for name, arr in solo["kv_blob"]["rows"].items():
                np.testing.assert_array_equal(
                    np.asarray(arr),
                    np.asarray(blob["rows"][name]))

    def test_migration_round_trip_mid_decode(self, params):
        """Evacuate a seeded session mid-decode, resume it on a
        second pool: remaining tokens bit-identical — the state blob
        round-trips exactly and the PRNG re-derives its splits."""
        import time
        single = _gen(params, 1)
        p = np.arange(1, 6)
        want = single.generate(p[None], 8, temperature=0.8, top_k=8,
                               seed=7)[0]
        d1 = _gen(params, 2).serving_decoder()
        d2 = _gen(params, 2).serving_decoder()
        try:
            fut = d1.submit(p, 8, temperature=0.8, top_k=8, seed=7)
            deadline = time.time() + 60.0
            while len(fut.emitted) < 3:
                assert time.time() < deadline, "3 emitted tokens"
                time.sleep(0.01)
            assert d1.evacuate() == 1
            with pytest.raises(SessionEvacuated) as ei:
                fut.result(10.0)
            state = ei.value.state
            # the exported blob is the O(1) state: one (H, hd, hd)
            # f32 blob per layer, whatever pos it was exported at
            hd = DIM // H
            for name, arr in state["kv_blob"]["rows"].items():
                assert arr.shape == (H, hd, hd)
                assert arr.dtype == np.float32
            got = d2.submit(p, 8, temperature=0.8, top_k=8, seed=7,
                            resume=state).result(120.0)
            np.testing.assert_array_equal(got, want)
            assert d2.stats()["resumed"] == 1
            assert d2.stats()["prefills"] == 0
        finally:
            d1.close()
            d2.close()


class TestRefusals:
    def test_explicit_draft_refused(self, params):
        gen = _gen(params, 2)
        attn_draft = Generator(_params(block_type="attention", seed=2),
                               V, T, num_layers=L, num_heads=H,
                               dim=DIM, batch_size=2)
        with pytest.raises(ValueError, match="speculative"):
            gen.serving_decoder(draft=attn_draft)

    def test_env_draft_refused(self, params, monkeypatch):
        monkeypatch.setenv("MXNET_SPEC_DRAFT", "layers=1")
        with pytest.raises(ValueError, match="speculative"):
            _gen(params, 2).serving_decoder()

    def test_rolling_cache_refused(self, params):
        with pytest.raises(ValueError, match="rolling_cache"):
            _gen(params, 2, rolling_cache=True)

    def test_streaming_works(self, params):
        """Streaming frames ride the ordinary _emit path — SSM slots
        change nothing (one quick end-to-end check)."""
        pool = _gen(params, 2)
        single = _gen(params, 1)
        p = np.arange(2, 7)
        frames = []
        with pool.serving_decoder() as dec:
            row = dec.handle_generate_stream(
                {"prompt": p.tolist(), "max_new_tokens": 6},
                lambda toks, off: frames.append((off, list(toks))))
        want = single.generate(p[None], 6)[0]
        np.testing.assert_array_equal(row, want)
        streamed = [t for _, chunk in sorted(frames) for t in chunk]
        np.testing.assert_array_equal(streamed, want[len(p):])
