"""The non-Python deploy surface, end to end: export a model with
Predictor.export, build the C ABI shim (_native/predict_shim.cc) and
the C host program (examples/c_predict/predict.c), run the C binary in
a clean process, and require its printed outputs to match the
in-process Python forward bit-for-bit-ish (1e-5).

Reference parity: src/c_api/c_predict_api.cc:363 + the predict-cpp
example — a C program loads an exported model and classifies without
any Python source in sight (here: without symbol source or params;
the artifact is one serialized XLA program + a meta json).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import _native
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.predictor import Predictor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _small_model():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=4)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    arg_shapes, _, _ = net.infer_shape(data=(2, 8))
    rng = np.random.RandomState(7)
    init = Xavier()
    args = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        arr = mx.nd.zeros(shp)
        init(name, arr)
        args[name] = arr
    return net, args


@pytest.fixture(scope="module")
def shim():
    so = _native.build_predict_shim()
    if so is None:
        pytest.skip("toolchain/Python headers unavailable")
    return so


@pytest.fixture(scope="module")
def c_binary(shim, tmp_path_factory):
    out = tmp_path_factory.mktemp("cbin") / "predict"
    native_dir = os.path.dirname(shim)
    src = os.path.join(REPO, "examples", "c_predict", "predict.c")
    r = subprocess.run(
        ["gcc", src, "-o", str(out), "-L%s" % native_dir,
         "-lpredict_shim", "-Wl,-rpath,%s" % native_dir],
        capture_output=True, text=True, timeout=120)
    if r.returncode != 0:
        pytest.skip("cannot build C host: %s" % r.stderr[-300:])
    return str(out)


def test_c_predict_matches_python(c_binary, tmp_path):
    net, args = _small_model()
    pred = Predictor(net, args, data_names=("data",))
    x = np.random.RandomState(0).standard_normal((2, 8)).astype(
        np.float32)
    want = np.asarray(pred.forward(x)[0].asnumpy(), np.float32)

    prefix = str(tmp_path / "model")
    pred.export(prefix, {"data": (2, 8)})
    assert os.path.exists(prefix + ".stablehlo")

    raw = tmp_path / "input.f32"
    raw.write_bytes(x.tobytes())

    env = dict(os.environ)
    # clean deploy process: repo on the path, CPU backend, and NO axon
    # plugin dir (a down tunnel would hang the embedded interpreter)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [c_binary, prefix, str(raw), str(x.size)],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, "C host failed: %s" % r.stderr[-500:]

    lines = r.stdout.strip().splitlines()
    assert lines[0].startswith("output 0 shape")
    shape = tuple(int(v) for v in lines[0].split("shape")[1].split())
    assert shape == want.shape
    got = np.array([float(v) for v in
                    lines[1:1 + want.size]]).reshape(shape)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_cpp_wrapper_matches_python(shim, tmp_path):
    """mxtpu_cpp.hpp (the predict-only cpp-package analogue, N28):
    the RAII C++ host must match the in-process Python forward."""
    native_dir = os.path.dirname(shim)
    src = os.path.join(REPO, "examples", "c_predict", "predict_cpp.cc")
    binary = str(tmp_path / "predict_cpp")
    r = subprocess.run(
        ["g++", "-std=c++17", src, "-o", binary,
         "-I%s" % os.path.dirname(src), "-L%s" % native_dir,
         "-lpredict_shim", "-Wl,-rpath,%s" % native_dir],
        capture_output=True, text=True, timeout=120)
    if r.returncode != 0:
        pytest.skip("cannot build C++ host: %s" % r.stderr[-300:])

    net, args = _small_model()
    pred = Predictor(net, args, data_names=("data",))
    x = np.random.RandomState(5).standard_normal((2, 8)).astype(
        np.float32)
    want = np.asarray(pred.forward(x)[0].asnumpy(), np.float32)
    prefix = str(tmp_path / "model")
    pred.export(prefix, {"data": (2, 8)})
    raw = tmp_path / "input.f32"
    raw.write_bytes(x.tobytes())

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([binary, prefix, str(raw), str(x.size)],
                       capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode == 0, "C++ host failed: %s" % r.stderr[-500:]
    lines = r.stdout.strip().splitlines()
    shape = tuple(int(v) for v in lines[0].split("shape")[1].split())
    got = np.array([float(v) for v in
                    lines[1:1 + want.size]]).reshape(shape)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_amalgamated_bundle(tmp_path):
    """tools/amalgamate.py: the bundle builds and predicts with the
    FRAMEWORK SOURCE ABSENT from PYTHONPATH — the reference
    amalgamation's 'deploy without the framework' property (N29)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import amalgamate
    finally:
        sys.path.pop(0)

    net, args = _small_model()
    pred = Predictor(net, args, data_names=("data",))
    x = np.random.RandomState(3).standard_normal((2, 8)).astype(
        np.float32)
    want = np.asarray(pred.forward(x)[0].asnumpy(), np.float32)
    prefix = str(tmp_path / "export" / "m")
    os.makedirs(os.path.dirname(prefix))
    pred.export(prefix, {"data": (2, 8)})

    bundle = str(tmp_path / "bundle")
    amalgamate.amalgamate(prefix, bundle)
    r = subprocess.run(["sh", os.path.join(bundle, "build.sh")],
                       capture_output=True, text=True, timeout=180)
    if r.returncode != 0:
        pytest.skip("bundle build failed (toolchain): %s"
                    % r.stderr[-300:])

    raw = tmp_path / "input.f32"
    raw.write_bytes(x.tobytes())
    env = dict(os.environ)
    env["PYTHONPATH"] = ""            # NO framework source anywhere
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [os.path.join(bundle, "predict"),
         os.path.join(bundle, "model"), str(raw), str(x.size)],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, "bundle predict failed: %s" % \
        r.stderr[-500:]
    lines = r.stdout.strip().splitlines()
    shape = tuple(int(v) for v in lines[0].split("shape")[1].split())
    got = np.array([float(v) for v in
                    lines[1:1 + want.size]]).reshape(shape)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_c_predict_error_surface(c_binary, tmp_path):
    """A bad model prefix must fail with a real error message through
    MXTpuGetLastError, not crash."""
    raw = tmp_path / "input.f32"
    raw.write_bytes(np.zeros(4, np.float32).tobytes())
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [c_binary, str(tmp_path / "nope"), str(raw), "4"],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 1
    assert "create" in r.stderr
