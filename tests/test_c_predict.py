"""The non-Python deploy surface, end to end: export a model with
Predictor.export, build the C ABI shim (_native/predict_shim.cc) and
the C host program (examples/c_predict/predict.c), run the C binary in
a clean process, and require its printed outputs to match the
in-process Python forward bit-for-bit-ish (1e-5).

Reference parity: src/c_api/c_predict_api.cc:363 + the predict-cpp
example — a C program loads an exported model and classifies without
any Python source in sight (here: without symbol source or params;
the artifact is one serialized XLA program + a meta json).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import _native
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.predictor import Predictor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _small_model():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=4)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    arg_shapes, _, _ = net.infer_shape(data=(2, 8))
    rng = np.random.RandomState(7)
    init = Xavier()
    args = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        arr = mx.nd.zeros(shp)
        init(name, arr)
        args[name] = arr
    return net, args


@pytest.fixture(scope="module")
def shim():
    so = _native.build_predict_shim()
    if so is None:
        pytest.skip("toolchain/Python headers unavailable")
    return so


@pytest.fixture(scope="module")
def c_binary(shim, tmp_path_factory):
    out = tmp_path_factory.mktemp("cbin") / "predict"
    native_dir = os.path.dirname(shim)
    src = os.path.join(REPO, "examples", "c_predict", "predict.c")
    r = subprocess.run(
        ["gcc", src, "-o", str(out), "-L%s" % native_dir,
         "-lpredict_shim", "-Wl,-rpath,%s" % native_dir],
        capture_output=True, text=True, timeout=120)
    if r.returncode != 0:
        pytest.skip("cannot build C host: %s" % r.stderr[-300:])
    return str(out)


def test_c_predict_matches_python(c_binary, tmp_path):
    net, args = _small_model()
    pred = Predictor(net, args, data_names=("data",))
    x = np.random.RandomState(0).standard_normal((2, 8)).astype(
        np.float32)
    want = np.asarray(pred.forward(x)[0].asnumpy(), np.float32)

    prefix = str(tmp_path / "model")
    pred.export(prefix, {"data": (2, 8)})
    assert os.path.exists(prefix + ".stablehlo")

    raw = tmp_path / "input.f32"
    raw.write_bytes(x.tobytes())

    env = dict(os.environ)
    # clean deploy process: repo on the path, CPU backend, and NO axon
    # plugin dir (a down tunnel would hang the embedded interpreter)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [c_binary, prefix, str(raw), str(x.size)],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, "C host failed: %s" % r.stderr[-500:]

    lines = r.stdout.strip().splitlines()
    assert lines[0].startswith("output 0 shape")
    shape = tuple(int(v) for v in lines[0].split("shape")[1].split())
    assert shape == want.shape
    got = np.array([float(v) for v in
                    lines[1:1 + want.size]]).reshape(shape)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_cpp_wrapper_matches_python(shim, tmp_path):
    """mxtpu_cpp.hpp (the predict-only cpp-package analogue, N28):
    the RAII C++ host must match the in-process Python forward."""
    native_dir = os.path.dirname(shim)
    src = os.path.join(REPO, "examples", "c_predict", "predict_cpp.cc")
    binary = str(tmp_path / "predict_cpp")
    r = subprocess.run(
        ["g++", "-std=c++17", src, "-o", binary,
         "-I%s" % os.path.dirname(src), "-L%s" % native_dir,
         "-lpredict_shim", "-Wl,-rpath,%s" % native_dir],
        capture_output=True, text=True, timeout=120)
    if r.returncode != 0:
        pytest.skip("cannot build C++ host: %s" % r.stderr[-300:])

    net, args = _small_model()
    pred = Predictor(net, args, data_names=("data",))
    x = np.random.RandomState(5).standard_normal((2, 8)).astype(
        np.float32)
    want = np.asarray(pred.forward(x)[0].asnumpy(), np.float32)
    prefix = str(tmp_path / "model")
    pred.export(prefix, {"data": (2, 8)})
    raw = tmp_path / "input.f32"
    raw.write_bytes(x.tobytes())

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([binary, prefix, str(raw), str(x.size)],
                       capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode == 0, "C++ host failed: %s" % r.stderr[-500:]
    lines = r.stdout.strip().splitlines()
    shape = tuple(int(v) for v in lines[0].split("shape")[1].split())
    got = np.array([float(v) for v in
                    lines[1:1 + want.size]]).reshape(shape)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_amalgamated_bundle(tmp_path):
    """tools/amalgamate.py: the bundle builds and predicts with the
    FRAMEWORK SOURCE ABSENT from PYTHONPATH — the reference
    amalgamation's 'deploy without the framework' property (N29)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import amalgamate
    finally:
        sys.path.pop(0)

    net, args = _small_model()
    pred = Predictor(net, args, data_names=("data",))
    x = np.random.RandomState(3).standard_normal((2, 8)).astype(
        np.float32)
    want = np.asarray(pred.forward(x)[0].asnumpy(), np.float32)
    prefix = str(tmp_path / "export" / "m")
    os.makedirs(os.path.dirname(prefix))
    pred.export(prefix, {"data": (2, 8)})

    bundle = str(tmp_path / "bundle")
    amalgamate.amalgamate(prefix, bundle)
    r = subprocess.run(["sh", os.path.join(bundle, "build.sh")],
                       capture_output=True, text=True, timeout=180)
    if r.returncode != 0:
        pytest.skip("bundle build failed (toolchain): %s"
                    % r.stderr[-300:])

    raw = tmp_path / "input.f32"
    raw.write_bytes(x.tobytes())
    env = dict(os.environ)
    env["PYTHONPATH"] = ""            # NO framework source anywhere
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [os.path.join(bundle, "predict"),
         os.path.join(bundle, "model"), str(raw), str(x.size)],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, "bundle predict failed: %s" % \
        r.stderr[-500:]
    lines = r.stdout.strip().splitlines()
    shape = tuple(int(v) for v in lines[0].split("shape")[1].split())
    got = np.array([float(v) for v in
                    lines[1:1 + want.size]]).reshape(shape)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_c_train_matches_python(shim, tmp_path):
    """The training ABI (round 5, N17/N28 closure): a C program drives
    N compiled train steps through MXTpuTrain* and must land on
    EXACTLY the same trained parameters as CompiledTrainStep run
    in-process (same exported program, same seed sequence)."""
    from mxnet_tpu.parallel import make_train_step
    from mxnet_tpu.parallel.trainer import CompiledTrainStep

    native_dir = os.path.dirname(shim)
    src = os.path.join(REPO, "examples", "c_predict", "train.c")
    binary = str(tmp_path / "train_host")
    r = subprocess.run(
        ["gcc", src, "-o", binary, "-L%s" % native_dir,
         "-lpredict_shim", "-Wl,-rpath,%s" % native_dir],
        capture_output=True, text=True, timeout=120)
    if r.returncode != 0:
        pytest.skip("cannot build C train host: %s" % r.stderr[-300:])

    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(mx.sym.Activation(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                              name="fc1"), act_type="relu"),
        num_hidden=2, name="fc2"), name="softmax")
    step = make_train_step(net, optimizer="sgd",
                           optimizer_params={"momentum": 0.9,
                                             "rescale_grad": 1.0 / 32})
    state = step.init_state(Xavier(), {"data": (32, 8),
                                       "softmax_label": (32,)})
    rng = np.random.RandomState(0)
    X = rng.standard_normal((32, 8)).astype(np.float32)
    y = (X @ rng.standard_normal(8) > 0).astype(np.float32)
    batch = step.place_batch({"data": X, "softmax_label": y})
    prefix = str(tmp_path / "m")
    step.export(prefix, state, batch)

    n_steps, lr = 25, 0.2
    ref = CompiledTrainStep.load(prefix)
    for _ in range(n_steps):
        outs = ref.step({"data": X, "softmax_label": y}, lr)
    want_out = np.asarray(outs[0], np.float32)
    want_w = np.asarray(ref.get_params()["fc1_weight"], np.float32)

    (tmp_path / "x.f32").write_bytes(X.tobytes())
    (tmp_path / "y.f32").write_bytes(y.tobytes())
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [binary, prefix, str(tmp_path / "x.f32"), str(X.size),
         str(tmp_path / "y.f32"), str(y.size), str(n_steps), str(lr),
         "fc1_weight"],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, "C train host failed: %s" % \
        r.stderr[-500:]

    lines = r.stdout.strip().splitlines()
    oshape = tuple(int(v) for v in lines[0].split("shape")[1].split())
    assert oshape == want_out.shape
    got_out = np.array([float(v) for v in
                        lines[1:1 + want_out.size]]).reshape(oshape)
    np.testing.assert_allclose(got_out, want_out, rtol=1e-5,
                               atol=1e-6)
    pline = 1 + want_out.size
    assert lines[pline].startswith("param fc1_weight shape")
    pshape = tuple(int(v) for v in
                   lines[pline].split("shape")[1].split())
    assert pshape == want_w.shape
    got_w = np.array([float(v) for v in
                      lines[pline + 1:pline + 1 + want_w.size]]
                     ).reshape(pshape)
    np.testing.assert_allclose(got_w, want_w, rtol=1e-5, atol=1e-6)
    # and the C-driven training moved the weights off their initial
    # exported values (the allclose above would also pass for a no-op
    # if the reference run were broken the same way)
    w0 = np.asarray(
        jax.device_get(state[0]["fc1_weight"]), np.float32)
    assert np.abs(got_w - w0).max() > 1e-4


def test_amalgamated_train_bundle(tmp_path):
    """A train-capable amalgamated bundle (TrainStep.export + the
    generated mxtpu_train_min.py) must train from C with the
    FRAMEWORK SOURCE ABSENT from PYTHONPATH and reproduce the
    in-process trajectory exactly."""
    from mxnet_tpu.parallel import make_train_step
    from mxnet_tpu.parallel.trainer import CompiledTrainStep

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import amalgamate
    finally:
        sys.path.pop(0)

    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), num_hidden=2, name="fc1"),
        name="softmax")
    step = make_train_step(net, optimizer="sgd",
                           optimizer_params={"momentum": 0.9,
                                             "rescale_grad": 1.0 / 16})
    state = step.init_state(Xavier(), {"data": (16, 8),
                                       "softmax_label": (16,)})
    rng = np.random.RandomState(2)
    X = rng.standard_normal((16, 8)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    batch = step.place_batch({"data": X, "softmax_label": y})
    prefix = str(tmp_path / "export" / "m")
    os.makedirs(os.path.dirname(prefix))
    step.export(prefix, state, batch)

    n_steps, lr = 10, 0.2
    ref = CompiledTrainStep.load(prefix)
    for _ in range(n_steps):
        ref.step({"data": X, "softmax_label": y}, lr)
    want_w = np.asarray(ref.get_params()["fc1_weight"], np.float32)

    bundle = str(tmp_path / "bundle")
    amalgamate.amalgamate(prefix, bundle)
    assert os.path.exists(os.path.join(bundle, "mxtpu_train_min.py"))
    r = subprocess.run(["sh", os.path.join(bundle, "build.sh")],
                       capture_output=True, text=True, timeout=180)
    if r.returncode != 0:
        pytest.skip("bundle build failed (toolchain): %s"
                    % r.stderr[-300:])

    (tmp_path / "x.f32").write_bytes(X.tobytes())
    (tmp_path / "y.f32").write_bytes(y.tobytes())
    env = dict(os.environ)
    env["PYTHONPATH"] = ""            # NO framework source anywhere
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [os.path.join(bundle, "train"), os.path.join(bundle, "model"),
         str(tmp_path / "x.f32"), str(X.size),
         str(tmp_path / "y.f32"), str(y.size), str(n_steps), str(lr),
         "fc1_weight"],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, "bundle train failed: %s" % \
        r.stderr[-500:]
    lines = r.stdout.strip().splitlines()
    osize = int(np.prod([int(v) for v in
                         lines[0].split("shape")[1].split()]))
    pline = 1 + osize
    assert lines[pline].startswith("param fc1_weight shape")
    pshape = tuple(int(v) for v in
                   lines[pline].split("shape")[1].split())
    got_w = np.array([float(v) for v in
                      lines[pline + 1:pline + 1 + want_w.size]]
                     ).reshape(pshape)
    np.testing.assert_allclose(got_w, want_w, rtol=1e-5, atol=1e-6)


def test_c_predict_error_surface(c_binary, tmp_path):
    """A bad model prefix must fail with a real error message through
    MXTpuGetLastError, not crash."""
    raw = tmp_path / "input.f32"
    raw.write_bytes(np.zeros(4, np.float32).tobytes())
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [c_binary, str(tmp_path / "nope"), str(raw), "4"],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 1
    assert "create" in r.stderr
