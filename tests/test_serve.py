"""The serving engine (mxnet_tpu/serve/): dynamic batching,
backpressure, drain, the AOT deploy chain, and the TCP front end.

Load-bearing acceptance gates:
- N concurrent clients produce < N engine forwards with mean batch
  fill > 1 (batching is real), and every row matches the in-process
  Predictor bitwise (batching is lossless).
- Every request gets exactly one response — correct payload or typed
  error — under MXNET_FAULT_SPEC drop/delay/disconnect injection on
  the serving wire.
- SIGTERM drains: admitted requests finish, new ones are rejected.
- Predictor.export -> CompiledPredictor served by ServeEngine is
  bitwise-identical to the in-process Predictor at EVERY bucket shape.
"""
import json
import signal
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import config, telemetry
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.parallel.resilience import (FaultInjector, RetryPolicy,
                                           install_fault_injector)
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.serve import (EngineClosed, Overloaded, RequestTimeout,
                             ServeClient, ServeEngine, ServeServer)

pytestmark = pytest.mark.serve

FEAT, CLASSES = 8, 4


def _predictor(seed=7):
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=CLASSES)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    arg_shapes, _, _ = net.infer_shape(data=(2, FEAT))
    mx.random.seed(seed)
    init = Xavier()
    args = {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        arr = mx.nd.zeros(shp)
        init(name, arr)
        args[name] = arr
    return Predictor(net, args, data_names=("data",))


@pytest.fixture(scope="module")
def pred():
    return _predictor()


@pytest.fixture
def no_injector():
    yield
    install_fault_injector(None)


class _Recorder:
    """Forward wrapper recording batch shapes (and optionally
    sleeping, to make queues observable)."""

    def __init__(self, pred, delay=0.0):
        self._pred = pred
        self.delay = delay
        self.shapes = []

    def forward(self, *arrays):
        self.shapes.append(tuple(a.shape[0] for a in arrays))
        if self.delay:
            time.sleep(self.delay)
        return self._pred.forward(*arrays)


class TestBatching:
    def test_concurrent_requests_batch_and_match(self, pred):
        """ACCEPTANCE: 8 concurrent single-row clients -> fewer than 8
        forwards, mean batch fill > 1 (via the serve.batch_fill
        histogram the stats mirror), and every row bitwise-equal to
        the in-process Predictor."""
        rng = np.random.RandomState(0)
        X = rng.standard_normal((8, FEAT)).astype(np.float32)
        want = pred.forward(X)[0].asnumpy()
        fill_before = telemetry.histogram(
            "serve.batch_fill", buckets=telemetry.COUNT_BUCKETS)
        n0, s0 = fill_before.count, fill_before.sum
        with ServeEngine(pred, buckets=(1, 2, 4, 8),
                         max_wait_ms=250.0, install_sigterm=False,
                         feature_shapes=[(FEAT,)]) as eng:
            eng.warmup()
            res = [None] * 8

            def go(i):
                res[i] = eng.infer(X[i:i + 1], timeout=30.0)

            ts = [threading.Thread(target=go, args=(i,))
                  for i in range(8)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            st = eng.stats()
        for i in range(8):
            np.testing.assert_array_equal(res[i][0][0], want[i])
        assert st["forwards"] < 8
        assert st["mean_fill"] > 1
        # and the process-global histogram carries the same evidence
        assert fill_before.count - n0 == st["forwards"]
        assert (fill_before.sum - s0) / (fill_before.count - n0) > 1

    def test_bucket_rounding_and_padding(self, pred):
        """A 3-row request pads to the 4-bucket; outputs slice back to
        exactly the request's rows."""
        rec = _Recorder(pred)
        rng = np.random.RandomState(1)
        X = rng.standard_normal((3, FEAT)).astype(np.float32)
        want = pred.forward(X)[0].asnumpy()
        with ServeEngine(rec, buckets=(1, 2, 4, 8), max_wait_ms=0.0,
                         install_sigterm=False) as eng:
            out = eng.infer(X, timeout=30.0)
        assert rec.shapes == [(4,)]
        assert out[0].shape[0] == 3
        np.testing.assert_array_equal(out[0], want)

    def test_oversized_request_rejected(self, pred):
        with ServeEngine(pred, buckets=(1, 2), max_wait_ms=0.0,
                         install_sigterm=False) as eng:
            with pytest.raises(ValueError, match="largest bucket"):
                eng.submit(np.zeros((3, FEAT), np.float32))

    def test_mismatched_rows_rejected_even_first(self, pred):
        """Row-count agreement is validated BEFORE feature shapes are
        learned — a malformed first request must not poison a group."""
        with ServeEngine(pred, buckets=(1, 2, 4), max_wait_ms=0.0,
                         install_sigterm=False) as eng:
            with pytest.raises(ValueError, match="rows must agree"):
                eng.submit(np.zeros((2, FEAT), np.float32),
                           np.zeros((3, FEAT), np.float32))

    def test_warmup_compiles_every_bucket(self, pred):
        rec = _Recorder(pred)
        with ServeEngine(rec, buckets=(1, 2, 4), max_wait_ms=0.0,
                         feature_shapes=[(FEAT,)],
                         install_sigterm=False) as eng:
            eng.warmup()
        assert rec.shapes == [(1,), (2,), (4,)]


class TestBackpressure:
    def test_overload_sheds_typed_and_admitted_complete(self, pred):
        """Queue cap 2 + slow model: floods shed with the typed
        Overloaded; every ADMITTED request still gets its payload
        (exactly one response each, nothing silently dropped)."""
        rec = _Recorder(pred, delay=0.1)
        x = np.zeros((1, FEAT), np.float32)
        with ServeEngine(rec, buckets=(1, 2, 4), max_wait_ms=0.0,
                         queue_cap=2, install_sigterm=False) as eng:
            futs, shed = [], 0
            for _ in range(12):
                try:
                    futs.append(eng.submit(x))
                except Overloaded:
                    shed += 1
            assert shed > 0
            assert eng.stats()["shed"] == shed
            for f in futs:
                assert f.result(30.0)[0].shape == (1, CLASSES)

    def test_deadline_timeout_typed(self, pred):
        """A request whose deadline lapses in the queue gets the typed
        RequestTimeout and never occupies a batch slot."""
        rec = _Recorder(pred, delay=0.25)
        x = np.zeros((1, FEAT), np.float32)
        with ServeEngine(rec, buckets=(1,), max_wait_ms=0.0,
                         install_sigterm=False) as eng:
            first = eng.submit(x)              # occupies the model
            doomed = eng.submit(x, deadline_ms=1.0)
            assert first.result(30.0)
            with pytest.raises(RequestTimeout):
                doomed.result(30.0)
            assert eng.stats()["timeouts"] == 1

    def test_default_deadline_from_env(self, pred):
        config.set_override("MXNET_SERVE_DEADLINE_MS", 1.0)
        try:
            rec = _Recorder(pred, delay=0.25)
            x = np.zeros((1, FEAT), np.float32)
            with ServeEngine(rec, buckets=(1,), max_wait_ms=0.0,
                             install_sigterm=False) as eng:
                first = eng.submit(x, deadline_ms=0)   # explicit: none
                doomed = eng.submit(x)                 # env default
                assert first.result(30.0)
                with pytest.raises(RequestTimeout):
                    doomed.result(30.0)
        finally:
            config.clear_override("MXNET_SERVE_DEADLINE_MS")


class TestDrain:
    def test_close_drains_queued(self, pred):
        rec = _Recorder(pred, delay=0.05)
        x = np.zeros((1, FEAT), np.float32)
        eng = ServeEngine(rec, buckets=(1, 2, 4), max_wait_ms=0.0,
                          install_sigterm=False)
        futs = [eng.submit(x) for _ in range(6)]
        eng.close()
        for f in futs:
            assert f.result(1.0)[0].shape == (1, CLASSES)
        with pytest.raises(EngineClosed):
            eng.submit(x)

    def test_sigterm_drains_and_rejects(self, pred):
        """ACCEPTANCE: SIGTERM through the chaining guardrail handler —
        in-flight requests finish, new submissions are rejected, and
        the previously-installed handler still runs (chained)."""
        rec = _Recorder(pred, delay=0.05)
        x = np.zeros((1, FEAT), np.float32)
        chained = []
        prev = signal.signal(signal.SIGTERM,
                             lambda *_: chained.append(1))
        try:
            eng = ServeEngine(rec, buckets=(1, 2, 4), max_wait_ms=0.0,
                              install_sigterm=True)
            futs = [eng.submit(x) for _ in range(5)]
            signal.raise_signal(signal.SIGTERM)
            for f in futs:
                assert f.result(30.0)[0].shape == (1, CLASSES)
            with pytest.raises(EngineClosed):
                eng.submit(x)
            assert chained == [1]
            eng.close()
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_engine_error_is_the_response(self, pred):
        """A model-side exception becomes each live request's one
        typed response — not a hang, not a silent drop."""
        class Broken:
            def forward(self, *a):
                raise RuntimeError("kaboom")

        with ServeEngine(Broken(), buckets=(1, 2), max_wait_ms=0.0,
                         install_sigterm=False) as eng:
            f = eng.submit(np.zeros((1, FEAT), np.float32))
            with pytest.raises(RuntimeError, match="kaboom"):
                f.result(30.0)


class TestDeployChain:
    def test_compiled_buckets_bitwise_match(self, pred, tmp_path):
        """ACCEPTANCE (satellite): Predictor.export_buckets ->
        ServeEngine.from_export returns BITWISE-identical outputs to
        the in-process Predictor at every configured bucket shape."""
        prefix = str(tmp_path / "m")
        buckets = (1, 2, 4)
        pred.export_buckets(prefix, [(FEAT,)], buckets=buckets)
        rng = np.random.RandomState(5)
        with ServeEngine.from_export(prefix, max_wait_ms=0.0,
                                     install_sigterm=False) as eng:
            eng.warmup()
            for b in buckets:
                X = rng.standard_normal((b, FEAT)).astype(np.float32)
                want = pred.forward(X)[0].asnumpy()
                got = eng.infer(X, timeout=30.0)[0]
                assert got.dtype == want.dtype
                np.testing.assert_array_equal(got, want)

    def test_manifest_contents(self, pred, tmp_path):
        prefix = str(tmp_path / "m")
        path = pred.export_buckets(prefix, [(FEAT,)], buckets=(1, 2))
        with open(path) as f:
            man = json.load(f)
        assert man["buckets"] == [1, 2]
        assert man["feature_shapes"] == [[FEAT]]
        assert man["data_names"] == ["data"]


class TestNet:
    def test_roundtrip_and_typed_errors(self, pred):
        with ServeEngine(pred, buckets=(1, 2, 4), max_wait_ms=0.0,
                         install_sigterm=False) as eng, \
                ServeServer(eng) as srv:
            c = ServeClient(srv.host, srv.port,
                            retry=RetryPolicy(base_delay=0.01))
            assert c.ping()
            x = np.random.RandomState(2).standard_normal(
                (1, FEAT)).astype(np.float32)
            out = c.request([x])
            np.testing.assert_array_equal(
                out[0], pred.forward(x)[0].asnumpy())
            c.close()

    def test_overload_raises_typed_across_wire(self, pred):
        with ServeEngine(pred, buckets=(1,), max_wait_ms=0.0,
                         queue_cap=0, install_sigterm=False) as eng, \
                ServeServer(eng) as srv:
            c = ServeClient(srv.host, srv.port,
                            retry=RetryPolicy(base_delay=0.01))
            with pytest.raises(Overloaded):
                c.request([np.zeros((1, FEAT), np.float32)])
            c.close()

    def test_closed_engine_raises_typed_across_wire(self, pred):
        eng = ServeEngine(pred, buckets=(1,), max_wait_ms=0.0,
                          install_sigterm=False)
        eng.close()
        with ServeServer(eng) as srv:
            c = ServeClient(srv.host, srv.port,
                            retry=RetryPolicy(base_delay=0.01))
            with pytest.raises(EngineClosed):
                c.request([np.zeros((1, FEAT), np.float32)])
            c.close()

    def test_stats_introspection_rpc(self, pred):
        """Satellite: the `stats` frame answers with the telemetry
        registry snapshot + live engine state, via ServeClient.stats()
        AND tools/telemetry_report.py's --stats fetch path."""
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        ".."))
        from tools.telemetry_report import fetch_stats, format_stats

        with ServeEngine(pred, buckets=(1, 2, 4), max_wait_ms=0.0,
                         feature_shapes=[(FEAT,)],
                         install_sigterm=False) as eng, \
                ServeServer(eng) as srv:
            eng.warmup()
            c = ServeClient(srv.host, srv.port,
                            retry=RetryPolicy(base_delay=0.01))
            c.request([np.zeros((1, FEAT), np.float32)])
            stats = c.stats()
            c.close()
            # the standalone tool speaks the wire without the framework
            tool_stats = fetch_stats("%s:%d" % (srv.host, srv.port))
        for got in (stats, tool_stats):
            assert set(got) == {"telemetry", "engine"}
            eng_state = got["engine"]
            assert eng_state["buckets"] == [1, 2, 4]
            assert eng_state["warmed"] == [1, 2, 4]
            assert eng_state["queue_depth"] == 0
            assert eng_state["admitted"] >= 1
            assert "serve.admitted" in got["telemetry"]
        text = format_stats(tool_stats)
        assert "warmed" in text and "serve.admitted" in text

    @pytest.mark.faults
    def test_exactly_one_response_under_faults(self, pred,
                                               no_injector):
        """ACCEPTANCE: drop/delay/disconnect injection on BOTH sides
        of the serving wire — every request still yields exactly one
        correct payload (the client replays on fresh connections;
        inference is pure, so replay is safe)."""
        install_fault_injector(FaultInjector(
            "serve_send:disconnect@3;serve_send:delay@5:0.02;"
            "serve_recv:drop@7;serve_srv_send:disconnect@11;"
            "serve_srv_recv:drop@14"))
        rng = np.random.RandomState(3)
        X = rng.standard_normal((6, FEAT)).astype(np.float32)
        want = pred.forward(X)[0].asnumpy()
        results = {}
        with ServeEngine(pred, buckets=(1, 2, 4), max_wait_ms=1.0,
                         install_sigterm=False) as eng, \
                ServeServer(eng) as srv:
            def client(i):
                c = ServeClient(srv.host, srv.port,
                                retry=RetryPolicy(base_delay=0.01,
                                                  seed=i))
                for j in range(3):
                    out = c.request([X[i:i + 1]])
                    results[(i, j)] = out[0][0]
                c.close()

            ts = [threading.Thread(target=client, args=(i,))
                  for i in range(6)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        assert len(results) == 18        # one response per request
        # responses arrive from whatever bucket shape the batcher
        # chose, so allclose (bucket shapes differ at the last ulp);
        # the bitwise gate lives in TestDeployChain at fixed shapes
        for (i, _j), row in results.items():
            np.testing.assert_allclose(row, want[i], rtol=1e-5,
                                       atol=1e-7)


class TestTelemetryReport:
    def test_serving_section_in_report(self, pred, tmp_path):
        """Engine traffic journals serve.* events; the report tool
        renders them as the serving section."""
        import os
        import sys
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        sys.path.insert(0, os.path.join(repo, "tools"))
        try:
            import telemetry_report
        finally:
            sys.path.pop(0)
        telemetry.close_journal()
        d = str(tmp_path / "tele")
        config.set_override("MXNET_TELEMETRY", d)
        try:
            with ServeEngine(pred, buckets=(1, 2, 4),
                             max_wait_ms=0.0, queue_cap=1,
                             install_sigterm=False) as eng:
                x = np.zeros((1, FEAT), np.float32)
                for _ in range(4):
                    eng.infer(x, timeout=30.0)
            path = telemetry.close_journal()
        finally:
            telemetry.close_journal()
            config.clear_override("MXNET_TELEMETRY")
        summary = telemetry_report.summarize(
            telemetry_report.load(path))
        assert summary["serving"]["forwards"] == 4
        assert summary["serving"]["mean_fill"] >= 1.0
        text = telemetry_report.format_report(summary)
        assert "serving:" in text and "mean batch fill" in text


class TestBenchServe:
    def test_bench_serve_emits_sweep_json(self, capsys):
        import bench_serve
        assert bench_serve.main(["--concurrency", "1,2",
                                 "--requests", "5",
                                 "--features", str(FEAT),
                                 "--hidden", "16",
                                 "--classes", str(CLASSES)]) == 0
        rec = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert rec["metric"] == "serve_throughput"
        assert rec["unit"] == "req/s"
        assert rec["value"] > 0
        assert len(rec["sweep"]) == 2
        row = rec["sweep"][0]
        assert {"concurrency", "throughput_rps", "latency_ms",
                "mean_batch_fill"} <= set(row)
        assert {"p50", "p95", "p99"} <= set(row["latency_ms"])
