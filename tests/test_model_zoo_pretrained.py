"""Model-zoo pretrained=True via the local model store (reference
model_zoo/model_store.py get_model_file + factory load_params; here
zero-egress, so the store is local-only)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import model_zoo
from mxnet_tpu.gluon.model_zoo import model_store


def test_get_model_file_missing_is_actionable(tmp_path):
    with pytest.raises(FileNotFoundError) as ei:
        model_store.get_model_file("squeezenet1.0", root=str(tmp_path))
    msg = str(ei.value)
    assert "squeezenet1.0.params" in msg and "zero egress" in msg


def test_store_root_resolution(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_HOME", str(tmp_path))
    assert model_store.model_store_root() == str(tmp_path / "models")
    assert model_store.model_store_root("/x/y") == "/x/y"


def test_pretrained_roundtrip_through_store(tmp_path, monkeypatch):
    """save_params -> local store -> pretrained=True reproduces the
    exact forward outputs (the pretrained-zoo inference contract,
    reference tests/python/gpu/test_forward.py made hermetic)."""
    store = tmp_path / "models"
    store.mkdir()
    monkeypatch.setenv("MXNET_HOME", str(tmp_path))

    mx.random.seed(42)
    np.random.seed(42)
    net = model_zoo.vision.squeezenet1_0(classes=10)
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(0).randn(2, 3, 64, 64)
                 .astype(np.float32))
    want = net(x).asnumpy()   # also completes deferred init
    net.save_params(str(store / "squeezenet1.0.params"))

    loaded = model_zoo.get_model("squeezenet1.0", pretrained=True,
                                 classes=10)
    got = loaded(x).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pretrained_false_ignores_store(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_HOME", str(tmp_path))   # empty store
    net = model_zoo.vision.mobilenet0_25(classes=10)  # must not raise
    assert net is not None
