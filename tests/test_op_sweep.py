"""Per-op verification sweep (VERDICT r1 item 3) — the TPU analogue of the
reference's tests/python/unittest/test_operator.py:

  * forward vs a numpy oracle
  * gradient vs central finite differences (differentiable ops)
  * eager (un-jitted) vs jit-compiled consistency
  * a completeness gate: >=90% of registered ops must carry a spec

Specs keep shapes tiny: the finite-difference check evaluates the op
twice per input element.
"""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ops import registry
from mxnet_tpu.test_utils import check_numeric_gradient

R = np.random.RandomState(7)


def f32(shape, lo=-1.0, hi=1.0):
    return R.uniform(lo, hi, shape).astype(np.float32)


# ---------------------------------------------------------------------------
# spec table
# ---------------------------------------------------------------------------
# op -> list of cases; each case:
#   inputs: list of np arrays (op tensor inputs, in order)
#   attrs:  kwargs
#   oracle: fn(*inputs, **attrs) -> np array / list (None: skip fwd check)
#   grad_args: indices of `inputs` to finite-difference (default: none)
#   rtol/atol: forward tolerance
SPECS = {}


def spec(name, inputs, attrs=None, oracle=None, grad_args=(),
         rtol=1e-4, atol=1e-5, grad_rtol=1e-2, grad_atol=1e-3):
    SPECS.setdefault(name, []).append(dict(
        inputs=inputs, attrs=dict(attrs or {}), oracle=oracle,
        grad_args=tuple(grad_args), rtol=rtol, atol=atol,
        grad_rtol=grad_rtol, grad_atol=grad_atol))


# -- unary math --------------------------------------------------------------
_v = np.vectorize
UNARY = {
    # name: (numpy fn, (lo, hi), differentiable)
    "abs": (np.abs, (0.2, 1.0), True),
    "sign": (np.sign, (-1, 1), False),
    "negative": (np.negative, (-1, 1), True),
    "reciprocal": (lambda x: 1.0 / x, (0.5, 1.5), True),
    "cbrt": (np.cbrt, (0.3, 2.0), True),
    "rcbrt": (lambda x: 1.0 / np.cbrt(x), (0.5, 2.0), True),
    "sqrt": (np.sqrt, (0.3, 2.0), True),
    "rsqrt": (lambda x: 1.0 / np.sqrt(x), (0.5, 2.0), True),
    "square": (np.square, (-1, 1), True),
    "exp": (np.exp, (-1, 1), True),
    "expm1": (np.expm1, (-1, 1), True),
    "log": (np.log, (0.5, 2.0), True),
    "log10": (np.log10, (0.5, 2.0), True),
    "log1p": (np.log1p, (-0.5, 1.0), True),
    "log2": (np.log2, (0.5, 2.0), True),
    "sin": (np.sin, (-1, 1), True),
    "cos": (np.cos, (-1, 1), True),
    "tan": (np.tan, (-1, 1), True),
    "sinh": (np.sinh, (-1, 1), True),
    "cosh": (np.cosh, (-1, 1), True),
    "tanh": (np.tanh, (-1, 1), True),
    "arcsin": (np.arcsin, (-0.8, 0.8), True),
    "arccos": (np.arccos, (-0.8, 0.8), True),
    "arctan": (np.arctan, (-1, 1), True),
    "arcsinh": (np.arcsinh, (-1, 1), True),
    "arccosh": (np.arccosh, (1.2, 2.0), True),
    "arctanh": (np.arctanh, (-0.8, 0.8), True),
    "degrees": (np.degrees, (-1, 1), True),
    "radians": (np.radians, (-1, 1), True),
    "gamma": (_v(math.gamma), (1.2, 3.0), True),
    "gammaln": (_v(math.lgamma), (1.2, 3.0), True),
    "erf": (_v(math.erf), (-1, 1), True),
    "relu": (lambda x: np.maximum(x, 0), (0.1, 1.0), True),
    "sigmoid": (lambda x: 1 / (1 + np.exp(-x)), (-1, 1), True),
    "softsign": (lambda x: x / (1 + np.abs(x)), (0.1, 1.0), True),
    "ceil": (np.ceil, (-1, 1), False),
    "floor": (np.floor, (-1, 1), False),
    "rint": (np.rint, (-1, 1), False),
    "round": (np.round, (-1, 1), False),
    "fix": (np.fix, (-1, 1), False),
    "trunc": (np.trunc, (-1, 1), False),
    "logical_not": (lambda x: (x == 0).astype(np.float32), (-1, 1), False),
}
for name, (fn, dom, diff) in UNARY.items():
    x = f32((2, 3), *dom)
    spec(name, [x], oracle=lambda x, _fn=fn: _fn(x),
         grad_args=(0,) if diff else (), rtol=1e-4, atol=1e-5)

# -- binary broadcast --------------------------------------------------------
BINARY = {
    "broadcast_add": (np.add, True),
    "broadcast_sub": (np.subtract, True),
    "broadcast_mul": (np.multiply, True),
    "broadcast_div": (np.divide, True),
    "broadcast_mod": (np.fmod, False),
    "broadcast_maximum": (np.maximum, False),
    "broadcast_minimum": (np.minimum, False),
    "broadcast_hypot": (np.hypot, True),
    "broadcast_power": (np.power, True),
    "broadcast_equal": (lambda a, b: (a == b).astype(np.float32), False),
    "broadcast_not_equal": (lambda a, b: (a != b).astype(np.float32), False),
    "broadcast_greater": (lambda a, b: (a > b).astype(np.float32), False),
    "broadcast_greater_equal":
        (lambda a, b: (a >= b).astype(np.float32), False),
    "broadcast_lesser": (lambda a, b: (a < b).astype(np.float32), False),
    "broadcast_lesser_equal":
        (lambda a, b: (a <= b).astype(np.float32), False),
    "broadcast_logical_and":
        (lambda a, b: ((a != 0) & (b != 0)).astype(np.float32), False),
    "broadcast_logical_or":
        (lambda a, b: ((a != 0) | (b != 0)).astype(np.float32), False),
    "broadcast_logical_xor":
        (lambda a, b: ((a != 0) ^ (b != 0)).astype(np.float32), False),
}
for name, (fn, diff) in BINARY.items():
    a, b = f32((2, 3), 0.5, 1.5), f32((1, 3), 0.5, 1.5)
    spec(name, [a, b], oracle=fn, grad_args=(0, 1) if diff else ())

spec("_grad_add", [f32((2, 3)), f32((2, 3))], oracle=np.add,
     grad_args=(0, 1))
spec("add_n", [f32((2, 3)), f32((2, 3)), f32((2, 3))],
     oracle=lambda *xs: sum(xs), grad_args=(0, 1, 2))

# -- scalar ops --------------------------------------------------------------
SCALAR = {
    "_plus_scalar": (lambda x, s: x + s, True),
    "_minus_scalar": (lambda x, s: x - s, True),
    "_rminus_scalar": (lambda x, s: s - x, True),
    "_mul_scalar": (lambda x, s: x * s, True),
    "_div_scalar": (lambda x, s: x / s, True),
    "_rdiv_scalar": (lambda x, s: s / x, True),
    "_mod_scalar": (lambda x, s: np.fmod(x, s), False),
    "_rmod_scalar": (lambda x, s: np.fmod(s, x), False),
    "_power_scalar": (lambda x, s: np.power(x, s), True),
    "_rpower_scalar": (lambda x, s: np.power(s, x), True),
    "_maximum_scalar": (lambda x, s: np.maximum(x, s), False),
    "_minimum_scalar": (lambda x, s: np.minimum(x, s), False),
    "_hypot_scalar": (lambda x, s: np.hypot(x, s), True),
    "_equal_scalar": (lambda x, s: (x == s).astype(np.float32), False),
    "_not_equal_scalar": (lambda x, s: (x != s).astype(np.float32), False),
    "_greater_scalar": (lambda x, s: (x > s).astype(np.float32), False),
    "_greater_equal_scalar":
        (lambda x, s: (x >= s).astype(np.float32), False),
    "_lesser_scalar": (lambda x, s: (x < s).astype(np.float32), False),
    "_lesser_equal_scalar":
        (lambda x, s: (x <= s).astype(np.float32), False),
}
for name, (fn, diff) in SCALAR.items():
    x = f32((2, 3), 0.6, 1.6)
    spec(name, [x], attrs={"scalar": 1.3},
         oracle=lambda x, scalar, _fn=fn: _fn(x, scalar),
         grad_args=(0,) if diff else ())

spec("smooth_l1", [f32((2, 3), 0.3, 2.0)], attrs={"scalar": 1.0},
     oracle=lambda x, scalar: np.where(
         np.abs(x) < 1.0 / scalar**2,
         0.5 * (scalar * x)**2, np.abs(x) - 0.5 / scalar**2),
     grad_args=(0,))
spec("clip", [f32((2, 3), -2, 2)], attrs={"a_min": -0.5, "a_max": 0.5},
     oracle=lambda x, a_min, a_max: np.clip(x, a_min, a_max))

# -- reductions --------------------------------------------------------------
REDUCE = {
    "sum": np.sum, "mean": np.mean, "prod": np.prod, "nansum": np.nansum,
    "nanprod": np.nanprod, "max": np.max, "min": np.min,
}
for name, fn in REDUCE.items():
    x = f32((2, 3, 2), 0.4, 1.4)
    diff = name in ("sum", "mean", "max", "min")
    spec(name, [x], oracle=lambda x, _fn=fn: _fn(x),
         grad_args=(0,) if name in ("sum", "mean") else ())
    spec(name, [x], attrs={"axis": 1},
         oracle=lambda x, axis, _fn=fn: _fn(x, axis=axis))
    spec(name, [x], attrs={"axis": (0, 2), "keepdims": True},
         oracle=lambda x, axis, keepdims, _fn=fn:
         _fn(x, axis=axis, keepdims=keepdims))

spec("argmax", [f32((3, 4))], attrs={"axis": 1},
     oracle=lambda x, axis: np.argmax(x, axis=axis).astype(np.float32))
spec("argmin", [f32((3, 4))], attrs={"axis": 1},
     oracle=lambda x, axis: np.argmin(x, axis=axis).astype(np.float32))
spec("argmax_channel", [f32((3, 4))],
     oracle=lambda x: np.argmax(x, axis=1).astype(np.float32))
spec("norm", [f32((3, 4))],
     oracle=lambda x: np.sqrt((x * x).sum())[None], grad_args=(0,))
spec("_square_sum", [f32((3, 4))], attrs={"axis": 1},
     oracle=lambda x, axis: (x * x).sum(axis=axis), grad_args=(0,))

# -- softmax family ----------------------------------------------------------
def _np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)

spec("softmax", [f32((3, 4))], oracle=lambda x: _np_softmax(x),
     grad_args=(0,))
spec("log_softmax", [f32((3, 4))],
     oracle=lambda x: np.log(_np_softmax(x)), grad_args=(0,))
_xe_x, _xe_l = f32((3, 4)), np.array([0, 2, 1], np.float32)
spec("softmax_cross_entropy", [_xe_x, _xe_l],
     oracle=lambda x, l: np.array(
         [-np.log(_np_softmax(x))[np.arange(3), l.astype(int)].sum()],
         np.float32),
     grad_args=(0,))

# -- shape/matrix ops --------------------------------------------------------
spec("reshape", [f32((2, 6))], attrs={"shape": (3, 4)},
     oracle=lambda x, shape: x.reshape(shape), grad_args=(0,))
spec("Flatten", [f32((2, 3, 2))],
     oracle=lambda x: x.reshape(2, 6), grad_args=(0,))
spec("transpose", [f32((2, 3, 4))], attrs={"axes": (2, 0, 1)},
     oracle=lambda x, axes: x.transpose(axes), grad_args=(0,))
spec("SwapAxis", [f32((2, 3, 4))], attrs={"dim1": 0, "dim2": 2},
     oracle=lambda x, dim1, dim2: np.swapaxes(x, dim1, dim2),
     grad_args=(0,))
spec("expand_dims", [f32((2, 3))], attrs={"axis": 1},
     oracle=lambda x, axis: np.expand_dims(x, axis), grad_args=(0,))
spec("squeeze", [f32((2, 1, 3))], attrs={"axis": 1},
     oracle=lambda x, axis: np.squeeze(x, axis))
spec("slice", [f32((4, 5))], attrs={"begin": (1, 0), "end": (3, 4)},
     oracle=lambda x, begin, end: x[1:3, 0:4], grad_args=(0,))
spec("slice_axis", [f32((4, 5))], attrs={"axis": 1, "begin": 1, "end": 4},
     oracle=lambda x, axis, begin, end: x[:, 1:4], grad_args=(0,))
spec("slice_like", [f32((4, 5)), f32((2, 3))],
     oracle=lambda x, ref: x[:2, :3])
spec("_index", [f32((4, 5))], attrs={"index": (1,)},
     oracle=lambda x, index: x[1])
spec("_slice_assign", [f32((4, 4)), f32((2, 2))],
     attrs={"begin": (1, 1), "end": (3, 3)},
     oracle=lambda x, y, begin, end: _np_slice_assign(x, y))
def _np_slice_assign(x, y):
    out = x.copy()
    out[1:3, 1:3] = y
    return out
spec("_crop_assign_scalar", [f32((4, 4))],
     attrs={"begin": (1, 1), "end": (3, 3), "scalar": 7.0},
     oracle=lambda x, begin, end, scalar: _np_crop_assign(x, scalar))
def _np_crop_assign(x, s):
    out = x.copy()
    out[1:3, 1:3] = s
    return out
spec("repeat", [f32((2, 3))], attrs={"repeats": 2, "axis": 1},
     oracle=lambda x, repeats, axis: np.repeat(x, repeats, axis),
     grad_args=(0,))
spec("tile", [f32((2, 3))], attrs={"reps": (2, 2)},
     oracle=lambda x, reps: np.tile(x, reps), grad_args=(0,))
spec("reverse", [f32((3, 4))], attrs={"axis": 1},
     oracle=lambda x, axis: x[:, ::-1], grad_args=(0,))
spec("stack", [f32((2, 3)), f32((2, 3))], attrs={"axis": 1},
     oracle=lambda a, b, axis: np.stack([a, b], axis), grad_args=(0, 1))
spec("Concat", [f32((2, 3)), f32((2, 2))], attrs={"dim": 1},
     oracle=lambda a, b, dim: np.concatenate([a, b], dim),
     grad_args=(0, 1))
spec("SliceChannel", [f32((2, 6))], attrs={"num_outputs": 3, "axis": 1},
     oracle=lambda x, num_outputs, axis:
         [x[:, 0:2], x[:, 2:4], x[:, 4:6]], grad_args=(0,))
_w_c = (R.uniform(size=(2, 3)) > 0.5).astype(np.float32)
spec("where", [_w_c, f32((2, 3)), f32((2, 3))],
     oracle=lambda c, x, y: np.where(c != 0, x, y), grad_args=(1, 2))
spec("broadcast_axis", [f32((2, 1, 3))], attrs={"axis": 1, "size": 4},
     oracle=lambda x, axis, size: np.broadcast_to(x, (2, 4, 3)),
     grad_args=(0,))
spec("broadcast_to", [f32((2, 1))], attrs={"shape": (2, 3)},
     oracle=lambda x, shape: np.broadcast_to(x, shape), grad_args=(0,))
spec("broadcast_like", [f32((2, 1)), f32((2, 3))],
     oracle=lambda x, ref: np.broadcast_to(x, ref.shape))
spec("Pad", [f32((1, 2, 3, 3))],
     attrs={"mode": "constant",
            "pad_width": (0, 0, 0, 0, 1, 1, 1, 1), "constant_value": 0.5},
     oracle=lambda x, mode, pad_width, constant_value: np.pad(
         x, ((0, 0), (0, 0), (1, 1), (1, 1)), mode="constant",
         constant_values=constant_value), grad_args=(0,))
spec("Crop", [f32((1, 2, 5, 5))], attrs={"offset": (1, 1), "h_w": (3, 3),
                                         "num_args": 1},
     oracle=lambda x, offset, h_w, num_args: x[:, :, 1:4, 1:4])
spec("_copy", [f32((2, 3))], oracle=lambda x: x, grad_args=(0,))
spec("BlockGrad", [f32((2, 3))], oracle=lambda x: x)
spec("make_loss", [f32((2, 3))], oracle=lambda x: x, grad_args=(0,))
spec("Cast", [f32((2, 3))], attrs={"dtype": "float64"},
     oracle=lambda x, dtype: x.astype(np.float64))
spec("_identity_with_attr_like_rhs", [f32((2, 3)), f32((2, 3))],
     oracle=lambda x, r: x)
spec("IdentityAttachKLSparseReg", [f32((2, 3))], oracle=lambda x: x)
spec("zeros_like", [f32((2, 3))], oracle=np.zeros_like)
spec("ones_like", [f32((2, 3))], oracle=np.ones_like)
spec("shuffle", [f32((6, 2))],
     oracle=None)  # checked separately: permutation property

# -- dot/linalg --------------------------------------------------------------
spec("dot", [f32((2, 3)), f32((3, 4))], oracle=np.dot, grad_args=(0, 1))
spec("dot", [f32((3, 2)), f32((3, 4))], attrs={"transpose_a": True},
     oracle=lambda a, b, transpose_a: a.T @ b, grad_args=(0, 1))
spec("batch_dot", [f32((2, 2, 3)), f32((2, 3, 4))],
     oracle=lambda a, b: np.einsum("bij,bjk->bik", a, b),
     grad_args=(0, 1))
spec("_linalg_gemm", [f32((2, 3)), f32((3, 4)), f32((2, 4))],
     attrs={"alpha": 2.0, "beta": 0.5},
     oracle=lambda a, b, c, alpha, beta: alpha * (a @ b) + beta * c,
     grad_args=(0, 1, 2))
spec("_linalg_gemm2", [f32((2, 3)), f32((3, 4))],
     oracle=lambda a, b: a @ b, grad_args=(0, 1))
_spd = np.array([[2.0, 0.5], [0.5, 1.5]], np.float32)
_tri = np.array([[1.5, 0.0], [0.5, 2.0]], np.float32)
spec("_linalg_potrf", [_spd],
     oracle=lambda a: np.linalg.cholesky(a))
spec("_linalg_potri", [_tri],
     oracle=lambda a: np.linalg.inv(np.tril(a) @ np.tril(a).T),
     rtol=1e-3, atol=1e-4)
spec("_linalg_trmm", [_tri, f32((2, 2))],
     oracle=lambda a, b: np.tril(a) @ b)
spec("_linalg_trsm", [_tri, f32((2, 2))],
     oracle=lambda a, b: np.linalg.solve(np.tril(a), b), rtol=1e-3)
spec("_linalg_syrk", [f32((2, 3))],
     oracle=lambda a: a @ a.T)
spec("_linalg_sumlogdiag", [_spd],
     oracle=lambda a: np.array([np.log(np.diag(a)).sum()], np.float32))
spec("_linalg_gelqf", [f32((2, 3))], oracle=None)  # property-checked below
spec("khatri_rao", [f32((2, 3)), f32((4, 3))],
     oracle=lambda a, b: np.vstack([np.kron(a[:, j], b[:, j])
                                    for j in range(3)]).T.reshape(8, 3)
     if False else np.concatenate(
         [(a[:, j][:, None] * b[:, j][None, :]).reshape(-1, 1)
          for j in range(3)], axis=1))

# -- indexing ----------------------------------------------------------------
_emb_idx = np.array([0, 2, 1], np.float32)
_emb_w = f32((3, 4))
spec("Embedding", [_emb_idx, _emb_w],
     attrs={"input_dim": 3, "output_dim": 4},
     oracle=lambda i, w, input_dim, output_dim: w[i.astype(int)])
spec("take", [f32((4, 3)), np.array([0, 3, 1], np.float32)],
     oracle=lambda a, i: a[i.astype(int)])
spec("batch_take", [f32((3, 4)), np.array([1, 0, 3], np.float32)],
     oracle=lambda a, i: a[np.arange(3), i.astype(int)])
spec("pick", [f32((3, 4)), np.array([1, 0, 3], np.float32)],
     oracle=lambda a, i: a[np.arange(3), i.astype(int)])
spec("one_hot", [np.array([0, 2, 1], np.float32)], attrs={"depth": 4},
     oracle=lambda i, depth: np.eye(depth, dtype=np.float32)[
         i.astype(int)])
spec("gather_nd", [f32((3, 4)), np.array([[0, 2], [1, 3]], np.float32)],
     oracle=lambda a, i: a[i[0].astype(int), i[1].astype(int)])
spec("scatter_nd", [f32((2,)), np.array([[0, 2], [1, 3]], np.float32)],
     attrs={"shape": (3, 4)},
     oracle=lambda d, i, shape: _np_scatter(d, i, shape))
def _np_scatter(d, i, shape):
    out = np.zeros(shape, np.float32)
    out[i[0].astype(int), i[1].astype(int)] = d
    return out
spec("_sparse_retain", [f32((4, 3)), np.array([0, 2], np.float32)],
     oracle=lambda d, i: _np_retain(d, i))
def _np_retain(d, i):
    out = np.zeros_like(d)
    out[i.astype(int)] = d[i.astype(int)]
    return out

# -- ordering ----------------------------------------------------------------
spec("sort", [f32((3, 4))],
     oracle=lambda x: np.sort(x, axis=-1))
spec("sort", [f32((3, 4))], attrs={"is_ascend": False},
     oracle=lambda x, is_ascend: -np.sort(-x, axis=-1))
spec("argsort", [f32((3, 4))],
     oracle=lambda x: np.argsort(x, axis=-1).astype(np.float32))
spec("topk", [f32((3, 5))], attrs={"k": 2},
     oracle=lambda x, k: np.argsort(-x, axis=-1)[:, :k].astype(
         np.float32))
spec("topk", [f32((3, 5))], attrs={"k": 2, "ret_typ": "value"},
     oracle=lambda x, k, ret_typ: -np.sort(-x, axis=-1)[:, :k])

# -- neural net --------------------------------------------------------------
_fc_x, _fc_w, _fc_b = f32((3, 5)), f32((4, 5)), f32((4,))
spec("FullyConnected", [_fc_x, _fc_w, _fc_b], attrs={"num_hidden": 4},
     oracle=lambda x, w, b, num_hidden: x @ w.T + b,
     grad_args=(0, 1, 2))


def _np_conv(x, w, b, stride=1, pad=0):
    n, ci, h, ww_ = x.shape
    co, _, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (ww_ + 2 * pad - kw) // stride + 1
    out = np.zeros((n, co, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride:i * stride + kh,
                       j * stride:j * stride + kw]
            out[:, :, i, j] = np.einsum("ncij,ocij->no", patch, w)
    return out + (b[None, :, None, None] if b is not None else 0)


_cv_x, _cv_w, _cv_b = f32((2, 3, 5, 5)), f32((4, 3, 3, 3)), f32((4,))
spec("Convolution", [_cv_x, _cv_w, _cv_b],
     attrs={"kernel": (3, 3), "num_filter": 4, "pad": (1, 1)},
     oracle=lambda x, w, b, kernel, num_filter, pad:
         _np_conv(x, w, b, 1, 1),
     grad_args=(1, 2), rtol=1e-3, atol=1e-4,
     grad_rtol=5e-2, grad_atol=3e-3)
spec("Convolution", [_cv_x, _cv_w, _cv_b],
     attrs={"kernel": (3, 3), "num_filter": 4, "stride": (2, 2)},
     oracle=lambda x, w, b, kernel, num_filter, stride:
         _np_conv(x, w, b, 2, 0), rtol=1e-3, atol=1e-4)


def _np_pool(x, k, stride, mode="max"):
    n, c, h, w = x.shape
    oh = (h - k) // stride + 1
    ow = (w - k) // stride + 1
    out = np.zeros((n, c, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, :, i * stride:i * stride + k,
                      j * stride:j * stride + k]
            out[:, :, i, j] = patch.max((2, 3)) if mode == "max" \
                else patch.mean((2, 3))
    return out


spec("Pooling", [f32((2, 3, 4, 4))],
     attrs={"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"},
     oracle=lambda x, kernel, stride, pool_type: _np_pool(x, 2, 2, "max"))
spec("Pooling", [f32((2, 3, 4, 4))],
     attrs={"kernel": (2, 2), "stride": (2, 2), "pool_type": "avg"},
     oracle=lambda x, kernel, stride, pool_type: _np_pool(x, 2, 2, "avg"))
spec("Pooling", [f32((2, 3, 4, 4))],
     attrs={"kernel": (2, 2), "global_pool": True, "pool_type": "max"},
     oracle=lambda x, kernel, global_pool, pool_type:
         x.max((2, 3), keepdims=True))

_bn_x = f32((2, 3, 4, 4))
_bn_g, _bn_b = f32((3,), 0.5, 1.5), f32((3,))
_bn_mm, _bn_mv = np.zeros(3, np.float32), np.ones(3, np.float32)
spec("BatchNorm", [_bn_x, _bn_g, _bn_b, _bn_mm, _bn_mv],
     attrs={"is_train": False, "eps": 1e-3, "fix_gamma": False},
     oracle=lambda x, g, b, mm, mv, is_train, eps, fix_gamma:
         g[None, :, None, None] * (x - mm[None, :, None, None]) /
         np.sqrt(mv[None, :, None, None] + eps) + b[None, :, None, None],
     rtol=1e-3, atol=1e-4)
spec("LayerNorm", [f32((3, 5)), f32((5,), 0.5, 1.5), f32((5,))],
     oracle=lambda x, g, b: g * (x - x.mean(-1, keepdims=True)) /
         np.sqrt(x.var(-1, keepdims=True) + 1e-5) + b,
     rtol=1e-3, atol=1e-4)
spec("InstanceNorm", [f32((2, 3, 4)), f32((3,), 0.5, 1.5), f32((3,))],
     oracle=lambda x, g, b: g[None, :, None] *
         (x - x.mean(-1, keepdims=True)) /
         np.sqrt(x.var(-1, keepdims=True) + 1e-3) + b[None, :, None],
     rtol=1e-3, atol=1e-4)
spec("L2Normalization", [f32((2, 6))],
     oracle=lambda x: x / np.sqrt((x * x).sum(1, keepdims=True) + 1e-10),
     grad_args=(0,))
def _lrn_oracle(x, nsize, alpha=1e-4, beta=0.75, knorm=2.0):
    """Across-channel LRN, reference lrn-inl.h semantics."""
    sq = x * x
    half = nsize // 2
    C = x.shape[1]
    den = np.zeros_like(x)
    for c in range(C):
        lo, hi = max(0, c - half), min(C, c + half + 1)
        den[:, c] = sq[:, lo:hi].sum(1)
    return x / (knorm + alpha / nsize * den) ** beta


spec("LRN", [f32((2, 5, 3, 3))], attrs={"nsize": 3},
     oracle=_lrn_oracle)
spec("Activation", [f32((2, 3))], attrs={"act_type": "relu"},
     oracle=lambda x, act_type: np.maximum(x, 0))
spec("Activation", [f32((2, 3))], attrs={"act_type": "tanh"},
     oracle=lambda x, act_type: np.tanh(x))
spec("Activation", [f32((2, 3))], attrs={"act_type": "sigmoid"},
     oracle=lambda x, act_type: 1 / (1 + np.exp(-x)))
spec("Activation", [f32((2, 3))], attrs={"act_type": "softrelu"},
     oracle=lambda x, act_type: np.log1p(np.exp(x)))
spec("LeakyReLU", [f32((2, 3))], attrs={"act_type": "leaky",
                                        "slope": 0.1},
     oracle=lambda x, act_type, slope: np.where(x > 0, x, slope * x))
spec("LeakyReLU", [f32((2, 3))], attrs={"act_type": "elu", "slope": 0.3},
     oracle=lambda x, act_type, slope:
         np.where(x > 0, x, slope * np.expm1(x)))
spec("SoftmaxActivation", [f32((3, 4))],
     oracle=lambda x: _np_softmax(x))
spec("Dropout", [f32((2, 3))], attrs={"p": 0.0},
     oracle=lambda x, p: x)
spec("Dropout", [f32((2, 3))], attrs={"p": 0.5, "is_train": False},
     oracle=lambda x, p, is_train: x)
spec("UpSampling", [f32((1, 2, 2, 2))],
     attrs={"scale": 2, "sample_type": "nearest", "num_args": 1},
     oracle=lambda x, scale, sample_type, num_args:
         x.repeat(2, 2).repeat(2, 3))

_sq_data = f32((4, 2, 3))   # (seq, batch, feat)
_sq_len = np.array([2, 4], np.float32)
spec("SequenceMask", [_sq_data, _sq_len],
     attrs={"use_sequence_length": True, "value": 0.0},
     oracle=lambda d, l, use_sequence_length, value: _np_seq_mask(d, l))
def _np_seq_mask(d, l):
    out = d.copy()
    for b, n in enumerate(l.astype(int)):
        out[n:, b] = 0.0
    return out
spec("SequenceLast", [_sq_data, _sq_len],
     attrs={"use_sequence_length": True},
     oracle=lambda d, l, use_sequence_length:
         np.stack([d[int(n) - 1, b] for b, n in enumerate(l)], 0))
spec("SequenceReverse", [_sq_data, _sq_len],
     attrs={"use_sequence_length": True},
     oracle=lambda d, l, use_sequence_length: _np_seq_rev(d, l))
def _np_seq_rev(d, l):
    out = d.copy()
    for b, n in enumerate(l.astype(int)):
        out[:n, b] = d[:n, b][::-1]
    return out

# -- losses ------------------------------------------------------------------
_lbl3 = np.array([0, 2, 1], np.float32)
spec("SoftmaxOutput", [f32((3, 4)), _lbl3],
     oracle=lambda x, l: _np_softmax(x))
spec("LinearRegressionOutput", [f32((3, 2)), f32((3, 2))],
     oracle=lambda x, l: x)
spec("LogisticRegressionOutput", [f32((3, 2)), f32((3, 2))],
     oracle=lambda x, l: 1 / (1 + np.exp(-x)))
spec("MAERegressionOutput", [f32((3, 2)), f32((3, 2))],
     oracle=lambda x, l: x)
spec("MakeLoss", [f32((3, 2), 0.1, 1.0)], oracle=lambda x: x)
spec("SVMOutput", [f32((3, 4)), _lbl3], oracle=lambda x, l: x)

# -- optimizer updates -------------------------------------------------------
_w0, _g0 = f32((3, 2)), f32((3, 2))
spec("sgd_update", [_w0, _g0], attrs={"lr": 0.1, "wd": 0.01},
     oracle=lambda w, g, lr, wd: w - lr * (g + wd * w))
_m0 = f32((3, 2))
spec("sgd_mom_update", [_w0, _g0, _m0],
     attrs={"lr": 0.1, "momentum": 0.9, "wd": 0.01},
     oracle=lambda w, g, m, lr, momentum, wd: _np_sgd_mom(w, g, m)[0])
def _np_sgd_mom(w, g, m, lr=0.1, mom=0.9, wd=0.01):
    m2 = mom * m - lr * (g + wd * w)
    return w + m2, m2
spec("signsgd_update", [_w0, _g0], attrs={"lr": 0.1},
     oracle=lambda w, g, lr: w - lr * np.sign(g))
_mean0, _var0 = f32((3, 2), 0.0, 0.1), f32((3, 2), 0.0, 0.1)
spec("adam_update", [_w0, _g0, _mean0, _var0],
     attrs={"lr": 0.1, "beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
     oracle=lambda w, g, m, v, lr, beta1, beta2, epsilon:
         w - lr * (beta1 * m + (1 - beta1) * g) /
         (np.sqrt(beta2 * v + (1 - beta2) * g * g) + epsilon))
_n0 = f32((3, 2), 0.0, 0.1)
spec("rmsprop_update", [_w0, _g0, _n0],
     attrs={"lr": 0.1, "gamma1": 0.95, "epsilon": 1e-8},
     oracle=lambda w, g, n, lr, gamma1, epsilon:
         w - lr * g / np.sqrt(gamma1 * n + (1 - gamma1) * g * g + epsilon))
def _rmspropalex_oracle(w, g, n, gbar, delta, lr, gamma1=0.95,
                        gamma2=0.9, epsilon=1e-8):
    """Graves RMSProp (rmsprop_update's centered sibling)."""
    nn_ = (1 - gamma1) * g * g + gamma1 * n
    gb = (1 - gamma1) * g + gamma1 * gbar
    d = gamma2 * delta - lr * g / np.sqrt(nn_ - gb * gb + epsilon)
    return w + d          # states update in place at the nd level


spec("rmspropalex_update",
     [_w0, _g0, _n0, f32((3, 2), 0.0, 0.1), f32((3, 2), 0.0, 0.1)],
     attrs={"lr": 0.1}, oracle=_rmspropalex_oracle)
def _ftrl_oracle(w, g, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0):
    nn_ = n + g * g
    sigma = (np.sqrt(nn_) - np.sqrt(n)) / lr
    zz = z + g - sigma * w
    return np.where(np.abs(zz) <= lamda1, 0.0,
                    -(zz - np.sign(zz) * lamda1)
                    / ((beta + np.sqrt(nn_)) / lr + wd))


spec("ftrl_update", [_w0, _g0, f32((3, 2), 0.0, 0.1),
                     f32((3, 2), 0.0, 0.1)],
     attrs={"lr": 0.1}, oracle=_ftrl_oracle)
spec("mp_sgd_update", [_w0, _g0, _w0.astype(np.float32)],
     attrs={"lr": 0.1, "wd": 0.01},
     oracle=lambda w, g, w32, lr, wd: (w32 - lr * (g + wd * w32)))
def _mp_sgd_mom_oracle(w, g, m, w32, lr, momentum=0.9, wd=0.01):
    gg = g.astype(np.float32) + wd * w32
    mm = momentum * m - lr * gg
    return (w32 + mm).astype(w.dtype)


spec("mp_sgd_mom_update", [_w0, _g0, _m0, _w0.astype(np.float32)],
     attrs={"lr": 0.1, "momentum": 0.9, "wd": 0.01},
     oracle=_mp_sgd_mom_oracle)

# -- init ops (no tensor inputs) --------------------------------------------
spec("_zeros", [], attrs={"shape": (2, 3)},
     oracle=lambda shape: np.zeros(shape, np.float32))
spec("_ones", [], attrs={"shape": (2, 3)},
     oracle=lambda shape: np.ones(shape, np.float32))
spec("_full", [], attrs={"shape": (2, 3), "value": 2.5},
     oracle=lambda shape, value: np.full(shape, value, np.float32))
spec("_arange", [], attrs={"start": 1.0, "stop": 7.0, "step": 2.0},
     oracle=lambda start, stop, step: np.arange(1.0, 7.0, 2.0,
                                                dtype=np.float32))
spec("_eye", [], attrs={"N": 3, "M": 4, "k": 1},
     oracle=lambda N, M, k: np.eye(N, M, k, dtype=np.float32))

# -- random samplers: moment checks ------------------------------------------
RANDOM_MOMENTS = {
    # name, attrs, expected mean, sd of estimator bound
    "_random_uniform": ({"low": 0.0, "high": 1.0, "shape": (4000,)}, 0.5,
                        0.05),
    "_random_normal": ({"loc": 1.0, "scale": 1.0, "shape": (4000,)}, 1.0,
                       0.08),
    "_random_exponential": ({"lam": 2.0, "shape": (4000,)}, 0.5, 0.05),
    "_random_gamma": ({"alpha": 2.0, "beta": 1.0, "shape": (4000,)}, 2.0,
                      0.15),
    "_random_poisson": ({"lam": 3.0, "shape": (4000,)}, 3.0, 0.15),
    "_random_negative_binomial": ({"k": 4, "p": 0.5, "shape": (4000,)},
                                  4.0, 0.3),
    "_random_generalized_negative_binomial":
        ({"mu": 2.0, "alpha": 0.3, "shape": (4000,)}, 2.0, 0.3),
}

SAMPLE_VEC = {
    "_sample_uniform": ([np.array([0.0, 1.0], np.float32),
                         np.array([1.0, 3.0], np.float32)],
                        np.array([0.5, 2.0])),
    "_sample_normal": ([np.array([0.0, 2.0], np.float32),
                        np.array([1.0, 0.5], np.float32)],
                       np.array([0.0, 2.0])),
    "_sample_exponential": ([np.array([1.0, 4.0], np.float32)],
                            np.array([1.0, 0.25])),
    "_sample_gamma": ([np.array([2.0, 3.0], np.float32),
                       np.array([1.0, 2.0], np.float32)],
                      np.array([2.0, 6.0])),
    "_sample_poisson": ([np.array([2.0, 5.0], np.float32)],
                        np.array([2.0, 5.0])),
    "_sample_negative_binomial": ([np.array([4.0, 2.0], np.float32),
                                   np.array([0.5, 0.5], np.float32)],
                                  np.array([4.0, 2.0])),
    "_sample_generalized_negative_binomial":
        ([np.array([2.0, 3.0], np.float32),
          np.array([0.2, 0.2], np.float32)], np.array([2.0, 3.0])),
}

# ops verified by their own dedicated tests elsewhere / not point-testable
EXEMPT = {
    "Deconvolution",       # covered in test_ops_nn
    "Dropout",             # train-mode distribution checked below
    "Embedding",
    "sample_multinomial",  # distribution checked below
    "shuffle",             # permutation checked below
    "cast_storage",        # sparse tests
    "_linalg_gelqf",       # property checked below
    "CTCLoss",             # tests/test_ctc.py
    "RNN",                 # tests/test_rnn_op.py
    "Custom",              # tests/test_custom_op.py
    # warp family — tests/test_warp_and_predict.py (vs oracles + grads)
    "BilinearSampler", "SpatialTransformer", "GridGenerator",
    "Correlation",
    # SSD stack — tests/test_ssd.py + test_detection_ops.py
    "_contrib_MultiBoxPrior", "_contrib_MultiBoxTarget",
    "_contrib_MultiBoxDetection", "ROIPooling",
    # RCNN family — tests/test_rcnn_contrib_ops.py (numpy oracles)
    "_contrib_Proposal", "_contrib_MultiProposal",
    "_contrib_PSROIPooling", "_contrib_DeformablePSROIPooling",
    "_contrib_DeformableConvolution",
    # contrib tail — tests/test_rcnn_contrib_ops.py
    "_contrib_fft", "_contrib_ifft", "_contrib_count_sketch",
    "_contrib_quantize", "_contrib_dequantize",
    # attention — tests/test_attention.py (vs reference + grads)
    "_contrib_FlashAttention",
    # MoE — tests/test_pipeline_moe.py (dense-vs-expert-parallel + gates)
    "_contrib_MoEFFN",
}


# ---------------------------------------------------------------------------
# the tests
# ---------------------------------------------------------------------------
ALL_CASES = [(name, i) for name, cases in sorted(SPECS.items())
             for i in range(len(cases))]


ALL_IDS = ["%s-%d" % p for p in ALL_CASES]


@pytest.mark.parametrize("name,idx", ALL_CASES, ids=ALL_IDS)
def test_forward_vs_numpy(name, idx):
    case = SPECS[name][idx]
    if case["oracle"] is None:
        pytest.skip("no oracle")
    ins = [nd.array(x) for x in case["inputs"]]
    out = getattr(nd, name)(*ins, **case["attrs"])
    want = case["oracle"](*case["inputs"], **case["attrs"])
    outs = out if isinstance(out, list) else [out]
    wants = want if isinstance(want, list) else [want]
    assert len(outs) >= len(wants)
    for o, w in zip(outs, wants):
        np.testing.assert_allclose(o.asnumpy(), np.asarray(w),
                                   rtol=case["rtol"], atol=case["atol"],
                                   err_msg=name)


@pytest.mark.parametrize("name,idx", ALL_CASES, ids=ALL_IDS)
def test_eager_vs_jit(name, idx):
    """Interpret-mode vs jit-compiled output of the raw kernel."""
    case = SPECS[name][idx]
    op = registry.get_op(name)
    attrs = registry.canon_attrs(op, case["attrs"])
    if op.takes_is_train and "is_train" not in attrs:
        attrs["is_train"] = False
    arrays = [jnp.asarray(x) for x in case["inputs"]]
    if op.needs_rng:
        key = jax.random.PRNGKey(3)
        with jax.disable_jit():
            eager = op.fn(*arrays, rng=key, **attrs)
        jitted = registry.jitted_op(op, attrs)(key, *arrays)
    else:
        with jax.disable_jit():
            eager = op.fn(*arrays, **attrs)
        jitted = registry.jitted_op(op, attrs)(*arrays)
    flat_e = jax.tree_util.tree_leaves(eager)
    flat_j = jax.tree_util.tree_leaves(jitted)
    assert len(flat_e) == len(flat_j)
    for e, j in zip(flat_e, flat_j):
        np.testing.assert_allclose(np.asarray(e), np.asarray(j),
                                   rtol=1e-4, atol=1e-5, err_msg=name)


GRAD_CASES = [(name, i) for name, cases in sorted(SPECS.items())
              for i, c in enumerate(cases) if c["grad_args"]]


@pytest.mark.parametrize("name,idx", GRAD_CASES,
                         ids=["%s-%d" % p for p in GRAD_CASES])
def test_gradient_vs_finite_difference(name, idx):
    case = SPECS[name][idx]
    op = registry.get_op(name)
    assert op.differentiable, "%s spec requests grad but op is nondiff" \
        % name
    wrt = list(case["grad_args"])
    fixed = {i: nd.array(x) for i, x in enumerate(case["inputs"])
             if i not in wrt}
    attrs = case["attrs"]

    def f(free):
        args = []
        it = iter(free)
        for i in range(len(case["inputs"])):
            args.append(next(it) if i in wrt else fixed[i])
        out = getattr(nd, name)(*args, **attrs)
        if isinstance(out, list):
            out = out[0]
        return out

    check_numeric_gradient(
        f, [nd.array(case["inputs"][i]) for i in wrt],
        rtol=case["grad_rtol"], atol=case["grad_atol"])


@pytest.mark.parametrize("name", sorted(RANDOM_MOMENTS), ids=str)
def test_random_moments(name):
    attrs, want_mean, tol = RANDOM_MOMENTS[name]
    mx.random.seed(5)
    out = getattr(nd, name)(**attrs).asnumpy()
    assert out.shape == attrs["shape"]
    assert abs(out.mean() - want_mean) < 3 * tol, \
        (name, out.mean(), want_mean)


@pytest.mark.parametrize("name", sorted(SAMPLE_VEC), ids=str)
def test_sample_vec_moments(name):
    params, want_means = SAMPLE_VEC[name]
    mx.random.seed(6)
    out = getattr(nd, name)(*[nd.array(p) for p in params],
                            shape=(3000,)).asnumpy()
    assert out.shape == (len(want_means), 3000)
    got = out.mean(axis=1)
    np.testing.assert_allclose(got, want_means, rtol=0.25, atol=0.15)


def test_shuffle_is_permutation():
    x = np.arange(12, dtype=np.float32).reshape(6, 2)
    mx.random.seed(3)
    y = nd.shuffle(nd.array(x)).asnumpy()
    np.testing.assert_array_equal(
        np.sort(y.ravel()), np.sort(x.ravel()))


def test_sample_multinomial_distribution():
    probs = np.array([[0.8, 0.1, 0.1], [0.1, 0.1, 0.8]], np.float32)
    mx.random.seed(4)
    out = nd.sample_multinomial(nd.array(probs), shape=(500,)).asnumpy()
    assert out.shape == (2, 500)
    assert (out[0] == 0).mean() > 0.6
    assert (out[1] == 2).mean() > 0.6


def test_dropout_train_mode_scales():
    x = np.ones((50, 50), np.float32)
    from mxnet_tpu import autograd
    mx.random.seed(11)
    with autograd.train_mode():
        y = nd.Dropout(nd.array(x), p=0.5).asnumpy()
    kept = y != 0
    assert 0.35 < kept.mean() < 0.65
    np.testing.assert_allclose(y[kept], 2.0, rtol=1e-6)


def test_gelqf_property():
    a = f32((2, 3))
    q, l = nd._linalg_gelqf(nd.array(a))   # reference order: Q, L
    lq = l.asnumpy() @ q.asnumpy()
    np.testing.assert_allclose(lq, a, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(q.asnumpy() @ q.asnumpy().T, np.eye(2),
                               rtol=1e-4, atol=1e-5)


def test_sweep_coverage():
    """>=90% of registered primary ops must carry a spec or be exempt
    (exempt ops are verified by dedicated tests)."""
    primary = set(registry._OP_REGISTRY)
    covered = set(SPECS) | set(RANDOM_MOMENTS) | set(SAMPLE_VEC) | EXEMPT
    missing = sorted(primary - covered)
    frac = 1.0 - len(missing) / len(primary)
    assert frac >= 0.90, "op sweep coverage %.1f%% — missing: %s" % (
        100 * frac, missing)
