"""Sparse storage tests (reference: tests/python/unittest/
test_sparse_ndarray.py + test_sparse_operator.py, abridged to the
TPU-native surface: index-carrying representations, csr dot, retain,
sparse optimizer updates, embedding-gradient path, kvstore pull)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse


def _rand_rs(rows, cols, nnz_rows, seed=0):
    rng = np.random.RandomState(seed)
    idx = np.sort(rng.choice(rows, nnz_rows, replace=False))
    vals = rng.randn(nnz_rows, cols).astype("float32")
    return sparse.row_sparse_array((vals, idx), shape=(rows, cols)), \
        idx, vals


class TestRepresentation:
    def test_row_sparse_carries_indices(self):
        rs, idx, vals = _rand_rs(100, 4, 5)
        assert rs.stype == "row_sparse"
        assert rs.shape == (100, 4)
        assert rs.nnz == 5
        # the values buffer is (nnz, cols) — NOT a dense (100, 4) costume
        assert rs.data.shape == (5, 4)
        np.testing.assert_array_equal(rs.indices.asnumpy(), idx)
        dense = rs.asnumpy()
        assert dense.shape == (100, 4)
        np.testing.assert_allclose(dense[idx], vals)
        assert np.all(dense[np.setdiff1d(np.arange(100), idx)] == 0)

    def test_csr_carries_structure(self):
        a = np.array([[0, 1, 0], [2, 0, 3], [0, 0, 0]], "float32")
        csr = sparse.csr_matrix(a)
        np.testing.assert_array_equal(csr.indptr.asnumpy(), [0, 1, 3, 3])
        np.testing.assert_array_equal(csr.indices.asnumpy(), [1, 0, 2])
        np.testing.assert_array_equal(csr.data.asnumpy(), [1, 2, 3])
        np.testing.assert_array_equal(csr.asnumpy(), a)

    def test_csr_from_components(self):
        csr = sparse.csr_matrix(([1., 2.], [0, 1], [0, 1, 2]),
                                shape=(2, 3))
        np.testing.assert_array_equal(
            csr.asnumpy(), [[1, 0, 0], [0, 2, 0]])

    def test_cast_storage_roundtrip(self):
        rng = np.random.RandomState(0)
        a = rng.randn(6, 3).astype("float32")
        a[[0, 2, 5]] = 0
        x = nd.array(a)
        rs = x.tostype("row_sparse")
        assert rs.nnz == 3
        np.testing.assert_allclose(rs.tostype("default").asnumpy(), a)
        csr = x.tostype("csr")
        np.testing.assert_allclose(csr.tostype("default").asnumpy(), a)
        # nd.cast_storage dispatches too
        rs2 = nd.cast_storage(x, stype="row_sparse")
        assert rs2.stype == "row_sparse" and rs2.nnz == 3

    def test_unsorted_indices_canonicalized(self):
        rs = sparse.row_sparse_array(
            (np.array([[2.], [1.]], "float32"), [5, 1]), shape=(8, 1))
        np.testing.assert_array_equal(rs.indices.asnumpy(), [1, 5])
        np.testing.assert_array_equal(rs.data.asnumpy(), [[1.], [2.]])

    def test_csr_row_slice(self):
        a = np.array([[0, 1, 0], [2, 0, 3], [4, 0, 0]], "float32")
        s = sparse.csr_matrix(a)[1:3]
        assert s.stype == "csr" and s.shape == (2, 3)
        np.testing.assert_array_equal(s.asnumpy(), a[1:3])

    def test_zeros_and_scalar_math(self):
        z = sparse.zeros("row_sparse", (10, 2))
        assert z.nnz == 0 and z.asnumpy().sum() == 0
        rs, idx, vals = _rand_rs(10, 2, 3)
        np.testing.assert_allclose((rs * 2.0).asnumpy(), rs.asnumpy() * 2,
                                   rtol=1e-6)
        np.testing.assert_allclose((-rs).asnumpy(), -rs.asnumpy())

    def test_dense_ops_refused(self):
        rs, _, _ = _rand_rs(10, 2, 3)
        with pytest.raises(TypeError):
            rs[0]
        with pytest.raises(TypeError):
            rs + nd.zeros((10, 2))


class TestKernels:
    def test_csr_dot_dense(self):
        rng = np.random.RandomState(1)
        a = rng.randn(5, 7).astype("float32")
        a[rng.rand(5, 7) < 0.6] = 0
        b = rng.randn(7, 3).astype("float32")
        csr = sparse.csr_matrix(a)
        out = nd.dot(csr, nd.array(b))
        np.testing.assert_allclose(out.asnumpy(), a @ b, rtol=1e-5)

    def test_csr_dot_transpose(self):
        rng = np.random.RandomState(2)
        a = rng.randn(5, 7).astype("float32")
        a[rng.rand(5, 7) < 0.6] = 0
        b = rng.randn(5, 3).astype("float32")
        csr = sparse.csr_matrix(a)
        out = nd.dot(csr, nd.array(b), transpose_a=True)
        np.testing.assert_allclose(out.asnumpy(), a.T @ b, rtol=1e-5)

    def test_retain(self):
        rs, idx, vals = _rand_rs(50, 3, 8, seed=3)
        keep = np.array([int(idx[0]), 17, int(idx[-1])])
        assert 17 not in idx
        out = nd._sparse_retain(rs, nd.array(np.sort(keep)))
        assert out.stype == "row_sparse"
        dense = out.asnumpy()
        np.testing.assert_allclose(dense[idx[0]], vals[0], rtol=1e-6)
        np.testing.assert_allclose(dense[idx[-1]], vals[-1], rtol=1e-6)
        assert dense.sum() == pytest.approx(
            vals[0].sum() + vals[-1].sum(), rel=1e-5)

    def test_rs_add_union(self):
        a = sparse.row_sparse_array(
            (np.array([[1.], [2.]], "float32"), [0, 3]), shape=(6, 1))
        b = sparse.row_sparse_array(
            (np.array([[10.], [20.]], "float32"), [3, 5]), shape=(6, 1))
        c = a + b
        assert c.stype == "row_sparse" and c.nnz == 3
        np.testing.assert_array_equal(
            c.asnumpy().ravel(), [1, 0, 0, 12, 0, 20])

    def test_square_sum(self):
        rs, idx, vals = _rand_rs(20, 4, 5, seed=4)
        out = nd._square_sum(rs)
        np.testing.assert_allclose(out.asnumpy(),
                                   [np.square(vals).sum()], rtol=1e-5)


class TestOptimizerUpdates:
    def test_sparse_sgd_lazy(self):
        rng = np.random.RandomState(5)
        w = rng.randn(40, 4).astype("float32")
        weight = nd.array(w)
        grad, idx, gvals = _rand_rs(40, 4, 6, seed=6)
        nd.sgd_update(weight, grad, out=weight, lr=0.5, wd=0.1)
        got = weight.asnumpy()
        expect = w.copy()
        expect[idx] -= 0.5 * (gvals + 0.1 * w[idx])
        np.testing.assert_allclose(got, expect, rtol=1e-5)
        # untouched rows saw neither grad nor weight decay (lazy update)
        untouched = np.setdiff1d(np.arange(40), idx)
        np.testing.assert_array_equal(got[untouched], w[untouched])

    def test_sparse_adam_state_rows_only(self):
        rng = np.random.RandomState(7)
        w = rng.randn(30, 2).astype("float32")
        weight = nd.array(w)
        mean, var = nd.zeros((30, 2)), nd.zeros((30, 2))
        grad, idx, _ = _rand_rs(30, 2, 4, seed=8)
        nd.adam_update(weight, grad, mean, var, out=weight, lr=0.1)
        touched = np.zeros(30, bool)
        touched[idx] = True
        assert np.all(mean.asnumpy()[~touched] == 0)
        assert np.any(mean.asnumpy()[touched] != 0)
        assert np.all(weight.asnumpy()[~touched] == w[~touched])

    def test_optimizer_class_routes_sparse(self):
        opt = mx.optimizer.SGD(learning_rate=0.5, momentum=0.9,
                               rescale_grad=1.0)
        w = nd.ones((20, 3))
        state = opt.create_state(0, w)
        grad, idx, gvals = _rand_rs(20, 3, 4, seed=9)
        before = w.asnumpy()
        opt.update(0, w, grad, state)
        after = w.asnumpy()
        untouched = np.setdiff1d(np.arange(20), idx)
        assert np.all(after[untouched] == before[untouched])
        assert np.all(after[idx] != before[idx])


class TestEmbeddingGradientPath:
    def test_take_grad_matches_dense(self):
        rng = np.random.RandomState(10)
        vocab, dim = 50, 8
        tokens = rng.randint(0, vocab, size=(4, 6))
        ograd = rng.randn(4, 6, dim).astype("float32")
        rs = sparse.take_grad(tokens, nd.array(ograd), vocab)
        dense = np.zeros((vocab, dim), "float32")
        np.add.at(dense, tokens.ravel(),
                  ograd.reshape(-1, dim))
        np.testing.assert_allclose(rs.asnumpy(), dense, rtol=1e-5)

    def test_never_densifies(self):
        """The embedding gradient for a big vocab stays O(nnz): the
        row-sparse grad + sparse update never allocate (vocab, dim)."""
        vocab, dim = 200_000, 32
        tokens = np.random.RandomState(11).randint(0, vocab, size=256)
        ograd = nd.ones((256, dim))
        rs = sparse.take_grad(tokens, ograd, vocab)
        n_unique = len(np.unique(tokens))
        assert rs.nnz == n_unique
        # values buffer is ~nnz*dim*4 bytes — 3 orders below vocab*dim*4
        assert rs.data.size * 4 <= n_unique * dim * 4
        assert rs.data.size * 4 < vocab * dim * 4 / 500

        weight = nd.zeros((vocab, dim))
        nd.sgd_update(weight, rs, out=weight, lr=1.0)
        touched = weight.asnumpy()[np.unique(tokens)]
        assert np.all(touched != 0)

    def test_end_to_end_embedding_training_step(self):
        """Forward gather + sparse backward + lazy update — the
        row_sparse embedding recipe (reference sparse embedding flow)."""
        vocab, dim = 1000, 4
        rng = np.random.RandomState(12)
        weight = nd.array(rng.randn(vocab, dim).astype("float32"))
        tokens = np.array([3, 99, 3, 512])
        emb = nd.take(weight, nd.array(tokens.astype("float32")))
        ograd = nd.ones((4, dim))
        gw = sparse.take_grad(tokens, ograd, vocab)
        before = weight.asnumpy()
        nd.sgd_update(weight, gw, out=weight, lr=0.1)
        after = weight.asnumpy()
        np.testing.assert_allclose(after[3], before[3] - 0.2,
                                   rtol=1e-5)  # token 3 appears twice
        np.testing.assert_allclose(after[99], before[99] - 0.1, rtol=1e-5)
        assert np.all(after[0] == before[0])


class TestDispatchEdges:
    def test_cast_storage_dense_out_kwarg(self):
        o = nd.zeros((2, 2))
        nd.cast_storage(nd.ones((2, 2)), stype="default", out=o)
        np.testing.assert_array_equal(o.asnumpy(), np.ones((2, 2)))

    def test_cast_storage_sparse_with_out(self):
        o = sparse.zeros("row_sparse", (3, 2))
        src = np.array([[1, 1], [0, 0], [2, 2]], "float32")
        nd.cast_storage(nd.array(src), stype="row_sparse", out=o)
        assert o.nnz == 2
        np.testing.assert_array_equal(o.asnumpy(), src)

    def test_unrouted_dense_op_rejects_sparse(self):
        csr = sparse.csr_matrix(
            np.array([[1, 2, 3], [0, 0, 0]], "float32"))
        with pytest.raises(TypeError):
            nd.dot(nd.ones((2, 2)), csr)     # sparse rhs: no kernel
        with pytest.raises(TypeError):
            nd.broadcast_add(csr, nd.ones((2, 3)))

    def test_elemwise_add_mixed(self):
        rs = sparse.row_sparse_array(
            (np.ones((1, 2), "float32"), [1]), shape=(3, 2))
        dense = nd.ones((3, 2))
        for out in (nd.elemwise_add(rs, dense),
                    nd.elemwise_add(dense, rs)):
            assert out.stype == "default"
            np.testing.assert_array_equal(
                out.asnumpy(), [[1, 1], [2, 2], [1, 1]])

    def test_sparse_routes_honour_out(self):
        rs = sparse.row_sparse_array(
            (np.ones((1, 2), "float32"), [1]), shape=(3, 2))
        o = nd.zeros((3, 2))
        got = nd.elemwise_add(rs, nd.ones((3, 2)), out=o)
        assert got is o
        np.testing.assert_array_equal(o.asnumpy(),
                                      [[1, 1], [2, 2], [1, 1]])
        csr = sparse.csr_matrix(np.eye(3, dtype="float32"))
        o2 = nd.zeros((3, 2))
        nd.dot(csr, nd.ones((3, 2)), out=o2)
        np.testing.assert_array_equal(o2.asnumpy(), np.ones((3, 2)))

    def test_mismatched_copyto_refused(self):
        rs = sparse.row_sparse_array(
            (np.ones((1, 2), "float32"), [1]), shape=(3, 2))
        csr = sparse.csr_matrix(np.eye(2, dtype="float32"))
        with pytest.raises(TypeError):
            rs.copyto(csr)
        with pytest.raises(TypeError):
            nd.ones((3, 2)).copyto(rs)


class TestKVStore:
    def test_plain_pull_densifies_sparse_store(self):
        kv = mx.kv.create("local")
        kv.init("w", nd.zeros((4, 2)))
        g = sparse.row_sparse_array(
            (np.ones((1, 2), "float32"), [2]), shape=(4, 2))
        kv.push("w", g)   # no updater: store holds the sparse reduction
        out = nd.zeros((4, 2))
        kv.pull("w", out=out)
        assert out.shape == (4, 2)
        np.testing.assert_array_equal(
            out.asnumpy(), [[0, 0], [0, 0], [1, 1], [0, 0]])

    def test_row_sparse_pull_dense_out_from_sparse_store(self):
        kv = mx.kv.create("local")
        kv.init("w", sparse.row_sparse_array(
            (np.full((2, 2), 3.0, "float32"), [1, 3]), shape=(5, 2)))
        out = nd.zeros((2, 2))
        kv.row_sparse_pull("w", out=out,
                           row_ids=nd.array(np.array([3., 0.])))
        np.testing.assert_array_equal(out.asnumpy(), [[3, 3], [0, 0]])

    def test_row_sparse_pull_from_dense(self):
        kv = mx.kv.create("local")
        w = np.random.RandomState(13).randn(30, 4).astype("float32")
        kv.init("emb", nd.array(w))
        out = sparse.zeros("row_sparse", (30, 4))
        rows = nd.array(np.array([2., 7., 19.]))
        kv.row_sparse_pull("emb", out=out, row_ids=rows)
        assert out.stype == "row_sparse" and out.nnz == 3
        np.testing.assert_allclose(out.asnumpy()[[2, 7, 19]],
                                   w[[2, 7, 19]], rtol=1e-6)

    def test_sparse_push_reduces_union(self):
        kv = mx.kv.create("local")
        kv.init("g", sparse.zeros("row_sparse", (10, 2)))
        a = sparse.row_sparse_array(
            (np.ones((1, 2), "float32"), [1]), shape=(10, 2))
        b = sparse.row_sparse_array(
            (np.full((1, 2), 2.0, "float32"), [1]), shape=(10, 2))
        c = sparse.row_sparse_array(
            (np.full((1, 2), 5.0, "float32"), [4]), shape=(10, 2))
        kv.push("g", [a, b, c])
        out = sparse.zeros("row_sparse", (10, 2))
        kv.row_sparse_pull("g", out=out,
                           row_ids=nd.array(np.array([1., 4.])))
        dense = out.asnumpy()
        np.testing.assert_array_equal(dense[1], [3, 3])
        np.testing.assert_array_equal(dense[4], [5, 5])


class TestSerialization:
    def test_save_load_preserves_sparse(self, tmp_path):
        path = str(tmp_path / "mixed.npz")
        rs, idx, vals = _rand_rs(20, 3, 4, seed=20)
        csr = sparse.csr_matrix(
            np.array([[0, 1.5], [2.5, 0]], "float32"))
        dense = nd.ones((2, 2))
        nd.save(path, {"rs": rs, "csr": csr, "w": dense})
        back = nd.load(path)
        assert back["rs"].stype == "row_sparse"
        assert back["rs"].nnz == 4
        np.testing.assert_allclose(back["rs"].asnumpy(), rs.asnumpy())
        assert back["csr"].stype == "csr"
        np.testing.assert_allclose(back["csr"].asnumpy(), csr.asnumpy())
        assert back["w"].stype == "default"

    def test_save_load_sparse_list(self, tmp_path):
        path = str(tmp_path / "list.npz")
        rs, _, _ = _rand_rs(10, 2, 3, seed=21)
        nd.save(path, [rs, nd.zeros((2,))])
        back = nd.load(path)
        assert back[0].stype == "row_sparse"
        assert back[1].shape == (2,)

    def test_reserved_suffix_keys_roundtrip(self, tmp_path):
        """User keys that look like sparse components stay intact."""
        path = str(tmp_path / "edge.npz")
        nd.save(path, {"emb:data": nd.ones((2, 2)),
                       "foo:stype": nd.zeros((1,)),
                       "arg:indptr": nd.ones((3,))})
        back = nd.load(path)
        assert set(back) == {"emb:data", "foo:stype", "arg:indptr"}
        np.testing.assert_array_equal(back["emb:data"].asnumpy(),
                                      np.ones((2, 2)))

    def test_reserved_namespace_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            nd.save(str(tmp_path / "x.npz"),
                    {"__mx_sparse__.0.data": nd.ones((1,))})
