"""Unified runtime telemetry (ISSUE 8): metrics registry, run journal,
exporters, and the instrumented hot loops.

The load-bearing assertions (acceptance):
- a TrainStep.fit and a Module.fit run with MXNET_TELEMETRY set each
  produce a journal from which tools/telemetry_report.py reconstructs
  samples/sec within 5% of the Speedometer figure;
- a fault-injected run's journal contains the matching retry /
  dead-worker / masked-step counters;
- telemetry-on vs telemetry-off host-sync counts are IDENTICAL in the
  hot loop (journal writes are host-side wall clock only);
- disabled mode is a no-op: no journal file, counter calls cheap;
- concurrent counter/histogram updates are exact; histogram quantiles
  match numpy on known data.
"""
import json
import logging
import os
import re
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import callback, config, io, metric, profiler, telemetry
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.parallel import make_train_step
from mxnet_tpu.parallel.ps_async import AsyncPSClient, AsyncPSServer
from mxnet_tpu.parallel.resilience import (FaultInjector,
                                           install_fault_injector)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from tools.telemetry_report import format_report, load, summarize  # noqa: E402

pytestmark = pytest.mark.telemetry

_SPEED_RE = re.compile(r"Speed: ([0-9.]+) samples/sec")


def _mlp():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=32)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=2)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _toy(n=96, d=16, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d) > 0).astype(np.float32)
    return X, y


def _step(**kwargs):
    kwargs.setdefault("optimizer", "sgd")
    kwargs.setdefault("optimizer_params", {"rescale_grad": 1.0 / 32})
    return make_train_step(_mlp(), **kwargs)


@pytest.fixture
def journal_dir(tmp_path):
    """Telemetry scoped to this test: fresh journal dir via override,
    journal closed + override cleared on exit."""
    telemetry.close_journal()
    d = str(tmp_path / "tele")
    config.set_override("MXNET_TELEMETRY", d)
    yield d
    telemetry.close_journal()
    config.clear_override("MXNET_TELEMETRY")
    config.clear_override("MXNET_TELEMETRY_PROM")


@pytest.fixture
def no_injector():
    yield
    install_fault_injector(None)


def _measured_records(path, loop):
    """Step records of the LAST fit in a journal (after the final
    fit.start event of that loop), plus the full record list."""
    recs = load(path)
    idx = max(i for i, r in enumerate(recs)
              if r.get("kind") == "event" and r.get("event") == "fit.start"
              and r.get("fields", {}).get("loop") == loop)
    steps = [r for r in recs[idx + 1:]
             if r.get("kind") == "step" and r.get("loop") == loop]
    return steps, recs


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_counter_gauge_histogram_basics():
    c = telemetry.counter("t.basic_counter")
    base = c.value
    c.inc()
    c.inc(5)
    assert c.value - base == 6
    g = telemetry.gauge("t.basic_gauge")
    g.set(3.5)
    assert g.value == 3.5
    with pytest.raises(TypeError):
        telemetry.gauge("t.basic_counter")    # name is the identity
    h = telemetry.histogram("t.basic_hist")
    with h.timer():
        pass
    assert h.count >= 1
    snap = telemetry.snapshot()
    assert snap["t.basic_counter"]["type"] == "counter"
    assert snap["t.basic_hist"]["count"] >= 1


def test_disabled_mode_no_journal_and_cheap_counters(tmp_path):
    """With MXNET_TELEMETRY unset: journal() is None, journal_step /
    journal_event are no-ops (no file, no recent-steps buffer), and a
    counter inc is cheap enough to sit on the host-sync path."""
    if os.environ.get("MXNET_TELEMETRY"):
        pytest.skip("MXNET_TELEMETRY set in the environment")
    telemetry.close_journal()
    config.clear_override("MXNET_TELEMETRY")
    assert telemetry.journal() is None
    telemetry.journal_step(loop="test", step=0, wall_ms=1.0, samples=1)
    telemetry.journal_event("test.event")
    assert telemetry.journal() is None
    assert telemetry.recent_steps() == []
    c = telemetry.counter("t.cheap")
    t0 = time.perf_counter()
    for _ in range(100_000):
        c.inc()
    assert time.perf_counter() - t0 < 2.0   # ~µs/inc with huge slack
    assert c.value >= 100_000


def test_concurrent_updates_are_exact():
    c = telemetry.counter("t.concurrent")
    h = telemetry.histogram("t.concurrent_hist")
    base_c, base_h = c.value, h.count
    n_threads, per_thread = 8, 5000

    def work():
        for _ in range(per_thread):
            c.inc()
            h.observe(1.0)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value - base_c == n_threads * per_thread
    assert h.count - base_h == n_threads * per_thread


def test_histogram_quantiles_match_numpy():
    rng = np.random.default_rng(3)
    data = rng.uniform(0.0, 100.0, 5000)
    h = telemetry.histogram("t.quantiles",
                            buckets=np.linspace(0.5, 100.0, 200))
    for v in data:
        h.observe(v)
    for q in (0.5, 0.95, 0.99):
        want = float(np.percentile(data, q * 100.0))
        got = h.quantile(q)
        assert abs(got - want) <= 1.0, (q, got, want)   # ~bucket width
    snap = h.snapshot()
    assert snap["count"] == 5000
    assert abs(snap["mean"] - float(data.mean())) <= 0.01


# ---------------------------------------------------------------------------
# journal + report round trip
# ---------------------------------------------------------------------------

def test_journal_schema_and_report_roundtrip(journal_dir):
    for i in range(10):
        telemetry.journal_step(loop="test", step=i, epoch=0,
                               wall_ms=10.0, data_wait_ms=1.0,
                               window_wait_ms=2.0, samples=32)
    telemetry.journal_event("ps.retry", op="push", attempt=1)
    path = telemetry.close_journal()
    assert path and os.path.exists(path)

    recs = load(path)
    kinds = {r["kind"] for r in recs}
    assert kinds == {"run_start", "step", "event", "snapshot"}
    for r in recs:
        assert r["v"] == telemetry.SCHEMA_VERSION
        assert isinstance(r["t"], float)

    s = summarize(recs)
    assert s["steps"] == 10 and s["samples"] == 320
    assert s["step_ms"]["p50"] == 10.0 and s["step_ms"]["p95"] == 10.0
    # 320 samples over 100 ms of step wall
    assert abs(s["samples_per_sec"] - 3200.0) < 1e-6
    assert s["events"]["ps.retry"] == 1
    assert "host_syncs" in s["counters"]
    report = format_report(s)
    assert "step time (ms)" in report and "ps.retry" in report

    # a torn FINAL line (crash signature) is tolerated...
    with open(path, "a") as f:
        f.write('{"v": 1, "kind": "st')
    assert len(load(path)) == len(recs)
    # ...corruption anywhere earlier is not
    bad = path + ".bad"
    lines = open(path).read().splitlines()
    lines[1] = "not json"
    with open(bad, "w") as f:
        f.write("\n".join(lines))
    with pytest.raises(ValueError, match="corrupt"):
        load(bad)


def test_compile_flag_only_marks_the_owning_step(journal_dir):
    """A compile event outside a step's wall window (e.g. score()'s
    infer compile between epochs) must NOT flag the next step — only a
    step whose own boundary-to-boundary wall covers the event is
    flagged."""
    telemetry.journal_event("compile", site="test", wall_ms=1.0)
    time.sleep(0.05)
    # this step's window is 5 ms: the compile 50 ms ago is not in it
    telemetry.journal_step(loop="test", step=0, wall_ms=5.0, samples=1)
    # a compile inside the window (5000 ms covers "just now") flags it
    telemetry.journal_event("compile", site="test", wall_ms=1.0)
    telemetry.journal_step(loop="test", step=1, wall_ms=5000.0,
                           samples=1)
    path = telemetry.close_journal()
    steps = [r for r in load(path) if r["kind"] == "step"]
    assert "compile" not in steps[0]
    assert steps[1].get("compile") is True


def test_prom_export_atomic(journal_dir, tmp_path):
    prom = str(tmp_path / "metrics.prom")
    config.set_override("MXNET_TELEMETRY_PROM", prom)
    telemetry.counter("t.prom_counter").inc()
    telemetry.gauge("t.prom_gauge").set(7.0)
    telemetry.histogram("t.prom_hist").observe(5.0)
    out = telemetry.write_prom()
    assert out == prom
    text = open(prom).read()
    assert "# TYPE mxnet_t_prom_counter counter" in text
    assert "# TYPE mxnet_t_prom_gauge gauge" in text
    assert "# TYPE mxnet_t_prom_hist summary" in text
    assert 'mxnet_t_prom_hist{quantile="0.5"}' in text
    assert "mxnet_t_prom_hist_count 1" in text
    assert not os.path.exists(prom + ".tmp")   # atomic publish


# ---------------------------------------------------------------------------
# instrumented fit loops (acceptance)
# ---------------------------------------------------------------------------

def test_trainstep_fit_report_matches_speedometer(journal_dir, caplog):
    """Acceptance: the journal of a TrainStep.fit run reconstructs
    samples/sec within 5% of Speedometer's figure — both read the same
    per-step wall records (one timing source of truth), Speedometer
    over its last-`frequent` window, the report over the whole run."""
    X, y = _toy(n=3232)                    # 101 steps/epoch @ batch 32
    step = _step()
    train = io.NDArrayIter(X, y, batch_size=32)
    # warm fit: compile + init (its records are filtered out below)
    state, _ = step.fit(train, num_epoch=1, initializer=Xavier(), lr=0.1)

    speedo = callback.Speedometer(32, frequent=100, auto_reset=False)
    with caplog.at_level(logging.INFO):
        step.fit(train, num_epoch=1, state=state, lr=0.1,
                 batch_end_callback=speedo)
    path = telemetry.close_journal()

    steps, recs = _measured_records(path, "trainstep")
    assert len(steps) == 101
    for rec in steps:
        for key in ("wall_ms", "data_wait_ms", "window_wait_ms",
                    "samples"):
            assert key in rec, rec
    assert any(r.get("kind") == "event" and r.get("event") == "compile"
               for r in recs)
    # the step that carried the (re)compile is flagged in its record
    assert any(r.get("compile") for r in steps)

    speeds = [float(m.group(1)) for m in
              (_SPEED_RE.search(r.message) for r in caplog.records)
              if m is not None]
    assert len(speeds) == 1
    # telemetry-sourced ticks also report batch-time quantiles
    assert any("p95-batch:" in r.message for r in caplog.records)

    s = summarize(steps)
    assert abs(s["samples_per_sec"] - speeds[0]) <= 0.05 * speeds[0], \
        (s["samples_per_sec"], speeds)


def test_module_fit_report_matches_speedometer(journal_dir, caplog):
    """Same acceptance gate for the Module.fit hot loop."""
    X, y = _toy(n=3232)                    # 101 steps/epoch @ batch 32
    train = io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    # warm fit (bind/init/compile)
    mod.fit(train, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})

    speedo = callback.Speedometer(32, frequent=100, auto_reset=False)
    with caplog.at_level(logging.INFO):
        mod.fit(train, num_epoch=1, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1},
                batch_end_callback=speedo, force_init=True,
                force_rebind=True)
    path = telemetry.close_journal()

    steps, _recs = _measured_records(path, "module")
    assert len(steps) == 101
    speeds = [float(m.group(1)) for m in
              (_SPEED_RE.search(r.message) for r in caplog.records)
              if m is not None]
    assert len(speeds) == 1
    s = summarize(steps)
    assert abs(s["samples_per_sec"] - speeds[0]) <= 0.05 * speeds[0], \
        (s["samples_per_sec"], speeds)


def test_fit_telemetry_adds_zero_host_syncs(tmp_path):
    """Acceptance: MXNET_TELEMETRY on vs off — the instrumented epoch
    performs the IDENTICAL number of blocking host syncs (telemetry is
    host wall-clock + file appends only)."""
    if os.environ.get("MXNET_TELEMETRY"):
        pytest.skip("MXNET_TELEMETRY set in the environment")
    telemetry.close_journal()
    config.clear_override("MXNET_TELEMETRY")
    X, y = _toy()
    step = _step()
    train = io.NDArrayIter(X, y, batch_size=32)   # 3 steps/epoch
    state, _ = step.fit(train, num_epoch=1, initializer=Xavier(),
                        lr=0.1)                   # warm (compiles)

    base = profiler.host_sync_count()
    state, _ = step.fit(train, num_epoch=1, state=state, lr=0.1)
    syncs_off = profiler.host_sync_count() - base

    config.set_override("MXNET_TELEMETRY", str(tmp_path / "tele"))
    try:
        base = profiler.host_sync_count()
        state, _ = step.fit(train, num_epoch=1, state=state, lr=0.1)
        syncs_on = profiler.host_sync_count() - base
    finally:
        path = telemetry.close_journal()
        config.clear_override("MXNET_TELEMETRY")
    assert syncs_on == syncs_off, (syncs_on, syncs_off)
    # and the journal really recorded the epoch it watched
    steps = [r for r in load(path) if r.get("kind") == "step"]
    assert len(steps) == 3


# ---------------------------------------------------------------------------
# fault-injected runs land in the journal (acceptance)
# ---------------------------------------------------------------------------

def test_fault_injected_run_journal_counters(journal_dir, no_injector):
    """Retry (injected transport fault), dead-worker (heartbeat-lapse
    declaration) and masked-step (nan@N) events all land in ONE run's
    journal, with the matching registry counters in its final
    snapshot."""
    retries0 = telemetry.counter("ps.retries").value
    reconnects0 = telemetry.counter("ps.reconnects").value
    dead0 = telemetry.counter("ps.dead_workers").value
    masked0 = telemetry.counter("guardrail.masked_steps").value

    # -- retry + reconnect: a dropped push replays on a new connection
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=1)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    client = AsyncPSClient("127.0.0.1", srv.port)
    try:
        client.init("w", np.ones(4, np.float32))
        # counts are per injector install: the next send (the push) drops
        install_fault_injector(FaultInjector("send:drop@1"))
        client.push("w", np.ones(4, np.float32))
        install_fault_injector(None)
    finally:
        client.close()
        srv.stop()
    assert telemetry.counter("ps.retries").value > retries0
    assert telemetry.counter("ps.reconnects").value > reconnects0

    # -- dead worker: heartbeat-lapse declaration path
    srv2 = AsyncPSServer(host="127.0.0.1", port=0, num_workers=2)
    try:
        srv2._declare_dead(7, "heartbeat lapse > 0.1s (test)")
    finally:
        srv2.stop()
    assert telemetry.counter("ps.dead_workers").value > dead0
    assert telemetry.counter("ps.heartbeat_lapses").value > 0

    # -- masked step: nan@2 through the real fit guardrail path
    X, y = _toy()
    install_fault_injector(FaultInjector("nan@2"))
    step = _step()
    train = io.NDArrayIter(X, y, batch_size=32)
    step.fit(train, num_epoch=1, initializer=Xavier(), lr=0.5)
    install_fault_injector(None)
    assert telemetry.counter("guardrail.masked_steps").value > masked0

    path = telemetry.close_journal()
    recs = load(path)
    events = {r["event"] for r in recs if r.get("kind") == "event"}
    assert {"ps.retry", "ps.reconnect", "ps.dead_worker",
            "guardrail.masked_step"} <= events
    counters = summarize(recs)["counters"]
    assert counters["ps.retries"] > retries0
    assert counters["ps.dead_workers"] > dead0
    assert counters["guardrail.masked_steps"] > masked0
    # the per-op latency histograms saw the ops
    snap = [r for r in recs if r.get("kind") == "snapshot"][-1]["metrics"]
    assert snap["ps.op_ms.push"]["count"] >= 1
    assert snap["ps.op_ms.init"]["count"] >= 1


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------

def test_journal_write_failure_disables_with_one_warning(journal_dir,
                                                         caplog):
    """ENOSPC / a dir yanked mid-run: the journal disables itself with
    ONE warning instead of raising into the training step."""
    telemetry.journal_step(loop="test", step=0, wall_ms=1.0, samples=1)
    jr = telemetry.journal()
    assert jr is not None and not jr._broken

    class Boom:
        def write(self, *_a):
            raise OSError(28, "No space left on device")

        def flush(self):
            pass

        def close(self):
            pass

    jr._f = Boom()
    with caplog.at_level(logging.WARNING):
        for i in range(5):     # repeated steps must not re-warn/raise
            telemetry.journal_step(loop="test", step=i + 1,
                                   wall_ms=1.0, samples=1)
            telemetry.journal_event("test.event")
    warned = [r for r in caplog.records
              if "journal writes disabled" in r.message]
    assert len(warned) == 1
    assert jr._broken


def test_prom_republish_failure_disables_with_one_warning(journal_dir,
                                                          tmp_path,
                                                          caplog):
    """The periodic Prometheus republish tolerates its directory going
    unwritable mid-run: one warning, then the export path goes quiet
    (the journal and the step keep working). The dir is replaced by a
    plain file to break it mid-run — a permission flip doesn't bind
    when tests run as root, but the OSError path is identical."""
    blocker = tmp_path / "ro"
    blocker.write_text("now a file, not a dir")
    prom = str(blocker / "sub" / "metrics.prom")
    config.set_override("MXNET_TELEMETRY_PROM", prom)
    telemetry._PROM_BROKEN[0] = False
    try:
        telemetry._LAST_EXPORT[0] = 0.0  # force the period expired
        with caplog.at_level(logging.WARNING):
            for i in range(5):
                telemetry._LAST_EXPORT[0] = 0.0
                telemetry.journal_step(loop="test", step=i,
                                       wall_ms=1.0, samples=1)
        warned = [r for r in caplog.records
                  if "periodic export disabled" in r.message]
        assert len(warned) == 1
        assert telemetry._PROM_BROKEN[0]
    finally:
        telemetry._PROM_BROKEN[0] = False
        config.clear_override("MXNET_TELEMETRY_PROM")


def test_mfu_gauge_and_report(journal_dir, monkeypatch):
    """Satellite: the Executor's compile-event path records the step
    variant's cost-analysis FLOPs into the step.model_flops gauge, and
    the report prints achieved FLOP/s + MFU under MXNET_PEAK_FLOPS."""
    X, y = _toy()
    train = io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    path = telemetry.close_journal()
    flops = telemetry.gauge("step.model_flops").value
    assert flops and flops > 0
    recs = load(path)
    compiles = [r for r in recs if r.get("kind") == "event"
                and r.get("event") == "compile"]
    assert any(c.get("fields", {}).get("flops") for c in compiles)
    monkeypatch.setenv("MXNET_PEAK_FLOPS", "1e12")
    s = summarize(recs)
    assert s["model_flops"] == flops
    assert s["flops_per_sec"] > 0
    assert s["peak_flops"] == 1e12
    assert s["mfu"] == pytest.approx(s["flops_per_sec"] / 1e12,
                                     abs=1e-4)
    report = format_report(s)
    assert "MFU" in report and "MXNET_PEAK_FLOPS" in report
    # without the hint: achieved FLOP/s still prints, no MFU claim
    monkeypatch.delenv("MXNET_PEAK_FLOPS")
    s2 = summarize(recs)
    assert "mfu" not in s2 and s2["flops_per_sec"] > 0


def test_device_memory_watermark_sample(journal_dir):
    """Satellite: boundary-only HBM watermark sampling is safe on any
    backend (CPU usually reports nothing) and feeds the mem.* gauges
    when stats exist. Exercised at epoch boundaries by both fit loops
    (this covers the helper's contract)."""
    stats = profiler.sample_device_memory("test")
    assert stats is None or isinstance(stats, dict)
    if stats is not None and stats.get("bytes_in_use") is not None:
        assert telemetry.gauge("mem.hbm_bytes_in_use").value == \
            stats["bytes_in_use"]


def test_speedometer_falls_back_without_telemetry(caplog):
    """No journal: Speedometer times with its own clock (no batch-time
    quantiles in the line) — unchanged legacy behavior."""
    if os.environ.get("MXNET_TELEMETRY"):
        pytest.skip("MXNET_TELEMETRY set in the environment")
    telemetry.close_journal()
    config.clear_override("MXNET_TELEMETRY")

    class P:
        epoch = 0
        eval_metric = None

        def __init__(self, nbatch):
            self.nbatch = nbatch

    speedo = callback.Speedometer(4, frequent=2)
    with caplog.at_level(logging.INFO):
        for n in range(5):
            speedo(P(n))
    lines = [r.message for r in caplog.records
             if "Speed:" in r.message]
    assert lines and all("p95-batch" not in ln for ln in lines)


def test_profiler_dump_embeds_telemetry_snapshot(tmp_path):
    """dump_profile metadata carries the registry snapshot — a trace
    capture ships its run's counters/quantiles."""
    out = str(tmp_path / "prof.json")
    profiler.profiler_set_config(mode="all", filename=out)
    profiler.profiler_set_state("run")
    try:
        mx.nd.ones((4,)).asnumpy()
    finally:
        profiler.profiler_set_state("stop")
    payload = json.load(open(profiler.dump_profile()))
    assert "telemetry" in payload
    assert payload["telemetry"]["host_syncs"]["type"] == "counter"
    assert payload["telemetry"]["host_syncs"]["value"] > 0


def test_host_sync_counter_is_a_telemetry_counter():
    """The PR 2 host-sync counter migrated into the registry behind
    the unchanged profiler API (tests keep working; the count now also
    rides the Prometheus export and dump_profile snapshot)."""
    base = profiler.host_sync_count()
    mx.nd.ones((2,)).asnumpy()
    assert profiler.host_sync_count() == base + 1
    assert telemetry.counter("host_syncs").value == \
        profiler.host_sync_count()
