"""Smoke-run the fast self-checking examples as subprocesses (each
asserts its own success metric; reference analogue: the nightly
tutorial/test_all.sh sweep). Long-running examples (bucketing, SPMD
resnet, transformer LM) have dedicated tests elsewhere.
"""
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAST_EXAMPLES = [
    "custom_op_softmax.py",
    "adversary_fgsm.py",
    "profile_model.py",
    "fit_spmd_elastic.py",
    "fcn_xs.py",
    "svm_digits.py",
    "vae.py",
    "neural_style.py",
    "dsd_pruning.py",
    "memcost_remat.py",
]

# The heaviest end-to-end demos (8-47 s each on the 1-core tier-1
# host) ride the slow tier: the suite crossed the 870 s tier-1
# wall-clock budget and these cost the most while their framework
# surfaces keep dedicated unit coverage in tier-1
# (generation/beam/speculative/int8 in test_generation.py +
# test_serve_decode.py/test_serve_disagg.py; the Module fit API in
# test_module.py and the perf-gate `module` scenario; rcnn/detection
# ops in test_rcnn_contrib_ops.py + test_detection_ops.py; the NCE op
# in test_op_sweep.py; gan_toy/multi_task are plain Module loops; RL
# uses no unique surface; image_folder_training and sgld_bayes are
# demo-only surfaces whose self-checks still run in the slow tier).
HEAVY_EXAMPLES = [
    "transformer_generate.py",
    "actor_critic.py",
    "stochastic_depth.py",
    "image_folder_training.py",
    "nce_loss.py",
    "sgld_bayes.py",
    "rcnn_train.py",
    "gan_toy.py",
    "multi_task.py",
]


@pytest.mark.slow
def test_speech_lstm_bucketing_example(tmp_path):
    """Speech-style bucketed pipeline: runs the example (self-checking:
    frame-accuracy floor + cross-bucket padding invariance, the check
    that caught the round-5 bucket-parameter-sharing regression).
    Slow tier: ~29 s on the tier-1 host; the bucketing machinery keeps
    fast coverage in test_rnn_toolkit.py's bucketing tests."""
    _run_example("speech_lstm_bucketing.py", tmp_path, timeout=600,
                 expect="speech_lstm_bucketing OK")


@pytest.mark.slow
def test_dec_clustering_example(tmp_path):
    """DEC has its own entry: the AE pretrain + refinement loop runs
    longer than the FAST_EXAMPLES budget (still self-checking —
    convergence criterion + accuracy floor + no-degradation)."""
    _run_example("dec_clustering.py", tmp_path, timeout=900,
                 expect="dec_clustering OK")


def _run_example(script, tmp_path, timeout=300, extra_args=(),
                 expect=None):
    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu")
    args = [sys.executable, os.path.join(_REPO, "examples", script)]
    args += list(extra_args)
    out = subprocess.run(args, capture_output=True, text=True,
                         timeout=timeout, env=env, cwd=str(tmp_path))
    assert out.returncode == 0, (out.stdout[-500:], out.stderr[-800:])
    if expect is not None:
        assert expect in out.stdout
    return out


@pytest.mark.parametrize("script", FAST_EXAMPLES + [
    pytest.param(s, marks=pytest.mark.slow) for s in HEAVY_EXAMPLES])
def test_example_runs(script, tmp_path):
    extra = [str(tmp_path / "trace.json")] \
        if script == "profile_model.py" else []
    _run_example(script, tmp_path, extra_args=extra)
