"""Smoke-run the fast self-checking examples as subprocesses (each
asserts its own success metric; reference analogue: the nightly
tutorial/test_all.sh sweep). Long-running examples (bucketing, SPMD
resnet, transformer LM) have dedicated tests elsewhere.
"""
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAST_EXAMPLES = [
    "custom_op_softmax.py",
    "adversary_fgsm.py",
    "profile_model.py",
    "gan_toy.py",
    "fit_spmd_elastic.py",
    "transformer_generate.py",
    "rcnn_train.py",
    "fcn_xs.py",
    "nce_loss.py",
    "actor_critic.py",
    "multi_task.py",
    "svm_digits.py",
    "vae.py",
    "neural_style.py",
]


@pytest.mark.parametrize("script", FAST_EXAMPLES)
def test_example_runs(script, tmp_path):
    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu")
    args = [sys.executable, os.path.join(_REPO, "examples", script)]
    if script == "profile_model.py":
        args.append(str(tmp_path / "trace.json"))
    out = subprocess.run(args, capture_output=True, text=True,
                         timeout=300, env=env, cwd=str(tmp_path))
    assert out.returncode == 0, (out.stdout[-500:], out.stderr[-800:])
