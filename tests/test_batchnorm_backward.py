"""BatchNorm one-pass/closed-form training path vs the naive two-pass
autodiff formulation: outputs, moving-stat updates, and ALL gradients
(data/gamma/beta) must agree to float32 tightness, across axes and
fix_gamma. Guards the HBM-traffic rewrite of ops/nn.py:_bn_train_core
(VERDICT r3 #3: BN stats measured at ~18% of the ResNet-50 step).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def _naive_bn(x, gamma, beta, eps, axis, fix_gamma):
    red = tuple(i for i in range(x.ndim) if i != axis)
    bshape = tuple(x.shape[axis] if i == axis else 1
                   for i in range(x.ndim))
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=red)
    var = jnp.var(xf, axis=red)
    inv = jax.lax.rsqrt(var.reshape(bshape) + eps)
    out = (xf - mean.reshape(bshape)) * inv * \
        g.reshape(bshape).astype(jnp.float32) + \
        beta.reshape(bshape).astype(jnp.float32)
    return out.astype(x.dtype), mean, var


@pytest.mark.parametrize("axis", [1, 3])
@pytest.mark.parametrize("fix_gamma", [False, True])
@pytest.mark.parametrize("impl", ["", "onepass"])
def test_train_bn_matches_naive(axis, fix_gamma, impl, monkeypatch):
    """Default (two-pass autodiff) and MXNET_BN_IMPL=onepass (the r4
    closed-form custom_vjp core) must both match the reference math —
    the env parametrization also guards the routing itself, so the
    A/B harness's *_onepass_bn configs cannot silently benchmark the
    default path twice."""
    monkeypatch.setenv("MXNET_BN_IMPL", impl)
    rng = np.random.RandomState(0)
    x = rng.randn(4, 5, 6, 7).astype(np.float32) * 2.0 + 0.5
    C = x.shape[axis]
    gamma = rng.rand(C).astype(np.float32) + 0.5
    beta = rng.randn(C).astype(np.float32)
    dy = rng.randn(*x.shape).astype(np.float32)
    eps = 1e-3

    from mxnet_tpu.ops.nn import _batch_norm

    def framework(x_, g_, b_):
        out = _batch_norm(jnp.asarray(x_), g_, b_,
                          jnp.zeros(C), jnp.ones(C), eps=eps,
                          fix_gamma=fix_gamma, axis=axis,
                          is_train=True)
        return out[0]

    def naive(x_, g_, b_):
        return _naive_bn(jnp.asarray(x_), g_, b_, eps, axis,
                         fix_gamma)[0]

    y_f = framework(x, gamma, beta)
    y_n = naive(x, gamma, beta)
    np.testing.assert_allclose(np.asarray(y_f), np.asarray(y_n),
                               rtol=2e-5, atol=2e-5)

    def loss_with(fn):
        def f(x_, g_, b_):
            return jnp.sum(fn(x_, g_, b_) * dy)
        return jax.grad(f, argnums=(0, 1, 2))

    gf = loss_with(framework)(x, gamma, beta)
    gn = loss_with(naive)(x, gamma, beta)
    for a, b, name in zip(gf, gn, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
            err_msg="%s mismatch (axis=%d fix_gamma=%s)"
                    % (name, axis, fix_gamma))


def test_moving_stats_and_eval_path():
    """Moving stats update from the one-pass mean/var; eval mode uses
    them (unchanged path)."""
    rng = np.random.RandomState(1)
    x = nd.array(rng.randn(8, 3, 5, 5).astype(np.float32))
    gamma, beta = nd.ones((3,)), nd.zeros((3,))
    mm, mv = nd.zeros((3,)), nd.ones((3,))
    with autograd.record():
        out = nd.BatchNorm(x, gamma, beta, mm, mv, fix_gamma=False,
                           momentum=0.9, eps=1e-3)
    got_mm = mm.asnumpy()
    want = 0.1 * x.asnumpy().mean(axis=(0, 2, 3))
    np.testing.assert_allclose(got_mm, want, rtol=1e-5, atol=1e-6)

    # eval: normalize with the (updated) moving stats
    out_eval = nd.BatchNorm(x, gamma, beta, mm, mv, fix_gamma=False)
    xn = x.asnumpy()
    ref = (xn - got_mm[None, :, None, None]) / np.sqrt(
        mv.asnumpy()[None, :, None, None] + 1e-3)
    np.testing.assert_allclose(out_eval.asnumpy(), ref, rtol=1e-4,
                               atol=1e-4)


def test_mean_var_output_cotangents():
    """Advisor r4: a graph that differentiates THROUGH the mean/var
    outputs (output_mean_var consumers) must get correct gradients —
    the closed-form backward folds d mean/dx = 1/m and
    d var/dx = 2(x-mean)/m into the dx pass, not silently dropping
    the cotangents."""
    from mxnet_tpu.ops.nn import _bn_train_core

    rng = np.random.RandomState(3)
    x = rng.randn(4, 3, 5, 5).astype(np.float32)
    gamma = rng.rand(3).astype(np.float32) + 0.5
    beta = rng.randn(3).astype(np.float32)
    eps = 1e-3
    red, bshape = (0, 2, 3), (1, 3, 1, 1)
    w_y = rng.randn(*x.shape).astype(np.float32)
    w_m = rng.randn(3).astype(np.float32)
    w_v = rng.randn(3).astype(np.float32)

    def core_loss(x_, g_, b_):
        y, mean, var = _bn_train_core(jnp.asarray(x_), g_, b_, eps,
                                      red, bshape)
        return (jnp.sum(y * w_y) + jnp.sum(mean * w_m)
                + jnp.sum(var * w_v))

    def naive_loss(x_, g_, b_):
        xf = jnp.asarray(x_).astype(jnp.float32)
        mean = jnp.mean(xf, axis=red)
        var = jnp.var(xf, axis=red)
        inv = jax.lax.rsqrt(var.reshape(bshape) + eps)
        y = (xf - mean.reshape(bshape)) * inv * g_.reshape(bshape) + \
            b_.reshape(bshape)
        return (jnp.sum(y * w_y) + jnp.sum(mean * w_m)
                + jnp.sum(var * w_v))

    gf = jax.grad(core_loss, argnums=(0, 1, 2))(x, gamma, beta)
    gn = jax.grad(naive_loss, argnums=(0, 1, 2))(x, gamma, beta)
    for a, b, name in zip(gf, gn, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
            err_msg="%s mismatch through mean/var outputs" % name)


def test_one_pass_variance_large_mean_accuracy(monkeypatch):
    """Advisor r4: naive E[x^2]-E[x]^2 catastrophically cancels when
    |mean| >> std. The shifted one-pass form must normalize a
    mean=1e4, std=1e-2 batch to two-pass accuracy (unshifted f32
    would clamp the variance to ~0 and blow the output up against
    eps). Pinned to the onepass routing: the DEFAULT is two-pass
    autodiff since round 5, which passes this trivially."""
    monkeypatch.setenv("MXNET_BN_IMPL", "onepass")
    from mxnet_tpu.ops.nn import _batch_norm

    rng = np.random.RandomState(4)
    noise = rng.randn(64, 2, 8, 8).astype(np.float32)
    x = (1e4 + 1e-2 * noise).astype(np.float32)
    out = _batch_norm(jnp.asarray(x), jnp.ones(2), jnp.zeros(2),
                      jnp.zeros(2), jnp.ones(2), eps=1e-5,
                      fix_gamma=False, is_train=True)
    y = np.asarray(out[0], np.float64)
    # unshifted one-pass: s2/m and mean^2 are ~1e8 with an f32 ulp of
    # ~8, so the 1e-4 true variance cancels to the clamp -> rsqrt(eps)
    # blows the output std up to ~300. The shifted form must keep a
    # unit-std output...
    for c in range(2):
        assert 0.9 < y[:, c].std() < 1.1, y[:, c].std()
    # ...and match the two-pass E[(x-mean)^2] formulation (both share
    # the f32 input-representation floor, so they agree tightly)
    xf = jnp.asarray(x)
    mean = jnp.mean(xf, axis=(0, 2, 3))
    var = jnp.var(xf, axis=(0, 2, 3))
    ref = (xf - mean.reshape(1, 2, 1, 1)) * jax.lax.rsqrt(
        var.reshape(1, 2, 1, 1) + 1e-5)
    np.testing.assert_allclose(y, np.asarray(ref), rtol=1e-3,
                               atol=5e-3)


def test_pallas_bn_matches_core():
    """ops/bn_pallas.py (the below-XLA BN experiment, interpret mode
    on CPU): outputs AND all gradients — including through the
    mean/var outputs — must match the jnp one-pass core."""
    from mxnet_tpu.ops.bn_pallas import bn_train_pallas
    from mxnet_tpu.ops.nn import _bn_train_core

    rng = np.random.RandomState(11)
    x = rng.randn(3, 5, 4, 6).astype(np.float32) * 2.0 + 1.0
    gamma = rng.rand(5).astype(np.float32) + 0.5
    beta = rng.randn(5).astype(np.float32)
    eps = 1e-3
    red, bshape = (0, 2, 3), (1, 5, 1, 1)
    w_y = rng.randn(*x.shape).astype(np.float32)
    w_m = rng.randn(5).astype(np.float32)
    w_v = rng.randn(5).astype(np.float32)

    def loss(core):
        def f(x_, g_, b_):
            y, mean, var = core(x_, g_, b_)
            return (jnp.sum(y.astype(jnp.float32) * w_y)
                    + jnp.sum(mean * w_m) + jnp.sum(var * w_v))
        return f

    pallas_core = lambda x_, g_, b_: bn_train_pallas(x_, g_, b_, eps)
    jnp_core = lambda x_, g_, b_: _bn_train_core(x_, g_, b_, eps,
                                                 red, bshape)

    yp, mp, vp = pallas_core(jnp.asarray(x), jnp.asarray(gamma),
                             jnp.asarray(beta))
    yj, mj, vj = jnp_core(jnp.asarray(x), jnp.asarray(gamma),
                          jnp.asarray(beta))
    np.testing.assert_allclose(np.asarray(yp), np.asarray(yj),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(mp), np.asarray(mj),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(vp), np.asarray(vj),
                               rtol=1e-6, atol=1e-6)

    gp = jax.grad(loss(pallas_core), argnums=(0, 1, 2))(x, gamma,
                                                        beta)
    gj = jax.grad(loss(jnp_core), argnums=(0, 1, 2))(x, gamma, beta)
    for a, b, name in zip(gp, gj, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg="%s mismatch (pallas vs core)" % name)


def test_pallas_bn_env_routing(monkeypatch):
    """MXNET_BN_PALLAS=1 routes the 4-D NCHW training path through the
    Pallas core with identical results (and bf16 activations — the
    bench configuration — round-trip through it)."""
    from mxnet_tpu.ops.nn import _batch_norm

    rng = np.random.RandomState(12)
    x = jnp.asarray(rng.randn(4, 3, 5, 5), jnp.bfloat16)
    g = jnp.ones(3)
    b = jnp.zeros(3)
    base = _batch_norm(x, g, b, jnp.zeros(3), jnp.ones(3), eps=1e-3,
                       fix_gamma=False, is_train=True)
    monkeypatch.setenv("MXNET_BN_PALLAS", "1")
    # prove the flag actually routes (outputs alone would agree even
    # if the guard silently stopped matching)
    from mxnet_tpu.ops import bn_pallas
    calls = []
    real = bn_pallas.bn_train_pallas
    monkeypatch.setattr(
        bn_pallas, "bn_train_pallas",
        lambda *a, **k: calls.append(1) or real(*a, **k))
    routed = _batch_norm(x, g, b, jnp.zeros(3), jnp.ones(3), eps=1e-3,
                         fix_gamma=False, is_train=True)
    assert calls, "MXNET_BN_PALLAS=1 did not route to the Pallas core"
    for a, c in zip(base, routed):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(c, np.float32),
            rtol=2e-2, atol=2e-2)  # bf16 activations


def test_one_pass_var_nonnegative(monkeypatch):
    """E[x^2]-E[x]^2 can go fractionally negative in f32; the clamp
    must keep rsqrt finite even for constant inputs. Pinned to the
    onepass routing (the default two-pass jnp.var cannot go
    negative)."""
    monkeypatch.setenv("MXNET_BN_IMPL", "onepass")
    x = jnp.full((4, 2, 8, 8), 3.14159, jnp.float32)
    from mxnet_tpu.ops.nn import _batch_norm
    out = _batch_norm(x, jnp.ones(2), jnp.zeros(2), jnp.zeros(2),
                      jnp.ones(2), eps=1e-3, is_train=True)
    assert np.isfinite(np.asarray(out[0])).all()
