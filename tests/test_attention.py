"""Flash attention kernel + ring attention sequence parallelism tests.

The Pallas kernel runs in interpreter mode on the CPU test mesh (same
numerics as compiled TPU execution); ring attention runs as a real
8-device shard_map program on the forced CPU mesh (tests/conftest.py).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ops.attention import flash_attention, _attn_reference
from mxnet_tpu.parallel import ring_attention


def _qkv(B, T, D, seed=0, heads=None):
    rng = np.random.RandomState(seed)
    shape = (B, T, D) if heads is None else (B, heads, T, D)
    return tuple(jnp.asarray(rng.randn(*shape).astype("float32"))
                 for _ in range(3))


class TestFlashKernel:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv(2, 64, 16)
        out = flash_attention(q, k, v, causal=causal, block_q=32,
                              block_k=32)
        ref = _attn_reference(q, k, v, 16 ** -0.5, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-6)

    def test_4d_and_cross_lengths(self):
        q, _, _ = _qkv(2, 32, 16, heads=4)
        _, k, v = _qkv(2, 48, 16, seed=1, heads=4)
        out = flash_attention(q, k, v)
        assert out.shape == (2, 4, 32, 16)
        ref = _attn_reference(q.reshape(8, 32, 16), k.reshape(8, 48, 16),
                              v.reshape(8, 48, 16), 16 ** -0.5, False)
        np.testing.assert_allclose(np.asarray(out).reshape(8, 32, 16),
                                   np.asarray(ref), rtol=2e-5, atol=2e-6)

    @pytest.mark.parametrize("causal", [False, True])
    def test_ragged_lengths(self, causal):
        """T not a multiple of the block size: padded keys must not leak
        into the softmax."""
        q, _, _ = _qkv(2, 40, 16, seed=5)
        _, k, v = _qkv(2, 40, 16, seed=6)
        out = flash_attention(q, k, v, causal=causal, block_q=32,
                              block_k=32)
        ref = _attn_reference(q, k, v, 16 ** -0.5, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-6)

    def test_gradients_match_reference(self):
        q, k, v = _qkv(1, 32, 8, seed=2)

        def loss_flash(q_, k_, v_):
            return (flash_attention(q_, k_, v_, causal=True) ** 2).sum()

        def loss_ref(q_, k_, v_):
            return (_attn_reference(q_, k_, v_, 8 ** -0.5, True) ** 2).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_multiblock_ragged(self, causal):
        """Pallas backward over several blocks incl. a ragged tail: the
        dq pass and the dk/dv pass must both mask padded rows/cols."""
        q, k, v = _qkv(2, 72, 16, seed=7)

        def loss_flash(q_, k_, v_):
            return (flash_attention(q_, k_, v_, causal=causal,
                                    block_q=32, block_k=32) ** 2).sum()

        def loss_ref(q_, k_, v_):
            return (_attn_reference(q_, k_, v_, 16 ** -0.5,
                                    causal) ** 2).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_cross_lengths(self, causal):
        """Backward with Tk != Tq (cross attention), incl. the causal
        row>=col masking against ragged q AND k tails."""
        q, _, _ = _qkv(1, 40, 16, seed=8)
        _, k, v = _qkv(1, 56, 16, seed=9)

        def loss_flash(q_, k_, v_):
            return (flash_attention(q_, k_, v_, causal=causal,
                                    block_q=32, block_k=32) ** 2).sum()

        def loss_ref(q_, k_, v_):
            return (_attn_reference(q_, k_, v_, 16 ** -0.5,
                                    causal) ** 2).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_registered_op(self):
        q, k, v = _qkv(1, 16, 8, heads=2)
        out = nd._contrib_FlashAttention(nd.array(np.asarray(q)),
                                         nd.array(np.asarray(k)),
                                         nd.array(np.asarray(v)),
                                         causal=True)
        assert out.shape == (1, 2, 16, 8)

    @pytest.mark.parametrize("window", [1, 3, 16, 100])
    def test_window_attention_matches_dense(self, window):
        """Sliding-window flash (fwd + Pallas bwd) equals the dense
        banded-mask oracle, across window widths incl. degenerate
        (1 = self-only) and wider-than-T (= plain causal)."""
        q, k, v = _qkv(2, 40, 16, seed=17)

        def dense(q_, k_, v_):
            s = jnp.einsum("bqd,bkd->bqk", q_, k_) * 16 ** -0.5
            r = jnp.arange(40)[:, None]
            c = jnp.arange(40)[None, :]
            s = jnp.where((r >= c) & (r - c < window), s, -1e30)
            return jnp.einsum("bqk,bkd->bqd",
                              jax.nn.softmax(s, axis=-1), v_)

        out = flash_attention(q, k, v, causal=True, window=window,
                              block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(dense(q, k, v)),
                                   rtol=2e-5, atol=2e-6)

        gf = jax.grad(lambda a, b, c: (flash_attention(
            a, b, c, causal=True, window=window, block_q=16,
            block_k=16) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda a, b, c: (dense(a, b, c) ** 2).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_window_requires_causal(self):
        q, k, v = _qkv(1, 16, 8)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, window=4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_lse_variant_gradients(self, causal):
        """flash_attention_with_lse: gradient flow through BOTH outputs
        (the lse cotangent folds into the backward's delta term)."""
        from mxnet_tpu.ops.attention import flash_attention_with_lse
        q, k, v = _qkv(2, 72, 16, seed=13)

        def loss_flash(q_, k_, v_):
            o, lse = flash_attention_with_lse(q_, k_, v_,
                                              causal=causal,
                                              block_q=32, block_k=32)
            return (o ** 2).sum() + (jnp.sin(lse) ** 2).sum()

        def loss_ref(q_, k_, v_):
            s = jnp.einsum("bqd,bkd->bqk", q_, k_) * 16 ** -0.5
            if causal:
                m = jnp.arange(72)[:, None] >= jnp.arange(72)[None, :]
                s = jnp.where(m, s, -1e30)
            lse = jax.scipy.special.logsumexp(s, axis=-1)
            o = jnp.einsum("bqk,bkd->bqd",
                           jnp.exp(s - lse[..., None]), v_)
            return (o ** 2).sum() + (jnp.sin(lse) ** 2).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    @pytest.mark.skipif(jax.device_count() < 2,
                        reason="needs a 2-device mesh")
    def test_replicated_shard_map_runs_kernel(self):
        """Fully-replicated q/k/v under a vma-checking shard_map: the
        kernel path itself runs (no varying operand, so no interpret
        fallback) and the out aval must declare vma=empty — omitting
        vma entirely raises under check_vma."""
        from mxnet_tpu.parallel._compat import shard_map
        mesh = Mesh(np.array(jax.devices()[:2]), ("sp",))
        q, k, v = _qkv(2, 32, 16, seed=12)
        fn = shard_map(
            lambda a, b, c: flash_attention(a, b, c, block_q=16,
                                            block_k=16),
            mesh=mesh, in_specs=(P(), P(), P()), out_specs=P())
        out = fn(q, k, v)
        ref = _attn_reference(q, k, v, 16 ** -0.5, False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-6)

    @pytest.mark.skipif(jax.device_count() < 2,
                        reason="needs a 2-device mesh")
    def test_grad_mixed_variance_shard_map(self):
        """Backward under a vma-checking shard_map where q is replicated
        while k/v vary over the mesh axis: the cotangent dq must come
        back replicated (psum over the extra axis), not union-varying
        (regression: the Pallas backward stamps outputs with the union
        vma; _narrow_vma reduces it to each primal's variance)."""
        from mxnet_tpu.parallel._compat import shard_map
        mesh = Mesh(np.array(jax.devices()[:2]), ("sp",))
        q, k, v = _qkv(2, 32, 16, seed=11)

        def body(q_, k_, v_):
            # each device attends its local half of the keys; q is
            # shared, so its cotangent must be psum'd back to replicated
            def loss(a, b, c):
                return (flash_attention(a, b, c, block_q=16,
                                        block_k=16)
                        .astype(jnp.float32) ** 2).sum()
            return jax.grad(loss, argnums=(0, 1, 2))(q_, k_, v_)

        fn = shard_map(body, mesh=mesh,
                       in_specs=(P(), P(None, "sp"), P(None, "sp")),
                       out_specs=(P(), P(None, "sp"), P(None, "sp")))
        dq, dk, dv = fn(q, k, v)   # raises if dq variance is wrong
        assert dq.shape == q.shape
        assert dk.shape == k.shape


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8-device mesh")
class TestRingAttention:
    def _mesh(self):
        return Mesh(np.array(jax.devices()[:8]), ("sp",))

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_single_device(self, causal):
        mesh = self._mesh()
        q, k, v = _qkv(2, 8 * 16, 32, heads=2)
        shard = NamedSharding(mesh, P(None, None, "sp", None))
        qs, ks, vs = (jax.device_put(x, shard) for x in (q, k, v))
        out = ring_attention(qs, ks, vs, mesh, "sp", causal=causal)
        B, H, T, D = q.shape
        ref = _attn_reference(q.reshape(B * H, T, D),
                              k.reshape(B * H, T, D),
                              v.reshape(B * H, T, D), D ** -0.5, causal)
        np.testing.assert_allclose(
            np.asarray(out).reshape(B * H, T, D), np.asarray(ref),
            rtol=2e-5, atol=2e-6)

    def test_output_stays_sequence_sharded(self):
        mesh = self._mesh()
        q, k, v = _qkv(1, 8 * 8, 16, heads=1)
        shard = NamedSharding(mesh, P(None, None, "sp", None))
        qs, ks, vs = (jax.device_put(x, shard) for x in (q, k, v))
        out = jax.jit(lambda a, b, c: ring_attention(
            a, b, c, mesh, "sp"))(qs, ks, vs)
        assert out.sharding.spec == P(None, None, "sp", None)

    def test_collectives_in_hlo(self):
        mesh = self._mesh()
        q, k, v = _qkv(1, 8 * 8, 16, heads=1)
        shard = NamedSharding(mesh, P(None, None, "sp", None))
        qs, ks, vs = (jax.device_put(x, shard) for x in (q, k, v))
        hlo = jax.jit(lambda a, b, c: ring_attention(
            a, b, c, mesh, "sp")).lower(qs, ks, vs).compile()\
            .as_text()
        assert "collective-permute" in hlo

    def test_gqa_through_flash_op_on_ring(self):
        """GQA kv broadcast happens BEFORE the ring branch in the flash
        op, so num_kv_heads < H trains sequence-parallel: the op with
        (B, 2, T, D) kv against (B, 4, T, D) q over the sp mesh must
        equal the dense GQA reference."""
        from mxnet_tpu.ops.attention import _flash_attention_op
        from mxnet_tpu.ops import _mesh_ctx
        mesh = self._mesh()
        B, H, Hkv, T, D = 1, 4, 2, 8 * 8, 16
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, Hkv, T, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, Hkv, T, D), jnp.float32)
        qs = jax.device_put(q, NamedSharding(
            mesh, P(None, None, "sp", None)))
        ks, vs = (jax.device_put(x, NamedSharding(
            mesh, P(None, None, "sp", None))) for x in (k, v))
        with _mesh_ctx.use_mesh(mesh):
            out = _flash_attention_op(qs, ks, vs, causal=True,
                                      seq_axis="sp")
        kr = jnp.repeat(k, H // Hkv, axis=1)
        vr = jnp.repeat(v, H // Hkv, axis=1)
        ref = _attn_reference(q.reshape(B * H, T, D),
                              kr.reshape(B * H, T, D),
                              vr.reshape(B * H, T, D), D ** -0.5,
                              True)
        np.testing.assert_allclose(
            np.asarray(out).reshape(B * H, T, D), np.asarray(ref),
            rtol=2e-5, atol=2e-6)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_dense(self, causal):
        """Long-context TRAINING path: autodiff through the ring
        (scan + ppermute) must equal dense-attention gradients."""
        mesh = self._mesh()
        B, H, T, D = 2, 2, 8 * 8, 16
        q, k, v = _qkv(B, T, D, heads=H)
        shard = NamedSharding(mesh, P(None, None, "sp", None))
        qs, ks, vs = (jax.device_put(x, shard) for x in (q, k, v))

        grads = jax.jit(jax.grad(
            lambda a, b, c: ring_attention(
                a, b, c, mesh, "sp", causal=causal).sum(),
            argnums=(0, 1, 2)))(qs, ks, vs)

        def dense(a, b, c):
            r = _attn_reference(a.reshape(B * H, T, D),
                                b.reshape(B * H, T, D),
                                c.reshape(B * H, T, D),
                                D ** -0.5, causal)
            return r.sum()

        want = jax.grad(dense, argnums=(0, 1, 2))(q, k, v)
        for g, w, name in zip(grads, want, "qkv"):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=2e-4, atol=2e-5,
                                       err_msg="d%s" % name)


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8-device mesh")
class TestSeqAxisOp:
    """seq_axis on _contrib_FlashAttention: the symbol-level
    sequence-parallel path (ring attention under an ambient mesh)."""

    def test_symbol_graph_rings_on_mesh(self):
        import mxnet_tpu as mx
        from mxnet_tpu.executor import _graph_eval_fn

        mesh = Mesh(np.array(jax.devices()[:8]), ("sp",))
        B, H, T, D = 2, 2, 8 * 8, 16
        q, k, v = (mx.sym.Variable(n) for n in "qkv")
        out = mx.sym.contrib.FlashAttention(q, k, v, causal=True,
                                            seq_axis="sp")
        qv, kv, vv = _qkv(B, T, D, heads=H)
        shard = NamedSharding(mesh, P(None, None, "sp", None))

        fn = _graph_eval_fn(out, mesh=mesh)
        args = {"q": jax.device_put(qv, shard),
                "k": jax.device_put(kv, shard),
                "v": jax.device_put(vv, shard)}
        jitted = jax.jit(lambda a: fn(a, {}, jax.random.PRNGKey(0),
                                      False)[0][0])
        got = jitted(args)
        # ring == dense reference
        ref = _attn_reference(qv.reshape(B * H, T, D),
                              kv.reshape(B * H, T, D),
                              vv.reshape(B * H, T, D), D ** -0.5, True)
        np.testing.assert_allclose(
            np.asarray(got).reshape(B * H, T, D), np.asarray(ref),
            rtol=2e-5, atol=2e-6)
        # and it really went around the ring
        hlo = jitted.lower(args).compile().as_text()
        assert "collective-permute" in hlo

    def test_no_mesh_falls_back_to_flash(self):
        import mxnet_tpu as mx
        from mxnet_tpu.executor import _graph_eval_fn

        q, k, v = (mx.sym.Variable(n) for n in "qkv")
        out = mx.sym.contrib.FlashAttention(q, k, v, causal=True,
                                            seq_axis="sp")
        qv, kv, vv = _qkv(1, 32, 16, heads=2)
        fn = _graph_eval_fn(out)   # no mesh
        got = fn({"q": qv, "k": kv, "v": vv}, {},
                 jax.random.PRNGKey(0), False)[0][0]
        ref = _attn_reference(qv.reshape(2, 32, 16),
                              kv.reshape(2, 32, 16),
                              vv.reshape(2, 32, 16), 16 ** -0.5, True)
        np.testing.assert_allclose(np.asarray(got).reshape(2, 32, 16),
                                   np.asarray(ref), rtol=2e-5,
                                   atol=2e-6)

    @pytest.mark.slow
    def test_transformer_trains_sequence_parallel(self):
        """End to end: transformer LM symbol with seq_axis, TrainStep
        over an {'sp': 8} mesh — compiles, runs, loss sane, ring
        collectives present. Slow tier (~14 s on the 1-core tier-1
        host); the seq-axis op keeps fast coverage in
        test_symbol_graph_rings_on_mesh/test_no_mesh_falls_back."""
        import mxnet_tpu as mx
        from mxnet_tpu.initializer import Xavier
        from mxnet_tpu.models import transformer
        from mxnet_tpu.parallel import make_mesh, make_train_step

        mesh = make_mesh({"sp": 8})
        vocab, T, B = 64, 8 * 8, 2
        sym_ = transformer.get_symbol(vocab, T, num_layers=1,
                                      num_heads=2, dim=32,
                                      seq_axis="sp")
        step = make_train_step(sym_, optimizer="adam", mesh=mesh)
        state = step.init_state(Xavier(), {"data": (B, T),
                                           "softmax_label": (B, T)})
        rng_np = np.random.RandomState(0)
        toks = rng_np.randint(0, vocab, (B, T)).astype(np.float32)
        labels = np.roll(toks, -1, axis=1)
        labels[:, -1] = -1
        batch = step.place_batch({"data": toks,
                                  "softmax_label": labels})
        hlo = step.lower(state, batch, 1e-3,
                         jax.random.PRNGKey(0)).compile().as_text()
        assert "collective-permute" in hlo
        state, outs = step(state, batch, 1e-3, jax.random.PRNGKey(0))
        probs = np.asarray(outs[0])
        assert np.isfinite(probs).all()
        np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-4)


@pytest.mark.slow
@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8-device mesh")
def test_full_composition_dp_sp_zero1_bf16():
    """The whole v5e-pod recipe in one step: 2-D data x sp mesh, ring
    attention per layer, ZeRO-1 optimizer sharding over 'data', bf16
    compute with f32 masters and protected token ids — compiles,
    rings, shards, and converges. Slow tier (~24 s on the 1-core
    tier-1 host); every ingredient keeps fast coverage (ring attention
    in TestRingAttention, seq-axis in TestSeqAxisOp, ZeRO-1/bf16 in
    test_gspmd.py)."""
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel import make_mesh, make_train_step

    mesh = make_mesh({"data": 2, "sp": 4})
    vocab, T, B = 512, 64, 4
    sym = transformer.get_symbol(vocab, T, num_layers=1, num_heads=2,
                                 dim=32, seq_axis="sp")
    step = make_train_step(sym, optimizer="adam", mesh=mesh,
                           optimizer_sharding="zero1",
                           compute_dtype="bfloat16")
    assert step._id_inputs == {"data"}   # ids survive the bf16 cast
    state = step.init_state(Xavier(), {"data": (B, T),
                                       "softmax_label": (B, T)})
    from tests._lm_utils import arith_corpus, lm_nll
    toks, labels = arith_corpus(B, T, vocab)
    batch = step.place_batch({"data": toks, "softmax_label": labels})
    rng = jax.random.PRNGKey(0)
    hlo = step.lower(state, batch, 1e-3, rng).compile().as_text()
    assert "collective-permute" in hlo          # the ring is real

    state, outs = step(state, batch, 2e-3, rng)
    first = lm_nll(outs, labels, vocab)
    for _ in range(60):
        state, outs = step(state, batch, 2e-3, rng)
    assert lm_nll(outs, labels, vocab) < first / 2
    # optimizer state stayed ZeRO-1 sharded through the run
    m = state[1]["layer0_qkv_weight"][0]
    assert "data" in str(m.sharding.spec), m.sharding


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8-device mesh")
class TestWindowedRingAttention:
    """Banded causal ring: compute and ring hops scale with the window.
    Every (window, shard) regime checked against the dense banded
    oracle — partial band blocks, full blocks, window under one shard,
    window past the whole context."""

    def _mesh(self):
        return Mesh(np.array(jax.devices()[:8]), ("sp",))

    # window=1000 (> T: the degenerate all-visible band) costs ~9 s of
    # compile on the tier-1 host — slow tier; 24 already exercises a
    # window spanning multiple ring hops
    @pytest.mark.parametrize("window",
                             [1, 5, 8, 13, 24,
                              pytest.param(1000,
                                           marks=pytest.mark.slow)])
    def test_matches_dense_banded(self, window):
        mesh = self._mesh()
        B, H, T, D = 1, 2, 8 * 8, 16
        q, k, v = _qkv(B, T, D, heads=H)
        shard = NamedSharding(mesh, P(None, None, "sp", None))
        qs, ks, vs = (jax.device_put(x, shard) for x in (q, k, v))
        out = ring_attention(qs, ks, vs, mesh, "sp", causal=True,
                             window=window)
        from mxnet_tpu.ops.attention import _dense_with_lse
        ref = _dense_with_lse(
            jnp.asarray(q).reshape(B * H, T, D),
            jnp.asarray(k).reshape(B * H, T, D),
            jnp.asarray(v).reshape(B * H, T, D),
            D ** -0.5, True, window)[0]
        np.testing.assert_allclose(
            np.asarray(out).reshape(B * H, T, D), np.asarray(ref),
            rtol=2e-5, atol=2e-6)

    @pytest.mark.parametrize("window", [5, 13])
    def test_gradients_match_dense_banded(self, window):
        mesh = self._mesh()
        B, H, T, D = 1, 1, 8 * 8, 16
        q, k, v = _qkv(B, T, D, heads=H)
        shard = NamedSharding(mesh, P(None, None, "sp", None))
        qs, ks, vs = (jax.device_put(x, shard) for x in (q, k, v))
        grads = jax.jit(jax.grad(
            lambda a, b, c: ring_attention(
                a, b, c, mesh, "sp", causal=True,
                window=window).sum(), argnums=(0, 1, 2)))(qs, ks, vs)
        from mxnet_tpu.ops.attention import _dense_with_lse

        def dense(a, b, c):
            return _dense_with_lse(
                a.reshape(B * H, T, D), b.reshape(B * H, T, D),
                c.reshape(B * H, T, D), D ** -0.5, True,
                window)[0].sum()

        want = jax.grad(dense, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        for g, w, name in zip(grads, want, "qkv"):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=2e-4, atol=2e-5,
                                       err_msg="d%s" % name)

    def test_window_requires_causal(self):
        mesh = self._mesh()
        q, k, v = _qkv(1, 8 * 8, 16, heads=1)
        with pytest.raises(ValueError, match="causal"):
            ring_attention(q, k, v, mesh, "sp", causal=False, window=4)
