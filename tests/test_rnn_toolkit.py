"""Symbolic RNN toolkit tests (reference: tests/python/unittest/test_rnn.py
and the lstm_bucketing example, example/rnn/lstm_bucketing.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _bind_run(outputs, data, **extra):
    ex = outputs.simple_bind(data=data.shape)
    for name, arr in ex.arg_dict.items():
        if name != "data" and name not in extra:
            arr[:] = np.random.uniform(-0.2, 0.2, arr.shape)
    for name, arr in extra.items():
        ex.arg_dict[name][:] = arr
    return ex, ex.forward(data=data)


class TestCells:
    def test_rnn_cell_shapes(self):
        cell = mx.rnn.RNNCell(10, prefix="rnn_")
        outputs, states = cell.unroll(3, mx.sym.Variable("data"),
                                      merge_outputs=True)
        assert sorted(outputs.list_arguments()) == sorted(
            ["data", "rnn_i2h_weight", "rnn_i2h_bias", "rnn_h2h_weight",
             "rnn_h2h_bias"])
        _, outs = _bind_run(outputs, np.zeros((2, 3, 4), "float32"))
        assert outs[0].shape == (2, 3, 10)

    def test_lstm_cell_shapes(self):
        cell = mx.rnn.LSTMCell(10, prefix="lstm_")
        outputs, states = cell.unroll(3, mx.sym.Variable("data"),
                                      merge_outputs=True)
        assert len(states) == 2
        _, outs = _bind_run(outputs, np.zeros((2, 3, 4), "float32"))
        assert outs[0].shape == (2, 3, 10)

    def test_gru_cell_shapes(self):
        cell = mx.rnn.GRUCell(10)
        outputs, _ = cell.unroll(3, mx.sym.Variable("data"),
                                 merge_outputs=True)
        _, outs = _bind_run(outputs, np.zeros((2, 3, 4), "float32"))
        assert outs[0].shape == (2, 3, 10)

    def test_unroll_list_inputs(self):
        cell = mx.rnn.RNNCell(6)
        ins = [mx.sym.Variable("t%d" % i) for i in range(3)]
        outputs, _ = cell.unroll(3, ins)
        assert isinstance(outputs, list) and len(outputs) == 3

    def test_stacked(self):
        stack = mx.rnn.SequentialRNNCell()
        stack.add(mx.rnn.LSTMCell(8, prefix="l0_"))
        stack.add(mx.rnn.LSTMCell(8, prefix="l1_"))
        outputs, states = stack.unroll(3, mx.sym.Variable("data"),
                                       merge_outputs=True)
        assert len(states) == 4
        _, outs = _bind_run(outputs, np.zeros((2, 3, 4), "float32"))
        assert outs[0].shape == (2, 3, 8)

    def test_bidirectional(self):
        cell = mx.rnn.BidirectionalCell(mx.rnn.GRUCell(5, prefix="l_"),
                                        mx.rnn.GRUCell(5, prefix="r_"))
        outputs, _ = cell.unroll(3, mx.sym.Variable("data"),
                                 merge_outputs=True)
        _, outs = _bind_run(outputs, np.zeros((2, 3, 4), "float32"))
        assert outs[0].shape == (2, 3, 10)

    def test_residual(self):
        cell = mx.rnn.ResidualCell(mx.rnn.GRUCell(4, prefix="res_"))
        outputs, _ = cell.unroll(3, mx.sym.Variable("data"),
                                 merge_outputs=True)
        _, outs = _bind_run(outputs, np.zeros((2, 3, 4), "float32"))
        assert outs[0].shape == (2, 3, 4)

    def test_zoneout_and_dropout(self):
        cell = mx.rnn.ZoneoutCell(mx.rnn.RNNCell(6, prefix="z_"), 0.3, 0.3)
        outputs, _ = cell.unroll(3, mx.sym.Variable("data"),
                                 merge_outputs=True)
        _, outs = _bind_run(outputs, np.zeros((2, 3, 4), "float32"))
        assert outs[0].shape == (2, 3, 6)

        stack = mx.rnn.SequentialRNNCell()
        stack.add(mx.rnn.LSTMCell(6, prefix="d0_"))
        stack.add(mx.rnn.DropoutCell(0.5))
        outputs, _ = stack.unroll(3, mx.sym.Variable("data"),
                                  merge_outputs=True)
        _, outs = _bind_run(outputs, np.zeros((2, 3, 4), "float32"))
        assert outs[0].shape == (2, 3, 6)


class TestFused:
    @pytest.mark.parametrize("mode,bidir", [("lstm", False), ("gru", False),
                                            ("rnn_tanh", False),
                                            ("lstm", True), ("gru", True)])
    def test_fused_matches_unfused(self, mode, bidir):
        np.random.seed(0)
        T, N, C, H = 4, 2, 3, 5
        x = np.random.randn(N, T, C).astype("float32")
        fused = mx.rnn.FusedRNNCell(H, num_layers=2, mode=mode,
                                    bidirectional=bidir, prefix="f_")
        fo, _ = fused.unroll(T, mx.sym.Variable("data"), layout="NTC",
                             merge_outputs=True)
        ex = fo.simple_bind(data=(N, T, C))
        blob = np.random.uniform(-0.5, 0.5,
                                 ex.arg_dict["f_parameters"].shape
                                 ).astype("float32")
        y_fused = ex.forward(data=x, f_parameters=blob)[0].asnumpy()

        stack = fused.unfuse()
        uo, _ = stack.unroll(T, mx.sym.Variable("data"), layout="NTC",
                             merge_outputs=True)
        cellargs = stack.pack_weights(fused.unpack_weights(
            {"f_parameters": mx.nd.array(blob)}))
        ex2 = uo.simple_bind(data=(N, T, C))
        for k, v in cellargs.items():
            ex2.arg_dict[k][:] = v.asnumpy()
        y_unfused = ex2.forward(data=x)[0].asnumpy()
        np.testing.assert_allclose(y_fused, y_unfused, rtol=1e-5, atol=1e-6)

    def test_pack_roundtrip(self):
        fused = mx.rnn.FusedRNNCell(5, num_layers=3, mode="lstm",
                                    bidirectional=True, prefix="p_")
        size = mx.ops.rnn_op.rnn_param_size("lstm", 7, 5, 3, True)
        blob = np.random.randn(size).astype("float32")
        unpacked = fused.unpack_weights({"p_parameters": mx.nd.array(blob)})
        repacked = fused.pack_weights(unpacked)
        np.testing.assert_array_equal(repacked["p_parameters"].asnumpy(),
                                      blob)

    def test_fused_state_outputs(self):
        fused = mx.rnn.FusedRNNCell(6, num_layers=2, mode="lstm",
                                    get_next_state=True, prefix="s_")
        outputs, states = fused.unroll(3, mx.sym.Variable("data"),
                                       layout="NTC", merge_outputs=True)
        assert len(states) == 2
        group = mx.sym.Group([outputs] + states)
        ex = group.simple_bind(data=(2, 3, 4))
        outs = ex.forward(
            data=np.zeros((2, 3, 4), "float32"),
            s_parameters=np.random.randn(
                *ex.arg_dict["s_parameters"].shape).astype("float32"))
        assert outs[0].shape == (2, 3, 6)
        assert outs[1].shape == (2, 2, 6)
        assert outs[2].shape == (2, 2, 6)


class TestFusedInit:
    def test_module_init_fused_blob(self):
        """Module.init_params routes the fused blob through the FusedRNN
        initializer (attr-driven), baking the lstm forget bias."""
        fused = mx.rnn.FusedRNNCell(4, num_layers=1, mode="lstm",
                                    prefix="f_")
        out, _ = fused.unroll(2, mx.sym.Variable("data"),
                              merge_outputs=True)
        out = mx.sym.MakeLoss(mx.sym.sum(out))
        mod = mx.mod.Module(out, ("data",), None)
        mod.bind([mx.io.DataDesc("data", (2, 2, 3))], None)
        mod.init_params(mx.init.Xavier())
        blob = mod.get_params()[0]["f_parameters"]
        unp = fused.unpack_weights({"f_parameters": blob})
        np.testing.assert_array_equal(
            unp["f_l0_i2h_f_bias"].asnumpy(), np.ones(4, "float32"))
        assert unp["f_l0_i2h_i_weight"].asnumpy().std() > 0


class TestUnfuseForgetBias:
    def test_forget_bias_propagates(self):
        fused = mx.rnn.FusedRNNCell(4, num_layers=1, mode="lstm",
                                    forget_bias=2.5, prefix="fb_")
        stack = fused.unfuse()
        cell = stack._cells[0]
        import json
        klass, kwargs = json.loads(cell._iB.attr("__init__"))
        assert klass.lower() == "lstmbias"
        assert kwargs["forget_bias"] == 2.5


class TestBucketIO:
    def test_encode_sentences(self):
        sents = [["a", "b", "c"], ["b", "c"]]
        enc, vocab = mx.rnn.encode_sentences(sents, start_label=1)
        assert enc[0] == [vocab["a"], vocab["b"], vocab["c"]]
        assert enc[1] == [vocab["b"], vocab["c"]]
        assert min(v for k, v in vocab.items() if k != "\n") == 1

    def test_bucket_sentence_iter(self):
        np.random.seed(0)
        sents = [[1] * int(n) for n in
                 np.random.randint(1, 9, size=100)]
        it = mx.rnn.BucketSentenceIter(sents, batch_size=4,
                                       buckets=[4, 8], invalid_label=0)
        seen = 0
        for batch in it:
            assert batch.bucket_key in (4, 8)
            assert batch.data[0].shape == (4, batch.bucket_key)
            assert batch.provide_data[0].shape == (4, batch.bucket_key)
            seen += 1
        assert seen > 0
        it.reset()
        assert sum(1 for _ in it) == seen

    def test_label_is_shifted(self):
        sents = [[5, 6, 7, 8]] * 4
        it = mx.rnn.BucketSentenceIter(sents, batch_size=4, buckets=[4],
                                       invalid_label=0)
        batch = next(it)
        np.testing.assert_array_equal(batch.data[0].asnumpy()[0],
                                      [5, 6, 7, 8])
        np.testing.assert_array_equal(batch.label[0].asnumpy()[0],
                                      [6, 7, 8, 0])


class TestPTBShapedTraining:
    """Workload parity config #4 (SURVEY Appendix B): bucketed LSTM LM via
    BucketingModule, perplexity decreasing."""

    def test_bucketing_lstm_lm(self):
        np.random.seed(0)
        vocab = 16
        # synthetic deterministic corpus: next token = (t + 1) % vocab
        sents = []
        for _ in range(60):
            ln = np.random.choice([4, 6])
            start = np.random.randint(0, vocab)
            sents.append([(start + i) % vocab for i in range(ln)])
        train = mx.rnn.BucketSentenceIter(sents, batch_size=8,
                                          buckets=[4, 6], invalid_label=-1)

        def sym_gen(seq_len):
            data = mx.sym.Variable("data")
            label = mx.sym.Variable("softmax_label")
            embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=8,
                                     name="embed")
            stack = mx.rnn.SequentialRNNCell()
            stack.add(mx.rnn.LSTMCell(16, prefix="lstm_l0_"))
            outputs, _ = stack.unroll(seq_len, embed, layout="NTC",
                                      merge_outputs=True)
            pred = mx.sym.Reshape(outputs, shape=(-1, 16))
            pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="pred")
            label = mx.sym.Reshape(label, shape=(-1,))
            return (mx.sym.SoftmaxOutput(pred, label, name="softmax"),
                    ("data",), ("softmax_label",))

        mod = mx.mod.BucketingModule(sym_gen,
                                     default_bucket_key=train.
                                     default_bucket_key)
        mod.bind(train.provide_data, train.provide_label)
        mod.init_params(mx.init.Xavier())
        mod.init_optimizer(optimizer="adam",
                           optimizer_params=(("learning_rate", 0.05),))
        metric = mx.metric.Perplexity(-1)

        perps = []
        for _epoch in range(8):
            train.reset()
            metric.reset()
            for batch in train:
                mod.forward(batch, is_train=True)
                mod.backward()
                mod.update()
                mod.update_metric(metric, batch.label)
            perps.append(metric.get()[1])
        assert perps[-1] < perps[0] / 2, perps

    def test_buckets_share_one_parameter_set(self):
        """Round-5 regression (caught by the speech example's padding-
        invariance check): bucket executors must adopt the default
        bucket's param/aux arrays BY REFERENCE — without it every
        bucket trains its own silently diverging parameter copy
        (reference executor_group.py:_bind_ith_exec shared_exec arg
        sharing)."""
        vocab = 16

        def sym_gen(seq_len):
            data = mx.sym.Variable("data")
            label = mx.sym.Variable("softmax_label")
            embed = mx.sym.Embedding(data, input_dim=vocab,
                                     output_dim=8, name="embed")
            cell = mx.rnn.LSTMCell(16, prefix="lstm_")
            outputs, _ = cell.unroll(seq_len, embed, layout="NTC",
                                     merge_outputs=True)
            pred = mx.sym.Reshape(outputs, shape=(-1, 16))
            # BatchNorm: its moving stats are AUX state — included so
            # the aux-sharing branch is exercised too
            pred = mx.sym.BatchNorm(pred, name="bn", fix_gamma=False)
            pred = mx.sym.FullyConnected(pred, num_hidden=vocab,
                                         name="pred")
            label = mx.sym.Reshape(label, shape=(-1,))
            return (mx.sym.SoftmaxOutput(pred, label, name="softmax"),
                    ("data",), ("softmax_label",))

        B = 4
        mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=6)
        mod.bind(data_shapes=[("data", (B, 6))],
                 label_shapes=[("softmax_label", (B, 6))])
        mod.init_params(mx.init.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params=(("learning_rate", 0.5),))

        def batch_for(T):
            x = np.arange(B * T, dtype=np.float32).reshape(B, T) % vocab
            return mx.io.DataBatch(
                data=[mx.nd.array(x)], label=[mx.nd.array(x)],
                bucket_key=T,
                provide_data=[("data", (B, T))],
                provide_label=[("softmax_label", (B, T))])

        # bind bucket 4 lazily, then train ONLY through bucket 6
        mod.forward(batch_for(4), is_train=False)
        for _ in range(3):
            mod.forward_backward(batch_for(6))
            mod.update()

        # the crisp assertion: every param/aux/grad NDArray is the
        # SAME object in both bucket executors
        e6 = mod._buckets[6]._exec_group.execs[0]
        e4 = mod._buckets[4]._exec_group.execs[0]
        for name in ("embed_weight", "lstm_i2h_weight", "pred_weight",
                     "pred_bias", "bn_gamma"):
            assert e6.arg_dict[name] is e4.arg_dict[name], name
            assert e6.grad_dict[name] is e4.grad_dict[name], name
        for name in ("bn_moving_mean", "bn_moving_var"):
            assert e6.aux_dict[name] is e4.aux_dict[name], name
        # and behaviorally: bucket 4 sees bucket 6's training,
        # including the BN moving stats it never ran itself
        w6 = mod._buckets[6].get_params()[0]["pred_weight"].asnumpy()
        w4 = mod._buckets[4].get_params()[0]["pred_weight"].asnumpy()
        np.testing.assert_array_equal(w6, w4)
        m6 = mod._buckets[6].get_params()[1]["bn_moving_mean"].asnumpy()
        m4 = mod._buckets[4].get_params()[1]["bn_moving_mean"].asnumpy()
        np.testing.assert_array_equal(m6, m4)
        assert np.abs(m6).max() > 0  # training actually moved them


class TestTimeMajorLayout:
    def test_tnc_unroll_matches_ntc(self):
        """The reference's example/rnn-time-major seam: layout='TNC'
        (time-major — the faster layout for cuDNN there, a free
        transpose choice under XLA) must be numerically identical to
        the default NTC unroll on transposed data."""
        B, T, F, H = 3, 5, 4, 6
        rng = np.random.RandomState(0)
        x = rng.randn(B, T, F).astype(np.float32)

        def run(layout, arr):
            cell = mx.rnn.LSTMCell(H, prefix="tm_")
            data = mx.sym.Variable("data")
            out, _ = cell.unroll(T, data, layout=layout,
                                 merge_outputs=True)
            ex = out.simple_bind(data=arr.shape)
            args = ex.arg_dict
            prng = np.random.RandomState(1)
            for name in sorted(args):
                if name != "data":
                    args[name][:] = mx.nd.array(prng.uniform(
                        -0.2, 0.2, args[name].shape).astype(
                        np.float32))
            args["data"][:] = mx.nd.array(arr)
            return ex.forward()[0].asnumpy()

        out_ntc = run("NTC", x)                        # (B, T, H)
        out_tnc = run("TNC", x.transpose(1, 0, 2))     # (T, B, H)
        np.testing.assert_allclose(out_tnc.transpose(1, 0, 2),
                                   out_ntc, rtol=1e-5, atol=1e-6)


class TestRNNCheckpoint:
    def test_fused_unfused_checkpoint_interop(self, tmp_path):
        """save with the fused cell, load into the unfused stack — the
        per-gate canonical layout bridges them (reference rnn.py)."""
        prefix = str(tmp_path / "lm")
        fused = mx.rnn.FusedRNNCell(6, num_layers=1, mode="lstm",
                                    prefix="ck_")
        out, _ = fused.unroll(3, mx.sym.Variable("data"),
                              merge_outputs=True)
        ex = out.simple_bind(data=(2, 3, 4))
        blob = np.random.RandomState(0).uniform(
            -0.5, 0.5, ex.arg_dict["ck_parameters"].shape
        ).astype("float32")
        arg_params = {"ck_parameters": mx.nd.array(blob)}
        mx.rnn.save_rnn_checkpoint(fused, prefix, 1, out, arg_params, {})

        stack = fused.unfuse()
        _, args, _ = mx.rnn.load_rnn_checkpoint(stack, prefix, 1)
        assert "ck_l0_i2h_weight" in args
        # round-trip back into the fused layout is lossless
        _, args2, _ = mx.rnn.load_rnn_checkpoint(fused, prefix, 1)
        np.testing.assert_allclose(
            args2["ck_parameters"].asnumpy(), blob, rtol=1e-6)

    def test_do_rnn_checkpoint_callback(self, tmp_path):
        prefix = str(tmp_path / "cb")
        cell = mx.rnn.LSTMCell(4, prefix="cb_")
        out, _ = cell.unroll(2, mx.sym.Variable("data"),
                             merge_outputs=True)
        ex = out.simple_bind(data=(1, 2, 3))
        args = {k: v for k, v in ex.arg_dict.items() if k != "data"}
        cb = mx.rnn.do_rnn_checkpoint(cell, prefix, period=2)
        cb(0, out, args, {})      # epoch 1: not a period boundary... (0+1)%2!=0
        cb(1, out, args, {})      # epoch 2: writes
        import os
        assert os.path.exists(prefix + "-0002.params")
