"""mx.viz + mx.rtc tests (reference: visualization.py, rtc.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _lenet():
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8,
                            name="conv1")
    a1 = mx.sym.Activation(c1, act_type="relu", name="relu1")
    p1 = mx.sym.Pooling(a1, kernel=(2, 2), stride=(2, 2),
                        pool_type="max", name="pool1")
    fc = mx.sym.FullyConnected(mx.sym.Flatten(p1), num_hidden=10,
                               name="fc1")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


class TestPrintSummary:
    def test_totals_and_rows(self, capsys):
        total = mx.viz.print_summary(_lenet(),
                                     shape={"data": (1, 1, 28, 28)})
        out = capsys.readouterr().out
        assert "conv1 (Convolution)" in out
        assert "fc1 (FullyConnected)" in out
        # conv1: 8*1*3*3 + 8;  fc1: 10*(8*13*13) + 10
        assert total == (8 * 9 + 8) + (10 * 8 * 13 * 13 + 10), total
        assert "Total params: %d" % total in out

    def test_without_shapes(self, capsys):
        mx.viz.print_summary(_lenet())
        assert "softmax (SoftmaxOutput)" in capsys.readouterr().out


class TestPlotNetwork:
    def test_digraph_or_skip(self):
        pytest.importorskip("graphviz")
        dot = mx.viz.plot_network(_lenet(),
                                  shape={"data": (1, 1, 28, 28)})
        src = dot.source
        assert "conv1" in src and "softmax" in src


class TestRtc:
    def test_saxpy_kernel(self):
        rtc = mx.rtc.Rtc("saxpy", ["x", "y"], ["out"], """
def saxpy(x, y):
    return 2.5 * x + y
""")
        x = nd.array(np.arange(6, dtype="float32"))
        y = nd.ones((6,))
        out = nd.zeros((6,))
        rtc.push([x, y], [out])
        np.testing.assert_allclose(out.asnumpy(),
                                   2.5 * np.arange(6) + 1, rtol=1e-6)

    def test_multi_output(self):
        rtc = mx.rtc.Rtc("squares", ["x"], ["a", "b"], """
def squares(x):
    return x * x, x + x
""")
        x = nd.array(np.array([1.0, 2.0], "float32"))
        a, b = nd.zeros((2,)), nd.zeros((2,))
        rtc.push([x], [a, b])
        np.testing.assert_allclose(a.asnumpy(), [1, 4])
        np.testing.assert_allclose(b.asnumpy(), [2, 4])

    def test_bad_source_rejected(self):
        with pytest.raises(ValueError):
            mx.rtc.Rtc("f", ["x"], ["y"], "g = 3")
