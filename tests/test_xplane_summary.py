"""Category mapping of the xplane device-time summarizer — the exact
rules the MFU evidence pack depends on (docs/mfu_analysis.md)."""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tools.xplane_summary import _category, summarize  # noqa: E402


@pytest.mark.parametrize("op,cat", [
    ("convolution.5", "convolution"),
    ("conv_general_fusion", "convolution"),
    ("convert.12", "copies / layout"),          # NOT convolution
    ("all-reduce.82", "collectives"),           # NOT bn-stats
    ("reduce-scatter", "collectives"),
    ("all-to-all.1", "collectives"),
    ("reduce-window.3", "pooling"),             # NOT bn-stats
    ("reduce.11", "bn-stats / reductions"),
    ("variance", "bn-stats / reductions"),
    ("dot.4", "matmul"),
    ("custom-call.2", "custom / pallas"),
    ("transpose.9", "copies / layout"),
    ("while.1", "other"),
])
def test_category_rules(op, cat):
    assert _category(op) == cat


def test_summarize_guards_proto_backend(tmp_path, monkeypatch):
    """With a non-python protobuf backend active, summarize refuses
    instead of silently mis-parsing (the guard checks the backend
    protobuf actually picked, not the env var)."""
    from google.protobuf.internal import api_implementation
    if api_implementation.Type() == "python":
        pytest.skip("pure-python protobuf backend active; guard "
                    "correctly lets this through")
    # env var set but too late — backend already locked: must refuse
    monkeypatch.setenv("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION",
                       "python")
    with pytest.raises(RuntimeError, match="backend"):
        summarize(str(tmp_path))
