"""Executor round-2 features: fused fwd+bwd, per-op Monitor capture, and
ctx_group/__shard__ lowering to sharding constraints (VERDICT r1 weaks
#5, #6, #8)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym as S
from mxnet_tpu import nd


def _mlp():
    x = S.Variable("data")
    h = S.FullyConnected(x, name="fc1", num_hidden=16)
    a = S.Activation(h, name="act1", act_type="relu")
    o = S.FullyConnected(a, name="fc2", num_hidden=4)
    return S.SoftmaxOutput(o, name="softmax")


def test_train_forward_caches_grads():
    """forward(is_train=True) runs the fused fwd+vjp program, so the
    default backward() needs no re-evaluation (no 2x forward)."""
    sym = _mlp()
    exe = sym.simple_bind(ctx=mx.cpu(), data=(8, 10),
                          softmax_label=(8,))
    exe.forward(is_train=True,
                data=np.random.randn(8, 10).astype(np.float32),
                softmax_label=np.zeros(8, np.float32))
    assert exe._cached_grads is not None
    cached = {n: np.asarray(v) for n, v in exe._cached_grads.items()}
    exe.backward()
    # backward must have written exactly the fused-cache values
    for n, v in cached.items():
        np.testing.assert_array_equal(v, exe.grad_dict[n].asnumpy())
    # cross-check against the explicit head-grad path (re-derivation)
    ones = [np.ones(o.shape, np.float32) for o in exe.outputs]
    exe2 = sym.simple_bind(ctx=mx.cpu(), data=(8, 10),
                           softmax_label=(8,))
    exe2.copy_params_from(exe.arg_dict)
    exe2.forward(is_train=True)
    exe2.backward(out_grads=ones)
    for n in exe.grad_dict:
        if exe.grad_dict[n] is None:
            continue
        np.testing.assert_allclose(exe.grad_dict[n].asnumpy(),
                                   exe2.grad_dict[n].asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_inference_forward_does_not_pay_grads():
    sym = _mlp()
    exe = sym.simple_bind(ctx=mx.cpu(), data=(4, 10),
                          softmax_label=(4,))
    exe.forward(is_train=False,
                data=np.zeros((4, 10), np.float32))
    assert exe._cached_grads is None


def test_monitor_sees_intermediate_tensors():
    """The Monitor must observe interior op outputs (fc1, act1), not just
    the graph heads — reference ExecuteMonCallback semantics."""
    from mxnet_tpu.monitor import Monitor

    sym = _mlp()
    exe = sym.simple_bind(ctx=mx.cpu(), data=(4, 10),
                          softmax_label=(4,))
    mon = Monitor(interval=1)
    mon.install(exe)
    mon.tic()
    exe.forward(is_train=True,
                data=np.random.randn(4, 10).astype(np.float32),
                softmax_label=np.zeros(4, np.float32))
    rows = mon.toc()
    names = {name for _, name, _ in rows}
    assert any("fc1" in n for n in names), names
    assert any("act1" in n for n in names), names
    # arg stats appended by toc
    assert any(n.endswith("_weight") for n in names), names


def test_monitor_inactive_steps_use_jit_path():
    from mxnet_tpu.monitor import Monitor

    sym = _mlp()
    exe = sym.simple_bind(ctx=mx.cpu(), data=(4, 10),
                          softmax_label=(4,))
    mon = Monitor(interval=5)
    mon.install(exe)
    mon.tic()      # step 0: active
    exe.forward(is_train=False, data=np.zeros((4, 10), np.float32))
    mon.toc()
    mon.tic()      # step 1: dormant -> fast path
    assert not mon.activated
    exe.forward(is_train=False, data=np.zeros((4, 10), np.float32))
    assert mon.toc() == []


def test_shard_annotation_lowers_to_collectives():
    """A __shard__ annotation over a 'model' mesh axis must show up as a
    sharding constraint: the compiled HLO of the train step contains
    all-reduce collectives beyond the data-parallel grad reduction."""
    import jax
    from mxnet_tpu.parallel import make_mesh, make_train_step
    from mxnet_tpu.initializer import Xavier

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")

    x = S.Variable("data")
    h = S.FullyConnected(x, name="fc1", num_hidden=8)
    h._set_attr(__shard__="None,model")   # activations sharded over model
    a = S.Activation(h, name="act1", act_type="relu")
    o = S.FullyConnected(a, name="fc2", num_hidden=4)
    sym = S.SoftmaxOutput(o, name="softmax")

    mesh = make_mesh({"data": 2, "model": 2},
                     devices=jax.devices()[:4])
    step = make_train_step(sym, optimizer="sgd", mesh=mesh)
    state = step.init_state(Xavier(), {"data": (8, 10),
                                       "softmax_label": (8,)})
    batch = step.place_batch({
        "data": np.zeros((8, 10), np.float32),
        "softmax_label": np.zeros((8,), np.float32)})
    import jax.numpy as jnp
    txt = step.lower(state, batch, 0.1,
                     jax.random.PRNGKey(0)).compile().as_text()
    assert "all-reduce" in txt or "all-gather" in txt or \
        "reduce-scatter" in txt, "no collectives in compiled HLO"
    # and the step still runs
    state, outs = step(state, batch, 0.1, jax.random.PRNGKey(0))
    jax.block_until_ready(outs)


def test_shard_annotation_bad_axis_raises():
    from mxnet_tpu.executor import _shard_constraint
    from mxnet_tpu.base import MXNetError
    import jax
    from mxnet_tpu.parallel import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
    import jax.numpy as jnp
    with pytest.raises(MXNetError):
        _shard_constraint(mesh, "bogus_axis", jnp.zeros((4, 4)))
    with pytest.raises(MXNetError):
        # not divisible: 3 % 2
        _shard_constraint(mesh, "data", jnp.zeros((3, 4)))


def test_backward_do_mirror_env_matches_plain(monkeypatch):
    """MXNET_BACKWARD_DO_MIRROR=1 remats the fused fwd+bwd program with
    identical gradients (reference memonger parity)."""
    import numpy as np
    import mxnet_tpu as mx

    def build():
        x = mx.sym.Variable("data")
        h = mx.sym.Activation(mx.sym.FullyConnected(
            x, num_hidden=8, name="fc1"), act_type="tanh")
        return mx.sym.MakeLoss(mx.sym.sum(
            mx.sym.FullyConnected(h, num_hidden=1, name="fc2")))

    loc = {"data": np.random.RandomState(0).randn(4, 3).astype("f"),
           "fc1_weight": np.random.RandomState(1).randn(8, 3).astype("f"),
           "fc1_bias": np.zeros(8, "f"),
           "fc2_weight": np.random.RandomState(2).randn(1, 8).astype("f"),
           "fc2_bias": np.zeros(1, "f")}

    def grads_with(env):
        if env:
            monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
        else:
            monkeypatch.delenv("MXNET_BACKWARD_DO_MIRROR",
                               raising=False)
        sym = build()
        args = {k: mx.nd.array(v) for k, v in loc.items()}
        gbuf = {k: mx.nd.zeros(v.shape) for k, v in loc.items()}
        ex = sym.bind(mx.cpu(), args, args_grad=gbuf)
        ex.forward(is_train=True)
        ex.backward()
        return {k: v.asnumpy() for k, v in ex.grad_dict.items()}

    plain = grads_with(False)
    mirrored = grads_with(True)
    for k in plain:
        np.testing.assert_allclose(plain[k], mirrored[k], rtol=1e-5,
                                   atol=1e-6, err_msg=k)


def test_shard_hint_is_lenient():
    """__shard_hint__ applies when the mesh carries the axis and is
    silently inert otherwise — unlike __shard__, which errors (so model
    builders can bake hints into reusable symbols)."""
    import jax
    from mxnet_tpu.executor import _graph_eval_fn
    from mxnet_tpu.parallel import make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")

    x = S.Variable("data")
    h = S.FullyConnected(x, name="fc1", num_hidden=8)
    h._set_attr(__shard_hint__="None,model")
    out = S.Activation(h, name="act", act_type="relu")

    args = {"data": np.zeros((4, 6), np.float32),
            "fc1_weight": np.zeros((8, 6), np.float32),
            "fc1_bias": np.zeros((8,), np.float32)}
    rng = jax.random.PRNGKey(0)

    # axis present: the constraint lands — the activation (and
    # everything downstream of it) comes out 'model'-sharded on dim 1
    mesh = make_mesh({"model": 4}, devices=jax.devices()[:4])
    fn = _graph_eval_fn(out, mesh=mesh)
    res = jax.jit(lambda a: fn(a, {}, rng, False)[0][0])(args)
    assert "model" in str(res.sharding.spec), res.sharding
    assert res.sharding.spec[1] == "model", res.sharding

    # axis absent: same symbol binds and runs, hint skipped
    mesh2 = make_mesh({"data": 4}, devices=jax.devices()[:4])
    fn2 = _graph_eval_fn(out, mesh=mesh2)
    res = fn2(args, {}, rng, False)[0][0]
    assert res.shape == (4, 8)

    # non-divisible dim: skipped, not an error
    mesh3 = make_mesh({"model": 3}, devices=jax.devices()[:3])
    fn3 = _graph_eval_fn(out, mesh=mesh3)
    res3 = fn3(args, {}, rng, False)[0][0]
    assert res3.shape == (4, 8)
