"""RecordIO tests — reference: tests/python/unittest/test_recordio.py."""
import os
import struct
import tempfile

import numpy as np
import pytest

from mxnet_tpu import recordio


def test_recordio_roundtrip():
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "x.rec")
        w = recordio.MXRecordIO(path, "w")
        payloads = [b"hello", b"x" * 1000, b"", b"abc\x00def"]
        for p in payloads:
            w.write(p)
        w.close()
        r = recordio.MXRecordIO(path, "r")
        for p in payloads:
            assert r.read() == p
        assert r.read() is None
        r.reset()
        assert r.read() == payloads[0]
        r.close()


def test_recordio_embedded_magic():
    """Payload containing the aligned magic word must round-trip (the
    split/rejoin continuation-flag path)."""
    magic = struct.pack("<I", 0xced7230a)
    payload = b"abcd" + magic + b"efgh" + magic + magic + b"zz"
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "m.rec")
        w = recordio.MXRecordIO(path, "w")
        w.write(payload)
        w.write(b"next")
        w.close()
        r = recordio.MXRecordIO(path, "r")
        assert r.read() == payload
        assert r.read() == b"next"


def test_indexed_recordio():
    with tempfile.TemporaryDirectory() as tmp:
        rec = os.path.join(tmp, "x.rec")
        idx = os.path.join(tmp, "x.idx")
        w = recordio.MXIndexedRecordIO(idx, rec, "w")
        for i in range(10):
            w.write_idx(i, b"rec%d" % i)
        w.close()
        r = recordio.MXIndexedRecordIO(idx, rec, "r")
        assert r.keys == list(range(10))
        assert r.read_idx(7) == b"rec7"
        assert r.read_idx(2) == b"rec2"


def test_irheader_pack_unpack():
    h = recordio.IRHeader(0, 3.0, 42, 0)
    s = recordio.pack(h, b"payload")
    h2, payload = recordio.unpack(s)
    assert h2.label == 3.0 and h2.id == 42
    assert payload == b"payload"
    # array label (detection)
    h = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0], np.float32), 7, 0)
    s = recordio.pack(h, b"img")
    h2, payload = recordio.unpack(s)
    np.testing.assert_allclose(h2.label, [1, 2, 3])
    assert payload == b"img"


def test_pack_img_roundtrip():
    img = (np.random.RandomState(0).rand(32, 32, 3) * 255).astype(np.uint8)
    # png is lossless -> exact roundtrip
    s = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), img,
                          img_fmt=".png")
    h, img2 = recordio.unpack_img(s, iscolor=1)
    assert h.label == 1.0
    np.testing.assert_array_equal(img, img2)
    # jpeg path decodes to the right shape
    s = recordio.pack_img(recordio.IRHeader(0, 2.0, 0, 0), img)
    h, img3 = recordio.unpack_img(s, iscolor=1)
    assert h.label == 2.0 and img3.shape == (32, 32, 3)
