"""Training guardrails (ISSUE 7): device-side non-finite detection and
masked updates, dynamic loss scaling, checkpoint auto-rollback, and
preemption-safe (SIGTERM) boundary checkpointing — all deterministically
driven by the ``nan@N`` / ``sigterm@N`` MXNET_FAULT_SPEC rules.

The load-bearing assertions (acceptance):
- an injected-NaN run keeps finite weights, completes, and performs the
  SAME number of blocking host syncs as a clean run (the finite flag is
  read at the dispatch-window wait the loop already pays);
- Perplexity/CrossEntropy exclude masked steps from their ``num``;
- a SIGTERM mid-epoch run exits with guardrail.EXIT_PREEMPTED and a
  boundary checkpoint, and a relaunch with resume= continues from the
  exact step (no update lost, none double-run).
"""
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import config, guardrail, io, metric, profiler
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.parallel import make_train_step
from mxnet_tpu.parallel.resilience import (FaultInjector,
                                           install_fault_injector)

pytestmark = pytest.mark.guardrail


def _mlp():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=32)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=2)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _toy(n=96, d=16, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d) > 0).astype(np.float32)
    return X, y


def _step(**kwargs):
    kwargs.setdefault("optimizer", "sgd")
    kwargs.setdefault("optimizer_params", {"rescale_grad": 1.0 / 32})
    return make_train_step(_mlp(), **kwargs)


@pytest.fixture
def no_injector():
    yield
    install_fault_injector(None)


@pytest.fixture
def knobs():
    """set_override-based knob scoping (restores on exit)."""
    touched = []

    def set_knob(name, value):
        touched.append(name)
        config.set_override(name, value)

    yield set_knob
    for name in touched:
        config.clear_override(name)


# ---------------------------------------------------------------------------
# fault-spec grammar
# ---------------------------------------------------------------------------

def test_fault_spec_step_rules_parse_and_count():
    inj = FaultInjector("nan@3x2;sigterm@5")
    hits = [inj.on_train_step("nan") for _ in range(6)]
    assert hits == [False, False, True, True, False, False]
    sig = [inj.on_train_step("sigterm") for _ in range(5)]
    assert sig == [False, False, False, False, True]
    assert ("nan", 3, "nan") in inj.fired
    assert ("sigterm", 5, "sigterm") in inj.fired
    # socket rules coexist with step rules in one spec
    FaultInjector("send:drop@2;nan@1")
    with pytest.raises(ValueError):
        FaultInjector("sigsegv@2")         # unknown step point


# ---------------------------------------------------------------------------
# non-finite detection + masking (TrainStep path)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("metric_name,kwargs", [
    ("ce", {}),
    ("perplexity", {"ignore_label": -1}),
])
def test_nan_step_masked_weights_finite_metric_excludes(
        no_injector, metric_name, kwargs):
    """nan@2 poisons step 2 of 3: the update is masked on device (final
    weights finite), training completes, and the fused metric's num
    counts only the 2 unmasked batches."""
    X, y = _toy()
    inj = install_fault_injector(FaultInjector("nan@2"))
    m = metric.create(metric_name, **kwargs)
    step = _step()
    train = io.NDArrayIter(X, y, batch_size=32)   # 3 steps/epoch
    state, _ = step.fit(train, num_epoch=1, initializer=Xavier(),
                        lr=0.5, eval_metric=m)
    assert inj.fired == [("nan", 2, "nan")]
    assert step.guard_report["masked_steps"] == 1
    for name, p in state[0].items():
        assert np.isfinite(jax.device_get(p)).all(), name
    stats = jax.device_get(m._dev_stats)
    assert stats["num"] == 64.0, stats    # 2 x 32, masked step excluded
    assert np.isfinite(stats["sum"]), stats


def test_nan_detection_adds_zero_host_syncs(no_injector):
    """Acceptance gate: host_sync_count over an instrumented epoch is
    IDENTICAL between a clean run and an injected-NaN run — the finite
    flag is read at the dispatch-window wait the loop already pays."""
    X, y = _toy()

    def one_epoch(spec):
        step = _step()
        train = io.NDArrayIter(X, y, batch_size=32)
        # warm epoch: compiles (not the measured regime)
        state, _ = step.fit(train, num_epoch=1, initializer=Xavier(),
                            lr=0.1)
        if spec:
            install_fault_injector(FaultInjector(spec))
        base = profiler.host_sync_count()
        step.fit(train, num_epoch=1, state=state, lr=0.1)
        syncs = profiler.host_sync_count() - base
        install_fault_injector(None)
        return syncs

    clean, injected = one_epoch(None), one_epoch("nan@2")
    assert clean == injected, (clean, injected)
    assert clean <= 3 + 1      # the PR 2 budget still holds


def test_guardrail_off_restores_unguarded_loop(no_injector, knobs):
    knobs("MXNET_GUARDRAIL", False)
    X, y = _toy()
    step = _step()
    train = io.NDArrayIter(X, y, batch_size=32)
    _, acc = step.fit(train, num_epoch=6, initializer=Xavier(), lr=0.5)
    assert acc > 0.9
    assert step.guard_report == {}


# ---------------------------------------------------------------------------
# dynamic loss scaling
# ---------------------------------------------------------------------------

def test_dynamic_loss_scaler_rule():
    s = guardrail.DynamicLossScaler(init_scale=1024.0, window=2)
    scale = jax.numpy.float32(1024.0)
    good = jax.numpy.float32(0.0)
    ok = jax.numpy.bool_(True)
    bad = jax.numpy.bool_(False)
    scale, good = s.next_state(scale, good, bad)       # overflow halves
    assert float(scale) == 512.0 and float(good) == 0.0
    scale, good = s.next_state(scale, good, ok)
    assert float(scale) == 512.0 and float(good) == 1.0
    scale, good = s.next_state(scale, good, ok)        # window hit
    assert float(scale) == 1024.0 and float(good) == 0.0
    static = guardrail.DynamicLossScaler(init_scale=8.0, dynamic=False)
    s2, g2 = static.next_state(scale, good, bad)
    assert s2 is scale and g2 is good

    assert guardrail.DynamicLossScaler.from_env() is None
    config.set_override("MXNET_LOSS_SCALE", "dynamic")
    try:
        assert guardrail.DynamicLossScaler.from_env().dynamic
        # static scales snap to the nearest power of two (the
        # exact-unscale guarantee only holds for exponent shifts)
        config.set_override("MXNET_LOSS_SCALE", "1000")
        snapped = guardrail.DynamicLossScaler.from_env()
        assert not snapped.dynamic and snapped.init_scale == 1024.0
    finally:
        config.clear_override("MXNET_LOSS_SCALE")


def test_static_loss_scale_training_parity(knobs):
    """A power-of-two static scale flows through the heads (cotangent)
    and unscales exactly — the trajectory matches the unscaled run."""
    X, y = _toy()

    def run(scale):
        mx.random.seed(11)
        np.random.seed(11)
        if scale:
            config.set_override("MXNET_LOSS_SCALE", scale)
        else:
            config.clear_override("MXNET_LOSS_SCALE")
        try:
            step = _step()
            train = io.NDArrayIter(X, y, batch_size=32)
            state, acc = step.fit(train, num_epoch=3,
                                  initializer=Xavier(), lr=0.5, seed=3)
        finally:
            config.clear_override("MXNET_LOSS_SCALE")
        return state, acc

    s0, a0 = run(None)
    s1, a1 = run("1024")
    assert abs(a0 - a1) <= 1e-6
    np.testing.assert_allclose(jax.device_get(s0[0]["fc1_weight"]),
                               jax.device_get(s1[0]["fc1_weight"]),
                               rtol=1e-5, atol=1e-6)


def test_scaler_state_halves_on_injected_overflow_and_checkpoints(
        no_injector, knobs, tmp_path):
    """Overflow (injected NaN) halves the scale; the scaler state rides
    the checkpoint and restores through load_state."""
    knobs("MXNET_LOSS_SCALE", "dynamic")
    X, y = _toy()
    inj = install_fault_injector(FaultInjector("nan@2"))
    step = _step()
    train = io.NDArrayIter(X, y, batch_size=32)
    pfx = str(tmp_path / "ck")
    state, _ = step.fit(train, num_epoch=1, initializer=Xavier(),
                        lr=0.5, checkpoint_prefix=pfx)
    assert inj.fired
    aux = state[2]
    scale = float(jax.device_get(aux[guardrail.SCALE_KEY]))
    assert scale == 2.0 ** 15          # one halving of the 2**16 init
    loaded = step.load_state(pfx + "_0000")
    assert float(jax.device_get(
        loaded[2][guardrail.SCALE_KEY])) == scale
    # a checkpoint from an unscaled run still loads (keys are optional)
    config.clear_override("MXNET_LOSS_SCALE")
    step.load_state(pfx + "_0000")


# ---------------------------------------------------------------------------
# rollback escalation
# ---------------------------------------------------------------------------

def test_rollback_restores_newest_checkpoint_then_recovers(
        no_injector, knobs, tmp_path):
    knobs("MXNET_MAX_BAD_STEPS", 2)
    X, y = _toy()
    pfx = str(tmp_path / "ck")
    step = _step()
    train = io.NDArrayIter(X, y, batch_size=32)
    state, _ = step.fit(train, num_epoch=2, initializer=Xavier(),
                        lr=0.5, checkpoint_prefix=pfx)
    # resume + 3 consecutive bad steps -> one rollback, then recovery
    install_fault_injector(FaultInjector("nan@1x3"))
    state, acc = step.fit(train, num_epoch=4, lr=0.5,
                          checkpoint_prefix=pfx)
    install_fault_injector(None)
    assert step.guard_report["rollbacks"] == 1
    assert acc is not None and np.isfinite(acc)
    for name, p in state[0].items():
        assert np.isfinite(jax.device_get(p)).all(), name


def test_rollback_exhaustion_raises_numerical_divergence(
        no_injector, knobs, tmp_path):
    knobs("MXNET_MAX_BAD_STEPS", 2)
    knobs("MXNET_MAX_ROLLBACKS", 1)
    X, y = _toy()
    pfx = str(tmp_path / "ck")
    step = _step()
    train = io.NDArrayIter(X, y, batch_size=32)
    step.fit(train, num_epoch=1, initializer=Xavier(), lr=0.5,
             checkpoint_prefix=pfx)
    install_fault_injector(FaultInjector("nan@1x*"))
    with pytest.raises(guardrail.NumericalDivergence):
        step.fit(train, num_epoch=3, lr=0.5, checkpoint_prefix=pfx)


def test_divergence_without_checkpoint_is_typed(no_injector, knobs):
    """No checkpoint_prefix -> nothing to roll back to -> the typed
    error fires on the first threshold hit."""
    knobs("MXNET_MAX_BAD_STEPS", 2)
    X, y = _toy()
    step = _step()
    train = io.NDArrayIter(X, y, batch_size=32)
    install_fault_injector(FaultInjector("nan@1x*"))
    with pytest.raises(guardrail.NumericalDivergence):
        step.fit(train, num_epoch=2, initializer=Xavier(), lr=0.5)


def test_rollback_lr_factor_applies(no_injector, knobs, tmp_path):
    knobs("MXNET_MAX_BAD_STEPS", 2)
    knobs("MXNET_ROLLBACK_LR_FACTOR", 0.5)
    X, y = _toy()
    pfx = str(tmp_path / "ck")
    step = _step()
    train = io.NDArrayIter(X, y, batch_size=32)
    step.fit(train, num_epoch=1, initializer=Xavier(), lr=0.5,
             checkpoint_prefix=pfx)
    install_fault_injector(FaultInjector("nan@1x3"))
    step.fit(train, num_epoch=3, lr=0.5, checkpoint_prefix=pfx)
    install_fault_injector(None)
    assert step.guard_report["lr_mult"] == 0.5


# ---------------------------------------------------------------------------
# preemption (SIGTERM) safety
# ---------------------------------------------------------------------------

def test_trainstep_sigterm_boundary_checkpoint_and_resume(
        no_injector, tmp_path):
    """sigterm@2 (a REAL signal through the chaining handler): fit
    exits EXIT_PREEMPTED with a boundary checkpoint recording the exact
    step; a rerun resumes there and runs exactly the remaining steps."""
    X, y = _toy()
    pfx = str(tmp_path / "ck")
    step = _step()
    train = io.NDArrayIter(X, y, batch_size=32)   # 3 steps/epoch
    install_fault_injector(FaultInjector("sigterm@2"))
    with pytest.raises(SystemExit) as exc:
        step.fit(train, num_epoch=3, initializer=Xavier(), lr=0.5,
                 checkpoint_prefix=pfx)
    install_fault_injector(None)
    assert exc.value.code == guardrail.EXIT_PREEMPTED
    with open(pfx + "_0000.meta.json") as f:
        meta = json.load(f)
    assert meta == {"n_update": 1, "epoch": 0, "nbatch": 1}
    # relaunch with the same command: continues at epoch 0 batch 1
    step2 = _step()
    train2 = io.NDArrayIter(X, y, batch_size=32)
    state, acc = step2.fit(train2, num_epoch=3, initializer=Xavier(),
                           lr=0.5, checkpoint_prefix=pfx)
    with open(pfx + "_0002.meta.json") as f:
        assert json.load(f)["n_update"] == 9   # no step lost or doubled
    assert acc is not None


def test_module_sigterm_boundary_checkpoint_and_resume(no_injector,
                                                       tmp_path):
    X, y = _toy()
    pfx = str(tmp_path / "mod")
    train = io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    install_fault_injector(FaultInjector("sigterm@2"))
    with pytest.raises(SystemExit) as exc:
        mod.fit(train, num_epoch=3, optimizer="sgd",
                optimizer_params={"learning_rate": 0.5},
                checkpoint_prefix=pfx)
    install_fault_injector(None)
    assert exc.value.code == guardrail.EXIT_PREEMPTED
    with open(pfx + "-0000.resume.json") as f:
        assert json.load(f) == {"epoch": 0, "nbatch": 1}
    mod2 = mx.mod.Module(_mlp(), context=mx.cpu())
    train2 = io.NDArrayIter(X, y, batch_size=32)
    mod2.fit(train2, num_epoch=3, optimizer="sgd",
             optimizer_params={"learning_rate": 0.5},
             checkpoint_prefix=pfx)
    assert os.path.exists(pfx + "-0003.params")
    w = mod2.get_params()[0]["fc1_weight"].asnumpy()
    assert np.isfinite(w).all()


@pytest.mark.slow
def test_sigterm_subprocess_exits_preempted_and_resumes(tmp_path):
    """Whole-process acceptance: the interpreter exits with code 83 and
    the relaunched command completes the run."""
    script = tmp_path / "run.py"
    script.write_text(
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import io\n"
        "from mxnet_tpu.initializer import Xavier\n"
        "from mxnet_tpu.parallel import make_train_step\n"
        "net = mx.sym.Variable('data')\n"
        "net = mx.sym.FullyConnected(net, name='fc1', num_hidden=32)\n"
        "net = mx.sym.SoftmaxOutput(net, name='softmax')\n"
        "rng = np.random.default_rng(0)\n"
        "X = rng.standard_normal((96, 16)).astype(np.float32)\n"
        "y = (X @ rng.standard_normal(16) > 0).astype(np.float32)\n"
        "step = make_train_step(net, optimizer='sgd',\n"
        "                       optimizer_params={'rescale_grad': 1/32})\n"
        "train = io.NDArrayIter(X, y, batch_size=32)\n"
        "state, acc = step.fit(train, num_epoch=2,\n"
        "                      initializer=Xavier(), lr=0.5,\n"
        "                      checkpoint_prefix=sys.argv[1])\n"
        "print('COMPLETED')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_FAULT_SPEC="sigterm@2")
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    pfx = str(tmp_path / "ck")
    first = subprocess.run([sys.executable, str(script), pfx], env=env,
                           capture_output=True, text=True, timeout=240)
    assert first.returncode == guardrail.EXIT_PREEMPTED, first.stderr
    env.pop("MXNET_FAULT_SPEC")
    second = subprocess.run([sys.executable, str(script), pfx], env=env,
                            capture_output=True, text=True, timeout=240)
    assert second.returncode == 0, second.stderr
    assert "COMPLETED" in second.stdout


def test_graceful_shutdown_chains_previous_handler():
    seen = []
    prev = signal.getsignal(signal.SIGTERM)
    try:
        signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
        with guardrail.GracefulShutdown() as shutdown:
            assert not shutdown.requested
            signal.raise_signal(signal.SIGTERM)
            assert shutdown.requested
            assert seen == [signal.SIGTERM]    # chained, not clobbered
        # uninstall restored the user handler
        signal.raise_signal(signal.SIGTERM)
        assert seen == [signal.SIGTERM, signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)


# ---------------------------------------------------------------------------
# Module-path masking
# ---------------------------------------------------------------------------

def test_module_fit_nan_masked_weights_finite(no_injector):
    X, y = _toy()
    inj = install_fault_injector(FaultInjector("nan@2"))
    train = io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    assert inj.fired == [("nan", 2, "nan")]
    for name, arr in mod.get_params()[0].items():
        assert np.isfinite(arr.asnumpy()).all(), name


def test_metric_device_ok_mask_excludes_batch():
    m = metric.create("acc")
    pred = mx.nd.array(np.eye(4, dtype=np.float32))
    label = mx.nd.array(np.arange(4, dtype=np.float32))
    m.update_device([label], [pred], ok=jax.numpy.bool_(True))
    m.update_device([label], [pred], ok=jax.numpy.bool_(False))
    stats = jax.device_get(m._dev_stats)
    assert stats["num"] == 4.0 and stats["sum"] == 4.0


# ---------------------------------------------------------------------------
# checkpoint durability (fsync satellite)
# ---------------------------------------------------------------------------

def test_trainstep_save_state_fsyncs_file_and_dir(tmp_path,
                                                  monkeypatch):
    calls = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync",
                        lambda fd: (calls.append(fd), real_fsync(fd)))
    X, y = _toy()
    step = _step()
    state = step.init_state(Xavier(), {"data": X.shape,
                                       "softmax_label": y.shape})
    step.save_state(str(tmp_path / "ck"), state)
    assert len(calls) >= 2     # tmp file + directory
    assert (tmp_path / "ck.npz").exists()


def test_module_save_checkpoint_fsyncs(tmp_path, monkeypatch):
    calls = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync",
                        lambda fd: (calls.append(fd), real_fsync(fd)))
    from mxnet_tpu.model import save_checkpoint
    sym = _mlp()
    args = {"fc1_weight": mx.nd.zeros((32, 16)),
            "fc1_bias": mx.nd.zeros((32,)),
            "fc2_weight": mx.nd.zeros((2, 32)),
            "fc2_bias": mx.nd.zeros((2,))}
    save_checkpoint(str(tmp_path / "m"), 1, sym, args, {})
    assert len(calls) >= 2     # tmp file + directory
    assert (tmp_path / "m-0001.params").exists()


# ---------------------------------------------------------------------------
# monitor batched reads (satellite)
# ---------------------------------------------------------------------------

def test_monitor_toc_is_one_batched_host_sync():
    from mxnet_tpu.monitor import Monitor
    sym = _mlp()
    exe = sym.simple_bind(ctx=mx.cpu(), data=(4, 16),
                          softmax_label=(4,))
    mon = Monitor(interval=1)
    mon.install(exe)
    mon.tic()
    exe.forward(is_train=True,
                data=np.random.RandomState(0).randn(4, 16)
                .astype(np.float32),
                softmax_label=np.zeros(4, np.float32))
    base = profiler.host_sync_count()
    rows = mon.toc()
    assert profiler.host_sync_count() - base == 1   # ONE device_get
    assert rows and all(r[2].strip() for r in rows)
    floats = [float(r[2].split("\t")[0]) for r in rows]
    assert all(np.isfinite(f) for f in floats)
