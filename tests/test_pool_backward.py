"""Max-pool dense backward (ops/nn.py:_max_pool2d_dense_bwd): the
custom VJP that replaces XLA's SelectAndScatter with kh*kw vectorized
passes must produce gradients IDENTICAL to the reduce_window autodiff
on tie-free data, across strides/pads/ceil-mode, and its
split-among-maxima tie semantics (a deliberate deviation from
mshadow's full-dy-per-tie routing) must hold."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.ops.nn import _pooling


def _grads(x, dy, env, monkeypatch, **attrs):
    monkeypatch.setenv("MXNET_POOL_DENSE_BWD", env)

    def loss(x_):
        return jnp.sum(_pooling(x_, pool_type="max", **attrs)
                       * jnp.asarray(dy))

    return np.asarray(jax.grad(loss)(jnp.asarray(x)))


@pytest.mark.parametrize("kernel,stride,pad,convention", [
    ((2, 2), (2, 2), (0, 0), "valid"),
    ((3, 3), (2, 2), (1, 1), "valid"),      # the ResNet stem shape
    ((3, 3), (1, 1), (1, 1), "valid"),
    ((3, 2), (2, 3), (1, 0), "valid"),      # asymmetric
    ((3, 3), (2, 2), (0, 0), "full"),       # ceil mode: extra hi pad
])
def test_dense_bwd_matches_select_and_scatter(kernel, stride, pad,
                                              convention,
                                              monkeypatch):
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 9, 9).astype(np.float32)   # ties measure-zero
    attrs = dict(kernel=kernel, stride=stride, pad=pad,
                 pooling_convention=convention)
    y_dense = _pooling(jnp.asarray(x), pool_type="max", **attrs)
    dy = rng.randn(*y_dense.shape).astype(np.float32)
    g_dense = _grads(x, dy, "1", monkeypatch, **attrs)
    g_xla = _grads(x, dy, "0", monkeypatch, **attrs)
    np.testing.assert_allclose(g_dense, g_xla, rtol=1e-6, atol=1e-6)
    # forwards agree too (same reduce_window under both gates)
    monkeypatch.setenv("MXNET_POOL_DENSE_BWD", "0")
    y_xla = _pooling(jnp.asarray(x), pool_type="max", **attrs)
    np.testing.assert_array_equal(np.asarray(y_dense),
                                  np.asarray(y_xla))


def test_tie_semantics_split_among_maxima(monkeypatch):
    """A tied window SPLITS dy equally among its maxima (dy/count
    each) — magnitude-preserving on tie-heavy quantized inputs, where
    mshadow's full-dy-to-every-tie routing inflates gradients (caught
    by the real-digits convergence gate) and SelectAndScatter picks
    one winner. Total gradient mass is conserved either way."""
    monkeypatch.setenv("MXNET_POOL_DENSE_BWD", "1")
    x = jnp.ones((1, 1, 2, 2), jnp.float32)

    def loss(x_):
        return jnp.sum(_pooling(x_, pool_type="max", kernel=(2, 2),
                                stride=(2, 2), pad=(0, 0)))

    dx = np.asarray(jax.grad(loss)(x))
    np.testing.assert_allclose(dx, np.full((1, 1, 2, 2), 0.25))
    # partial tie: two maxima share, non-maxima get nothing
    x2 = jnp.asarray([[[[2.0, 2.0], [1.0, 0.0]]]], jnp.float32)
    dx2 = np.asarray(jax.grad(loss)(x2))
    np.testing.assert_allclose(dx2, [[[[0.5, 0.5], [0.0, 0.0]]]])


def test_int_and_3d_fall_back(monkeypatch):
    """The dense path covers float 2-D pooling; int dtypes and 3-D
    keep the reduce_window route (forward-only parity check)."""
    monkeypatch.setenv("MXNET_POOL_DENSE_BWD", "1")
    xi = jnp.asarray(np.arange(16).reshape(1, 1, 4, 4), jnp.int32)
    yi = _pooling(xi, pool_type="max", kernel=(2, 2), stride=(2, 2),
                  pad=(0, 0))
    np.testing.assert_array_equal(
        np.asarray(yi), [[[[5, 7], [13, 15]]]])
    x3 = jnp.asarray(np.random.RandomState(1).randn(1, 1, 4, 4, 4),
                     jnp.float32)
    y3 = _pooling(x3, pool_type="max", kernel=(2, 2, 2),
                  stride=(2, 2, 2), pad=(0, 0, 0))
    assert y3.shape == (1, 1, 2, 2, 2)
