"""NDArray basics — mirrors reference tests/python/unittest/test_ndarray.py."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation():
    a = nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == np.float32
    assert a.asnumpy().sum() == 0

    b = nd.ones((2, 2), dtype="int32")
    assert b.dtype == np.int32
    assert b.asnumpy().sum() == 4

    c = nd.full((2,), 7.5)
    np.testing.assert_allclose(c.asnumpy(), [7.5, 7.5])

    d = nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)
    assert d.dtype == np.float32

    e = nd.arange(0, 10, 2)
    np.testing.assert_allclose(e.asnumpy(), [0, 2, 4, 6, 8])


def test_arithmetic():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[10.0, 20.0], [30.0, 40.0]])
    np.testing.assert_allclose((a + b).asnumpy(), [[11, 22], [33, 44]])
    np.testing.assert_allclose((b - a).asnumpy(), [[9, 18], [27, 36]])
    np.testing.assert_allclose((a * b).asnumpy(), [[10, 40], [90, 160]])
    np.testing.assert_allclose((b / a).asnumpy(), [[10, 10], [10, 10]])
    np.testing.assert_allclose((a + 1).asnumpy(), [[2, 3], [4, 5]])
    np.testing.assert_allclose((1 + a).asnumpy(), [[2, 3], [4, 5]])
    np.testing.assert_allclose((a - 1).asnumpy(), [[0, 1], [2, 3]])
    np.testing.assert_allclose((10 - a).asnumpy(), [[9, 8], [7, 6]])
    np.testing.assert_allclose((a * 2).asnumpy(), [[2, 4], [6, 8]])
    np.testing.assert_allclose((a / 2).asnumpy(), [[0.5, 1], [1.5, 2]])
    np.testing.assert_allclose((2 / a).asnumpy(), [[2, 1], [2/3, 0.5]],
                               rtol=1e-6)
    np.testing.assert_allclose((a ** 2).asnumpy(), [[1, 4], [9, 16]])
    np.testing.assert_allclose((-a).asnumpy(), [[-1, -2], [-3, -4]])
    np.testing.assert_allclose(abs(-a).asnumpy(), [[1, 2], [3, 4]])


def test_inplace_arithmetic():
    a = nd.ones((2, 2))
    a += 1
    np.testing.assert_allclose(a.asnumpy(), 2 * np.ones((2, 2)))
    a *= 3
    np.testing.assert_allclose(a.asnumpy(), 6 * np.ones((2, 2)))
    a -= 2
    np.testing.assert_allclose(a.asnumpy(), 4 * np.ones((2, 2)))
    a /= 4
    np.testing.assert_allclose(a.asnumpy(), np.ones((2, 2)))


def test_comparisons():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([3.0, 2.0, 1.0])
    np.testing.assert_allclose((a == b).asnumpy(), [0, 1, 0])
    np.testing.assert_allclose((a != b).asnumpy(), [1, 0, 1])
    np.testing.assert_allclose((a > b).asnumpy(), [0, 0, 1])
    np.testing.assert_allclose((a >= b).asnumpy(), [0, 1, 1])
    np.testing.assert_allclose((a < b).asnumpy(), [1, 0, 0])
    np.testing.assert_allclose((a <= b).asnumpy(), [1, 1, 0])


def test_indexing():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    np.testing.assert_allclose(a[0].asnumpy(), np.arange(12).reshape(3, 4))
    np.testing.assert_allclose(a[1, 2].asnumpy(), [20, 21, 22, 23])
    np.testing.assert_allclose(a[:, 1].asnumpy(),
                               np.arange(24).reshape(2, 3, 4)[:, 1])
    np.testing.assert_allclose(a[0, 1:3].asnumpy(),
                               np.arange(24).reshape(2, 3, 4)[0, 1:3])


def test_setitem():
    a = nd.zeros((3, 3))
    a[1] = 5.0
    expected = np.zeros((3, 3))
    expected[1] = 5
    np.testing.assert_allclose(a.asnumpy(), expected)
    a[:] = 1.0
    np.testing.assert_allclose(a.asnumpy(), np.ones((3, 3)))
    a[0, 1] = 9
    assert a.asnumpy()[0, 1] == 9


def test_reshape_transpose():
    a = nd.array(np.arange(12).reshape(3, 4))
    assert a.reshape(4, 3).shape == (4, 3)
    assert a.reshape((2, 6)).shape == (2, 6)
    assert a.reshape(-1).shape == (12,)
    assert a.reshape(0, -1).shape == (3, 4)
    assert a.T.shape == (4, 3)
    np.testing.assert_allclose(a.T.asnumpy(),
                               np.arange(12).reshape(3, 4).T)


def test_reduce_methods():
    a = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert a.sum().asscalar() == 66
    np.testing.assert_allclose(a.sum(axis=0).asnumpy(),
                               np.arange(12).reshape(3, 4).sum(0))
    np.testing.assert_allclose(a.mean(axis=1).asnumpy(),
                               np.arange(12).reshape(3, 4).mean(1))
    assert a.max().asscalar() == 11
    assert a.min().asscalar() == 0
    np.testing.assert_allclose(a.argmax(axis=1).asnumpy(), [3, 3, 3])


def test_dot():
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    b = nd.array(np.random.rand(4, 5).astype(np.float32))
    np.testing.assert_allclose(nd.dot(a, b).asnumpy(),
                               a.asnumpy() @ b.asnumpy(), rtol=1e-5)


def test_conversion():
    a = nd.array([3.5])
    assert a.asscalar() == 3.5
    assert float(a) == 3.5
    assert int(nd.array([7])) == 7
    assert len(nd.zeros((5, 2))) == 5
    assert nd.zeros((2, 3)).size == 6
    assert nd.zeros((2, 3)).ndim == 2


def test_astype_copy():
    a = nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = a.copy()
    c[:] = 0.0
    np.testing.assert_allclose(a.asnumpy(), [1.5, 2.5])


def test_context():
    a = nd.zeros((2, 2), ctx=mx.cpu(0))
    assert a.context.device_type in ("cpu", "gpu")
    b = a.as_in_context(mx.cpu(0))
    assert b.shape == (2, 2)


def test_broadcast_ops():
    a = nd.array(np.ones((2, 1, 3)))
    b = nd.array(np.ones((1, 4, 3)))
    assert (a + b).shape == (2, 4, 3)
    c = nd.broadcast_to(nd.array([[1.0], [2.0]]), shape=(2, 3))
    np.testing.assert_allclose(c.asnumpy(), [[1, 1, 1], [2, 2, 2]])


def test_concat_split_stack():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    parts = nd.split(nd.array(np.arange(12).reshape(2, 6)), num_outputs=2,
                     axis=1)
    assert len(parts) == 2 and parts[0].shape == (2, 3)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrays")
    data = {"w": nd.array([1.0, 2.0]), "b": nd.zeros((2, 2))}
    nd.save(fname, data)
    loaded = nd.load(fname)
    np.testing.assert_allclose(loaded["w"].asnumpy(), [1, 2])
    lst = [nd.ones((2,)), nd.zeros((3,))]
    nd.save(fname, lst)
    loaded = nd.load(fname)
    assert isinstance(loaded, list) and len(loaded) == 2


def test_unary_method_fallback():
    a = nd.array([[0.5, 1.0]])
    np.testing.assert_allclose(a.exp().asnumpy(), np.exp([[0.5, 1.0]]),
                               rtol=1e-6)
    np.testing.assert_allclose(a.log().asnumpy(), np.log([[0.5, 1.0]]),
                               rtol=1e-6)
    np.testing.assert_allclose(a.sqrt().asnumpy(), np.sqrt([[0.5, 1.0]]),
                               rtol=1e-6)


def test_take_embedding():
    w = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    idx = nd.array([0, 2])
    out = nd.Embedding(idx, w, input_dim=4, output_dim=3)
    np.testing.assert_allclose(out.asnumpy(),
                               np.arange(12).reshape(4, 3)[[0, 2]])
    out2 = nd.take(w, idx)
    np.testing.assert_allclose(out2.asnumpy(),
                               np.arange(12).reshape(4, 3)[[0, 2]])


def test_onehot():
    out = nd.one_hot(nd.array([0, 2]), depth=3)
    np.testing.assert_allclose(out.asnumpy(), [[1, 0, 0], [0, 0, 1]])


def test_random_seeded():
    mx.random.seed(42)
    a = nd.random_uniform(shape=(5,))
    mx.random.seed(42)
    b = nd.random_uniform(shape=(5,))
    np.testing.assert_allclose(a.asnumpy(), b.asnumpy())
    assert ((a.asnumpy() >= 0) & (a.asnumpy() < 1)).all()

    n = nd.random_normal(loc=5.0, scale=0.001, shape=(100,))
    assert abs(n.asnumpy().mean() - 5.0) < 0.1
