"""Pipeline parallelism (GPipe schedule) and expert-parallel MoE —
both validated against serial oracles on the CPU device mesh
(new TPU-native capabilities; SURVEY §2.3 lists both as absent from the
reference)."""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from mxnet_tpu.parallel import moe_ffn, pipeline_apply


def _mesh(n, name):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip("needs %d devices" % n)
    return Mesh(np.array(devs[:n]), (name,))


def _stage(p, h):
    W, b = p
    return jnp.tanh(h @ W + b)


def test_pipeline_matches_serial():
    S, M, MB, D = 4, 6, 4, 16
    mesh = _mesh(S, "pipe")
    rng = np.random.RandomState(0)
    Ws = jnp.array(rng.randn(S, D, D).astype(np.float32) * 0.3)
    bs = jnp.array(rng.randn(S, D).astype(np.float32) * 0.1)
    x = jnp.array(rng.randn(M, MB, D).astype(np.float32))

    out = jax.jit(lambda p, v: pipeline_apply(_stage, p, v, mesh))(
        (Ws, bs), x)
    ref = x
    for s in range(S):
        ref = jnp.tanh(ref @ Ws[s] + bs[s])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_gradients_match_serial():
    S, M, MB, D = 4, 4, 2, 8
    mesh = _mesh(S, "pipe")
    rng = np.random.RandomState(1)
    params = (jnp.array(rng.randn(S, D, D).astype(np.float32) * 0.3),
              jnp.array(rng.randn(S, D).astype(np.float32) * 0.1))
    x = jnp.array(rng.randn(M, MB, D).astype(np.float32))

    g = jax.jit(jax.grad(lambda p, v: jnp.sum(
        pipeline_apply(_stage, p, v, mesh) ** 2)))(params, x)

    def serial_loss(p, v):
        h = v
        for s in range(S):
            h = jnp.tanh(h @ p[0][s] + p[1][s])
        return jnp.sum(h ** 2)
    g_ref = jax.jit(jax.grad(serial_loss))(params, x)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_moe_matches_routing_oracle():
    n, E, D, H, T = 4, 8, 16, 32, 64
    mesh = _mesh(n, "expert")
    rng = np.random.RandomState(0)
    x = jnp.array(rng.randn(T, D).astype(np.float32))
    gw = jnp.array(rng.randn(D, E).astype(np.float32) * 0.5)
    w1 = jnp.array(rng.randn(E, D, H).astype(np.float32) * 0.2)
    w2 = jnp.array(rng.randn(E, H, D).astype(np.float32) * 0.2)

    out = jax.jit(lambda *a: moe_ffn(*a, mesh=mesh))(x, gw, w1, w2)

    # oracle: replay top-1 routing with per-shard capacity dropping
    Tl = T // n
    cap = max(1, int(math.ceil(Tl * 1.25 / E)))
    ref = np.zeros((T, D), np.float32)
    dropped = 0
    for d in range(n):
        xs = np.asarray(x[d * Tl:(d + 1) * Tl])
        probs = np.asarray(jax.nn.softmax(jnp.array(xs) @ gw, axis=-1))
        exp, gate = probs.argmax(-1), probs.max(-1)
        counts = {}
        for t in range(Tl):
            e = int(exp[t])
            pos = counts.get(e, 0)
            counts[e] = pos + 1
            if pos >= cap:
                dropped += 1
                continue
            h = np.maximum(xs[t] @ np.asarray(w1[e]), 0)
            ref[d * Tl + t] = (h @ np.asarray(w2[e])) * gate[t]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                               atol=2e-4)
    assert dropped < T // 2          # routing isn't degenerate

    g = jax.jit(jax.grad(lambda *a: jnp.sum(
        moe_ffn(*a, mesh=mesh) ** 2)))(x, gw, w1, w2)
    assert np.isfinite(np.asarray(g).sum())


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8-device mesh")
def test_moe_transformer_expert_axis_trains():
    """expert_axis through the symbol API: the MoE transformer's FFN
    runs the all_to_all expert-parallel form when trained over an
    {'expert': n} mesh (ambient-mesh contract, same as seq_axis)."""
    import mxnet_tpu as mx
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel import make_mesh, make_train_step

    mesh = make_mesh({"expert": 8})
    vocab, T, B, E = 32, 16, 8, 8
    sym = transformer.get_symbol(vocab, T, num_layers=1, num_heads=2,
                                 dim=32, num_experts=E,
                                 expert_axis="expert")
    step = make_train_step(sym, optimizer="adam", mesh=mesh)
    state = step.init_state(Xavier(), {"data": (B, T),
                                       "softmax_label": (B, T)})
    rng_np = np.random.RandomState(0)
    toks = rng_np.randint(0, vocab, (B, T)).astype(np.float32)
    labels = np.roll(toks, -1, axis=1)
    labels[:, -1] = -1
    batch = step.place_batch({"data": toks, "softmax_label": labels})
    rng = jax.random.PRNGKey(0)
    hlo = step.lower(state, batch, 1e-3, rng).compile().as_text()
    assert "all-to-all" in hlo
    # expert weights (and their adam state) live sharded over the
    # expert axis — 1/n parameters per device, not replicated
    w1 = state[0]["layer0_experts_w1_weight"]
    assert "expert" in str(w1.sharding.spec), w1.sharding
    m1 = state[1]["layer0_experts_w1_weight"][0]
    assert "expert" in str(m1.sharding.spec), m1.sharding
    state, outs = step(state, batch, 1e-3, rng)
    probs = np.asarray(outs[0])
    assert np.isfinite(probs).all()
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-4)


@pytest.mark.slow
@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8-device mesh")
def test_pipeline_from_symbol_matches_sequential():
    """Symbol-defined GPipe stage (transformer block) over a pipe mesh
    == applying the S stages in a Python loop. Slow tier (~19 s on the
    1-core tier-1 host); the pipeline schedule keeps fast parity
    coverage in test_pipeline_matches_serial/_gradients_match_serial
    and the symbol entry stays validated fast below."""
    import mxnet_tpu as mx
    from mxnet_tpu.executor import _graph_eval_fn
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel import make_mesh, pipeline_from_symbol

    mesh = make_mesh({"pipe": 8})
    S, M, mb, T, D = 8, 4, 2, 8, 16
    stage_sym = transformer.get_stage_symbol(num_heads=2, dim=D)

    # per-stage random params, stacked on the leading stage dim
    arg_shapes, _, _ = stage_sym.infer_shape(data=(mb, T, D))
    names = stage_sym.list_arguments()
    rng_np = np.random.RandomState(0)
    stacked = {n: (0.1 * rng_np.randn(S, *shp)).astype(np.float32)
               for n, shp in zip(names, arg_shapes) if n != "data"}
    stream = rng_np.randn(M, mb, T, D).astype(np.float32)

    got = np.asarray(jax.jit(
        lambda p, s: pipeline_from_symbol(stage_sym, p, s, mesh))(
            stacked, stream))

    # oracle: sequential composition with the plain executor eval
    eval_fn = _graph_eval_fn(stage_sym)
    want = np.empty_like(stream)
    for m in range(M):
        h = stream[m]
        for s in range(S):
            outs, _ = eval_fn(
                {**{n: v[s] for n, v in stacked.items()}, "data": h},
                {}, jax.random.PRNGKey(0), False)
            h = np.asarray(outs[0])
        want[m] = h
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_pipeline_from_symbol_validation():
    import mxnet_tpu as mx
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel import make_mesh, pipeline_from_symbol

    mesh = make_mesh({"pipe": jax.device_count()})
    stage = transformer.get_stage_symbol(num_heads=2, dim=16)
    with pytest.raises(ValueError, match="missing"):
        pipeline_from_symbol(stage, {}, np.zeros((2, 2, 8, 16),
                                                 np.float32), mesh)
    # a BN stage carries aux states -> rejected up front
    bn = mx.sym.BatchNorm(mx.sym.Variable("data"), name="bn")
    with pytest.raises(ValueError, match="auxiliary"):
        pipeline_from_symbol(bn, {}, np.zeros((2, 2, 8),
                                              np.float32), mesh)


@pytest.mark.slow
@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8-device mesh")
def test_moe_data_expert_zero1_composition():
    """2-D data x expert mesh with ZeRO-1: expert weights shard over
    'expert', and their optimizer state additionally shards over
    'data' (P('expert','data',None)) — the layered MoE memory recipe.
    Training trajectory unchanged. Slow tier (~14 s on the 1-core
    tier-1 host); the MoE routing oracle and the expert-axis training
    path keep fast coverage above, ZeRO-1 in test_gspmd.py."""
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel import make_mesh, make_train_step

    from tests._lm_utils import arith_corpus, lm_nll

    mesh = make_mesh({"data": 2, "expert": 4})
    vocab, T, B = 32, 16, 16
    sym = transformer.get_symbol(vocab, T, num_layers=1, num_heads=2,
                                 dim=32, num_experts=8,
                                 expert_axis="expert")
    step = make_train_step(sym, optimizer="adam", mesh=mesh,
                           optimizer_sharding="zero1")
    state = step.init_state(Xavier(), {"data": (B, T),
                                       "softmax_label": (B, T)})
    # trailing replicated dims are normalized away by the placement
    # layer (sharding._ns: placements must compare equal to XLA's own
    # normalized output shardings or step 2 pays a spurious recompile)
    w1 = state[0]["layer0_experts_w1_weight"]
    assert tuple(w1.sharding.spec) == ("expert",), w1.sharding
    m1 = state[1]["layer0_experts_w1_weight"][0]
    assert tuple(m1.sharding.spec) == ("expert", "data"), m1.sharding

    toks, labels = arith_corpus(B, T, vocab)
    batch = step.place_batch({"data": toks, "softmax_label": labels})
    rng = jax.random.PRNGKey(0)
    state, outs = step(state, batch, 3e-3, rng)
    first = lm_nll(outs, labels, vocab)
    for _ in range(60):
        state, outs = step(state, batch, 3e-3, rng)
    assert lm_nll(outs, labels, vocab) < first / 2
