"""Profiler tests (reference: src/engine/profiler.h chrome-trace dump +
python/mxnet/profiler.py control surface)."""
import json

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler


def _stop():
    profiler.profiler_set_state("stop")


def test_eager_op_timeline(tmp_path):
    out = str(tmp_path / "profile.json")
    profiler.profiler_set_config(mode="all", filename=out)
    profiler.profiler_set_state("run")
    try:
        a = nd.ones((8, 8))
        b = nd.dot(a, a)
        (b + 1).wait_to_read()
    finally:
        _stop()
    path = profiler.dump_profile()
    trace = json.load(open(path))
    names = [e["name"] for e in trace["traceEvents"]]
    assert "dot" in names
    assert any(n in names for n in ("_plus_scalar", "broadcast_add"))
    ev = trace["traceEvents"][0]
    assert ev["ph"] == "X" and ev["dur"] >= 1


def test_symbolic_mode_records_executor_only(tmp_path):
    out = str(tmp_path / "profile_sym.json")
    profiler.profiler_set_config(mode="symbolic", filename=out)
    profiler.profiler_set_state("run")
    try:
        x = mx.sym.Variable("data")
        y = mx.sym.FullyConnected(x, num_hidden=4, name="fc")
        ex = y.simple_bind(data=(2, 3))
        for name, arr in ex.arg_dict.items():
            if name != "data":
                arr[:] = np.ones(arr.shape, "float32")
        ex.forward(data=np.ones((2, 3), "float32"))
        nd.ones((4,)).wait_to_read()   # eager op: must NOT be recorded
    finally:
        _stop()
    trace = json.load(open(profiler.dump_profile()))
    cats = {e["cat"] for e in trace["traceEvents"]}
    names = [e["name"] for e in trace["traceEvents"]]
    assert "executor" in cats
    assert "executor_forward" in names
    assert "_ones" not in names


def test_stop_clears_collection_on_restart(tmp_path):
    profiler.profiler_set_config(mode="all",
                                 filename=str(tmp_path / "p.json"))
    profiler.profiler_set_state("run")
    nd.ones((2,)).wait_to_read()
    _stop()
    profiler.profiler_set_state("run")
    _stop()
    trace = json.load(open(profiler.dump_profile()))
    assert trace["traceEvents"] == []


def test_scope_nesting(tmp_path):
    profiler.profiler_set_config(mode="all",
                                 filename=str(tmp_path / "s.json"))
    profiler.profiler_set_state("run")
    try:
        with profiler.scope("outer", "user"):
            (nd.ones((2,)) + 1).wait_to_read()
    finally:
        _stop()
    trace = json.load(open(profiler.dump_profile()))
    names = [e["name"] for e in trace["traceEvents"]]
    assert "outer" in names and "_plus_scalar" in names
