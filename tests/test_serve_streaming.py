"""Streaming serving (docs/serving.md §streaming).

Load-bearing acceptance gates:

* a streamed generate delivers EXACTLY the one-shot row's generated
  tail, token-for-token, one ``on_token`` call per token — greedy and
  seeded, f32 and the quantized/reduced-precision caches alike (the
  terminal reply still carries the full row, so every streamed call
  cross-checks itself bitwise);
* the router relays frames as they arrive, never buffering a stream:
  the first token reaches the caller while the decoder is still
  decoding, and mid-stream replica death resumes on a survivor with
  no duplicated and no missing tokens (delivered-prefix replay);
* chunked prefill (MXNET_PREFILL_CHUNK) and batched prefill
  (PrefillEngine coalescing) are bitwise invisible: same tokens, same
  exported KV rows as the monolithic/sequential paths;
* a stalled stream is detected by the per-frame idle timeout
  (MXNET_STREAM_IDLE_TIMEOUT) — never by the old whole-request
  deadline — and recovery delivers every token exactly once.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.generation import Generator
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.models import transformer
from mxnet_tpu.parallel import make_train_step
from mxnet_tpu.parallel.resilience import (FaultInjector, RetryPolicy,
                                           install_fault_injector)
from mxnet_tpu.serve import (ContinuousDecoder, PrefillEngine,
                             ServeRouter, ServeServer)
from mxnet_tpu.serve.decode import prefill_chunk
from mxnet_tpu.serve.net import ServeClient, stream_idle_timeout

pytestmark = pytest.mark.serve

V, L, H, DIM, T = 50, 2, 2, 32, 24


def _params(seed=0):
    sym = transformer.get_symbol(V, 12, num_layers=L, num_heads=H,
                                 dim=DIM, max_len=T)
    step = make_train_step(sym, optimizer="sgd")
    mx.random.seed(seed)
    return step.init_state(Xavier(), {"data": (2, 12),
                                      "softmax_label": (2, 12)})[0]


@pytest.fixture(scope="module")
def params():
    return _params()


def _gen(params, batch_size, **kw):
    return Generator(params, V, T, num_layers=L, num_heads=H, dim=DIM,
                     batch_size=batch_size, **kw)


def _cval(name):
    e = telemetry.snapshot().get(name)
    return int(e["value"]) if e else 0


GREEDY = {"temperature": 0.0}
SEEDED = {"temperature": 0.8, "top_k": 8, "seed": 3}


# -- (a) streamed == one-shot --------------------------------------------
class TestStreamedEqualsOneShot:
    # the seeded twin re-runs the same wire path for ~4 s — slow tier
    # (sampled streamed==one-shot exactness stays pinned there and in
    # the failover/chaos suites)
    @pytest.mark.parametrize("sampling",
                             [GREEDY,
                              pytest.param(SEEDED,
                                           marks=pytest.mark.slow)],
                             ids=["greedy", "seeded"])
    def test_client_stream_matches_oneshot(self, params, sampling):
        p = np.arange(1, 5)
        want = _gen(params, 1).generate(p[None], 8, eos_id=0,
                                        **sampling)[0]
        dec = ContinuousDecoder(_gen(params, 2))
        srv = ServeServer(dec)
        f0 = _cval("serve.net.stream_frames")
        try:
            with ServeClient(srv.host, srv.port) as cli:
                toks = []
                out = cli.generate(p, 8, eos_id=0,
                                   on_token=toks.append, **sampling)
                np.testing.assert_array_equal(out, want)
                np.testing.assert_array_equal(np.asarray(toks),
                                              want[p.size:])
                assert _cval("serve.net.stream_frames") > f0
        finally:
            srv.close()
            dec.close()

    @pytest.mark.slow
    @pytest.mark.parametrize("genkw", [{"dtype": "bfloat16"},
                                       {"quantize_kv": True}],
                             ids=["bf16", "int8kv"])
    def test_stream_matches_oneshot_reduced_precision(self, params,
                                                      genkw):
        """The frame path carries whatever the cache dtype decodes —
        bf16 and int8-KV streams byte-equal their one-shot twins."""
        p = np.arange(1, 5)
        want = _gen(params, 1, **genkw).generate(p[None], 6, eos_id=0,
                                                 **SEEDED)[0]
        dec = ContinuousDecoder(_gen(params, 2, **genkw))
        srv = ServeServer(dec)
        try:
            with ServeClient(srv.host, srv.port) as cli:
                toks = []
                out = cli.generate(p, 6, eos_id=0,
                                   on_token=toks.append, **SEEDED)
                np.testing.assert_array_equal(out, want)
                np.testing.assert_array_equal(np.asarray(toks),
                                              want[p.size:])
        finally:
            srv.close()
            dec.close()

    def test_generate_stream_iterator(self, params):
        """The pull-style twin: the iterator yields the same tail and
        returns the full row as its StopIteration value."""
        p = np.arange(2, 6)
        want = _gen(params, 1).generate(p[None], 6, eos_id=0)[0]
        dec = ContinuousDecoder(_gen(params, 2))
        srv = ServeServer(dec)
        try:
            with ServeClient(srv.host, srv.port) as cli:
                it = cli.generate_stream(p, 6, eos_id=0)
                got = []
                row = None
                while True:
                    try:
                        got.append(next(it))
                    except StopIteration as stop:
                        row = stop.value
                        break
                np.testing.assert_array_equal(np.asarray(got),
                                              want[p.size:])
                np.testing.assert_array_equal(row, want)
        finally:
            srv.close()
            dec.close()


# -- (b) router relay: unbuffered, failover-exact ------------------------
class _Fleet:
    """Two real decode replicas behind a poll-less router —
    deterministic: tests drive poll_now() themselves."""

    def __init__(self, params, **genkw):
        self.decoders = [ContinuousDecoder(_gen(params, 2, **genkw))
                         for _ in range(2)]
        self.servers = [ServeServer(d) for d in self.decoders]
        self.router = ServeRouter(poll_ms=0)
        for i, s in enumerate(self.servers):
            self.router.add_replica(s.host, s.port,
                                    name="replica%d" % i)
        self.router.poll_now()

    def decoder_of(self, name):
        return self.decoders[int(name[-1])]

    def close(self):
        self.router.close()
        for s in self.servers:
            s.close()
        for d in self.decoders:
            d.close()


class TestRouterRelay:
    def test_relays_without_buffering(self, params, tmp_path):
        """The first token reaches the caller while the decoder is
        still mid-sequence (finished stays 0 at first frame), and the
        relay/first-token trace events mark the path — a buffering
        relay would deliver everything after the terminal reply."""
        from mxnet_tpu import trace
        from tools.trace_report import load

        p = np.arange(1, 5)
        want = _gen(params, 1).generate(p[None], 16, eos_id=0)[0]
        if want.size - p.size < 4:
            pytest.skip("model finished too fast to observe")
        f = _Fleet(params)
        dest = tmp_path / "trace.jsonl"
        trace.start_tracing(str(dest))
        seen_finished = []
        toks = []

        def on_token(t):
            if not toks:
                seen_finished.append(
                    sum(d.stats()["finished"] for d in f.decoders))
            toks.append(t)

        try:
            out = f.router.generate(p, 16, eos_id=0, session="s",
                                    on_token=on_token)
        finally:
            trace.stop_tracing()
            f.close()
        np.testing.assert_array_equal(out, want)
        np.testing.assert_array_equal(np.asarray(toks),
                                      want[p.size:])
        # at the FIRST frame no sequence had finished anywhere — the
        # frame outran the terminal reply by construction
        assert seen_finished == [0]
        names = {r.get("name") for r in load(str(dest))}
        assert "serve.router.stream_relay" in names
        assert "serve.stream.first_token" in names

    def test_midstream_death_resumes_token_exact(self, params):
        """Replica killed after the second delivered token: the
        delivered-prefix replay resumes on the survivor and the
        caller sees every remaining token exactly once — the
        concatenation byte-equals the fault-free tail."""
        p = np.arange(1, 5)
        sampling = {"temperature": 0.8, "top_k": 8, "seed": 11}
        want = _gen(params, 1).generate(p[None], 12, eos_id=0,
                                        **sampling)[0]
        if want.size - p.size < 5:
            pytest.skip("model finished too fast to kill mid-stream")
        f = _Fleet(params)
        f0 = _cval("serve.router.failovers")
        try:
            # pin the session with a plain generate first
            np.testing.assert_array_equal(
                f.router.generate(p, 12, eos_id=0, session="s",
                                  **sampling), want)
            pin = f.router.sessions()["s"]
            idx = int(pin[-1])
            toks = []

            def on_token(t):
                toks.append(t)
                if len(toks) == 2:
                    # the pinned replica "dies" now: every further
                    # frame read AND the control probe drop — the
                    # mid-stream read is where a dead replica shows
                    install_fault_injector(FaultInjector(
                        "router%d_recv:drop@1x*;"
                        "router%d_ctl_send:drop@1x*" % (idx, idx)))

            try:
                out = f.router.generate(p, 12, eos_id=0, session="s",
                                        on_token=on_token, **sampling)
            finally:
                install_fault_injector(None)
            np.testing.assert_array_equal(out, want)
            np.testing.assert_array_equal(np.asarray(toks),
                                          want[p.size:])
            assert f.router.sessions()["s"] != pin
            assert _cval("serve.router.failovers") == f0 + 1
        finally:
            f.close()


# -- (c) chunked prefill -------------------------------------------------
class TestChunkedPrefill:
    # the seeded twin costs another ~4 s for the same chunked path —
    # slow tier (the sampling stream's chunk-invariance is also pinned
    # by the perf-gate streaming scenario's seeded row)
    @pytest.mark.parametrize("sampling",
                             [GREEDY,
                              pytest.param(SEEDED,
                                           marks=pytest.mark.slow)],
                             ids=["greedy", "seeded"])
    def test_chunked_parity(self, params, monkeypatch, sampling):
        """A chunked prefill admits the same sequence the monolithic
        one does — bitwise — and the chunk counter/stat move."""
        p = np.arange(1, 11)                       # 10 > chunk 3
        want = _gen(params, 1).generate(p[None], 6, eos_id=0,
                                        **sampling)[0]
        monkeypatch.setenv("MXNET_PREFILL_CHUNK", "3")
        c0 = _cval("serve.decode.prefill_chunks")
        with _gen(params, 2).serving_decoder() as dec:
            out = dec.submit(p, 6, eos_id=0, **sampling).result(120.0)
            np.testing.assert_array_equal(out, want)
            assert dec.stats()["prefills"] == 1
        assert _cval("serve.decode.prefill_chunks") == c0 + 4

    def test_short_prompts_not_held_behind_chunked(self, params,
                                                   monkeypatch,
                                                   tmp_path):
        """A short prompt admitted behind a long chunked prefill
        still decodes concurrently (the chunking slot is reserved,
        not the loop), and the chunk spans land in the trace."""
        from mxnet_tpu import trace
        from tools.trace_report import load

        monkeypatch.setenv("MXNET_PREFILL_CHUNK", "4")
        long_p, short_p = np.arange(1, 13), np.arange(1, 4)
        want_l = _gen(params, 1).generate(long_p[None], 4,
                                          eos_id=0)[0]
        want_s = _gen(params, 1).generate(short_p[None], 4,
                                          eos_id=0)[0]
        dest = tmp_path / "trace.jsonl"
        trace.start_tracing(str(dest))
        try:
            with _gen(params, 2).serving_decoder() as dec:
                f_long = dec.submit(long_p, 4, eos_id=0)
                f_short = dec.submit(short_p, 4, eos_id=0)
                np.testing.assert_array_equal(f_long.result(120.0),
                                              want_l)
                np.testing.assert_array_equal(f_short.result(120.0),
                                              want_s)
        finally:
            trace.stop_tracing()
        spans = [r for r in load(str(dest))
                 if r.get("name") == "serve.decode.prefill_chunk"]
        assert len(spans) == 3             # ceil(12 / 4); the short
        # prompt prefilled monolithically — never behind the chunks

    def test_chunk_knob_validated_loudly(self, params, monkeypatch):
        monkeypatch.setenv("MXNET_PREFILL_CHUNK", "-1")
        with pytest.raises(ValueError, match="MXNET_PREFILL_CHUNK"):
            prefill_chunk()
        with _gen(params, 2).serving_decoder() as dec:
            with pytest.raises(ValueError,
                               match="MXNET_PREFILL_CHUNK"):
                dec.submit(np.arange(1, 5), 4, eos_id=0)


# -- (d) batched prefill -------------------------------------------------
class TestBatchedPrefill:
    def test_batched_parity_vs_sequential(self, params, monkeypatch):
        """Concurrent prefills coalesced into one padded forward give
        each request the SAME first token and KV rows a sequential
        engine gives it — causal masking makes the padding inert."""
        monkeypatch.setenv("MXNET_SERVE_MAX_WAIT_MS", "30")
        batched = PrefillEngine(_gen(params, 4))
        monkeypatch.setenv("MXNET_SERVE_MAX_WAIT_MS", "0")
        solo = PrefillEngine(_gen(params, 4))
        b0 = _cval("serve.prefill.batched")
        prompts = [np.arange(1, 5), np.arange(2, 9),
                   np.arange(3, 6)]
        res = [None] * len(prompts)

        def go(i):
            res[i] = batched.prefill(prompts[i], temperature=0.8,
                                     top_k=8, seed=100 + i)

        try:
            threads = [threading.Thread(target=go, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for i, p in enumerate(prompts):
                ref = solo.prefill(p, temperature=0.8, top_k=8,
                                   seed=100 + i)
                assert res[i]["first_token"] == ref["first_token"]
                assert res[i]["pos"] == ref["pos"]
                got_b, ref_b = res[i]["kv_blob"], ref["kv_blob"]
                assert got_b["pos"] == ref_b["pos"]
                assert set(got_b["rows"]) == set(ref_b["rows"])
                for name, arr in ref_b["rows"].items():
                    assert got_b["rows"][name].dtype == arr.dtype
                    np.testing.assert_array_equal(
                        got_b["rows"][name], arr)
            assert _cval("serve.prefill.batched") > b0
        finally:
            batched.close()
            solo.close()

    def test_close_fails_stranded_waiters(self, params, monkeypatch):
        """close() never strands a queued prefill: the batcher drains
        what it can and anything left fails typed, fast."""
        from mxnet_tpu.serve import EngineClosed
        monkeypatch.setenv("MXNET_SERVE_MAX_WAIT_MS", "30")
        eng = PrefillEngine(_gen(params, 4))
        eng.close()
        with pytest.raises(EngineClosed):
            eng.prefill(np.arange(1, 5))


# -- (e) idle timeout ----------------------------------------------------
class _StallingEngine:
    """Wire-level stall double: streams the real decoder's frames on
    every call EXCEPT the stalled one, where it emits one frame and
    then goes silent (socket open, no frames — the failure mode only
    a per-frame idle timeout can see)."""

    def __init__(self, dec, stall_on=2):
        self._dec = dec
        self._calls = 0
        self._stall_on = stall_on
        self.released = threading.Event()

    def handle_generate(self, payload):
        return self._dec.handle_generate(payload)

    def handle_generate_stream(self, payload, emit):
        self._calls += 1
        if self._calls != self._stall_on:
            return self._dec.handle_generate_stream(payload, emit)
        row = self._dec.handle_generate(payload)
        tail = [int(t) for t in
                np.asarray(row).reshape(-1)[
                    np.asarray(payload["prompt"]).size:]]
        emit(tail[:1], 0)                 # one frame, then silence
        self.released.wait(30.0)
        return row

    def stats(self):
        return self._dec.stats()


class TestIdleTimeout:
    def test_knob_validated_loudly(self, monkeypatch):
        for bad in ("0", "-3", "inf", "nan"):
            monkeypatch.setenv("MXNET_STREAM_IDLE_TIMEOUT", bad)
            with pytest.raises(ValueError,
                               match="MXNET_STREAM_IDLE_TIMEOUT"):
                stream_idle_timeout()
        monkeypatch.setenv("MXNET_STREAM_IDLE_TIMEOUT", "2.5")
        assert stream_idle_timeout() == 2.5

    def test_stalled_stream_detected_and_replayed_exact(
            self, params, monkeypatch):
        """A replica that stalls mid-stream (alive, silent) trips the
        per-frame idle timeout — NOT the old 120s+1s/token request
        deadline — and the replay delivers every token exactly once:
        the frame delivered before the stall is never re-delivered."""
        monkeypatch.setenv("MXNET_STREAM_IDLE_TIMEOUT", "0.4")
        p = np.arange(1, 5)
        want = _gen(params, 1).generate(p[None], 8, eos_id=0)[0]
        dec = ContinuousDecoder(_gen(params, 2))
        stall = _StallingEngine(dec, stall_on=2)
        srv = ServeServer(stall)
        try:
            with ServeClient(srv.host, srv.port) as cli:
                toks = []
                cli.generate(p, 8, eos_id=0,
                             on_token=lambda t: None)  # call 1: clean
                t0 = time.monotonic()
                out = cli.generate(p, 8, eos_id=0,   # call 2: stalls
                                   on_token=toks.append)
                wall = time.monotonic() - t0
            np.testing.assert_array_equal(out, want)
            np.testing.assert_array_equal(np.asarray(toks),
                                          want[p.size:])
            # detected by the idle timeout, nowhere near the old
            # whole-request deadline
            assert wall < 30.0
        finally:
            stall.released.set()
            srv.close()
            dec.close()

    def test_hung_replica_fails_fast_when_alone(self, params,
                                                monkeypatch):
        """No survivor, no recovery: a permanently silent stream
        exhausts the retry budget in idle-timeout time, not the
        blanket generate deadline."""
        monkeypatch.setenv("MXNET_STREAM_IDLE_TIMEOUT", "0.2")
        dec = ContinuousDecoder(_gen(params, 2))
        stall = _StallingEngine(dec, stall_on=1)
        srv = ServeServer(stall)
        try:
            cli = ServeClient(srv.host, srv.port,
                              retry=RetryPolicy(max_retries=1,
                                                base_delay=0.01,
                                                deadline=10.0))
            t0 = time.monotonic()
            with pytest.raises(Exception):
                cli.generate(np.arange(1, 5), 8, eos_id=0,
                             on_token=lambda t: None)
            assert time.monotonic() - t0 < 10.0
            cli.close()
        finally:
            stall.released.set()
            srv.close()
            dec.close()
