"""Launcher tests (reference: tools/launch.py + dmlc_tracker launch modes,
reference tools/launch.py:29-96). The ssh transport is mocked — the test
asserts the wiring (per-rank env, coordinator choice, command quoting),
not real ssh."""
import os
import shlex
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from tools import launch  # noqa: E402


class _FakeProc:
    calls = []

    def __init__(self, argv, env=None):
        _FakeProc.calls.append((argv, env))

    def wait(self):
        return 0

    def poll(self):
        return 0


@pytest.fixture
def fake_popen(monkeypatch):
    _FakeProc.calls = []
    monkeypatch.setattr(subprocess, "Popen", _FakeProc)
    return _FakeProc


def test_ssh_two_node_wiring(fake_popen, tmp_path, monkeypatch):
    hosts = tmp_path / "hosts.txt"
    hosts.write_text("node-a\nnode-b\nnode-c\n")
    rc = launch.main(["-n", "2", "--launcher", "ssh", "-H", str(hosts),
                      "--env", "FOO=a b", "python", "train.py",
                      "--lr", "0.1"])
    assert rc == 0
    assert len(fake_popen.calls) == 2
    for rank, (argv, env) in enumerate(fake_popen.calls):
        assert argv[0] == "ssh"
        assert argv[-2] == ("node-a", "node-b")[rank]
        remote = argv[-1]
        # every worker points at host 0 as coordinator, with its own rank
        assert "DMLC_PS_ROOT_URI=node-a" in remote
        assert "DMLC_WORKER_ID=%d" % rank in remote
        assert "DMLC_NUM_WORKER=2" in remote
        assert "DMLC_ROLE=worker" in remote
        # --env values and the command survive shell quoting
        assert shlex.quote("a b") in remote
        assert remote.endswith("python train.py --lr 0.1")
    # both workers agree on the coordinator port
    ports = {argv[-1].split("DMLC_PS_ROOT_PORT=")[1].split()[0]
             for argv, _ in fake_popen.calls}
    assert len(ports) == 1


def test_ssh_needs_enough_hosts(fake_popen, tmp_path):
    hosts = tmp_path / "hosts.txt"
    hosts.write_text("only-one\n")
    with pytest.raises(SystemExit):
        launch.main(["-n", "2", "--launcher", "ssh", "-H", str(hosts),
                     "python", "x.py"])


def test_local_env_wiring(fake_popen):
    rc = launch.main(["-n", "2", "--launcher", "local", "--env",
                      "BAR=1", "python", "x.py"])
    assert rc == 0
    assert len(fake_popen.calls) == 2
    ranks = set()
    for argv, env in fake_popen.calls:
        assert argv == ["python", "x.py"]
        assert env["DMLC_PS_ROOT_URI"] == "127.0.0.1"
        assert env["DMLC_NUM_WORKER"] == "2"
        assert env["BAR"] == "1"
        ranks.add(env["DMLC_WORKER_ID"])
    assert ranks == {"0", "1"}


def test_env_flag_requires_equals(fake_popen, capsys):
    with pytest.raises(SystemExit):
        launch.main(["-n", "1", "--env", "NOVALUE", "python", "x.py"])
    assert "K=V" in capsys.readouterr().err
