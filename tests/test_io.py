"""IO tests — reference: tests/python/unittest/test_io.py (NDArrayIter
shuffle/pad/discard semantics)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import io


def test_ndarrayiter_basic():
    data = np.arange(30).reshape(10, 3).astype(np.float32)
    label = np.arange(10).astype(np.float32)
    it = io.NDArrayIter(data, label, batch_size=5)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (5, 3)
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:5])
    np.testing.assert_allclose(batches[1].label[0].asnumpy(), label[5:])
    # reset + reiterate
    it.reset()
    assert len(list(it)) == 2


def test_ndarrayiter_pad():
    data = np.arange(21).reshape(7, 3).astype(np.float32)
    it = io.NDArrayIter(data, None, batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].pad == 0
    assert batches[1].pad == 1
    # padded part wraps to the beginning
    np.testing.assert_allclose(batches[1].data[0].asnumpy()[-1], data[0])


def test_ndarrayiter_discard():
    data = np.arange(21).reshape(7, 3).astype(np.float32)
    it = io.NDArrayIter(data, None, batch_size=4,
                        last_batch_handle="discard")
    assert len(list(it)) == 1


def test_ndarrayiter_dict_inputs():
    it = io.NDArrayIter({"a": np.zeros((8, 2)), "b": np.ones((8, 3))},
                        np.arange(8), batch_size=4)
    assert sorted(d.name for d in it.provide_data) == ["a", "b"]
    batch = next(it)
    assert batch.data[0].shape in ((4, 2), (4, 3))


def test_resize_iter():
    data = np.zeros((10, 2))
    it = io.ResizeIter(io.NDArrayIter(data, batch_size=5), size=5)
    assert len(list(it)) == 5


def test_prefetching_iter():
    data = np.arange(40).reshape(20, 2).astype(np.float32)
    base = io.NDArrayIter(data, np.arange(20), batch_size=5)
    it = io.PrefetchingIter(base)
    batches = list(it)
    assert len(batches) == 4
    it.reset()
    assert len(list(it)) == 4


def test_data_desc_layout():
    d = io.DataDesc("data", (32, 3, 224, 224), layout="NCHW")
    assert io.DataDesc.get_batch_axis(d.layout) == 0
    assert io.DataDesc.get_batch_axis("TNC") == 1
