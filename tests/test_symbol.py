"""Symbol compose / infer_shape / JSON round-trip / executor fwd+bwd.

Modeled on the reference's tests/python/unittest/test_symbol.py and
test_infer_shape.py (SURVEY.md §4).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym


def _mlp():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, name="fc1", num_hidden=64)
    act = sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = sym.FullyConnected(act, name="fc2", num_hidden=10)
    return sym.SoftmaxOutput(fc2, name="softmax")


def test_compose_and_arguments():
    net = _mlp()
    args = net.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight",
                    "fc2_bias", "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]


def test_infer_shape_backward_params():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(32, 100))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d["fc1_weight"] == (64, 100)
    assert d["fc1_bias"] == (64,)
    assert d["fc2_weight"] == (10, 64)
    assert out_shapes == [(32, 10)]
    assert aux_shapes == []


def test_infer_shape_conv():
    data = sym.Variable("data")
    conv = sym.Convolution(data, name="conv1", kernel=(3, 3), num_filter=8,
                           pad=(1, 1))
    bn = sym.BatchNorm(conv, name="bn1")
    pool = sym.Pooling(bn, kernel=(2, 2), stride=(2, 2), pool_type="max")
    arg_shapes, out_shapes, aux_shapes = pool.infer_shape(data=(2, 3, 8, 8))
    d = dict(zip(pool.list_arguments(), arg_shapes))
    assert d["conv1_weight"] == (8, 3, 3, 3)
    assert d["conv1_bias"] == (8,)
    assert d["bn1_gamma"] == (8,)
    assert out_shapes == [(2, 8, 4, 4)]
    assert aux_shapes == [(8,), (8,)]
    assert pool.list_auxiliary_states() == ["bn1_moving_mean",
                                            "bn1_moving_var"]


def test_json_roundtrip():
    net = _mlp()
    js = net.tojson()
    net2 = mx.sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    a1, o1, _ = net.infer_shape(data=(4, 20))
    a2, o2, _ = net2.infer_shape(data=(4, 20))
    assert a1 == a2 and o1 == o2


def test_group_and_internals():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, name="fc1", num_hidden=4)
    act = sym.Activation(fc1, name="act1", act_type="tanh")
    grp = mx.sym.Group([fc1, act])
    assert grp.list_outputs() == ["fc1_output", "act1_output"]
    internals = act.get_internals()
    assert "fc1_output" in internals.list_outputs()
    fc1_again = internals["fc1_output"]
    assert fc1_again.list_outputs() == ["fc1_output"]


def test_simple_bind_forward_backward():
    np.random.seed(0)
    net = _mlp()
    ex = net.simple_bind(mx.cpu(), data=(8, 100))
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr[:] = np.random.uniform(-0.1, 0.1, arr.shape)
    x = np.random.uniform(-1, 1, (8, 100)).astype(np.float32)
    y = np.random.randint(0, 10, (8,)).astype(np.float32)
    outs = ex.forward(is_train=True, data=x, softmax_label=y)
    p = outs[0].asnumpy()
    assert p.shape == (8, 10)
    np.testing.assert_allclose(p.sum(axis=1), np.ones(8), rtol=1e-5)
    ex.backward()
    gw = ex.grad_dict["fc2_weight"].asnumpy()
    assert np.abs(gw).sum() > 0
    # SoftmaxOutput grad at data: p - onehot
    gd = ex.grad_dict["data"].asnumpy()
    assert gd.shape == x.shape


def test_executor_grad_req_add_and_null():
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("w")
    out = data * w
    ex = out.bind(mx.cpu(),
                  args={"data": mx.nd.array([1.0, 2.0]),
                        "w": mx.nd.array([3.0, 4.0])},
                  grad_req={"data": "null", "w": "add"})
    ex.forward(is_train=True)
    ex.backward(mx.nd.array([1.0, 1.0]))
    ex.forward(is_train=True)
    ex.backward(mx.nd.array([1.0, 1.0]))
    np.testing.assert_allclose(ex.grad_dict["w"].asnumpy(), [2.0, 4.0])
    assert ex.grad_dict["data"] is None


def test_batchnorm_aux_update_in_executor():
    data = sym.Variable("data")
    bn = sym.BatchNorm(data, name="bn", momentum=0.5, fix_gamma=False)
    ex = bn.simple_bind(mx.cpu(), data=(4, 3))
    ex.arg_dict["bn_gamma"][:] = 1.0
    ex.aux_dict["bn_moving_var"][:] = 1.0
    x = np.random.RandomState(1).normal(3.0, 2.0, (4, 3)).astype(np.float32)
    ex.forward(is_train=True, data=x)
    mm = ex.aux_dict["bn_moving_mean"].asnumpy()
    np.testing.assert_allclose(mm, 0.5 * x.mean(axis=0), rtol=1e-5)
    # inference path uses (unchanged) moving stats
    ex2_out = ex.forward(is_train=False, data=x)[0].asnumpy()
    expect = (x - mm) / np.sqrt(
        ex.aux_dict["bn_moving_var"].asnumpy() + 1e-3)
    np.testing.assert_allclose(ex2_out, expect, rtol=1e-4)


def test_attr_scope_ctx_group():
    with mx.AttrScope(ctx_group="dev1"):
        data = sym.Variable("data")
        fc = sym.FullyConnected(data, name="fc", num_hidden=3)
    assert fc.attr("__ctx_group__") == "dev1"


def test_symbol_arith_and_methods():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = (a + b * 2.0).sum()
    ex = c.bind(mx.cpu(), args={"a": mx.nd.array([1.0, 2.0]),
                                "b": mx.nd.array([3.0, 4.0])})
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, 17.0)


def test_variable_shape_attr_used_in_infer():
    data = sym.Variable("data", shape=(5, 7))
    fc = sym.FullyConnected(data, num_hidden=2)
    arg_shapes, out_shapes, _ = fc.infer_shape()
    assert out_shapes == [(5, 2)]


def test_load_legacy_v08_json():
    """Pre-0.9 saves: attrs under 'param', layer nodes without parameter
    inputs, bare hidden keys — the loader upgrades all three (reference
    src/nnvm/legacy_json_util.cc UpgradeJSON_* passes)."""
    import json
    legacy = json.dumps({
        "nodes": [
            {"op": "null", "name": "data", "inputs": []},
            {"op": "FullyConnected", "name": "fc1",
             "param": {"num_hidden": "8", "lr_mult": "2.0"},
             "inputs": [[0, 0]]},          # weight/bias edges missing
            {"op": "Activation", "name": "act",
             "param": {"act_type": "relu"}, "inputs": [[1, 0]]},
        ],
        "heads": [[2, 0, 0]],
    })
    sym = mx.sym.load_json(legacy)
    args = sym.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias"]
    shapes = sym.infer_shape(data=(4, 3))[0]
    assert shapes[args.index("fc1_weight")] == (8, 3)
    # hidden key became a __dunder__ attr
    assert sym.attr_dict().get("fc1", {}).get("__lr_mult__") == "2.0"
    # and the upgraded graph executes
    ex = sym.simple_bind(mx.cpu(), data=(4, 3))
    out = ex.forward()
    assert out[0].shape == (4, 8)
