"""Module API tests — reference: tests/python/unittest/test_module.py (681
LoC) + tests/python/train/test_mlp.py convergence gate."""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io


def _mlp_sym(num_hidden=32, num_classes=2):
    data = mx.sym.Variable("data")
    net = mx.sym.Flatten(data=data)
    net = mx.sym.FullyConnected(data=net, name="fc1",
                                num_hidden=num_hidden)
    net = mx.sym.Activation(data=net, act_type="relu")
    net = mx.sym.FullyConnected(data=net, name="fc2",
                                num_hidden=num_classes)
    return mx.sym.SoftmaxOutput(data=net, name="softmax")


def _toy_data(n=256, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, 1, 8, 8)).astype(np.float32)
    w = rng.standard_normal(64)
    y = (X.reshape(n, -1) @ w > 0).astype(np.float32)
    return X, y


def test_module_input_names_validation():
    sym = _mlp_sym()
    with pytest.raises(ValueError):
        mx.mod.Module(sym, data_names=["wrong_name"])


def test_module_bind_forward_shapes():
    sym = _mlp_sym()
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 1, 8, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    batch = io.DataBatch(data=[mx.nd.ones((4, 1, 8, 8))],
                         label=[mx.nd.zeros((4,))])
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (4, 2)
    np.testing.assert_allclose(out.asnumpy().sum(axis=1),
                               np.ones(4), rtol=1e-5)


def test_module_train_convergence():
    """End-to-end convergence gate (reference
    tests/python/train/test_mlp.py asserts final accuracy)."""
    X, y = _toy_data()
    mx.random.seed(0)
    np.random.seed(0)  # NDArrayIter shuffles via the global numpy RNG
    train = io.NDArrayIter(X, y, batch_size=32, shuffle=True)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, num_epoch=5, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            eval_metric="acc")
    score = mod.score(train, "acc")
    assert score[0][1] > 0.95, score


def test_module_multi_device_matches_single():
    """Data-parallel mesh (4 virtual devices) reaches the same training
    result as single device — the TPU analogue of the reference's
    multi_lenet.py multi-GPU parity test."""
    X, y = _toy_data(n=128)

    def run(ctxs, kvstore):
        mx.random.seed(42)
        np.random.seed(42)
        train = io.NDArrayIter(X, y, batch_size=32)
        mod = mx.mod.Module(_mlp_sym(), context=ctxs)
        mod.fit(train, num_epoch=3, optimizer="sgd",
                optimizer_params={"learning_rate": 0.5},
                kvstore=kvstore, eval_metric="acc",
                initializer=mx.init.Xavier())
        args, _ = mod.get_params()
        return {k: v.asnumpy() for k, v in args.items()}

    single = run(mx.cpu(), "local")
    multi = run([mx.cpu(i) for i in range(4)], "device")
    for k in single:
        np.testing.assert_allclose(single[k], multi[k], rtol=1e-4,
                                   atol=1e-5, err_msg=k)


def test_module_checkpoint_roundtrip():
    X, y = _toy_data(n=64)
    train = io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, num_epoch=1, optimizer="sgd")
    with tempfile.TemporaryDirectory() as tmp:
        prefix = os.path.join(tmp, "model")
        mod.save_checkpoint(prefix, 1, save_optimizer_states=True)
        assert os.path.exists(prefix + "-symbol.json")
        assert os.path.exists(prefix + "-0001.params")

        mod2 = mx.mod.Module.load(prefix, 1, context=mx.cpu())
        mod2.bind(data_shapes=train.provide_data,
                  label_shapes=train.provide_label)
        args1, _ = mod.get_params()
        args2, _ = mod2.get_params()
        for k in args1:
            np.testing.assert_allclose(args1[k].asnumpy(),
                                       args2[k].asnumpy(), err_msg=k)


def test_module_predict_and_score():
    X, y = _toy_data(n=64)
    train = io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, num_epoch=1)
    preds = mod.predict(train)
    assert preds.shape == (64, 2)
    res = mod.score(train, ["acc", "ce"])
    assert len(res) == 2


def test_module_input_grads():
    sym = _mlp_sym()
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 1, 8, 8))],
             label_shapes=[("softmax_label", (4,))],
             inputs_need_grad=True)
    mod.init_params()
    batch = io.DataBatch(data=[mx.nd.ones((4, 1, 8, 8))],
                         label=[mx.nd.zeros((4,))])
    mod.forward(batch, is_train=True)
    mod.backward()
    ig = mod.get_input_grads()[0]
    assert ig.shape == (4, 1, 8, 8)
    assert float(mx.nd.abs(ig).sum().asscalar()) > 0


def test_module_batch_size_reshape():
    """Forward with a different batch size re-specializes (reference
    module.py:forward reshape path)."""
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 1, 8, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    batch = io.DataBatch(data=[mx.nd.ones((2, 1, 8, 8))],
                         label=[mx.nd.zeros((2,))])
    mod.forward(batch, is_train=False)
    assert mod.get_outputs()[0].shape == (2, 2)


def test_kvstore_push_pull():
    """reference tests/python/unittest/test_kvstore.py semantics."""
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.ones((2, 3)))
    out = mx.nd.zeros((2, 3))
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.ones((2, 3)))
    # push list -> sum-reduce
    kv.push(3, [mx.nd.ones((2, 3))] * 4)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), 4 * np.ones((2, 3)))
    # updater path
    kv2 = mx.kv.create("local")
    kv2.init("w", mx.nd.zeros((2,)))

    def updater(key, grad, weight):
        weight += grad * 2
    kv2.set_updater(updater)
    kv2.push("w", mx.nd.ones((2,)))
    o = mx.nd.zeros((2,))
    kv2.pull("w", out=o)
    np.testing.assert_allclose(o.asnumpy(), [2.0, 2.0])


def test_sequential_module():
    from mxnet_tpu.module import SequentialModule
    net1 = mx.sym.FullyConnected(mx.sym.Variable("data"), name="fc1",
                                 num_hidden=8)
    net2 = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("fc1_output"), name="fc2",
                              num_hidden=2), name="softmax")
    seq = SequentialModule()
    seq.add(mx.mod.Module(net1, label_names=None, context=mx.cpu()),
            auto_wiring=True)
    seq.add(mx.mod.Module(net2, data_names=["fc1_output"],
                          context=mx.cpu()), take_labels=True,
            auto_wiring=True)
    seq.bind(data_shapes=[("data", (4, 16))],
             label_shapes=[("softmax_label", (4,))])
    seq.init_params()
    seq.init_optimizer(kvstore=None)
    batch = io.DataBatch(data=[mx.nd.ones((4, 16))],
                         label=[mx.nd.zeros((4,))])
    seq.forward(batch, is_train=True)
    out = seq.get_outputs()[0]
    assert out.shape == (4, 2)
    seq.backward()
    seq.update()


def test_reshape_preserves_params():
    """Regression: batch-shape reshape must NOT wipe trained params."""
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 1, 8, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Uniform(0.5))
    before = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    batch = io.DataBatch(data=[mx.nd.ones((2, 1, 8, 8))],
                         label=[mx.nd.zeros((2,))])
    mod.forward(batch, is_train=False)
    out_small = mod.get_outputs()[0].asnumpy()
    assert np.abs(out_small).sum() > 0
    after = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    for k in before:
        np.testing.assert_allclose(before[k], after[k], err_msg=k)


def test_module_fit_checkpoint_resume(tmp_path):
    """fit(checkpoint_prefix=...) writes prefix-NNNN.params each epoch
    and a rerun resumes AFTER the newest readable checkpoint — the
    elastic-restart hook (docs/robustness.md). A torn file from a
    crash mid-save falls back to the previous checkpoint instead of
    killing the restarted worker."""
    X, y = _toy_data(n=64)
    prefix = str(tmp_path / "ck")

    def make_iter():
        return io.NDArrayIter(X, y, batch_size=32)

    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(make_iter(), num_epoch=2, checkpoint_prefix=prefix)
    assert os.path.exists(prefix + "-0001.params")
    assert os.path.exists(prefix + "-0002.params")

    # a torn newest checkpoint must not break resume
    with open(prefix + "-0003.params", "wb") as f:
        f.write(b"\x00torn-by-simulated-crash")
    epochs = []
    mod2 = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod2.fit(make_iter(), num_epoch=4, checkpoint_prefix=prefix,
             epoch_end_callback=lambda e, *_: epochs.append(e))
    assert epochs == [2, 3], epochs    # resumed after ck-0002, not 0
    assert os.path.exists(prefix + "-0004.params")

    # a third run with nothing left trains zero epochs...
    epochs3 = []
    mod3 = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod3.fit(make_iter(), num_epoch=4, checkpoint_prefix=prefix,
             epoch_end_callback=lambda e, *_: epochs3.append(e))
    assert epochs3 == []
    # ...and params were actually adopted from the checkpoint, not
    # re-initialized: mod3 ends up bit-identical to ck-0004
    saved = {k.split(":", 1)[1]: v for k, v in
             mx.nd.load(prefix + "-0004.params").items()
             if k.startswith("arg:")}
    arg3, _ = mod3.get_params()
    for k, v in saved.items():
        np.testing.assert_array_equal(arg3[k].asnumpy(), v.asnumpy(),
                                      err_msg=k)
    # resume=False ignores the EXISTING checkpoints (ck-0004 is on
    # disk) and trains from scratch, starting at epoch 0
    epochs4 = []
    mod4 = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod4.fit(make_iter(), num_epoch=1, checkpoint_prefix=prefix,
             resume=False,
             epoch_end_callback=lambda e, *_: epochs4.append(e))
    assert epochs4 == [0]
