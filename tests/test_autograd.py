"""Autograd — mirrors reference tests/python/unittest/test_autograd.py."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2, 4, 6])


def test_chain():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x) * 2
        z = y.sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(),
                               2 * np.exp([[1, 2], [3, 4]]), rtol=1e-5)


def test_multi_input():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = a * b + a
    c.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), [4, 5])  # b + 1
    np.testing.assert_allclose(b.grad.asnumpy(), [1, 2])  # a


def test_head_grads():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = 3 * x
    y.backward(nd.array([10.0, 100.0]))
    np.testing.assert_allclose(x.grad.asnumpy(), [30, 300])


def test_grad_req_add():
    x = nd.array([1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = 2 * x
        y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [6.0])


def test_pause():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        with autograd.pause():
            z = y * 2  # not recorded
        w = y + 1
    w.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2, 4])


def test_training_modes():
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_training()
        assert autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
    assert not autograd.is_recording()
    with autograd.train_mode():
        assert autograd.is_training()


def test_detach():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])  # y treated const


def test_matmul_grad():
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    w = nd.array(np.random.rand(5, 4).astype(np.float32))
    w.attach_grad()
    with autograd.record():
        out = nd.FullyConnected(a, w, no_bias=True, num_hidden=5)
        loss = out.sum()
    loss.backward()
    expected = np.ones((3, 5)).T @ a.asnumpy()
    np.testing.assert_allclose(w.grad.asnumpy(), expected, rtol=1e-5)


def test_softmax_output_grad():
    data = nd.array(np.random.rand(4, 3).astype(np.float32))
    label = nd.array([0.0, 1.0, 2.0, 1.0])
    data.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(data, label)
    out.backward()
    p = np.exp(data.asnumpy())
    p /= p.sum(axis=1, keepdims=True)
    onehot = np.eye(3)[[0, 1, 2, 1]]
    np.testing.assert_allclose(data.grad.asnumpy(), p - onehot, rtol=1e-5,
                               atol=1e-6)


def test_mark_variables():
    x = nd.array([1.0, 2.0])
    g = nd.zeros((2,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(g.asnumpy(), [2, 4])


def test_grad_function():
    x = nd.array([1.0, 2.0, 3.0])
    grads = autograd.grad_fn = None
    x2 = nd.array([3.0])

    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = 1 / (1 + nd.exp(-x))
            self.save = y
            return y

        def backward(self, dy):
            y = self.save
            return dy * y * (1 - y)

    f = Sigmoid()
    inp = nd.array([0.0])
    inp.attach_grad()
    with autograd.record():
        out = f(inp)
    out.backward()
    np.testing.assert_allclose(inp.grad.asnumpy(), [0.25], rtol=1e-5)


def test_numeric_gradient_helper():
    from mxnet_tpu.test_utils import check_numeric_gradient
    x = nd.array(np.random.rand(3, 2).astype(np.float32))

    def f(inputs):
        return (inputs[0] * inputs[0] + 2 * inputs[0]).sum()
    check_numeric_gradient(f, [x])


def test_batchnorm_aux_update():
    data = nd.array(np.random.rand(4, 3, 2, 2).astype(np.float32) + 5)
    gamma = nd.ones((3,))
    beta = nd.zeros((3,))
    mm = nd.zeros((3,))
    mv = nd.ones((3,))
    with autograd.record():
        out = nd.BatchNorm(data, gamma, beta, mm, mv, fix_gamma=False,
                           momentum=0.9)
    # moving stats updated in-place toward batch stats
    assert mm.asnumpy().mean() > 0.1
    # out is normalized
    assert abs(out.asnumpy().mean()) < 1e-3
