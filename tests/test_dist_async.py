"""dist_async kvstore: a REAL host-side parameter server applying each
push on arrival — the reference's kvstore_dist_server.h async mode
(sync_mode_=false), previously a documented drop. In-thread unit tests
for the server protocol + a 1-server/2-worker multiprocess test of the
full mx.kv.create("dist_async") surface.

The defining assertion: a worker that pushes and immediately pulls sees
its own update WITHOUT any other worker participating — no aggregation
barrier exists (dist_sync would block in the cross-worker collective).
"""
import os
import socket
import subprocess
import sys
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel.ps_async import AsyncPSClient, AsyncPSServer
from mxnet_tpu.parallel.resilience import (DeadWorkerError, FaultInjected,
                                           FaultInjector, RetryPolicy,
                                           install_fault_injector)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _free_port_block(n):
    """Server i binds base+i under the default endpoint layout; reuse
    the launcher's own block prober rather than a drifting copy."""
    from tools.launch import _free_port_block as block
    return block(n)


@pytest.fixture
def server():
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=2)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.stop()


def _client(srv):
    return AsyncPSClient(host="127.0.0.1", port=srv.port)


def test_push_replaces_without_optimizer(server):
    c = _client(server)
    c.init("w", np.full((3,), 5.0, np.float32))
    np.testing.assert_allclose(c.pull("w"), 5.0)
    c.push("w", np.full((3,), 2.0, np.float32))
    np.testing.assert_allclose(c.pull("w"), 2.0)   # replaced, not summed
    c.close()


def test_async_apply_with_server_side_optimizer(server):
    a, b = _client(server), _client(server)
    a.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    a.init("w", np.ones((4,), np.float32))
    # a pushes and immediately sees the applied update — no b involved
    a.push("w", np.ones((4,), np.float32))
    np.testing.assert_allclose(a.pull("w"), 0.9, rtol=1e-6)
    # b's push lands on a's result: updates serialize, never aggregate
    b.push("w", np.full((4,), 2.0, np.float32))
    np.testing.assert_allclose(b.pull("w"), 0.7, rtol=1e-6)
    np.testing.assert_allclose(a.pull("w"), 0.7, rtol=1e-6)
    a.close()
    b.close()


def test_init_first_writer_wins(server):
    a, b = _client(server), _client(server)
    a.init("w", np.zeros((2,), np.float32))
    b.init("w", np.ones((2,), np.float32))      # ignored: already there
    np.testing.assert_allclose(b.pull("w"), 0.0)
    a.close()
    b.close()


def test_concurrent_pushes_to_distinct_keys_apply_in_parallel(server):
    """The r4 advisor/judge finding: the old single global lock
    serialized every key (and the optimizer apply) — the reference
    applied different keys in parallel via per-key engine write deps.
    A deliberately slow updater proves the lock table: the two apply
    INTERVALS must overlap in time (a global lock would force them
    disjoint) — asserted on the recorded intervals, not a wall-clock
    bound, so a loaded CI machine can't flake it."""
    import time

    c = _client(server)
    c.init("a", np.zeros((2,), np.float32))
    c.init("b", np.zeros((2,), np.float32))

    intervals = []

    def slow_updater(index, grad, weight):
        t0 = time.time()
        time.sleep(0.4)      # value unasserted; overlap is the subject
        intervals.append((t0, time.time()))

    server._updater = slow_updater     # in-thread unit surface

    clients = [_client(server), _client(server)]
    ts = [threading.Thread(target=clients[i].push,
                           args=("ab"[i], np.ones((2,), np.float32)))
          for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert len(intervals) == 2
    (s0, e0), (s1, e1) = intervals
    assert s0 < e1 and s1 < e0, \
        "distinct-key applies never overlapped: %r" % (intervals,)
    for cl in clients + [c]:
        cl.close()


def test_sharded_client_routes_and_stripes():
    """2-server in-thread topology: whole keys route by the stable
    crc32 shard hash (identical on every client), and arrays above
    MXNET_KVSTORE_BIGARRAY_BOUND stripe across BOTH servers; pull
    reassembles exactly — including from a fresh client that derives
    the stripe plan from shape alone (never pushed the key)."""
    from mxnet_tpu.parallel.ps_async import (ShardedPSClient,
                                             shard_for_key)

    srvs = [AsyncPSServer(host="127.0.0.1", port=0, num_workers=1)
            for _ in range(2)]
    for s in srvs:
        threading.Thread(target=s.serve_forever, daemon=True).start()
    eps = [("127.0.0.1", s.port) for s in srvs]
    old = os.environ.get("MXNET_KVSTORE_BIGARRAY_BOUND")
    os.environ["MXNET_KVSTORE_BIGARRAY_BOUND"] = "100"
    try:
        c = ShardedPSClient(eps)
        keys = ["w%d" % i for i in range(8)]
        for i, k in enumerate(keys):
            c.init(k, np.full((4,), float(i), np.float32))
        for i, k in enumerate(keys):
            np.testing.assert_allclose(c.pull(k), float(i))
        # routing: every key landed exactly on its crc32 shard
        held = [set(AsyncPSClient(*eps[i]).stats()) for i in range(2)]
        for k in keys:
            sid = shard_for_key(k, 2)
            assert k in held[sid] and k not in held[1 - sid]
        assert all(h for h in held), "a server holds no keys: %r" % held
        # striping: > bound elements -> both servers hold a strip
        big = np.arange(257, dtype=np.float32).reshape(257, 1)
        c.init("emb", big)
        held = [set(AsyncPSClient(*eps[i]).stats()) for i in range(2)]
        assert "emb__strip0" in held[0] and "emb__strip1" in held[1]
        np.testing.assert_allclose(c.pull("emb"), big)
        # a FRESH client pulls the striped key from shape alone
        c2 = ShardedPSClient(eps)
        np.testing.assert_allclose(
            c2.pull("emb", shape=(257, 1), dtype=np.float32), big)
        # striped push without optimizer replaces stripe-wise
        c.push("emb", big * 2)
        np.testing.assert_allclose(c2.pull("emb", shape=(257, 1),
                                           dtype=np.float32), big * 2)
    finally:
        if old is None:
            os.environ.pop("MXNET_KVSTORE_BIGARRAY_BOUND", None)
        else:
            os.environ["MXNET_KVSTORE_BIGARRAY_BOUND"] = old
        for s in srvs:
            s.stop()


def test_barrier_counts_workers(server):
    a, b = _client(server), _client(server)
    hits = []

    def wait_then_barrier():
        b.barrier()
        hits.append("b")

    t = threading.Thread(target=wait_then_barrier, daemon=True)
    t.start()
    assert not hits              # b is blocked until a arrives
    a.barrier()
    t.join(timeout=10)
    assert hits == ["b"]
    a.close()
    b.close()


_WORKER_SRC = r"""
import os, sys, time
sys.path.insert(0, os.environ["REPO"])
import numpy as np
import mxnet_tpu as mx

rank = int(os.environ["DMLC_WORKER_ID"])
kv = mx.kv.create("dist_async")
assert kv.type == "dist_async"
assert kv.rank == rank and kv.num_workers == 2

if rank == 0:
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
kv.init("w", mx.nd.ones((2, 3)))        # internal barrier: optimizer set
out = mx.nd.zeros((2, 3))
kv.pull("w", out=out)
np.testing.assert_allclose(out.asnumpy(), 1.0)

if rank == 0:
    # ASYNC: push then pull with worker 1 idle — must see own update
    kv.push("w", mx.nd.ones((2, 3)))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 0.9, rtol=1e-6)
kv.barrier()
if rank == 1:
    kv.push("w", mx.nd.ones((2, 3)) * 2)
kv.barrier()
kv.pull("w", out=out)
np.testing.assert_allclose(out.asnumpy(), 0.7, rtol=1e-6)
print("ASYNC_WORKER_OK", rank)
"""

_SERVER_SRC = r"""
import os, sys
sys.path.insert(0, os.environ["REPO"])
from mxnet_tpu.kvstore_server import _init_kvstore_server_module
_init_kvstore_server_module()
"""

_FIT_WORKER_SRC = r"""
import os, sys
sys.path.insert(0, os.environ["REPO"])
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import io

rank = int(os.environ["DMLC_WORKER_ID"])
rng = np.random.RandomState(0)
protos = rng.randn(10, 32).astype(np.float32)
lab = rng.randint(0, 10, 512)
X = (protos[lab] + 0.3 * rng.randn(512, 32)).astype(np.float32)
y = lab.astype(np.float32)
# each worker trains on ITS shard — updates meet only on the server
Xw, yw = X[rank::2], y[rank::2]

net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(mx.sym.Activation(
    mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=32,
                          name="fc1"), act_type="relu"),
    num_hidden=10, name="fc2"), name="softmax")
it = io.NDArrayIter(Xw, yw, batch_size=32, shuffle=True)
mod = mx.mod.Module(net, context=mx.cpu())
mod.fit(it, num_epoch=8, optimizer="sgd", kvstore="dist_async",
        initializer=mx.init.Xavier(),
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                          "rescale_grad": 1.0 / 32})
score = mod.score(it, "acc")
acc = score[0][1] if isinstance(score, list) else float(score)
assert acc > 0.9, "rank %d acc %.3f" % (rank, acc)
print("FIT_WORKER_OK", rank)
"""


_SHARDED_WORKER_SRC = r"""
import os, sys
sys.path.insert(0, os.environ["REPO"])
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu.parallel.ps_async import (AsyncPSClient,
                                         server_endpoints,
                                         shard_for_key)

rank = int(os.environ["DMLC_WORKER_ID"])
kv = mx.kv.create("dist_async")
assert kv.num_workers == 4

keys = ["w%d" % i for i in range(8)]
if rank == 0:
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    for i, k in enumerate(keys):
        kv.init(k, mx.nd.full((3,), float(i)))
else:
    for k in keys:
        kv.init(k, mx.nd.zeros((3,)))   # non-root init is a no-op
kv.barrier()

# every worker pushes ones to ITS OWN subset; async: applied on arrival
out = mx.nd.zeros((3,))
for i, k in enumerate(keys):
    if i % 4 == rank:
        kv.push(k, mx.nd.ones((3,)))
kv.barrier()
for i, k in enumerate(keys):
    kv.pull(k, out=out)
    np.testing.assert_allclose(out.asnumpy(), float(i) - 0.1,
                               rtol=1e-6)

if rank == 0:
    # key distribution: each key sits exactly on its crc32 shard, and
    # BOTH servers hold a non-empty subset (the point of sharding)
    eps = server_endpoints()
    assert len(eps) == 2
    held = [set(AsyncPSClient(*ep).stats()) for ep in eps]
    for k in keys:
        sid = shard_for_key(k, 2)
        assert k in held[sid], (k, sid, held)
        assert k not in held[1 - sid], (k, sid, held)
    assert held[0] and held[1], held
kv.barrier()
print("SHARDED_WORKER_OK", rank)
"""


@pytest.mark.slow
def test_dist_async_two_servers_four_workers(tmp_path):
    """VERDICT r4 item 4: DMLC_NUM_SERVER=2 with key sharding — a
    2-server/4-worker job where pushes route by the stable shard hash,
    the server-side optimizer applies per shard, and the key
    distribution across servers is asserted from a worker. Slow tier
    (~16 s on the 1-core tier-1 host); the shard-hash routing keeps
    fast in-thread coverage in test_sharded_client_routes_and_stripes
    and the end-to-end job shape in test_module_fit_dist_async."""
    port = _free_port_block(2)
    base_env = dict(os.environ)
    base_env.update({
        "REPO": REPO,
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": "4",
        "DMLC_NUM_SERVER": "2",
        "MXNET_KVSTORE_TYPE": "dist_async",
    })
    (tmp_path / "server.py").write_text(_SERVER_SRC)
    (tmp_path / "worker.py").write_text(_SHARDED_WORKER_SRC)

    servers = [subprocess.Popen(
        [sys.executable, str(tmp_path / "server.py")],
        env=dict(base_env, DMLC_ROLE="server", DMLC_SERVER_ID=str(s)),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for s in range(2)]
    workers = []
    try:
        for wid in range(4):
            workers.append(subprocess.Popen(
                [sys.executable, str(tmp_path / "worker.py")],
                env=dict(base_env, DMLC_ROLE="worker",
                         DMLC_WORKER_ID=str(wid)),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        for wid, w in enumerate(workers):
            out, _ = w.communicate(timeout=180)
            assert w.returncode == 0, "worker %d:\n%s" % (wid, out[-900:])
            assert "SHARDED_WORKER_OK %d" % wid in out
        for sid, s in enumerate(servers):
            sout, _ = s.communicate(timeout=60)
            assert s.returncode == 0, "server %d:\n%s" % (sid, sout[-900:])
    finally:
        for p in workers + servers:
            if p.poll() is None:
                p.kill()


def test_module_fit_dist_async(tmp_path):
    """The reference's actual async workflow: Module.fit with
    kvstore='dist_async' — grads pushed to the server-side optimizer,
    possibly-stale weights pulled, two workers on disjoint shards —
    must still converge."""
    port = _free_port()
    base_env = dict(os.environ)
    base_env.update({
        "REPO": REPO,
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": "2",
        "MXNET_KVSTORE_TYPE": "dist_async",
    })
    (tmp_path / "server.py").write_text(_SERVER_SRC)
    (tmp_path / "fit_worker.py").write_text(_FIT_WORKER_SRC)

    server = subprocess.Popen(
        [sys.executable, str(tmp_path / "server.py")],
        env=dict(base_env, DMLC_ROLE="server"),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    workers = []
    try:
        for wid in range(2):
            workers.append(subprocess.Popen(
                [sys.executable, str(tmp_path / "fit_worker.py")],
                env=dict(base_env, DMLC_ROLE="worker",
                         DMLC_WORKER_ID=str(wid)),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        for wid, w in enumerate(workers):
            out, _ = w.communicate(timeout=300)
            assert w.returncode == 0, "worker %d:\n%s" % (wid, out[-900:])
            assert "FIT_WORKER_OK %d" % wid in out
        sout, _ = server.communicate(timeout=60)
        assert server.returncode == 0, "server:\n%s" % sout[-900:]
    finally:
        for p in workers + [server]:
            if p.poll() is None:
                p.kill()


def test_dist_async_multiprocess(tmp_path):
    port = _free_port()
    base_env = dict(os.environ)
    base_env.update({
        "REPO": REPO,
        "PYTHONPATH": REPO,            # drop the axon plugin site
        "JAX_PLATFORMS": "cpu",
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": "2",
        "MXNET_KVSTORE_TYPE": "dist_async",
    })
    (tmp_path / "server.py").write_text(_SERVER_SRC)
    (tmp_path / "worker.py").write_text(_WORKER_SRC)

    senv = dict(base_env, DMLC_ROLE="server")
    server = subprocess.Popen(
        [sys.executable, str(tmp_path / "server.py")], env=senv,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    workers = []
    try:
        for wid in range(2):
            wenv = dict(base_env, DMLC_ROLE="worker",
                        DMLC_WORKER_ID=str(wid))
            workers.append(subprocess.Popen(
                [sys.executable, str(tmp_path / "worker.py")],
                env=wenv, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        for wid, w in enumerate(workers):
            out, _ = w.communicate(timeout=180)
            assert w.returncode == 0, "worker %d:\n%s" % (wid, out[-900:])
            assert "ASYNC_WORKER_OK %d" % wid in out
        sout, _ = server.communicate(timeout=60)   # exits after 2 byes
        assert server.returncode == 0, "server:\n%s" % sout[-900:]
    finally:
        for p in workers + [server]:
            if p.poll() is None:
                p.kill()


def test_concurrent_push_stress_no_lost_updates():
    """Race hunt for the per-key lock table: 4 client threads hammer 3
    shared keys with constant-gradient SGD pushes. The update is
    commutative for identical gradients, so ANY lost or torn update
    changes the deterministic final value. (The old global lock was
    trivially lossless; the point is that the parallel lock table must
    be too.)"""
    # num_workers is the shutdown quorum: keep it above the client
    # count so worker close()/byes can't stop the server before the
    # final verification pulls
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=99)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        boot = _client(srv)
        boot.set_optimizer(mx.optimizer.SGD(learning_rate=0.01,
                                            rescale_grad=1.0))
        keys = ["wa", "wb", "wc"]
        for k in keys:
            boot.init(k, np.full((4,), 5.0, np.float32))

        PUSHES = 50
        errs = []

        def worker():
            try:
                c = _client(srv)
                rng = np.random.RandomState()
                for _ in range(PUSHES):
                    c.push(keys[rng.randint(3)],
                           np.ones((4,), np.float32))
                c.close()
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
        # a deadlocked lock table would leave workers alive (and the
        # later pull would hang forever) — fail loudly here instead
        assert not any(th.is_alive() for th in threads), \
            "worker threads stuck: server-side deadlock?"
        assert not errs, errs

        # every push moves its key by -lr, so the summed displacement
        # counts the pushes: any LOST update is a whole missing unit,
        # far outside f32 accumulation noise (~0.005 units observed)
        total = 0.0
        for k in keys:
            w = np.asarray(boot.pull(k))
            assert np.all(w == w[0])          # never torn
            total += (5.0 - w[0]) / 0.01
        assert abs(total - 4 * PUSHES) < 0.5, \
            "lost/torn updates: counted %.3f of %d" % (total,
                                                       4 * PUSHES)
        boot.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# failure paths (resilience layer): driven by the deterministic
# FaultInjector — no real process kills needed for the fast tier; the
# multi-process variant at the bottom is marked slow.
# ---------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    """A failing fault test must not leak its injector into the next
    test's socket traffic."""
    yield
    install_fault_injector(None)


@pytest.mark.faults
def test_retry_policy_deterministic_backoff_and_classification():
    import socket as socket_mod

    a, b = RetryPolicy(seed="w3"), RetryPolicy(seed="w3")
    assert [a.delay(i) for i in range(1, 6)] == \
        [b.delay(i) for i in range(1, 6)], "jitter must be deterministic"
    # backoff grows (up to the cap) and jitter never exceeds the raw delay
    raw = RetryPolicy(seed=0, base_delay=0.1, max_delay=60.0)
    assert raw.delay(4) > raw.delay(1)
    assert raw.delay(1) <= 0.1
    # transport faults retry; cohort death and application errors do not
    assert RetryPolicy.is_transient(ConnectionResetError())
    assert RetryPolicy.is_transient(socket_mod.timeout())
    assert RetryPolicy.is_transient(FaultInjected("x"))
    assert not RetryPolicy.is_transient(DeadWorkerError("x"))
    assert not RetryPolicy.is_transient(ValueError("x"))
    assert not RetryPolicy.is_transient(RuntimeError("async PS error"))


@pytest.mark.faults
def test_fault_spec_parsing_and_counting():
    with pytest.raises(ValueError, match="MXNET_FAULT_SPEC"):
        FaultInjector("send:explode@1")
    with pytest.raises(ValueError, match="MXNET_FAULT_SPEC"):
        FaultInjector("send@1")

    class _Sock:
        def shutdown(self, *_a):
            pass

        def close(self):
            pass

    inj = FaultInjector("send:drop@2x2")
    hits = []
    for _ in range(5):
        try:
            inj.on_send("send", _Sock(), b"xx")
            hits.append(False)
        except FaultInjected:
            hits.append(True)
    assert hits == [False, True, True, False, False]
    assert inj.fired == [("send", 2, "drop"), ("send", 3, "drop")]
    # x*: every call from nth on; counts are per point
    inj = FaultInjector("recv:drop@2x*")
    inj._step("send")            # other points don't advance 'recv'
    with pytest.raises(FaultInjected):
        [inj.on_recv("recv", _Sock()) for _ in range(2)]


@pytest.mark.faults
def test_mid_push_disconnect_same_final_weights(monkeypatch):
    """The acceptance gate: with MXNET_FAULT_SPEC-style injection
    tearing a push frame mid-message (and severing a pull reply), a
    training-style push loop lands on the SAME final weights as the
    fault-free run — the seq-number dedup proves the server never
    double-applies a retried gradient."""
    monkeypatch.setenv("MXNET_PS_RETRY_BASE", "0.01")

    def run(spec):
        srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=1)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        c = _client(srv)
        c.set_optimizer(mx.optimizer.SGD(learning_rate=0.1,
                                         rescale_grad=1.0))
        c.init("w", np.ones((4,), np.float32))
        inj = None
        if spec:
            inj = install_fault_injector(FaultInjector(spec))
        try:
            for i in range(8):
                c.push("w", np.full((4,), float(i % 3), np.float32))
        finally:
            install_fault_injector(None)
        w = np.asarray(c.pull("w"))
        c.close()
        srv.stop()
        return w, inj

    w_plain, _ = run(None)
    w_fault, inj = run("send:disconnect@3;recv:drop@6")
    assert inj.fired == [("send", 3, "disconnect"),
                         ("recv", 6, "drop")]
    np.testing.assert_allclose(w_fault, w_plain)


@pytest.mark.faults
def test_drop_connection_mid_pull_retries(monkeypatch):
    """Severing the connection between the pull request and its reply
    must transparently reconnect and re-pull (pull is idempotent — no
    dedup involvement)."""
    monkeypatch.setenv("MXNET_PS_RETRY_BASE", "0.01")
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=1)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        c = _client(srv)
        c.init("w", np.full((3,), 7.0, np.float32))
        inj = install_fault_injector(FaultInjector("recv:drop@1"))
        try:
            np.testing.assert_allclose(c.pull("w"), 7.0)
        finally:
            install_fault_injector(None)
        assert inj.fired == [("recv", 1, "drop")]
        c.close()
    finally:
        srv.stop()


@pytest.mark.faults
def test_dead_server_push_fails_cleanly_after_bounded_retries(
        monkeypatch):
    """kill-server-mid-push: when every (re)send fails, the client must
    surface a ConnectionError after its bounded retry schedule — never
    hang, never succeed silently."""
    import time as time_mod

    monkeypatch.setenv("MXNET_PS_RETRY_MAX", "2")
    monkeypatch.setenv("MXNET_PS_RETRY_BASE", "0.01")
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=1)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        c = _client(srv)
        c.init("w", np.zeros((2,), np.float32))
        inj = install_fault_injector(FaultInjector("send:drop@1x*"))
        t0 = time_mod.time()
        with pytest.raises(ConnectionError):
            c.push("w", np.ones((2,), np.float32))
        install_fault_injector(None)
        assert time_mod.time() - t0 < 30
        # initial attempt + exactly max_retries replays
        assert len(inj.fired) == 3
        # the value never moved: no partial application happened
        np.testing.assert_allclose(c.pull("w"), 0.0)
        c.close()
    finally:
        install_fault_injector(None)
        srv.stop()


def _two_workers(srv, monkeypatch):
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    a = _client(srv)
    monkeypatch.setenv("DMLC_WORKER_ID", "1")
    b = _client(srv)
    return a, b


def _kill_without_bye(c):
    """Simulate a worker death: heartbeat stops and the socket closes
    with no bye (what a SIGKILL'd process looks like to the server)."""
    c._hb_stop.set()
    if c._hb_thread is not None:
        c._hb_thread.join(timeout=10)
    with c._lock:
        c._drop_connection_locked()


@pytest.mark.faults
def test_worker_death_during_barrier_releases_with_error(monkeypatch):
    """A dead peer used to leave survivors spinning in the barrier
    until job end; now the heartbeat monitor releases them with an
    explicit DeadWorkerError within the heartbeat timeout."""
    import time as time_mod

    monkeypatch.setenv("MXNET_PS_HEARTBEAT_INTERVAL", "0.2")
    monkeypatch.setenv("MXNET_PS_HEARTBEAT_TIMEOUT", "1.0")
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=2)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        a, b = _two_workers(srv, monkeypatch)
        time_mod.sleep(0.6)          # b must have pinged at least once
        _kill_without_bye(b)
        t0 = time_mod.time()
        with pytest.raises(DeadWorkerError):
            a.barrier()
        assert time_mod.time() - t0 < 10
        # the cohort is broken for good: later barriers fail fast
        with pytest.raises(DeadWorkerError):
            a.barrier()
        a.close()
    finally:
        srv.stop()


@pytest.mark.faults
def test_worker_death_elastic_shrinks_cohort(monkeypatch):
    """MXNET_PS_ELASTIC=1: instead of failing the job, a dead worker
    shrinks _num_workers — the survivor's barrier RELEASES and training
    degrades gracefully."""
    import time as time_mod

    monkeypatch.setenv("MXNET_PS_HEARTBEAT_INTERVAL", "0.2")
    monkeypatch.setenv("MXNET_PS_HEARTBEAT_TIMEOUT", "1.0")
    monkeypatch.setenv("MXNET_PS_ELASTIC", "1")
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=2)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        a, b = _two_workers(srv, monkeypatch)
        time_mod.sleep(0.6)
        _kill_without_bye(b)

        done = []
        t = threading.Thread(target=lambda: (a.barrier(),
                                             done.append(True)),
                             daemon=True)
        t.start()
        t.join(timeout=15)
        assert done == [True], \
            "elastic cohort shrink did not release the barrier"
        assert srv._num_workers == 1
        # pushes keep applying for the survivor
        a.init("w", np.zeros((2,), np.float32))
        a.push("w", np.full((2,), 3.0, np.float32))
        np.testing.assert_allclose(a.pull("w"), 3.0)
        a.close()
    finally:
        srv.stop()


@pytest.mark.faults
def test_barrier_replay_is_idempotent(monkeypatch):
    """A client whose connection dies while it WAITS in a barrier
    replays the same barrier op on reconnect; membership is a set
    keyed by client id, so the replay must not double-count (a raw
    counter would release the barrier with a worker missing)."""
    monkeypatch.setenv("MXNET_PS_RETRY_BASE", "0.01")
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=2)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        a, b = _two_workers(srv, monkeypatch)
        released = []

        def barrier_through_fault():
            # sever a's connection right before it reads the barrier
            # release — forcing reconnect + replay of the SAME barrier
            install_fault_injector(FaultInjector("recv:drop@1"))
            try:
                a.barrier()
            finally:
                install_fault_injector(None)
            released.append("a")

        t = threading.Thread(target=barrier_through_fault, daemon=True)
        t.start()
        import time as time_mod
        time_mod.sleep(0.7)   # a has entered (and replayed) the barrier
        assert not released, \
            "barrier released before the second worker arrived"
        b.barrier()
        t.join(timeout=15)
        assert released == ["a"]
        a.close()
        b.close()
    finally:
        srv.stop()


@pytest.mark.faults
def test_replay_of_inflight_push_waits_not_reexecutes(monkeypatch):
    """A per-attempt timeout can fire while the server is STILL
    applying the push (slow optimizer, contended key). The client's
    replay must then block until the original completes and reuse its
    cached reply — re-executing would double-apply the gradient."""
    import time as time_mod
    from mxnet_tpu import optimizer as opt_mod

    monkeypatch.setenv("MXNET_PS_RETRY_BASE", "0.01")
    monkeypatch.setenv("MXNET_PS_OP_TIMEOUT", "0.3")
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=1)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        c = _client(srv)
        c.init("w", np.zeros((2,), np.float32))
        real = opt_mod.get_updater(
            opt_mod.SGD(learning_rate=1.0, rescale_grad=1.0))
        applies = []

        def slow_updater(index, grad, weight):
            applies.append(index)
            time_mod.sleep(0.8)          # > MXNET_PS_OP_TIMEOUT
            real(index, grad, weight)

        srv._updater = slow_updater
        c.push("w", np.ones((2,), np.float32))
        assert len(applies) == 1, applies
        srv._updater = None
        np.testing.assert_allclose(c.pull("w"), -1.0)
        c.close()
    finally:
        srv.stop()


@pytest.mark.faults
def test_concurrent_op_cannot_evict_dedup_during_backoff(monkeypatch):
    """Two threads share one client. Thread A's push reply is lost, so
    A backs off and replays; thread B's ops must NOT reach the wire in
    between — the server's one-slot dedup would forget A's completed
    push and A's replay would apply it a second time."""
    monkeypatch.setenv("MXNET_PS_RETRY_BASE", "0.05")
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=1)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        c = _client(srv)
        c.set_optimizer(mx.optimizer.SGD(learning_rate=1.0,
                                         rescale_grad=1.0))
        c.init("w", np.zeros((2,), np.float32))
        inj = install_fault_injector(FaultInjector("recv:drop@1"))
        try:
            threads = [threading.Thread(
                target=lambda: [c.push("w", np.ones((2,), np.float32))
                                for _ in range(3)]) for _ in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
        finally:
            install_fault_injector(None)
        assert inj.fired == [("recv", 1, "drop")]
        # exactly-once: 6 pushes of grad 1 at lr 1 from w0=0
        np.testing.assert_allclose(c.pull("w"), -6.0)
        c.close()
    finally:
        srv.stop()


@pytest.mark.faults
def test_clean_bye_is_not_a_death(monkeypatch):
    """A worker that says BYE and leaves stops pinging — the monitor
    must read that silence as a clean departure, not a heartbeat-lapse
    death (which would abort the survivors' barriers)."""
    import time as time_mod

    monkeypatch.setenv("MXNET_PS_HEARTBEAT_INTERVAL", "0.2")
    monkeypatch.setenv("MXNET_PS_HEARTBEAT_TIMEOUT", "1.0")
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=2)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        a, b = _two_workers(srv, monkeypatch)
        time_mod.sleep(0.6)          # both have pinged
        b.close()                    # clean bye
        time_mod.sleep(2.0)          # well past the heartbeat timeout
        assert not srv._dead_workers
        assert srv._barrier_abort is None
        a.close()
    finally:
        srv.stop()


@pytest.mark.faults
def test_false_death_revives_on_next_ping_elastic(monkeypatch):
    """A worker stalled past the heartbeat timeout (GC/VM pause) gets
    declared dead — but it is NOT dead. Its next ping must readmit it
    and regrow the elastic cohort, and barriers must again require the
    full cohort (a stale 'dead' marking would let either worker's
    barrier release alone)."""
    import time as time_mod

    monkeypatch.setenv("MXNET_PS_HEARTBEAT_INTERVAL", "0.2")
    monkeypatch.setenv("MXNET_PS_HEARTBEAT_TIMEOUT", "1.2")
    monkeypatch.setenv("MXNET_PS_ELASTIC", "1")
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=2)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        a, b = _two_workers(srv, monkeypatch)
        time_mod.sleep(0.5)
        # simulate the pause: b's heartbeat stops, but b never died
        b._hb_stop.set()
        b._hb_thread.join(timeout=10)
        deadline = time_mod.time() + 15
        while 1 not in srv._dead_workers and \
                time_mod.time() < deadline:
            time_mod.sleep(0.05)
        assert 1 in srv._dead_workers
        assert srv._num_workers == 1
        # b resumes: one ping readmits it and regrows the cohort
        b._call("ping", b._wid)
        assert 1 not in srv._dead_workers
        assert srv._num_workers == 2
        # barriers synchronize over the FULL cohort again
        released = []
        t = threading.Thread(target=lambda: (a.barrier(),
                                             released.append("a")),
                             daemon=True)
        t.start()
        time_mod.sleep(0.5)
        assert not released, "barrier released with one worker missing"
        b.barrier()
        t.join(timeout=15)
        assert released == ["a"]
        a.close()
        b.close()
    finally:
        srv.stop()


@pytest.mark.faults
def test_elastic_floor_death_then_revive_does_not_inflate(monkeypatch):
    """A sole-worker elastic cohort is floored at 1 on death; the
    revive must NOT regrow past the configured size (an inflated
    cohort would deadlock every later barrier waiting for a worker
    that cannot exist)."""
    import time as time_mod

    monkeypatch.setenv("MXNET_PS_HEARTBEAT_INTERVAL", "0.2")
    monkeypatch.setenv("MXNET_PS_HEARTBEAT_TIMEOUT", "1.2")
    monkeypatch.setenv("MXNET_PS_ELASTIC", "1")
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=1)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        a = _client(srv)
        time_mod.sleep(0.4)
        a._hb_stop.set()
        a._hb_thread.join(timeout=10)
        deadline = time_mod.time() + 15
        while 0 not in srv._dead_workers and \
                time_mod.time() < deadline:
            time_mod.sleep(0.05)
        assert 0 in srv._dead_workers
        assert srv._num_workers == 1     # floored, never 0
        a._call("ping", a._wid)
        assert 0 not in srv._dead_workers
        assert srv._num_workers == 1     # revive must not inflate to 2
        a.barrier()                      # sole worker releases alone
        a.close()
    finally:
        srv.stop()


@pytest.mark.faults
def test_full_cohort_revival_clears_barrier_abort(monkeypatch):
    """Non-elastic: a false death (GC stall) sets the barrier abort,
    but once EVERY declared-dead worker provably revives the abort
    must clear — a healthy cohort must not keep failing barriers."""
    import time as time_mod

    monkeypatch.setenv("MXNET_PS_HEARTBEAT_INTERVAL", "0.2")
    monkeypatch.setenv("MXNET_PS_HEARTBEAT_TIMEOUT", "1.2")
    srv = AsyncPSServer(host="127.0.0.1", port=0, num_workers=2)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        a, b = _two_workers(srv, monkeypatch)
        time_mod.sleep(0.5)
        b._hb_stop.set()                 # b stalls, but never died
        b._hb_thread.join(timeout=10)
        with pytest.raises(DeadWorkerError):
            a.barrier()
        # b resumes: its ping falsifies the verdict and clears the abort
        b._call("ping", b._wid)
        assert srv._barrier_abort is None
        released = []
        t = threading.Thread(target=lambda: (a.barrier(),
                                             released.append("a")),
                             daemon=True)
        t.start()
        time_mod.sleep(0.3)
        assert not released
        b.barrier()
        t.join(timeout=15)
        assert released == ["a"]
        a.close()
        b.close()
    finally:
        srv.stop()


_FAULT_WORKER_SRC = r"""
import os, sys
sys.path.insert(0, os.environ["REPO"])
import numpy as np
import mxnet_tpu as mx

kv = mx.kv.create("dist_async")
kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, rescale_grad=1.0))
kv.init("w", mx.nd.ones((2, 3)))
for _ in range(10):
    kv.push("w", mx.nd.ones((2, 3)))
out = mx.nd.zeros((2, 3))
kv.pull("w", out=out)
# exactly-once application: 10 pushes of grad 1 at lr .1 from w0=1
np.testing.assert_allclose(out.asnumpy(), 0.0, atol=1e-6)
print("FAULT_WORKER_OK")
"""


@pytest.mark.slow
@pytest.mark.faults
def test_dist_async_multiprocess_with_fault_spec(tmp_path):
    """The full mx.kv.create('dist_async') surface under
    MXNET_FAULT_SPEC: the worker process's transport is torn mid-push
    and mid-pull, and the job still lands on the exact fault-free
    weights (server-side dedup, reconnect-and-replay)."""
    port = _free_port()
    base_env = dict(os.environ)
    base_env.update({
        "REPO": REPO,
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": "1",
        "MXNET_KVSTORE_TYPE": "dist_async",
        "MXNET_PS_RETRY_BASE": "0.01",
    })
    (tmp_path / "server.py").write_text(_SERVER_SRC)
    (tmp_path / "worker.py").write_text(_FAULT_WORKER_SRC)

    server = subprocess.Popen(
        [sys.executable, str(tmp_path / "server.py")],
        env=dict(base_env, DMLC_ROLE="server"),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    worker = subprocess.Popen(
        [sys.executable, str(tmp_path / "worker.py")],
        env=dict(base_env, DMLC_ROLE="worker", DMLC_WORKER_ID="0",
                 MXNET_FAULT_SPEC="send:disconnect@4;recv:drop@7"),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        out, _ = worker.communicate(timeout=180)
        assert worker.returncode == 0, "worker:\n%s" % out[-900:]
        assert "FAULT_WORKER_OK" in out
        sout, _ = server.communicate(timeout=60)
        assert server.returncode == 0, "server:\n%s" % sout[-900:]
    finally:
        for p in (worker, server):
            if p.poll() is None:
                p.kill()
